"""Score calculators (reference: `org.deeplearning4j.earlystopping.
scorecalc.DataSetLossCalculator`)."""
from __future__ import annotations


class DataSetLossCalculator:
    """Average model loss over a holdout iterator; lower is better."""

    minimize_score = True

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        self.iterator.reset()
        while self.iterator.has_next():
            ds = self.iterator.next()
            total += float(model.score(ds)) * ds.num_examples()
            n += ds.num_examples()
        if n == 0:
            raise ValueError("empty score iterator")
        return total / n if self.average else total
