"""Early stopping (SURVEY.md D12 — `org.deeplearning4j.earlystopping`).

`EarlyStoppingConfiguration.Builder` + termination conditions +
score calculators + model savers + `EarlyStoppingTrainer`, matching
the reference's class names and semantics: train epoch-by-epoch,
score on a holdout every N epochs, keep the best model, stop when an
epoch/iteration termination condition fires, return an
`EarlyStoppingResult` with the best model restored.
"""
from .conditions import (BestScoreEpochTerminationCondition,
                         MaxEpochsTerminationCondition,
                         MaxScoreIterationTerminationCondition,
                         MaxTimeIterationTerminationCondition,
                         ScoreImprovementEpochTerminationCondition)
from .saver import InMemoryModelSaver, LocalFileModelSaver
from .scorecalc import DataSetLossCalculator
from .trainer import (EarlyStoppingConfiguration, EarlyStoppingResult,
                      EarlyStoppingTrainer)

__all__ = ["EarlyStoppingConfiguration", "EarlyStoppingTrainer",
           "EarlyStoppingResult", "MaxEpochsTerminationCondition",
           "ScoreImprovementEpochTerminationCondition",
           "BestScoreEpochTerminationCondition",
           "MaxTimeIterationTerminationCondition",
           "MaxScoreIterationTerminationCondition",
           "DataSetLossCalculator", "InMemoryModelSaver",
           "LocalFileModelSaver"]
