from deeplearning4j_tpu.lossfunctions.losses import LossFunction  # noqa: F401
