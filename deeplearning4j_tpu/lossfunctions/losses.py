"""Loss functions.

Reference parity: ``org.nd4j.linalg.lossfunctions.LossFunctions.LossFunction``
enum + ``ILossFunction`` impls (SURVEY.md J8): computeScore /
computeScoreArray / computeGradient — here ``score_array`` gives the
per-example loss and gradients come from jax autodiff of ``score``.

Conventions (matching the reference):
- inputs are ``(labels, preds)`` with shape [batch, ...]; an optional
  per-example or per-timestep ``mask`` zeroes contributions and the mean
  divides by the *active* count;
- MCXENT/NEGATIVELOGLIKELIHOOD expect probabilities (post-softmax), as the
  reference's do — the numerically-fused path (logits) is selected
  automatically by the NN layer when activation=SOFTMAX, mirroring the
  reference's softmax+MCXENT fusion.
"""
from __future__ import annotations

import enum

import jax.numpy as jnp

_EPS = 1e-7


def _apply_mask_and_reduce(per_example, mask, average: bool):
    """per_example: [batch, ...] already reduced over feature dims to
    [batch] or [batch, time]. Applies mask, reduces to scalar."""
    if mask is not None:
        mask = jnp.asarray(mask)
        while mask.ndim < per_example.ndim:
            mask = mask[..., None]
        mask = jnp.broadcast_to(mask.reshape(mask.shape[:per_example.ndim]),
                                per_example.shape)
        per_example = per_example * mask
        denom = jnp.maximum(jnp.sum(mask), 1.0)
    else:
        denom = per_example.size
    total = jnp.sum(per_example)
    return total / denom if average else total


def _feature_sum(x):
    """Sum across all non-batch/time leading dims -> [batch] or [batch,t]."""
    if x.ndim <= 1:
        return x
    if x.ndim == 2:
        return jnp.sum(x, axis=-1)
    # [batch, time, feat...] -> [batch, time]
    return jnp.sum(x.reshape(x.shape[0], x.shape[1], -1), axis=-1)


def _mse(labels, preds):
    return _feature_sum((preds - labels) ** 2) / _nfeat(labels)


def _nfeat(labels):
    if labels.ndim <= 1:
        return 1
    return labels.shape[-1]


def _l1(labels, preds):
    return _feature_sum(jnp.abs(preds - labels))


def _l2(labels, preds):
    return _feature_sum((preds - labels) ** 2)


def _mae(labels, preds):
    return _feature_sum(jnp.abs(preds - labels)) / _nfeat(labels)


def _mcxent(labels, preds):
    p = jnp.clip(preds, _EPS, 1.0 - _EPS)
    return -_feature_sum(labels * jnp.log(p))


def _xent(labels, preds):
    p = jnp.clip(preds, _EPS, 1.0 - _EPS)
    return -_feature_sum(labels * jnp.log(p) +
                         (1.0 - labels) * jnp.log(1.0 - p))


def _hinge(labels, preds):
    # labels in {-1, +1} (reference convention)
    return _feature_sum(jnp.maximum(0.0, 1.0 - labels * preds))


def _squared_hinge(labels, preds):
    return _feature_sum(jnp.maximum(0.0, 1.0 - labels * preds) ** 2)


def _kld(labels, preds):
    y = jnp.clip(labels, _EPS, 1.0)
    p = jnp.clip(preds, _EPS, 1.0)
    return _feature_sum(y * (jnp.log(y) - jnp.log(p)))


def _poisson(labels, preds):
    p = jnp.clip(preds, _EPS, None)
    return _feature_sum(p - labels * jnp.log(p))


def _msle(labels, preds):
    return _feature_sum((jnp.log1p(jnp.maximum(preds, -1 + _EPS)) -
                         jnp.log1p(jnp.maximum(labels, -1 + _EPS))) ** 2) \
        / _nfeat(labels)


def _cosine_proximity(labels, preds):
    def _norm(v):
        return jnp.sqrt(jnp.maximum(_feature_sum(v * v), _EPS))
    return -(_feature_sum(labels * preds) / (_norm(labels) * _norm(preds)))


_IMPLS = {}


class LossFunction(enum.Enum):
    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    MEAN_ABSOLUTE_ERROR = "mae"
    MEAN_SQUARED_LOGARITHMIC_ERROR = "msle"
    MCXENT = "mcxent"
    NEGATIVELOGLIKELIHOOD = "nll"   # alias of MCXENT in the reference
    XENT = "xent"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    KL_DIVERGENCE = "kld"
    RECONSTRUCTION_CROSSENTROPY = "recon_xent"
    POISSON = "poisson"
    COSINE_PROXIMITY = "cosine_proximity"

    # ------------------------------------------------------------------
    def score_array(self, labels, preds, mask=None):
        """Per-example (or per-example-per-timestep) loss."""
        labels = jnp.asarray(labels)
        preds = jnp.asarray(preds)
        out = _IMPLS[self](labels, preds)
        if mask is not None:
            m = jnp.asarray(mask)
            while m.ndim < out.ndim:        # same padding as score()
                m = m[..., None]
            m = m.reshape(m.shape[:out.ndim])
            out = out * jnp.broadcast_to(m, out.shape)
        return out

    def score(self, labels, preds, mask=None, average=True):
        labels = jnp.asarray(labels)
        preds = jnp.asarray(preds)
        per = _IMPLS[self](labels, preds)
        return _apply_mask_and_reduce(per, mask, average)

    # Fused from-logits path for softmax/sigmoid heads (TPU-first: avoids
    # the clip+log of the probability-space formulas; selected by the
    # output layer when it owns the final activation).
    def supports_logits(self) -> bool:
        return self in (LossFunction.MCXENT,
                        LossFunction.NEGATIVELOGLIKELIHOOD,
                        LossFunction.XENT)

    def score_from_logits(self, labels, logits, mask=None, average=True):
        import jax
        labels = jnp.asarray(labels)
        logits = jnp.asarray(logits)
        if self in (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD):
            per = -_feature_sum(labels * jax.nn.log_softmax(logits, axis=-1))
        elif self is LossFunction.XENT:
            per = _feature_sum(
                jnp.maximum(logits, 0) - logits * labels +
                jnp.log1p(jnp.exp(-jnp.abs(logits))))
        else:
            raise ValueError(f"{self} has no logits form")
        return _apply_mask_and_reduce(per, mask, average)

    @staticmethod
    def from_name(name: str) -> "LossFunction":
        return LossFunction[name.upper()]


_IMPLS.update({
    LossFunction.MSE: _mse,
    LossFunction.L1: _l1,
    LossFunction.L2: _l2,
    LossFunction.MEAN_ABSOLUTE_ERROR: _mae,
    LossFunction.MEAN_SQUARED_LOGARITHMIC_ERROR: _msle,
    LossFunction.MCXENT: _mcxent,
    LossFunction.NEGATIVELOGLIKELIHOOD: _mcxent,
    LossFunction.XENT: _xent,
    LossFunction.HINGE: _hinge,
    LossFunction.SQUARED_HINGE: _squared_hinge,
    LossFunction.KL_DIVERGENCE: _kld,
    LossFunction.RECONSTRUCTION_CROSSENTROPY: _xent,
    LossFunction.POISSON: _poisson,
    LossFunction.COSINE_PROXIMITY: _cosine_proximity,
})
