"""Native host-runtime bridge (ctypes over ``native/dl4j_native.cpp``).

The reference reaches its native engine over a flat C ABI
(`NativeOps.h` + JavaCPP JNI — SURVEY.md N14/J4). Here the seam is
ctypes over a small C ABI: no JNI, no codegen, and every entry point
has a pure-Python fallback so the package works before/without the
compiled library (set ``DL4J_TPU_DISABLE_NATIVE=1`` to force the
fallbacks).

The library auto-builds on first import via ``make -C native`` when a
compiler is present; the result is cached at
``native/build/libdl4j_native.so``.
"""
from .bridge import (NativeQueue, arena, available, crc32, ensure_built,
                     parse_csv_floats, threshold_decode,
                     threshold_encode, threshold_residual, toposort)

__all__ = ["available", "ensure_built", "crc32", "threshold_encode",
           "threshold_decode", "threshold_residual", "toposort",
           "parse_csv_floats", "NativeQueue", "arena"]
