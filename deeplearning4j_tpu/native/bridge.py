"""ctypes binding + pure-Python fallbacks for the native runtime.

Every public function dispatches to the compiled library when
available and to a numpy implementation otherwise, so callers never
branch. SURVEY.md §2.7 item 4: the host-language↔C++ boundary of the
new stack (ctypes in place of the reference's JavaCPP JNI seam).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import zlib
from typing import Optional, Sequence, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
#: DL4J_TPU_NATIVE_LIB overrides the library path (the sanitizer
#: suite points it at the ASan+UBSan build)
_SO_PATH = os.environ.get(
    "DL4J_TPU_NATIVE_LIB",
    os.path.join(_NATIVE_DIR, "build", "libdl4j_native.so"))

_lib = None
_lock = threading.Lock()
_build_attempted = False


def _configure(lib):
    c = ctypes
    lib.dl4j_crc32.restype = c.c_uint32
    lib.dl4j_crc32.argtypes = [c.c_void_p, c.c_int64]
    lib.dl4j_threshold_encode.restype = c.c_int64
    lib.dl4j_threshold_encode.argtypes = [c.c_void_p, c.c_int64,
                                          c.c_float, c.c_void_p,
                                          c.c_int64]
    lib.dl4j_threshold_decode.restype = None
    lib.dl4j_threshold_decode.argtypes = [c.c_void_p, c.c_int64,
                                          c.c_float, c.c_void_p,
                                          c.c_int64]
    lib.dl4j_threshold_residual.restype = None
    lib.dl4j_threshold_residual.argtypes = [c.c_void_p, c.c_void_p,
                                            c.c_int64, c.c_float,
                                            c.c_int64]
    lib.dl4j_arena_create.restype = c.c_void_p
    lib.dl4j_arena_create.argtypes = [c.c_int64]
    lib.dl4j_arena_alloc.restype = c.c_void_p
    lib.dl4j_arena_alloc.argtypes = [c.c_void_p, c.c_int64, c.c_int64]
    lib.dl4j_arena_reset.argtypes = [c.c_void_p]
    lib.dl4j_arena_used.restype = c.c_int64
    lib.dl4j_arena_used.argtypes = [c.c_void_p]
    lib.dl4j_arena_high_water.restype = c.c_int64
    lib.dl4j_arena_high_water.argtypes = [c.c_void_p]
    lib.dl4j_arena_destroy.argtypes = [c.c_void_p]
    lib.dl4j_queue_create.restype = c.c_void_p
    lib.dl4j_queue_create.argtypes = [c.c_int32]
    lib.dl4j_queue_push.restype = c.c_int32
    lib.dl4j_queue_push.argtypes = [c.c_void_p, c.c_size_t, c.c_double]
    lib.dl4j_queue_pop.restype = c.c_int32
    lib.dl4j_queue_pop.argtypes = [c.c_void_p,
                                   c.POINTER(c.c_size_t), c.c_double]
    lib.dl4j_queue_size.restype = c.c_int64
    lib.dl4j_queue_size.argtypes = [c.c_void_p]
    lib.dl4j_queue_close.argtypes = [c.c_void_p]
    lib.dl4j_queue_destroy.argtypes = [c.c_void_p]
    lib.dl4j_parse_csv_floats.restype = c.c_int64
    lib.dl4j_parse_csv_floats.argtypes = [
        c.c_char_p, c.c_int64, c.c_char, c.c_void_p, c.c_int64,
        c.POINTER(c.c_int64), c.POINTER(c.c_int64)]
    lib.dl4j_toposort.restype = c.c_int32
    lib.dl4j_toposort.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                  c.c_int32, c.c_void_p]
    return lib


def ensure_built(force: bool = False) -> bool:
    """Build (once) and load the native library. Returns success."""
    global _lib, _build_attempted
    if os.environ.get("DL4J_TPU_DISABLE_NATIVE"):
        return False
    if _lib is not None and not force:
        # lock-free fast path: every native entry point calls this,
        # so the loaded case must not serialize threads
        return True
    with _lock:
        if _lib is not None:
            return True
        if _build_attempted and not force:
            return False
        _build_attempted = True
        if os.environ.get("DL4J_TPU_NATIVE_LIB"):
            # explicit override: load-or-fail — silently degrading to
            # the Python fallbacks would defeat the point (e.g. a
            # sanitizer run that never touches native code)
            if not os.path.exists(_SO_PATH):
                raise OSError(
                    f"DL4J_TPU_NATIVE_LIB={_SO_PATH} does not exist "
                    f"(build it first, e.g. `make -C native "
                    f"sanitize`)")
            _lib = _configure(ctypes.CDLL(_SO_PATH))
            return True
        if not os.path.exists(_SO_PATH) or force:
            if not os.path.isdir(_NATIVE_DIR):
                return False
            import logging
            log = logging.getLogger(__name__)
            log.info("building native runtime (make -C %s) — one-time,"
                     " may take up to ~2 min", _NATIVE_DIR)
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR],
                               check=True, capture_output=True,
                               timeout=120)
            except subprocess.CalledProcessError as e:
                log.warning("native build failed, using Python "
                            "fallbacks:\n%s",
                            e.stderr.decode(errors="replace")[-2000:])
                return False
            except Exception as e:
                log.warning("native build unavailable (%s), using "
                            "Python fallbacks", e)
                return False
        try:
            _lib = _configure(ctypes.CDLL(_SO_PATH))
            return True
        except OSError:
            _lib = None
            return False


def available() -> bool:
    return ensure_built()


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


# ---------------------------------------------------------------------------
# CRC32
# ---------------------------------------------------------------------------
def crc32(data) -> int:
    buf = np.ascontiguousarray(
        np.frombuffer(data, np.uint8) if isinstance(data, (bytes,
                                                           bytearray))
        else np.asarray(data).view(np.uint8).ravel())
    if ensure_built():
        return int(_lib.dl4j_crc32(_ptr(buf), buf.size))
    return zlib.crc32(buf.tobytes()) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Threshold codec (host side; the in-graph jax version lives in
# parallel/encoding.py — same wire format)
# ---------------------------------------------------------------------------
def threshold_encode(g: np.ndarray, tau: float) -> np.ndarray:
    g = np.ascontiguousarray(np.asarray(g, np.float32).ravel())
    if ensure_built():
        cap = max(16, int(g.size))
        out = np.empty(cap, np.int32)
        k = int(_lib.dl4j_threshold_encode(_ptr(g), g.size,
                                           ctypes.c_float(tau),
                                           _ptr(out), cap))
        return out[:k].copy()
    idx = np.nonzero(np.abs(g) >= tau)[0]
    return ((idx + 1) * np.sign(g[idx])).astype(np.int32)


def threshold_decode(enc: np.ndarray, tau: float, n: int,
                     out: Optional[np.ndarray] = None) -> np.ndarray:
    enc = np.ascontiguousarray(np.asarray(enc, np.int32).ravel())
    if out is None:
        out = np.zeros(n, np.float32)
    elif (out.dtype != np.float32 or not out.flags.c_contiguous
          or out.size < n):
        raise ValueError(
            f"out must be C-contiguous float32 with size >= {n}, got "
            f"{out.dtype} size {out.size} contiguous="
            f"{out.flags.c_contiguous}")
    if ensure_built():
        _lib.dl4j_threshold_decode(_ptr(enc), enc.size,
                                   ctypes.c_float(tau), _ptr(out), n)
        return out
    idx = np.abs(enc) - 1
    np.add.at(out, idx, np.where(enc > 0, tau, -tau).astype(np.float32))
    return out


def threshold_residual(residual: np.ndarray, enc: np.ndarray,
                       tau: float) -> np.ndarray:
    """In-place: residual -= decode(enc); returns residual."""
    residual = np.ascontiguousarray(residual, np.float32)
    enc = np.ascontiguousarray(np.asarray(enc, np.int32).ravel())
    if ensure_built():
        _lib.dl4j_threshold_residual(_ptr(residual), _ptr(enc),
                                     enc.size, ctypes.c_float(tau),
                                     residual.size)
        return residual
    idx = np.abs(enc) - 1
    np.add.at(residual, idx,
              np.where(enc > 0, -tau, tau).astype(np.float32))
    return residual


# ---------------------------------------------------------------------------
# toposort
# ---------------------------------------------------------------------------
def toposort(edges: Sequence[Tuple[int, int]], n_nodes: int):
    """Kahn topological order for (src, dst) edges; raises on cycles."""
    if n_nodes == 0:
        return []
    e = np.asarray(list(edges), np.int32).reshape(-1, 2)
    if ensure_built():
        src = np.ascontiguousarray(e[:, 0])
        dst = np.ascontiguousarray(e[:, 1])
        order = np.empty(n_nodes, np.int32)
        placed = int(_lib.dl4j_toposort(_ptr(src), _ptr(dst),
                                        len(e), n_nodes, _ptr(order)))
        if placed < 0:
            raise ValueError("toposort: edge endpoint out of range")
        if placed < n_nodes:
            raise ValueError("toposort: graph has a cycle")
        return order.tolist()
    indeg = [0] * n_nodes
    adj = [[] for _ in range(n_nodes)]
    for s, d in e.tolist():
        adj[s].append(d)
        indeg[d] += 1
    ready = [i for i in range(n_nodes) if indeg[i] == 0]
    order = []
    for u in ready:
        order.append(u)
        for d in adj[u]:
            indeg[d] -= 1
            if indeg[d] == 0:
                ready.append(d)
    if len(order) < n_nodes:
        raise ValueError("toposort: graph has a cycle")
    return order


# ---------------------------------------------------------------------------
# CSV fast path
# ---------------------------------------------------------------------------
def parse_csv_floats(text, delim: str = ",") -> np.ndarray:
    """Parse delimiter-separated floats into a [rows, cols] array."""
    if isinstance(text, str):
        text = text.encode()
    if ensure_built():
        cap = max(16, text.count(delim.encode()) + text.count(b"\n")
                  + 2)
        out = np.empty(cap, np.float32)
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        k = int(_lib.dl4j_parse_csv_floats(
            text, len(text), ctypes.c_char(delim.encode()), _ptr(out),
            cap, ctypes.byref(rows), ctypes.byref(cols)))
        if k == -2:
            raise ValueError("ragged CSV rows")
        if k >= 0:
            return out[:k].reshape(rows.value, cols.value).copy()
        # k == -1 capacity miss -> fall through to python path
    def to_f(x):
        try:
            return float(x)
        except ValueError:     # non-numeric field -> NaN (native
            return float("nan")  # strtof behaves the same way)

    rows = [r for r in text.decode().split("\n") if r.strip()]
    parsed = [[to_f(x) if x.strip() else float("nan")
               for x in r.split(delim)] for r in rows]
    width = {len(r) for r in parsed}
    if len(width) > 1:
        raise ValueError("ragged CSV rows")
    return np.asarray(parsed, np.float32)


# ---------------------------------------------------------------------------
# bounded blocking queue (native pthread ring; Python deque fallback)
# ---------------------------------------------------------------------------
class NativeQueue:
    """Bounded blocking queue of Python objects. Objects park in a
    slot table; only their slot tokens cross the C boundary (same
    opaque-handle style as the reference's JNI buffer ids)."""

    def __init__(self, capacity: int = 8):
        self.capacity = capacity
        self._native = ensure_built()
        self._slots = {}
        self._next_token = [1]
        self._slot_lock = threading.Lock()
        if self._native:
            self._q = _lib.dl4j_queue_create(capacity)
        else:
            import collections
            self._q = collections.deque()
            self._cv = threading.Condition()
            self._closed = False

    def put(self, obj, timeout: Optional[float] = None) -> bool:
        if self._native:
            with self._slot_lock:
                tok = self._next_token[0]
                self._next_token[0] += 1
                self._slots[tok] = obj
            r = _lib.dl4j_queue_push(
                self._q, tok, -1.0 if timeout is None else timeout)
            if r != 1:
                with self._slot_lock:
                    self._slots.pop(tok, None)
                if r == -1:
                    raise RuntimeError("queue closed")
                return False
            return True
        import time
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cv:
            while len(self._q) >= self.capacity and not self._closed:
                rem = None if deadline is None else \
                    deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    return False
                if not self._cv.wait(rem):
                    return False
            if self._closed:
                raise RuntimeError("queue closed")
            self._q.append(obj)
            self._cv.notify_all()
            return True

    def get(self, timeout: Optional[float] = None):
        """Returns the object, or raises queue.Empty on timeout /
        StopIteration when closed and drained."""
        import queue as _pyqueue
        if self._native:
            tok = ctypes.c_size_t()
            r = _lib.dl4j_queue_pop(
                self._q, ctypes.byref(tok),
                -1.0 if timeout is None else timeout)
            if r == 0:
                raise _pyqueue.Empty()
            if r == -1:
                raise StopIteration()
            with self._slot_lock:
                return self._slots.pop(tok.value)
        import time
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cv:
            while not self._q and not self._closed:
                rem = None if deadline is None else \
                    deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    raise _pyqueue.Empty()
                if not self._cv.wait(rem):
                    raise _pyqueue.Empty()
            if self._q:
                obj = self._q.popleft()
                self._cv.notify_all()
                return obj
            raise StopIteration()

    def qsize(self) -> int:
        if self._native:
            return int(_lib.dl4j_queue_size(self._q))
        with self._cv:
            return len(self._q)

    def close(self):
        if self._native:
            _lib.dl4j_queue_close(self._q)
        else:
            with self._cv:
                self._closed = True
                self._cv.notify_all()

    def __del__(self):
        try:
            if self._native and _lib is not None:
                _lib.dl4j_queue_destroy(self._q)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# arena
# ---------------------------------------------------------------------------
class arena:
    """Workspace-style host staging arena (context manager).

    With the native lib, allocations live in one malloc'd block and
    ``reset()`` is O(1) — the reference's MemoryWorkspace behavior.
    Fallback allocates numpy arrays (still scope-tracked)."""

    def __init__(self, capacity_bytes: int = 1 << 20):
        self.capacity = capacity_bytes
        self._native = ensure_built()
        self._handle = (_lib.dl4j_arena_create(capacity_bytes)
                        if self._native else None)
        self._spill = []

    def alloc(self, shape, dtype=np.float32) -> np.ndarray:
        dtype = np.dtype(dtype)
        size = int(np.prod(shape)) * dtype.itemsize
        if self._native:
            p = _lib.dl4j_arena_alloc(self._handle, size, 64)
            if p:
                buf = (ctypes.c_char * size).from_address(p)
                # keep the arena alive while any view escapes: the
                # array's base chain reaches buf, and buf pins the
                # arena (else __del__ would free() under live views)
                buf._owner = self
                return np.frombuffer(buf, dtype).reshape(shape)
        a = np.empty(shape, dtype)
        self._spill.append(a)
        return a

    def reset(self):
        if self._native:
            _lib.dl4j_arena_reset(self._handle)
        self._spill.clear()

    @property
    def used(self) -> int:
        return (int(_lib.dl4j_arena_used(self._handle))
                if self._native else
                sum(a.nbytes for a in self._spill))

    @property
    def high_water(self) -> int:
        return (int(_lib.dl4j_arena_high_water(self._handle))
                if self._native else self.used)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.reset()
        return False

    def __del__(self):
        try:
            if self._native and _lib is not None and self._handle:
                _lib.dl4j_arena_destroy(self._handle)
                self._handle = None
        except Exception:
            pass
