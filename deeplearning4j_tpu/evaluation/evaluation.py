"""Evaluation classes.

Reference parity: ``org.nd4j.evaluation.classification.Evaluation`` (acc/
precision/recall/F1/confusion + stats()), ``ROC`` (AUC, thresholded or
exact), ``EvaluationBinary``, ``EvaluationCalibration``, and
``org.nd4j.evaluation.regression.RegressionEvaluation`` (SURVEY.md J10).

Accumulation happens host-side in numpy (evaluation is not a hot path);
the model's forward passes that produce predictions are jitted.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def _np(x):
    from deeplearning4j_tpu.ndarray.ndarray import INDArray
    if isinstance(x, INDArray):
        return x.to_numpy()
    return np.asarray(x)


def _flatten_time(labels, preds, mask):
    """[b, t, c] -> [b*t, c] with mask filtering (per-timestep eval,
    reference: time-series evaluation with label masks)."""
    if labels.ndim == 3:
        b, t, c = labels.shape
        labels = labels.reshape(b * t, c)
        preds = preds.reshape(b * t, c)
        if mask is not None:
            keep = mask.reshape(b * t) > 0
            labels, preds = labels[keep], preds[keep]
            mask = None
    return labels, preds, mask


class Evaluation:
    """Multi-class classification metrics. ``top_n > 1`` additionally
    tracks top-N accuracy (reference: Evaluation(int numClasses, int
    topN) — a prediction counts as top-N correct when the true class is
    among the N highest-probability outputs)."""

    def __init__(self, num_classes: Optional[int] = None, labels=None,
                 top_n: int = 1):
        self.num_classes = num_classes
        self.label_names = labels
        self.confusion: Optional[np.ndarray] = None
        self.top_n = max(1, int(top_n))
        self._top_n_correct = 0
        self._top_n_total = 0

    # ------------------------------------------------------------------
    def eval(self, labels, predictions, mask=None):  # noqa: A003
        labels = _np(labels)
        preds = _np(predictions)
        mask = _np(mask) if mask is not None else None
        labels, preds, mask = _flatten_time(labels, preds, mask)
        if labels.ndim == 2:
            true_idx = labels.argmax(-1)
            n = labels.shape[-1]
        else:
            true_idx = labels.astype(int)
            n = int(true_idx.max()) + 1 if self.num_classes is None \
                else self.num_classes
        pred_idx = preds.argmax(-1) if preds.ndim == 2 \
            else preds.astype(int)
        if self.num_classes is None:
            self.num_classes = n
        if self.confusion is None:
            self.confusion = np.zeros((self.num_classes, self.num_classes),
                                      dtype=np.int64)
        if mask is not None:
            keep = mask.reshape(-1) > 0
            true_idx, pred_idx = true_idx[keep], pred_idx[keep]
            preds = preds[keep] if preds.ndim == 2 else preds
        np.add.at(self.confusion, (true_idx, pred_idx), 1)
        if self.top_n > 1 and preds.ndim == 2:
            k = min(self.top_n, preds.shape[-1])
            topk = np.argpartition(-preds, k - 1, axis=-1)[:, :k]
            self._top_n_correct += int(
                (topk == true_idx[:, None]).any(-1).sum())
            self._top_n_total += int(true_idx.size)
        return self

    def top_n_accuracy(self) -> float:
        """Reference: Evaluation.topNAccuracy()."""
        if self.top_n == 1:
            return self.accuracy()
        if self._top_n_total == 0:
            return float("nan")
        return self._top_n_correct / self._top_n_total

    # ------------------------------------------------------------------
    def _tp(self):
        return np.diag(self.confusion).astype(np.float64)

    def accuracy(self) -> float:
        total = self.confusion.sum()
        return float(self._tp().sum() / total) if total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        col = self.confusion.sum(0).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(col > 0, self._tp() / col, np.nan)
        if cls is not None:
            return float(per[cls])
        return float(np.nanmean(per))

    def recall(self, cls: Optional[int] = None) -> float:
        row = self.confusion.sum(1).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(row > 0, self._tp() / row, np.nan)
        if cls is not None:
            return float(per[cls])
        return float(np.nanmean(per))

    def f1(self, cls: Optional[int] = None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0

    def false_positive_rate(self, cls: int) -> float:
        fp = self.confusion[:, cls].sum() - self.confusion[cls, cls]
        tn = self.confusion.sum() - self.confusion[cls].sum() - \
            self.confusion[:, cls].sum() + self.confusion[cls, cls]
        return float(fp / (fp + tn)) if (fp + tn) else 0.0

    def confusion_matrix(self) -> np.ndarray:
        return self.confusion

    def stats(self) -> str:
        lines = [
            "========================Evaluation Metrics=================",
            f" # of classes:    {self.num_classes}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "",
            "=========================Confusion Matrix==================",
            str(self.confusion),
        ]
        return "\n".join(lines)


class EvaluationBinary:
    """Per-output independent binary metrics (reference: same name)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = self.fp = self.tn = self.fn = None

    def eval(self, labels, predictions, mask=None):  # noqa: A003
        labels = _np(labels)
        preds = (_np(predictions) > self.threshold)
        lab = labels > 0.5
        if self.tp is None:
            n = labels.shape[-1]
            self.tp = np.zeros(n, np.int64)
            self.fp = np.zeros(n, np.int64)
            self.tn = np.zeros(n, np.int64)
            self.fn = np.zeros(n, np.int64)
        flat_l = lab.reshape(-1, labels.shape[-1])
        flat_p = preds.reshape(-1, labels.shape[-1])
        if mask is not None:
            keep = _np(mask).reshape(-1) > 0
            flat_l, flat_p = flat_l[keep], flat_p[keep]
        self.tp += (flat_l & flat_p).sum(0)
        self.fp += (~flat_l & flat_p).sum(0)
        self.tn += (~flat_l & ~flat_p).sum(0)
        self.fn += (flat_l & ~flat_p).sum(0)
        return self

    def accuracy(self, i: int) -> float:
        tot = self.tp[i] + self.fp[i] + self.tn[i] + self.fn[i]
        return float((self.tp[i] + self.tn[i]) / tot) if tot else 0.0

    def precision(self, i: int) -> float:
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i] / d) if d else 0.0

    def recall(self, i: int) -> float:
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i] / d) if d else 0.0

    def f1(self, i: int) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0


class ROC:
    """Binary ROC / AUC, exact (threshold-free), matching the reference's
    ROC(0) exact mode. For probability outputs [n] or [n, 2] (uses class-1
    column)."""

    def __init__(self):
        self.scores = []
        self.labels = []

    def eval(self, labels, predictions, mask=None):  # noqa: A003
        labels = _np(labels)
        preds = _np(predictions)
        if preds.ndim == 2 and preds.shape[-1] == 2:
            preds = preds[:, 1]
            labels = labels[:, 1] if labels.ndim == 2 else labels
        if mask is not None:
            keep = _np(mask).reshape(-1) > 0
            labels, preds = labels.reshape(-1)[keep], \
                preds.reshape(-1)[keep]
        self.scores.append(preds.reshape(-1))
        self.labels.append(labels.reshape(-1))
        return self

    def calculate_auc(self) -> float:
        s = np.concatenate(self.scores)
        y = np.concatenate(self.labels) > 0.5
        n_pos = int(y.sum())
        n_neg = y.size - n_pos
        if n_pos == 0 or n_neg == 0:
            return float("nan")
        # rank-sum (Mann-Whitney) AUC with tie correction
        order = np.argsort(s, kind="mergesort")
        ranks = np.empty_like(order, dtype=np.float64)
        sorted_s = s[order]
        i = 0
        while i < len(sorted_s):
            j = i
            while j + 1 < len(sorted_s) and sorted_s[j + 1] == sorted_s[i]:
                j += 1
            ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0
            i = j + 1
        auc = (ranks[y].sum() - n_pos * (n_pos + 1) / 2.0) / \
            (n_pos * n_neg)
        return float(auc)

    def calculate_auprc(self) -> float:
        """Area under the precision-recall curve (reference:
        ROC.calculateAUCPR), exact interpolation-free sum."""
        s = np.concatenate(self.scores)
        y = np.concatenate(self.labels) > 0.5
        if y.sum() == 0:
            return float("nan")
        order = np.argsort(-s, kind="mergesort")
        y_sorted = y[order]
        tp = np.cumsum(y_sorted)
        precision = tp / np.arange(1, y_sorted.size + 1)
        # average precision: sum precision at each positive hit
        return float(precision[y_sorted].sum() / y.sum())


class _PerColumnROC:
    """One independent ROC per label/class column; [b, t, c] time series
    flatten through the label mask first (shared spine of ROCBinary and
    ROCMultiClass)."""

    def __init__(self):
        self.rocs: list = []

    def eval(self, labels, predictions, mask=None):  # noqa: A003
        labels = _np(labels)
        preds = _np(predictions)
        if labels.ndim == 1:
            labels, preds = labels[:, None], preds[:, None]
        mask = _np(mask) if mask is not None else None
        if labels.ndim == 3:   # [b, t, c] time series
            labels, preds, mask = _flatten_time(labels, preds, mask)
        n_col = labels.shape[-1]
        if not self.rocs:
            self.rocs = [ROC() for _ in range(n_col)]
        for i in range(n_col):
            # a per-output [n, c] mask selects column i; an [n] mask
            # applies to every column
            m = mask
            if m is not None and m.ndim == 2:
                m = m[:, i]
            self.rocs[i].eval(labels[:, i], preds[:, i], mask=m)
        return self

    def calculate_auc(self, i: int) -> float:
        return self.rocs[i].calculate_auc()

    def calculate_auprc(self, i: int) -> float:
        return self.rocs[i].calculate_auprc()

    def calculate_average_auc(self) -> float:
        aucs = [r.calculate_auc() for r in self.rocs]
        aucs = [a for a in aucs if not np.isnan(a)]
        return float(np.mean(aucs)) if aucs else float("nan")


class ROCBinary(_PerColumnROC):
    """Per-output binary ROC for multi-label sigmoid heads (reference:
    org.nd4j.evaluation.classification.ROCBinary)."""

    def num_labels(self) -> int:
        return len(self.rocs)


class ROCMultiClass(_PerColumnROC):
    """One-vs-all ROC per class for softmax heads (reference:
    org.nd4j.evaluation.classification.ROCMultiClass)."""

    def num_classes(self) -> int:
        return len(self.rocs)


class EvaluationCalibration:
    """Reliability-diagram accumulation (reference: same name)."""

    def __init__(self, n_bins: int = 10):
        self.n_bins = n_bins
        self.bin_counts = np.zeros(n_bins, np.int64)
        self.bin_correct = np.zeros(n_bins, np.int64)
        self.bin_conf_sum = np.zeros(n_bins, np.float64)

    def eval(self, labels, predictions, mask=None):  # noqa: A003
        labels = _np(labels)
        preds = _np(predictions)
        conf = preds.max(-1)
        correct = preds.argmax(-1) == labels.argmax(-1)
        bins = np.clip((conf * self.n_bins).astype(int), 0,
                       self.n_bins - 1)
        np.add.at(self.bin_counts, bins, 1)
        np.add.at(self.bin_correct, bins, correct.astype(np.int64))
        np.add.at(self.bin_conf_sum, bins, conf)
        return self

    def expected_calibration_error(self) -> float:
        tot = self.bin_counts.sum()
        if tot == 0:
            return 0.0
        acc = np.where(self.bin_counts > 0,
                       self.bin_correct / np.maximum(self.bin_counts, 1),
                       0.0)
        conf = np.where(self.bin_counts > 0,
                        self.bin_conf_sum / np.maximum(self.bin_counts, 1),
                        0.0)
        return float(np.sum(self.bin_counts / tot * np.abs(acc - conf)))


class RegressionEvaluation:
    """Column-wise regression metrics (reference: same name)."""

    def __init__(self, n_columns: Optional[int] = None):
        self.n = 0
        self.n_columns = n_columns
        self.sum_sq = None
        self.sum_abs = None
        self.sum_label = None
        self.sum_label_sq = None
        self.sum_pred = None
        self.sum_label_pred = None
        self.sum_pred_sq = None

    def eval(self, labels, predictions, mask=None):  # noqa: A003
        labels = _np(labels).astype(np.float64)
        preds = _np(predictions).astype(np.float64)
        labels, preds, _ = _flatten_time(labels, preds,
                                         _np(mask) if mask is not None
                                         else None)
        if self.sum_sq is None:
            c = labels.shape[-1]
            self.n_columns = c
            z = lambda: np.zeros(c, np.float64)
            self.sum_sq, self.sum_abs = z(), z()
            self.sum_label, self.sum_label_sq = z(), z()
            self.sum_pred, self.sum_pred_sq = z(), z()
            self.sum_label_pred = z()
        err = preds - labels
        self.n += labels.shape[0]
        self.sum_sq += (err ** 2).sum(0)
        self.sum_abs += np.abs(err).sum(0)
        self.sum_label += labels.sum(0)
        self.sum_label_sq += (labels ** 2).sum(0)
        self.sum_pred += preds.sum(0)
        self.sum_pred_sq += (preds ** 2).sum(0)
        self.sum_label_pred += (labels * preds).sum(0)
        return self

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self.sum_sq[col] / self.n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self.sum_abs[col] / self.n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int = 0) -> float:
        ss_tot = self.sum_label_sq[col] - \
            self.sum_label[col] ** 2 / self.n
        ss_res = self.sum_sq[col]
        return float(1.0 - ss_res / ss_tot) if ss_tot > 0 else 0.0

    def pearson_correlation(self, col: int = 0) -> float:
        n = self.n
        num = self.sum_label_pred[col] - \
            self.sum_label[col] * self.sum_pred[col] / n
        den = np.sqrt((self.sum_label_sq[col] -
                       self.sum_label[col] ** 2 / n) *
                      (self.sum_pred_sq[col] -
                       self.sum_pred[col] ** 2 / n))
        return float(num / den) if den > 0 else 0.0

    def stats(self) -> str:
        cols = range(self.n_columns or 0)
        lines = ["Column    MSE            MAE            RMSE           R^2"]
        for c in cols:
            lines.append(f"col_{c}   {self.mean_squared_error(c):<14.6f} "
                         f"{self.mean_absolute_error(c):<14.6f} "
                         f"{self.root_mean_squared_error(c):<14.6f} "
                         f"{self.r_squared(c):.6f}")
        return "\n".join(lines)
