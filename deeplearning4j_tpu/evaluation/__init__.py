from deeplearning4j_tpu.evaluation.evaluation import (  # noqa: F401
    Evaluation, RegressionEvaluation, ROC, ROCBinary, ROCMultiClass,
    EvaluationBinary, EvaluationCalibration)
