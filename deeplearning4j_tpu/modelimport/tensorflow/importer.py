"""Frozen TF GraphDef → SameDiff importer.

Reference parity: `TensorflowFrameworkImporter.runImport` /
`ImportGraph` in `samediff-import-tensorflow`, and the legacy
`org.nd4j.imports.graphmapper.tf.TFGraphMapper` (SURVEY.md S6/S7,
call stack §3.3 "Import front-door").

TPU-first design: rather than replaying TF's dynamic-shape machinery,
the importer (a) constant-folds the GraphDef's shape-arithmetic chains
(Shape → StridedSlice → Pack → Reshape) with numpy, using
``jax.eval_shape`` to propagate static shapes through every emitted op,
and (b) emits into the SameDiff op DAG, which compiles to ONE XLA
program at execution. Static shapes are exactly what XLA:TPU wants.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from deeplearning4j_tpu.autodiff.registry import get_op
from deeplearning4j_tpu.autodiff.samediff import (SameDiff, SDVariable,
                                                  VariableType)
from deeplearning4j_tpu.modelimport.tensorflow import (mappings,
                                                       v1_control_flow)
from deeplearning4j_tpu.modelimport.tensorflow.mappings import TF_OP_MAP
from deeplearning4j_tpu.modelimport.tensorflow.protobuf import (
    Attr, FunctionDef, NodeDef, parse_graphdef_with_library,
    tf_dtype_to_np)

_SKIP_OPS = {"NoOp", "Assert", "SaveV2", "RestoreV2", "MergeV2Checkpoints"}

#: functional control-flow ops handled by the importer itself (not
#: TF_OP_MAP rules): bodies live in the GraphDef function library
_FUNCTIONAL_OPS = {"While", "StatelessWhile", "If", "StatelessIf"}


def _canon(ref: str) -> str:
    """TF input ref → canonical var name ('x:0' == 'x'; '^x' is a
    control dep on x)."""
    if ref.startswith("^"):
        ref = ref[1:]
    if ref.endswith(":0"):
        ref = ref[:-2]
    return ref


def _node_of(ref: str) -> str:
    ref = _canon(ref)
    return ref.split(":")[0]


#: output-arg order of mapped multi-output TF ops (from the TF op
#: registry): function-body refs name the PORT ('node:indices:0');
#: binding uses flat indices, so the port name must translate to its
#: base offset. Single-output ops need no entry (their only port is
#: flat index 0); ops with one REPEATED output arg ('output') are flat
#: already.
_TF_MULTI_OUT_ARGS = {
    "TopKV2": ["values", "indices"],
    "Unique": ["y", "idx"],
    "UniqueV2": ["y", "idx"],
    "FusedBatchNorm": ["y", "batch_mean", "batch_variance",
                       "reserve_space_1", "reserve_space_2"],
    "FusedBatchNormV2": ["y", "batch_mean", "batch_variance",
                         "reserve_space_1", "reserve_space_2"],
    "FusedBatchNormV3": ["y", "batch_mean", "batch_variance",
                         "reserve_space_1", "reserve_space_2",
                         "reserve_space_3"],
    "SoftmaxCrossEntropyWithLogits": ["loss", "backprop"],
    "SparseSoftmaxCrossEntropyWithLogits": ["loss", "backprop"],
}


def _canon_func_ref(ref: str, producer_ops: Optional[dict] = None
                    ) -> str:
    """Function-body tensor refs are ``node:out_arg_name:idx`` (vs the
    graph's ``node:idx``); normalize to the graph style the importer
    binds (flat-index ports: 'node' for 0, 'node:i' otherwise).
    ``producer_ops`` maps node name -> TF op name so named ports of
    multi-output ops translate to their flat offset."""
    if ref.startswith("^"):
        return ref
    parts = ref.split(":")
    if len(parts) == 3:
        node, port, idx = parts
        flat = int(idx)
        op_name = (producer_ops or {}).get(node)
        args = _TF_MULTI_OUT_ARGS.get(op_name)
        if args is not None:
            if port not in args:
                raise NotImplementedError(
                    f"TF import: unknown output port '{port}' of "
                    f"{op_name} node '{node}'")
            flat += args.index(port)
        return node if flat == 0 else f"{node}:{flat}"
    if len(parts) == 2 and not parts[1].isdigit():
        return parts[0]
    return ref


class _Ctx:
    """Mapping context handed to each TF_OP_MAP rule (the attr/tensor
    adapter surface of the reference's MappingProcess)."""

    def __init__(self, importer: "GraphDefImporter"):
        self._imp = importer
        self.sd = importer.sd

    def var(self, ref: str) -> SDVariable:
        return self._imp._materialize(_canon(ref))

    def static(self, ref: str) -> Optional[np.ndarray]:
        return self._imp.static_values.get(_canon(ref))

    def require_static(self, node: NodeDef, i: int) -> np.ndarray:
        ref = _canon(node.inputs[i])
        val = self._imp.static_values.get(ref)
        if val is None:
            raise ValueError(
                f"TF import: input {i} ('{ref}') of node "
                f"'{node.name}' ({node.op}) must be statically known — "
                f"provide concrete input_shapes so shape chains fold")
        return val


class GraphDefImporter:
    """One-shot importer for a frozen (inference) GraphDef."""

    def __init__(self, graph_def, input_shapes: Optional[dict] = None,
                 while_max_iterations=None,
                 outputs: Optional[List[str]] = None):
        if isinstance(graph_def, (str, os.PathLike)):
            with open(graph_def, "rb") as fh:
                graph_def = fh.read()
        self.functions: Dict[str, FunctionDef] = {}
        if isinstance(graph_def, (bytes, bytearray)):
            self.nodes, self.functions = parse_graphdef_with_library(
                bytes(graph_def))
        else:                        # already a parsed NodeDef list
            self.nodes = list(graph_def)
        self.input_shapes = {k: tuple(v) for k, v in
                             (input_shapes or {}).items()}
        #: int (all loops) or {while_node_name: int}: lower imported
        #: While ops to the bounded reverse-differentiable form
        #: (SameDiff.while_loop(max_iterations=...)); None = unbounded
        #: forward-only import
        self.while_max_iterations = while_max_iterations
        self.sd = SameDiff()
        self.static_values: Dict[str, np.ndarray] = {}
        self.var_map: Dict[str, SDVariable] = {}
        self.avals: Dict[str, jax.ShapeDtypeStruct] = {}
        self.placeholders: List[str] = []
        #: requested fetches; None = infer terminals after import.
        #: ':0' normalizes to the bare name (var_map keys the FIRST
        #: output bare, 'name:i' for the rest — see _bind)
        self.requested_outputs = (
            [o[:-2] if o.endswith(":0") else o for o in outputs]
            if outputs else None)
        self.outputs: List[str] = []

    # -- name/value plumbing ------------------------------------------
    def _materialize(self, name: str) -> SDVariable:
        v = self.var_map.get(name)
        if v is not None:
            return v
        if name in self.static_values:
            arr = self.static_values[name]
            if arr.dtype == object:
                raise ValueError(f"string tensor '{name}' cannot be a "
                                 f"graph input")
            c = self.sd.constant(name, arr)
            if c.name != name:       # name collided with an sd-internal
                raise RuntimeError(f"constant name collision: {name}")
            self.var_map[name] = c
            self.avals[name] = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
            return c
        raise KeyError(f"TF import: reference to unknown tensor "
                       f"'{name}'")

    def _bind(self, node: NodeDef, result, start_idx: int):
        """Attach mapping result vars to 'name', 'name:1', …"""
        if result is None:
            return
        outs = (list(result) if isinstance(result, (list, tuple))
                else [result])
        for i, v in enumerate(outs):
            target = node.name if i == 0 else f"{node.name}:{i}"
            if not isinstance(v, SDVariable):
                raise TypeError(f"mapping for {node.op} returned "
                                f"{type(v)}")
            if v.name in self.var_map:
                # passthrough of an already-bound tensor (constant
                # splat &c): alias the TF name to it, keep the var
                self.var_map[target] = v
                if v.name in self.avals:
                    self.avals[target] = self.avals[v.name]
            else:
                if v.name != target:
                    self._rename_local(v, target, start_idx)
                self.var_map[target] = v

    def _rename_local(self, v: SDVariable, new: str, start_idx: int):
        """Rename a var created by THIS mapping rule. Only ops emitted
        since start_idx can reference it, so the rewrite is O(ops in
        this rule) — not SameDiff._rename's whole-graph scan (which
        would make a 2000-node BERT import quadratic)."""
        sd = self.sd
        old = v.name
        if new in sd.vars:
            raise ValueError(f"variable '{new}' already exists")
        sd.vars.pop(old)
        v.name = new
        sd.vars[new] = v
        if old in sd._arrays:
            sd._arrays[new] = sd._arrays.pop(old)
        if old in sd._producer:
            sd._producer[new] = sd._producer.pop(old)
        for op_node in sd.ops[start_idx:]:
            op_node.inputs = [new if i == old else i
                              for i in op_node.inputs]
            op_node.outputs = [new if o == old else o
                               for o in op_node.outputs]

    # -- shape propagation --------------------------------------------
    def _infer_new_ops(self, start_idx: int):
        """eval_shape every op emitted since start_idx; record avals and
        fill in SDVariable shapes (cheap — no FLOPs, no device)."""
        for node in self.sd.ops[start_idx:]:
            in_avals = []
            ok = True
            for name in node.inputs:
                av = self.avals.get(name)
                if av is None:
                    arr = self.sd._arrays.get(name)
                    if arr is not None:
                        av = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
                        self.avals[name] = av
                    else:
                        ok = False
                        break
                in_avals.append(av)
            if not ok:
                continue
            attrs = dict(node.attrs or {})
            if node.op_name in ("random_normal", "random_uniform",
                                "random_bernoulli", "dropout"):
                attrs["rng"] = jax.random.PRNGKey(0)
            try:
                out = jax.eval_shape(
                    lambda *xs: get_op(node.op_name)(list(xs), attrs),
                    *in_avals)
            except Exception:
                continue
            outs = out if isinstance(out, (list, tuple)) else [out]
            for on, av in zip(node.outputs, outs):
                self.avals[on] = jax.ShapeDtypeStruct(av.shape, av.dtype)
                sv = self.sd.vars[on]
                sv.shape = tuple(av.shape)
                sv.dtype = av.dtype

    def _known_shape(self, ref: str) -> Optional[Tuple[int, ...]]:
        av = self.avals.get(ref)
        if av is not None:
            return tuple(av.shape)
        arr = self.static_values.get(ref)
        if arr is not None:
            return tuple(arr.shape)
        return None

    # -- constant folding ---------------------------------------------
    def _try_fold(self, node: NodeDef) -> bool:
        fold = _FOLDERS.get(node.op)
        if fold is None:
            return False
        try:
            result = fold(self, node)
        except _NoFold:
            return False
        if result is None:
            return False
        if isinstance(result, (list, tuple)):
            for i, arr in enumerate(result):
                key = node.name if i == 0 else f"{node.name}:{i}"
                self.static_values[key] = np.asarray(arr)
        else:
            self.static_values[node.name] = np.asarray(result)
        return True

    def _statics(self, node: NodeDef) -> List[np.ndarray]:
        vals = []
        for ref in node.inputs:
            if ref.startswith("^"):
                continue
            v = self.static_values.get(_canon(ref))
            if v is None:
                raise _NoFold()
            vals.append(v)
        return vals

    # -- main loop -----------------------------------------------------
    def _all_reachable_nodes(self, order) -> List[NodeDef]:
        """Top-level nodes plus the bodies of every function reachable
        through functional control-flow (transitively), so the
        unmapped-op precheck sees loop/branch internals too."""
        out = list(order)
        seen = set()
        stack = list(order)
        while stack:
            node = stack.pop()
            if node.op not in _FUNCTIONAL_OPS:
                continue
            for key in ("cond", "body", "then_branch", "else_branch"):
                fname = node.attr(key)
                if not fname or fname in seen:
                    continue
                seen.add(fname)
                fd = self.functions.get(fname)
                if fd is None:
                    continue        # _function raises at import time
                out.extend(fd.nodes)
                stack.extend(fd.nodes)
        return out

    def run(self, optimize: Optional[bool] = None) -> SameDiff:
        if any(n.op in v1_control_flow.V1_CONTROL_FLOW_OPS
               for n in self.nodes):
            # legacy v1 frames (frozen tf.while_loop/tf.cond) →
            # functional While/If, which lower to lax below
            self.nodes = v1_control_flow.deframe(
                self.nodes, self.functions,
                keep=frozenset(_node_of(o) for o in
                               (self.requested_outputs or ())))
        _resolve_tensor_lists(self.nodes)
        by_name = {n.name: n for n in self.nodes}
        order = _topo_sort(self.nodes, by_name)
        unmapped = sorted({n.op
                           for n in self._all_reachable_nodes(order)
                           if n.op not in TF_OP_MAP
                           and n.op not in ("Const", "Placeholder")
                           and n.op not in _SKIP_OPS
                           and n.op not in _FUNCTIONAL_OPS
                           and n.op not in _FOLDERS})
        if unmapped:
            raise NotImplementedError(
                f"TF import: no mapping for ops {unmapped} "
                f"(reference parity: OpMappingRegistry lookup failure)")
        self._import_node_list(order, _Ctx(self))
        if self.requested_outputs is not None:
            missing = [o for o in self.requested_outputs
                       if o not in self.var_map]
            if missing:
                raise KeyError(f"TF import: requested outputs "
                               f"{missing} not found in graph")
            self.outputs = list(self.requested_outputs)
        else:
            self.outputs = _terminal_names(order, self.var_map)
        # post-import GraphOptimizer pipeline: canonicalize the
        # exporter's baked cast/mask/LayerNorm/GELU arithmetic and
        # fuse attention (autodiff.passes). Default on; kill with
        # DL4J_TPU_GRAPHOPT=0 or optimize=False.
        from deeplearning4j_tpu.autodiff.passes import graphopt_enabled
        if optimize if optimize is not None else graphopt_enabled():
            self.graphopt_counts = self.sd.optimize()
            self.sd.graphopt_counts = self.graphopt_counts
        return self.sd

    def _import_node_list(self, order, ctx):
        """The per-node import loop — shared by the top-level graph
        and function bodies (While/If cond/body subgraphs)."""
        for node in order:
            if node.op in _SKIP_OPS:
                continue
            if node.op == "Const":
                val = node.attr("value")
                if isinstance(val, Exception):
                    raise NotImplementedError(
                        f"TF import: Const '{node.name}' holds a "
                        f"tensor this decoder cannot represent "
                        f"({val})") from val
                self.static_values[node.name] = val
                continue
            if node.op == "Placeholder":
                shape = self.input_shapes.get(node.name)
                if shape is None:
                    shape = node.attr("shape")
                dtype = tf_dtype_to_np(int(node.attr("dtype", 1)))
                ph = self.sd.placeholder(node.name, shape, dtype)
                self.var_map[node.name] = ph
                self.placeholders.append(node.name)
                if shape is not None and all(
                        d is not None and d >= 0 for d in shape):
                    self.avals[node.name] = jax.ShapeDtypeStruct(
                        tuple(shape), np.dtype(dtype))
                continue
            if node.op in ("While", "StatelessWhile"):
                self._import_while(node)
                continue
            if node.op in ("If", "StatelessIf"):
                self._import_if(node)
                continue
            if self._try_fold(node):
                continue
            # control deps ('^x') order execution in TF; the compiled
            # XLA program has no side effects to order, so they are
            # dropped before positional/variadic input handling
            node.inputs = [r for r in node.inputs
                           if not r.startswith("^")]
            rule = TF_OP_MAP[node.op]
            n_ops_before = len(self.sd.ops)
            result = rule(ctx, node)
            self._bind(node, result, n_ops_before)
            self._infer_new_ops(n_ops_before)

    # -- functional control flow (TF2 While/If; SURVEY.md S3:
    # the reference maps legacy Enter/Exit/NextIteration frames — TF2
    # exports the same loops as library functions) -------------------
    def _function(self, name: str) -> FunctionDef:
        fd = self.functions.get(name)
        if fd is None:
            raise NotImplementedError(
                f"TF import: GraphDef references function '{name}' "
                f"but the library does not define it")
        return fd

    def _function_as_callable(self, fd: FunctionDef):
        """Wrap a FunctionDef as a python callable over SDVariables,
        suitable for SameDiff.while_loop/cond subgraph tracing: the
        body's nodes import into the CHILD graph the proxies live in,
        with function args bound by position."""
        arg_names = [a for a, _ in fd.input_args]
        producer_ops = {n.name: n.op for n in fd.nodes}
        norm_nodes = [
            NodeDef(n.name, n.op,
                    [_canon_func_ref(r, producer_ops)
                     for r in n.inputs],
                    n.attrs)
            for n in fd.nodes]
        _resolve_tensor_lists(norm_nodes)

        def fn(*args):
            # the child graph comes from the proxies, or (zero-arg
            # branches) from the handle _trace_subgraph publishes
            child_sd = (args[0].sd if args
                        else getattr(fn, "_trace_child_sd", self.sd))
            sub = GraphDefImporter.__new__(GraphDefImporter)
            sub.nodes = norm_nodes
            sub.functions = self.functions
            sub.input_shapes = {}
            sub.while_max_iterations = self.while_max_iterations
            sub.sd = child_sd
            sub.static_values = {}
            sub.var_map = dict(zip(arg_names, args))
            sub.avals = {}
            sub.placeholders = []
            sub.outputs = []
            by_name = {n.name: n for n in norm_nodes}
            order = _topo_sort(norm_nodes, by_name,
                               external=set(arg_names))
            sub._import_node_list(order, _Ctx(sub))
            outs = []
            for out_name, _ in fd.output_args:
                ref = _canon_func_ref(fd.ret.get(out_name, out_name),
                                      producer_ops)
                outs.append(sub._materialize(_canon(ref)))
            return outs

        return fn

    def _import_while(self, node: NodeDef):
        cond_fd = self._function(node.attr("cond"))
        body_fd = self._function(node.attr("body"))
        loop_vars = [self._materialize(_canon(r)) for r in node.inputs
                     if not r.startswith("^")]
        mi = self.while_max_iterations
        if isinstance(mi, dict):
            key = node.name
            if key not in mi and key.endswith("__v1_while"):
                # deframed v1 loop: fall back to the TF loop name
                key = key[:-len("__v1_while")]
            mi = mi.get(key)
        n_ops_before = len(self.sd.ops)
        outs = self.sd.while_loop(
            loop_vars, self._function_as_callable(cond_fd),
            self._function_as_callable(body_fd),
            max_iterations=None if mi is None else int(mi))
        self._bind(node, outs, n_ops_before)
        self._infer_new_ops(n_ops_before)

    def _import_if(self, node: NodeDef):
        then_fd = self._function(node.attr("then_branch"))
        else_fd = self._function(node.attr("else_branch"))
        ins = [r for r in node.inputs if not r.startswith("^")]
        pred = self._materialize(_canon(ins[0]))
        operands = [self._materialize(_canon(r)) for r in ins[1:]]
        n_ops_before = len(self.sd.ops)
        outs = self.sd.cond(
            pred, self._function_as_callable(then_fd),
            self._function_as_callable(else_fd), operands)
        self._bind(node, outs, n_ops_before)
        self._infer_new_ops(n_ops_before)


def _resolve_tensor_lists(nodes: Sequence[NodeDef]):
    """Pre-pass for TensorArray/TensorList graphs: a static-size list
    materializes as a dense [n, *element_shape] zeros tensor (the
    XLA-native loop-carry accumulator).  TF records element_shape=-1
    on TensorListReserve but the CONCRETE shape on downstream
    Stack/GetItem/Gather consts, so the handle is followed — including
    POSITIONALLY through While/StatelessWhile boundaries (functional
    While maps inputs to outputs 1:1) — until a concrete shape
    appears.  Results are stashed in the Reserve node's attrs for the
    mapping rule; unresolved Reserves fail loudly there."""
    by_name = {n.name: n for n in nodes}

    def const_ints(ref):
        nd = by_name.get(_node_of(ref))
        if nd is None or nd.op != "Const":
            return None
        val = nd.attr("value")
        if isinstance(val, Exception):
            return None
        arr = np.asarray(val).reshape(-1)
        if arr.size and (arr.astype(np.int64) < 0).any():
            return None
        return tuple(int(v) for v in arr)

    for res in nodes:
        if res.op != "TensorListReserve":
            continue
        data_in = [r for r in res.inputs if not r.startswith("^")]
        shape = const_ints(data_in[0])        # concrete on the nose?
        num = const_ints(data_in[1])
        num = num[0] if num else None
        aliases = {res.name}
        changed = True
        while changed and shape is None:
            changed = False
            for n in nodes:
                data = [r for r in n.inputs if not r.startswith("^")]
                for i, r in enumerate(data):
                    if _canon(r) not in aliases:
                        continue
                    if n.op in ("While", "StatelessWhile"):
                        al = n.name if i == 0 else f"{n.name}:{i}"
                        if al not in aliases:
                            aliases.add(al)
                            changed = True
                    elif n.op in ("Identity", "TensorListSetItem") \
                            and i == 0 and n.name not in aliases:
                        # SetItem returns the updated handle
                        aliases.add(n.name)
                        changed = True
                    elif n.op in ("TensorListStack",
                                  "TensorListGetItem",
                                  "TensorListGather") and i == 0:
                        sh = const_ints(data[-1])
                        if sh is not None:
                            shape = sh
        if shape is not None and num is not None:
            res.attrs["_tl_shape"] = Attr("resolved", shape)
            res.attrs["_tl_num"] = Attr("resolved", num)


class _NoFold(Exception):
    pass


def _topo_sort(nodes: Sequence[NodeDef], by_name,
               external=frozenset()) -> List[NodeDef]:
    """``external``: names resolvable outside this node list (function
    args in While/If bodies) — legal dangling references."""
    order: List[NodeDef] = []
    state: Dict[str, int] = {}        # 0 visiting, 1 done

    def visit(n: NodeDef):
        stack = [(n, iter(n.inputs))]
        state[n.name] = 0
        while stack:
            node, it = stack[-1]
            advanced = False
            for ref in it:
                dep = by_name.get(_node_of(ref))
                if dep is None:
                    if _node_of(ref) in external:
                        continue
                    raise KeyError(f"missing node '{_node_of(ref)}'")
                st = state.get(dep.name)
                if st == 0:
                    raise ValueError(f"cycle at '{dep.name}' — "
                                     f"control-flow loops unsupported")
                if st is None:
                    state[dep.name] = 0
                    stack.append((dep, iter(dep.inputs)))
                    advanced = True
                    break
            if not advanced:
                state[node.name] = 1
                order.append(node)
                stack.pop()

    for n in nodes:
        if state.get(n.name) is None:
            visit(n)
    return order


def _terminal_names(order, var_map) -> List[str]:
    consumed = set()
    for n in order:
        for ref in n.inputs:
            consumed.add(_node_of(ref))
    return [n.name for n in order
            if n.name not in consumed and n.name in var_map]


# -- numpy constant folders -------------------------------------------------
def _f_shape(imp, node):
    ref = _canon(node.inputs[0])
    shape = imp._known_shape(ref)
    if shape is None or any(d is None or d < 0 for d in shape):
        raise _NoFold()
    return np.asarray(shape, np.int32)


def _f_identity(imp, node):
    return imp._statics(node)[0]


def _f_slice(imp, node):
    x, begin, size = imp._statics(node)
    idx = tuple(slice(int(b), None if int(s) == -1 else int(b) + int(s))
                for b, s in zip(begin.reshape(-1), size.reshape(-1)))
    return np.asarray(x)[idx]


def _f_strided_slice(imp, node):
    from deeplearning4j_tpu.autodiff.registry import spec_to_index
    x, begin, end, strides = imp._statics(node)
    spec = mappings.strided_slice_spec(
        [int(v) for v in begin], [int(v) for v in end],
        [int(v) for v in strides], node.attr("begin_mask", 0),
        node.attr("end_mask", 0), node.attr("ellipsis_mask", 0),
        node.attr("new_axis_mask", 0), node.attr("shrink_axis_mask", 0))
    return np.asarray(x)[spec_to_index(spec)]


def _f_pack(imp, node):
    return np.stack(imp._statics(node), axis=node.attr("axis", 0))


def _f_concat(imp, node):
    vals = imp._statics(node)
    return np.concatenate(vals[:-1], axis=int(vals[-1]))


def _f_binop(fn):
    def fold(imp, node):
        a, b = imp._statics(node)
        return fn(a, b)
    return fold


def _f_unop(fn):
    def fold(imp, node):
        return fn(imp._statics(node)[0])
    return fold


def _f_reshape(imp, node):
    x, shape = imp._statics(node)
    return np.reshape(x, [int(s) for s in shape])


def _f_cast(imp, node):
    dst = tf_dtype_to_np(int(node.attr("DstT", 1)))
    return imp._statics(node)[0].astype(dst)


def _f_range(imp, node):
    s, l, d = [np.asarray(v).reshape(())[()] for v in
               imp._statics(node)]
    return np.arange(s, l, d)


def _f_fill(imp, node):
    dims, val = imp._statics(node)
    return np.full([int(d) for d in dims],
                   np.asarray(val).reshape(())[()])


def _f_gather_v2(imp, node):
    if int(node.attr("batch_dims", 0)) != 0:
        raise _NoFold()          # keep parity with the emit path
    x, idx, axis = imp._statics(node)
    return np.take(x, idx.astype(np.int64), axis=int(axis))


def _f_expand_dims(imp, node):
    x, ax = imp._statics(node)
    return np.expand_dims(x, int(np.asarray(ax).reshape(())[()]))


def _f_squeeze(imp, node):
    dims = node.attr("squeeze_dims") or None
    x = imp._statics(node)[0]
    return np.squeeze(x, tuple(int(d) for d in dims) if dims else None)


def _f_transpose(imp, node):
    x, perm = imp._statics(node)
    return np.transpose(x, [int(p) for p in perm])


def _f_prod(imp, node):
    x, axes = imp._statics(node)
    return np.prod(x, axis=tuple(int(a) for a in
                                 np.asarray(axes).reshape(-1)),
                   keepdims=bool(node.attr("keep_dims", False)))


def _f_unpack(imp, node):
    x = imp._statics(node)[0]
    axis = node.attr("axis", 0)
    return [np.squeeze(s, axis) for s in
            np.split(x, x.shape[axis], axis=axis)]


def _f_size(imp, node):
    ref = _canon(node.inputs[0])
    shape = imp._known_shape(ref)
    if shape is None or any(d is None or d < 0 for d in shape):
        raise _NoFold()
    return np.asarray(int(np.prod(shape)), np.int32)


def _f_rank(imp, node):
    ref = _canon(node.inputs[0])
    shape = imp._known_shape(ref)
    if shape is None:
        raise _NoFold()
    return np.asarray(len(shape), np.int32)


_FOLDERS = {
    "Shape": _f_shape, "ShapeN": None, "Size": _f_size, "Rank": _f_rank,
    "Identity": _f_identity, "StridedSlice": _f_strided_slice,
    "Slice": _f_slice,
    "Pack": _f_pack, "ConcatV2": _f_concat, "Reshape": _f_reshape,
    "Cast": _f_cast, "Range": _f_range, "Fill": _f_fill,
    "GatherV2": _f_gather_v2, "ExpandDims": _f_expand_dims,
    "Squeeze": _f_squeeze, "Transpose": _f_transpose, "Prod": _f_prod,
    "Unpack": _f_unpack,
    "Add": _f_binop(np.add), "AddV2": _f_binop(np.add),
    "Sub": _f_binop(np.subtract), "Mul": _f_binop(np.multiply),
    "RealDiv": _f_binop(np.true_divide),
    "FloorDiv": _f_binop(np.floor_divide),
    "FloorMod": _f_binop(np.mod),
    "Maximum": _f_binop(np.maximum), "Minimum": _f_binop(np.minimum),
    "Neg": _f_unop(np.negative),
}
_FOLDERS = {k: v for k, v in _FOLDERS.items() if v is not None}


class TensorflowFrameworkImporter:
    """Reference: org.nd4j.samediff.frameworkimport.tensorflow.importer.
    TensorflowFrameworkImporter (SURVEY.md S6)."""

    @staticmethod
    def run_import(graph_def, input_shapes: Optional[dict] = None,
                   while_max_iterations=None,
                   outputs: Optional[List[str]] = None,
                   optimize: Optional[bool] = None) -> SameDiff:
        return GraphDefImporter(graph_def, input_shapes,
                                while_max_iterations,
                                outputs=outputs).run(optimize=optimize)

    runImport = run_import


class TFGraphMapper:
    """Legacy front-door (reference: TFGraphMapper, SURVEY.md S7)."""

    @staticmethod
    def import_graph(graph_def, input_shapes: Optional[dict] = None,
                     while_max_iterations=None,
                     outputs: Optional[List[str]] = None,
                     optimize: Optional[bool] = None) -> SameDiff:
        return GraphDefImporter(graph_def, input_shapes,
                                while_max_iterations,
                                outputs=outputs).run(optimize=optimize)

    importGraph = import_graph
