"""Minimal protobuf wire-format decoder for TensorFlow GraphDef.

Reference parity: the Kotlin import stack parses TF protos via
generated protobuf classes (SURVEY.md S6, `samediff-import-tensorflow`).
TPU-first twist: we decode the wire format directly (~no TF or
protobuf-runtime dependency at import time), covering exactly the
message subset a frozen GraphDef uses: GraphDef, NodeDef, AttrValue,
TensorProto, TensorShapeProto.

Wire format: each field is a (field_number << 3 | wire_type) varint key
followed by a payload — varint (0), fixed64 (1), length-delimited (2),
fixed32 (5).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np


def _varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _signed(v: int) -> int:
    """protobuf int64: negative values are 64-bit two's complement."""
    return v - (1 << 64) if v >= (1 << 63) else v


def decode_fields(buf: bytes) -> Dict[int, List[Tuple[int, object]]]:
    """Decode one message into {field_number: [(wire_type, raw), ...]}."""
    fields: Dict[int, List[Tuple[int, object]]] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _varint(buf, pos)
        fnum, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = _varint(buf, pos)
        elif wt == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = _varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        fields.setdefault(fnum, []).append((wt, val))
    return fields


def _packed_varints(entries) -> List[int]:
    """A repeated varint field: packed (wire 2) or unpacked (wire 0)."""
    out: List[int] = []
    for wt, raw in entries:
        if wt == 0:
            out.append(_signed(raw))
        else:
            pos = 0
            while pos < len(raw):
                v, pos = _varint(raw, pos)
                out.append(_signed(v))
    return out


def _packed_floats(entries) -> List[float]:
    out: List[float] = []
    for wt, raw in entries:
        if wt == 5:
            out.append(struct.unpack("<f", raw)[0])
        else:
            out.extend(struct.unpack(f"<{len(raw) // 4}f", raw))
    return out


def _packed_doubles(entries) -> List[float]:
    out: List[float] = []
    for wt, raw in entries:
        if wt == 1:
            out.append(struct.unpack("<d", raw)[0])
        else:
            out.extend(struct.unpack(f"<{len(raw) // 8}d", raw))
    return out


# TF DataType enum -> numpy dtype (common subset)
TF_DTYPES: Dict[int, np.dtype] = {
    1: np.dtype(np.float32), 2: np.dtype(np.float64),
    3: np.dtype(np.int32), 4: np.dtype(np.uint8),
    5: np.dtype(np.int16), 6: np.dtype(np.int8),
    9: np.dtype(np.int64), 10: np.dtype(np.bool_),
    17: np.dtype(np.uint16), 19: np.dtype(np.float16),
    22: np.dtype(np.uint32), 23: np.dtype(np.uint64),
}


def tf_dtype_to_np(enum: int) -> np.dtype:
    if enum == 14:                       # DT_BFLOAT16
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    if enum == 7:                        # DT_STRING
        return np.dtype(object)
    if enum in TF_DTYPES:
        return TF_DTYPES[enum]
    raise ValueError(f"unsupported TF dtype enum {enum}")


def parse_shape(buf: bytes) -> Optional[Tuple[int, ...]]:
    """TensorShapeProto: dim=2 (Dim.size=1), unknown_rank=3."""
    f = decode_fields(buf)
    if 3 in f and f[3][0][1]:
        return None
    dims = []
    for _, dbuf in f.get(2, []):
        df = decode_fields(dbuf)
        # proto3 omits zero-valued fields: an absent size IS 0 (e.g.
        # the shape-[0] element_shape tensor of a scalar TensorList);
        # unknown dims are an explicit -1
        size = _signed(df[1][0][1]) if 1 in df else 0
        dims.append(size)
    return tuple(dims)


def parse_tensor(buf: bytes) -> np.ndarray:
    """TensorProto → numpy array."""
    f = decode_fields(buf)
    dtype_enum = f[1][0][1] if 1 in f else 1
    dtype = tf_dtype_to_np(dtype_enum)
    shape = parse_shape(f[2][0][1]) if 2 in f else ()
    shape = tuple(d for d in (shape or ()))
    count = int(np.prod(shape)) if shape else 1
    if 4 in f and len(f[4][0][1]):                 # tensor_content
        content = b"".join(raw for _, raw in f[4])
        arr = np.frombuffer(content, dtype=dtype)
        return arr.reshape(shape).copy()
    vals: Optional[np.ndarray] = None
    if dtype_enum in (1,) and 5 in f:              # float_val
        vals = np.asarray(_packed_floats(f[5]), np.float32)
    elif dtype_enum == 2 and 6 in f:               # double_val
        vals = np.asarray(_packed_doubles(f[6]), np.float64)
    elif dtype_enum in (3, 4, 5, 6, 17) and 7 in f:  # int_val
        vals = np.asarray(_packed_varints(f[7]), dtype)
    elif dtype_enum == 9 and 10 in f:              # int64_val
        vals = np.asarray(_packed_varints(f[10]), np.int64)
    elif dtype_enum == 10 and 11 in f:             # bool_val
        vals = np.asarray([bool(v) for v in _packed_varints(f[11])])
    elif dtype_enum in (14, 19) and 13 in f:       # half_val (bit patterns)
        bits = np.asarray(_packed_varints(f[13]), np.uint16)
        vals = bits.view(dtype)
    elif dtype_enum == 7 and 8 in f:               # string_val
        vals = np.asarray([raw for _, raw in f[8]], object)
    if vals is None:
        return np.zeros(shape, dtype)
    if vals.size == 1 and count > 1:               # splat fill
        return np.full(shape, vals.reshape(-1)[0], dtype)
    return vals.reshape(shape)


class Attr:
    """One decoded AttrValue. ``kind`` in {s,i,f,b,type,shape,tensor,
    list,func,placeholder}; ``value`` is the python-native payload."""
    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value):
        self.kind = kind
        self.value = value

    def __repr__(self):
        return f"Attr({self.kind}={self.value!r})"


def parse_attr(buf: bytes) -> Attr:
    f = decode_fields(buf)
    if 2 in f:
        return Attr("s", f[2][0][1])
    if 3 in f:
        return Attr("i", _signed(f[3][0][1]))
    if 4 in f:
        return Attr("f", struct.unpack("<f", f[4][0][1])[0])
    if 5 in f:
        return Attr("b", bool(f[5][0][1]))
    if 6 in f:
        return Attr("type", f[6][0][1])
    if 7 in f:
        return Attr("shape", parse_shape(f[7][0][1]))
    if 8 in f:
        try:
            return Attr("tensor", parse_tensor(f[8][0][1]))
        except Exception as e:
            # e.g. DT_VARIANT consts (TensorArray/TensorList flow
            # state) or unknown-rank shapes: defer the failure so the
            # importer's unmapped-op precheck can report the real
            # problem first; touching the value raises then
            return Attr("tensor_error", e)
    if 10 in f:
        nf = decode_fields(f[10][0][1])
        name = nf[1][0][1].decode() if 1 in nf else ""
        return Attr("func", name)
    if 1 in f:                                     # ListValue
        lf = decode_fields(f[1][0][1])
        if 2 in lf:
            return Attr("list", [raw for _, raw in lf[2]])
        if 3 in lf:
            return Attr("list", _packed_varints(lf[3]))
        if 4 in lf:
            return Attr("list", _packed_floats(lf[4]))
        if 5 in lf:
            return Attr("list", [bool(v) for v in _packed_varints(lf[5])])
        if 6 in lf:
            return Attr("list", _packed_varints(lf[6]))
        if 7 in lf:
            return Attr("list", [parse_shape(raw) for _, raw in lf[7]])
        if 8 in lf:
            return Attr("list", [parse_tensor(raw) for _, raw in lf[8]])
        return Attr("list", [])
    return Attr("b", False)


class NodeDef:
    __slots__ = ("name", "op", "inputs", "attrs")

    def __init__(self, name: str, op: str, inputs: List[str],
                 attrs: Dict[str, Attr]):
        self.name = name
        self.op = op
        self.inputs = inputs
        self.attrs = attrs

    def attr(self, key: str, default=None):
        a = self.attrs.get(key)
        return a.value if a is not None else default

    def __repr__(self):
        return f"NodeDef({self.op} '{self.name}' <- {self.inputs})"


def parse_node(buf: bytes) -> NodeDef:
    f = decode_fields(buf)
    name = f[1][0][1].decode() if 1 in f else ""
    op = f[2][0][1].decode() if 2 in f else ""
    inputs = [raw.decode() for _, raw in f.get(3, [])]
    attrs: Dict[str, Attr] = {}
    for _, entry in f.get(5, []):                  # map<string, AttrValue>
        ef = decode_fields(entry)
        key = ef[1][0][1].decode() if 1 in ef else ""
        attrs[key] = parse_attr(ef[2][0][1]) if 2 in ef else Attr("b",
                                                                  False)
    return NodeDef(name, op, inputs, attrs)


def parse_graphdef(buf: bytes) -> List[NodeDef]:
    """GraphDef: node=1 (repeated NodeDef)."""
    f = decode_fields(buf)
    return [parse_node(raw) for _, raw in f.get(1, [])]


class FunctionDef:
    """One decoded tf.FunctionDef (the body/cond subgraphs of
    functional control flow: While/StatelessWhile/If)."""
    __slots__ = ("name", "input_args", "output_args", "nodes", "ret")

    def __init__(self, name, input_args, output_args, nodes, ret):
        self.name = name
        self.input_args = input_args    # [(arg_name, dtype_enum)]
        self.output_args = output_args  # [(arg_name, dtype_enum)]
        self.nodes = nodes              # [NodeDef]
        self.ret = ret                  # {output_arg_name: tensor_ref}

    def __repr__(self):
        return (f"FunctionDef('{self.name}' "
                f"{[a for a, _ in self.input_args]} -> "
                f"{[a for a, _ in self.output_args]}, "
                f"{len(self.nodes)} nodes)")


def _parse_arg_def(buf: bytes) -> Tuple[str, int]:
    """OpDef.ArgDef: name=1, type=3."""
    f = decode_fields(buf)
    name = f[1][0][1].decode() if 1 in f else ""
    dtype = f[3][0][1] if 3 in f else 0
    return name, dtype


def parse_function_def(buf: bytes) -> FunctionDef:
    """FunctionDef: signature(OpDef)=1, node_def=3, ret(map)=4."""
    f = decode_fields(buf)
    name, in_args, out_args = "", [], []
    if 1 in f:                                     # OpDef
        sf = decode_fields(f[1][0][1])
        name = sf[1][0][1].decode() if 1 in sf else ""
        in_args = [_parse_arg_def(raw) for _, raw in sf.get(2, [])]
        out_args = [_parse_arg_def(raw) for _, raw in sf.get(3, [])]
    nodes = [parse_node(raw) for _, raw in f.get(3, [])]
    ret: Dict[str, str] = {}
    for _, entry in f.get(4, []):                  # map<string,string>
        ef = decode_fields(entry)
        k = ef[1][0][1].decode() if 1 in ef else ""
        v = ef[2][0][1].decode() if 2 in ef else ""
        ret[k] = v
    return FunctionDef(name, in_args, out_args, nodes, ret)


def parse_graphdef_with_library(buf: bytes
                                ) -> Tuple[List[NodeDef],
                                           Dict[str, FunctionDef]]:
    """GraphDef: node=1, library(FunctionDefLibrary{function=1})=2."""
    f = decode_fields(buf)
    nodes = [parse_node(raw) for _, raw in f.get(1, [])]
    functions: Dict[str, FunctionDef] = {}
    for _, raw in f.get(2, []):
        lf = decode_fields(raw)
        for _, fraw in lf.get(1, []):
            fd = parse_function_def(fraw)
            functions[fd.name] = fd
    return nodes, functions
