"""TF GraphDef import (SURVEY.md S6/S7)."""
from deeplearning4j_tpu.modelimport.tensorflow.importer import (
    GraphDefImporter, TensorflowFrameworkImporter, TFGraphMapper)

__all__ = ["GraphDefImporter", "TensorflowFrameworkImporter",
           "TFGraphMapper"]
