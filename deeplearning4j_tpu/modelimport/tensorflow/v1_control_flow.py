"""TF v1 (legacy) control-flow frame reconstruction.

Reference parity: `org.nd4j.imports.graphmapper.tf.TFGraphMapper` and
the Kotlin `samediff-import-tensorflow` stack map the v1 dataflow ops
Enter/Exit/NextIteration/Merge/Switch/LoopCond directly (SURVEY.md
S3/S7).  Frozen graphs — the dominant real-world TF export form,
produced by ``convert_variables_to_constants`` — lower every
``tf.while_loop``/``tf.cond`` into these frames.

TPU-first design: instead of replaying TF's tagged-token dataflow
machine (per-op dead/alive propagation — hostile to XLA's static
schedule), this pass RECONSTRUCTS the structured form: each while
frame becomes a synthetic functional ``While`` node (cond/body as
synthetic FunctionDefs) and each Switch/Merge diamond becomes a
synthetic ``If`` — both of which the importer already lowers to
``lax.while_loop``/``lax.scan``/``lax.cond`` inside ONE jitted XLA
program.

Frame anatomy (per loop variable i)::

    Enter_i -> Merge_i(Enter_i, NextIteration_i)
            -> Switch_i(Merge_i, LoopCond)
               ├── :0 (pred false) -> Exit_i        (leaves the frame)
               └── :1 (pred true)  -> body ... -> NextIteration_i

Loop invariants enter via ``Enter(is_constant=true)`` with no
Merge/Switch and are threaded as extra pass-through loop vars.  v1
``tf.cond`` has no frame: every external value enters a branch through
``Switch(value, pred)`` and the branch results join at ``Merge``;
port 1 is the true branch, port 0 the false branch.
"""
from __future__ import annotations

from typing import (AbstractSet, Dict, List, Optional, Sequence, Set,
                    Tuple)

from deeplearning4j_tpu.modelimport.tensorflow.protobuf import (
    Attr, FunctionDef, NodeDef)

_ENTER = {"Enter", "RefEnter"}
_EXIT = {"Exit", "RefExit"}
_MERGE = {"Merge", "RefMerge"}
_SWITCH = {"Switch", "RefSwitch"}
_NEXT = {"NextIteration", "RefNextIteration"}
#: every op this pass must eliminate; anything left after deframing is
#: an irreducible structure and fails loudly
V1_CONTROL_FLOW_OPS = (_ENTER | _EXIT | _MERGE | _SWITCH | _NEXT
                       | {"LoopCond"})


def _node_of(ref: str) -> str:
    if ref.startswith("^"):
        ref = ref[1:]
    return ref.split(":")[0]


def _port_of(ref: str) -> int:
    if ref.startswith("^"):
        return -1
    parts = ref.split(":")
    return int(parts[-1]) if len(parts) > 1 else 0


def _data_inputs(node: NodeDef) -> List[str]:
    return [r for r in node.inputs if not r.startswith("^")]


class _Irreducible(NotImplementedError):
    pass


def _err(msg: str) -> _Irreducible:
    return _Irreducible(
        f"TF import: legacy v1 control flow: {msg} (reference parity: "
        f"TFGraphMapper frame reconstruction)")


def deframe(nodes: List[NodeDef], functions: Dict[str, FunctionDef],
            keep: AbstractSet[str] = frozenset()) -> List[NodeDef]:
    """Rewrite all v1 while frames and Switch/Merge conds in ``nodes``
    into functional While/If nodes.  Registers synthetic FunctionDefs
    into ``functions`` (mutated).  Returns the new node list.
    ``keep`` names requested graph outputs (fetches): they are never
    removed by the dead-node sweep even if rewriting swallowed their
    last consumer."""
    nodes = _deframe_whiles(nodes, functions, keep)
    nodes = _deframe_conds(nodes, functions, keep=keep)
    return _sweep_dead_v1(nodes)


# -- shared helpers ----------------------------------------------------------

def _consumers_map(nodes: Sequence[NodeDef]) -> Dict[str, List[NodeDef]]:
    out: Dict[str, List[NodeDef]] = {}
    for n in nodes:
        for ref in n.inputs:
            out.setdefault(_node_of(ref), []).append(n)
    return out


def _is_pivot_anchor(node: Optional[NodeDef], by_name) -> bool:
    """The switch_t/switch_f shape: an Identity whose single data
    input is a Switch port — the control anchor v1 conds hang
    constant-only branches on."""
    if node is None or node.op != "Identity":
        return False
    data = _data_inputs(node)
    if len(data) != 1:
        return False
    sw = by_name.get(_node_of(data[0]))
    return sw is not None and sw.op in _SWITCH


def _backslice(roots: Sequence[str], by_name: Dict[str, NodeDef],
               stops: Set[str], follow_anchors: bool = False
               ) -> Set[str]:
    """Backward data-flow closure from ``roots`` (node names), never
    entering ``stops``.  With ``follow_anchors`` the walk also crosses
    control deps whose target matches the PIVOT-ANCHOR shape
    (Identity-of-Switch) — while-frame slices need it so a nested
    cond's ``^switch_t`` chain (anchor → pivot Switch → pred) rides
    into the body function where the cond reconstruction can use it.
    Arbitrary control deps (e.g. ``^ext`` ordering against outer
    nodes) are NOT followed — swallowing out-of-frame nodes would
    delete them from the enclosing graph.  Names not in ``by_name``
    terminate the walk."""
    seen: Set[str] = set()
    stack = [r for r in roots if r not in stops]
    while stack:
        nm = stack.pop()
        if nm in seen or nm in stops:
            continue
        node = by_name.get(nm)
        if node is None:
            continue
        seen.add(nm)
        for ref in node.inputs:
            dep = _node_of(ref)
            if dep in seen or dep in stops:
                continue
            if ref.startswith("^"):
                if follow_anchors and _is_pivot_anchor(
                        by_name.get(dep), by_name):
                    stack.append(dep)
                continue
            stack.append(dep)
    return seen


def _fresh(base: str, taken) -> str:
    name = base
    k = 0
    while name in taken:
        k += 1
        name = f"{base}_{k}"
    return name


def _guarded_rewrite(ref: str, ref_map: Dict[str, str],
                     expect_port: Dict[str, int]) -> str:
    """Boundary ref → argument name, refusing dead-port reads (e.g.
    Switch:0 inside a loop body, Merge:1 value_index)."""
    nm = _node_of(ref)
    arg = ref_map.get(nm)
    if arg is None:
        return ref
    want = expect_port.get(nm)
    if want is not None and _port_of(ref) != want:
        raise _err(f"a subgraph reads port {_port_of(ref)} of '{nm}' "
                   f"(expected port {want})")
    return arg


def _rewrite_slice(slice_nodes: Sequence[NodeDef],
                   ref_map: Dict[str, str],
                   expect_port: Dict[str, int]) -> List[NodeDef]:
    """Copy slice nodes into a synthetic function body: boundary refs
    (``ref_map`` keyed by node name) become argument names.  Control
    deps are KEPT at this stage — a nested constant-only cond's branch
    parity lives in its ``^switch_t``/``^switch_f`` anchors, which the
    fn-level cond reconstruction still needs; `_strip_control_deps`
    runs after it."""
    out = []
    for n in slice_nodes:
        new_inputs = [r if r.startswith("^")
                      else _guarded_rewrite(r, ref_map, expect_port)
                      for r in n.inputs]
        out.append(NodeDef(n.name, n.op, new_inputs, n.attrs))
    return out


def _strip_control_deps(nodes: List[NodeDef]) -> List[NodeDef]:
    """Final fn-body pass: the lowered XLA program has no side effects
    to order, and out-of-list control targets would break the
    importer's topo sort."""
    for n in nodes:
        n.inputs = [r for r in n.inputs if not r.startswith("^")]
    return nodes


# -- while frames ------------------------------------------------------------

class _LoopVar:
    __slots__ = ("enter", "merge", "nextiter", "switch", "exits")

    def __init__(self, enter, merge, nextiter, switch, exits):
        self.enter = enter
        self.merge = merge
        self.nextiter = nextiter
        self.switch = switch
        self.exits = exits


def _frame_structure(enters: List[NodeDef], nodes: List[NodeDef],
                     by_name, consumers):
    const_enters = [e for e in enters if e.attr("is_constant", False)]
    var_enters = [e for e in enters if not e.attr("is_constant", False)]
    var_enter_names = {e.name for e in var_enters}
    loop_vars: List[_LoopVar] = []
    loopcond: Optional[NodeDef] = None
    for m in nodes:
        if m.op not in _MERGE:
            continue
        ins = _data_inputs(m)
        if not any(_node_of(r) in var_enter_names for r in ins):
            continue
        if len(ins) != 2:
            raise _err(f"loop Merge '{m.name}' has {len(ins)} inputs")
        enter_ref = next(r for r in ins
                         if _node_of(r) in var_enter_names)
        other_ref = next(r for r in ins if r is not enter_ref)
        ni = by_name.get(_node_of(other_ref))
        if ni is None or ni.op not in _NEXT:
            raise _err(f"Merge '{m.name}' back edge is not a "
                       f"NextIteration")
        # only the LoopCond-gated Switch is the loop-var switch; a
        # tf.cond inside the loop's cond subgraph may ALSO switch on
        # the Merge (its pred is an ordinary bool, not a LoopCond)
        switches = []
        for c in consumers.get(m.name, ()):
            if c.op not in _SWITCH or _node_of(c.inputs[0]) != m.name:
                continue
            p = by_name.get(_node_of(c.inputs[1]))
            if p is not None and p.op == "LoopCond":
                switches.append(c)
        if len(switches) != 1:
            raise _err(f"loop var '{m.name}' has {len(switches)} "
                       f"LoopCond-gated Switches (expected 1)")
        sw = switches[0]
        lc = by_name[_node_of(sw.inputs[1])]
        if loopcond is not None and lc.name != loopcond.name:
            raise _err("frame spans two LoopConds")
        loopcond = lc
        exits = [c for c in consumers.get(sw.name, ())
                 if c.op in _EXIT]
        for e in exits:
            if _port_of(e.inputs[0]) != 0:
                raise _err(f"Exit '{e.name}' reads the body port")
        loop_vars.append(_LoopVar(by_name[_node_of(enter_ref)], m, ni,
                                  sw, exits))
    if not loop_vars or loopcond is None:
        raise _err("while frame has no Merge/LoopCond structure")
    return loop_vars, const_enters, loopcond


def _deframe_whiles(nodes: List[NodeDef],
                    functions: Dict[str, FunctionDef],
                    keep: AbstractSet[str] = frozenset()
                    ) -> List[NodeDef]:
    while True:
        frames: Dict[str, List[NodeDef]] = {}
        for n in nodes:
            if n.op in _ENTER:
                fname = n.attr("frame_name")
                if isinstance(fname, bytes):
                    fname = fname.decode()
                frames.setdefault(fname or n.name, []).append(n)
        if not frames:
            return nodes
        by_name = {n.name: n for n in nodes}
        consumers = _consumers_map(nodes)
        progressed = False
        for fname, enters in frames.items():
            plan = _plan_while(fname, enters, nodes, by_name, consumers)
            if plan is None:        # nested frame inside — do it first
                continue
            nodes = _apply_while(plan, nodes, functions, by_name, keep)
            progressed = True
            break                   # rebuild maps, rescan
        if not progressed:
            raise _err(f"no reducible frame among {sorted(frames)}")


def _plan_while(fname, enters, nodes, by_name, consumers):
    loop_vars, const_enters, loopcond = _frame_structure(
        enters, nodes, by_name, consumers)
    merge_names = {lv.merge.name for lv in loop_vars}
    switch_names = {lv.switch.name for lv in loop_vars}
    const_names = {c.name for c in const_enters}
    stops = merge_names | switch_names | const_names | {loopcond.name}
    cond_slice = _backslice([_node_of(loopcond.inputs[0])], by_name,
                            stops, follow_anchors=True)
    body_slice = _backslice(
        [_node_of(lv.nextiter.inputs[0]) for lv in loop_vars],
        by_name, stops, follow_anchors=True)
    for nm in cond_slice | body_slice:
        if by_name[nm].op in _ENTER:    # nested frame — defer
            return None
    return (fname, loop_vars, const_enters, loopcond, cond_slice,
            body_slice)


def _apply_while(plan, nodes, functions, by_name,
                 keep: AbstractSet[str] = frozenset()):
    (fname, loop_vars, const_enters, loopcond, cond_slice,
     body_slice) = plan
    n_lv, n_inv = len(loop_vars), len(const_enters)
    ref_map: Dict[str, str] = {}
    expect_port: Dict[str, int] = {}
    for i, lv in enumerate(loop_vars):
        ref_map[lv.merge.name] = f"__lv{i}"
        expect_port[lv.merge.name] = 0
        ref_map[lv.switch.name] = f"__lv{i}"
        expect_port[lv.switch.name] = 1       # body reads the true port
    for j, ce in enumerate(const_enters):
        ref_map[ce.name] = f"__inv{j}"

    in_args = ([(f"__lv{i}", 0) for i in range(n_lv)]
               + [(f"__inv{j}", 0) for j in range(n_inv)])

    def _rw_ref(ref: str) -> str:
        return _guarded_rewrite(ref, ref_map, expect_port)

    node_order = {n.name: k for k, n in enumerate(nodes)}

    def _fn_nodes(slice_set):
        picked = sorted(slice_set, key=node_order.get)
        rewritten = _rewrite_slice([by_name[nm] for nm in picked],
                                   ref_map, expect_port)
        # nested cond reconstruction inside the body: pivot anchors
        # (control-only Switch/Identity chains) live OUTSIDE the data
        # slice, so hand the full graph as a parity lookup
        return _strip_control_deps(
            _deframe_conds(rewritten, functions,
                           pivot_lookup=by_name))

    cond_fn_nodes = _fn_nodes(cond_slice)
    body_fn_nodes = _fn_nodes(body_slice)

    cond_name = _fresh(f"__v1_{fname}_cond", functions)
    functions[cond_name] = FunctionDef(
        cond_name, in_args, [("__pred", 0)], cond_fn_nodes,
        {"__pred": _rw_ref(loopcond.inputs[0])})
    body_name = _fresh(f"__v1_{fname}_body", functions)
    body_ret = {}
    for i, lv in enumerate(loop_vars):
        body_ret[f"__out{i}"] = _rw_ref(lv.nextiter.inputs[0])
    for j in range(n_inv):
        body_ret[f"__out{n_lv + j}"] = f"__inv{j}"
    functions[body_name] = FunctionDef(
        body_name, in_args,
        [(f"__out{k}", 0) for k in range(n_lv + n_inv)],
        body_fn_nodes, body_ret)

    # name after the user-facing TF loop name (frame names append
    # "/while_context") so while_max_iterations={"<loop name>": N}
    # keys keep working on deframed graphs
    base = (fname[:-len("/while_context")]
            if fname.endswith("/while_context") else fname)
    wname = _fresh(f"{base}__v1_while", by_name)
    while_node = NodeDef(
        wname, "While",
        [_data_inputs(lv.enter)[0] for lv in loop_vars]
        + [_data_inputs(ce)[0] for ce in const_enters],
        {"cond": Attr("func", cond_name),
         "body": Attr("func", body_name)})
    aliases = []
    for i, lv in enumerate(loop_vars):
        src = wname if i == 0 else f"{wname}:{i}"
        for e in lv.exits:
            aliases.append(NodeDef(e.name, "Identity", [src], {}))

    removed = (cond_slice | body_slice
               | {lv.merge.name for lv in loop_vars}
               | {lv.switch.name for lv in loop_vars}
               | {lv.nextiter.name for lv in loop_vars}
               | {lv.enter.name for lv in loop_vars}
               | {e.name for lv in loop_vars for e in lv.exits}
               | {ce.name for ce in const_enters} | {loopcond.name})
    exit_names = {e.name for lv in loop_vars for e in lv.exits}
    anchor = (min((k for k, n in enumerate(nodes)
                   if n.name in exit_names), default=len(nodes))
              if exit_names else
              min(k for k, n in enumerate(nodes)
                  if n.name in removed))
    out: List[NodeDef] = []
    for k, n in enumerate(nodes):
        if k == anchor:
            out.append(while_node)
            out.extend(aliases)
        if n.name in removed:
            continue
        out.append(n)
    if anchor == len(nodes):
        out.append(while_node)
        out.extend(aliases)
    return _check_no_dangling(out, removed, nodes, keep)


def _check_no_dangling(nodes, removed, original,
                       keep: AbstractSet[str] = frozenset()):
    """Post-rewrite integrity pass.  Two cleanups cascade to a
    fixpoint: (a) pivot residue — Switch/Identity/Const chains with
    dangling references into the swallowed structure; (b) DEAD nodes:
    anything that HAD consumers in the original graph but lost every
    one to the removal (e.g. a pred feeding only a pivot's control
    anchors).  Original graph outputs were never consumed, so (b)
    cannot touch them.  A node still dangling at the fixpoint means
    the structure was not reducible."""
    out = list(nodes)
    live_ok = {n.name for n in out}
    orig_consumed = {_node_of(r) for n in original for r in n.inputs}
    changed = True
    while changed:
        changed = False
        consumed_now = {_node_of(r) for n in out for r in n.inputs}
        for n in list(out):
            dangling = [r for r in _data_inputs(n)
                        if _node_of(r) in removed
                        and _node_of(r) not in live_ok]
            dead = (n.name in orig_consumed
                    and n.name not in consumed_now
                    and n.op != "Placeholder"    # feeds stay
                    and n.name not in keep)      # fetches stay
            cascadable = (n.op in _SWITCH
                          or n.op in ("Identity", "Const"))
            if (dangling and cascadable) or dead:
                out.remove(n)
                live_ok.discard(n.name)
                removed.add(n.name)
                changed = True
    for n in out:
        for r in _data_inputs(n):
            nm = _node_of(r)
            if nm in removed and nm not in live_ok:
                raise _err(f"node '{n.name}' references "
                           f"frame-internal '{nm}' from outside the "
                           f"frame")
        n.inputs = [r for r in n.inputs
                    if not (r.startswith("^")
                            and _node_of(r) in removed
                            and _node_of(r) not in live_ok)]
    return out


# -- v1 cond (Switch/Merge diamonds) -----------------------------------------

def _resolve_identity(name: str, by_name) -> str:
    """Follow Identity chains (pred_id pivots) to the producing node."""
    seen = set()
    while True:
        node = by_name.get(name)
        if (node is None or node.op != "Identity"
                or name in seen or not _data_inputs(node)):
            return name
        seen.add(name)
        name = _node_of(_data_inputs(node)[0])


class _CondMerge:
    __slots__ = ("merge", "branch_refs", "slices", "switches",
                 "pred_ref")

    def __init__(self, merge, branch_refs, slices, switches, pred_ref):
        self.merge = merge
        self.branch_refs = branch_refs  # {0: false_ref, 1: true_ref}
        self.slices = slices            # {0: set, 1: set}
        self.switches = switches        # {0: [names], 1: [names]}
        self.pred_ref = pred_ref        # raw ref driving the Switches


def _pivot_parity(slice_set: Set[str], root_ref: str, by_name
                  ) -> Tuple[Optional[int], Optional[str]]:
    """Branch parity for a slice with NO data Switch (constant-only
    branches): v1 anchors such branches with control deps on the
    pivot Identities (``^cond/switch_t`` = Identity(Switch:1),
    ``^cond/switch_f`` = Identity(Switch:0)).  Returns (port,
    pred_ref) or (None, None)."""
    names = set(slice_set) | {_node_of(root_ref)}
    for nm in names:
        node = by_name.get(nm)
        if node is None:
            continue
        for ref in node.inputs:
            if not ref.startswith("^"):
                continue
            anchor = by_name.get(_node_of(ref))
            if anchor is None or anchor.op != "Identity":
                continue
            data = _data_inputs(anchor)
            if not data:
                continue
            sw = by_name.get(_node_of(data[0]))
            if sw is not None and sw.op in _SWITCH:
                return _port_of(data[0]), sw.inputs[1]
    return None, None


def _plan_cond_merge(m: NodeDef, by_name,
                     pivot_lookup=None) -> Optional[_CondMerge]:
    """Classify one Merge's two inputs into true/false branches by the
    Switch ports their backward slices read.  Returns None if an inner
    Merge makes it not-yet-reducible."""
    ins = _data_inputs(m)
    if len(ins) != 2:
        raise _err(f"cond Merge '{m.name}' has {len(ins)} inputs")
    infos = []
    pred_ref = None
    for ref in ins:
        sl = _backslice_stop_switch([_node_of(ref)], by_name)
        if sl is None:
            return None
        slice_set, switch_refs = sl
        root = by_name.get(_node_of(ref))
        if root is not None and root.op in _SWITCH:
            switch_refs = switch_refs + [ref]
        ports = {_port_of(r) for r in switch_refs}
        sw_names = sorted({_node_of(r) for r in switch_refs})
        if len(ports) > 1:
            raise _err(f"branch of Merge '{m.name}' reads both Switch "
                       f"ports")
        port = ports.pop() if ports else None
        if port is None:
            # constant-only branch: parity lives in the control deps
            # anchoring it to the pivot (switch_t/switch_f)
            port, piv_pred = _pivot_parity(
                slice_set, ref, pivot_lookup or by_name)
            if pred_ref is None:
                pred_ref = piv_pred
        if sw_names and pred_ref is None:
            pred_ref = by_name[sw_names[0]].inputs[1]
        infos.append((ref, slice_set, port, sw_names))
    p0, p1 = infos[0][2], infos[1][2]
    if p0 is None and p1 is None:
        raise _err(f"Merge '{m.name}' has no Switch on either input — "
                   f"cannot reconstruct a cond")
    if p0 is None:
        p0 = 1 - p1
    elif p1 is None:
        p1 = 1 - p0
    if p0 == p1:
        raise _err(f"both inputs of Merge '{m.name}' read Switch "
                   f"port {p0}")
    if pred_ref is None:
        raise _err(f"Merge '{m.name}': no predicate source found")
    by_port = {p0: infos[0], p1: infos[1]}
    return _CondMerge(
        m,
        {port: by_port[port][0] for port in (0, 1)},
        {port: by_port[port][1] for port in (0, 1)},
        {port: by_port[port][3] for port in (0, 1)},
        pred_ref)


def _backslice_stop_switch(roots, by_name):
    """Backward slice stopping at (not entering) Switch nodes; collects
    the Switch-port refs crossed.  Returns None when the slice contains
    a Merge (inner cond not yet reduced)."""
    seen: Set[str] = set()
    switch_refs: List[str] = []
    stack = list(roots)
    while stack:
        nm = stack.pop()
        if nm in seen:
            continue
        node = by_name.get(nm)
        if node is None:
            continue
        if node.op in _SWITCH:
            continue
        if node.op in _MERGE:
            return None
        seen.add(nm)
        for ref in _data_inputs(node):
            dep = _node_of(ref)
            depn = by_name.get(dep)
            if depn is not None and depn.op in _SWITCH:
                switch_refs.append(ref)
                continue
            if dep not in seen:
                stack.append(dep)
    return seen, switch_refs


def _deframe_conds(nodes: List[NodeDef],
                   functions: Dict[str, FunctionDef],
                   pivot_lookup: Optional[Dict[str, NodeDef]] = None,
                   keep: AbstractSet[str] = frozenset()
                   ) -> List[NodeDef]:
    while True:
        by_name = {n.name: n for n in nodes}
        # parity anchors of nested const-only conds may live outside
        # this node list (while-body slices): consult the enclosing
        # graph for pivot lookups only — never for slicing
        lookup = ({**pivot_lookup, **by_name} if pivot_lookup
                  else by_name)
        merges = [n for n in nodes if n.op in _MERGE]
        if not merges:
            return nodes
        plans: Dict[str, List[_CondMerge]] = {}
        for m in merges:
            cm = _plan_cond_merge(m, by_name, lookup)
            if cm is None:
                continue
            pred = _resolve_identity(_node_of(cm.pred_ref), lookup)
            plans.setdefault(pred, []).append(cm)
        if not plans:
            raise _err(f"no reducible Switch/Merge diamond among "
                       f"{sorted(m.name for m in merges)}")
        # apply EVERY group planned this sweep (deferred members
        # re-plan next sweep): sweep count scales with cond nesting
        # depth, not with the number of diamonds
        for pred in sorted(plans):
            by_name = {n.name: n for n in nodes}
            group = _independent_subgroup(plans[pred], by_name)
            nodes = _apply_cond(group, nodes, functions, by_name,
                                keep)


def _independent_subgroup(group: List[_CondMerge], by_name
                          ) -> List[_CondMerge]:
    """Diamonds sharing a pred merge into ONE multi-output If — but
    only if they don't feed each other.  Chained conds on the same
    pred (cond B's Switch data input is cond A's Merge) must stay
    separate Ifs, or the combined node would reference its own output.
    Keeps the members whose inputs reach no other member's Merge; the
    rest reduce on a later sweep (once the alias exists)."""
    if len(group) == 1:
        return group
    merge_names = {cm.merge.name for cm in group}
    indep = []
    for cm in group:
        roots = [_node_of(by_name[nm].inputs[0])
                 for port in (0, 1) for nm in cm.switches[port]]
        reach = _backslice(roots, by_name, set())
        if not (reach & (merge_names - {cm.merge.name})):
            indep.append(cm)
    if not indep:
        raise _err("cyclic dependency between Switch/Merge diamonds "
                   "sharing a predicate")
    return indep


def _apply_cond(group: List[_CondMerge], nodes, functions, by_name,
                keep: AbstractSet[str] = frozenset()):
    node_order = {n.name: k for k, n in enumerate(nodes)}
    switch_names = sorted({nm for cm in group
                           for port in (0, 1)
                           for nm in cm.switches[port]},
                          key=node_order.get)
    ref_map = {nm: f"__br{k}" for k, nm in enumerate(switch_names)}
    in_args = [(ref_map[nm], 0) for nm in switch_names]

    def _branch_fn(port: int):
        slice_set: Set[str] = set()
        for cm in group:
            slice_set |= cm.slices[port]
        picked = sorted(slice_set, key=node_order.get)
        expect = {nm: port for nm in switch_names}
        fn_nodes = _strip_control_deps(
            _rewrite_slice([by_name[nm] for nm in picked],
                           ref_map, expect))
        ret = {}
        for i, cm in enumerate(group):
            ret[f"__out{i}"] = _guarded_rewrite(cm.branch_refs[port],
                                                ref_map, expect)
        return fn_nodes, ret

    then_nodes, then_ret = _branch_fn(1)
    else_nodes, else_ret = _branch_fn(0)
    base = group[0].merge.name
    then_name = _fresh(f"__v1_cond_{base}_then", functions)
    else_name = _fresh(f"__v1_cond_{base}_else", functions)
    out_args = [(f"__out{i}", 0) for i in range(len(group))]
    functions[then_name] = FunctionDef(then_name, in_args, out_args,
                                       then_nodes, then_ret)
    functions[else_name] = FunctionDef(else_name, in_args, out_args,
                                       else_nodes, else_ret)

    pred_ref = group[0].pred_ref
    if_name = _fresh(f"{group[0].merge.name}__v1_if", by_name)
    if_node = NodeDef(
        if_name, "If",
        [pred_ref] + [_data_inputs(by_name[nm])[0]
                      for nm in switch_names],
        {"then_branch": Attr("func", then_name),
         "else_branch": Attr("func", else_name)})
    aliases = [NodeDef(cm.merge.name, "Identity",
                       [if_name if i == 0 else f"{if_name}:{i}"], {})
               for i, cm in enumerate(group)]

    removed = set(switch_names) | {cm.merge.name for cm in group}
    for cm in group:
        removed |= cm.slices[0] | cm.slices[1]
    merge_names = {cm.merge.name for cm in group}
    # unconditional nodes pulled into a slice but still consumed
    # outside the diamond stay live (they are duplicated into the
    # branch, which is semantically identical)
    consumers = _consumers_map(nodes)
    changed = True
    while changed:
        changed = False
        for nm in sorted(removed - merge_names - set(switch_names)):
            for c in consumers.get(nm, ()):
                if c.name not in removed:
                    removed.discard(nm)
                    changed = True
                    break
    anchor = min(k for k, n in enumerate(nodes) if n.name in removed)
    out: List[NodeDef] = []
    for k, n in enumerate(nodes):
        if k == anchor:
            out.append(if_node)
            out.extend(aliases)
        if n.name in removed:
            continue
        out.append(n)
    return _check_no_dangling(out, removed, nodes, keep)


# -- final sweep -------------------------------------------------------------

def _sweep_dead_v1(nodes: List[NodeDef]) -> List[NodeDef]:
    """Remove pivot residue: pred Switches (data==pred) and the
    Identity/Const anchors hanging off them.  Anything else that still
    carries a v1 op — or real computation that would be swept with it —
    is an irreducible structure and raises."""
    removed = {n.name for n in nodes if n.op in V1_CONTROL_FLOW_OPS}
    if not removed:
        return nodes
    changed = True
    while changed:
        changed = False
        for n in nodes:
            if n.name in removed:
                continue
            if any(_node_of(r) in removed for r in _data_inputs(n)):
                if n.op not in ("Identity", "Const"):
                    raise _err(
                        f"irreducible v1 structure: '{n.name}' "
                        f"({n.op}) depends on an unreconstructed "
                        f"control-flow op")
                removed.add(n.name)
                changed = True
    out = []
    for n in nodes:
        if n.name in removed:
            continue
        n.inputs = [r for r in n.inputs
                    if not (r.startswith("^")
                            and _node_of(r) in removed)]
        out.append(n)
    return out
