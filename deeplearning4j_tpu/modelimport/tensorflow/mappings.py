"""Per-op TF → SameDiff mapping rules.

Reference parity: `OpMappingRegistry` + per-op `MappingProcess` rules in
`samediff-import-tensorflow` (SURVEY.md S6) — each TF NodeDef is mapped
by a registered rule that adapts attrs/static tensors and emits ops into
the target graph. Here a rule is a plain function
``(ctx, node) -> SDVariable | sequence`` registered in ``TF_OP_MAP``.
"""
from __future__ import annotations

from typing import Callable, Dict

import numpy as np

TF_OP_MAP: Dict[str, Callable] = {}


def tf_op(*names):
    def deco(fn):
        for n in names:
            TF_OP_MAP[n] = fn
        return fn
    return deco


def _ints(arr) -> list:
    return [int(v) for v in np.asarray(arr).reshape(-1)]


# -- passthrough ------------------------------------------------------------
@tf_op("Identity", "StopGradient", "PreventGradient", "CheckNumerics",
       "Snapshot", "EnsureShape", "PlaceholderWithDefault")
def _identity(ctx, node):
    # a real (zero-cost, XLA-fused) op so the TF node name stays
    # addressable as a graph variable
    return ctx.sd._op("identity", [ctx.var(node.inputs[0])])


@tf_op("IdentityN")
def _identity_n(ctx, node):
    return [ctx.sd._op("identity", [ctx.var(i)]) for i in node.inputs]


# -- elementwise binary -----------------------------------------------------
_BINARY = {
    "Add": "add", "AddV2": "add", "Sub": "sub", "Mul": "mul",
    "RealDiv": "div", "Div": "div", "FloorDiv": "floordiv",
    "FloorMod": "mod", "Mod": "mod", "Maximum": "maximum",
    "Minimum": "minimum", "Pow": "pow",
    "SquaredDifference": "squared_difference", "Atan2": "atan2",
    "Greater": "gt", "GreaterEqual": "gte", "Less": "lt",
    "LessEqual": "lte", "Equal": "eq", "NotEqual": "neq",
    "LogicalAnd": "logical_and", "LogicalOr": "logical_or",
}


def _binary(ctx, node):
    return ctx.sd._op(_BINARY[node.op],
                      [ctx.var(node.inputs[0]), ctx.var(node.inputs[1])])


for _name in _BINARY:
    TF_OP_MAP[_name] = _binary

# -- elementwise unary ------------------------------------------------------
_UNARY = {
    "Neg": "neg", "Abs": "abs", "Exp": "exp", "Log": "log",
    "Log1p": "log1p", "Expm1": "expm1", "Sqrt": "sqrt", "Rsqrt": "rsqrt",
    "Square": "square", "Sign": "sign", "Floor": "floor", "Ceil": "ceil",
    "Round": "round", "Reciprocal": "reciprocal", "Inv": "reciprocal",
    "Erf": "erf", "Erfc": "erfc", "Tanh": "tanh", "Sigmoid": "sigmoid",
    "Sin": "sin", "Cos": "cos", "Tan": "tan", "Asin": "asin",
    "Acos": "acos", "Atan": "atan", "Sinh": "sinh", "Cosh": "cosh",
    "Asinh": "asinh", "Acosh": "acosh", "Atanh": "atanh",
    "Relu": "relu", "Relu6": "relu6", "Elu": "elu", "Selu": "selu",
    "Softplus": "softplus", "Softsign": "softsign",
    "LogicalNot": "logical_not", "Softmax": "softmax",
    "LogSoftmax": "log_softmax", "IsNan": "is_nan", "IsInf": "is_inf",
    "IsFinite": "is_finite", "OnesLike": "ones_like",
    "ZerosLike": "zeros_like",
}


def _unary(ctx, node):
    return ctx.sd._op(_UNARY[node.op], [ctx.var(node.inputs[0])])


for _name in _UNARY:
    TF_OP_MAP[_name] = _unary


@tf_op("LeakyRelu")
def _leaky(ctx, node):
    return ctx.sd._op("leaky_relu", [ctx.var(node.inputs[0])],
                      {"alpha": node.attr("alpha", 0.2)})


@tf_op("AddN")
def _addn(ctx, node):
    out = ctx.var(node.inputs[0])
    for ref in node.inputs[1:]:
        out = ctx.sd._op("add", [out, ctx.var(ref)])
    return out


@tf_op("L2Loss")
def _l2loss(ctx, node):
    sq = ctx.sd._op("square", [ctx.var(node.inputs[0])])
    s = ctx.sd._op("reduce_sum", [sq], {"axis": None})
    half = ctx.sd.constant(np.float32(0.5))
    return ctx.sd._op("mul", [s, half])


@tf_op("Select", "SelectV2")
def _select(ctx, node):
    return ctx.sd._op("where", [ctx.var(i) for i in node.inputs[:3]])


@tf_op("ClipByValue")
def _clip(ctx, node):
    lo = float(np.asarray(ctx.require_static(node, 1)).reshape(())[()])
    hi = float(np.asarray(ctx.require_static(node, 2)).reshape(())[()])
    return ctx.sd._op("clip_by_value", [ctx.var(node.inputs[0])],
                      {"clip_value_min": lo, "clip_value_max": hi})


# -- matmul / einsum --------------------------------------------------------
@tf_op("MatMul")
def _matmul(ctx, node):
    return ctx.sd._op("matmul",
                      [ctx.var(node.inputs[0]), ctx.var(node.inputs[1])],
                      {"transpose_a": bool(node.attr("transpose_a")),
                       "transpose_b": bool(node.attr("transpose_b"))})


@tf_op("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3")
def _batch_matmul(ctx, node):
    return ctx.sd._op("matmul",
                      [ctx.var(node.inputs[0]), ctx.var(node.inputs[1])],
                      {"transpose_a": bool(node.attr("adj_x")),
                       "transpose_b": bool(node.attr("adj_y"))})


@tf_op("Einsum")
def _einsum(ctx, node):
    eq = node.attr("equation", b"").decode()
    return ctx.sd._op("einsum", [ctx.var(i) for i in node.inputs],
                      {"equation": eq})


@tf_op("BiasAdd")
def _bias_add(ctx, node):
    x = ctx.var(node.inputs[0])
    b = ctx.var(node.inputs[1])
    fmt = node.attr("data_format", b"NHWC")
    if fmt == b"NCHW":
        nd = len(x.shape) if x.shape else 4
        b = ctx.sd._op("reshape", [b],
                       {"shape": [-1] + [1] * (nd - 2)})
    return ctx.sd._op("add", [x, b])


# -- reductions -------------------------------------------------------------
_REDUCE = {"Sum": "reduce_sum", "Mean": "reduce_mean",
           "Max": "reduce_max", "Min": "reduce_min",
           "Prod": "reduce_prod", "All": "reduce_all",
           "Any": "reduce_any"}


def _reduce(ctx, node):
    axes = _ints(ctx.require_static(node, 1))
    keep = bool(node.attr("keep_dims", False))
    return ctx.sd._op(_REDUCE[node.op], [ctx.var(node.inputs[0])],
                      {"axis": axes if len(axes) != 1 else axes[0],
                       "keep_dims": keep})


for _name in _REDUCE:
    TF_OP_MAP[_name] = _reduce


@tf_op("ArgMax")
def _argmax(ctx, node):
    axis = int(np.asarray(ctx.require_static(node, 1)).reshape(())[()])
    return ctx.sd._op("argmax", [ctx.var(node.inputs[0])], {"axis": axis})


@tf_op("ArgMin")
def _argmin(ctx, node):
    axis = int(np.asarray(ctx.require_static(node, 1)).reshape(())[()])
    return ctx.sd._op("argmin", [ctx.var(node.inputs[0])], {"axis": axis})


@tf_op("Cumsum")
def _cumsum(ctx, node):
    axis = int(np.asarray(ctx.require_static(node, 1)).reshape(())[()])
    return ctx.sd._op("cumsum", [ctx.var(node.inputs[0])],
                      {"axis": axis,
                       "exclusive": bool(node.attr("exclusive", False)),
                       "reverse": bool(node.attr("reverse", False))})


@tf_op("TopKV2")
def _topk(ctx, node):
    k = int(np.asarray(ctx.require_static(node, 1)).reshape(())[()])
    return ctx.sd._op("top_k", [ctx.var(node.inputs[0])], {"k": k},
                      n_out=2)


# -- shape ops --------------------------------------------------------------
@tf_op("Shape")
def _shape(ctx, node):
    return ctx.sd._op("shape_of", [ctx.var(node.inputs[0])])


@tf_op("Size")
def _size(ctx, node):
    return ctx.sd._op("size", [ctx.var(node.inputs[0])])


@tf_op("Rank")
def _rank(ctx, node):
    return ctx.sd._op("rank", [ctx.var(node.inputs[0])])


@tf_op("Reshape")
def _reshape(ctx, node):
    shape = _ints(ctx.require_static(node, 1))
    return ctx.sd._op("reshape", [ctx.var(node.inputs[0])],
                      {"shape": shape})


@tf_op("Transpose")
def _transpose(ctx, node):
    perm = _ints(ctx.require_static(node, 1))
    return ctx.sd._op("permute", [ctx.var(node.inputs[0])],
                      {"axes": perm})


@tf_op("ExpandDims")
def _expand_dims(ctx, node):
    axis = int(np.asarray(ctx.require_static(node, 1)).reshape(())[()])
    return ctx.sd._op("expand_dims", [ctx.var(node.inputs[0])],
                      {"axis": axis})


@tf_op("Squeeze")
def _squeeze(ctx, node):
    dims = node.attr("squeeze_dims") or None
    if dims is not None:
        dims = tuple(int(d) for d in dims) or None
    return ctx.sd._op("squeeze", [ctx.var(node.inputs[0])],
                      {"axis": dims})


@tf_op("ConcatV2")
def _concat_v2(ctx, node):
    axis = int(np.asarray(
        ctx.require_static(node, len(node.inputs) - 1)).reshape(())[()])
    ins = [ctx.var(i) for i in node.inputs[:-1]]
    return ctx.sd._op("concat", ins, {"axis": axis})


@tf_op("Concat")
def _concat(ctx, node):
    axis = int(np.asarray(ctx.require_static(node, 0)).reshape(())[()])
    ins = [ctx.var(i) for i in node.inputs[1:]]
    return ctx.sd._op("concat", ins, {"axis": axis})


@tf_op("Pack")
def _pack(ctx, node):
    return ctx.sd._op("stack", [ctx.var(i) for i in node.inputs],
                      {"axis": node.attr("axis", 0)})


@tf_op("Unpack")
def _unpack(ctx, node):
    n = node.attr("num")
    return ctx.sd._op("unstack", [ctx.var(node.inputs[0])],
                      {"axis": node.attr("axis", 0)}, n_out=int(n))


@tf_op("Split")
def _split(ctx, node):
    axis = int(np.asarray(ctx.require_static(node, 0)).reshape(())[()])
    n = int(node.attr("num_split"))
    return ctx.sd._op("split", [ctx.var(node.inputs[1])],
                      {"num_splits": n, "axis": axis}, n_out=n)


@tf_op("SplitV")
def _split_v(ctx, node):
    sizes = _ints(ctx.require_static(node, 1))
    axis = int(np.asarray(ctx.require_static(node, 2)).reshape(())[()])
    return ctx.sd._op("split_v", [ctx.var(node.inputs[0])],
                      {"size_splits": sizes, "axis": axis},
                      n_out=len(sizes))


@tf_op("Tile")
def _tile(ctx, node):
    reps = _ints(ctx.require_static(node, 1))
    return ctx.sd._op("tile", [ctx.var(node.inputs[0])], {"reps": reps})


@tf_op("Pad", "PadV2", "MirrorPad")
def _pad(ctx, node):
    pads = np.asarray(ctx.require_static(node, 1)).astype(int).tolist()
    attrs = {"paddings": pads}
    if node.op == "PadV2" and len(node.inputs) > 2:
        attrs["constant"] = float(np.asarray(
            ctx.require_static(node, 2)).reshape(())[()])
    if node.op == "MirrorPad":
        mode = node.attr("mode", b"REFLECT")
        attrs["mode"] = ("reflect" if mode == b"REFLECT"
                         else "symmetric")
    return ctx.sd._op("pad", [ctx.var(node.inputs[0])], attrs)


@tf_op("StridedSlice")
def _strided_slice(ctx, node):
    begin = _ints(ctx.require_static(node, 1))
    end = _ints(ctx.require_static(node, 2))
    strides = _ints(ctx.require_static(node, 3))
    spec = strided_slice_spec(
        begin, end, strides, node.attr("begin_mask", 0),
        node.attr("end_mask", 0), node.attr("ellipsis_mask", 0),
        node.attr("new_axis_mask", 0), node.attr("shrink_axis_mask", 0))
    return ctx.sd._op("index", [ctx.var(node.inputs[0])], {"spec": spec})


@tf_op("Slice")
def _slice(ctx, node):
    begin = _ints(ctx.require_static(node, 1))
    size = _ints(ctx.require_static(node, 2))
    return ctx.sd._op("slice", [ctx.var(node.inputs[0])],
                      {"begin": begin, "size": size})


@tf_op("GatherV2")
def _gather_v2(ctx, node):
    axis = int(np.asarray(ctx.require_static(node, 2)).reshape(())[()])
    bd = int(node.attr("batch_dims", 0))
    return ctx.sd._op("gather",
                      [ctx.var(node.inputs[0]), ctx.var(node.inputs[1])],
                      {"axis": axis, "batch_dims": bd})


@tf_op("Gather")
def _gather(ctx, node):
    return ctx.sd._op("gather",
                      [ctx.var(node.inputs[0]), ctx.var(node.inputs[1])],
                      {"axis": 0})


@tf_op("GatherNd")
def _gather_nd(ctx, node):
    return ctx.sd._op("gather_nd",
                      [ctx.var(node.inputs[0]), ctx.var(node.inputs[1])])


@tf_op("OneHot")
def _one_hot(ctx, node):
    depth = int(np.asarray(ctx.require_static(node, 1)).reshape(())[()])
    on = float(np.asarray(ctx.require_static(node, 2)).reshape(())[()])
    off = float(np.asarray(ctx.require_static(node, 3)).reshape(())[()])
    axis = int(node.attr("axis", -1))
    oh = ctx.sd._op("one_hot", [ctx.var(node.inputs[0])],
                    {"depth": depth, "axis": axis})
    if on != 1.0 or off != 0.0:
        scale = ctx.sd.constant(np.float32(on - off))
        shift = ctx.sd.constant(np.float32(off))
        oh = ctx.sd._op("add", [ctx.sd._op("mul", [oh, scale]), shift])
    return oh


@tf_op("BroadcastTo")
def _broadcast_to(ctx, node):
    shape = _ints(ctx.require_static(node, 1))
    return ctx.sd._op("broadcast_to", [ctx.var(node.inputs[0])],
                      {"shape": shape})


@tf_op("Fill")
def _fill(ctx, node):
    dims = _ints(ctx.require_static(node, 0))
    val = ctx.static(node.inputs[1])
    if val is not None:
        v = np.asarray(val).reshape(())[()]
        return ctx.sd.constant(np.full(dims, v))
    return ctx.sd._op("broadcast_to", [ctx.var(node.inputs[1])],
                      {"shape": dims})


@tf_op("Cast")
def _cast(ctx, node):
    from deeplearning4j_tpu.modelimport.tensorflow.protobuf import \
        tf_dtype_to_np
    dst = tf_dtype_to_np(int(node.attr("DstT", 1)))
    return ctx.sd._op("cast", [ctx.var(node.inputs[0])],
                      {"dtype": np.dtype(dst).name})


@tf_op("Range")
def _range(ctx, node):
    start = np.asarray(ctx.require_static(node, 0)).reshape(())[()]
    limit = np.asarray(ctx.require_static(node, 1)).reshape(())[()]
    delta = np.asarray(ctx.require_static(node, 2)).reshape(())[()]
    return ctx.sd.constant(np.arange(start, limit, delta))


# -- conv / pool / norm -----------------------------------------------------
def _to_nhwc(ctx, x, fmt):
    if fmt == b"NCHW":
        return ctx.sd._op("permute", [x], {"axes": [0, 2, 3, 1]})
    return x


def _from_nhwc(ctx, x, fmt):
    if fmt == b"NCHW":
        return ctx.sd._op("permute", [x], {"axes": [0, 3, 1, 2]})
    return x


def _conv_attrs(node, fmt):
    strides = [int(s) for s in node.attr("strides", [1, 1, 1, 1])]
    dil = [int(d) for d in node.attr("dilations", [1, 1, 1, 1])]
    if fmt == b"NCHW":
        sh, sw = strides[2], strides[3]
        dh, dw = dil[2], dil[3]
    else:
        sh, sw = strides[1], strides[2]
        dh, dw = dil[1], dil[2]
    padding = node.attr("padding", b"SAME").decode()
    if padding == "EXPLICIT":
        ep = [int(p) for p in node.attr("explicit_paddings", [])]
        if fmt == b"NCHW":
            padding = [(ep[4], ep[5]), (ep[6], ep[7])]
        else:
            padding = [(ep[2], ep[3]), (ep[4], ep[5])]
    return {"stride": (sh, sw), "padding": padding,
            "dilation": (dh, dw)}


@tf_op("Conv2D")
def _conv2d(ctx, node):
    fmt = node.attr("data_format", b"NHWC")
    x = _to_nhwc(ctx, ctx.var(node.inputs[0]), fmt)
    w = ctx.var(node.inputs[1])
    out = ctx.sd._op("conv2d", [x, w], _conv_attrs(node, fmt))
    return _from_nhwc(ctx, out, fmt)


@tf_op("DepthwiseConv2dNative")
def _depthwise(ctx, node):
    fmt = node.attr("data_format", b"NHWC")
    x = _to_nhwc(ctx, ctx.var(node.inputs[0]), fmt)
    w = ctx.var(node.inputs[1])
    out = ctx.sd._op("depthwise_conv2d", [x, w], _conv_attrs(node, fmt))
    return _from_nhwc(ctx, out, fmt)


@tf_op("Conv2DBackpropInput")
def _conv2d_transpose(ctx, node):
    fmt = node.attr("data_format", b"NHWC")
    x = _to_nhwc(ctx, ctx.var(node.inputs[2]), fmt)
    w = ctx.var(node.inputs[1])
    attrs = _conv_attrs(node, fmt)
    attrs["transpose_kernel"] = True
    out = ctx.sd._op("deconv2d", [x, w], attrs)
    return _from_nhwc(ctx, out, fmt)


@tf_op("MaxPool", "AvgPool")
def _pool(ctx, node):
    fmt = node.attr("data_format", b"NHWC")
    ks = [int(k) for k in node.attr("ksize", [1, 2, 2, 1])]
    st = [int(s) for s in node.attr("strides", [1, 2, 2, 1])]
    if fmt == b"NCHW":
        kernel, stride = (ks[2], ks[3]), (st[2], st[3])
    else:
        kernel, stride = (ks[1], ks[2]), (st[1], st[2])
    x = _to_nhwc(ctx, ctx.var(node.inputs[0]), fmt)
    opn = "max_pool2d" if node.op == "MaxPool" else "avg_pool2d"
    out = ctx.sd._op(opn, [x],
                     {"kernel": kernel, "stride": stride,
                      "padding": node.attr("padding", b"VALID").decode()})
    return _from_nhwc(ctx, out, fmt)


@tf_op("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_bn(ctx, node):
    if node.attr("is_training", True):
        raise NotImplementedError(
            "FusedBatchNorm with is_training=True (freeze the graph "
            "for inference import)")
    fmt = node.attr("data_format", b"NHWC")
    x = _to_nhwc(ctx, ctx.var(node.inputs[0]), fmt)
    gamma = ctx.var(node.inputs[1])
    beta = ctx.var(node.inputs[2])
    mean = ctx.var(node.inputs[3])
    var = ctx.var(node.inputs[4])
    y = ctx.sd._op("batch_norm", [x, mean, var, gamma, beta],
                   {"epsilon": node.attr("epsilon", 1e-3)})
    y = _from_nhwc(ctx, y, fmt)
    # outputs 1..5 (batch stats / reserves) pass through the moving stats
    return [y, mean, var, mean, var, mean]


# -- image ------------------------------------------------------------------
@tf_op("ResizeBilinear")
def _resize_bilinear(ctx, node):
    size = _ints(ctx.require_static(node, 1))
    return ctx.sd._op("resize_bilinear", [ctx.var(node.inputs[0])],
                      {"size": size})


@tf_op("ResizeNearestNeighbor")
def _resize_nearest(ctx, node):
    size = _ints(ctx.require_static(node, 1))
    return ctx.sd._op("resize_nearest", [ctx.var(node.inputs[0])],
                      {"size": size})


@tf_op("ResizeBicubic")
def _resize_bicubic(ctx, node):
    if node.attr("align_corners", False) or \
            not node.attr("half_pixel_centers", False):
        raise NotImplementedError(
            "ResizeBicubic without half_pixel_centers unsupported "
            "(TF2's tf.image.resize emits half-pixel centers; legacy "
            "TF1 corner conventions are not lowered)")
    size = _ints(ctx.require_static(node, 1))
    return ctx.sd._op("resize_bicubic", [ctx.var(node.inputs[0])],
                      {"size": size})


@tf_op("ResizeArea")
def _resize_area(ctx, node):
    if node.attr("align_corners", False):
        raise NotImplementedError("ResizeArea align_corners=True "
                                  "unsupported")
    size = _ints(ctx.require_static(node, 1))
    return ctx.sd._op("resize_area", [ctx.var(node.inputs[0])],
                      {"size": size})


# -- random (rare in frozen inference graphs) -------------------------------
@tf_op("RandomStandardNormal")
def _random_normal(ctx, node):
    shape = _ints(ctx.require_static(node, 0))
    return ctx.sd._op("random_normal", [], {"shape": shape})


@tf_op("RandomUniform")
def _random_uniform(ctx, node):
    shape = _ints(ctx.require_static(node, 0))
    return ctx.sd._op("random_uniform", [], {"shape": shape})


def strided_slice_spec(begin, end, strides, begin_mask, end_mask,
                       ellipsis_mask, new_axis_mask, shrink_axis_mask):
    """TF StridedSlice masks → generic ``index`` op spec."""
    spec = []
    for i in range(len(begin)):
        if ellipsis_mask & (1 << i):
            spec.append({"kind": "ellipsis"})
        elif new_axis_mask & (1 << i):
            spec.append({"kind": "newaxis"})
        elif shrink_axis_mask & (1 << i):
            spec.append({"kind": "int", "i": int(begin[i])})
        else:
            item = {"kind": "slice", "stride": int(strides[i])}
            if not begin_mask & (1 << i):
                item["begin"] = int(begin[i])
            if not end_mask & (1 << i):
                item["end"] = int(end[i])
            spec.append(item)
    return spec


# -- breadth batch 2: 3D conv/pool, block rearrange, segment/scatter, --------
# -- linalg, xent losses (SURVEY.md S6 coverage accounting) ------------------
def _block_rearrange(ctx, node, op_name):
    """SpaceToDepth/DepthToSpace in either layout: the registry op is
    NHWC-native; NCHW wraps it in two transposes (XLA folds layout
    permutations into the surrounding program)."""
    x = ctx.var(node.inputs[0])
    attrs = {"block_size": int(node.attr("block_size", 2))}
    fmt = node.attr("data_format", b"NHWC")
    if fmt not in (b"NHWC", b"NCHW"):
        raise NotImplementedError(f"{node.op}: data_format={fmt}")
    nchw = fmt == b"NCHW"
    if nchw:
        x = ctx.sd._op("transpose", [x], {"axes": (0, 2, 3, 1)})
    y = ctx.sd._op(op_name, [x], attrs)
    if nchw:
        y = ctx.sd._op("transpose", [y], {"axes": (0, 3, 1, 2)})
    return y


@tf_op("SpaceToDepth")
def _space_to_depth(ctx, node):
    return _block_rearrange(ctx, node, "space_to_depth")


@tf_op("DepthToSpace")
def _depth_to_space(ctx, node):
    return _block_rearrange(ctx, node, "depth_to_space")


def _ncdhw_layout(node):
    """NDHWC is the registry-native 3D layout; NCDHW wraps in two
    transposes (same treatment as _block_rearrange — XLA folds the
    layout permutations into the surrounding program).  Per-element
    attrs (strides/ksize/dilations) arrive in the GRAPH layout, so
    the caller permutes them with the returned index map."""
    fmt = node.attr("data_format", b"NDHWC")
    if fmt not in (b"NDHWC", b"NCDHW"):
        raise NotImplementedError(f"{node.op}: data_format={fmt}")
    return fmt == b"NCDHW"


_NCDHW_TO_NDHWC = (0, 2, 3, 4, 1)
_NDHWC_TO_NCDHW = (0, 4, 1, 2, 3)


@tf_op("Conv3D")
def _conv3d(ctx, node):
    ncdhw = _ncdhw_layout(node)
    strides = [int(s) for s in node.attr("strides", [1] * 5)]
    dil = [int(d) for d in node.attr("dilations", [1] * 5)]
    x = ctx.var(node.inputs[0])
    if ncdhw:
        x = ctx.sd._op("transpose", [x], {"axes": _NCDHW_TO_NDHWC})
        strides = [strides[i] for i in _NCDHW_TO_NDHWC]
        dil = [dil[i] for i in _NCDHW_TO_NDHWC]
    y = ctx.sd._op(
        "conv3d", [x, ctx.var(node.inputs[1])],
        {"stride": tuple(strides[1:4]), "dilation": tuple(dil[1:4]),
         "padding": node.attr("padding", b"SAME").decode()})
    if ncdhw:
        y = ctx.sd._op("transpose", [y], {"axes": _NDHWC_TO_NCDHW})
    return y


@tf_op("MaxPool3D", "AvgPool3D")
def _pool3d(ctx, node):
    ncdhw = _ncdhw_layout(node)
    ks = [int(k) for k in node.attr("ksize", [1, 2, 2, 2, 1])]
    st = [int(s) for s in node.attr("strides", [1, 2, 2, 2, 1])]
    x = ctx.var(node.inputs[0])
    if ncdhw:
        x = ctx.sd._op("transpose", [x], {"axes": _NCDHW_TO_NDHWC})
        ks = [ks[i] for i in _NCDHW_TO_NDHWC]
        st = [st[i] for i in _NCDHW_TO_NDHWC]
    opn = "max_pool3d" if node.op == "MaxPool3D" else "avg_pool3d"
    y = ctx.sd._op(opn, [x],
                   {"kernel": tuple(ks[1:4]),
                    "stride": tuple(st[1:4]),
                    "padding": node.attr("padding",
                                         b"VALID").decode()})
    if ncdhw:
        y = ctx.sd._op("transpose", [y], {"axes": _NDHWC_TO_NCDHW})
    return y


@tf_op("ReverseV2")
def _reverse_v2(ctx, node):
    axes = np.asarray(ctx.require_static(node, 1))
    return ctx.sd._op("reverse", [ctx.var(node.inputs[0])],
                      {"axes": [int(a) for a in axes.reshape(-1)]})


@tf_op("Cumprod")
def _cumprod(ctx, node):
    axis = int(np.asarray(ctx.require_static(node, 1)))
    return ctx.sd._op("cumprod", [ctx.var(node.inputs[0])],
                      {"axis": axis,
                       "exclusive": bool(node.attr("exclusive", False)),
                       "reverse": bool(node.attr("reverse", False))})


@tf_op("Roll")
def _roll_tf(ctx, node):
    shift = np.asarray(ctx.require_static(node, 1))
    axes = np.asarray(ctx.require_static(node, 2))
    return ctx.sd._op("roll", [ctx.var(node.inputs[0])],
                      {"shift": [int(s) for s in shift.reshape(-1)],
                       "axes": [int(a) for a in axes.reshape(-1)]})


@tf_op("ScatterNd")
def _scatter_nd_tf(ctx, node):
    shape = np.asarray(ctx.require_static(node, 2))
    return ctx.sd._op("scatter_nd",
                      [ctx.var(node.inputs[0]),
                       ctx.var(node.inputs[1])],
                      {"shape": [int(s) for s in shape.reshape(-1)]})


@tf_op("InvertPermutation")
def _invert_perm(ctx, node):
    return ctx.sd._op("invert_permutation", [ctx.var(node.inputs[0])])


@tf_op("SegmentSum", "SegmentMax", "SegmentMin", "SegmentMean",
       "SegmentProd")
def _segment(ctx, node):
    opn = {"SegmentSum": "segment_sum", "SegmentMax": "segment_max",
           "SegmentMin": "segment_min", "SegmentMean": "segment_mean",
           "SegmentProd": "segment_prod"}[node.op]
    # num_segments must be static under jit; fold it from the ids
    ids = np.asarray(ctx.require_static(node, 1))
    return ctx.sd._op(opn, [ctx.var(node.inputs[0]),
                            ctx.var(node.inputs[1])],
                      {"num_segments": int(ids.max()) + 1})


@tf_op("UnsortedSegmentSum", "UnsortedSegmentMax", "UnsortedSegmentMin",
       "UnsortedSegmentProd")
def _unsorted_segment(ctx, node):
    opn = {"UnsortedSegmentSum": "unsorted_segment_sum",
           "UnsortedSegmentMax": "unsorted_segment_max",
           "UnsortedSegmentMin": "unsorted_segment_min",
           "UnsortedSegmentProd": "unsorted_segment_prod"}[node.op]
    n = int(np.asarray(ctx.require_static(node, 2)))
    return ctx.sd._op(opn, [ctx.var(node.inputs[0]),
                            ctx.var(node.inputs[1])],
                      {"num_segments": n})


@tf_op("LRN")
def _lrn(ctx, node):
    # TF windows [i-r, i+r] (2r+1 wide); our lrn takes the full width
    r = int(node.attr("depth_radius", 5))
    return ctx.sd._op("lrn", [ctx.var(node.inputs[0])],
                      {"depth": 2 * r + 1,
                       "bias": float(node.attr("bias", 1.0)),
                       "alpha": float(node.attr("alpha", 1.0)),
                       "beta": float(node.attr("beta", 0.5))})


def _check_diag_k(ctx, node):
    """V2/V3 carry a k (diagonal offset) input; only k=0 is supported."""
    if len(node.inputs) > 1:
        k = np.asarray(ctx.require_static(node, 1))
        if np.any(k != 0):
            raise NotImplementedError(
                f"{node.op}: only the main diagonal (k=0) is supported")


@tf_op("MatrixDiag", "MatrixDiagV2", "MatrixDiagV3")
def _matrix_diag_tf(ctx, node):
    _check_diag_k(ctx, node)
    return ctx.sd._op("matrix_diag", [ctx.var(node.inputs[0])])


@tf_op("MatrixDiagPart", "MatrixDiagPartV2", "MatrixDiagPartV3")
def _matrix_diag_part_tf(ctx, node):
    _check_diag_k(ctx, node)
    return ctx.sd._op("matrix_diag_part", [ctx.var(node.inputs[0])])


@tf_op("Cholesky")
def _cholesky_tf(ctx, node):
    return ctx.sd._op("cholesky", [ctx.var(node.inputs[0])])


@tf_op("MatrixInverse")
def _matrix_inverse_tf(ctx, node):
    return ctx.sd._op("matrix_inverse", [ctx.var(node.inputs[0])])


@tf_op("SoftmaxCrossEntropyWithLogits")
def _softmax_xent(ctx, node):
    logits = ctx.var(node.inputs[0])
    labels = ctx.var(node.inputs[1])
    loss = ctx.sd._op("softmax_cross_entropy", [labels, logits],
                      {"reduction": "none"})
    # TF also returns backprop dL/dlogits = softmax - labels
    sm = ctx.sd._op("softmax", [logits])
    grad = ctx.sd._op("sub", [sm, labels])
    return [loss, grad]


@tf_op("SparseSoftmaxCrossEntropyWithLogits")
def _sparse_softmax_xent(ctx, node):
    logits = ctx.var(node.inputs[0])
    labels = ctx.var(node.inputs[1])
    loss = ctx.sd._op("sparse_softmax_cross_entropy",
                      [labels, logits], {"reduction": "none"})
    if not (logits.shape and logits.shape[-1] and
            int(logits.shape[-1]) > 0):
        raise NotImplementedError(
            "SparseSoftmaxCrossEntropyWithLogits: class count must be "
            "statically known for the backprop output")
    onehot = ctx.sd._op("one_hot", [labels],
                        {"depth": int(logits.shape[-1])})
    sm = ctx.sd._op("softmax", [logits])
    grad = ctx.sd._op("sub", [sm, onehot])
    return [loss, grad]


# -- TensorList / TensorArray (TF2 dynamic-loop accumulators; the v2
# lowering of tf.TensorArray — SURVEY.md S3) --------------------------------
@tf_op("TensorListReserve")
def _tensor_list_reserve(ctx, node):
    shape = node.attr("_tl_shape")     # stashed by _resolve_tensor_lists
    num = node.attr("_tl_num")
    if shape is None or num is None:
        raise NotImplementedError(
            f"TensorListReserve '{node.name}': element shape or size "
            f"not recoverable by the resolver (it reads direct Const "
            f"producers, following the handle through While "
            f"boundaries). Either the list is dynamic-size "
            f"(PushBack-style — no static-shape lowering exists) or "
            f"the size/shape comes through a derived chain this "
            f"resolver does not fold yet")
    from deeplearning4j_tpu.modelimport.tensorflow.protobuf import \
        tf_dtype_to_np
    dt = tf_dtype_to_np(int(node.attr("element_dtype", 1)))
    return ctx.sd.constant(f"{node.name}_storage",
                           np.zeros((int(num),) + tuple(shape), dt))


@tf_op("TensorListSetItem")
def _tensor_list_set_item(ctx, node):
    if node.attr("resize_if_index_out_of_bounds", False):
        # dynamic growth: the dense static-size representation would
        # silently DROP out-of-bounds writes
        raise NotImplementedError(
            "TensorListSetItem with resize_if_index_out_of_bounds "
            "(dynamic-size TensorList) has no static-shape lowering")
    return ctx.sd._op("tensor_list_set_item",
                      [ctx.var(node.inputs[0]), ctx.var(node.inputs[1]),
                       ctx.var(node.inputs[2])])


@tf_op("TensorListGetItem")
def _tensor_list_get_item(ctx, node):
    return ctx.sd._op("tensor_list_get_item",
                      [ctx.var(node.inputs[0]),
                       ctx.var(node.inputs[1])])


@tf_op("TensorListStack", "TensorListFromTensor")
def _tensor_list_identity(ctx, node):
    # dense representation: the storage IS the stacked tensor
    return ctx.var(node.inputs[0])


@tf_op("TensorListLength")
def _tensor_list_length(ctx, node):
    return ctx.sd._op("tensor_list_length", [ctx.var(node.inputs[0])])


@tf_op("TensorListGather")
def _tensor_list_gather(ctx, node):
    return ctx.sd._op("gather",
                      [ctx.var(node.inputs[0]),
                       ctx.var(node.inputs[1])], {"axis": 0})
