"""Per-op ONNX → SameDiff mapping rules (SURVEY.md S7:
`samediff-import-onnx`'s OpMappingRegistry equivalent — the same
rule-function pattern as the TF importer's `mappings.py`).

ONNX convs/pools are NCHW with OIHW weights; our conv ops are NHWC
with HWIO kernels (the TPU-friendly layout), so rules transpose on
the way in/out and XLA cancels adjacent transposes after fusion.
"""
from __future__ import annotations

from typing import Callable, Dict

import numpy as np

ONNX_OP_MAP: Dict[str, Callable] = {}


def onnx_op(*names):
    def deco(fn):
        for n in names:
            ONNX_OP_MAP[n] = fn
        return fn
    return deco


# -- passthrough ------------------------------------------------------------
@onnx_op("Identity")
def _identity(ctx, node):
    return ctx.sd._op("identity", [ctx.var(node.inputs[0])])


@onnx_op("Dropout")
def _dropout(ctx, node):
    # inference import: identity (+ all-true mask if requested)
    y = ctx.sd._op("identity", [ctx.var(node.inputs[0])])
    if len(node.outputs) > 1:
        mask = ctx.sd._op("ones_like", [ctx.var(node.inputs[0])])
        return [y, mask]
    return y


# -- elementwise ------------------------------------------------------------
_BINARY = {"Add": "add", "Sub": "sub", "Mul": "mul", "Div": "div",
           "Pow": "pow", "Greater": "gt", "Less": "lt",
           "Equal": "eq", "Min": "minimum", "Max": "maximum",
           "And": "logical_and", "Or": "logical_or"}


def _binary(ctx, node):
    out = ctx.var(node.inputs[0])
    for other in node.inputs[1:]:
        out = ctx.sd._op(_BINARY[node.op], [out, ctx.var(other)])
    return out


for _n in _BINARY:
    ONNX_OP_MAP[_n] = _binary


@onnx_op("Sum", "Mean")
def _variadic(ctx, node):
    out = ctx.var(node.inputs[0])
    for other in node.inputs[1:]:
        out = ctx.sd._op("add", [out, ctx.var(other)])
    if node.op == "Mean" and len(node.inputs) > 1:
        out = ctx.sd._op("div", [out, ctx.sd.constant(
            ctx.unique("mean_n"),
            np.float32(len(node.inputs)))])
    return out


_UNARY = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
          "Exp": "exp", "Log": "log", "Sqrt": "sqrt", "Neg": "neg",
          "Abs": "abs", "Erf": "erf", "Floor": "floor",
          "Ceil": "ceil", "Round": "round", "Sign": "sign",
          "Softplus": "softplus", "Softsign": "softsign",
          "Not": "logical_not", "Reciprocal": "reciprocal",
          "Sin": "sin", "Cos": "cos", "Tan": "tan", "Asin": "asin",
          "Acos": "acos", "Atan": "atan", "Sinh": "sinh",
          "Cosh": "cosh", "Asinh": "asinh", "Acosh": "acosh",
          "Atanh": "atanh"}


def _unary(ctx, node):
    return ctx.sd._op(_UNARY[node.op], [ctx.var(node.inputs[0])])


for _n in _UNARY:
    ONNX_OP_MAP[_n] = _unary


@onnx_op("LeakyRelu")
def _leaky(ctx, node):
    return ctx.sd._op("leaky_relu", [ctx.var(node.inputs[0])],
                      {"alpha": node.attr("alpha", 0.01)})


@onnx_op("Elu")
def _elu(ctx, node):
    return ctx.sd._op("elu", [ctx.var(node.inputs[0])])


@onnx_op("Selu")
def _selu(ctx, node):
    return ctx.sd._op("selu", [ctx.var(node.inputs[0])])


@onnx_op("Clip")
def _clip(ctx, node):
    lo, hi = -np.inf, np.inf
    if node.attrs.get("min") is not None:
        lo = node.attr("min")
    elif len(node.inputs) > 1 and node.inputs[1]:
        lo = float(ctx.require_static(node, 1))
    if node.attrs.get("max") is not None:
        hi = node.attr("max")
    elif len(node.inputs) > 2 and node.inputs[2]:
        hi = float(ctx.require_static(node, 2))
    return ctx.sd._op("clip_by_value", [ctx.var(node.inputs[0])],
                      {"clip_value_min": float(lo),
                       "clip_value_max": float(hi)})


@onnx_op("Softmax", "LogSoftmax")
def _softmax(ctx, node):
    axis = int(node.attr("axis", -1))
    opn = "softmax" if node.op == "Softmax" else "log_softmax"
    return ctx.sd._op(opn, [ctx.var(node.inputs[0])], {"axis": axis})


@onnx_op("Gelu")
def _gelu(ctx, node):
    return ctx.sd._op("gelu", [ctx.var(node.inputs[0])])


# -- linear algebra ---------------------------------------------------------
@onnx_op("MatMul")
def _matmul(ctx, node):
    return ctx.sd._op("matmul", [ctx.var(node.inputs[0]),
                                 ctx.var(node.inputs[1])])


@onnx_op("Gemm")
def _gemm(ctx, node):
    alpha = node.attr("alpha", 1.0)
    beta = node.attr("beta", 1.0)
    ta, tb = node.attr("transA", 0), node.attr("transB", 0)
    a = ctx.var(node.inputs[0])
    b = ctx.var(node.inputs[1])
    y = ctx.sd._op("matmul", [a, b],
                   {"transpose_a": bool(ta), "transpose_b": bool(tb)})
    if alpha != 1.0:
        y = ctx.sd._op("mul", [y, ctx.sd.constant(
            ctx.unique("gemm_alpha"), np.float32(alpha))])
    if len(node.inputs) > 2 and node.inputs[2]:
        c = ctx.var(node.inputs[2])
        if beta != 1.0:
            c = ctx.sd._op("mul", [c, ctx.sd.constant(
                ctx.unique("gemm_beta"), np.float32(beta))])
        y = ctx.sd._op("add", [y, c])
    return y


# -- shape ops --------------------------------------------------------------
@onnx_op("Reshape")
def _reshape(ctx, node):
    shape = [int(v) for v in
             np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    return ctx.sd._op("reshape", [ctx.var(node.inputs[0])],
                      {"shape": shape})


@onnx_op("Flatten")
def _flatten(ctx, node):
    axis = int(node.attr("axis", 1))
    x = ctx.var(node.inputs[0])
    shape = ctx.shape_of(node.inputs[0])
    if shape is not None and axis <= len(shape):
        lead = int(np.prod(shape[:axis])) if axis else 1
        return ctx.sd._op("reshape", [x], {"shape": [lead, -1]})
    raise NotImplementedError("Flatten with unknown input shape")


@onnx_op("Transpose")
def _transpose(ctx, node):
    perm = node.attr("perm")
    return ctx.sd._op("transpose", [ctx.var(node.inputs[0])],
                      {"axes": [int(p) for p in perm]
                       if perm is not None else None})


@onnx_op("Concat")
def _concat(ctx, node):
    return ctx.sd._op("concat", [ctx.var(i) for i in node.inputs],
                      {"axis": int(node.attr("axis", 0))})


@onnx_op("Squeeze")
def _squeeze(ctx, node):
    axes = node.attr("axes")
    if axes is None and len(node.inputs) > 1:
        axes = [int(v) for v in
                np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    return ctx.sd._op("squeeze", [ctx.var(node.inputs[0])],
                      {"axis": tuple(int(a) for a in axes)
                       if axes is not None else None})


@onnx_op("Unsqueeze")
def _unsqueeze(ctx, node):
    axes = node.attr("axes")
    if axes is None and len(node.inputs) > 1:
        axes = [int(v) for v in
                np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    x = ctx.var(node.inputs[0])
    for ax in sorted(int(a) for a in axes):
        x = ctx.sd._op("expand_dims", [x], {"axis": ax})
    return x


@onnx_op("Gather")
def _gather(ctx, node):
    return ctx.sd._op("gather", [ctx.var(node.inputs[0]),
                                 ctx.var(node.inputs[1])],
                      {"axis": int(node.attr("axis", 0))})


@onnx_op("Slice")
def _slice(ctx, node):
    if len(node.inputs) > 1:       # opset 10+: starts/ends as inputs
        starts = [int(v) for v in
                  np.asarray(ctx.require_static(node, 1)).reshape(-1)]
        ends = [int(v) for v in
                np.asarray(ctx.require_static(node, 2)).reshape(-1)]
        axes = ([int(v) for v in np.asarray(
            ctx.require_static(node, 3)).reshape(-1)]
            if len(node.inputs) > 3 and node.inputs[3]
            else list(range(len(starts))))
        steps = ([int(v) for v in np.asarray(
            ctx.require_static(node, 4)).reshape(-1)]
            if len(node.inputs) > 4 and node.inputs[4]
            else [1] * len(starts))
    else:
        starts = [int(v) for v in node.attr("starts")]
        ends = [int(v) for v in node.attr("ends")]
        axes = [int(v) for v in node.attr("axes",
                                          range(len(starts)))]
        steps = [1] * len(starts)
    shape = ctx.shape_of(node.inputs[0])
    if shape is None:
        raise NotImplementedError("Slice of unknown-shape tensor")
    begin = [0] * len(shape)
    end = list(shape)
    stride = [1] * len(shape)
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        d = shape[ax]
        if st < 0:
            st += d
        if en < 0:
            en += d
        begin[ax] = min(max(st, 0), d)
        end[ax] = min(max(en, 0), d)
        stride[ax] = sp
    return ctx.sd._op("strided_slice", [ctx.var(node.inputs[0])],
                      {"begin": begin, "end": end, "strides": stride})


@onnx_op("Cast")
def _cast(ctx, node):
    from .protobuf import ONNX_DTYPES
    to = ONNX_DTYPES[int(node.attr("to"))]
    return ctx.sd._op("cast", [ctx.var(node.inputs[0])],
                      {"dtype": np.dtype(to).name})


@onnx_op("Shape")
def _shape(ctx, node):
    shape = ctx.shape_of(node.inputs[0])
    if shape is None:
        raise NotImplementedError("Shape of unknown-shape tensor")
    return ctx.sd.constant(ctx.unique(f"{node.outputs[0]}_shape"),
                           np.asarray(shape, np.int64))


@onnx_op("Pad")
def _pad(ctx, node):
    mode = node.attr("mode", b"constant")
    if isinstance(mode, bytes):
        mode = mode.decode()
    if len(node.inputs) > 1:
        pads = [int(v) for v in
                np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    else:
        pads = [int(v) for v in node.attr("pads")]
    n = len(pads) // 2
    pairs = [(pads[i], pads[i + n]) for i in range(n)]
    return ctx.sd._op("pad", [ctx.var(node.inputs[0])],
                      {"paddings": pairs, "mode": mode})


# -- reductions -------------------------------------------------------------
_REDUCE = {"ReduceMean": "reduce_mean", "ReduceSum": "reduce_sum",
           "ReduceMax": "reduce_max", "ReduceMin": "reduce_min",
           "ReduceProd": "reduce_prod"}


def _reduce(ctx, node):
    axes = node.attr("axes")
    if axes is None and len(node.inputs) > 1 and node.inputs[1]:
        axes = [int(v) for v in
                np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    keep = bool(node.attr("keepdims", 1))
    return ctx.sd._op(_REDUCE[node.op], [ctx.var(node.inputs[0])],
                      {"axis": tuple(int(a) for a in axes)
                       if axes is not None else None,
                       "keep_dims": keep})


for _n in _REDUCE:
    ONNX_OP_MAP[_n] = _reduce


@onnx_op("Resize", "Upsample")
def _resize(ctx, node):
    """torch F.interpolate / nn.Upsample export target. 4-D NCHW only
    (the shape every mainstream exporter emits); modes nearest /
    linear / cubic map onto the registry's NHWC resize ops. The
    supported coordinate conventions are exactly what torch emits —
    half_pixel/pytorch_half_pixel for linear/cubic, asymmetric+floor
    for nearest — and every other combination raises loudly rather
    than silently computing the wrong convention."""
    mode = node.attr("mode", b"nearest")
    if isinstance(mode, bytes):
        mode = mode.decode()
    # opset-18 antialias and cubic's exclude_outside change the filter
    # footprint — the registry lowering implements neither, so nonzero
    # values must fail loudly, not silently diverge (ADVICE.md)
    if int(node.attr("antialias", 0)):
        raise NotImplementedError(
            "Resize with antialias=1 unsupported (the lowering has no "
            "antialiasing filter) — export with antialias=False")
    if int(node.attr("exclude_outside", 0)):
        raise NotImplementedError(
            "Resize with exclude_outside=1 unsupported — export with "
            "exclude_outside=0")
    # Resize-10 (inputs X, scales) and opset-9 Upsample predate the
    # coordinate_transformation_mode attr; their spec semantics are
    # "asymmetric". Resize-11+ always carries roi at input 1.
    legacy = node.op == "Upsample" or len(node.inputs) == 2
    ct = node.attr("coordinate_transformation_mode",
                   b"asymmetric" if legacy else b"half_pixel")
    if isinstance(ct, bytes):
        ct = ct.decode()
    in_shape = ctx.shape_of(node.inputs[0])
    if in_shape is None or len(in_shape) != 4:
        raise NotImplementedError(
            "Resize needs a static 4-D NCHW input shape")
    size = None
    if len(node.inputs) >= 4 and node.inputs[3]:
        sizes = [int(s) for s in ctx.require_static(node, 3)]
        if sizes[:2] != [int(in_shape[0]), int(in_shape[1])]:
            raise NotImplementedError(
                f"Resize of batch/channel dims ({sizes[:2]} vs input "
                f"{tuple(in_shape[:2])}) unsupported")
        size = sizes[2:]
    else:
        si = 2 if len(node.inputs) >= 3 and node.inputs[2] else 1
        scales = np.asarray(ctx.require_static(node, si),
                            np.float64).reshape(-1)
        if scales.size != 4 or scales[0] != 1 or scales[1] != 1:
            raise NotImplementedError(
                f"Resize with batch/channel scaling {scales}")
        size = [int(np.floor(in_shape[2] * scales[2])),
                int(np.floor(in_shape[3] * scales[3]))]
    op_for = {"nearest": "resize_nearest", "linear": "resize_bilinear",
              "cubic": "resize_bicubic"}
    if mode not in op_for:
        raise NotImplementedError(f"Resize mode {mode!r}")
    attrs = {"size": tuple(size)}
    if mode == "nearest":
        nm = node.attr("nearest_mode", b"round_prefer_floor")
        if isinstance(nm, bytes):
            nm = nm.decode()
        # torch exports asymmetric+floor; legacy Upsample/Resize-10
        # are asymmetric by spec (nearest_mode attr didn't exist —
        # floor is their defined behavior)
        if ct == "asymmetric" and (nm == "floor" or legacy):
            attrs["coordinate_mode"] = "asymmetric"
        else:
            raise NotImplementedError(
                f"Resize nearest with coordinate mode {ct!r} + "
                f"nearest_mode {nm!r} unsupported (torch exports "
                f"asymmetric+floor)")
    else:
        # only the half-pixel family matches the registry lowering
        # (asymmetric linear/cubic differ even at integer factors)
        if ct not in ("half_pixel", "pytorch_half_pixel"):
            if ct == "align_corners":
                raise NotImplementedError(
                    "Resize coordinate_transformation_mode="
                    "align_corners unsupported (export with "
                    "align_corners=False)")
            raise NotImplementedError(
                f"Resize {mode} with coordinate mode {ct!r} "
                f"unsupported (half_pixel family only)")
        if ct == "pytorch_half_pixel" and (size[0] <= 1 or
                                           size[1] <= 1):
            raise NotImplementedError(
                "pytorch_half_pixel with an output dim of 1 diverges "
                "from half_pixel")
        if mode == "cubic":
            attrs["cubic_coeff_a"] = float(
                node.attr("cubic_coeff_a", -0.75))
            attrs["boundary"] = "clamp"  # the torch/ONNX convention
    x = _nchw_to_nhwc(ctx, ctx.var(node.inputs[0]))
    y = ctx.sd._op(op_for[mode], [x], attrs)
    return _nhwc_to_nchw(ctx, y)


# -- conv / pool / norm (NCHW -> NHWC) --------------------------------------
def _nchw_to_nhwc(ctx, v):
    return ctx.sd._op("transpose", [v], {"axes": [0, 2, 3, 1]})


def _nhwc_to_nchw(ctx, v):
    return ctx.sd._op("transpose", [v], {"axes": [0, 3, 1, 2]})


def _conv_padding(node):
    auto = node.attr("auto_pad", b"NOTSET")
    if isinstance(auto, bytes):
        auto = auto.decode()
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        return "SAME"
    if auto == "VALID":
        return "VALID"
    pads = node.attr("pads")
    if not pads:
        return "VALID"
    pads = [int(p) for p in pads]
    n = len(pads) // 2
    return [(pads[i], pads[i + n]) for i in range(n)]


@onnx_op("Conv")
def _conv(ctx, node):
    w_np = ctx.static(node.inputs[1])
    if w_np is None:
        raise NotImplementedError("Conv with non-constant weights")
    group = int(node.attr("group", 1))
    strides = [int(s) for s in node.attr("strides", [1, 1])]
    dil = [int(d) for d in node.attr("dilations", [1, 1])]
    padding = _conv_padding(node)
    x = _nchw_to_nhwc(ctx, ctx.var(node.inputs[0]))
    attrs = {"stride": tuple(strides), "padding": padding,
             "dilation": tuple(dil)}
    cin_total = w_np.shape[1] * group
    if group == 1:
        w = ctx.sd.constant(ctx.unique(f"{node.inputs[1]}_hwio"),
                            np.transpose(w_np, (2, 3, 1, 0)))
        y = ctx.sd._op("conv2d", [x, w], attrs)
    elif group == cin_total and w_np.shape[1] == 1:
        # depthwise: OIHW [C*m, 1, kH, kW] -> HWC(m) [kH, kW, C, m]
        m = w_np.shape[0] // group
        dw = np.transpose(w_np, (2, 3, 0, 1)).reshape(
            w_np.shape[2], w_np.shape[3], group, m)
        w = ctx.sd.constant(ctx.unique(f"{node.inputs[1]}_dw"), dw)
        y = ctx.sd._op("depthwise_conv2d", [x, w], attrs)
    else:
        # grouped conv: per-group conv2d + concat on channels
        outs = []
        cg = w_np.shape[1]
        og = w_np.shape[0] // group
        xin_shape = ctx.shape_of(node.inputs[0])   # NCHW
        if xin_shape is None:
            raise NotImplementedError("grouped Conv without shape")
        n_, c_, h_, w_ = xin_shape
        for g in range(group):
            xs = ctx.sd._op(
                "strided_slice", [x],
                {"begin": [0, 0, 0, g * cg],
                 "end": [n_, h_, w_, (g + 1) * cg],
                 "strides": [1, 1, 1, 1]})
            wg = ctx.sd.constant(
                ctx.unique(f"{node.inputs[1]}_g{g}"),
                np.transpose(w_np[g * og:(g + 1) * og], (2, 3, 1, 0)))
            outs.append(ctx.sd._op("conv2d", [xs, wg], attrs))
        y = ctx.sd._op("concat", outs, {"axis": 3})
    if len(node.inputs) > 2 and node.inputs[2]:
        y = ctx.sd._op("add", [y, ctx.var(node.inputs[2])])
    return _nhwc_to_nchw(ctx, y)


@onnx_op("MaxPool", "AveragePool")
def _pool(ctx, node):
    ks = [int(k) for k in node.attr("kernel_shape")]
    st = [int(s) for s in node.attr("strides", ks)]
    padding = _conv_padding(node)
    x = _nchw_to_nhwc(ctx, ctx.var(node.inputs[0]))
    opn = "max_pool2d" if node.op == "MaxPool" else "avg_pool2d"
    y = ctx.sd._op(opn, [x], {"kernel": tuple(ks),
                              "stride": tuple(st),
                              "padding": padding})
    return _nhwc_to_nchw(ctx, y)


@onnx_op("GlobalAveragePool", "GlobalMaxPool")
def _global_pool(ctx, node):
    opn = ("reduce_mean" if node.op == "GlobalAveragePool"
           else "reduce_max")
    return ctx.sd._op(opn, [ctx.var(node.inputs[0])],
                      {"axis": (2, 3), "keep_dims": True})


@onnx_op("BatchNormalization")
def _batch_norm(ctx, node):
    x = _nchw_to_nhwc(ctx, ctx.var(node.inputs[0]))
    gamma = ctx.var(node.inputs[1])
    beta = ctx.var(node.inputs[2])
    mean = ctx.var(node.inputs[3])
    var = ctx.var(node.inputs[4])
    y = ctx.sd._op("batch_norm", [x, mean, var, gamma, beta],
                   {"epsilon": node.attr("epsilon", 1e-5)})
    return _nhwc_to_nchw(ctx, y)


@onnx_op("Constant")
def _constant(ctx, node):
    for key in ("value", "value_float", "value_int", "value_floats",
                "value_ints"):
        v = node.attr(key)
        if v is not None:
            arr = np.asarray(v)
            if key == "value_int":
                arr = arr.astype(np.int64)
            if key == "value_ints":
                arr = arr.astype(np.int64)
            ctx.set_static(node.outputs[0], arr)
            return None
    raise NotImplementedError("Constant without value attr")


@onnx_op("ConstantOfShape")
def _constant_of_shape(ctx, node):
    shape = [int(v) for v in
             np.asarray(ctx.require_static(node, 0)).reshape(-1)]
    v = node.attr("value")
    fill = np.asarray(v).reshape(-1) if v is not None else \
        np.zeros(1, np.float32)
    ctx.set_static(node.outputs[0],
                   np.full(shape, fill[0], fill.dtype))
    return None


# -- breadth batch 2 (SURVEY.md S7 coverage): shape/index/norm/rnn ----------
@onnx_op("Split")
def _split(ctx, node):
    axis = int(node.attr("axis", 0))
    sizes = node.attr("split")
    if sizes is None and len(node.inputs) > 1 and node.inputs[1]:
        sizes = [int(s) for s in
                 np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    x = ctx.var(node.inputs[0])
    n_out = len(node.outputs)
    if sizes is None:
        return ctx.sd._op("split", [x],
                          {"num_splits": n_out, "axis": axis},
                          n_out=n_out)
    return ctx.sd._op("split_v", [x],
                      {"size_splits": [int(s) for s in sizes],
                       "axis": axis},
                      n_out=n_out)


@onnx_op("Expand")
def _expand(ctx, node):
    shape = [int(s) for s in
             np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    in_shape = ctx.shape_of(node.inputs[0])
    if in_shape is not None:
        # ONNX Expand is max-dim broadcast: a target dim of 1 keeps
        # the input dim (plain broadcast_to would reject it)
        shape = list(np.broadcast_shapes(tuple(in_shape), tuple(shape)))
    return ctx.sd._op("broadcast_to", [ctx.var(node.inputs[0])],
                      {"shape": shape})


@onnx_op("Where")
def _where(ctx, node):
    return ctx.sd._op("where", [ctx.var(node.inputs[0]),
                                ctx.var(node.inputs[1]),
                                ctx.var(node.inputs[2])])


@onnx_op("ArgMax", "ArgMin")
def _argminmax(ctx, node):
    opn = "argmax" if node.op == "ArgMax" else "argmin"
    axis = int(node.attr("axis", 0))
    out = ctx.sd._op(opn, [ctx.var(node.inputs[0])], {"axis": axis})
    if bool(node.attr("keepdims", 1)):
        out = ctx.sd._op("expand_dims", [out], {"axis": axis})
    return out


@onnx_op("Tile")
def _tile(ctx, node):
    reps = [int(r) for r in
            np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    return ctx.sd._op("tile", [ctx.var(node.inputs[0])],
                      {"reps": reps})


@onnx_op("Range")
def _range(ctx, node):
    start = np.asarray(ctx.require_static(node, 0)).reshape(-1)[0]
    limit = np.asarray(ctx.require_static(node, 1)).reshape(-1)[0]
    delta = np.asarray(ctx.require_static(node, 2)).reshape(-1)[0]
    # ONNX: output dtype == input dtype (int Range must stay int)
    arr = np.arange(start, limit, delta, dtype=start.dtype)
    ctx.set_static(node.outputs[0], arr)
    return ctx.sd.constant(ctx.unique("range"), arr)


@onnx_op("OneHot")
def _one_hot(ctx, node):
    depth = int(np.asarray(ctx.require_static(node, 1)).reshape(-1)[0])
    vals = np.asarray(ctx.require_static(node, 2)).reshape(-1)
    axis = int(node.attr("axis", -1))
    oh = ctx.sd._op("one_hot", [ctx.var(node.inputs[0])],
                    {"depth": depth, "axis": axis})
    if float(vals[0]) != 0.0 or float(vals[1]) != 1.0:
        off, on = float(vals[0]), float(vals[1])
        scale = ctx.sd.constant(ctx.unique("oh_s"),
                                np.float32(on - off))
        shift = ctx.sd.constant(ctx.unique("oh_o"), np.float32(off))
        oh = ctx.sd._op("add", [ctx.sd._op("mul", [oh, scale]), shift])
    return oh


@onnx_op("CumSum")
def _cumsum(ctx, node):
    axis = int(np.asarray(ctx.require_static(node, 1)).reshape(-1)[0])
    return ctx.sd._op("cumsum", [ctx.var(node.inputs[0])],
                      {"axis": axis,
                       "exclusive": bool(node.attr("exclusive", 0)),
                       "reverse": bool(node.attr("reverse", 0))})


@onnx_op("TopK")
def _topk(ctx, node):
    k = int(np.asarray(ctx.require_static(node, 1)).reshape(-1)[0])
    return ctx.sd._op("top_k", [ctx.var(node.inputs[0])],
                      {"k": k, "axis": int(node.attr("axis", -1)),
                       "largest": bool(node.attr("largest", 1))},
                      n_out=2)


@onnx_op("Einsum")
def _einsum(ctx, node):
    return ctx.sd._op("einsum",
                      [ctx.var(i) for i in node.inputs],
                      {"equation": node.attr("equation").decode()
                       if isinstance(node.attr("equation"), bytes)
                       else node.attr("equation")})


@onnx_op("LRN")
def _lrn_onnx(ctx, node):
    # ONNX LRN is NCHW over the C axis; ours is channel-last
    x = _nchw_to_nhwc(ctx, ctx.var(node.inputs[0]))
    y = ctx.sd._op("lrn", [x],
                   {"depth": int(node.attr("size", 5)),
                    "bias": float(node.attr("bias", 1.0)),
                    # ONNX alpha is the SUM coefficient pre-divided
                    # by size; our op multiplies the raw window sum
                    "alpha": float(node.attr("alpha", 1e-4)) /
                    int(node.attr("size", 5)),
                    "beta": float(node.attr("beta", 0.75))})
    return _nhwc_to_nchw(ctx, y)


@onnx_op("SpaceToDepth")
def _space_to_depth_onnx(ctx, node):
    # ONNX output channels order [dy, dx, c] — exactly the NHWC op's
    # layout, so only the NCHW<->NHWC transposes are needed
    x = _nchw_to_nhwc(ctx, ctx.var(node.inputs[0]))
    y = ctx.sd._op("space_to_depth", [x],
                   {"block_size": int(node.attr("blocksize", 2))})
    return _nhwc_to_nchw(ctx, y)


@onnx_op("DepthToSpace")
def _depth_to_space_onnx(ctx, node):
    mode = node.attr("mode", b"DCR")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    b = int(node.attr("blocksize", 2))
    x = _nchw_to_nhwc(ctx, ctx.var(node.inputs[0]))
    if mode == "DCR":
        # DCR channel order [dy, dx, co] == the NHWC op's expectation
        xg = x
    else:
        # CRD stores [co, dy, dx]; permute to [dy, dx, co] (needs the
        # static channel count)
        cin = ctx.shape_of(node.inputs[0])
        if cin is None:
            raise NotImplementedError(
                "DepthToSpace CRD: unknown input shape")
        c = cin[1]
        co = c // (b * b)
        perm = np.arange(c).reshape(co, b * b).T.reshape(-1)
        xg = ctx.sd._op("gather", [x, ctx.sd.constant(
            ctx.unique("d2s_perm"), perm.astype(np.int32))],
            {"axis": -1})
    y = ctx.sd._op("depth_to_space", [xg], {"block_size": b})
    return _nhwc_to_nchw(ctx, y)


@onnx_op("ScatterND")
def _scatter_nd_onnx(ctx, node):
    data = ctx.var(node.inputs[0])
    idx = ctx.var(node.inputs[1])
    upd = ctx.var(node.inputs[2])
    return ctx.sd._op("scatter_nd_update", [data, idx, upd])


@onnx_op("ReduceL2")
def _reduce_l2(ctx, node):
    axes = node.attr("axes")
    if axes is None and len(node.inputs) > 1 and node.inputs[1]:
        axes = [int(v) for v in
                np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    keep = bool(node.attr("keepdims", 1))
    sq = ctx.sd._op("square", [ctx.var(node.inputs[0])])
    s = ctx.sd._op("reduce_sum", [sq],
                   {"axis": tuple(axes) if axes else None,
                    "keep_dims": keep})
    return ctx.sd._op("sqrt", [s])


@onnx_op("InstanceNormalization")
def _instance_norm(ctx, node):
    # NCHW: normalize over spatial dims per channel per example
    x = ctx.var(node.inputs[0])
    scale = ctx.var(node.inputs[1])
    bias = ctx.var(node.inputs[2])
    eps = float(node.attr("epsilon", 1e-5))
    xn = ctx.sd._op("standardize", [x], {"axis": (2, 3),
                                         "epsilon": eps})
    s = ctx.sd._op("reshape", [scale], {"shape": (1, -1, 1, 1)})
    b = ctx.sd._op("reshape", [bias], {"shape": (1, -1, 1, 1)})
    return ctx.sd._op("add", [ctx.sd._op("mul", [xn, s]), b])


@onnx_op("LayerNormalization")
def _layer_norm_onnx(ctx, node):
    """ONNX normalizes over dims [axis, rank): a non-last axis becomes
    a tuple of axes; Scale/B have shape x.shape[axis:] so they
    broadcast against x without reshapes."""
    axis = int(node.attr("axis", -1))
    eps = float(node.attr("epsilon", 1e-5))
    if axis == -1:
        ax = -1
    else:
        in_shape = ctx.shape_of(node.inputs[0])
        if in_shape is None:
            raise NotImplementedError(
                "LayerNormalization: non-last axis needs a known "
                "input shape")
        rank = len(in_shape)
        ax = axis % rank
        ax = -1 if ax == rank - 1 else tuple(range(ax, rank))
    ins = [ctx.var(node.inputs[0]), ctx.var(node.inputs[1])]
    if len(node.inputs) > 2 and node.inputs[2]:
        ins.append(ctx.var(node.inputs[2]))
    return ctx.sd._op("layer_norm", ins, {"axis": ax, "epsilon": eps})


@onnx_op("PRelu")
def _prelu_onnx(ctx, node):
    x = ctx.var(node.inputs[0])
    a = ctx.var(node.inputs[1])
    pos = ctx.sd._op("relu", [x])
    neg = ctx.sd._op("mul", [a, ctx.sd._op("minimum", [
        x, ctx.sd.constant(ctx.unique("zero"), np.float32(0.0))])])
    return ctx.sd._op("add", [pos, neg])


@onnx_op("HardSigmoid")
def _hard_sigmoid(ctx, node):
    alpha = float(node.attr("alpha", 0.2))
    beta = float(node.attr("beta", 0.5))
    x = ctx.var(node.inputs[0])
    ax = ctx.sd._op("mul", [x, ctx.sd.constant(
        ctx.unique("hs_a"), np.float32(alpha))])
    s = ctx.sd._op("add", [ax, ctx.sd.constant(
        ctx.unique("hs_b"), np.float32(beta))])
    return ctx.sd._op("clip_by_value", [s],
                      {"clip_value_min": 0.0, "clip_value_max": 1.0})


@onnx_op("Mod")
def _mod(ctx, node):
    if not int(node.attr("fmod", 0)):
        return ctx.sd._op("mod", [ctx.var(node.inputs[0]),
                                  ctx.var(node.inputs[1])])
    return ctx.sd._op("fmod", [ctx.var(node.inputs[0]),
                               ctx.var(node.inputs[1])])


@onnx_op("ConvTranspose")
def _conv_transpose_onnx(ctx, node):
    """Full ONNX attribute surface: group, dilations, output_padding,
    asymmetric pads, auto_pad.  Output size per spatial dim:
    (i-1)*s + (k-1)*d + 1 - pad_begin - pad_end + output_padding."""
    w_np = ctx.static(node.inputs[1])
    if w_np is None:
        raise NotImplementedError(
            "ConvTranspose with non-constant weights")
    group = int(node.attr("group", 1))
    strides = [int(s) for s in node.attr("strides", [1, 1])]
    dil = [int(d) for d in node.attr("dilations", [1, 1])]
    out_pad = [int(p) for p in node.attr("output_padding", [0, 0])]
    kh, kw = w_np.shape[2], w_np.shape[3]
    ke = [(kh - 1) * dil[0] + 1, (kw - 1) * dil[1] + 1]
    ap = node.attr("auto_pad", b"NOTSET")
    ap = ap.decode() if isinstance(ap, bytes) else ap
    out_shape = node.attr("output_shape")

    def _split(totals, extra_at_begin):
        # int(t/2) truncates toward zero: negative totals (stride >
        # kernel extent) must keep begin at 0 so the first output
        # sample stays at the origin
        begin = [(t - int(t / 2) if extra_at_begin else int(t / 2))
                 for t in totals]
        return begin + [t - b for t, b in zip(totals, begin)]

    if out_shape is not None:
        # pads derived from the requested output size (spec formula)
        xin = ctx.shape_of(node.inputs[0])
        if xin is None:
            raise NotImplementedError(
                "ConvTranspose: output_shape needs a known input "
                "shape")
        totals = [strides[d] * (xin[2 + d] - 1) + out_pad[d] + ke[d]
                  - int(out_shape[d]) for d in range(2)]
        pads = _split(totals, extra_at_begin=(ap != "SAME_UPPER"))
    elif ap in ("SAME_UPPER", "SAME_LOWER"):
        # output_shape[i] = input_shape[i] * strides[i]; a negative
        # total (stride > kernel extent) flows through as extra
        # conv_transpose padding — no clamp
        totals = [ke[d] - strides[d] for d in range(2)]
        pads = _split(totals, extra_at_begin=(ap == "SAME_LOWER"))
    elif ap in ("NOTSET", "", "VALID"):
        pads = [int(p) for p in node.attr("pads", [0, 0, 0, 0])]
    else:
        raise NotImplementedError(f"ConvTranspose: auto_pad={ap}")
    # conv_transpose explicit padding applies to the s-dilated input;
    # ke-1-p per side yields the ONNX output size, with
    # output_padding widening the END side only
    attrs = {"stride": tuple(strides), "dilation": tuple(dil),
             "padding": [(ke[0] - 1 - pads[0],
                          ke[0] - 1 - pads[2] + out_pad[0]),
                         (ke[1] - 1 - pads[1],
                          ke[1] - 1 - pads[3] + out_pad[1])]}
    x = _nchw_to_nhwc(ctx, ctx.var(node.inputs[0]))

    def _wv(arr, tag):
        # ONNX W is IOHW [C_in, C_out, kH, kW]; ours HWIO
        # (conv_transpose applies the kernel un-mirrored, matching
        # gradient-of-conv with the spatial flip baked in here)
        w = np.transpose(arr, (2, 3, 0, 1))[::-1, ::-1]
        return ctx.sd.constant(ctx.unique(f"{node.inputs[1]}{tag}"),
                               np.ascontiguousarray(w))

    if group == 1:
        y = ctx.sd._op("deconv2d", [x, _wv(w_np, "_hwio")], attrs)
    else:
        # per-group transpose-conv + concat on channels (W holds
        # C_in total rows, C_out/group columns)
        xin_shape = ctx.shape_of(node.inputs[0])   # NCHW
        if xin_shape is None:
            raise NotImplementedError(
                "grouped ConvTranspose without a known input shape")
        n_, c_, h_, wdim = xin_shape
        cg = c_ // group
        outs = []
        for g in range(group):
            xs = ctx.sd._op(
                "strided_slice", [x],
                {"begin": [0, 0, 0, g * cg],
                 "end": [n_, h_, wdim, (g + 1) * cg],
                 "strides": [1, 1, 1, 1]})
            outs.append(ctx.sd._op(
                "deconv2d",
                [xs, _wv(w_np[g * cg:(g + 1) * cg], f"_g{g}")],
                attrs))
        y = ctx.sd._op("concat", outs, {"axis": 3})
    if len(node.inputs) > 2 and node.inputs[2]:
        y = ctx.sd._op("add", [y, ctx.var(node.inputs[2])])
    return _nhwc_to_nchw(ctx, y)


# -- control flow (SURVEY.md S7/S3: ONNX If/Loop map to the same lax
# lowering the TF While/If path uses) ---------------------------------------
def _scan_accumulators(ctx, node, body, scan_names, m):
    """Dense [m, *elem] zero accumulators for Loop/Scan scan outputs;
    shape and dtype must be declared in the body graph."""
    accs = []
    for sn in scan_names:
        sh = body.output_shapes.get(sn)
        if sh is None or any(d is None or d < 0 for d in sh):
            raise NotImplementedError(
                f"{node.op} '{node.name}': scan output '{sn}' needs "
                f"a declared concrete shape in the body graph")
        dt = body.output_dtypes.get(sn)
        if isinstance(dt, int):
            raise NotImplementedError(
                f"{node.op} '{node.name}': scan output '{sn}' has "
                f"unsupported ONNX element dtype enum {dt}")
        if dt is None:
            raise NotImplementedError(
                f"{node.op} '{node.name}': scan output '{sn}' needs "
                f"a declared element dtype in the body graph")
        accs.append(ctx.sd.constant(
            ctx.unique(f"{node.name}_scan"),
            np.zeros((m,) + tuple(sh), dt)))
    return accs
@onnx_op("If")
def _if_onnx(ctx, node):
    then_g = node.attrs["then_branch"].value
    else_g = node.attrs["else_branch"].value
    pred = ctx.var(node.inputs[0])
    outs = ctx.sd.cond(pred,
                       ctx.subgraph_callable(then_g, []),
                       ctx.subgraph_callable(else_g, []), [])
    return outs if isinstance(outs, tuple) else (outs,)


@onnx_op("Loop")
def _loop_onnx(ctx, node):
    """ONNX Loop: inputs (M?, cond?, v_initial...), body graph with
    inputs (iter_num, cond_in, v_in...) and outputs (cond_out,
    v_out..., scan_outputs...).  Lowers to SameDiff.while_loop over
    loop vars (i, cond, *carried, *scan_accumulators) — with a STATIC
    trip count M the bounded, reverse-differentiable form.  Scan
    outputs accumulate into dense [M, elem] tensors (the TensorArray
    lowering); early-terminating conds leave tail rows zero (README
    migration table).  Dynamic M raises loudly."""
    body = node.attrs["body"].value
    m_name = node.inputs[0] if len(node.inputs) > 0 else ""
    cond_name = node.inputs[1] if len(node.inputs) > 1 else ""
    carried_names = [n for n in node.inputs[2:]]
    n_carried = len(carried_names)
    body_in_names = [n for n, _ in body.inputs]
    n_scan = len(body.outputs) - 1 - n_carried
    if n_scan < 0:
        raise NotImplementedError(
            f"Loop '{node.name}': body declares fewer outputs than "
            f"1 + {n_carried} carried values")
    if len(body_in_names) != 2 + n_carried:
        raise NotImplementedError(
            f"Loop '{node.name}': body declares {len(body_in_names)} "
            f"inputs for 2 + {n_carried} loop-carried values")
    m_static = ctx.static(m_name) if m_name else None
    if m_static is not None:
        m_static = int(np.asarray(m_static).reshape(())[()])
        if m_static >= 2 ** 31 - 1:
            if not cond_name:
                # no cond to ever stop it: lowering would hang, not
                # run a quintillion-trip for-loop
                raise NotImplementedError(
                    f"Loop '{node.name}': trip count {m_static} "
                    f"with no cond input cannot lower")
            # torch exports while-style loops as M=INT64_MAX plus a
            # real cond: effectively unbounded
            m_static = None
    elif m_name:
        # a runtime trip count can't bound the lowered loop — silence
        # here would run a DIFFERENT trip count than the model says
        raise NotImplementedError(
            f"Loop '{node.name}': trip count '{m_name}' must be a "
            f"constant/initializer (dynamic M unsupported)")
    scan_names = body.outputs[1 + n_carried:]
    accs = []
    if n_scan:
        # scan outputs: dense [M, *elem] accumulators written per
        # iteration (the TensorArray lowering).  Needs a static M and
        # declared element shapes.  Documented divergence (README):
        # an early-terminating cond leaves the tail rows ZERO —
        # static shapes cannot express ONNX's [actual_trips, ...].
        if m_static is None:
            raise NotImplementedError(
                f"Loop '{node.name}': scan outputs need a FINITE "
                f"constant trip count M (unbounded/while-style loops "
                f"cannot preallocate the stacked result)")
        accs = _scan_accumulators(ctx, node, body, scan_names,
                                  m_static)
    carried = [ctx.var(n) for n in carried_names]
    i0 = ctx.sd.constant(ctx.unique("loop_i"), np.asarray(0, np.int32))
    if cond_name:
        cond0 = ctx.var(cond_name)
    else:
        if m_static is None:
            # neither a trip count nor a cond input: the spec's
            # "infinite loop" form, which cannot lower
            raise NotImplementedError(
                f"Loop '{node.name}': no trip count and no cond "
                f"input (infinite loop form) cannot lower")
        cond0 = ctx.sd.constant(ctx.unique("loop_c"),
                                np.asarray(True))
    m_const = (None if m_static is None else
               ctx.sd.constant(ctx.unique("loop_m"),
                               np.asarray(m_static, np.int32)))

    body_fn_inner = ctx.subgraph_callable(body, body_in_names)

    def cond_fn(i, c, *vs):
        csd = i.sd
        if not cond_name:
            # for-loop form (M given, cond input absent): the spec
            # says the body's cond output is ignored — drive the
            # loop purely by i < M (the body cond is still carried,
            # it just never gates continuation)
            return csd._op("lt", [i, m_const])
        keep = c
        if m_const is not None:
            keep = csd._op("logical_and",
                           [keep, csd._op("lt", [i, m_const])])
        return keep

    def body_fn(i, c, *vs):
        csd = i.sd
        carried_in = vs[:n_carried]
        acc_in = vs[n_carried:]
        outs = body_fn_inner(i, c, *carried_in)
        cond_out = outs[0]
        v_outs = list(outs[1:1 + n_carried])
        scan_vals = outs[1 + n_carried:]
        acc_out = [csd._op("tensor_list_set_item", [a, i, sv])
                   for a, sv in zip(acc_in, scan_vals)]
        one = csd._as_var(np.asarray(1, np.int32))
        return tuple([csd._op("add", [i, one]), cond_out]
                     + v_outs + acc_out)

    outs = ctx.sd.while_loop(
        [i0, cond0] + carried + accs, cond_fn, body_fn,
        max_iterations=m_static)
    return tuple(outs[2:2 + n_carried + n_scan])


@onnx_op("Scan")
def _scan_onnx(ctx, node):
    """ONNX Scan (opset 9+ form, no sequence_lens): inputs = N state
    initials then M scan inputs sliced along axis 0 per iteration;
    body(state..., slices...) -> (new_state..., scan_outputs...).
    The trip count is the scan inputs' leading dim (static), so the
    lowering is the bounded differentiable while: slices read with a
    dynamic index, scan outputs accumulate densely."""
    body = node.attrs["body"].value
    if node.attr("num_scan_inputs") is None:
        raise NotImplementedError(
            f"Scan '{node.name}': required attribute "
            f"num_scan_inputs is missing")
    n_scan_in = int(node.attr("num_scan_inputs"))
    n_state = len(node.inputs) - n_scan_in
    if n_state < 0:
        raise NotImplementedError(
            f"Scan '{node.name}': num_scan_inputs "
            f"{n_scan_in} > {len(node.inputs)} inputs")
    if len(body.outputs) < n_state:
        raise NotImplementedError(
            f"Scan '{node.name}': body declares {len(body.outputs)} "
            f"outputs for {n_state} states")
    for a in ("scan_input_axes", "scan_input_directions",
              "scan_output_axes", "scan_output_directions"):
        v = node.attr(a)
        if v is not None and any(int(e) for e in v):
            raise NotImplementedError(
                f"Scan '{node.name}': non-default {a} unsupported")
    body_in_names = [n for n, _ in body.inputs]
    if len(body_in_names) != n_state + n_scan_in:
        raise NotImplementedError(
            f"Scan '{node.name}': body declares "
            f"{len(body_in_names)} inputs for {n_state} states + "
            f"{n_scan_in} scan inputs")
    states = [ctx.var(n) for n in node.inputs[:n_state]]
    scan_ins = [ctx.var(n) for n in node.inputs[n_state:]]
    lengths = {ctx.shape_of(n)[0] if ctx.shape_of(n) else None
               for n in node.inputs[n_state:]}
    if (len(lengths) != 1 or None in lengths
            or any(l < 0 for l in lengths)):
        # an UNKNOWN length must fail too: a shorter actual input
        # would silently re-read its last row for the tail
        # iterations; a SYMBOLIC length parses as -1 and would flow
        # into np.zeros((-1,...)) with a confusing ValueError
        raise NotImplementedError(
            f"Scan '{node.name}': every scan-input length must be "
            f"static and uniform (got "
            f"{sorted(lengths, key=str)})")
    m = int(lengths.pop())
    n_scan_out = len(body.outputs) - n_state
    scan_out_names = body.outputs[n_state:]
    accs = _scan_accumulators(ctx, node, body, scan_out_names, m)
    i0 = ctx.sd.constant(ctx.unique("scan_i"), np.asarray(0, np.int32))
    body_fn_inner = ctx.subgraph_callable(body, body_in_names)

    def cond_fn(i, *vs):
        return i.sd._op("lt", [i, i.sd._as_var(
            np.asarray(m, np.int32))])

    def body_fn(i, *vs):
        csd = i.sd
        st = vs[:n_state]
        sc = vs[n_state:n_state + n_scan_in]
        acc = vs[n_state + n_scan_in:]
        slices = [csd._op("tensor_list_get_item", [s, i]) for s in sc]
        outs = body_fn_inner(*(list(st) + slices))
        new_st = list(outs[:n_state])
        scan_vals = outs[n_state:]
        new_acc = [csd._op("tensor_list_set_item", [a, i, sv])
                   for a, sv in zip(acc, scan_vals)]
        one = csd._as_var(np.asarray(1, np.int32))
        return tuple([csd._op("add", [i, one])] + new_st
                     + list(sc) + new_acc)

    outs = ctx.sd.while_loop(
        [i0] + states + scan_ins + accs, cond_fn, body_fn,
        max_iterations=m)
    final_states = outs[1:1 + n_state]
    final_accs = outs[1 + n_state + n_scan_in:]
    return tuple(list(final_states) + list(final_accs[:n_scan_out]))



def _rnn_guards(ctx, node, default_acts):
    """Shared LSTM/GRU precondition checks.  Returns (direction,
    dirs).  An ``activations`` attr spelling out the per-direction
    DEFAULTS is accepted (tf2onnx serializes it explicitly)."""
    if int(node.attr("layout", 0)) != 0:
        raise NotImplementedError(
            f"{node.op} '{node.name}': layout=1 (batch-major) "
            f"unsupported")
    direction = node.attr("direction", b"forward")
    direction = (direction.decode()
                 if isinstance(direction, bytes) else direction)
    dirs = 2 if direction == "bidirectional" else 1
    acts = node.attr("activations")
    if acts is not None:
        got = [a.decode().lower() if isinstance(a, bytes)
               else str(a).lower() for a in acts]
        if got != default_acts * dirs:
            raise NotImplementedError(
                f"{node.op} '{node.name}': custom activations "
                f"{got} unsupported")
    if len(node.inputs) > 4 and node.inputs[4]:
        raise NotImplementedError(
            f"{node.op} '{node.name}': sequence_lens unsupported")
    if node.attr("clip") is not None:
        raise NotImplementedError(
            f"{node.op} '{node.name}': clip unsupported")
    return direction, dirs


def _rnn_initial(ctx, node, idx, dirs, b, H, tag):
    """Per-direction initial state: slice of the [dirs, b, H] input,
    or zeros."""
    if len(node.inputs) > idx and node.inputs[idx]:
        v = ctx.var(node.inputs[idx])
        return [ctx.sd._op("tensor_list_get_item",
                           [v, ctx.sd.constant(
                               ctx.unique(f"{tag}_d"),
                               np.asarray(d, np.int32))])
                for d in range(dirs)]
    zero = ctx.sd.constant(ctx.unique(tag),
                           np.zeros((b, H), np.float32))
    return [zero] * dirs


def _rnn_concat(ctx, parts, axis):
    return (parts[0] if len(parts) == 1
            else ctx.sd._op("concat", parts, {"axis": axis}))



def _rnn_directions(ctx, direction, dirs, xb, run_dir):
    """Shared per-direction scaffolding for LSTM/GRU/RNN: time-flip
    the input for the reverse direction, call ``run_dir(d, xin) ->
    (h_seq [b,t,H], *states [b,H])``, un-flip, reshape to the ONNX
    [t, dirs, b, H] layout and concat across directions.  Returns
    (Y, *concatenated_states)."""
    y_dirs = None
    state_lists = None
    for d in range(dirs):
        xin = xb
        if d == 1 or direction == "reverse":
            xin = ctx.sd._op("reverse", [xb], {"axes": (1,)})
        outs = run_dir(d, xin)
        h_seq, states = outs[0], outs[1:]
        if d == 1 or direction == "reverse":
            h_seq = ctx.sd._op("reverse", [h_seq], {"axes": (1,)})
        ht = ctx.sd._op("transpose", [h_seq], {"axes": (1, 0, 2)})
        if y_dirs is None:
            y_dirs = []
            state_lists = [[] for _ in states]
        y_dirs.append(ctx.sd._op("expand_dims", [ht], {"axis": 1}))
        for lst, st in zip(state_lists, states):
            lst.append(ctx.sd._op("expand_dims", [st], {"axis": 0}))
    return tuple([_rnn_concat(ctx, y_dirs, 1)]
                 + [_rnn_concat(ctx, lst, 0) for lst in state_lists])


@onnx_op("LSTM")
def _lstm_onnx(ctx, node):
    """ONNX LSTM (what torch exports nn.LSTM to): X [seq, b, in]
    (layout=0), W [dirs, 4H, in] / R [dirs, 4H, H] in gate order
    (i, o, f, c), B [dirs, 8H] = Wb ++ Rb.  Lowers onto the scan-based
    ``lstm_layer`` op (gate order [i, f, o, g]): weights reorder and
    transpose statically; the reverse direction flips time around the
    scan.  Outputs Y [seq, dirs, b, H], Y_h / Y_c [dirs, b, H]."""
    direction, dirs = _rnn_guards(ctx, node,
                                  ["sigmoid", "tanh", "tanh"])
    if len(node.inputs) > 7 and node.inputs[7]:
        raise NotImplementedError(
            f"LSTM '{node.name}': peephole weights (P) unsupported")
    if node.attr("input_forget"):
        raise NotImplementedError(
            f"LSTM '{node.name}': input_forget (coupled gates) "
            f"unsupported")
    H = int(node.attr("hidden_size"))
    w_np = np.asarray(ctx.require_static(node, 1))   # [dirs, 4H, in]
    r_np = np.asarray(ctx.require_static(node, 2))   # [dirs, 4H, H]
    b_np = (np.asarray(ctx.require_static(node, 3))
            if len(node.inputs) > 3 and node.inputs[3]
            else np.zeros((dirs, 8 * H), np.float32))

    def reorder(m):
        # rows (i, o, f, c) -> (i, f, o, g)
        blocks = [m[0:H], m[2 * H:3 * H], m[H:2 * H], m[3 * H:]]
        return np.concatenate(blocks, axis=0)

    x = ctx.var(node.inputs[0])
    xb = ctx.sd._op("transpose", [x], {"axes": (1, 0, 2)})  # [b,t,in]
    in_shape = ctx.shape_of(node.inputs[0])
    if in_shape is None:
        raise NotImplementedError(
            f"LSTM '{node.name}': input shape must be known")
    b = int(in_shape[1])

    h0s = _rnn_initial(ctx, node, 5, dirs, b, H, f"{node.name}_h0")
    c0s = _rnn_initial(ctx, node, 6, dirs, b, H, f"{node.name}_c0")

    def run_dir(d, xin):
        w = ctx.sd.constant(ctx.unique(f"{node.name}_w{d}"),
                            np.ascontiguousarray(
                                reorder(w_np[d]).T))     # [in, 4H]
        rw = ctx.sd.constant(ctx.unique(f"{node.name}_r{d}"),
                             np.ascontiguousarray(
                                 reorder(r_np[d]).T))    # [H, 4H]
        bias = ctx.sd.constant(
            ctx.unique(f"{node.name}_b{d}"),
            reorder(b_np[d][:4 * H])
            + reorder(b_np[d][4 * H:]))
        return ctx.sd._op("lstm_layer",
                          [xin, h0s[d], c0s[d], w, rw, bias],
                          n_out=3)

    return _rnn_directions(ctx, direction, dirs, xb, run_dir)


@onnx_op("GRU")
def _gru_onnx(ctx, node):
    """ONNX GRU (torch nn.GRU export): X [seq, b, in], W [dirs, 3H,
    in] / R [dirs, 3H, H] in gate order (z, r, h), B [dirs, 6H] =
    Wb ++ Rb, ``linear_before_reset`` attr (torch exports 1).  Lowers
    onto the scan-based ``gru_layer`` op, which keeps the ONNX gate
    order natively — only a transpose of the static weights."""
    direction, dirs = _rnn_guards(ctx, node, ["sigmoid", "tanh"])
    H = int(node.attr("hidden_size"))
    lbr = int(node.attr("linear_before_reset", 0))
    w_np = np.asarray(ctx.require_static(node, 1))   # [dirs, 3H, in]
    r_np = np.asarray(ctx.require_static(node, 2))   # [dirs, 3H, H]
    b_np = (np.asarray(ctx.require_static(node, 3))
            if len(node.inputs) > 3 and node.inputs[3]
            else np.zeros((dirs, 6 * H), np.float32))

    x = ctx.var(node.inputs[0])
    xb = ctx.sd._op("transpose", [x], {"axes": (1, 0, 2)})  # [b,t,in]
    in_shape = ctx.shape_of(node.inputs[0])
    if in_shape is None:
        raise NotImplementedError(
            f"GRU '{node.name}': input shape must be known")
    b = int(in_shape[1])

    h0s = _rnn_initial(ctx, node, 5, dirs, b, H, f"{node.name}_h0")
    y_dirs, h_lasts = [], []
    for d in range(dirs):
        w = ctx.sd.constant(ctx.unique(f"{node.name}_w{d}"),
                            np.ascontiguousarray(w_np[d].T))
        rw = ctx.sd.constant(ctx.unique(f"{node.name}_r{d}"),
                             np.ascontiguousarray(r_np[d].T))
        wb = ctx.sd.constant(ctx.unique(f"{node.name}_wb{d}"),
                             b_np[d][:3 * H])
        rb = ctx.sd.constant(ctx.unique(f"{node.name}_rb{d}"),
                             b_np[d][3 * H:])
        xin = xb
        if d == 1 or direction == "reverse":
            xin = ctx.sd._op("reverse", [xb], {"axes": (1,)})
        h_seq, h_last = ctx.sd._op(
            "gru_layer", [xin, h0s[d], w, rw, wb, rb],
            {"linear_before_reset": lbr}, n_out=2)
        if d == 1 or direction == "reverse":
            h_seq = ctx.sd._op("reverse", [h_seq], {"axes": (1,)})
        ht = ctx.sd._op("transpose", [h_seq], {"axes": (1, 0, 2)})
        y_dirs.append(ctx.sd._op("expand_dims", [ht], {"axis": 1}))
        h_lasts.append(ctx.sd._op("expand_dims", [h_last],
                                  {"axis": 0}))

    return (_rnn_concat(ctx, y_dirs, 1), _rnn_concat(ctx, h_lasts, 0))


@onnx_op("RNN")
def _rnn_onnx(ctx, node):
    """ONNX vanilla RNN: h_t = tanh(x W^T + h R^T + Wb + Rb), with
    W [dirs, H, in] / R [dirs, H, H] / B [dirs, 2H]."""
    direction, dirs = _rnn_guards(ctx, node, ["tanh"])
    H = int(node.attr("hidden_size"))
    w_np = np.asarray(ctx.require_static(node, 1))
    r_np = np.asarray(ctx.require_static(node, 2))
    b_np = (np.asarray(ctx.require_static(node, 3))
            if len(node.inputs) > 3 and node.inputs[3]
            else np.zeros((dirs, 2 * H), np.float32))
    x = ctx.var(node.inputs[0])
    xb = ctx.sd._op("transpose", [x], {"axes": (1, 0, 2)})
    in_shape = ctx.shape_of(node.inputs[0])
    if in_shape is None:
        raise NotImplementedError(
            f"RNN '{node.name}': input shape must be known")
    b = int(in_shape[1])
    h0s = _rnn_initial(ctx, node, 5, dirs, b, H, f"{node.name}_h0")
    def run_dir(d, xin):
        w = ctx.sd.constant(ctx.unique(f"{node.name}_w{d}"),
                            np.ascontiguousarray(w_np[d].T))
        rw = ctx.sd.constant(ctx.unique(f"{node.name}_r{d}"),
                             np.ascontiguousarray(r_np[d].T))
        bias = ctx.sd.constant(ctx.unique(f"{node.name}_b{d}"),
                               b_np[d][:H] + b_np[d][H:])
        return ctx.sd._op(
            "rnn_layer", [xin, h0s[d], w, rw, bias], n_out=2)

    return _rnn_directions(ctx, direction, dirs, xb, run_dir)
