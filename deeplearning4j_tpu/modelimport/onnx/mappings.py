"""Per-op ONNX → SameDiff mapping rules (SURVEY.md S7:
`samediff-import-onnx`'s OpMappingRegistry equivalent — the same
rule-function pattern as the TF importer's `mappings.py`).

ONNX convs/pools are NCHW with OIHW weights; our conv ops are NHWC
with HWIO kernels (the TPU-friendly layout), so rules transpose on
the way in/out and XLA cancels adjacent transposes after fusion.
"""
from __future__ import annotations

from typing import Callable, Dict

import numpy as np

ONNX_OP_MAP: Dict[str, Callable] = {}


def onnx_op(*names):
    def deco(fn):
        for n in names:
            ONNX_OP_MAP[n] = fn
        return fn
    return deco


# -- passthrough ------------------------------------------------------------
@onnx_op("Identity")
def _identity(ctx, node):
    return ctx.sd._op("identity", [ctx.var(node.inputs[0])])


@onnx_op("Dropout")
def _dropout(ctx, node):
    # inference import: identity (+ all-true mask if requested)
    y = ctx.sd._op("identity", [ctx.var(node.inputs[0])])
    if len(node.outputs) > 1:
        mask = ctx.sd._op("ones_like", [ctx.var(node.inputs[0])])
        return [y, mask]
    return y


# -- elementwise ------------------------------------------------------------
_BINARY = {"Add": "add", "Sub": "sub", "Mul": "mul", "Div": "div",
           "Pow": "pow", "Greater": "gt", "Less": "lt",
           "Equal": "eq", "Min": "minimum", "Max": "maximum",
           "And": "logical_and", "Or": "logical_or"}


def _binary(ctx, node):
    out = ctx.var(node.inputs[0])
    for other in node.inputs[1:]:
        out = ctx.sd._op(_BINARY[node.op], [out, ctx.var(other)])
    return out


for _n in _BINARY:
    ONNX_OP_MAP[_n] = _binary


@onnx_op("Sum", "Mean")
def _variadic(ctx, node):
    out = ctx.var(node.inputs[0])
    for other in node.inputs[1:]:
        out = ctx.sd._op("add", [out, ctx.var(other)])
    if node.op == "Mean" and len(node.inputs) > 1:
        out = ctx.sd._op("div", [out, ctx.sd.constant(
            ctx.unique("mean_n"),
            np.float32(len(node.inputs)))])
    return out


_UNARY = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
          "Exp": "exp", "Log": "log", "Sqrt": "sqrt", "Neg": "neg",
          "Abs": "abs", "Erf": "erf", "Floor": "floor",
          "Ceil": "ceil", "Round": "round", "Sign": "sign",
          "Softplus": "softplus", "Softsign": "softsign",
          "Not": "logical_not", "Reciprocal": "reciprocal",
          "Sin": "sin", "Cos": "cos", "Tan": "tan", "Asin": "asin",
          "Acos": "acos", "Atan": "atan", "Sinh": "sinh",
          "Cosh": "cosh", "Asinh": "asinh", "Acosh": "acosh",
          "Atanh": "atanh"}


def _unary(ctx, node):
    return ctx.sd._op(_UNARY[node.op], [ctx.var(node.inputs[0])])


for _n in _UNARY:
    ONNX_OP_MAP[_n] = _unary


@onnx_op("LeakyRelu")
def _leaky(ctx, node):
    return ctx.sd._op("leaky_relu", [ctx.var(node.inputs[0])],
                      {"alpha": node.attr("alpha", 0.01)})


@onnx_op("Elu")
def _elu(ctx, node):
    return ctx.sd._op("elu", [ctx.var(node.inputs[0])])


@onnx_op("Selu")
def _selu(ctx, node):
    return ctx.sd._op("selu", [ctx.var(node.inputs[0])])


@onnx_op("Clip")
def _clip(ctx, node):
    lo, hi = -np.inf, np.inf
    if node.attrs.get("min") is not None:
        lo = node.attr("min")
    elif len(node.inputs) > 1 and node.inputs[1]:
        lo = float(ctx.require_static(node, 1))
    if node.attrs.get("max") is not None:
        hi = node.attr("max")
    elif len(node.inputs) > 2 and node.inputs[2]:
        hi = float(ctx.require_static(node, 2))
    return ctx.sd._op("clip_by_value", [ctx.var(node.inputs[0])],
                      {"clip_value_min": float(lo),
                       "clip_value_max": float(hi)})


@onnx_op("Softmax", "LogSoftmax")
def _softmax(ctx, node):
    axis = int(node.attr("axis", -1))
    opn = "softmax" if node.op == "Softmax" else "log_softmax"
    return ctx.sd._op(opn, [ctx.var(node.inputs[0])], {"axis": axis})


@onnx_op("Gelu")
def _gelu(ctx, node):
    return ctx.sd._op("gelu", [ctx.var(node.inputs[0])])


# -- linear algebra ---------------------------------------------------------
@onnx_op("MatMul")
def _matmul(ctx, node):
    return ctx.sd._op("matmul", [ctx.var(node.inputs[0]),
                                 ctx.var(node.inputs[1])])


@onnx_op("Gemm")
def _gemm(ctx, node):
    alpha = node.attr("alpha", 1.0)
    beta = node.attr("beta", 1.0)
    ta, tb = node.attr("transA", 0), node.attr("transB", 0)
    a = ctx.var(node.inputs[0])
    b = ctx.var(node.inputs[1])
    y = ctx.sd._op("matmul", [a, b],
                   {"transpose_a": bool(ta), "transpose_b": bool(tb)})
    if alpha != 1.0:
        y = ctx.sd._op("mul", [y, ctx.sd.constant(
            ctx.unique("gemm_alpha"), np.float32(alpha))])
    if len(node.inputs) > 2 and node.inputs[2]:
        c = ctx.var(node.inputs[2])
        if beta != 1.0:
            c = ctx.sd._op("mul", [c, ctx.sd.constant(
                ctx.unique("gemm_beta"), np.float32(beta))])
        y = ctx.sd._op("add", [y, c])
    return y


# -- shape ops --------------------------------------------------------------
@onnx_op("Reshape")
def _reshape(ctx, node):
    shape = [int(v) for v in
             np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    return ctx.sd._op("reshape", [ctx.var(node.inputs[0])],
                      {"shape": shape})


@onnx_op("Flatten")
def _flatten(ctx, node):
    axis = int(node.attr("axis", 1))
    x = ctx.var(node.inputs[0])
    shape = ctx.shape_of(node.inputs[0])
    if shape is not None and axis <= len(shape):
        lead = int(np.prod(shape[:axis])) if axis else 1
        return ctx.sd._op("reshape", [x], {"shape": [lead, -1]})
    raise NotImplementedError("Flatten with unknown input shape")


@onnx_op("Transpose")
def _transpose(ctx, node):
    perm = node.attr("perm")
    return ctx.sd._op("transpose", [ctx.var(node.inputs[0])],
                      {"axes": [int(p) for p in perm]
                       if perm is not None else None})


@onnx_op("Concat")
def _concat(ctx, node):
    return ctx.sd._op("concat", [ctx.var(i) for i in node.inputs],
                      {"axis": int(node.attr("axis", 0))})


@onnx_op("Squeeze")
def _squeeze(ctx, node):
    axes = node.attr("axes")
    if axes is None and len(node.inputs) > 1:
        axes = [int(v) for v in
                np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    return ctx.sd._op("squeeze", [ctx.var(node.inputs[0])],
                      {"axis": tuple(int(a) for a in axes)
                       if axes is not None else None})


@onnx_op("Unsqueeze")
def _unsqueeze(ctx, node):
    axes = node.attr("axes")
    if axes is None and len(node.inputs) > 1:
        axes = [int(v) for v in
                np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    x = ctx.var(node.inputs[0])
    for ax in sorted(int(a) for a in axes):
        x = ctx.sd._op("expand_dims", [x], {"axis": ax})
    return x


@onnx_op("Gather")
def _gather(ctx, node):
    return ctx.sd._op("gather", [ctx.var(node.inputs[0]),
                                 ctx.var(node.inputs[1])],
                      {"axis": int(node.attr("axis", 0))})


@onnx_op("Slice")
def _slice(ctx, node):
    if len(node.inputs) > 1:       # opset 10+: starts/ends as inputs
        starts = [int(v) for v in
                  np.asarray(ctx.require_static(node, 1)).reshape(-1)]
        ends = [int(v) for v in
                np.asarray(ctx.require_static(node, 2)).reshape(-1)]
        axes = ([int(v) for v in np.asarray(
            ctx.require_static(node, 3)).reshape(-1)]
            if len(node.inputs) > 3 and node.inputs[3]
            else list(range(len(starts))))
        steps = ([int(v) for v in np.asarray(
            ctx.require_static(node, 4)).reshape(-1)]
            if len(node.inputs) > 4 and node.inputs[4]
            else [1] * len(starts))
    else:
        starts = [int(v) for v in node.attr("starts")]
        ends = [int(v) for v in node.attr("ends")]
        axes = [int(v) for v in node.attr("axes",
                                          range(len(starts)))]
        steps = [1] * len(starts)
    shape = ctx.shape_of(node.inputs[0])
    if shape is None:
        raise NotImplementedError("Slice of unknown-shape tensor")
    begin = [0] * len(shape)
    end = list(shape)
    stride = [1] * len(shape)
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        d = shape[ax]
        if st < 0:
            st += d
        if en < 0:
            en += d
        begin[ax] = min(max(st, 0), d)
        end[ax] = min(max(en, 0), d)
        stride[ax] = sp
    return ctx.sd._op("strided_slice", [ctx.var(node.inputs[0])],
                      {"begin": begin, "end": end, "strides": stride})


@onnx_op("Cast")
def _cast(ctx, node):
    from .protobuf import ONNX_DTYPES
    to = ONNX_DTYPES[int(node.attr("to"))]
    return ctx.sd._op("cast", [ctx.var(node.inputs[0])],
                      {"dtype": np.dtype(to).name})


@onnx_op("Shape")
def _shape(ctx, node):
    shape = ctx.shape_of(node.inputs[0])
    if shape is None:
        raise NotImplementedError("Shape of unknown-shape tensor")
    return ctx.sd.constant(ctx.unique(f"{node.outputs[0]}_shape"),
                           np.asarray(shape, np.int64))


@onnx_op("Pad")
def _pad(ctx, node):
    mode = node.attr("mode", b"constant")
    if isinstance(mode, bytes):
        mode = mode.decode()
    if len(node.inputs) > 1:
        pads = [int(v) for v in
                np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    else:
        pads = [int(v) for v in node.attr("pads")]
    n = len(pads) // 2
    pairs = [(pads[i], pads[i + n]) for i in range(n)]
    return ctx.sd._op("pad", [ctx.var(node.inputs[0])],
                      {"paddings": pairs, "mode": mode})


# -- reductions -------------------------------------------------------------
_REDUCE = {"ReduceMean": "reduce_mean", "ReduceSum": "reduce_sum",
           "ReduceMax": "reduce_max", "ReduceMin": "reduce_min",
           "ReduceProd": "reduce_prod"}


def _reduce(ctx, node):
    axes = node.attr("axes")
    if axes is None and len(node.inputs) > 1 and node.inputs[1]:
        axes = [int(v) for v in
                np.asarray(ctx.require_static(node, 1)).reshape(-1)]
    keep = bool(node.attr("keepdims", 1))
    return ctx.sd._op(_REDUCE[node.op], [ctx.var(node.inputs[0])],
                      {"axis": tuple(int(a) for a in axes)
                       if axes is not None else None,
                       "keep_dims": keep})


for _n in _REDUCE:
    ONNX_OP_MAP[_n] = _reduce


# -- conv / pool / norm (NCHW -> NHWC) --------------------------------------
def _nchw_to_nhwc(ctx, v):
    return ctx.sd._op("transpose", [v], {"axes": [0, 2, 3, 1]})


def _nhwc_to_nchw(ctx, v):
    return ctx.sd._op("transpose", [v], {"axes": [0, 3, 1, 2]})


def _conv_padding(node):
    auto = node.attr("auto_pad", b"NOTSET")
    if isinstance(auto, bytes):
        auto = auto.decode()
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        return "SAME"
    if auto == "VALID":
        return "VALID"
    pads = node.attr("pads")
    if not pads:
        return "VALID"
    pads = [int(p) for p in pads]
    n = len(pads) // 2
    return [(pads[i], pads[i + n]) for i in range(n)]


@onnx_op("Conv")
def _conv(ctx, node):
    w_np = ctx.static(node.inputs[1])
    if w_np is None:
        raise NotImplementedError("Conv with non-constant weights")
    group = int(node.attr("group", 1))
    strides = [int(s) for s in node.attr("strides", [1, 1])]
    dil = [int(d) for d in node.attr("dilations", [1, 1])]
    padding = _conv_padding(node)
    x = _nchw_to_nhwc(ctx, ctx.var(node.inputs[0]))
    attrs = {"stride": tuple(strides), "padding": padding,
             "dilation": tuple(dil)}
    cin_total = w_np.shape[1] * group
    if group == 1:
        w = ctx.sd.constant(ctx.unique(f"{node.inputs[1]}_hwio"),
                            np.transpose(w_np, (2, 3, 1, 0)))
        y = ctx.sd._op("conv2d", [x, w], attrs)
    elif group == cin_total and w_np.shape[1] == 1:
        # depthwise: OIHW [C*m, 1, kH, kW] -> HWC(m) [kH, kW, C, m]
        m = w_np.shape[0] // group
        dw = np.transpose(w_np, (2, 3, 0, 1)).reshape(
            w_np.shape[2], w_np.shape[3], group, m)
        w = ctx.sd.constant(ctx.unique(f"{node.inputs[1]}_dw"), dw)
        y = ctx.sd._op("depthwise_conv2d", [x, w], attrs)
    else:
        # grouped conv: per-group conv2d + concat on channels
        outs = []
        cg = w_np.shape[1]
        og = w_np.shape[0] // group
        xin_shape = ctx.shape_of(node.inputs[0])   # NCHW
        if xin_shape is None:
            raise NotImplementedError("grouped Conv without shape")
        n_, c_, h_, w_ = xin_shape
        for g in range(group):
            xs = ctx.sd._op(
                "strided_slice", [x],
                {"begin": [0, 0, 0, g * cg],
                 "end": [n_, h_, w_, (g + 1) * cg],
                 "strides": [1, 1, 1, 1]})
            wg = ctx.sd.constant(
                ctx.unique(f"{node.inputs[1]}_g{g}"),
                np.transpose(w_np[g * og:(g + 1) * og], (2, 3, 1, 0)))
            outs.append(ctx.sd._op("conv2d", [xs, wg], attrs))
        y = ctx.sd._op("concat", outs, {"axis": 3})
    if len(node.inputs) > 2 and node.inputs[2]:
        y = ctx.sd._op("add", [y, ctx.var(node.inputs[2])])
    return _nhwc_to_nchw(ctx, y)


@onnx_op("MaxPool", "AveragePool")
def _pool(ctx, node):
    ks = [int(k) for k in node.attr("kernel_shape")]
    st = [int(s) for s in node.attr("strides", ks)]
    padding = _conv_padding(node)
    x = _nchw_to_nhwc(ctx, ctx.var(node.inputs[0]))
    opn = "max_pool2d" if node.op == "MaxPool" else "avg_pool2d"
    y = ctx.sd._op(opn, [x], {"kernel": tuple(ks),
                              "stride": tuple(st),
                              "padding": padding})
    return _nhwc_to_nchw(ctx, y)


@onnx_op("GlobalAveragePool", "GlobalMaxPool")
def _global_pool(ctx, node):
    opn = ("reduce_mean" if node.op == "GlobalAveragePool"
           else "reduce_max")
    return ctx.sd._op(opn, [ctx.var(node.inputs[0])],
                      {"axis": (2, 3), "keep_dims": True})


@onnx_op("BatchNormalization")
def _batch_norm(ctx, node):
    x = _nchw_to_nhwc(ctx, ctx.var(node.inputs[0]))
    gamma = ctx.var(node.inputs[1])
    beta = ctx.var(node.inputs[2])
    mean = ctx.var(node.inputs[3])
    var = ctx.var(node.inputs[4])
    y = ctx.sd._op("batch_norm", [x, mean, var, gamma, beta],
                   {"epsilon": node.attr("epsilon", 1e-5)})
    return _nhwc_to_nchw(ctx, y)


@onnx_op("Constant")
def _constant(ctx, node):
    for key in ("value", "value_float", "value_int", "value_floats",
                "value_ints"):
        v = node.attr(key)
        if v is not None:
            arr = np.asarray(v)
            if key == "value_int":
                arr = arr.astype(np.int64)
            if key == "value_ints":
                arr = arr.astype(np.int64)
            ctx.set_static(node.outputs[0], arr)
            return None
    raise NotImplementedError("Constant without value attr")


@onnx_op("ConstantOfShape")
def _constant_of_shape(ctx, node):
    shape = [int(v) for v in
             np.asarray(ctx.require_static(node, 0)).reshape(-1)]
    v = node.attr("value")
    fill = np.asarray(v).reshape(-1) if v is not None else \
        np.zeros(1, np.float32)
    ctx.set_static(node.outputs[0],
                   np.full(shape, fill[0], fill.dtype))
    return None
