"""ONNX → SameDiff importer (SURVEY.md S7: `samediff-import-onnx`,
`OnnxFrameworkImporter.runImport` equivalent).

ONNX names TENSORS (every node output has an explicit name and graphs
are serialized in topological order), so the importer is a single
forward pass: initializers become constants, non-initializer graph
inputs become placeholders, each node maps through `ONNX_OP_MAP`, and
graph outputs become SameDiff outputs.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ...autodiff.samediff import SameDiff, SDVariable
from .mappings import ONNX_OP_MAP
from .protobuf import OnnxGraph, OnnxNode, parse_model


class OnnxImporter:
    """One-shot importer for an ONNX inference model."""

    def __init__(self, model, input_shapes: Optional[dict] = None):
        if isinstance(model, (str, os.PathLike)):
            with open(model, "rb") as fh:
                model = fh.read()
        if isinstance(model, (bytes, bytearray)):
            self.graph = parse_model(bytes(model))
        elif isinstance(model, OnnxGraph):
            self.graph = model
        else:
            raise TypeError(type(model))
        self.input_shapes = {k: tuple(v) for k, v in
                             (input_shapes or {}).items()}
        self.sd = SameDiff()
        self.var_map: Dict[str, SDVariable] = {}
        self.statics: Dict[str, np.ndarray] = dict(
            self.graph.initializers)
        self.shapes: Dict[str, Tuple[int, ...]] = {}
        self.avals: Dict[str, jax.ShapeDtypeStruct] = {}
        self.placeholders: List[str] = []
        self._uniq = 0

    # -- ctx API used by mapping rules --------------------------------
    def var(self, name: str) -> SDVariable:
        v = self.var_map.get(name)
        if v is not None:
            return v
        if name in self.statics:
            arr = self.statics[name]
            c = self.sd.constant(self.unique(name), arr)
            self.var_map[name] = c
            self.shapes[name] = tuple(arr.shape)
            return c
        raise KeyError(f"ONNX import: unknown tensor '{name}'")

    def static(self, name: str) -> Optional[np.ndarray]:
        return self.statics.get(name)

    def require_static(self, node: OnnxNode, i: int) -> np.ndarray:
        name = node.inputs[i]
        arr = self.statics.get(name)
        if arr is None:
            raise NotImplementedError(
                f"{node.op} '{node.name}': input {i} ('{name}') must "
                f"be a constant/initializer")
        return arr

    def set_static(self, name: str, arr: np.ndarray):
        self.statics[name] = arr
        self.shapes[name] = tuple(arr.shape)

    def shape_of(self, name: str) -> Optional[Tuple[int, ...]]:
        sh = self.shapes.get(name)
        if sh is not None:
            return sh
        v = self.var_map.get(name)
        if v is not None:
            av = self.avals.get(v.name)
            if av is not None:
                return tuple(av.shape)
        return None

    def unique(self, base: str) -> str:
        self._uniq += 1
        return f"{base}__{self._uniq}"

    # -- shape inference (same machinery as the TF importer) ----------
    def _infer_new_ops(self, start_idx: int):
        """jax.eval_shape every op emitted since start_idx — abstract
        eval only, no FLOPs — so rules downstream can read concrete
        shapes (Flatten/Slice/grouped Conv need them)."""
        from ...autodiff.samediff import get_op
        for node in self.sd.ops[start_idx:]:
            in_avals = []
            ok = True
            for name in node.inputs:
                av = self.avals.get(name)
                if av is None:
                    arr = self.sd._arrays.get(name)
                    if arr is not None:
                        av = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
                        self.avals[name] = av
                    else:
                        ok = False
                        break
                in_avals.append(av)
            if not ok:
                continue
            attrs = dict(node.attrs or {})
            try:
                out = jax.eval_shape(
                    lambda *xs: get_op(node.op_name)(list(xs), attrs),
                    *in_avals)
            except Exception:
                continue
            outs = out if isinstance(out, (list, tuple)) else [out]
            for on, av in zip(node.outputs, outs):
                self.avals[on] = jax.ShapeDtypeStruct(av.shape,
                                                      av.dtype)
                sv = self.sd.vars[on]
                sv.shape = tuple(av.shape)
                sv.dtype = av.dtype

    def run(self, optimize: Optional[bool] = None) -> SameDiff:
        g = self.graph
        init_names = set(g.initializers)
        for name, shape in g.inputs:
            if name in init_names:
                continue
            shape = self.input_shapes.get(name, shape)
            if shape is None or any(d < 0 for d in shape):
                raise ValueError(
                    f"input '{name}' needs a concrete shape; pass "
                    f"input_shapes={{'{name}': (...)}}")
            ph = self.sd.placeholder(name, shape=tuple(shape))
            self.var_map[name] = ph
            self.shapes[name] = tuple(shape)
            self.avals[ph.name] = jax.ShapeDtypeStruct(
                tuple(shape), np.float32)
            self.placeholders.append(name)

        self._import_nodes(g.nodes)

        for out in g.outputs:
            self.var(out)             # materialize if static
        self.sd.outputs = list(g.outputs)
        # post-import GraphOptimizer pipeline (autodiff.passes):
        # canonicalize the exporter's cast/mask/LayerNorm/GELU
        # arithmetic and fuse attention. Default on; kill with
        # DL4J_TPU_GRAPHOPT=0 or optimize=False.
        from deeplearning4j_tpu.autodiff.passes import graphopt_enabled
        if optimize if optimize is not None else graphopt_enabled():
            self.graphopt_counts = self.sd.optimize()
            self.sd.graphopt_counts = self.graphopt_counts
        return self.sd

    def _import_nodes(self, nodes):
        for node in nodes:
            rule = ONNX_OP_MAP.get(node.op)
            if rule is None:
                raise NotImplementedError(
                    f"no ONNX mapping for op '{node.op}' "
                    f"(node '{node.name}')")
            start_idx = len(self.sd.ops)
            result = rule(self, node)
            self._infer_new_ops(start_idx)
            if result is None:        # rule produced statics only
                continue
            outs = (list(result) if isinstance(result, (list, tuple))
                    else [result])
            for i, v in enumerate(outs):
                if i < len(node.outputs) and node.outputs[i]:
                    self.var_map[node.outputs[i]] = v
                    av = self.avals.get(v.name)
                    if av is not None:
                        self.shapes[node.outputs[i]] = tuple(av.shape)

    def subgraph_callable(self, g, arg_names):
        """Wrap a control-flow subgraph (If/Loop body GraphProto) as a
        callable for ``SameDiff.cond/while_loop`` tracing.  ONNX
        subgraphs are LEXICALLY scoped: names not bound by arguments
        or subgraph initializers resolve from THIS importer — the
        child graph captures them (live op inputs)."""
        parent = self

        def fn(*args):
            child_sd = (args[0].sd if args
                        else getattr(fn, "_trace_child_sd",
                                     parent.sd))
            sub = _SubImporter(parent, g, child_sd,
                               dict(zip(arg_names, args)))
            sub._import_nodes(g.nodes)
            return [sub.var(o) for o in g.outputs]

        return fn

    def output(self, placeholders: dict, outputs=None):
        """Run the imported graph: {input_name: array} -> list of
        output arrays, ordered like the ONNX graph outputs."""
        outs = outputs or self.sd.outputs
        ph = {self.var_map[k].name: v for k, v in placeholders.items()}
        res = self.sd.output(ph, [self.var_map[o].name for o in outs])
        return [res[self.var_map[o].name] for o in outs]


class _SubImporter(OnnxImporter):
    """Importer for a control-flow subgraph: emits into the CHILD
    SameDiff the cond/while tracer provides; unresolved names fall
    back to the enclosing importer (lexical scoping)."""

    def __init__(self, parent, g, child_sd, bound):
        self.graph = g
        self.input_shapes = {}
        self.sd = child_sd
        self.var_map = dict(bound)
        self.statics = dict(parent.statics)
        self.statics.update(g.initializers)
        # seed shapes from the subgraph's declared ValueInfos so
        # shape-dependent rules (Flatten/Slice/Conv) work inside
        # bodies
        self.shapes = {name: tuple(shape)
                       for name, shape in g.inputs
                       if shape is not None
                       and all(d is not None and d >= 0
                               for d in shape)}
        self.avals = {}
        self.placeholders = []
        self._uniq = 0
        self._parent = parent

    def var(self, name: str):
        try:
            return super().var(name)
        except KeyError:
            # lexical capture from the enclosing graph: referencing
            # the parent's var inside the child registers a live
            # capture (samediff._import_foreign)
            return self._parent.var(name)

    def shape_of(self, name: str):
        sh = super().shape_of(name)
        if sh is None and name not in self.var_map:
            sh = self._parent.shape_of(name)   # captured tensor
        return sh


def import_onnx(model, input_shapes: Optional[dict] = None,
                optimize: Optional[bool] = None) -> "OnnxImporter":
    """Parse + map an ONNX model; returns the importer (``.sd`` is
    the SameDiff graph, ``.output`` runs it). ``optimize`` controls
    the post-import GraphOptimizer pipeline (None = the
    DL4J_TPU_GRAPHOPT env default, on)."""
    imp = OnnxImporter(model, input_shapes)
    imp.run(optimize=optimize)
    return imp
