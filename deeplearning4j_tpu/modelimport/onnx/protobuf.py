"""ONNX wire-format codec (no `onnx` package dependency).

Decoder mirrors `..tensorflow.protobuf` (shared varint/field
machinery) for the ONNX schema subset an inference importer needs:
ModelProto → GraphProto → NodeProto/TensorProto/AttributeProto/
ValueInfoProto. Field numbers follow onnx.proto3 (onnx/onnx.proto,
IR version 3+).

A minimal ENCODER for the same subset lives here too — it writes
valid ModelProto bytes for graphs we construct (used by the test
fixtures, and usable as a lightweight exporter).

Reference parity: `samediff-import-onnx` (SURVEY.md S7) decodes ONNX
protobuf via the official Java bindings; the wire format is the
contract, not the library.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..tensorflow.protobuf import decode_fields, _packed_floats, \
    _packed_varints, _signed

# onnx TensorProto.DataType
ONNX_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16,
    11: np.float64, 12: np.uint32, 13: np.uint64,
}
NP_TO_ONNX = {np.dtype(v): k for k, v in ONNX_DTYPES.items()}


class OnnxTensor:
    def __init__(self, name: str, array: np.ndarray):
        self.name = name
        self.array = array


def parse_tensor(buf: bytes) -> OnnxTensor:
    f = decode_fields(buf)
    dims = _packed_varints(f.get(1, []))
    dt = int(f[2][0][1]) if 2 in f else 1
    name = f[8][0][1].decode() if 8 in f else ""
    np_dt = ONNX_DTYPES.get(dt)
    if np_dt is None:
        raise NotImplementedError(f"onnx tensor dtype enum {dt}")
    if 9 in f:                                  # raw_data
        arr = np.frombuffer(f[9][0][1], np_dt)
    elif 4 in f:                                # float_data
        arr = np.asarray(_packed_floats(f[4]), np.float32)
    elif 7 in f:                                # int64_data
        arr = np.asarray([_signed(v) for v in _packed_varints(f[7])],
                         np.int64)
    elif 5 in f:                                # int32_data
        arr = np.asarray([_signed(v) for v in _packed_varints(f[5])],
                         np.int32).astype(np_dt)
    elif 10 in f:                               # double_data
        from ..tensorflow.protobuf import _packed_doubles
        arr = np.asarray(_packed_doubles(f[10]), np.float64)
    else:
        arr = np.zeros(0, np_dt)
    return OnnxTensor(name, arr.reshape(dims).astype(np_dt, copy=False))


class OnnxAttr:
    def __init__(self, name: str, kind: int, value):
        self.name = name
        self.kind = kind
        self.value = value


def parse_attribute(buf: bytes) -> OnnxAttr:
    f = decode_fields(buf)
    name = f[1][0][1].decode() if 1 in f else ""
    # AttributeProto.type enum: 1=FLOAT 2=INT 3=STRING 4=TENSOR
    # 5=GRAPH 6=FLOATS 7=INTS 8=STRINGS
    kind = int(f[20][0][1]) if 20 in f else 0
    if 6 in f and kind in (0, 5):               # g: control-flow body
        return OnnxAttr(name, 5, parse_graph(f[6][0][1]))
    if 2 in f and kind in (0, 1):
        raw = f[2][0][1]
        val = (struct.unpack("<f", raw)[0]
               if isinstance(raw, (bytes, bytearray)) else float(raw))
        return OnnxAttr(name, 1, val)
    if 3 in f and kind in (0, 2):
        return OnnxAttr(name, 2, _signed(int(f[3][0][1])))
    if 4 in f and kind in (0, 3):
        return OnnxAttr(name, 3, f[4][0][1])
    if 5 in f and kind in (0, 4):
        return OnnxAttr(name, 4, parse_tensor(f[5][0][1]).array)
    if 7 in f and kind in (0, 6):
        return OnnxAttr(name, 6, _packed_floats(f[7]))
    if 8 in f and kind in (0, 7):
        return OnnxAttr(name, 7,
                        [_signed(v) for v in _packed_varints(f[8])])
    if 9 in f and kind in (0, 8):
        return OnnxAttr(name, 8, [e[1] for e in f[9]])
    return OnnxAttr(name, kind, None)


class OnnxNode:
    def __init__(self, op_type: str, inputs: List[str],
                 outputs: List[str], name: str,
                 attrs: Dict[str, OnnxAttr]):
        self.op = op_type
        self.inputs = inputs
        self.outputs = outputs
        self.name = name
        self.attrs = attrs

    def attr(self, key: str, default=None):
        a = self.attrs.get(key)
        return default if a is None else a.value

    def __repr__(self):
        return (f"OnnxNode({self.op}, in={self.inputs}, "
                f"out={self.outputs})")


def parse_node(buf: bytes) -> OnnxNode:
    f = decode_fields(buf)
    return OnnxNode(
        op_type=f[4][0][1].decode() if 4 in f else "",
        inputs=[e[1].decode() for e in f.get(1, [])],
        outputs=[e[1].decode() for e in f.get(2, [])],
        name=f[3][0][1].decode() if 3 in f else "",
        attrs={a.name: a for a in
               (parse_attribute(e[1]) for e in f.get(5, []))})


def parse_value_info(buf: bytes) -> Tuple[str,
                                          Optional[Tuple[int, ...]],
                                          Optional[type]]:
    """ValueInfoProto -> (name, shape or None, numpy dtype or None).
    Dims with dim_param (symbolic) become -1."""
    f = decode_fields(buf)
    name = f[1][0][1].decode() if 1 in f else ""
    shape = None
    dtype = None
    if 2 in f:                                   # TypeProto
        t = decode_fields(f[2][0][1])
        if 1 in t:                               # tensor_type
            tt = decode_fields(t[1][0][1])
            if 1 in tt:                          # elem_type
                enum = int(tt[1][0][1])
                # unmapped enums keep the raw int so consumers can
                # say "unsupported dtype N" instead of "missing"
                dtype = ONNX_DTYPES.get(enum, enum)
            if 2 in tt:                          # TensorShapeProto
                sh = decode_fields(tt[2][0][1])
                dims = []
                for _, dbuf in sh.get(1, []):    # Dimension
                    d = decode_fields(dbuf)
                    if 1 in d:                   # dim_value
                        dims.append(int(d[1][0][1]))
                    else:
                        dims.append(-1)
                shape = tuple(dims)
    return name, shape, dtype


class OnnxGraph:
    def __init__(self, nodes, initializers, inputs, outputs, name,
                 output_shapes=None, output_dtypes=None):
        self.nodes: List[OnnxNode] = nodes
        self.initializers: Dict[str, np.ndarray] = initializers
        self.inputs: List[Tuple[str, Optional[tuple]]] = inputs
        self.outputs: List[str] = outputs
        #: declared output shapes/dtypes (control-flow bodies: Loop
        #: scan outputs need their element shape + dtype)
        self.output_shapes: Dict[str, Optional[tuple]] = \
            output_shapes or {}
        self.output_dtypes: Dict[str, Optional[type]] = \
            output_dtypes or {}
        self.name = name


def parse_graph(buf: bytes) -> OnnxGraph:
    f = decode_fields(buf)
    nodes = [parse_node(e[1]) for e in f.get(1, [])]
    inits = {}
    for _, tbuf in f.get(5, []):
        t = parse_tensor(tbuf)
        inits[t.name] = t.array
    inputs = [parse_value_info(e[1])[:2] for e in f.get(11, [])]
    out_infos = [parse_value_info(e[1]) for e in f.get(12, [])]
    name = f[2][0][1].decode() if 2 in f else ""
    return OnnxGraph(
        nodes, inits, inputs, [n for n, _, _ in out_infos], name,
        output_shapes={n: sh for n, sh, _ in out_infos},
        output_dtypes={n: dt for n, _, dt in out_infos})


def parse_model(buf: bytes) -> OnnxGraph:
    f = decode_fields(buf)
    if 7 not in f:
        raise ValueError("not an ONNX ModelProto (no graph field)")
    return parse_graph(f[7][0][1])


# ---------------------------------------------------------------------------
# minimal encoder
# ---------------------------------------------------------------------------
def _varint(v: int) -> bytes:
    out = bytearray()
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _int_field(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v)


def encode_tensor(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    out = bytearray()
    for d in arr.shape:
        out += _int_field(1, d)
    out += _int_field(2, NP_TO_ONNX[arr.dtype])
    out += _len_field(8, name.encode())
    out += _len_field(9, arr.tobytes())
    return bytes(out)


class GraphAttr:
    """Wrapper marking an attr value as an encoded subgraph (If/Loop
    bodies) for :func:`encode_attr`."""

    def __init__(self, graph_bytes: bytes):
        self.graph_bytes = graph_bytes


def encode_graph(nodes: Sequence[bytes],
                 initializers: Dict[str, np.ndarray],
                 inputs: Sequence[bytes],
                 outputs: Sequence[bytes],
                 graph_name: str = "sub") -> bytes:
    """Bare GraphProto bytes (control-flow subgraph)."""
    g = bytearray()
    for n in nodes:
        g += _len_field(1, n)
    g += _len_field(2, graph_name.encode())
    for name, arr in initializers.items():
        g += _len_field(5, encode_tensor(name, arr))
    for vi in inputs:
        g += _len_field(11, vi)
    for vi in outputs:
        g += _len_field(12, vi)
    return bytes(g)


def encode_attr(name: str, value) -> bytes:
    out = bytearray()
    out += _len_field(1, name.encode())
    if isinstance(value, GraphAttr):
        out += _len_field(6, value.graph_bytes)
        out += _int_field(20, 5)
        return bytes(out)
    if isinstance(value, float):
        out += _tag(2, 5) + struct.pack("<f", value)
        out += _int_field(20, 1)
    elif isinstance(value, (bool, int, np.integer)):
        out += _tag(3, 0) + _varint(int(value))
        out += _int_field(20, 2)
    elif isinstance(value, (bytes, str)):
        v = value.encode() if isinstance(value, str) else value
        out += _len_field(4, v)
        out += _int_field(20, 3)
    elif isinstance(value, np.ndarray):
        out += _len_field(5, encode_tensor("", value))
        out += _int_field(20, 4)
    elif isinstance(value, (list, tuple)) and value and \
            isinstance(value[0], float):
        for v in value:
            out += _tag(7, 5) + struct.pack("<f", v)
        out += _int_field(20, 6)
    elif isinstance(value, (list, tuple)) and value and \
            isinstance(value[0], (bytes, str)):
        for v in value:
            out += _len_field(9, v.encode() if isinstance(v, str)
                              else v)
        out += _int_field(20, 8)
    elif isinstance(value, (list, tuple)):
        for v in value:
            out += _tag(8, 0) + _varint(int(v) & ((1 << 64) - 1))
        out += _int_field(20, 7)
    else:
        raise TypeError(f"attr {name}: {type(value)}")
    return bytes(out)


def encode_node(op_type: str, inputs: Sequence[str],
                outputs: Sequence[str], name: str = "",
                **attrs) -> bytes:
    out = bytearray()
    for i in inputs:
        out += _len_field(1, i.encode())
    for o in outputs:
        out += _len_field(2, o.encode())
    if name:
        out += _len_field(3, name.encode())
    out += _len_field(4, op_type.encode())
    for k, v in attrs.items():
        out += _len_field(5, encode_attr(k, v))
    return bytes(out)


def encode_value_info(name: str, shape: Sequence[int],
                      dtype=np.float32) -> bytes:
    # a negative dim encodes as a SYMBOLIC dim_param (what real
    # exporters emit for unknown dims; parse_value_info maps it to
    # -1).  One symbol per position — a shared dim_param would assert
    # the unknown dims are EQUAL.
    dims = b"".join(
        _len_field(1, (_len_field(2, f"N{i}".encode()) if d < 0
                       else _int_field(1, d)))
        for i, d in enumerate(shape))
    tshape = _len_field(2, dims)
    tensor_type = _int_field(1, NP_TO_ONNX[np.dtype(dtype)]) + tshape
    type_proto = _len_field(1, tensor_type)
    return _len_field(1, name.encode()) + _len_field(2, type_proto)


def encode_model(nodes: Sequence[bytes],
                 initializers: Dict[str, np.ndarray],
                 inputs: Sequence[bytes],
                 outputs: Sequence[bytes],
                 graph_name: str = "graph") -> bytes:
    model = _int_field(1, 8)                      # ir_version
    model += _len_field(7, encode_graph(nodes, initializers, inputs,
                                        outputs, graph_name))
    # opset_import: domain "" version 13
    model += _len_field(8, _len_field(1, b"") + _int_field(2, 13))
    return bytes(model)
