"""ONNX model import (SURVEY.md S7 — `samediff-import-onnx` parity).

Wire-format protobuf decode (no `onnx` package needed), an
`OpMappingRegistry`-style rule table, and a one-pass importer into
SameDiff. A minimal encoder lives in `.protobuf` for building ONNX
bytes (tests, lightweight export).
"""
from .importer import OnnxImporter, import_onnx
from .protobuf import parse_model

__all__ = ["OnnxImporter", "import_onnx", "parse_model"]
