"""Model import: TF GraphDef (S6/S7) and Keras (D14) front-doors."""
