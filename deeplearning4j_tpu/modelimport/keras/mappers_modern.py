"""Keras layer mappers, modern batch (SURVEY.md D14; round-2 verdict
ask #5): ConvLSTM2D, LayerNormalization, MultiHeadAttention,
Conv1DTranspose/Conv3DTranspose, 3D global pooling, and the
custom-layer registry seam.

Weight-layout notes (verified against live Keras in
tests/test_keras_import_modern.py):
- ConvLSTM2D cell kernels are (kh, kw, C, 4F) with keras gate order
  [i, f, c, o]; ours is [i, f, o, g], reordered on the last axis.
- MultiHeadAttention stores einsum sublayers query/key/value
  (d, h, dh) + (h, dh) bias and output (h, dh, d_out) + (d_out,);
  they flatten to this framework's Wq/Wk/Wv [d, h*dh], Wo [h*dh,
  d_out] layout.
- Conv1DTranspose kernel is (k, out, in), gradient-of-conv oriented:
  transposed to (k, in, out) and spatially mirrored for our
  un-mirrored ``conv_transpose``.

Custom-layer seam: :func:`register_keras_layer_mapper` — the public
analogue of the reference's ``KerasLayer.registerCustomLayer`` — lets
users register a mapper for their own layer class before import.
"""
from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.modelimport.keras.importer import (
    Emit, InvalidKerasConfigurationException, KERAS_LAYER_MAP,
    _activation, _conv_mode,
    _lstm_reorder as _convlstm_reorder,   # same [i,f,c,o]→[i,f,o,g]
    _pair, keras_layer)
from deeplearning4j_tpu.nn.conf.layers import (GlobalPoolingLayer,
                                               PoolingType)
from deeplearning4j_tpu.nn.conf.layers_attention import \
    SelfAttentionLayer
from deeplearning4j_tpu.nn.conf.layers_conv_1d3d import (Deconvolution1D,
                                                         Deconvolution3D)
from deeplearning4j_tpu.nn.conf.layers_misc import LayerNormalization
from deeplearning4j_tpu.nn.conf.layers_recurrent import ConvLSTM2D


def register_keras_layer_mapper(class_name: str, mapper=None):
    """Register a custom Keras layer mapper (reference:
    ``KerasLayer.registerCustomLayer`` /
    ``KerasLayerUtils.getCustomLayer`` — SURVEY.md D14).

    ``mapper(cfg, bag) -> [Emit(...)]`` receives the layer's config
    dict and its :class:`WeightBag`.  Usable directly or as a
    decorator::

        @register_keras_layer_mapper("MyLayer")
        def map_my_layer(cfg, bag):
            return [Emit(layer=..., params={...})]
    """
    if mapper is None:
        return keras_layer(class_name)
    KERAS_LAYER_MAP[class_name] = mapper
    return mapper


def _reject_output_padding(cfg):
    op = cfg.get("output_padding")
    if op is not None and any(
            int(p) for p in (op if isinstance(op, (list, tuple))
                             else [op])):
        raise InvalidKerasConfigurationException(
            f"{cfg['__class__']} output_padding unsupported")


@keras_layer("ConvLSTM2D")
def _map_convlstm2d(cfg, bag):
    if cfg.get("data_format", "channels_last") == "channels_first":
        raise InvalidKerasConfigurationException(
            "channels_first ConvLSTM2D unsupported (NHWC-native)")
    if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
        raise InvalidKerasConfigurationException(
            "ConvLSTM2D dilation_rate != 1 unsupported")
    F = int(cfg["filters"])
    layer = ConvLSTM2D(
        n_out=F,
        kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=_conv_mode(cfg),
        activation=_activation(cfg),
        gate_activation=_activation(cfg, "recurrent_activation"),
        has_bias=bool(cfg.get("use_bias", True)),
        return_sequences=bool(cfg.get("return_sequences", False)))
    params = {"W": _convlstm_reorder(
                  np.asarray(bag.get(0, "kernel")), F),
              "RW": _convlstm_reorder(
                  np.asarray(bag.get(1, "recurrent_kernel")), F)}
    if layer.has_bias:
        params["b"] = _convlstm_reorder(
            np.asarray(bag.get(2, "bias")), F)
    return [Emit(layer=layer, params=params)]


def _scale_center_params(cfg, bag):
    """Shared gamma/beta extraction for the norm-layer family (keras
    weight order: gamma first when present, then beta)."""
    scale = bool(cfg.get("scale", True))
    center = bool(cfg.get("center", True))
    params = {}
    i = 0
    if scale:
        params["gamma"] = bag.get(i, "gamma")
        i += 1
    if center:
        params["beta"] = bag.get(i, "beta")
    return scale, center, params


@keras_layer("LayerNormalization")
def _map_layer_norm(cfg, bag):
    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        axis = axis[0] if len(axis) == 1 else axis
    if axis != -1:
        # a positive axis might equal rank-1, but the rank is unknown
        # at mapping time — only the unambiguous form imports
        raise InvalidKerasConfigurationException(
            f"LayerNormalization axis={axis} unsupported (axis=-1 "
            f"only — channels are the TPU lane dim)")
    scale, center, params = _scale_center_params(cfg, bag)
    layer = LayerNormalization(eps=float(cfg.get("epsilon", 1e-3)),
                               scale=scale, center=center)
    return [Emit(layer=layer, params=params)]


@keras_layer("UnitNormalization")
def _map_unit_norm(cfg, bag):
    from deeplearning4j_tpu.nn.conf.layers_misc import UnitNormLayer
    axis = cfg.get("axis", -1)
    if axis not in (-1, [-1], (-1,)):
        raise InvalidKerasConfigurationException(
            f"UnitNormalization axis={axis} unsupported (last only)")
    return [Emit(layer=UnitNormLayer())]


@keras_layer("MultiHeadAttention")
def _map_mha(cfg, bag):
    """Self-attention form (query == key == value — the importer
    collapses the duplicate inbound edges).  Cross-attention needs a
    two-input vertex and is rejected loudly at the graph builder."""
    h = int(cfg["num_heads"])
    dk = int(cfg["key_dim"])
    vdim = cfg.get("value_dim")
    if vdim is not None and int(vdim) != dk:
        # SelfAttentionLayer has ONE head_size; importing value_dim !=
        # key_dim would leave the layer config inconsistent with the
        # loaded Wv/Wo shapes (re-init or round-trip would mismatch)
        raise InvalidKerasConfigurationException(
            f"MultiHeadAttention value_dim={vdim} != key_dim={dk} "
            f"unsupported (uniform head size only)")
    use_bias = bool(cfg.get("use_bias", True))
    att_axes = cfg.get("attention_axes")
    if att_axes not in (None, [1], (1,), 1):
        raise InvalidKerasConfigurationException(
            f"MultiHeadAttention attention_axes={att_axes} "
            f"unsupported (sequence axis only)")
    qb, kb, vb, ob = (cfg.get(f"__{s}_bag__") for s in
                      ("query_dense", "key_dense", "value_dense",
                       "output_dense"))
    if qb is None or kb is None or vb is None or ob is None:
        raise InvalidKerasConfigurationException(
            "MultiHeadAttention weights not found — save the model in "
            ".keras (v3) format")

    def flat_kernel(b):
        k = np.asarray(b.get(0, "kernel"))      # (d, h, dh)
        return k.reshape(k.shape[0], -1)

    wo = np.asarray(ob.get(0, "kernel"))        # (h, dv, d_out)
    n_out = wo.shape[-1]
    layer = SelfAttentionLayer(n_heads=h, head_size=dk,
                               has_bias=use_bias, n_out=n_out)
    params = {"Wq": flat_kernel(qb), "Wk": flat_kernel(kb),
              "Wv": flat_kernel(vb),
              "Wo": wo.reshape(-1, n_out)}
    if use_bias:
        params.update({
            "bq": np.asarray(qb.get(1, "bias")).reshape(-1),
            "bk": np.asarray(kb.get(1, "bias")).reshape(-1),
            "bv": np.asarray(vb.get(1, "bias")).reshape(-1),
            "bo": np.asarray(ob.get(1, "bias")).reshape(-1)})
    return [Emit(layer=layer, params=params)]


@keras_layer("Conv1DTranspose")
def _map_conv1d_transpose(cfg, bag):
    if cfg.get("data_format", "channels_last") == "channels_first":
        raise InvalidKerasConfigurationException(
            "channels_first Conv1DTranspose unsupported")
    dil = cfg.get("dilation_rate", 1)
    if (dil[0] if isinstance(dil, (list, tuple)) else dil) != 1:
        raise InvalidKerasConfigurationException(
            "Conv1DTranspose dilation_rate != 1 unsupported")
    _reject_output_padding(cfg)
    layer = Deconvolution1D(
        n_out=int(cfg["filters"]),
        kernel_size=cfg["kernel_size"],
        stride=cfg.get("strides", 1),
        convolution_mode=_conv_mode(cfg),
        activation=_activation(cfg),
        has_bias=bool(cfg.get("use_bias", True)))
    # keras kernel (k, out, in) → (k, in, out), spatially mirrored
    k = np.asarray(bag.get(0, "kernel"))
    params = {"W": np.ascontiguousarray(
        np.transpose(k, (0, 2, 1))[::-1])}
    if layer.has_bias:
        params["b"] = bag.get(1, "bias")
    return [Emit(layer=layer, params=params)]


@keras_layer("Conv3DTranspose")
def _map_conv3d_transpose(cfg, bag):
    if cfg.get("data_format", "channels_last") == "channels_first":
        raise InvalidKerasConfigurationException(
            "channels_first Conv3DTranspose unsupported")
    ks = tuple(int(k) for k in cfg["kernel_size"])
    st = cfg.get("strides", (1, 1, 1))
    st = tuple(int(s) for s in (st if isinstance(st, (list, tuple))
                                else (st,) * 3))
    _reject_output_padding(cfg)
    layer = Deconvolution3D(
        n_out=int(cfg["filters"]), kernel_size=ks, stride=st,
        convolution_mode=_conv_mode(cfg),
        activation=_activation(cfg),
        has_bias=bool(cfg.get("use_bias", True)))
    # keras kernel (kd, kh, kw, out, in) → (kd, kh, kw, in, out),
    # mirrored on every spatial axis
    k = np.asarray(bag.get(0, "kernel"))
    params = {"W": np.ascontiguousarray(
        np.transpose(k, (0, 1, 2, 4, 3))[::-1, ::-1, ::-1])}
    if layer.has_bias:
        params["b"] = bag.get(1, "bias")
    return [Emit(layer=layer, params=params)]


@keras_layer("GlobalMaxPooling3D", "GlobalAveragePooling3D")
def _map_global_pool_3d(cfg, bag):
    kind = (PoolingType.MAX if "Max" in cfg["__class__"]
            else PoolingType.AVG)
    return [Emit(layer=GlobalPoolingLayer(pooling_type=kind))]


@keras_layer("GroupNormalization")
def _map_group_norm(cfg, bag):
    from deeplearning4j_tpu.nn.conf.layers_misc import \
        GroupNormalization
    axis = cfg.get("axis", -1)
    if axis != -1:
        raise InvalidKerasConfigurationException(
            f"GroupNormalization axis={axis} unsupported (channels "
            f"last only)")
    scale, center, params = _scale_center_params(cfg, bag)
    layer = GroupNormalization(groups=int(cfg.get("groups", 32)),
                               eps=float(cfg.get("epsilon", 1e-3)),
                               scale=scale, center=center)
    return [Emit(layer=layer, params=params)]


# -- preprocessing layers (common heads of exported vision models) ----------
@keras_layer("Rescaling")
def _map_rescaling(cfg, bag):
    from deeplearning4j_tpu.nn.conf.layers_misc import ScaleOffsetLayer

    def coef(v, dflt):
        if v is None:
            return dflt
        if isinstance(v, (int, float)):
            return float(v)
        return [float(e) for e in np.asarray(v).reshape(-1)]

    return [Emit(layer=ScaleOffsetLayer(
        scale=coef(cfg.get("scale"), 1.0),
        offset=coef(cfg.get("offset"), 0.0)))]


@keras_layer("Resizing")
def _map_resizing(cfg, bag):
    interp = cfg.get("interpolation", "bilinear")
    if interp not in ("bilinear", "nearest"):
        raise InvalidKerasConfigurationException(
            f"Resizing interpolation={interp} unsupported")
    if cfg.get("crop_to_aspect_ratio") or cfg.get(
            "pad_to_aspect_ratio"):
        raise InvalidKerasConfigurationException(
            "Resizing with aspect-ratio crop/pad unsupported")
    from deeplearning4j_tpu.nn.conf.layers_misc import ResizingLayer
    return [Emit(layer=ResizingLayer(
        height=int(cfg["height"]), width=int(cfg["width"]),
        interpolation=interp))]


@keras_layer("ActivityRegularization")
def _map_activity_regularization(cfg, bag):
    # contributes only a training-loss penalty; inference no-op
    return [Emit(skip=True)]


@keras_layer("RandomFlip", "RandomRotation", "RandomZoom",
             "RandomTranslation", "RandomContrast", "RandomBrightness")
def _map_random_augment(cfg, bag):
    # shape-preserving augmentation layers are inference no-ops
    # (keras applies them only under training=True).  RandomCrop is
    # NOT here: it center-crops at inference, changing shapes —
    # unmapped, so it fails loudly.
    return [Emit(skip=True)]
