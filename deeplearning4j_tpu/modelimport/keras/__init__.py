"""Keras HDF5/.keras import (SURVEY.md D14)."""
from deeplearning4j_tpu.modelimport.keras.importer import (
    InvalidKerasConfigurationException, KerasModelImport)
from deeplearning4j_tpu.modelimport.keras import mappers_extra  # noqa: F401

__all__ = ["KerasModelImport", "InvalidKerasConfigurationException"]
