"""Keras HDF5/.keras import (SURVEY.md D14)."""
from deeplearning4j_tpu.modelimport.keras.importer import (
    InvalidKerasConfigurationException, KerasModelImport)

__all__ = ["KerasModelImport", "InvalidKerasConfigurationException"]
