"""Keras HDF5/.keras import (SURVEY.md D14)."""
from deeplearning4j_tpu.modelimport.keras.importer import (
    InvalidKerasConfigurationException, KerasModelImport)
from deeplearning4j_tpu.modelimport.keras import mappers_extra  # noqa: F401
from deeplearning4j_tpu.modelimport.keras import mappers_modern  # noqa: F401
from deeplearning4j_tpu.modelimport.keras.mappers_modern import \
    register_keras_layer_mapper

__all__ = ["KerasModelImport", "InvalidKerasConfigurationException",
           "register_keras_layer_mapper"]
