"""Keras layer mappers beyond the core set (SURVEY.md D14 — the
reference's ~60 `KerasLayer` subclasses; this module covers the conv
1D/3D/transposed/separable family, pooling 1D/3D, shape layers
(crop/pad/upsample/repeat), PReLU, and the TimeDistributed and
Bidirectional wrappers).

Weight-layout notes (verified against live Keras in
tests/test_keras_import_extra.py):
- Conv1D kernel (k, in, out) and Conv3D (kd, kh, kw, in, out) match
  this framework's layouts directly.
- Conv2DTranspose kernel is (kh, kw, OUT, IN); jax
  ``conv_transpose(transpose_kernel=True)`` consumes exactly that
  gradient-of-conv orientation, so the Deconvolution2D forward flips it
  into our (kh, kw, in, out) with a spatial mirror.
- SeparableConv2D splits into depthwise (kh, kw, in, mult) +
  pointwise (1, 1, in*mult, out) — our SeparableConvolution2D layout.
"""
from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.modelimport.keras.importer import (
    Emit, InvalidKerasConfigurationException, _activation, _conv_mode,
    _pair, keras_layer)
from deeplearning4j_tpu.nn.conf.layers import PoolingType
from deeplearning4j_tpu.nn.conf.layers_conv_1d3d import (
    Convolution1DLayer, Convolution3D, Subsampling1DLayer,
    Subsampling3DLayer)
from deeplearning4j_tpu.nn.conf.layers_conv_extra import (
    Deconvolution2D, DepthwiseConvolution2D, SeparableConvolution2D,
    Upsampling2D)
from deeplearning4j_tpu.nn.conf.layers_misc import PReLULayer
from deeplearning4j_tpu.nn.conf.layers_recurrent import (
    Bidirectional, BidirectionalMode)
from deeplearning4j_tpu.nn.conf.layers_shape import (
    Cropping1D, Cropping2D, Cropping3D, RepeatVector, TimeDistributed,
    Upsampling1D, Upsampling3D, ZeroPadding1DLayer, ZeroPadding3DLayer,
    ZeroPaddingLayer)


from deeplearning4j_tpu.nn.conf.layers_conv_1d3d import _triple  # noqa: E402


def _check_channels_last(cfg):
    if cfg.get("data_format", "channels_last") == "channels_first":
        raise InvalidKerasConfigurationException(
            f"channels_first {cfg['__class__']} unsupported "
            f"(NHWC-native framework)")


@keras_layer("Conv1D")
def _map_conv1d(cfg, bag):
    _check_channels_last(cfg)
    layer = Convolution1DLayer(
        n_out=int(cfg["filters"]),
        kernel_size=int(_first(cfg["kernel_size"])),
        stride=int(_first(cfg.get("strides", 1))),
        dilation=int(_first(cfg.get("dilation_rate", 1))),
        causal=cfg.get("padding") == "causal",
        convolution_mode=_conv_mode(cfg),
        activation=_activation(cfg),
        has_bias=bool(cfg.get("use_bias", True)))
    params = {"W": bag.get(0, "kernel")}
    if layer.has_bias:
        params["b"] = bag.get(1, "bias")
    return [Emit(layer=layer, params=params)]


@keras_layer("Conv3D")
def _map_conv3d(cfg, bag):
    _check_channels_last(cfg)
    layer = Convolution3D(
        n_out=int(cfg["filters"]),
        kernel_size=_triple(cfg["kernel_size"]),
        stride=_triple(cfg.get("strides", 1)),
        dilation=_triple(cfg.get("dilation_rate", 1)),
        convolution_mode=_conv_mode(cfg),
        activation=_activation(cfg),
        has_bias=bool(cfg.get("use_bias", True)))
    params = {"W": bag.get(0, "kernel")}
    if layer.has_bias:
        params["b"] = bag.get(1, "bias")
    return [Emit(layer=layer, params=params)]


@keras_layer("Conv2DTranspose")
def _map_conv2d_transpose(cfg, bag):
    _check_channels_last(cfg)
    if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
        raise InvalidKerasConfigurationException(
            "Conv2DTranspose with dilation_rate != 1 unsupported")
    layer = Deconvolution2D(
        n_out=int(cfg["filters"]),
        kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=_conv_mode(cfg),
        activation=_activation(cfg),
        has_bias=bool(cfg.get("use_bias", True)))
    # keras kernel (kh, kw, out, in) built for gradient-of-conv; our
    # conv_transpose(transpose_kernel=False, HWIO) needs (kh, kw, in,
    # out) mirrored spatially
    k = np.asarray(bag.get(0, "kernel"))
    w = np.transpose(k, (0, 1, 3, 2))[::-1, ::-1]
    params = {"W": w}
    if layer.has_bias:
        params["b"] = bag.get(1, "bias")
    return [Emit(layer=layer, params=params)]


@keras_layer("SeparableConv2D")
def _map_separable_conv2d(cfg, bag):
    _check_channels_last(cfg)
    layer = SeparableConvolution2D(
        n_out=int(cfg["filters"]),
        kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        dilation=_pair(cfg.get("dilation_rate", 1)),
        depth_multiplier=int(cfg.get("depth_multiplier", 1)),
        convolution_mode=_conv_mode(cfg),
        activation=_activation(cfg),
        has_bias=bool(cfg.get("use_bias", True)))
    params = {"dW": bag.get(0, "depthwise_kernel"),
              "pW": bag.get(1, "pointwise_kernel")}
    if layer.has_bias:
        params["b"] = bag.get(2, "bias")
    return [Emit(layer=layer, params=params)]


@keras_layer("DepthwiseConv2D")
def _map_depthwise_conv2d(cfg, bag):
    _check_channels_last(cfg)
    layer = DepthwiseConvolution2D(
        kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        dilation=_pair(cfg.get("dilation_rate", 1)),
        depth_multiplier=int(cfg.get("depth_multiplier", 1)),
        convolution_mode=_conv_mode(cfg),
        activation=_activation(cfg),
        has_bias=bool(cfg.get("use_bias", True)))
    params = {"dW": bag.get(0, "depthwise_kernel")}
    if layer.has_bias:
        params["b"] = bag.get(1, "bias")
    return [Emit(layer=layer, params=params)]


def _first(v):
    return v[0] if isinstance(v, (list, tuple)) else v


@keras_layer("MaxPooling1D", "AveragePooling1D")
def _map_pool1d(cfg, bag):
    kind = (PoolingType.MAX if "Max" in cfg["__class__"]
            else PoolingType.AVG)
    pool = int(_first(cfg.get("pool_size", 2)))
    strides = cfg.get("strides")
    layer = Subsampling1DLayer(
        pooling_type=kind, kernel_size=pool,
        stride=int(_first(strides)) if strides is not None else pool,
        convolution_mode=_conv_mode(cfg))
    return [Emit(layer=layer)]


@keras_layer("MaxPooling3D", "AveragePooling3D")
def _map_pool3d(cfg, bag):
    kind = (PoolingType.MAX if "Max" in cfg["__class__"]
            else PoolingType.AVG)
    pool = _triple(cfg.get("pool_size", 2))
    strides = cfg.get("strides")
    layer = Subsampling3DLayer(
        pooling_type=kind, kernel_size=pool,
        stride=_triple(strides) if strides is not None else pool,
        convolution_mode=_conv_mode(cfg))
    return [Emit(layer=layer)]


@keras_layer("UpSampling1D")
def _map_upsample1d(cfg, bag):
    return [Emit(layer=Upsampling1D(size=int(cfg.get("size", 2))))]


@keras_layer("UpSampling2D")
def _map_upsample2d(cfg, bag):
    if cfg.get("interpolation", "nearest") != "nearest":
        raise InvalidKerasConfigurationException(
            "UpSampling2D: only nearest interpolation supported")
    return [Emit(layer=Upsampling2D(size=_pair(cfg.get("size", 2))))]


@keras_layer("UpSampling3D")
def _map_upsample3d(cfg, bag):
    return [Emit(layer=Upsampling3D(size=_triple(cfg.get("size", 2))))]


@keras_layer("Cropping1D")
def _map_cropping1d(cfg, bag):
    return [Emit(layer=Cropping1D(cropping=_pair(cfg["cropping"])))]


@keras_layer("Cropping2D")
def _map_cropping2d(cfg, bag):
    c = cfg["cropping"]
    if isinstance(c, int):
        tb = lr = (c, c)
    else:
        tb, lr = _pair(c[0]), _pair(c[1])
    return [Emit(layer=Cropping2D(crop_top_bottom=tb,
                                  crop_left_right=lr))]


@keras_layer("Cropping3D")
def _map_cropping3d(cfg, bag):
    c = cfg["cropping"]
    return [Emit(layer=Cropping3D(crop_depth=_pair(c[0]),
                                  crop_height=_pair(c[1]),
                                  crop_width=_pair(c[2])))]


@keras_layer("ZeroPadding1D")
def _map_zeropad1d(cfg, bag):
    return [Emit(layer=ZeroPadding1DLayer(
        padding=_pair(cfg["padding"])))]


@keras_layer("ZeroPadding2D")
def _map_zeropad2d(cfg, bag):
    p = cfg["padding"]
    if isinstance(p, int):
        tb = lr = (p, p)
    else:
        tb, lr = _pair(p[0]), _pair(p[1])
    return [Emit(layer=ZeroPaddingLayer(pad_top_bottom=tb,
                                        pad_left_right=lr))]


@keras_layer("ZeroPadding3D")
def _map_zeropad3d(cfg, bag):
    p = cfg["padding"]
    return [Emit(layer=ZeroPadding3DLayer(pad_depth=_pair(p[0]),
                                          pad_height=_pair(p[1]),
                                          pad_width=_pair(p[2])))]


@keras_layer("RepeatVector")
def _map_repeat_vector(cfg, bag):
    return [Emit(layer=RepeatVector(repetition_factor=int(cfg["n"])))]


@keras_layer("PReLU")
def _map_prelu(cfg, bag):
    shared = cfg.get("shared_axes")
    layer = PReLULayer(shared_axes=tuple(shared) if shared else None)
    return [Emit(layer=layer, params={"alpha": bag.get(0, "alpha")})]


@keras_layer("TimeDistributed")
def _map_time_distributed(cfg, bag):
    from deeplearning4j_tpu.modelimport.keras.importer import \
        KERAS_LAYER_MAP
    inner_cfg = dict(cfg["layer"]["config"])
    inner_cls = cfg["layer"]["class_name"]
    inner_cfg["__class__"] = inner_cls
    if inner_cls not in KERAS_LAYER_MAP:
        raise InvalidKerasConfigurationException(
            f"TimeDistributed: no mapper for inner layer {inner_cls}")
    inner_bag = cfg.get("__layer_bag__")
    if inner_bag is not None and inner_bag.ordered:
        bag = inner_bag
    inner = KERAS_LAYER_MAP[inner_cls](inner_cfg, bag)
    if len(inner) != 1 or inner[0].layer is None:
        raise InvalidKerasConfigurationException(
            "TimeDistributed: inner layer must map to one layer")
    return [Emit(layer=TimeDistributed(underlying=inner[0].layer),
                 params=inner[0].params)]


@keras_layer("Bidirectional")
def _map_bidirectional(cfg, bag):
    from deeplearning4j_tpu.modelimport.keras.importer import \
        KERAS_LAYER_MAP
    inner_cls = cfg["layer"]["class_name"]
    inner_cfg = dict(cfg["layer"]["config"])
    inner_cfg["__class__"] = inner_cls
    if not inner_cfg.get("return_sequences", False):
        # keras return_sequences=False merges fwd's LAST step with
        # bwd's last PROCESSED step (original t=0); position-based
        # LastTimeStep extraction cannot express that — reject rather
        # than import wrong semantics
        raise InvalidKerasConfigurationException(
            "Bidirectional with return_sequences=False unsupported "
            "(keras merges fwd[T-1] with bwd[0])")
    mode = {"concat": BidirectionalMode.CONCAT,
            "sum": BidirectionalMode.ADD,
            "ave": BidirectionalMode.AVERAGE,
            "mul": BidirectionalMode.MUL}.get(
                cfg.get("merge_mode", "concat"))
    if mode is None:
        raise InvalidKerasConfigurationException(
            f"Bidirectional merge_mode {cfg.get('merge_mode')}")
    fwd_bag = cfg.get("__forward_layer_bag__")
    bwd_bag = cfg.get("__backward_layer_bag__")
    if fwd_bag is None or bwd_bag is None:
        raise InvalidKerasConfigurationException(
            "Bidirectional: forward/backward weights not found "
            "(use the .keras format)")
    fwd = KERAS_LAYER_MAP[inner_cls](dict(inner_cfg), fwd_bag)
    bwd = KERAS_LAYER_MAP[inner_cls](dict(inner_cfg), bwd_bag)
    layer = Bidirectional(fwd=fwd[0].layer, mode=mode)
    return [Emit(layer=layer, params={"fwd": fwd[0].params,
                                      "bwd": bwd[0].params})]


@keras_layer("GaussianNoise", "GaussianDropout", "AlphaDropout",
             "SpatialDropout1D", "SpatialDropout2D", "SpatialDropout3D")
def _map_noise_layers(cfg, bag):
    """Training-only noise layers -> DropoutLayer with the matching
    IDropout variant (identity at inference, same as keras)."""
    from deeplearning4j_tpu.nn.conf.dropout import (
        AlphaDropout, GaussianDropout, GaussianNoise, SpatialDropout)
    from deeplearning4j_tpu.nn.conf.layers import DropoutLayer
    cls = cfg["__class__"]
    if cls == "GaussianNoise":
        d = GaussianNoise(stddev=float(cfg.get("stddev", 0.1)))
    elif cls == "GaussianDropout":
        d = GaussianDropout(rate=float(cfg.get("rate", 0.1)))
    elif cls == "AlphaDropout":
        d = AlphaDropout(p=1.0 - float(cfg.get("rate", 0.05)))
    else:
        d = SpatialDropout(p=1.0 - float(cfg.get("rate", 0.5)))
    return [Emit(layer=DropoutLayer(dropout=d))]
