"""MNIST dataset iterator.

Reference parity: ``org.deeplearning4j.datasets.iterator.impl.
MnistDataSetIterator`` (SURVEY.md D13). The reference downloads IDX files
to ``~/.deeplearning4j``; this container has zero network egress, so the
loader resolves, in order:

1. IDX files under ``$DL4J_TPU_DATA_DIR`` or ``~/.deeplearning4j/mnist``
   (``train-images-idx3-ubyte`` etc., optionally ``.gz``);
2. a keras-style ``mnist.npz`` in the same directories;
3. a deterministic **synthetic MNIST surrogate** (seeded class-conditional
   patterns at 28x28, same shapes/dtypes/split sizes) so every pipeline,
   test, and benchmark runs without the real data. A warning is logged.

Features are flat [batch, 784] float32 in [0, 1] — matching the
reference's default (flattened, /255) — labels one-hot [batch, 10].
"""
from __future__ import annotations

import gzip
import logging
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

log = logging.getLogger("deeplearning4j_tpu")

_SEARCH_DIRS = [
    os.environ.get("DL4J_TPU_DATA_DIR", ""),
    str(Path.home() / ".deeplearning4j" / "mnist"),
    str(Path.home() / ".keras" / "datasets"),
]


def _read_idx(path: Path) -> np.ndarray:
    op = gzip.open if path.suffix == ".gz" else open
    with op(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)


def _find(name: str) -> Optional[Path]:
    for d in _SEARCH_DIRS:
        if not d:
            continue
        for cand in (Path(d) / name, Path(d) / (name + ".gz")):
            if cand.exists():
                return cand
    return None


def _load_real(train: bool) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    prefix = "train" if train else "t10k"
    imgs = _find(f"{prefix}-images-idx3-ubyte")
    lbls = _find(f"{prefix}-labels-idx1-ubyte")
    if imgs is not None and lbls is not None:
        x = _read_idx(imgs).astype(np.float32) / 255.0
        y = _read_idx(lbls)
        return x.reshape(x.shape[0], -1), y
    npz = _find("mnist.npz")
    if npz is not None:
        with np.load(npz) as z:
            if train:
                x, y = z["x_train"], z["y_train"]
            else:
                x, y = z["x_test"], z["y_test"]
        return (x.astype(np.float32) / 255.0).reshape(x.shape[0], -1), y
    return None


_warned = False


def synthetic_mnist(n: int, train: bool, seed: int = 123
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped surrogate: each class is a fixed smooth
    28x28 template plus pixel noise. Linearly separable enough for LeNet to
    reach reference-gate accuracy, hard enough that an untrained net is at
    chance."""
    rng = np.random.RandomState(seed if train else seed + 1)
    tpl_rng = np.random.RandomState(seed)  # templates shared by splits
    templates = tpl_rng.rand(10, 28, 28).astype(np.float32)
    # smooth the templates so convolutions have local structure to find
    k = np.ones((5, 5), np.float32) / 25.0
    for c in range(10):
        t = templates[c]
        padded = np.pad(t, 2, mode="edge")
        sm = np.zeros_like(t)
        for i in range(5):
            for j in range(5):
                sm += k[i, j] * padded[i:i + 28, j:j + 28]
        templates[c] = sm
    ys = rng.randint(0, 10, size=n)
    noise = rng.rand(n, 28, 28).astype(np.float32)
    xs = np.clip(0.65 * templates[ys] + 0.35 * noise, 0.0, 1.0)
    return xs.reshape(n, -1), ys


class MnistDataSetIterator(DataSetIterator):
    def __init__(self, batch_size: int, train: bool = True,
                 seed: int = 123, num_examples: Optional[int] = None,
                 binarize: bool = False, shuffle: bool = True):
        super().__init__()
        global _warned
        real = _load_real(train)
        if real is not None:
            x, y = real
            self.synthetic = False
        else:
            if not _warned:
                log.warning(
                    "MNIST data not found on disk (zero-egress container); "
                    "using the deterministic synthetic MNIST surrogate. "
                    "Place IDX files or mnist.npz under ~/.deeplearning4j/"
                    "mnist or $DL4J_TPU_DATA_DIR for the real dataset.")
                _warned = True
            n = num_examples or (60000 if train else 10000)
            x, y = synthetic_mnist(n, train, seed)
            self.synthetic = True
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        if binarize:
            x = (x > 0.5).astype(np.float32)
        if shuffle:
            perm = np.random.RandomState(seed).permutation(x.shape[0])
            x, y = x[perm], y[perm]
        self._x = x
        self._y = np.eye(10, dtype=np.float32)[y]
        self._batch_size = batch_size
        self._pos = 0

    def reset(self):
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < self._x.shape[0]

    def next(self) -> DataSet:  # noqa: A003
        if not self.has_next():
            raise StopIteration("iterator exhausted; call reset()")
        i = self._pos
        self._pos += self._batch_size
        ds = DataSet(self._x[i:i + self._batch_size],
                     self._y[i:i + self._batch_size])
        return self._apply_pre(ds)

    def batch(self) -> int:
        return self._batch_size

    def total_examples(self) -> int:
        return int(self._x.shape[0])
