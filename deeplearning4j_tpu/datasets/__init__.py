from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet  # noqa: F401
from deeplearning4j_tpu.datasets.iterators import (  # noqa: F401
    DataSetIterator, ListDataSetIterator, ExistingDataSetIterator,
    AsyncDataSetIterator)
from deeplearning4j_tpu.datasets.prefetch import (  # noqa: F401
    DevicePrefetcher, maybe_device_prefetch)
from deeplearning4j_tpu.datasets.normalizers import (  # noqa: F401
    NormalizerStandardize, NormalizerMinMaxScaler,
    ImagePreProcessingScaler)
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.iris import IrisDataSetIterator  # noqa: F401
from deeplearning4j_tpu.datasets.vision import (  # noqa: F401
    Cifar10DataSetIterator, EmnistDataSetIterator,
    TinyImageNetDataSetIterator)
