"""DevicePrefetcher: device-side double-buffered input staging.

The feeding ladder (SURVEY.md call stack 3.1, "iter.next() (async
prefetch thread)"):

1. **sync** — ``fit`` calls ``iter.next()`` inline; ETL, the host->
   device copy, and the device step all serialize.
2. **host-async** — :class:`AsyncDataSetIterator` moves ETL (decode/
   augment/normalize) onto a feeder thread, but the H2D copy still
   happens synchronously at the jit boundary inside ``fit``.
3. **device-prefetch** (this module) — batches are ALSO
   ``jax.device_put`` onto the target sharding ahead of consumption,
   double-buffered, so the H2D DMA of batch n+1 overlaps the device
   step on batch n and step time approaches ``max(compute, transfer)``
   instead of ``compute + transfer``.

Where the ``device_put`` is issued (``thread_put``):

- On accelerator backends (TPU/GPU — the default there) the feeder
  thread issues it, so even a *synchronous* transfer overlaps compute.
- On the CPU backend the consumer thread issues it one batch ahead of
  the step dispatch (the ``flax.jax_utils.prefetch_to_device`` idiom:
  async dispatch keeps the copy off the critical path when the runtime
  allows). Every jax call then happens on the fit thread — the
  conservative choice for the virtual-device CPU test mesh, where the
  runtime sees patterns no production TPU client does.

Placement: replicated/default-device on single chip; with ``mesh=``,
batch arrays are laid out with ``data_sharding(mesh, ...)`` (leading
axis over the ``data`` mesh axis) so the per-device shards DMA
directly without a gather/scatter at dispatch. Callers with bespoke
placement (ParallelWrapper's trim+shard, SharedTrainingMaster's
multi-host global assembly) pass ``place_fn``.

Donation safety: every train-step funnel donates ONLY params/states/
updater-state (``donate_argnums=(0, 1, 2)`` — batch arguments are
never donated), so a prefetched buffer is never aliased by XLA and a
staged DataSet can be re-fed (see tests/test_device_prefetch.py).

An :class:`AsyncDataSetIterator` base is unwrapped: this feeder thread
already overlaps the ETL, and stacking a second consumer thread on the
async iterator's (possibly native) queue buys nothing.

``fit`` wraps iterators automatically via :func:`maybe_device_prefetch`
(``DL4J_TPU_DEVICE_PREFETCH=0`` opts out; depth via
``DL4J_TPU_DEVICE_PREFETCH_DEPTH``, default 2 = double buffering).
"""
from __future__ import annotations

import copy
import logging
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.environment import Environment
from deeplearning4j_tpu.datasets.iterators import (AsyncDataSetIterator,
                                                   DataSetIterator)

log = logging.getLogger("deeplearning4j_tpu")

#: the attrs whose floats are cast to the model dtype before the copy
#: (mirrors ``_as_jnp(x, dtype)`` in the fit funnels); masks keep their
#: dtype (the funnels call ``_as_jnp(mask)`` with no dtype)
_CAST_ATTRS = ("features", "labels")

_STAGED_BYTES_HELP = ("bytes of device-prefetched batches currently "
                      "staged ahead of the step loop")


def _ds_nbytes(ds) -> int:
    """Host-estimated byte size of a DataSet's arrays (the staged-bytes
    gauge feeding diagnostics.memory_report attribution)."""
    from deeplearning4j_tpu.parallel.mesh import DATASET_ARRAY_ATTRS
    total = 0
    for attr in DATASET_ARRAY_ATTRS:
        v = getattr(ds, attr, None)
        if v is None:
            continue
        for a in (v if isinstance(v, (list, tuple)) else (v,)):
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                total += int(np.prod(a.shape, dtype=np.int64) *
                             np.dtype(a.dtype).itemsize)
    return total


class _FeederError:
    """Exception captured on the feeder thread, re-raised on the
    consumer so a failing base iterator fails ``fit`` loudly."""
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class DevicePrefetcher(DataSetIterator):
    """Wrap any :class:`DataSetIterator`; ETL runs on a feeder thread
    and the next ``depth`` batches are staged device-side ahead of
    consumption."""

    _SENTINEL = object()

    def __init__(self, base: DataSetIterator, *, depth: int = 2,
                 mesh=None, data_axis: str = "data",
                 dtype=None,
                 place_fn: Optional[Callable] = None,
                 thread_put: Optional[bool] = None):
        super().__init__()
        if isinstance(base, AsyncDataSetIterator):
            base = base._base        # module docstring: no double wrap
        self._base = base
        self._depth = max(1, int(depth))
        self._mesh = mesh
        self._data_axis = data_axis
        self._dtype = dtype
        self._place_fn = place_fn
        self._thread_put = thread_put
        self._queue: queue.Queue = queue.Queue(self._depth)
        self._thread: Optional[threading.Thread] = None
        self._next = None
        self._error: Optional[BaseException] = None
        self._started = False
        self._consumed = False

    # -- staging stages -------------------------------------------------
    def _resolve_thread_put(self) -> bool:
        if self._thread_put is None:
            import jax
            self._thread_put = jax.default_backend() != "cpu"
        return self._thread_put

    def _cast_host(self, ds):
        """Host-side dtype cast (numpy, feeder thread) so the device
        buffer already has the model dtype and _as_jnp's astype is a
        no-op. Skipped when the caller owns placement."""
        if self._place_fn is not None or self._dtype is None:
            return ds

        def cast(a):
            if isinstance(a, np.ndarray) and \
                    np.issubdtype(a.dtype, np.floating):
                return np.asarray(a, self._dtype)
            return a

        out = copy.copy(ds)
        for attr in _CAST_ATTRS:
            v = getattr(ds, attr, None)
            if v is None:
                continue
            if isinstance(v, (list, tuple)):
                setattr(out, attr, [cast(x) for x in v])
            else:
                setattr(out, attr, cast(v))
        return out

    def _put(self, ds):
        """Issue the device transfer (async dispatch where the runtime
        supports it — the DMA proceeds while the caller moves on)."""
        if self._place_fn is not None:
            return self._place_fn(ds)
        import jax
        from deeplearning4j_tpu.parallel.mesh import (DATASET_ARRAY_ATTRS,
                                                      data_sharding)

        def put(a):
            if a is None or not hasattr(a, "ndim"):
                return a
            if self._mesh is not None and getattr(a, "ndim", 0) > 0:
                return jax.device_put(
                    a, data_sharding(self._mesh, a.ndim, self._data_axis))
            return jax.device_put(a)

        out = copy.copy(ds)
        for attr in DATASET_ARRAY_ATTRS:
            v = getattr(ds, attr, None)
            if v is None:
                continue
            if isinstance(v, (list, tuple)):
                setattr(out, attr, [put(x) for x in v])
            else:
                setattr(out, attr, put(v))
        return out

    # -- feeder ---------------------------------------------------------
    def _feeder(self, q: queue.Queue, thread_put: bool):
        try:
            self._base.reset()
            while self._base.has_next():
                with telemetry.span("prefetch.stage"):
                    ds = self._cast_host(self._base.next())
                    if thread_put:
                        ds = self._timed_put(ds)
                q.put(ds)
                if telemetry.enabled():
                    telemetry.counter(
                        "dl4j_prefetch_batches_staged_total",
                        "batches staged by the device prefetcher"
                    ).inc()
                    telemetry.gauge(
                        "dl4j_prefetch_queue_depth",
                        "staged batches currently queued ahead of the "
                        "step loop").set(q.qsize())
                    telemetry.gauge(
                        "dl4j_prefetch_staged_bytes",
                        _STAGED_BYTES_HELP).inc(_ds_nbytes(ds))
            q.put(self._SENTINEL)
        except BaseException as e:       # noqa: BLE001 — re-raised on
            q.put(_FeederError(e))       # the consumer thread

    def _timed_put(self, ds):
        if not telemetry.enabled():
            return self._put(ds)
        t0 = time.perf_counter()
        out = self._put(ds)
        telemetry.histogram(
            "dl4j_prefetch_device_put_seconds",
            "host->device staging dispatch time per batch (seconds)"
        ).observe(time.perf_counter() - t0)
        return out

    def reset(self):
        t = self._thread
        if t is not None and t.is_alive():
            # drain so the old feeder can finish; timed gets because
            # the terminal item may already have been consumed while
            # the feeder is between its final put and thread exit
            # (the AsyncDataSetIterator drain discipline)
            while t.is_alive():
                try:
                    item = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                if item is self._SENTINEL or isinstance(item,
                                                        _FeederError):
                    break
            t.join()
        thread_put = self._resolve_thread_put()
        self._queue = queue.Queue(self._depth)
        self._error = None
        self._thread = threading.Thread(
            target=self._feeder, args=(self._queue, thread_put),
            daemon=True, name="dl4j-tpu-device-prefetch")
        self._thread.start()
        self._started = True
        self._consumed = False
        self._advance()

    def _advance(self):
        """Pull the next batch and — in consumer-put mode — issue its
        H2D now, BEFORE the caller dispatches the step on the batch we
        just handed out: transfer n+1 overlaps step n."""
        if telemetry.enabled():
            t0 = time.perf_counter()
            item = self._queue.get()
            telemetry.observe_feed_stall(time.perf_counter() - t0,
                                         source="device_prefetch")
            telemetry.gauge(
                "dl4j_prefetch_queue_depth",
                "staged batches currently queued ahead of the step "
                "loop").set(self._queue.qsize())
        else:
            item = self._queue.get()
        if isinstance(item, _FeederError):
            self._error = item.exc
            self._next = None
        elif item is self._SENTINEL:
            self._next = None
        else:
            if telemetry.enabled():
                telemetry.gauge(
                    "dl4j_prefetch_staged_bytes",
                    _STAGED_BYTES_HELP).dec(_ds_nbytes(item))
            self._next = item if self._thread_put else \
                self._timed_put(item)

    def has_next(self) -> bool:
        if not self._started:
            self.reset()
        if self._error is not None:
            e, self._error = self._error, None
            raise e
        return self._next is not None

    def next(self):  # noqa: A003
        if not self.has_next():
            raise StopIteration("iterator exhausted; call reset()")
        ds = self._next
        self._consumed = True
        self._advance()
        return ds

    def __iter__(self):
        # a freshly-reset prefetcher already has batches staged — only
        # re-reset when stale, so fit's reset() + `for ds in it` does
        # not discard the staged window every epoch
        if not self._started or self._consumed:
            self.reset()
        while self.has_next():
            yield self.next()

    def batch(self) -> int:
        return self._base.batch()

    def set_pre_processor(self, p):
        # preprocessing must see HOST arrays, on the feeder thread —
        # delegate to the wrapped iterator
        self._base.set_pre_processor(p)


def maybe_device_prefetch(iterator, *, mesh=None, dtype=None,
                          place_fn=None, depth: Optional[int] = None):
    """The fit-funnel hook: wrap ``iterator`` in a
    :class:`DevicePrefetcher` when the ``DL4J_TPU_DEVICE_PREFETCH``
    flag is on (default). Returns the input unchanged when the flag is
    off, when it is already device-prefetched, or when it is not a
    resettable DataSetIterator-shaped stream (plain lists/generators
    stay sync — they cannot be re-fed across epochs anyway)."""
    env = Environment.get()
    if not env.device_prefetch:
        return iterator
    if isinstance(iterator, DevicePrefetcher):
        return iterator
    if not (hasattr(iterator, "reset") and hasattr(iterator, "has_next")
            and hasattr(iterator, "next")):
        return iterator
    return DevicePrefetcher(
        iterator, depth=depth or env.device_prefetch_depth, mesh=mesh,
        dtype=dtype, place_fn=place_fn)
