"""DataSet / MultiDataSet: the feature/label/mask bundle fit() consumes.

Reference parity: ``org.nd4j.linalg.dataset.DataSet`` / ``MultiDataSet``
(SURVEY.md J9). Arrays are numpy on the host (the input pipeline side);
they cross to device inside the jitted step, staged by the iterator's
prefetch (SURVEY.md section 3.1: async prefetch thread is the host
boundary).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def _np(x):
    from deeplearning4j_tpu.ndarray.ndarray import INDArray
    if isinstance(x, INDArray):
        return x.to_numpy()
    import jax
    if isinstance(x, jax.Array):
        # keep device-resident arrays on device — np.asarray would
        # round-trip them through the host (and on tunneled TPUs,
        # through the network) on every fit
        return x
    return np.asarray(x)


class DataSet:
    def __init__(self, features, labels, features_mask=None,
                 labels_mask=None):
        self.features = _np(features)
        self.labels = _np(labels)
        self.features_mask = _np(features_mask) \
            if features_mask is not None else None
        self.labels_mask = _np(labels_mask) \
            if labels_mask is not None else None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def get_features(self):
        return self.features

    def get_labels(self):
        return self.labels

    # -- reference API ---------------------------------------------------
    def split_test_and_train(self, n_train: int):
        tr = DataSet(self.features[:n_train], self.labels[:n_train],
                     self.features_mask[:n_train]
                     if self.features_mask is not None else None,
                     self.labels_mask[:n_train]
                     if self.labels_mask is not None else None)
        te = DataSet(self.features[n_train:], self.labels[n_train:],
                     self.features_mask[n_train:]
                     if self.features_mask is not None else None,
                     self.labels_mask[n_train:]
                     if self.labels_mask is not None else None)
        return tr, te

    def shuffle(self, seed: Optional[int] = None):
        rng = np.random.RandomState(seed)
        perm = rng.permutation(self.num_examples())
        self.features = self.features[perm]
        self.labels = self.labels[perm]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[perm]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[perm]

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        out = []
        n = self.num_examples()
        for i in range(0, n, batch_size):
            out.append(DataSet(
                self.features[i:i + batch_size],
                self.labels[i:i + batch_size],
                self.features_mask[i:i + batch_size]
                if self.features_mask is not None else None,
                self.labels_mask[i:i + batch_size]
                if self.labels_mask is not None else None))
        return out

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        return DataSet(
            np.concatenate([d.features for d in datasets]),
            np.concatenate([d.labels for d in datasets]),
            np.concatenate([d.features_mask for d in datasets])
            if datasets[0].features_mask is not None else None,
            np.concatenate([d.labels_mask for d in datasets])
            if datasets[0].labels_mask is not None else None)

    def __repr__(self):
        return (f"DataSet(features={self.features.shape}, "
                f"labels={self.labels.shape})")


class MultiDataSet:
    """N features / M labels (reference: org.nd4j.linalg.dataset.MultiDataSet)."""

    def __init__(self, features, labels, features_masks=None,
                 labels_masks=None):
        as_list = lambda x: [_np(a) for a in x] \
            if isinstance(x, (list, tuple)) else [_np(x)]
        self.features = as_list(features)
        self.labels = as_list(labels)
        self.features_masks = [_np(m) if m is not None else None
                               for m in features_masks] \
            if features_masks else None
        self.labels_masks = [_np(m) if m is not None else None
                             for m in labels_masks] \
            if labels_masks else None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])
