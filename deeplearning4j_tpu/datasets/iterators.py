"""DataSetIterator: the minibatch stream contract fit() consumes.

Reference parity: ``org.nd4j.linalg.dataset.api.iterator.DataSetIterator``,
``ListDataSetIterator``, ``ExistingDataSetIterator``, and the async
prefetch wrappers (``AsyncDataSetIterator``) — SURVEY.md J9, call stack
3.1's "iter.next() (async prefetch thread)".

The feeding ladder, from fully serial to fully overlapped:

1. **sync** — any plain iterator: ETL + H2D copy + device step all on
   the fit thread.
2. **host-async** — :class:`AsyncDataSetIterator` (this module): ETL
   (decode/augment/normalize) runs on a feeder thread; the host->device
   copy still happens synchronously at the jit boundary.
3. **device-prefetch** — :class:`~deeplearning4j_tpu.datasets.prefetch.
   DevicePrefetcher`: the feeder thread also ``jax.device_put``s onto
   the target sharding, double-buffered, so the H2D DMA of batch n+1
   overlaps the device step on batch n. ``fit`` applies it to any
   resettable iterator automatically (``DL4J_TPU_DEVICE_PREFETCH=0``
   opts out); ``benchmarks/bench_input_pipeline.py`` measures the
   per-step host-wait each rung removes.
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Iterable + reset; optional preprocessor (a normalizer)."""

    def __init__(self):
        self.pre_processor = None

    # -- reference API ---------------------------------------------------
    def set_pre_processor(self, p):
        self.pre_processor = p

    def reset(self):
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self) -> DataSet:  # noqa: A003
        raise NotImplementedError

    def batch(self) -> int:
        raise NotImplementedError

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        while self.has_next():
            yield self.next()

    def _apply_pre(self, ds: DataSet) -> DataSet:
        if self.pre_processor is not None:
            self.pre_processor.transform(ds)
        return ds


class ListDataSetIterator(DataSetIterator):
    """Iterate a list of pre-batched DataSets, or one big DataSet split
    into minibatches (reference: ListDataSetIterator)."""

    def __init__(self, data, batch_size: Optional[int] = None):
        super().__init__()
        if isinstance(data, DataSet):
            data = data.batch_by(batch_size or 32)
        self._data: List[DataSet] = list(data)
        self._batch = batch_size or (self._data[0].num_examples()
                                     if self._data else 0)
        self._pos = 0

    def reset(self):
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._data)

    def next(self) -> DataSet:  # noqa: A003
        if not self.has_next():
            raise StopIteration("iterator exhausted; call reset()")
        ds = self._data[self._pos]
        self._pos += 1
        return self._apply_pre(ds)

    def batch(self) -> int:
        return self._batch

    def total_examples(self) -> int:
        return sum(d.num_examples() for d in self._data)


class ExistingDataSetIterator(DataSetIterator):
    """Wrap any python iterable of DataSets (reference: same name)."""

    def __init__(self, iterable):
        super().__init__()
        self._iterable = iterable
        self._it = None
        self._next = None

    def reset(self):
        self._it = iter(self._iterable)
        self._advance()

    def _advance(self):
        try:
            self._next = next(self._it)
        except StopIteration:
            self._next = None

    def has_next(self) -> bool:
        if self._it is None:
            self.reset()
        return self._next is not None

    def next(self) -> DataSet:  # noqa: A003
        if not self.has_next():
            raise StopIteration("iterator exhausted; call reset()")
        ds = self._next
        self._advance()
        return self._apply_pre(ds)

    def batch(self) -> int:
        return -1


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch (reference: AsyncDataSetIterator with
    its queue-feeder thread). Overlaps host ETL with device steps."""

    _SENTINEL = object()

    def __init__(self, base: DataSetIterator, queue_size: int = 4,
                 use_native: bool = True):
        super().__init__()
        self._base = base
        self._queue_size = max(1, queue_size)
        self._use_native = use_native
        self._queue = self._make_queue()
        self._thread: Optional[threading.Thread] = None
        self._next = None
        self._started = False

    def _make_queue(self):
        """The bounded ring between the feeder thread and fit() is the
        native pthread queue when the C++ runtime is built (reference:
        the native workspace-backed async queue), else queue.Queue."""
        if self._use_native:
            from deeplearning4j_tpu.native import NativeQueue, available
            if available():
                return NativeQueue(self._queue_size)
        return queue.Queue(self._queue_size)

    def _feeder(self):
        self._base.reset()
        while self._base.has_next():
            self._queue.put(self._base.next())
        self._queue.put(self._SENTINEL)

    def reset(self):
        t = self._thread
        if t is not None and t.is_alive():
            # Drain so the old feeder can finish. Timed gets, because
            # the sentinel may ALREADY have been consumed (iterator
            # fully exhausted) while the feeder is still between its
            # final put and thread exit — a blocking get would then
            # wait forever on a producer that never pushes again.
            while t.is_alive():
                try:
                    if self._queue.get(timeout=0.05) is self._SENTINEL:
                        break
                except Exception:   # Empty timeout / closed: re-check
                    continue
            t.join()
        self._queue = self._make_queue()
        self._thread = threading.Thread(target=self._feeder, daemon=True)
        self._thread.start()
        self._started = True
        self._advance()

    def _advance(self):
        item = self._queue.get()
        self._next = None if item is self._SENTINEL else item

    def has_next(self) -> bool:
        if not self._started:
            self.reset()
        return self._next is not None

    def next(self) -> DataSet:  # noqa: A003
        if not self.has_next():
            raise StopIteration("iterator exhausted; call reset()")
        ds = self._next
        self._advance()
        return self._apply_pre(ds)

    def batch(self) -> int:
        return self._base.batch()
