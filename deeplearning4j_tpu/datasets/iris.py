"""Iris dataset iterator.

Reference parity: ``org.deeplearning4j.datasets.iterator.impl.
IrisDataSetIterator`` (SURVEY.md D13). The classic 150-row table is not
shipped in this zero-egress container; a deterministic Gaussian surrogate
with the classic class structure (one linearly separable class, two
overlapping) stands in, with the real CSV loadable from
``$DL4J_TPU_DATA_DIR/iris.csv`` when present.
"""
from __future__ import annotations

import os
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

_MEANS = np.array([[5.0, 3.4, 1.5, 0.25],
                   [5.9, 2.8, 4.3, 1.3],
                   [6.6, 3.0, 5.6, 2.0]], dtype=np.float32)
_STDS = np.array([[0.35, 0.38, 0.17, 0.10],
                  [0.52, 0.31, 0.47, 0.20],
                  [0.64, 0.32, 0.55, 0.27]], dtype=np.float32)


def _load() -> DataSet:
    csv = Path(os.environ.get("DL4J_TPU_DATA_DIR", "/nonexistent")) / \
        "iris.csv"
    if csv.exists():
        raw = np.loadtxt(csv, delimiter=",", usecols=(0, 1, 2, 3, 4))
        x = raw[:, :4].astype(np.float32)
        y = raw[:, 4].astype(int)
    else:
        rng = np.random.RandomState(6)
        ys = np.repeat(np.arange(3), 50)
        x = (_MEANS[ys] + _STDS[ys] * rng.randn(150, 4)).astype(np.float32)
        y = ys
    labels = np.eye(3, dtype=np.float32)[y]
    return DataSet(x, labels)


class IrisDataSetIterator(ListDataSetIterator):
    def __init__(self, batch_size: int = 150, num_examples: int = 150):
        ds = _load()
        ds.shuffle(seed=42)
        ds = DataSet(ds.features[:num_examples], ds.labels[:num_examples])
        super().__init__(ds, batch_size)
