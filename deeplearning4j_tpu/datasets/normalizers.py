"""Data normalizers with fit/transform/revert.

Reference parity: ``org.nd4j.linalg.dataset.api.preprocessor.
{NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler}``
(SURVEY.md J9). Normalizers mutate DataSets in place (matching the
reference) and are serialized with models by ModelSerializer.
"""
from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class Normalizer:
    def fit(self, it_or_ds):
        raise NotImplementedError

    def transform(self, ds: DataSet):
        raise NotImplementedError

    def revert(self, ds: DataSet):
        raise NotImplementedError

    def pre_process(self, ds: DataSet):
        self.transform(ds)

    # serde
    def to_map(self) -> dict:
        return {"@class": type(self).__name__,
                **{k: (v.tolist() if isinstance(v, np.ndarray) else v)
                   for k, v in self.__dict__.items()}}

    @staticmethod
    def from_map(d: dict) -> "Normalizer":
        d = dict(d)
        cls = _REGISTRY[d.pop("@class")]
        obj = cls.__new__(cls)
        for k, v in d.items():
            setattr(obj, k, np.asarray(v) if isinstance(v, list) else v)
        return obj


def _feature_stats(it_or_ds, stat_fn):
    if isinstance(it_or_ds, DataSet):
        batches = [it_or_ds.features]
    else:
        it_or_ds.reset()
        batches = [ds.features for ds in it_or_ds]
    return stat_fn(np.concatenate([b.reshape(b.shape[0], -1)
                                   for b in batches], axis=0))


class NormalizerStandardize(Normalizer):
    """Per-feature z-score."""

    def __init__(self):
        self.mean = None
        self.std = None

    def fit(self, it_or_ds):
        def stats(flat):
            return flat.mean(0), flat.std(0) + 1e-8
        self.mean, self.std = _feature_stats(it_or_ds, stats)

    def transform(self, ds: DataSet):
        shp = ds.features.shape
        flat = ds.features.reshape(shp[0], -1)
        ds.features = ((flat - self.mean) / self.std).reshape(shp) \
            .astype(np.float32)

    def revert(self, ds: DataSet):
        shp = ds.features.shape
        flat = ds.features.reshape(shp[0], -1)
        ds.features = (flat * self.std + self.mean).reshape(shp)


class NormalizerMinMaxScaler(Normalizer):
    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.data_min = None
        self.data_max = None

    def fit(self, it_or_ds):
        def stats(flat):
            return flat.min(0), flat.max(0)
        self.data_min, self.data_max = _feature_stats(it_or_ds, stats)

    def transform(self, ds: DataSet):
        shp = ds.features.shape
        flat = ds.features.reshape(shp[0], -1)
        denom = np.maximum(self.data_max - self.data_min, 1e-8)
        scaled = (flat - self.data_min) / denom
        scaled = scaled * (self.max_range - self.min_range) + self.min_range
        ds.features = scaled.reshape(shp).astype(np.float32)

    def revert(self, ds: DataSet):
        shp = ds.features.shape
        flat = ds.features.reshape(shp[0], -1)
        denom = np.maximum(self.data_max - self.data_min, 1e-8)
        unscaled = (flat - self.min_range) / \
            (self.max_range - self.min_range) * denom + self.data_min
        ds.features = unscaled.reshape(shp)


class ImagePreProcessingScaler(Normalizer):
    """Pixel [0, max_pixel] -> [min, max] (reference: same name; the
    MNIST/ImageNet default 0-255 -> 0-1)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 max_pixel: float = 255.0):
        self.min_range = min_range
        self.max_range = max_range
        self.max_pixel = max_pixel

    def fit(self, it_or_ds):
        pass  # stateless

    def transform(self, ds: DataSet):
        scale = (self.max_range - self.min_range) / self.max_pixel
        ds.features = (ds.features * scale + self.min_range) \
            .astype(np.float32)

    def revert(self, ds: DataSet):
        scale = (self.max_range - self.min_range) / self.max_pixel
        ds.features = (ds.features - self.min_range) / scale


_REGISTRY = {c.__name__: c for c in
             (NormalizerStandardize, NormalizerMinMaxScaler,
              ImagePreProcessingScaler)}
