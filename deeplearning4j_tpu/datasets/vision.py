"""CIFAR-10 / EMNIST / TinyImageNet iterators (SURVEY.md D13:
`org.deeplearning4j.datasets.iterator.impl.{Cifar10DataSetIterator,
EmnistDataSetIterator, TinyImageNetDataSetIterator}`).

Zero-egress container: real files load from ``$DL4J_TPU_DATA_DIR``
(CIFAR-10 binary batches, EMNIST/TinyImageNet ``.npz``); otherwise a
deterministic synthetic surrogate with smooth class templates (same
scheme as the MNIST surrogate) keeps every pipeline testable.
"""
from __future__ import annotations

import logging
import os
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

log = logging.getLogger("deeplearning4j_tpu")


def _data_dir() -> Path:
    return Path(os.environ.get("DL4J_TPU_DATA_DIR",
                               Path.home() / ".deeplearning4j"))


def synthetic_images(n: int, h: int, w: int, c: int, n_classes: int,
                     train: bool, seed: int,
                     template_weight: float = 0.6
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Class-conditional smooth templates + noise, [n,h,w,c] float32.

    ``template_weight`` sets the signal fraction (default 0.6).  The
    pretrained-zoo gates evaluate on a LOWER-weight ("hard") split so
    the gate sits measurably below saturation (a gate that cannot
    fail is a plumbing test — round-2 verdict Weak #4)."""
    rng = np.random.RandomState(seed if train else seed + 1)
    tpl_rng = np.random.RandomState(seed)
    tpl = tpl_rng.rand(n_classes, h, w, c).astype(np.float32)
    # separable box blur for local structure
    k = 5
    for ax in (1, 2):
        pad = [(0, 0)] * 4
        pad[ax] = (k // 2, k // 2)
        p = np.pad(tpl, pad, mode="edge")
        sl = [slice(None)] * 4
        acc = np.zeros_like(tpl)
        for i in range(k):
            sl[ax] = slice(i, i + tpl.shape[ax])
            acc += p[tuple(sl)]
        tpl = acc / k
    ys = rng.randint(0, n_classes, n)
    noise = rng.rand(n, h, w, c).astype(np.float32)
    tw = float(template_weight)
    xs = np.clip(tw * tpl[ys] + (1.0 - tw) * noise, 0, 1)
    return xs, ys


class _ArrayIterator(DataSetIterator):
    def __init__(self, x, y, n_classes, batch_size, seed, shuffle):
        super().__init__()
        if shuffle:
            perm = np.random.RandomState(seed).permutation(len(x))
            x, y = x[perm], y[perm]
        self._x = x
        self._y = np.eye(n_classes, dtype=np.float32)[y]
        self._batch_size = batch_size
        self._pos = 0

    def reset(self):
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._x)

    def next(self) -> DataSet:  # noqa: A003
        if not self.has_next():
            raise StopIteration("iterator exhausted; call reset()")
        i = self._pos
        self._pos += self._batch_size
        return self._apply_pre(DataSet(self._x[i:self._pos],
                                       self._y[i:self._pos]))

    def batch(self) -> int:
        return self._batch_size

    def total_examples(self) -> int:
        return len(self._x)


def _load_cifar10(train: bool) -> Optional[Tuple[np.ndarray,
                                                 np.ndarray]]:
    base = _data_dir() / "cifar10"
    names = ([f"data_batch_{i}.bin" for i in range(1, 6)]
             if train else ["test_batch.bin"])
    paths = [base / n for n in names]
    if not all(p.exists() for p in paths):
        return None
    xs, ys = [], []
    for p in paths:
        raw = np.frombuffer(p.read_bytes(), np.uint8).reshape(-1, 3073)
        ys.append(raw[:, 0].astype(np.int64))
        img = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        xs.append(img.astype(np.float32) / 255.0)
    return np.concatenate(xs), np.concatenate(ys)


class Cifar10DataSetIterator(_ArrayIterator):
    """reference: Cifar10DataSetIterator(batch, train) — NHWC/255."""

    def __init__(self, batch_size: int, train: bool = True,
                 seed: int = 123,
                 num_examples: Optional[int] = None,
                 shuffle: bool = True):
        real = _load_cifar10(train)
        if real is None:
            log.warning("CIFAR-10 binaries not found; using synthetic "
                        "surrogate (place them under %s)",
                        _data_dir() / "cifar10")
            n = num_examples or (50000 if train else 10000)
            x, y = synthetic_images(n, 32, 32, 3, 10, train, seed)
        else:
            x, y = real
            if num_examples:
                x, y = x[:num_examples], y[:num_examples]
        self.synthetic = real is None
        super().__init__(x, y, 10, batch_size, seed, shuffle)


class EmnistDataSetIterator(_ArrayIterator):
    """reference: EmnistDataSetIterator(set, batch, train). Sets:
    LETTERS (26), DIGITS (10), BALANCED (47), BYCLASS (62)."""

    SETS = {"LETTERS": 26, "DIGITS": 10, "BALANCED": 47,
            "BYCLASS": 62}

    def __init__(self, emnist_set: str, batch_size: int,
                 train: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None,
                 shuffle: bool = True):
        emnist_set = emnist_set.upper()
        if emnist_set not in self.SETS:
            raise ValueError(f"unknown EMNIST set {emnist_set}; "
                             f"one of {sorted(self.SETS)}")
        n_cls = self.SETS[emnist_set]
        p = _data_dir() / f"emnist_{emnist_set.lower()}.npz"
        if p.exists():
            z = np.load(p)
            k = "train" if train else "test"
            x, y = z[f"x_{k}"].astype(np.float32), z[f"y_{k}"]
            if x.max() > 1.5:
                x = x / 255.0
            x = x.reshape(len(x), -1)
            self.synthetic = False
        else:
            log.warning("EMNIST %s not found; synthetic surrogate",
                        emnist_set)
            n = num_examples or (10000 if train else 2000)
            x, y = synthetic_images(n, 28, 28, 1, n_cls, train, seed)
            x = x.reshape(n, -1)
            self.synthetic = True
        if num_examples:
            x, y = x[:num_examples], y[:num_examples]
        self.n_classes = n_cls
        super().__init__(x, y, n_cls, batch_size, seed, shuffle)


class TinyImageNetDataSetIterator(_ArrayIterator):
    """reference: TinyImageNetDataSetIterator — 200 classes, 64x64."""

    def __init__(self, batch_size: int, train: bool = True,
                 seed: int = 123,
                 num_examples: Optional[int] = None,
                 shuffle: bool = True):
        p = _data_dir() / "tiny_imagenet.npz"
        if p.exists():
            z = np.load(p)
            k = "train" if train else "val"
            x = z[f"x_{k}"].astype(np.float32)
            if x.max() > 1.5:
                x = x / 255.0
            y = z[f"y_{k}"]
            self.synthetic = False
        else:
            log.warning("TinyImageNet not found; synthetic surrogate")
            n = num_examples or (2000 if train else 500)
            x, y = synthetic_images(n, 64, 64, 3, 200, train, seed)
            self.synthetic = True
        if num_examples:
            x, y = x[:num_examples], y[:num_examples]
        super().__init__(x, y, 200, batch_size, seed, shuffle)
