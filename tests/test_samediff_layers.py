"""SameDiffLayer / SameDiffOutputLayer / SameDiffVertex wrapper tests
(reference test style: TestSameDiffDense / TestSameDiffOutput /
TestSameDiffVertex in org.deeplearning4j.nn.layers.samediff,
SURVEY.md D4 "SameDiff wrapper layers")."""
import jax
import jax.numpy as jnp
import numpy as np
from dataclasses import dataclass

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.layers_samediff import (
    SameDiffLayer, SameDiffOutputLayer, SameDiffVertex)
from deeplearning4j_tpu.nn.graph import ComputationGraph


@dataclass
class SDDense(SameDiffLayer):
    """Custom dense layer built from the SameDiff graph API."""

    def define_parameters(self):
        return {"W": (self.n_in, self.n_out), "b": (self.n_out,)}

    def define_layer(self, sd, layer_input, params):
        return sd.nn.relu(layer_input.mmul(params["W"]) + params["b"])


@dataclass
class SDMseOutput(SameDiffOutputLayer):
    """Custom linear output head."""

    def define_parameters(self):
        return {"W": (self.n_in, self.n_out)}

    def define_layer(self, sd, layer_input, params):
        return layer_input.mmul(params["W"])


class GatedSumVertex(SameDiffVertex):
    """sigmoid(a) * b — custom 2-input vertex."""

    def define_vertex(self, sd, inputs):
        a, b = inputs
        return sd.nn.sigmoid(a).mul(b)


class TestSameDiffLayer:
    def test_matches_builtin_dense(self):
        """SDDense forward == DenseLayer forward given identical params."""
        sd_layer = SDDense(n_in=4, n_out=8)
        dense = DenseLayer(n_in=4, n_out=8, activation=Activation.RELU)
        key = jax.random.PRNGKey(0)
        p = dense.init_params(key, InputType.feed_forward(4))
        x = jnp.asarray(np.random.RandomState(0).randn(6, 4),
                        jnp.float32)
        y_ref, _ = dense.forward(p, x, training=False)
        y_sd, _ = sd_layer.forward(p, x, training=False)
        np.testing.assert_allclose(np.asarray(y_sd), np.asarray(y_ref),
                                   rtol=1e-5)

    def test_trains_in_network(self):
        rng = np.random.RandomState(0)
        xs = rng.randn(128, 4).astype(np.float32)
        ys = (xs[:, 0] + xs[:, 1] > 0).astype(int)
        labels = np.eye(2, dtype=np.float32)[ys]
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(1e-2))
                .list()
                .layer(SDDense(n_out=16))
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(60):
            net.fit(xs, labels)
        acc = (np.asarray(net.output(xs)).argmax(-1) == ys).mean()
        assert acc > 0.9

    def test_gradients_flow_to_custom_params(self):
        layer = SDDense(n_in=3, n_out=8)
        p = layer.init_params(jax.random.PRNGKey(0),
                              InputType.feed_forward(3))
        x = jnp.asarray(np.random.RandomState(1).randn(16, 3), jnp.float32)

        def loss(pp):
            y, _ = layer.forward(pp, x, training=True)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(p)
        assert float(jnp.abs(g["W"]).sum()) > 0.0
        assert float(jnp.abs(g["b"]).sum()) > 0.0


class TestSameDiffOutputLayer:
    def test_regression_head(self):
        rng = np.random.RandomState(0)
        xs = rng.randn(128, 3).astype(np.float32)
        w_true = np.array([[1.0], [-2.0], [0.5]], np.float32)
        ys = xs @ w_true
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(5e-2))
                .list()
                .layer(SDMseOutput(n_out=1,
                                   loss_function=LossFunction.MSE,
                                   activation=Activation.IDENTITY))
                .set_input_type(InputType.feed_forward(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(100):
            net.fit(xs, ys)
        w = np.asarray(net.params["layer_0"]["W"])
        np.testing.assert_allclose(w, w_true, atol=0.05)


class TestSameDiffVertex:
    def test_gated_sum_in_graph(self):
        v = GatedSumVertex()
        a = jnp.ones((2, 3))
        b = jnp.full((2, 3), 2.0)
        out = v.forward([a, b], training=False)
        np.testing.assert_allclose(np.asarray(out),
                                   2.0 / (1.0 + np.exp(-1.0)), rtol=1e-5)

    def test_inside_computation_graph(self):
        g = (NeuralNetConfiguration.Builder()
             .seed(0).updater(Adam(1e-2))
             .graph_builder())
        g.add_inputs("in")
        g.add_layer("d1", DenseLayer(n_out=4,
                                     activation=Activation.IDENTITY),
                    "in")
        g.add_layer("d2", DenseLayer(n_out=4,
                                     activation=Activation.IDENTITY),
                    "in")
        g.add_vertex("gate", GatedSumVertex(), "d1", "d2")
        g.add_layer("out", OutputLayer(
            n_out=2, loss_function=LossFunction.MCXENT,
            activation=Activation.SOFTMAX), "gate")
        g.set_outputs("out")
        g.set_input_types(InputType.feed_forward(3))
        net = ComputationGraph(g.build()).init()
        x = np.random.RandomState(0).randn(5, 3).astype(np.float32)
        out = net.output(x)
        arr = np.asarray(out[0] if isinstance(out, (list, tuple)) else
                         out)
        assert arr.shape == (5, 2)
