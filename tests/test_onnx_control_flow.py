"""ONNX control-flow import (SURVEY.md S7/S3): If and Loop map to the
same lax lowering the TF While/If path uses; subgraphs are LEXICALLY
scoped (outer tensors captured live).  Fixtures hand-encoded with the
in-repo encoder; ground truth is the spec semantics in numpy."""
import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.onnx import import_onnx
from deeplearning4j_tpu.modelimport.onnx.protobuf import (
    GraphAttr, encode_graph, encode_model, encode_node,
    encode_value_info)

R = np.random.RandomState(2)


def _model(nodes, inits, in_specs, out_specs):
    return encode_model(
        nodes, inits,
        [encode_value_info(n, s) for n, s in in_specs],
        [encode_value_info(n, s) for n, s in out_specs])


class TestIf:
    def test_if_with_lexical_capture(self):
        """Branches reference the OUTER tensor x and initializer z by
        name (no subgraph inputs) — ONNX lexical scoping."""
        then_g = encode_graph(
            [encode_node("Mul", ["x", "z"], ["tout"], "m")],
            {}, [], [encode_value_info("tout", (3,))])
        else_g = encode_graph(
            [encode_node("Sub", ["x", "z"], ["eout"], "s")],
            {}, [], [encode_value_info("eout", (3,))])
        nodes = [
            encode_node("ReduceSum", ["x"], ["s"], "rs", keepdims=0),
            encode_node("Greater", ["s", "thr"], ["p"], "gt"),
            encode_node("If", ["p"], ["y"], "if",
                        then_branch=GraphAttr(then_g),
                        else_branch=GraphAttr(else_g)),
        ]
        inits = {"z": np.float32([2.0, 3.0, 4.0]),
                 "thr": np.float32(0.0)}
        m = _model(nodes, inits, [("x", (3,))], [("y", (3,))])
        imp = import_onnx(m)
        for xv in (np.float32([1, 2, 3]), np.float32([-1, -2, -3])):
            got = np.asarray(imp.output({"x": xv})[0])
            want = xv * inits["z"] if xv.sum() > 0 else xv - inits["z"]
            np.testing.assert_allclose(got, want, rtol=1e-6)


class TestLoop:
    def _loop_model(self, m_val=None, with_cond_update=False):
        # body: (i, c, v) -> (c_out, v*1.1 + x)
        body_nodes = [
            encode_node("Mul", ["v_in", "scale"], ["vs"], "m"),
            encode_node("Add", ["vs", "x"], ["v_out"], "a"),
        ]
        if with_cond_update:
            # keep while sum(v) < 40
            body_nodes += [
                encode_node("ReduceSum", ["v_out"], ["sv"], "rs",
                            keepdims=0),
                encode_node("Less", ["sv", "limit"], ["c_out"], "lt"),
            ]
        else:
            body_nodes += [
                encode_node("Identity", ["c_in"], ["c_out"], "ci"),
            ]
        body = encode_graph(
            body_nodes, {"scale": np.float32(1.1)},
            [encode_value_info("i", ()),
             encode_value_info("c_in", ()),
             encode_value_info("v_in", (2,))],
            [encode_value_info("c_out", ()),
             encode_value_info("v_out", (2,))])
        inits = {"v0": np.float32([1.0, 2.0]),
                 "limit": np.float32(40.0)}
        loop_inputs = ["M", "cond0", "v0"]
        if m_val is not None:
            inits["M"] = np.asarray(m_val, np.int64)
        inits["cond0"] = np.asarray(True)
        nodes = [encode_node("Loop", loop_inputs, ["vf"], "loop",
                             body=GraphAttr(body))]
        return _model(nodes, inits, [("x", (2,))], [("vf", (2,))])

    def test_static_trip_count(self):
        imp = import_onnx(self._loop_model(m_val=4))
        xv = np.float32([0.5, -0.25])
        got = np.asarray(imp.output({"x": xv})[0])
        v = np.float32([1.0, 2.0])
        for _ in range(4):
            v = v * np.float32(1.1) + xv
        np.testing.assert_allclose(got, v, rtol=1e-5)

    def test_dynamic_condition(self):
        imp = import_onnx(self._loop_model(m_val=50,
                                           with_cond_update=True))
        xv = np.float32([1.0, 2.0])
        got = np.asarray(imp.output({"x": xv})[0])
        v = np.float32([1.0, 2.0])
        # ONNX: iterate while cond (checked BEFORE each iteration)
        cond = True
        for _ in range(50):
            if not cond:
                break
            v = v * np.float32(1.1) + xv
            cond = v.sum() < 40.0
        np.testing.assert_allclose(got, v, rtol=1e-5)

    def test_for_loop_form_ignores_body_cond(self):
        """For-loop form (M given, cond input ABSENT): the spec says
        the body's cond output is IGNORED — a valid model whose body
        emits a non-true cond placeholder must still run all M trips
        (round-3 advisor finding: it used to terminate after one)."""
        body = encode_graph(
            [encode_node("Not", ["c_in"], ["c_out"], "ci"),
             encode_node("Mul", ["v_in", "scale"], ["vs"], "m"),
             encode_node("Add", ["vs", "x"], ["v_out"], "a")],
            {"scale": np.float32(1.1)},
            [encode_value_info("i", ()),
             encode_value_info("c_in", ()),
             encode_value_info("v_in", (2,))],
            [encode_value_info("c_out", ()),
             encode_value_info("v_out", (2,))])
        inits = {"M": np.asarray(4, np.int64),
                 "v0": np.float32([1.0, 2.0])}
        nodes = [encode_node("Loop", ["M", "", "v0"], ["vf"], "loop",
                             body=GraphAttr(body))]
        m = _model(nodes, inits, [("x", (2,))], [("vf", (2,))])
        imp = import_onnx(m)
        xv = np.float32([0.5, -0.25])
        got = np.asarray(imp.output({"x": xv})[0])
        v = np.float32([1.0, 2.0])
        for _ in range(4):
            v = v * np.float32(1.1) + xv
        np.testing.assert_allclose(got, v, rtol=1e-5)

    def test_no_trip_count_no_cond_rejected(self):
        """Neither M nor cond = the spec's infinite-loop form, which
        cannot lower to a bounded program — must raise loudly."""
        body = encode_graph(
            [encode_node("Identity", ["c_in"], ["c_out"], "ci"),
             encode_node("Add", ["v_in", "x"], ["v_out"], "a")],
            {},
            [encode_value_info("i", ()),
             encode_value_info("c_in", ()),
             encode_value_info("v_in", (2,))],
            [encode_value_info("c_out", ()),
             encode_value_info("v_out", (2,))])
        nodes = [encode_node("Loop", ["", "", "v0"], ["vf"], "loop",
                             body=GraphAttr(body))]
        m = _model(nodes, {"v0": np.float32([1.0, 2.0])},
                   [("x", (2,))], [("vf", (2,))])
        with pytest.raises(NotImplementedError, match="infinite"):
            import_onnx(m)

    def _scan_model(self):
        body = encode_graph(
            [encode_node("Identity", ["c_in"], ["c_out"], "ci"),
             encode_node("Add", ["v_in", "x"], ["v_out"], "a"),
             encode_node("Identity", ["v_out"], ["scan0"], "sc")],
            {},
            [encode_value_info("i", ()),
             encode_value_info("c_in", ()),
             encode_value_info("v_in", (2,))],
            [encode_value_info("c_out", ()),
             encode_value_info("v_out", (2,)),
             encode_value_info("scan0", (2,))])
        inits = {"M": np.asarray(3, np.int64),
                 "cond0": np.asarray(True),
                 "v0": np.float32([0.0, 0.0])}
        nodes = [encode_node("Loop", ["M", "cond0", "v0"],
                             ["vf", "stack"], "loop",
                             body=GraphAttr(body))]
        return _model(nodes, inits, [("x", (2,))],
                      [("vf", (2,)), ("stack", (3, 2))])

    def test_onnx_scan_op(self):
        """ONNX Scan: cumulative sum over the leading axis — one
        state, one scan input, one scan output."""
        body = encode_graph(
            [encode_node("Add", ["s_in", "x_t"], ["s_out"], "a"),
             encode_node("Identity", ["s_out"], ["y_t"], "i")],
            {},
            [encode_value_info("s_in", (2,)),
             encode_value_info("x_t", (2,))],
            [encode_value_info("s_out", (2,)),
             encode_value_info("y_t", (2,))])
        inits = {"s0": np.float32([0.0, 10.0])}
        nodes = [encode_node("Scan", ["s0", "xs"], ["sf", "ys"],
                             "scan", body=GraphAttr(body),
                             num_scan_inputs=1)]
        m = _model(nodes, inits, [("xs", (4, 2))],
                   [("sf", (2,)), ("ys", (4, 2))])
        imp = import_onnx(m)
        xs = R.randn(4, 2).astype(np.float32)
        sf, ys = (np.asarray(a) for a in imp.output({"xs": xs}))
        want = np.cumsum(xs, axis=0) + np.float32([0.0, 10.0])
        np.testing.assert_allclose(ys, want, rtol=1e-5)
        np.testing.assert_allclose(sf, want[-1], rtol=1e-5)

    def test_scan_symbolic_length_rejected(self):
        """A symbolic scan-input length parses as -1; it must hit the
        intended NotImplementedError, not np.zeros((-1,...))'s
        confusing ValueError (round-3 advisor finding).  Exercised at
        the mapping level with a ctx whose shape lookup yields -1 —
        the shape a symbolic dim_param decodes to."""
        from deeplearning4j_tpu.modelimport.onnx.mappings import (
            ONNX_OP_MAP)
        from deeplearning4j_tpu.modelimport.onnx.protobuf import (
            parse_graph)
        body = encode_graph(
            [encode_node("Add", ["s_in", "x_t"], ["s_out"], "a"),
             encode_node("Identity", ["s_out"], ["y_t"], "i")],
            {},
            [encode_value_info("s_in", (2,)),
             encode_value_info("x_t", (2,))],
            [encode_value_info("s_out", (2,)),
             encode_value_info("y_t", (2,))])
        g = parse_graph(encode_graph(
            [encode_node("Scan", ["s0", "xs"], ["sf", "ys"],
                         "scan", num_scan_inputs=1,
                         body=GraphAttr(body))],
            {}, [encode_value_info("s0", (2,)),
                 encode_value_info("xs", (-1, 2))],
            [encode_value_info("sf", (2,)),
             encode_value_info("ys", (-1, 2))]))
        scan_node = g.nodes[0]
        assert scan_node.op == "Scan"

        class _Ctx:
            def var(self, name):
                return name

            def shape_of(self, name):
                return {"s0": (2,), "xs": (-1, 2)}[name]

        with pytest.raises(NotImplementedError,
                           match="static and uniform"):
            ONNX_OP_MAP["Scan"](_Ctx(), scan_node)

    def test_scan_outputs_stack_per_iteration(self):
        """Scan outputs accumulate into a dense [M, elem] tensor (the
        TensorArray lowering): vf = 3x, stack = [x, 2x, 3x]."""
        imp = import_onnx(self._scan_model())
        xv = np.float32([1.5, -0.5])
        vf, stack = (np.asarray(a) for a in imp.output({"x": xv}))
        np.testing.assert_allclose(vf, 3 * xv, rtol=1e-6)
        np.testing.assert_allclose(
            stack, np.stack([xv, 2 * xv, 3 * xv]), rtol=1e-6)


class TestOnnxLSTM:
    def _lstm_model(self, direction, seq=5, b=3, inp=4, H=6,
                    with_initial=False):
        dirs = 2 if direction == "bidirectional" else 1
        rng = np.random.RandomState(8)
        # build in ONNX gate order (i, o, f, c) directly
        W = (rng.randn(dirs, 4 * H, inp) * 0.3).astype(np.float32)
        Rw = (rng.randn(dirs, 4 * H, H) * 0.3).astype(np.float32)
        B = (rng.randn(dirs, 8 * H) * 0.1).astype(np.float32)
        inits = {"W": W, "R": Rw, "B": B}
        ins = ["x", "W", "R", "B"]
        if with_initial:
            inits["h0"] = (rng.randn(dirs, b, H) * 0.2).astype(
                np.float32)
            inits["c0"] = (rng.randn(dirs, b, H) * 0.2).astype(
                np.float32)
            ins += ["", "h0", "c0"]
        nodes = [encode_node("LSTM", ins, ["Y", "Yh", "Yc"], "lstm",
                             hidden_size=H, direction=direction)]
        m = _model(nodes, inits, [("x", (seq, b, inp))],
                   [("Y", (seq, dirs, b, H)), ("Yh", (dirs, b, H)),
                    ("Yc", (dirs, b, H))])
        return m, W, Rw, B, inits

    @staticmethod
    def _ref_lstm(x, W, Rw, B, h0, c0):
        """numpy reference in ONNX (i, o, f, c) order, one
        direction."""
        seq, b, _ = x.shape
        H = Rw.shape[1]
        sig = lambda v: 1.0 / (1.0 + np.exp(-v))
        h, c = h0.copy(), c0.copy()
        ys = []
        wb, rb = B[:4 * H], B[4 * H:]
        for t in range(seq):
            z = x[t] @ W.T + h @ Rw.T + wb + rb
            i = sig(z[:, :H])
            o = sig(z[:, H:2 * H])
            f = sig(z[:, 2 * H:3 * H])
            g = np.tanh(z[:, 3 * H:])
            c = f * c + i * g
            h = o * np.tanh(c)
            ys.append(h.copy())
        return np.stack(ys), h, c

    @pytest.mark.parametrize("direction", ["forward", "reverse",
                                           "bidirectional"])
    def test_lstm_matches_reference(self, direction):
        seq, b, inp, H = 5, 3, 4, 6
        m, W, Rw, B, inits = self._lstm_model(direction, seq, b, inp,
                                              H, with_initial=True)
        imp = import_onnx(m)
        x = np.random.RandomState(1).randn(seq, b, inp) \
            .astype(np.float32) * 0.5
        Y, Yh, Yc = (np.asarray(a) for a in imp.output({"x": x}))
        dirs = Y.shape[1]
        for d in range(dirs):
            xd = x if (direction == "forward" or d == 0
                       and direction == "bidirectional") else x[::-1]
            if direction == "reverse":
                xd = x[::-1]
            ys, h, c = self._ref_lstm(xd, W[d], Rw[d], B[d],
                                      inits["h0"][d], inits["c0"][d])
            if direction == "reverse" or d == 1:
                ys = ys[::-1]
            np.testing.assert_allclose(Y[:, d], ys, rtol=1e-4,
                                       atol=1e-5)
            np.testing.assert_allclose(Yh[d], h, rtol=1e-4,
                                       atol=1e-5)
            np.testing.assert_allclose(Yc[d], c, rtol=1e-4,
                                       atol=1e-5)


class TestOnnxGRU:
    @staticmethod
    def _ref_gru(x, W, Rw, B, h0, lbr):
        seq = x.shape[0]
        H = Rw.shape[1]
        sig = lambda v: 1.0 / (1.0 + np.exp(-v))
        wb, rb = B[:3 * H], B[3 * H:]
        h = h0.copy()
        ys = []
        for t in range(seq):
            xz = x[t] @ W.T + wb
            hz = h @ Rw.T
            z = sig(xz[:, :H] + hz[:, :H] + rb[:H])
            r = sig(xz[:, H:2 * H] + hz[:, H:2 * H] + rb[H:2 * H])
            if lbr:
                n = np.tanh(xz[:, 2 * H:]
                            + r * (hz[:, 2 * H:] + rb[2 * H:]))
            else:
                n = np.tanh(xz[:, 2 * H:]
                            + (r * h) @ Rw.T[:, 2 * H:] + rb[2 * H:])
            h = (1.0 - z) * n + z * h
            ys.append(h.copy())
        return np.stack(ys), h

    @pytest.mark.parametrize("direction,lbr",
                             [("forward", 1), ("forward", 0),
                              ("bidirectional", 1)])
    def test_gru_matches_reference(self, direction, lbr):
        seq, b, inp, H = 5, 3, 4, 6
        dirs = 2 if direction == "bidirectional" else 1
        rng = np.random.RandomState(9)
        W = (rng.randn(dirs, 3 * H, inp) * 0.3).astype(np.float32)
        Rw = (rng.randn(dirs, 3 * H, H) * 0.3).astype(np.float32)
        B = (rng.randn(dirs, 6 * H) * 0.1).astype(np.float32)
        h0 = (rng.randn(dirs, b, H) * 0.2).astype(np.float32)
        nodes = [encode_node(
            "GRU", ["x", "W", "R", "B", "", "h0"], ["Y", "Yh"],
            "gru", hidden_size=H, direction=direction,
            linear_before_reset=lbr)]
        m = _model(nodes, {"W": W, "R": Rw, "B": B, "h0": h0},
                   [("x", (seq, b, inp))],
                   [("Y", (seq, dirs, b, H)), ("Yh", (dirs, b, H))])
        imp = import_onnx(m)
        x = rng.randn(seq, b, inp).astype(np.float32) * 0.5
        Y, Yh = (np.asarray(a) for a in imp.output({"x": x}))
        for d in range(dirs):
            xd = x[::-1] if d == 1 else x
            ys, h = self._ref_gru(xd, W[d], Rw[d], B[d], h0[d], lbr)
            if d == 1:
                ys = ys[::-1]
            np.testing.assert_allclose(Y[:, d], ys, rtol=1e-4,
                                       atol=1e-5)
            np.testing.assert_allclose(Yh[d], h, rtol=1e-4,
                                       atol=1e-5)


class TestRnnDefaults:
    def test_lstm_no_initial_no_bias_default_activations(self):
        """Zero-default initial states and bias, plus an activations
        attr spelling out the DEFAULTS (tf2onnx does this) — all must
        import."""
        seq, b, inp, H = 4, 2, 3, 5
        rng = np.random.RandomState(11)
        W = (rng.randn(1, 4 * H, inp) * 0.3).astype(np.float32)
        Rw = (rng.randn(1, 4 * H, H) * 0.3).astype(np.float32)
        nodes = [encode_node(
            "LSTM", ["x", "W", "R"], ["Y", "Yh", "Yc"], "lstm",
            hidden_size=H,
            activations=[b"Sigmoid", b"Tanh", b"Tanh"])]
        m = _model(nodes, {"W": W, "R": Rw},
                   [("x", (seq, b, inp))],
                   [("Y", (seq, 1, b, H)), ("Yh", (1, b, H)),
                    ("Yc", (1, b, H))])
        imp = import_onnx(m)
        x = rng.randn(seq, b, inp).astype(np.float32) * 0.5
        Y, Yh, Yc = (np.asarray(a) for a in imp.output({"x": x}))
        B0 = np.zeros(8 * H, np.float32)
        ys, h, c = TestOnnxLSTM._ref_lstm(
            x, W[0], Rw[0], B0, np.zeros((b, H), np.float32),
            np.zeros((b, H), np.float32))
        np.testing.assert_allclose(Y[:, 0], ys, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(Yh[0], h, rtol=1e-4, atol=1e-5)

    def test_lstm_truly_custom_activations_rejected(self):
        W = np.zeros((1, 20, 3), np.float32)
        Rw = np.zeros((1, 20, 5), np.float32)
        nodes = [encode_node(
            "LSTM", ["x", "W", "R"], ["Y", "Yh", "Yc"], "lstm",
            hidden_size=5,
            activations=[b"HardSigmoid", b"Tanh", b"Tanh"])]
        m = _model(nodes, {"W": W, "R": Rw}, [("x", (4, 2, 3))],
                   [("Y", (4, 1, 2, 5)), ("Yh", (1, 2, 5)),
                    ("Yc", (1, 2, 5))])
        with pytest.raises(NotImplementedError, match="activations"):
            import_onnx(m)


class TestOnnxVanillaRNN:
    def test_rnn_matches_reference(self):
        seq, b, inp, H = 4, 2, 3, 5
        rng = np.random.RandomState(12)
        W = (rng.randn(1, H, inp) * 0.4).astype(np.float32)
        Rw = (rng.randn(1, H, H) * 0.4).astype(np.float32)
        B = (rng.randn(1, 2 * H) * 0.1).astype(np.float32)
        nodes = [encode_node("RNN", ["x", "W", "R", "B"],
                             ["Y", "Yh"], "rnn", hidden_size=H)]
        m = _model(nodes, {"W": W, "R": Rw, "B": B},
                   [("x", (seq, b, inp))],
                   [("Y", (seq, 1, b, H)), ("Yh", (1, b, H))])
        imp = import_onnx(m)
        x = rng.randn(seq, b, inp).astype(np.float32) * 0.5
        Y, Yh = (np.asarray(a) for a in imp.output({"x": x}))
        h = np.zeros((b, H), np.float32)
        ys = []
        for t in range(seq):
            h = np.tanh(x[t] @ W[0].T + h @ Rw[0].T
                        + B[0][:H] + B[0][H:])
            ys.append(h.copy())
        np.testing.assert_allclose(Y[:, 0], np.stack(ys), rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(Yh[0], h, rtol=1e-4, atol=1e-5)
