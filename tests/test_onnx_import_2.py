"""ONNX import conformance, batch 2 (SURVEY.md S7/§4.4): shape/index
ops, normalization, ConvTranspose, PRelu — fixtures hand-encoded with
the in-repo ONNX encoder, ground truth from torch CPU."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deeplearning4j_tpu.modelimport.onnx import import_onnx
from deeplearning4j_tpu.modelimport.onnx.protobuf import (
    encode_model, encode_node, encode_value_info)

R = np.random.RandomState(0)


def _run(nodes, inits, in_specs, out_specs, feeds):
    model = encode_model(nodes, inits,
                         [encode_value_info(n, s) for n, s in in_specs],
                         [encode_value_info(n, s) for n, s in out_specs])
    imp = import_onnx(model)
    return imp.output(feeds)


class TestShapeIndexOps:
    def test_split_where_argmax(self):
        x = R.randn(4, 6).astype(np.float32)
        nodes = [
            encode_node("Split", ["x"], ["a", "b"], "sp", axis=1,
                        split=[2, 4]),
            encode_node("ArgMax", ["b"], ["am"], "am", axis=1,
                        keepdims=0),
            encode_node("Cast", ["am"], ["amf"], "c", to=1),
            encode_node("ReduceSum", ["a"], ["s"], "rs", axes=[1],
                        keepdims=0),
            encode_node("Greater", ["s", "amf"], ["g"], "gt"),
            encode_node("Where", ["g", "s", "amf"], ["y"], "w"),
        ]
        got = _run(nodes, {}, [("x", (4, 6))], [("y", (4,))],
                   {"x": x})[0]
        a, b = x[:, :2], x[:, 2:]
        s = a.sum(1)
        am = b.argmax(1).astype(np.float32)
        want = np.where(s > am, s, am)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)

    def test_tile_expand_onehot(self):
        idx = np.asarray([0, 2, 1], np.int64)
        nodes = [
            encode_node("OneHot", ["i", "depth", "vals"], ["oh"], "oh",
                        axis=-1),
            encode_node("Tile", ["oh", "reps"], ["t"], "t"),
            encode_node("Expand", ["t", "eshape"], ["y"], "e"),
        ]
        inits = {"depth": np.asarray(4, np.int64),
                 "vals": np.asarray([0.0, 1.0], np.float32),
                 "reps": np.asarray([2, 1], np.int64),
                 "eshape": np.asarray([1, 6, 4], np.int64)}
        got = _run(nodes, inits, [("i", (3,))], [("y", (1, 6, 4))],
                   {"i": idx})[0]
        oh = np.eye(4, dtype=np.float32)[idx]
        want = np.tile(oh, (2, 1))[None]
        np.testing.assert_allclose(np.asarray(got), want)

    def test_topk_cumsum(self):
        x = R.randn(3, 8).astype(np.float32)
        nodes = [
            encode_node("TopK", ["x", "k"], ["v", "i"], "tk", axis=-1),
            encode_node("CumSum", ["v", "ax"], ["y"], "cs"),
        ]
        inits = {"k": np.asarray(3, np.int64),
                 "ax": np.asarray(1, np.int32)}
        got = _run(nodes, inits, [("x", (3, 8))], [("y", (3, 3))],
                   {"x": x})[0]
        tv = np.sort(x, axis=-1)[:, ::-1][:, :3]
        np.testing.assert_allclose(np.asarray(got),
                                   np.cumsum(tv, axis=1), atol=1e-5)

    def test_expand_with_ones_dims(self):
        """ONNX Expand max-dim semantics: target dim 1 keeps the input
        dim (regression: plain broadcast_to rejected it)."""
        x = R.randn(3, 4).astype(np.float32)
        nodes = [encode_node("Expand", ["x", "s"], ["y"], "e")]
        got = _run(nodes, {"s": np.asarray([3, 1], np.int64)},
                   [("x", (3, 4))], [("y", (3, 4))], {"x": x})[0]
        np.testing.assert_allclose(np.asarray(got), x)

    def test_topk_positive_last_axis(self):
        """axis given as rank-1 instead of -1 (regression)."""
        x = R.randn(3, 8).astype(np.float32)
        nodes = [encode_node("TopK", ["x", "k"], ["v", "i"], "tk",
                             axis=1)]
        got = _run(nodes, {"k": np.asarray(2, np.int64)},
                   [("x", (3, 8))], [("v", (3, 2))], {"x": x})[0]
        want = np.sort(x, axis=-1)[:, ::-1][:, :2]
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)

    def test_scatter_nd(self):
        data = np.zeros((5,), np.float32)
        nodes = [encode_node("ScatterND", ["d", "i", "u"], ["y"], "sc")]
        inits = {"i": np.asarray([[1], [3]], np.int64),
                 "u": np.asarray([7.0, 9.0], np.float32)}
        got = _run(nodes, inits, [("d", (5,))], [("y", (5,))],
                   {"d": data})[0]
        np.testing.assert_allclose(np.asarray(got),
                                   [0, 7, 0, 9, 0])


class TestNormAndActivations:
    def test_layer_norm_matches_torch(self):
        x = torch.randn(4, 10)
        ln = torch.nn.LayerNorm(10).eval()
        with torch.no_grad():
            ln.weight.copy_(torch.rand(10) + 0.5)
            ln.bias.copy_(torch.randn(10) * 0.1)
        want = ln(x).detach().numpy()
        nodes = [encode_node("LayerNormalization", ["x", "g", "b"],
                             ["y"], "ln", axis=-1,
                             epsilon=float(ln.eps))]
        inits = {"g": ln.weight.detach().numpy(),
                 "b": ln.bias.detach().numpy()}
        got = _run(nodes, inits, [("x", (4, 10))], [("y", (4, 10))],
                   {"x": x.numpy()})[0]
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)

    def test_instance_norm_matches_torch(self):
        x = torch.randn(2, 3, 8, 8)
        inorm = torch.nn.InstanceNorm2d(3, affine=True).eval()
        with torch.no_grad():
            inorm.weight.copy_(torch.rand(3) + 0.5)
            inorm.bias.copy_(torch.randn(3) * 0.1)
        want = inorm(x).detach().numpy()
        nodes = [encode_node("InstanceNormalization", ["x", "g", "b"],
                             ["y"], "in", epsilon=1e-5)]
        inits = {"g": inorm.weight.detach().numpy(),
                 "b": inorm.bias.detach().numpy()}
        got = _run(nodes, inits, [("x", (2, 3, 8, 8))],
                   [("y", (2, 3, 8, 8))], {"x": x.numpy()})[0]
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-4)

    def test_prelu_hardsigmoid(self):
        x = torch.randn(3, 6)
        alpha = torch.rand(6) * 0.4
        want = torch.nn.functional.hardsigmoid(
            torch.nn.functional.prelu(x, alpha)).numpy()
        # torch hardsigmoid: clip(x/6 + 1/2, 0, 1) -> alpha=1/6, beta=.5
        nodes = [
            encode_node("PRelu", ["x", "a"], ["p"], "pr"),
            encode_node("HardSigmoid", ["p"], ["y"], "hs",
                        alpha=1.0 / 6.0, beta=0.5),
        ]
        got = _run(nodes, {"a": alpha.numpy()}, [("x", (3, 6))],
                   [("y", (3, 6))], {"x": x.numpy()})[0]
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)

    def test_lrn_matches_torch(self):
        x = torch.randn(2, 8, 5, 5)
        lrn = torch.nn.LocalResponseNorm(5, alpha=1e-3, beta=0.75,
                                         k=1.0)
        want = lrn(x).detach().numpy()
        nodes = [encode_node("LRN", ["x"], ["y"], "lrn", size=5,
                             alpha=1e-3, beta=0.75, bias=1.0)]
        got = _run(nodes, {}, [("x", (2, 8, 5, 5))],
                   [("y", (2, 8, 5, 5))], {"x": x.numpy()})[0]
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


class TestConvTranspose:
    @pytest.mark.parametrize("stride,pad", [(2, 0), (2, 1), (1, 1)])
    def test_matches_torch(self, stride, pad):
        torch.manual_seed(0)
        m = torch.nn.ConvTranspose2d(3, 4, 3, stride=stride,
                                     padding=pad).eval()
        x = torch.randn(2, 3, 5, 5)
        want = m(x).detach().numpy()
        nodes = [encode_node("ConvTranspose", ["x", "w", "b"], ["y"],
                             "ct", strides=[stride, stride],
                             pads=[pad, pad, pad, pad],
                             kernel_shape=[3, 3])]
        inits = {"w": m.weight.detach().numpy(),
                 "b": m.bias.detach().numpy()}
        got = _run(nodes, inits, [("x", (2, 3, 5, 5))],
                   [("y", tuple(want.shape))], {"x": x.numpy()})[0]
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-4)


class TestBlockRearrange:
    def test_depth_to_space_dcr_matches_torch(self):
        x = torch.randn(1, 8, 3, 3)
        want = torch.nn.functional.pixel_shuffle(x, 2).numpy()
        # torch pixel_shuffle == ONNX DepthToSpace mode=CRD
        nodes = [encode_node("DepthToSpace", ["x"], ["y"], "d2s",
                             blocksize=2, mode="CRD")]
        got = _run(nodes, {}, [("x", (1, 8, 3, 3))],
                   [("y", (1, 2, 6, 6))], {"x": x.numpy()})[0]
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)

    def test_space_to_depth_roundtrip_with_dcr(self):
        x = R.randn(1, 2, 4, 4).astype(np.float32)
        nodes = [
            encode_node("SpaceToDepth", ["x"], ["s"], "s2d",
                        blocksize=2),
            encode_node("DepthToSpace", ["s"], ["y"], "d2s",
                        blocksize=2, mode="DCR"),
        ]
        got = _run(nodes, {}, [("x", (1, 2, 4, 4))],
                   [("y", (1, 2, 4, 4))], {"x": x})[0]
        np.testing.assert_allclose(np.asarray(got), x, atol=1e-6)
