"""Tensor-parallelism tests (SURVEY.md §2.6 P7 — TPU-native extension).

Every TP-sharded form must match its single-device (tp=1) equivalent,
forward AND backward, on the virtual 8-device CPU mesh (conftest)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel import make_mesh
from deeplearning4j_tpu.parallel.mesh import shard_map as _shard_map
from deeplearning4j_tpu.parallel.tensor import (
    init_tp_block_params, tp_mlp, tp_self_attention,
    tp_transformer_block)

B, T, D, H, FF = 2, 16, 32, 4, 64


def _x(seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(B, T, D).astype(np.float32))


def _ref_params():
    """tp=1 params (the full weights every sharded run slices)."""
    return init_tp_block_params(jax.random.PRNGKey(7), D, H, FF,
                                tp=1, tp_rank=0)


def _run_sharded(fn, x, tp, sequence_parallel=False):
    """Run fn(params_shard, x) under shard_map over a model axis of
    size ``tp``; params are built per-rank inside the shard_map so each
    device holds only its slice."""
    mesh = make_mesh({"model": tp}, jax.devices()[:tp])

    def body(xs):
        rank = jax.lax.axis_index("model")
        params = init_tp_block_params(jax.random.PRNGKey(7), D, H, FF,
                                      tp=tp, tp_rank=rank)
        return fn(params, xs)

    in_spec = P(None, "model", None) if sequence_parallel else P()
    out_spec = in_spec
    return _shard_map(body, mesh, in_specs=(in_spec,),
                      out_specs=out_spec)(x)


class TestTpMlp:
    @pytest.mark.parametrize("tp", [2, 4])
    def test_matches_dense(self, tp):
        x = _x()
        ref = tp_mlp_ref(x)
        out = _run_sharded(
            lambda p, xs: tp_mlp(xs, p["mlp"]), x, tp)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_sequence_parallel_matches(self):
        x = _x()
        ref = tp_mlp_ref(x)
        out = _run_sharded(
            lambda p, xs: tp_mlp(xs, p["mlp"], sequence_parallel=True),
            x, tp=4, sequence_parallel=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)


def tp_mlp_ref(x):
    p = _ref_params()
    return tp_mlp_local(x, p["mlp"])


def tp_mlp_local(x, mp):
    return jax.nn.gelu(x @ mp["Wi"] + mp["bi"]) @ mp["Wo"] + mp["bo"]


def attn_ref(x):
    return attn_ref_p(x, _ref_params()["attn"])


class TestTpAttention:
    @pytest.mark.parametrize("tp", [2, 4])
    def test_matches_dense(self, tp):
        x = _x()
        out = _run_sharded(
            lambda p, xs: tp_self_attention(xs, p["attn"], H // tp),
            x, tp)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(attn_ref(x)), atol=1e-5)

    def test_sequence_parallel_matches(self):
        x = _x()
        out = _run_sharded(
            lambda p, xs: tp_self_attention(xs, p["attn"], H // 2,
                                            sequence_parallel=True),
            x, tp=2, sequence_parallel=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(attn_ref(x)), atol=1e-5)


class TestTpBlock:
    def block_ref(self, x):
        p = _ref_params()
        from deeplearning4j_tpu.parallel.tensor import layer_norm
        h = layer_norm(x, p["ln1_g"], p["ln1_b"])
        x = x + attn_ref_p(h, p["attn"])
        h = layer_norm(x, p["ln2_g"], p["ln2_b"])
        return x + tp_mlp_local(h, p["mlp"])

    @pytest.mark.parametrize("sp", [False, True])
    def test_matches_dense(self, sp):
        x = _x(3)
        tp = 2
        out = _run_sharded(
            lambda p, xs: tp_transformer_block(
                xs, p, H // tp, sequence_parallel=sp),
            x, tp, sequence_parallel=sp)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(self.block_ref(x)),
                                   atol=2e-5)

    def test_gradients_match(self):
        """Backward through the sharded block == backward through the
        dense block (shard_map transposes the collectives)."""
        x = _x(5)
        tp = 2

        def loss_sharded(xs):
            out = _run_sharded(
                lambda p, z: tp_transformer_block(z, p, H // tp), xs, tp)
            return jnp.sum(out ** 2)

        def loss_ref(xs):
            return jnp.sum(self.block_ref(xs) ** 2)

        g1 = jax.grad(loss_sharded)(x)
        g2 = jax.grad(loss_ref)(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=5e-4, rtol=1e-4)


def attn_ref_p(x, p):
    from deeplearning4j_tpu.ops.attention import dot_product_attention
    dh = D // H

    def heads(a):
        return a.reshape(B, T, H, dh).transpose(0, 2, 1, 3)

    o = dot_product_attention(heads(x @ p["Wq"]), heads(x @ p["Wk"]),
                              heads(x @ p["Wv"]))
    return o.transpose(0, 2, 1, 3).reshape(B, T, D) @ p["Wo"] + p["bo"]
