"""SameDiff graph-layer tests (SURVEY.md §2.3 S1-S5, §4.3 op-validation
pattern: forward values AND analytic-vs-numeric gradients)."""
import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.autodiff import (OP_REGISTRY, SameDiff,
                                         TrainingConfig, VariableType,
                                         op_coverage)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.learning.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.weights import WeightInit


def test_build_and_eval_arithmetic():
    sd = SameDiff.create()
    a = sd.var("a", array=np.array([1.0, 2.0, 3.0]))
    b = sd.constant("b", np.array([10.0, 20.0, 30.0]))
    c = (a + b) * 2.0
    out = c.eval()
    np.testing.assert_allclose(out, [22.0, 44.0, 66.0])


def test_placeholder_mlp_forward():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    w = sd.var("w", shape=(4, 3), init=WeightInit.XAVIER)
    b = sd.var("b", array=np.zeros(3, np.float32))
    logits = sd.nn.linear(x, w, b, name="logits")
    probs = sd.nn.softmax(logits, name="probs")
    xv = np.random.RandomState(0).randn(5, 4).astype(np.float32)
    res = sd.output({"x": xv}, [probs.name])
    assert res[probs.name].shape == (5, 3)
    np.testing.assert_allclose(res[probs.name].sum(-1), np.ones(5),
                               rtol=1e-5)


def test_whole_graph_is_one_jit_cache_entry():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    y = sd.math.tanh(x * 2.0)
    xv = np.ones((3, 4), np.float32)
    sd.output({"x": xv}, [y.name])
    sd.output({"x": xv}, [y.name])          # same sig -> cached
    assert len(sd._exec_cache) == 1
    sd.output({"x": np.ones((6, 4), np.float32)}, [y.name])
    assert len(sd._exec_cache) == 2


def test_analytic_vs_numeric_gradient():
    """The §4.3 OpValidation pattern: finite-difference check."""
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 3))
    w = sd.var("w", array=np.array([[0.5], [-1.0], [2.0]], np.float32))
    out = sd.math.sigmoid(x @ w)
    loss = out.sum()
    sd.set_loss_variables(loss.name)
    xv = np.random.RandomState(1).randn(4, 3).astype(np.float32)
    g = sd.calculate_gradients({"x": xv}, ["w"])["w"]

    eps = 1e-3
    w0 = sd.get_variable("w").get_arr()
    num = np.zeros_like(w0)
    for i in range(3):
        for sgn, acc in ((1, 1), (-1, -1)):
            wp = w0.copy()
            wp[i, 0] += sgn * eps
            sd.get_variable("w").set_arr(wp)
            num[i, 0] += acc * sd.output({"x": xv},
                                         [loss.name])[loss.name]
    sd.get_variable("w").set_arr(w0)
    num /= 2 * eps
    np.testing.assert_allclose(g, num, rtol=1e-2, atol=1e-3)


def test_fit_linear_regression():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 2))
    y = sd.placeholder("y", shape=(None, 1))
    w = sd.var("w", array=np.zeros((2, 1), np.float32))
    b = sd.var("b", array=np.zeros((1,), np.float32))
    pred = x @ w + b
    loss = sd.loss.mean_squared_error(y, pred, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(
        TrainingConfig.Builder().updater(Adam(0.1))
        .data_set_feature_mapping("x").data_set_label_mapping("y")
        .build())
    rng = np.random.RandomState(0)
    xv = rng.randn(256, 2).astype(np.float32)
    true_w = np.array([[2.0], [-3.0]], np.float32)
    yv = xv @ true_w + 0.5
    it = ListDataSetIterator([DataSet(xv[i:i + 64], yv[i:i + 64])
                              for i in range(0, 256, 64)])
    hist = sd.fit(it, n_epochs=60)
    assert hist.final_loss() < 1e-2
    np.testing.assert_allclose(sd.get_variable("w").get_arr(), true_w,
                               atol=0.1)
    np.testing.assert_allclose(sd.get_variable("b").get_arr(), [0.5],
                               atol=0.1)


def test_save_load_roundtrip(tmp_path):
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    w = sd.var("w", shape=(4, 2), init=WeightInit.XAVIER)
    out = sd.nn.softmax(x @ w, name="out")
    sd.set_loss_variables("out")
    sd.set_training_config(
        TrainingConfig.Builder().updater(Sgd(0.01))
        .data_set_feature_mapping("x").build())
    xv = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    before = sd.output({"x": xv}, ["out"])["out"]

    p = str(tmp_path / "model.sdz")
    sd.save(p)
    sd2 = SameDiff.load(p)
    after = sd2.output({"x": xv}, ["out"])["out"]
    np.testing.assert_allclose(after, before, rtol=1e-6)
    assert sd2.training_config.updater == Sgd(0.01)
    assert sd2.loss_variables == ["out"]


def test_save_load_resumes_updater_state(tmp_path):
    """load must restore optimizer moments, not reset them (reference
    contract: .fb carries updater state)."""
    def make():
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 2))
        y = sd.placeholder("y", shape=(None, 1))
        w = sd.var("w", array=np.zeros((2, 1), np.float32))
        loss = sd.loss.mean_squared_error(y, x @ w, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(
            TrainingConfig.Builder().updater(Adam(0.05))
            .data_set_feature_mapping("x").data_set_label_mapping("y")
            .build())
        return sd

    rng = np.random.RandomState(0)
    xv = rng.randn(32, 2).astype(np.float32)
    yv = (xv @ np.array([[1.0], [2.0]], np.float32))
    it = ListDataSetIterator([DataSet(xv, yv)])

    sd = make()
    sd.fit(it, n_epochs=3)
    p = str(tmp_path / "resume.sdz")
    sd.save(p)
    sd.fit(it, n_epochs=2)                       # continue in-memory
    expected = sd.get_variable("w").get_arr()

    sd2 = SameDiff.load(p)
    sd2.fit(it, n_epochs=2)                      # resume from disk
    np.testing.assert_allclose(sd2.get_variable("w").get_arr(),
                               expected, rtol=1e-5, atol=1e-6)


def test_multi_output_ops():
    sd = SameDiff.create()
    x = sd.var("x", array=np.arange(12, dtype=np.float32).reshape(3, 4))
    parts = sd.math.split(x, 2, axis=1)
    assert len(parts) == 2
    np.testing.assert_allclose(parts[0].eval(),
                               np.arange(12).reshape(3, 4)[:, :2])
    m, v = sd.math.moments(x, axis=0)
    np.testing.assert_allclose(
        m.eval(), np.arange(12).reshape(3, 4).mean(0), rtol=1e-6)


def test_attention_op():
    sd = SameDiff.create()
    b, t, d, h = 2, 5, 8, 2
    x = sd.placeholder("x", shape=(None, t, d))
    rng = np.random.RandomState(0)

    def w():
        return rng.randn(d, d).astype(np.float32) * 0.1

    wq, wk, wv, wo = (sd.constant(w()) for _ in range(4))
    att = sd.nn.multi_head_dot_product_attention(x, wq, wk, wv, wo,
                                                 num_heads=h)
    mask = sd.placeholder("mask", shape=(None, t))
    att_m = sd.nn.multi_head_dot_product_attention(
        x, wq, wk, wv, wo, num_heads=h, mask=mask)
    xv = rng.randn(b, t, d).astype(np.float32)
    out = sd.output({"x": xv}, [att.name])[att.name]
    assert out.shape == (b, t, d)
    mv = np.ones((b, t), np.float32)
    mv[:, -2:] = 0
    out_m = sd.output({"x": xv, "mask": mv}, [att_m.name])[att_m.name]
    assert np.isfinite(out_m).all()


def test_dropout_training_vs_inference():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 50))
    y = sd.nn.dropout(x, rate=0.5)
    xv = np.ones((4, 50), np.float32)
    inference = sd.output({"x": xv}, [y.name])[y.name]
    np.testing.assert_allclose(inference, xv)   # no-op at inference
    train = sd.output({"x": xv}, [y.name], training=True)[y.name]
    assert (train == 0).sum() > 0               # some dropped
    kept = train[train != 0]
    np.testing.assert_allclose(kept, 2.0)        # inverted scaling


def test_op_coverage_domains():
    """§4.3 coverage accounting: every Appendix-A domain populated."""
    cov = op_coverage()
    for domain in ("arithmetic", "transform", "activation", "blas",
                   "linalg", "reduce", "indexreduce", "boolean",
                   "bitwise", "shape", "segment", "normalization",
                   "convolution", "image", "random", "loss",
                   "attention", "recurrent", "compression"):
        assert cov.get(domain, 0) > 0, f"empty op domain {domain}"
    assert len(OP_REGISTRY) >= 180


def test_rename_and_summary():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 2))
    y = sd.math.tanh(x).rename("activation_out")
    assert sd.has_variable("activation_out")
    res = sd.output({"x": np.zeros((1, 2), np.float32)},
                    ["activation_out"])
    assert "activation_out" in res
    assert "activation_out" in sd.summary()


def test_unknown_op_raises():
    sd = SameDiff.create()
    a = sd.var("a", array=np.ones(3))
    with pytest.raises(KeyError):
        sd._op("definitely_not_an_op", [a])


def test_constant_set_arr_invalidates_cache():
    """set_arr on a CONSTANT must not serve stale cached executions."""
    sd = SameDiff.create()
    c = sd.constant("c", np.float32(1.0))
    y = c + 1.0
    assert float(y.eval()) == 2.0
    c.set_arr(np.float32(5.0))
    assert float(y.eval()) == 6.0


def test_fit_does_not_touch_unrelated_branch():
    """Variables outside the loss subgraph keep their values even with
    l2 regularization configured (code-review regression)."""
    from deeplearning4j_tpu.autodiff.training import TrainingConfig
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 2))
    w = sd.var("w", array=np.ones((2, 1), np.float32))
    other = sd.var("other", array=np.full((3,), 7.0, np.float32))
    pred = x @ w
    lbl = sd.placeholder("y", shape=(None, 1))
    loss = sd.loss.mean_squared_error(lbl, pred, name="loss")
    sd.set_loss_variables([loss.name])
    sd.set_training_config(
        TrainingConfig(updater=Sgd(0.1), l2=0.1,
                       data_set_feature_mapping=["x"],
                       data_set_label_mapping=["y"]))
    it = ListDataSetIterator([DataSet(np.ones((4, 2), np.float32),
                                      np.zeros((4, 1), np.float32))])
    sd.fit(it, n_epochs=1)
    np.testing.assert_array_equal(other.get_arr(),
                                  np.full((3,), 7.0, np.float32))
    assert not np.allclose(w.get_arr(), np.ones((2, 1)))


def test_nms_pads_with_minus_one():
    from deeplearning4j_tpu.autodiff.registry import get_op
    boxes = np.array([[0, 0, 1, 1], [0, 0, 1, 1.01]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    out = np.asarray(get_op("non_max_suppression")(
        [jnp.asarray(boxes), jnp.asarray(scores)],
        {"max_output_size": 5, "iou_threshold": 0.5}))
    assert out[0] == 0
    assert all(out[1:] == -1)  # second box suppressed, rest padded


def test_fit_steps_matches_sequential_fit():
    """One fori-loop dispatch of n steps == n sequential fit steps on
    the same batch (the benchmark-grade loop must not change the
    math; rng only matters for dropout, absent here)."""
    def build():
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 2))
        y = sd.placeholder("y", shape=(None, 1))
        w = sd.var("w", array=np.zeros((2, 1), np.float32))
        b = sd.var("b", array=np.zeros((1,), np.float32))
        sd.loss.mean_squared_error(y, x @ w + b, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(
            TrainingConfig.Builder().updater(Adam(0.1))
            .data_set_feature_mapping("x")
            .data_set_label_mapping("y").build())
        return sd

    rng = np.random.RandomState(0)
    xv = rng.randn(64, 2).astype(np.float32)
    yv = (xv @ np.array([[2.0], [-3.0]], np.float32)) + 0.5
    batch = {"x": xv, "y": yv}

    sd_seq = build()
    it = ListDataSetIterator([DataSet(xv, yv)] * 7)
    hist = sd_seq.fit(it, n_epochs=1)
    seq_final = hist.loss_curve()[-1]

    sd_multi = build()
    multi_final = sd_multi.fit_steps(batch, 7)
    np.testing.assert_allclose(multi_final, seq_final,
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(sd_multi.get_variable("w").get_arr()),
        np.asarray(sd_seq.get_variable("w").get_arr()),
        rtol=1e-5, atol=1e-6)


def test_fit_steps_then_fit_shares_updater_state():
    """fit_steps updates persist: a following fit() resumes from the
    advanced variables (and the updater state tree already exists)."""
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 2))
    y = sd.placeholder("y", shape=(None, 1))
    w = sd.var("w", array=np.zeros((2, 1), np.float32))
    sd.loss.mean_squared_error(y, x @ w, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(
        TrainingConfig.Builder().updater(Adam(0.1))
        .data_set_feature_mapping("x")
        .data_set_label_mapping("y").build())
    rng = np.random.RandomState(1)
    xv = rng.randn(64, 2).astype(np.float32)
    yv = (xv @ np.array([[1.0], [2.0]], np.float32))
    first = sd.fit_steps({"x": xv, "y": yv}, 5)
    hist = sd.fit(ListDataSetIterator([DataSet(xv, yv)] * 3),
                  n_epochs=1)
    assert hist.loss_curve()[-1] < first


def test_bf16_variables_keep_dtype_through_training():
    """Updater math runs in f32 (bias corrections), but a bf16
    variable must come back bf16 from every step — the silent
    f32 promotion recompiled the step per fit() call and broke
    fit_steps' fori carry (round-4 regression)."""
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 2))
    y = sd.placeholder("y", shape=(None, 1))
    w = sd.var("w", array=np.zeros((2, 1), np.float32))
    sd.loss.mean_squared_error(y, x @ w, name="loss")
    sd.set_loss_variables("loss")
    sd.convert_to_variables(
        ["w"], {"w": np.zeros((2, 1)).astype("bfloat16")})
    sd.set_training_config(
        TrainingConfig.Builder().updater(Adam(0.1))
        .data_set_feature_mapping("x")
        .data_set_label_mapping("y").build())
    rng = np.random.RandomState(0)
    xv = rng.randn(32, 2).astype(np.float32)
    yv = (xv @ np.array([[2.0], [-3.0]], np.float32))
    sd.fit(ListDataSetIterator([DataSet(xv, yv)] * 2), n_epochs=1)
    assert str(sd.get_variable("w").get_arr().dtype) == "bfloat16"
    sd.fit_steps({"x": xv, "y": yv}, 3)   # fori carry needs it too
    assert str(sd.get_variable("w").get_arr().dtype) == "bfloat16"


def test_set_training_config_evicts_fit_steps_cache():
    """A new TrainingConfig must invalidate the cached fori-loop
    program too — the updater/lr are baked into it (code-review
    regression: only ("train", ...) entries were evicted)."""
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 2))
    y = sd.placeholder("y", shape=(None, 1))
    sd.var("w", array=np.zeros((2, 1), np.float32))
    sd.loss.mean_squared_error(y, x @ sd.get_variable("w"),
                               name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(
        TrainingConfig.Builder().updater(Sgd(0.0))
        .data_set_feature_mapping("x")
        .data_set_label_mapping("y").build())
    rng = np.random.RandomState(0)
    xv = rng.randn(32, 2).astype(np.float32)
    yv = (xv @ np.array([[2.0], [-3.0]], np.float32))
    batch = {"x": xv, "y": yv}
    sd.fit_steps(batch, 3)          # lr=0: w must not move
    w0 = np.asarray(sd.get_variable("w").get_arr()).copy()
    assert np.all(w0 == 0.0)
    sd.set_training_config(
        TrainingConfig.Builder().updater(Sgd(0.5))
        .data_set_feature_mapping("x")
        .data_set_label_mapping("y").build())
    sd.fit_steps(batch, 3)          # must recompile with lr=0.5
    w1 = np.asarray(sd.get_variable("w").get_arr())
    assert np.any(w1 != 0.0), "stale fori program kept lr=0"


def test_fit_steps_data_parallel_matches_single_device():
    """fit_steps(mesh=...) shards the batch over the mesh's data axis
    with replicated variables; results must match the single-device
    run (GSPMD's all-reduced grads == the unsharded sum)."""
    from conftest import require_devices
    require_devices(8)
    import jax
    from deeplearning4j_tpu.parallel import make_mesh

    def build():
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 2))
        y = sd.placeholder("y", shape=(None, 1))
        w = sd.var("w", array=np.zeros((2, 1), np.float32))
        b = sd.var("b", array=np.zeros((1,), np.float32))
        sd.loss.mean_squared_error(y, x @ w + b, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(
            TrainingConfig.Builder().updater(Adam(0.1))
            .data_set_feature_mapping("x")
            .data_set_label_mapping("y").build())
        return sd

    rng = np.random.RandomState(0)
    xv = rng.randn(64, 2).astype(np.float32)
    yv = (xv @ np.array([[2.0], [-3.0]], np.float32)) + 0.5
    batch = {"x": xv, "y": yv}

    single = build()
    l_single = single.fit_steps(batch, 6)

    mesh = make_mesh({"data": 8}, jax.devices()[:8])
    dp = build()
    l_dp = dp.fit_steps(batch, 6, mesh=mesh)
    np.testing.assert_allclose(l_dp, l_single, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(dp.get_variable("w").get_arr()),
        np.asarray(single.get_variable("w").get_arr()),
        rtol=1e-5, atol=1e-6)

    # indivisible batch must be rejected loudly
    bad = {"x": xv[:60], "y": yv[:60]}
    try:
        dp.fit_steps(bad, 1, mesh=mesh)
        assert False, "expected ValueError for indivisible batch"
    except ValueError:
        pass


def test_fit_steps_data_parallel_replicates_scalar_placeholder():
    """Scalar placeholders (loss scales, rate knobs) replicate under
    fit_steps(mesh=...) instead of being rejected (code-review
    regression — the inline sharding predated `shard_batch`)."""
    from conftest import require_devices
    require_devices(8)
    import jax
    from deeplearning4j_tpu.parallel import make_mesh
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 2))
    y = sd.placeholder("y", shape=(None, 1))
    s = sd.placeholder("s", shape=())
    w = sd.var("w", array=np.zeros((2, 1), np.float32))
    sd._op("mul", [sd.loss.mean_squared_error(y, x @ w), s]) \
        .rename("sloss")
    sd.set_loss_variables("sloss")
    sd.set_training_config(
        TrainingConfig.Builder().updater(Adam(0.1))
        .data_set_feature_mapping("x")
        .data_set_label_mapping("y").build())
    rng = np.random.RandomState(0)
    xv = rng.randn(64, 2).astype(np.float32)
    yv = xv @ np.array([[2.0], [-3.0]], np.float32)
    mesh = make_mesh({"data": 8}, jax.devices()[:8])
    l = sd.fit_steps({"x": xv, "y": yv, "s": np.float32(1.0)}, 5,
                     mesh=mesh)
    assert np.isfinite(l)


def test_output_data_parallel_matches_single_device():
    """output(mesh=...) — DP batched inference: identical results to
    the single-device run, scalars replicate."""
    from conftest import require_devices
    require_devices(8)
    import jax
    from deeplearning4j_tpu.parallel import make_mesh
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    w = sd.var("w", array=np.arange(8, dtype=np.float32)
               .reshape(4, 2))
    sd.nn.softmax(x @ w, name="probs")
    rng = np.random.RandomState(0)
    xv = rng.randn(64, 4).astype(np.float32)
    want = sd.output({"x": xv}, ["probs"])["probs"]
    mesh = make_mesh({"data": 8}, jax.devices()[:8])
    got = sd.output({"x": xv}, ["probs"], mesh=mesh)["probs"]
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_chained_fit_steps_matches_single_call():
    """The updater iteration persists across fit_steps calls: two
    fit_steps(batch, 5) == one fit_steps(batch, 10) (Adam's
    bias-correction warmup must not restart per call — r4 advisor
    finding)."""
    def build():
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 2))
        y = sd.placeholder("y", shape=(None, 1))
        w = sd.var("w", array=np.zeros((2, 1), np.float32))
        sd.loss.mean_squared_error(y, x @ w, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(
            TrainingConfig.Builder().updater(Adam(0.1))
            .data_set_feature_mapping("x")
            .data_set_label_mapping("y").build())
        return sd

    rng = np.random.RandomState(0)
    xv = rng.randn(64, 2).astype(np.float32)
    yv = xv @ np.array([[2.0], [-3.0]], np.float32)
    batch = {"x": xv, "y": yv}

    chained = build()
    chained.fit_steps(batch, 5)
    chained.fit_steps(batch, 5)
    assert chained.iteration_count == 10

    single = build()
    single.fit_steps(batch, 10)
    np.testing.assert_allclose(
        np.asarray(chained.get_variable("w").get_arr()),
        np.asarray(single.get_variable("w").get_arr()),
        rtol=1e-5, atol=1e-6)


def test_fit_continues_iteration_after_fit_steps():
    """fit() after fit_steps() continues the shared iteration counter
    instead of restarting Adam warmup at 0."""
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 2))
    y = sd.placeholder("y", shape=(None, 1))
    w = sd.var("w", array=np.zeros((2, 1), np.float32))
    sd.loss.mean_squared_error(y, x @ w, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(
        TrainingConfig.Builder().updater(Adam(0.1))
        .data_set_feature_mapping("x")
        .data_set_label_mapping("y").build())
    rng = np.random.RandomState(1)
    xv = rng.randn(32, 2).astype(np.float32)
    yv = xv @ np.array([[1.0], [2.0]], np.float32)
    sd.fit_steps({"x": xv, "y": yv}, 4)
    it = ListDataSetIterator([DataSet(xv, yv)] * 3)
    sd.fit(it, n_epochs=1)
    assert sd.iteration_count == 7


def test_fit_steps_mesh_replicates_non_batch_placeholder():
    """A non-batch placeholder whose leading dim is NOT divisible by
    the data axis (e.g. a [n_classes] weight vector) replicates
    instead of being rejected (r4 advisor finding: only BATCH
    placeholders need the divisibility contract)."""
    from conftest import require_devices
    require_devices(8)
    import jax
    from deeplearning4j_tpu.parallel import make_mesh
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 2))
    y = sd.placeholder("y", shape=(None, 1))
    cw = sd.placeholder("cw", shape=(3,))      # len 3: not % 8
    w = sd.var("w", array=np.zeros((2, 1), np.float32))
    err = sd.loss.mean_squared_error(y, x @ w)
    sd._op("mul", [err, sd.math.reduce_sum(cw)]).rename("loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(
        TrainingConfig.Builder().updater(Adam(0.1))
        .data_set_feature_mapping("x")
        .data_set_label_mapping("y").build())
    rng = np.random.RandomState(0)
    xv = rng.randn(64, 2).astype(np.float32)
    yv = xv @ np.array([[2.0], [-3.0]], np.float32)
    mesh = make_mesh({"data": 8}, jax.devices()[:8])
    l = sd.fit_steps({"x": xv, "y": yv,
                      "cw": np.ones(3, np.float32) / 3}, 5, mesh=mesh)
    assert np.isfinite(l)
    # the real batch stays guarded: indivisible BATCH still raises
    try:
        sd.fit_steps({"x": xv[:60], "y": yv[:60],
                      "cw": np.ones(3, np.float32)}, 1, mesh=mesh)
        assert False, "expected ValueError for indivisible batch"
    except ValueError:
        pass


def test_fuse_attention_patterns_rewrites_and_matches():
    """Graph-optimization pass (reference role: GraphOptimizer): the
    exporter attention chain matmul(q,k,T)->div->add(bias)->softmax->
    matmul(.,v) fuses to ONE sdpa_core op with identical outputs;
    non-matching softmaxes are left alone."""
    rng = np.random.RandomState(0)

    def build():
        sd = SameDiff.create()
        q = sd.placeholder("q", shape=(2, 3, 8, 4))
        k = sd.placeholder("k", shape=(2, 3, 8, 4))
        v = sd.placeholder("v", shape=(2, 3, 8, 4))
        bias = sd.placeholder("bias", shape=(2, 1, 1, 8))
        scores = sd._op("matmul", [q, k],
                        {"transpose_a": False, "transpose_b": True})
        scaled = sd._op("div", [scores, sd.constant(
            "scale_c", np.float32(2.0))])
        biased = sd.math.add(scaled, bias)
        probs = sd.nn.softmax(biased)
        ctx = sd._op("matmul", [probs, v]).rename("ctx")
        # an unrelated softmax that must NOT be touched
        sd.nn.softmax(sd.math.reduce_sum(ctx, axis=-1),
                      name="other_sm")
        return sd

    feeds = {"q": rng.randn(2, 3, 8, 4).astype(np.float32),
             "k": rng.randn(2, 3, 8, 4).astype(np.float32),
             "v": rng.randn(2, 3, 8, 4).astype(np.float32),
             "bias": rng.randn(2, 1, 1, 8).astype(np.float32)}
    sd = build()
    want = sd.output(feeds, ["ctx", "other_sm"])
    n = sd.fuse_attention_patterns()
    assert n == 1
    fused_ops = [o for o in sd.ops if o.op_name == "sdpa_core"]
    assert len(fused_ops) == 1
    assert fused_ops[0].attrs["scale"] == 0.5      # 1 / div-const
    got = sd.output(feeds, ["ctx", "other_sm"])
    for kk in want:
        np.testing.assert_allclose(np.asarray(got[kk]),
                                   np.asarray(want[kk]),
                                   rtol=1e-5, atol=1e-6)
    # idempotent: a second pass finds nothing
    assert sd.fuse_attention_patterns() == 0


def test_fuse_attention_skips_multi_consumer_probs():
    """If the softmax probabilities feed anything besides the context
    matmul (e.g. attention visualization), the site must NOT fuse."""
    sd = SameDiff.create()
    q = sd.placeholder("q", shape=(1, 2, 4, 4))
    k = sd.placeholder("k", shape=(1, 2, 4, 4))
    v = sd.placeholder("v", shape=(1, 2, 4, 4))
    scores = sd._op("matmul", [q, k],
                    {"transpose_a": False, "transpose_b": True})
    scaled = sd._op("mul", [scores, sd.constant(
        "c", np.float32(0.5))])
    probs = sd.nn.softmax(scaled)
    sd._op("matmul", [probs, v]).rename("ctx")
    sd.math.reduce_sum(probs, name="viz")          # second consumer
    assert sd.fuse_attention_patterns() == 0


def test_shard_placeholders_warns_on_batch_dim_tie(caplog):
    """Inferred batch-dim votes can tie OR be outvoted by aux
    placeholders; the losers are silently replicated (no DP sharding,
    no divisibility check) — that must at least WARN, pointing at
    explicit mappings (ADVICE.md r5)."""
    import logging
    from conftest import require_devices
    require_devices(2)
    from deeplearning4j_tpu.autodiff.samediff import _shard_placeholders
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"data": 2}, __import__("jax").devices()[:2])
    ph = {"a": jnp.ones((4, 8)), "b": jnp.ones((6, 8))}
    with caplog.at_level(logging.WARNING, "deeplearning4j_tpu"):
        _shard_placeholders(mesh, ph)
    assert any("replicated" in r.message for r in caplog.records)
    # the aux-outvote case: two aux tensors sharing a leading dim
    # outvote the true batch tensor, which gets replicated — warn too
    caplog.clear()
    ph3 = {"x": jnp.ones((4, 8)), "aux1": jnp.ones((6, 8)),
           "aux2": jnp.ones((6, 2))}
    with caplog.at_level(logging.WARNING, "deeplearning4j_tpu"):
        out, _ = _shard_placeholders(mesh, ph3)
    assert any("'x'" in r.message and "replicated" in r.message
               for r in caplog.records)
    # explicit batch_names: unambiguous, no warning
    caplog.clear()
    with caplog.at_level(logging.WARNING, "deeplearning4j_tpu"):
        _shard_placeholders(mesh, ph, batch_names=["a"])
    assert not any("replicated" in r.message for r in caplog.records)


def test_shard_placeholders_explicit_specs(caplog):
    """Explicit placeholder->PartitionSpec mappings bypass batch-dim
    inference entirely (the mesh-run escape hatch, ADVICE.md r5)."""
    import logging
    import jax
    from jax.sharding import PartitionSpec as P
    from conftest import require_devices
    require_devices(2)
    from deeplearning4j_tpu.autodiff.samediff import _shard_placeholders
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"data": 2}, jax.devices()[:2])
    ph = {"x": jnp.ones((4, 8)), "aux1": jnp.ones((6, 8)),
          "aux2": jnp.ones((6, 2))}
    specs = {"aux1": P(), "aux2": P()}
    with caplog.at_level(logging.WARNING, "deeplearning4j_tpu"):
        out, sig = _shard_placeholders(mesh, ph, specs=specs)
    # spec'd placeholders no longer vote: x wins, no warning
    assert not any("replicated" in r.message for r in caplog.records)
    assert out["x"].sharding.spec == P("data", None)
    assert out["aux1"].sharding.spec == P()
    # explicit specs key the compiled-program cache
    _, sig_none = _shard_placeholders(mesh, dict(ph),
                                      batch_names=["x"])
    assert sig != sig_none
    # tuple form coerces; unknown names are rejected loudly
    out2, _ = _shard_placeholders(mesh, dict(ph),
                                  specs={"x": ("data",)})
    assert out2["x"].sharding.spec == P("data")
    with pytest.raises(ValueError, match="unknown placeholder"):
        _shard_placeholders(mesh, dict(ph), specs={"nope": P()})
