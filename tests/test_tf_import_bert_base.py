"""BASELINE config #4 at REAL dimensions (round-3 verdict ask #1):
a full BERT-base (L=12, H=768, A=12, vocab 30522) GraphDef frozen by
the in-image TF must import through S6, reproduce TF's forward
outputs, and TRAIN (MLM objective, weight-tied head) as ONE jitted
program.  The toy-dim conformance lives in test_tf_import; this file
proves the import path is production-grade, not toy-grade."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import sys  # noqa: E402
import os  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from benchmarks.tf_bert_builder import (  # noqa: E402
    build_frozen_bert, import_and_attach_mlm)

SEQ, BATCH = 128, 2
VOCAB, HIDDEN, HEADS, LAYERS = 30522, 768, 12, 12


@pytest.fixture(scope="module")
def frozen():
    gd, run_tf = build_frozen_bert(SEQ, BATCH, vocab=VOCAB,
                                   hidden=HIDDEN, heads=HEADS,
                                   layers=LAYERS)
    return gd, run_tf


def _feeds(seed=3):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, VOCAB, (BATCH, SEQ)).astype(np.int32)
    seg = np.zeros((BATCH, SEQ), np.int32)
    seg[:, SEQ // 2:] = 1
    mask = np.ones((BATCH, SEQ), np.int32)
    mask[1, SEQ - 16:] = 0
    return ids, seg, mask


class TestBertBaseRealDims:
    def test_forward_conformance(self, frozen):
        """Imported forward == TF forward at real dimensions."""
        gd, run_tf = frozen
        ids, seg, mask = _feeds()
        want = run_tf(ids, seg, mask)
        from deeplearning4j_tpu.modelimport.tensorflow import \
            TensorflowFrameworkImporter
        sd = TensorflowFrameworkImporter.run_import(
            gd, {"ids": (BATCH, SEQ), "seg": (BATCH, SEQ),
                 "mask": (BATCH, SEQ)})
        out = sorted(n for n in sd.vars
                     if n.startswith("Identity"))[0]
        got = sd.output({"ids": ids, "seg": seg, "mask": mask},
                        [out])[out]
        assert got.shape == (BATCH, SEQ, HIDDEN)
        # 12 layers of f32 accumulation: slightly looser than the toy
        np.testing.assert_allclose(got, want, atol=5e-3, rtol=1e-3)

    def test_mlm_training_step_runs_and_learns(self, frozen):
        """The imported graph trains: promote frozen weights, attach
        the weight-tied MLM head, run jitted Adam steps — the loss on
        a fixed batch must drop (memorization)."""
        gd, _ = frozen
        from deeplearning4j_tpu.learning import Adam
        sd, loss_name = import_and_attach_mlm(
            gd, BATCH, SEQ, vocab=VOCAB, hidden=HIDDEN,
            updater=Adam(5e-4))
        rs = np.random.RandomState(0)
        ids, seg, mask = _feeds()
        labels = np.where(rs.rand(BATCH, SEQ) < 0.15,
                          rs.randint(0, VOCAB, (BATCH, SEQ)),
                          -1).astype(np.int32)
        batch = {"ids": ids, "seg": seg, "mask": mask,
                 "mlm_labels": labels}
        hist = sd.fit([batch] * 10, n_epochs=1,
                      placeholders_fn=lambda b: b)
        curve = hist.loss_curve()
        assert np.isfinite(curve).all()
        # ln(30522) ~ 10.3 start; 10 Adam steps on one batch must cut it
        assert curve[-1] < 0.7 * curve[0], curve


class TestGatheredMlmHead:
    """``import_and_attach_mlm(max_predictions=k)`` — the FLOP-matched
    gathered decode head the imported-model benchmark compares against
    the native model (BENCH_notes_r04.md).  Toy dims: equivalence, not
    scale (real dims are covered above)."""

    def test_gathered_head_matches_full_head_loss(self):
        vocab, hidden, heads, layers, seq, batch, k = \
            50, 16, 2, 2, 16, 2, 4
        gd, _ = build_frozen_bert(seq, batch, vocab=vocab,
                                  hidden=hidden, heads=heads,
                                  layers=layers, intermediate=32)
        rs = np.random.RandomState(7)
        ids = rs.randint(0, vocab, (batch, seq)).astype(np.int32)
        seg = np.zeros((batch, seq), np.int32)
        mask = np.ones((batch, seq), np.int32)
        positions = np.stack(
            [rs.choice(seq, k, replace=False)
             for _ in range(batch)]).astype(np.int32)
        lab_k = rs.randint(0, vocab, (batch, k)).astype(np.int32)
        # full-head labels: the same labels scattered at the gathered
        # positions, -1 (ignored) everywhere else
        lab_full = np.full((batch, seq), -1, np.int32)
        for b in range(batch):
            lab_full[b, positions[b]] = lab_k[b]

        sd_full, _ = import_and_attach_mlm(
            gd, batch, seq, vocab=vocab, hidden=hidden)
        sd_gat, _ = import_and_attach_mlm(
            gd, batch, seq, vocab=vocab, hidden=hidden,
            max_predictions=k)
        feeds = {"ids": ids, "seg": seg, "mask": mask}
        loss_full = sd_full.output(
            {**feeds, "mlm_labels": lab_full},
            ["mlm_loss"])["mlm_loss"]
        loss_gat = sd_gat.output(
            {**feeds, "mlm_positions": positions,
             "mlm_labels": lab_k},
            ["mlm_loss"])["mlm_loss"]
        np.testing.assert_allclose(loss_gat, loss_full,
                                   rtol=1e-5, atol=1e-6)

    def test_gathered_head_trains(self):
        vocab, hidden, heads, layers, seq, batch, k = \
            50, 16, 2, 2, 16, 2, 4
        gd, _ = build_frozen_bert(seq, batch, vocab=vocab,
                                  hidden=hidden, heads=heads,
                                  layers=layers, intermediate=32)
        from deeplearning4j_tpu.learning import Adam
        sd, _ = import_and_attach_mlm(
            gd, batch, seq, vocab=vocab, hidden=hidden,
            updater=Adam(1e-2), max_predictions=k)
        rs = np.random.RandomState(1)
        batch_d = {
            "ids": rs.randint(0, vocab,
                              (batch, seq)).astype(np.int32),
            "seg": np.zeros((batch, seq), np.int32),
            "mask": np.ones((batch, seq), np.int32),
            "mlm_positions": np.stack(
                [rs.choice(seq, k, replace=False)
                 for _ in range(batch)]).astype(np.int32),
            "mlm_labels": rs.randint(0, vocab,
                                     (batch, k)).astype(np.int32)}
        hist = sd.fit([batch_d] * 20, n_epochs=1,
                      placeholders_fn=lambda b: b)
        curve = hist.loss_curve()
        assert np.isfinite(curve).all()
        assert curve[-1] < 0.5 * curve[0], curve

    def test_imported_model_trains_data_parallel(self):
        """An IMPORTED program trains data-parallel over a device
        mesh via fit_steps(mesh=...) and matches the single-device
        run — import and scale-out compose (the reference's SameDiff
        is single-device; SURVEY P1 x S6)."""
        from conftest import require_devices
        require_devices(8)
        import jax
        from deeplearning4j_tpu.parallel import make_mesh
        vocab, hidden, heads, layers, seq, batch, k = \
            50, 16, 2, 2, 16, 8, 4
        gd, _ = build_frozen_bert(seq, batch, vocab=vocab,
                                  hidden=hidden, heads=heads,
                                  layers=layers, intermediate=32)
        from deeplearning4j_tpu.learning import Adam
        rs = np.random.RandomState(2)
        batch_d = {
            "ids": rs.randint(0, vocab,
                              (batch, seq)).astype(np.int32),
            "seg": np.zeros((batch, seq), np.int32),
            "mask": np.ones((batch, seq), np.int32),
            "mlm_positions": np.stack(
                [rs.choice(seq, k, replace=False)
                 for _ in range(batch)]).astype(np.int32),
            "mlm_labels": rs.randint(0, vocab,
                                     (batch, k)).astype(np.int32)}

        def build():
            sd, _ = import_and_attach_mlm(
                gd, batch, seq, vocab=vocab, hidden=hidden,
                updater=Adam(1e-2), max_predictions=k)
            return sd

        l_single = build().fit_steps(batch_d, 8)
        mesh = make_mesh({"data": 8}, jax.devices()[:8])
        l_dp = build().fit_steps(batch_d, 8, mesh=mesh)
        assert np.isfinite(l_dp)
        np.testing.assert_allclose(l_dp, l_single,
                                   rtol=1e-4, atol=1e-5)

    def test_imported_trained_model_save_load_resume(self, tmp_path):
        """Imported graph + attached head + training state survives
        sd.save/load: identical loss after restore, and training
        RESUMES (import x serialization compose — the reference's
        SameDiff.save carries updater state the same way)."""
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        from deeplearning4j_tpu.learning import Adam
        vocab, hidden, heads, layers, seq, batch, k = \
            50, 16, 2, 2, 16, 2, 4
        gd, _ = build_frozen_bert(seq, batch, vocab=vocab,
                                  hidden=hidden, heads=heads,
                                  layers=layers, intermediate=32)
        sd, _ = import_and_attach_mlm(
            gd, batch, seq, vocab=vocab, hidden=hidden,
            updater=Adam(1e-2), max_predictions=k)
        rs = np.random.RandomState(1)
        b = {"ids": rs.randint(0, vocab,
                               (batch, seq)).astype(np.int32),
             "seg": np.zeros((batch, seq), np.int32),
             "mask": np.ones((batch, seq), np.int32),
             "mlm_positions": np.stack(
                 [rs.choice(seq, k, replace=False)
                  for _ in range(batch)]).astype(np.int32),
             "mlm_labels": rs.randint(0, vocab,
                                      (batch, k)).astype(np.int32)}
        sd.fit_steps(b, 5)
        p = str(tmp_path / "imported.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        l1 = float(sd.output(b, ["mlm_loss"])["mlm_loss"])
        l2 = float(sd2.output(b, ["mlm_loss"])["mlm_loss"])
        assert abs(l1 - l2) < 1e-6, (l1, l2)
        # the UPDATER state must round-trip too (a fresh Adam would
        # also reduce the loss — discriminate via the saved leaves)
        import jax as _jax
        loaded = getattr(sd2, "_loaded_updater_leaves", None)
        assert loaded, "no updater leaves restored by load()"
        want = _jax.tree_util.tree_leaves(sd._updater_state)
        assert len(loaded) == len(want)
        for a, b_ in zip(loaded, want):
            np.testing.assert_allclose(np.asarray(a),
                                       np.asarray(b_),
                                       rtol=1e-6, atol=1e-7)
        l3 = sd2.fit_steps(b, 5)
        assert np.isfinite(l3) and l3 < l2


class TestAttentionFusion:
    """The importer's attention-pattern fusion pass on a REAL frozen
    TF graph (toy dims): every layer's attention must fuse and the
    forward/loss/training trajectory must be unchanged."""

    def test_imported_bert_fuses_all_layers_exactly(self):
        from deeplearning4j_tpu.learning import Adam
        vocab, hidden, heads, layers, seq, batch = 50, 16, 2, 3, 16, 2
        gd, _ = build_frozen_bert(seq, batch, vocab=vocab,
                                  hidden=hidden, heads=heads,
                                  layers=layers, intermediate=32)

        # optimize=False: this test exercises the MANUAL fusion entry
        # point on an untouched import (the default import now runs
        # the full GraphOptimizer pipeline, which fuses attention
        # itself — covered below and in test_graph_optimizer.py)
        def fresh():
            sd, loss = import_and_attach_mlm(
                gd, batch, seq, vocab=vocab, hidden=hidden,
                updater=Adam(1e-3), optimize=False)
            return sd, loss

        rs = np.random.RandomState(0)
        feeds = {
            "ids": rs.randint(0, vocab, (batch, seq)).astype(np.int32),
            "seg": np.zeros((batch, seq), np.int32),
            "mask": np.ones((batch, seq), np.int32),
            "mlm_labels": np.where(rs.rand(batch, seq) < 0.3,
                                   rs.randint(0, vocab, (batch, seq)),
                                   -1).astype(np.int32)}

        plain, loss_name = fresh()
        fused, _ = fresh()
        assert fused.fuse_attention_patterns() == layers

        want = plain.output(feeds, [loss_name])[loss_name]
        got = fused.output(feeds, [loss_name])[loss_name]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

        # identical TRAINING trajectory (same updater, same steps)
        lp = plain.fit_steps(feeds, 4)
        lf = fused.fit_steps(feeds, 4)
        np.testing.assert_allclose(lf, lp, rtol=1e-4, atol=1e-5)

        # the DEFAULT import path runs the optimizer pipeline and
        # fuses every layer on its own — re-fusing finds nothing
        # (full-pipeline trajectory exactness: test_graph_optimizer.py)
        auto, loss_a = import_and_attach_mlm(
            gd, batch, seq, vocab=vocab, hidden=hidden,
            updater=Adam(1e-3))
        assert auto.graphopt_counts["attention_fuse"] == layers
        assert auto.fuse_attention_patterns() == 0
        got_a = auto.output(feeds, [loss_a])[loss_a]
        np.testing.assert_allclose(got_a, want, rtol=1e-5, atol=1e-6)
