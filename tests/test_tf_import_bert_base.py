"""BASELINE config #4 at REAL dimensions (round-3 verdict ask #1):
a full BERT-base (L=12, H=768, A=12, vocab 30522) GraphDef frozen by
the in-image TF must import through S6, reproduce TF's forward
outputs, and TRAIN (MLM objective, weight-tied head) as ONE jitted
program.  The toy-dim conformance lives in test_tf_import; this file
proves the import path is production-grade, not toy-grade."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import sys  # noqa: E402
import os  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from benchmarks.tf_bert_builder import (  # noqa: E402
    build_frozen_bert, import_and_attach_mlm)

SEQ, BATCH = 128, 2
VOCAB, HIDDEN, HEADS, LAYERS = 30522, 768, 12, 12


@pytest.fixture(scope="module")
def frozen():
    gd, run_tf = build_frozen_bert(SEQ, BATCH, vocab=VOCAB,
                                   hidden=HIDDEN, heads=HEADS,
                                   layers=LAYERS)
    return gd, run_tf


def _feeds(seed=3):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, VOCAB, (BATCH, SEQ)).astype(np.int32)
    seg = np.zeros((BATCH, SEQ), np.int32)
    seg[:, SEQ // 2:] = 1
    mask = np.ones((BATCH, SEQ), np.int32)
    mask[1, SEQ - 16:] = 0
    return ids, seg, mask


class TestBertBaseRealDims:
    def test_forward_conformance(self, frozen):
        """Imported forward == TF forward at real dimensions."""
        gd, run_tf = frozen
        ids, seg, mask = _feeds()
        want = run_tf(ids, seg, mask)
        from deeplearning4j_tpu.modelimport.tensorflow import \
            TensorflowFrameworkImporter
        sd = TensorflowFrameworkImporter.run_import(
            gd, {"ids": (BATCH, SEQ), "seg": (BATCH, SEQ),
                 "mask": (BATCH, SEQ)})
        out = sorted(n for n in sd.vars
                     if n.startswith("Identity"))[0]
        got = sd.output({"ids": ids, "seg": seg, "mask": mask},
                        [out])[out]
        assert got.shape == (BATCH, SEQ, HIDDEN)
        # 12 layers of f32 accumulation: slightly looser than the toy
        np.testing.assert_allclose(got, want, atol=5e-3, rtol=1e-3)

    def test_mlm_training_step_runs_and_learns(self, frozen):
        """The imported graph trains: promote frozen weights, attach
        the weight-tied MLM head, run jitted Adam steps — the loss on
        a fixed batch must drop (memorization)."""
        gd, _ = frozen
        from deeplearning4j_tpu.learning import Adam
        sd, loss_name = import_and_attach_mlm(
            gd, BATCH, SEQ, vocab=VOCAB, hidden=HIDDEN,
            updater=Adam(5e-4))
        rs = np.random.RandomState(0)
        ids, seg, mask = _feeds()
        labels = np.where(rs.rand(BATCH, SEQ) < 0.15,
                          rs.randint(0, VOCAB, (BATCH, SEQ)),
                          -1).astype(np.int32)
        batch = {"ids": ids, "seg": seg, "mask": mask,
                 "mlm_labels": labels}
        hist = sd.fit([batch] * 10, n_epochs=1,
                      placeholders_fn=lambda b: b)
        curve = hist.loss_curve()
        assert np.isfinite(curve).all()
        # ln(30522) ~ 10.3 start; 10 Adam steps on one batch must cut it
        assert curve[-1] < 0.7 * curve[0], curve
