"""DevicePrefetcher + persistent compile cache tests (ISSUE 1).

Covers: overlap correctness (bit-identical results vs sync feeding),
donation-aliasing safety, mesh-sharded placement, reset/exhaustion,
feeder-thread exception propagation, the env off-switch, and the
persistent XLA compilation cache (entry created; a second process
compiling the same program HITS the cache)."""
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.common.environment import Environment
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (DataSetIterator,
                                                   ListDataSetIterator)
from deeplearning4j_tpu.datasets.prefetch import (DevicePrefetcher,
                                                  maybe_device_prefetch)
from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.weights import WeightInit


def _mlp_conf(seed=42):
    return (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(Sgd(1e-2))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4))
            .build())


def _batches(n=6, batch=32, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = rng.randn(batch, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, batch)]
        out.append(DataSet(x, y))
    return out


class _FailingIterator(DataSetIterator):
    """Raises from next() on the feeder thread after 2 good batches."""

    def __init__(self, good):
        super().__init__()
        self._good = good
        self._i = 0

    def reset(self):
        self._i = 0

    def has_next(self):
        return True

    def next(self):  # noqa: A003
        if self._i >= len(self._good):
            raise RuntimeError("ETL exploded")
        ds = self._good[self._i]
        self._i += 1
        return ds

    def batch(self):
        return self._good[0].num_examples()


class TestDevicePrefetcher:
    def test_yields_all_batches_in_order(self):
        data = _batches()
        pf = DevicePrefetcher(ListDataSetIterator(data), depth=2)
        seen = list(pf)
        assert len(seen) == len(data)
        for got, want in zip(seen, data):
            np.testing.assert_array_equal(np.asarray(got.features),
                                          want.features)

    def test_arrays_are_device_resident(self):
        data = _batches(n=2)
        pf = DevicePrefetcher(ListDataSetIterator(data), depth=2,
                              dtype=jnp.float32)
        ds = next(iter(pf))
        assert isinstance(ds.features, jax.Array)
        assert isinstance(ds.labels, jax.Array)
        assert ds.features.dtype == jnp.float32

    @pytest.mark.parametrize("thread_put", [False, True])
    def test_results_bit_identical_to_sync(self, thread_put):
        """Both put disciplines (consumer-side = CPU default,
        feeder-thread = accelerator default) change timing only."""
        data = _batches()
        net_sync = MultiLayerNetwork(_mlp_conf()).init()
        net_pf = MultiLayerNetwork(_mlp_conf()).init()
        net_sync.fit(ListDataSetIterator(data), n_epochs=2)
        net_pf.fit(DevicePrefetcher(ListDataSetIterator(data),
                                    dtype=net_pf._dtype,
                                    thread_put=thread_put), n_epochs=2)
        for a, b in zip(jax.tree_util.tree_leaves(net_sync.params),
                        jax.tree_util.tree_leaves(net_pf.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_donation_safety_batch_reusable(self):
        """Train-step funnels donate only params/states/updater state —
        a staged batch must survive the step and be re-feedable."""
        data = _batches(n=1)
        pf = DevicePrefetcher(ListDataSetIterator(data))
        ds = next(iter(pf))
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(ds)
        # a donated buffer would raise on access; re-fitting must work
        np.asarray(ds.features)
        net.fit(ds)
        assert np.isfinite(net.score())

    def test_mesh_sharded_placement(self):
        from conftest import require_devices
        require_devices(4)
        from deeplearning4j_tpu.parallel.mesh import make_mesh
        mesh = make_mesh({"data": 4}, jax.devices()[:4])
        data = _batches(n=2, batch=32)
        pf = DevicePrefetcher(ListDataSetIterator(data), mesh=mesh)
        ds = next(iter(pf))
        sh = ds.features.sharding
        assert sh.spec[0] == "data"
        assert len(set(d for d in sh.device_set)) == 4

    def test_reset_and_exhaustion(self):
        data = _batches(n=4)
        pf = DevicePrefetcher(ListDataSetIterator(data), depth=2)
        assert len(list(pf)) == 4
        assert not pf.has_next()            # exhausted
        with pytest.raises(StopIteration):
            pf.next()
        pf.reset()                           # restartable
        assert len(list(pf)) == 4
        pf.reset()
        pf.next()
        pf.reset()                           # reset mid-stream
        assert len(list(pf)) == 4

    def test_feeder_exception_propagates(self):
        pf = DevicePrefetcher(_FailingIterator(_batches(n=2)), depth=2)
        with pytest.raises(RuntimeError, match="ETL exploded"):
            list(pf)

    def test_env_flag_off_switch(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DEVICE_PREFETCH", "0")
        Environment.reset()
        try:
            it = ListDataSetIterator(_batches(n=2))
            assert maybe_device_prefetch(it) is it
        finally:
            Environment.reset()

    def test_maybe_wraps_iterators_only(self):
        Environment.reset()
        it = ListDataSetIterator(_batches(n=2))
        wrapped = maybe_device_prefetch(it)
        assert isinstance(wrapped, DevicePrefetcher)
        assert maybe_device_prefetch(wrapped) is wrapped
        plain = [1, 2, 3]
        assert maybe_device_prefetch(plain) is plain

    def test_async_base_is_unwrapped(self):
        """DevicePrefetcher subsumes the host-async rung: wrapping an
        AsyncDataSetIterator must not stack a second consumer thread
        on the async iterator's (possibly native) queue."""
        from deeplearning4j_tpu.datasets.iterators import \
            AsyncDataSetIterator
        data = _batches(n=3)
        base = ListDataSetIterator(data)
        pf = DevicePrefetcher(AsyncDataSetIterator(base))
        assert pf._base is base
        assert len(list(pf)) == 3

    def test_preprocessor_applied_on_feeder(self):
        class _Shift:
            def transform(self, ds):
                ds.features = np.asarray(ds.features) + 1.0

        data = _batches(n=2)
        base = ListDataSetIterator([DataSet(np.array(d.features),
                                            np.array(d.labels))
                                    for d in data])
        pf = DevicePrefetcher(base)
        pf.set_pre_processor(_Shift())
        got = next(iter(pf))
        np.testing.assert_allclose(np.asarray(got.features),
                                   data[0].features + 1.0)


class TestRetraceGuard:
    def test_warns_past_threshold(self, caplog):
        import logging
        from deeplearning4j_tpu.common.compilecache import RetraceGuard
        g = RetraceGuard("net", threshold=2)
        with caplog.at_level(logging.WARNING, "deeplearning4j_tpu"):
            for b in (1, 2, 3):
                g.record(np.zeros((b, 4)), None)
        assert g.n_signatures == 3
        assert any("distinct input signatures" in r.message
                   for r in caplog.records)
        # repeat signatures don't re-warn or re-count
        n = len(caplog.records)
        g.record(np.zeros((2, 4)), None)
        assert g.n_signatures == 3
        assert len(caplog.records) == n


_CACHE_CHILD = """
import sys, jax
import numpy as np
jax.config.update("jax_platforms", "cpu")
hits = []
from jax._src import monitoring
monitoring.register_event_listener(
    lambda ev, **kw: hits.append(ev))
from deeplearning4j_tpu.common.environment import Environment
Environment.reset()
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.activations import Activation
conf = (NeuralNetConfiguration.Builder().seed(1).updater(Sgd(1e-2))
        .weight_init(WeightInit.XAVIER).list()
        .layer(DenseLayer(n_out=8, activation=Activation.RELU))
        .layer(OutputLayer(n_out=3,
                           loss_function=LossFunction.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(4)).build())
net = MultiLayerNetwork(conf).init()
x = np.ones((8, 4), np.float32)
y = np.eye(3, dtype=np.float32)[np.zeros(8, int)]
net.fit(x, y)
print("CACHE_HITS=%d" %
      sum(1 for h in hits if h.endswith("cache_hits")))
"""


class TestPersistentCompileCache:
    def test_second_process_hits_cache(self, tmp_path):
        """The acceptance check: process 1 populates the on-disk cache,
        process 2 compiling the same network loads from it."""
        cache_dir = str(tmp_path / "xla-cache")
        env = {**os.environ,
               "DL4J_TPU_COMPILE_CACHE": "1",
               "DL4J_TPU_COMPILE_CACHE_DIR": cache_dir,
               "JAX_PLATFORMS": "cpu"}
        env.pop("PYTHONPATH", None)
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))

        def run():
            return subprocess.run(
                [sys.executable, "-c", _CACHE_CHILD], env=env,
                capture_output=True, text=True, timeout=300, cwd=root)

        r1 = run()
        assert r1.returncode == 0, r1.stderr[-2000:]
        entries = os.listdir(cache_dir)
        assert any(e.endswith("-cache") for e in entries), entries
        r2 = run()
        assert r2.returncode == 0, r2.stderr[-2000:]
        hits = int(r2.stdout.strip().rsplit("CACHE_HITS=", 1)[1])
        assert hits > 0, (r2.stdout, r2.stderr[-2000:])

    def test_cache_dir_created_and_flag_off(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.common import compilecache
        monkeypatch.setenv("DL4J_TPU_COMPILE_CACHE_DIR",
                           str(tmp_path / "cc"))
        monkeypatch.setenv("DL4J_TPU_COMPILE_CACHE", "1")
        Environment.reset()
        compilecache._reset_for_tests()
        try:
            d = compilecache.enable_persistent_cache()
            assert d == str(tmp_path / "cc")
            assert os.path.isdir(d)
            # idempotent
            assert compilecache.enable_persistent_cache() == d
            monkeypatch.setenv("DL4J_TPU_COMPILE_CACHE", "0")
            Environment.reset()
            compilecache._reset_for_tests()
            assert compilecache.enable_persistent_cache() is None
        finally:
            Environment.reset()
            compilecache._reset_for_tests()
