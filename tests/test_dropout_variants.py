"""IDropout variants + WeightNoise tests (reference test style:
TestDropout / TestWeightNoise in org.deeplearning4j.nn.conf.dropout,
SURVEY.md D1/D4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.dropout import (AlphaDropout, Dropout,
                                                GaussianDropout,
                                                GaussianNoise, IDropout,
                                                SpatialDropout,
                                                WeightNoise)
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer, Layer,
                                               OutputLayer)

K = jax.random.PRNGKey(0)
X = jax.random.normal(jax.random.PRNGKey(1), (512, 64))


class TestVariants:
    def test_dropout_zeroes_and_scales(self):
        y = np.asarray(Dropout(p=0.8).apply(X, K))
        frac_zero = (y == 0).mean()
        assert 0.1 < frac_zero < 0.3          # ~20% dropped
        kept = y[y != 0]
        x = np.asarray(X)[y != 0]
        np.testing.assert_allclose(kept, x / 0.8, rtol=1e-5)

    def test_gaussian_dropout_mean_preserving(self):
        big = jnp.ones((200_000,))
        y = np.asarray(GaussianDropout(rate=0.2).apply(big, K))
        assert abs(y.mean() - 1.0) < 0.01
        assert abs(y.std() - 0.5) < 0.02      # sqrt(0.2/0.8) = 0.5

    def test_gaussian_noise_additive(self):
        big = jnp.zeros((200_000,))
        y = np.asarray(GaussianNoise(stddev=0.3).apply(big, K))
        assert abs(y.mean()) < 0.01
        assert abs(y.std() - 0.3) < 0.01

    def test_alpha_dropout_preserves_moments(self):
        big = jax.random.normal(K, (500_000,))
        y = np.asarray(AlphaDropout(p=0.9).apply(big,
                                                 jax.random.PRNGKey(7)))
        assert abs(y.mean()) < 0.02
        assert abs(y.std() - 1.0) < 0.02

    def test_spatial_dropout_drops_whole_channels(self):
        x = jnp.ones((8, 5, 5, 16))
        y = np.asarray(SpatialDropout(p=0.5).apply(x, K))
        # per (example, channel): either all zero or all scaled
        per_chan = y.reshape(8, 25, 16)
        all_zero = (per_chan == 0).all(axis=1)
        all_kept = (per_chan == 2.0).all(axis=1)
        assert np.all(all_zero | all_kept)
        assert 0.2 < all_zero.mean() < 0.8

    def test_serde_roundtrip(self):
        layer = DenseLayer(n_in=4, n_out=3,
                           dropout=GaussianDropout(rate=0.3),
                           weight_noise=WeightNoise(stddev=0.1))
        back = Layer.from_map(layer.to_map())
        assert isinstance(back.dropout, GaussianDropout)
        assert back.dropout.rate == pytest.approx(0.3)
        assert isinstance(back.weight_noise, WeightNoise)


class TestInNetwork:
    def _net(self, **layer_kw):
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_out=16, activation=Activation.RELU,
                                  **layer_kw))
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(4))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_gaussian_dropout_trains(self):
        rng = np.random.RandomState(0)
        xs = rng.randn(128, 4).astype(np.float32)
        ys = (xs[:, 0] > 0).astype(int)
        labels = np.eye(2, dtype=np.float32)[ys]
        net = self._net(dropout=GaussianDropout(rate=0.1))
        for _ in range(60):
            net.fit(xs, labels)
        acc = (np.asarray(net.output(xs)).argmax(-1) == ys).mean()
        assert acc > 0.9

    def test_weight_noise_training_vs_inference(self):
        """Noise perturbs training forwards only; inference is clean
        and deterministic."""
        rng = np.random.RandomState(0)
        xs = rng.randn(16, 4).astype(np.float32)
        net = self._net(weight_noise=WeightNoise(stddev=0.5))
        out1 = np.asarray(net.output(xs))
        out2 = np.asarray(net.output(xs))
        np.testing.assert_array_equal(out1, out2)
        # training still converges (small noise)
        net2 = self._net(weight_noise=WeightNoise(stddev=0.02))
        ys = (xs[:, 0] > 0).astype(int)
        labels = np.eye(2, dtype=np.float32)[ys]
        for _ in range(80):
            net2.fit(xs, labels)
        acc = (np.asarray(net2.output(xs)).argmax(-1) == ys).mean()
        assert acc > 0.85

    def test_dropconnect(self):
        """DropConnect zeroes weights during training forwards."""
        wn = WeightNoise(is_dropconnect=True, p=0.5)
        params = {"W": jnp.ones((10, 10)), "b": jnp.ones((10,))}
        out = wn.apply(params, K)
        w = np.asarray(out["W"])
        assert set(np.unique(w)).issubset({0.0, 2.0})
        assert 0.2 < (w == 0).mean() < 0.8
        np.testing.assert_array_equal(np.asarray(out["b"]), 1.0)
