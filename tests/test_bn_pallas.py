"""Fused Pallas BN-backward (ops/bn_pallas.py, reference parity:
CudnnBatchNormalizationHelper.backprop — SURVEY.md D9/N8).  Off-TPU
the kernels run in interpret mode, so these tests exercise the same
code path the chip runs."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common.environment import Environment
from deeplearning4j_tpu.ops.bn_pallas import bn_train_normalize

R = np.random.RandomState(5)


def _reference_bn(x, gamma, beta, eps):
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)
    y = (x - mean) / jnp.sqrt(var + eps) * gamma + beta
    return y, mean, var


@pytest.fixture
def fused_flag():
    env = Environment.get()
    env.extra["fused_bn_bwd"] = True
    yield
    env.extra.pop("fused_bn_bwd", None)


class TestFusedBnBwd:
    @pytest.mark.parametrize("shape", [(2, 5, 5, 3),   # M=50: ragged
                                       (4, 8, 8, 16),
                                       (32, 7)])       # 2D feature BN
    def test_gradients_match_autodiff(self, shape):
        """dx/dgamma/dbeta from the hand kernels == jax autodiff of
        the plain formulation, f32."""
        x = R.randn(*shape).astype(np.float32)
        C = shape[-1]
        gamma = (1.0 + 0.1 * R.randn(C)).astype(np.float32)
        beta = (0.1 * R.randn(C)).astype(np.float32)
        ct = R.randn(*shape).astype(np.float32)

        def loss_fused(x, g, b):
            y, _, _ = bn_train_normalize(x, g, b, 1e-5)
            return jnp.sum(y * ct)

        def loss_ref(x, g, b):
            y, _, _ = _reference_bn(x, g, b, 1e-5)
            return jnp.sum(y * ct)

        got = jax.grad(loss_fused, argnums=(0, 1, 2))(x, gamma, beta)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, gamma, beta)
        for g_, w_ in zip(got, want):
            np.testing.assert_allclose(np.asarray(g_), np.asarray(w_),
                                       rtol=2e-4, atol=2e-4)

    def test_stat_cotangents_flow(self):
        """Gradients THROUGH the returned mean/var (the running-stat
        update) must match autodiff — the kernel folds the dmean/dvar
        cotangents into the dx coefficients."""
        x = R.randn(3, 4, 4, 2).astype(np.float32)
        g = np.ones(2, np.float32)
        b = np.zeros(2, np.float32)

        def loss_fused(x):
            y, mean, var = bn_train_normalize(x, g, b, 1e-5)
            return jnp.sum(y) + 3.0 * jnp.sum(mean) - 2.0 * jnp.sum(var)

        def loss_ref(x):
            y, mean, var = _reference_bn(x, g, b, 1e-5)
            return jnp.sum(y) + 3.0 * jnp.sum(mean) - 2.0 * jnp.sum(var)

        got = jax.grad(loss_fused)(x)
        want = jax.grad(loss_ref)(x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_bf16_activation(self):
        x = (R.randn(4, 6, 6, 8) * 0.5).astype(jnp.bfloat16)
        g = np.ones(8, np.float32)
        b = np.zeros(8, np.float32)
        y, mean, var = bn_train_normalize(x, g, b, 1e-5)
        assert y.dtype == jnp.bfloat16
        dx = jax.grad(lambda x: jnp.sum(
            bn_train_normalize(x, g, b, 1e-5)[0].astype(jnp.float32)))(x)
        assert dx.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(dx, np.float32)).all()

    def test_f64_gradient_check_through_layer(self, fused_flag):
        """Numeric f64 gradient check through a CNN+BN network with the
        fused path ENABLED (the verdict's acceptance bar: 'f64 gradient
        checks pass')."""
        from deeplearning4j_tpu.activations import Activation
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.learning import NoOp
        from deeplearning4j_tpu.lossfunctions import LossFunction
        from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers import (
            BatchNormalization, ConvolutionLayer, OutputLayer)
        from deeplearning4j_tpu.utils.gradientcheck import \
            GradientCheckUtil

        conf = (NeuralNetConfiguration.Builder()
                .seed(3)
                .updater(NoOp())
                .list()
                .layer(ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                        activation=Activation.IDENTITY))
                .layer(BatchNormalization(activation=Activation.TANH))
                .layer(OutputLayer(
                    n_out=2, loss_function=LossFunction.MCXENT,
                    activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional(6, 6, 2))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(0)
        ds = DataSet(rng.randn(4, 6, 6, 2).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.randint(0, 2, 4)])
        assert GradientCheckUtil.check_gradients(net, ds), \
            "f64 gradient check failed with fused BN bwd"

    def test_layer_uses_fused_path(self, fused_flag):
        """Flag on: layer forward output must equal the plain path's
        (same statistics, same normalize)."""
        from deeplearning4j_tpu.nn.conf.layers import BatchNormalization
        layer = BatchNormalization()
        x = R.randn(2, 4, 4, 3).astype(np.float32)
        params = {"gamma": jnp.ones(3), "beta": jnp.zeros(3)}
        state = {"mean": jnp.zeros(3), "var": jnp.ones(3)}
        got, st = layer.forward(params, jnp.asarray(x), training=True,
                                state=state)
        env = Environment.get()
        env.extra["fused_bn_bwd"] = False
        want, st2 = layer.forward(params, jnp.asarray(x),
                                  training=True, state=state)
        env.extra["fused_bn_bwd"] = True
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(st["mean"]),
                                   np.asarray(st2["mean"]), rtol=1e-5)
