"""Paged KV-cache pool: allocator lifecycle, exhaustion shedding,
block-table chaining, and the memory-report resident class.

The allocator tests run with ``device_arrays=False`` (pure numpy
bookkeeping, no XLA involvement) — block accounting is host logic and
should be testable at host speed. The end-to-end 429 + Retry-After
behavior rides the real server in test_generative.py.
"""
from __future__ import annotations

import numpy as np
import pytest

from deeplearning4j_tpu.common import diagnostics, telemetry
from deeplearning4j_tpu.serving.kvcache import (KVBlockPool,
                                                PoolExhausted,
                                                pool_report,
                                                pool_resident_bytes)


def _pool(num_blocks=8, block=4, **kw):
    kw.setdefault("device_arrays", False)
    return KVBlockPool(2, num_blocks, block, 2, 8, name="t", **kw)


class TestAllocator:
    def test_alloc_rounds_tokens_up_to_blocks(self):
        p = _pool()
        assert p.blocks_for(1) == 1
        assert p.blocks_for(4) == 1
        assert p.blocks_for(5) == 2
        p.alloc("a", 5)
        assert p.live_blocks == 2
        assert len(p.table("a")) == 2
        assert p.length("a") == 5

    def test_block_zero_is_never_handed_out(self):
        p = _pool(num_blocks=4)
        ids = []
        for s in ("a", "b", "c"):
            p.alloc(s, 4)
            ids.extend(p.table(s))
        assert 0 not in ids
        assert sorted(ids) == [1, 2, 3]

    def test_extend_chains_blocks_at_boundaries(self):
        p = _pool(block=4)
        p.alloc("a", 3)
        assert len(p.table("a")) == 1
        p.extend("a")                       # token 4: still block 1
        assert len(p.table("a")) == 1
        p.extend("a")                       # token 5: chains block 2
        assert len(p.table("a")) == 2
        assert p.length("a") == 5

    def test_free_returns_blocks_and_is_idempotent(self):
        p = _pool()
        p.alloc("a", 10)
        before = p.free_blocks
        assert p.free("a") == 3
        assert p.free_blocks == before + 3
        assert p.free("a") == 0             # second free is a no-op
        assert p.live_sequences == 0

    def test_exhaustion_sheds_not_partially_allocates(self):
        p = _pool(num_blocks=4)             # 3 usable
        p.alloc("a", 8)                     # 2 blocks
        free_before = p.free_blocks
        with pytest.raises(PoolExhausted) as ei:
            p.alloc("b", 8)                 # needs 2, only 1 free
        assert ei.value.reason == "kv_pool"
        assert p.free_blocks == free_before     # nothing leaked
        assert telemetry.counter(
            "dl4j_kv_pool_shed_total", "").value(pool="t") >= 1

    def test_extend_exhaustion_raises_for_that_sequence(self):
        p = _pool(num_blocks=3, block=2)    # 2 usable
        p.alloc("a", 4)                     # both blocks
        with pytest.raises(PoolExhausted):
            p.extend("a")
        assert p.length("a") == 4           # length unchanged

    def test_padded_table_is_fixed_width_scratch_padded(self):
        p = _pool(block=4)
        p.alloc("a", 6)
        row = p.padded_table("a", 5)
        assert row.dtype == np.int32 and row.shape == (5,)
        assert list(row[2:]) == [0, 0, 0]   # scratch-block padding

    def test_occupancy_and_gauges_track_alloc_free(self):
        p = _pool(num_blocks=9)             # 8 usable
        p.alloc("a", 16)                    # 4 blocks
        assert p.occupancy == pytest.approx(0.5)
        g = telemetry.gauge("dl4j_kv_pool_blocks", "")
        assert g.value(pool="t", state="live") == 4
        assert g.value(pool="t", state="free") == 4
        p.free("a")
        assert g.value(pool="t", state="live") == 0

    def test_needs_two_blocks_minimum(self):
        with pytest.raises(ValueError):
            _pool(num_blocks=1)


class TestMemoryReport:
    def test_pool_is_its_own_resident_class(self):
        p = KVBlockPool(2, 4, 4, 2, 8, name="resident-t")
        rep = diagnostics.memory_report()
        mine = [e for e in rep["kv_pools"]
                if e["pool"] == "resident-t"]
        assert len(mine) == 1
        # [n_layers, blocks, block, heads, head_dim] f32, k + v
        expect = 2 * 4 * 4 * 2 * 8 * 4 * 2
        assert mine[0]["bytes"] == expect
        assert rep["kv_pool_bytes"] >= expect
        # the pool is inside accounted_bytes, not the residual
        assert rep["accounted_bytes"] >= expect
        assert pool_resident_bytes() >= expect
        assert any(e["pool"] == "resident-t" for e in pool_report())

    def test_dropped_pool_leaves_the_report(self):
        import gc
        p = KVBlockPool(1, 2, 2, 1, 4, name="dropme",
                        device_arrays=False)
        assert any(e["pool"] == "dropme" for e in pool_report())
        del p
        gc.collect()
        assert not any(e["pool"] == "dropme" for e in pool_report())
