"""Scaling-observatory tests (ISSUE 9): per-step time decomposition,
cross-host aggregation with clock-skew handshake and straggler
detection, the clock-corrected multi-host trace merge, flight-recorder
retention, the bounded on-demand profile capture behind
``POST /api/profile``, and the regression-gate polarity of the new
``scaling`` / ``step_breakdown`` bench blocks."""
import json
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.common import stepstats, telemetry
from deeplearning4j_tpu.common.environment import Environment
from deeplearning4j_tpu.common.stepstats import (CaptureActiveError,
                                                 ProfileCapture,
                                                 StepStatsAggregator,
                                                 StepStatsClient,
                                                 estimate_clock_offset)
from deeplearning4j_tpu.common.telemetry import MetricsRegistry

_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_registry():
    # MetricsRegistry reset also resets the StepStats singleton
    MetricsRegistry._reset_for_tests()
    ProfileCapture._reset_for_tests()
    yield
    ProfileCapture._reset_for_tests()
    MetricsRegistry._reset_for_tests()


def _net_and_data(n=64):
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    rng = np.random.RandomState(0)
    x = rng.randn(n, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
         .list()
         .layer(DenseLayer(n_out=8, activation=Activation.RELU))
         .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                            loss_function=LossFunction.MCXENT))
         .set_input_type(InputType.feed_forward(4)).build())).init()
    return net, DataSet(x, y)


def _breakdown(step, step_seconds, worker=0, host=None, phases=None):
    """A hand-built worker record in the shape StepStats.close_step
    emits — what the aggregator ingests."""
    ph = {p: 0.0 for p in stepstats.PHASES}
    ph["compute"] = step_seconds
    if phases:
        ph.update(phases)
        ph["compute"] = max(step_seconds - sum(phases.values()), 0.0)
    return {"step": step, "model": "m", "worker": worker,
            "host": host or f"host{worker}", "n_workers": 3,
            "step_seconds": step_seconds,
            "total_seconds": step_seconds, "phases": ph,
            "collectives": {}}


class TestStepBreakdown:
    def test_phases_sum_to_step_time(self):
        ss = stepstats.collector()
        ss.note_data_wait(0.02, "iterator")
        ss.note_in_step("updater", 0.01)
        rec = ss.close_step("mln", 3, 0.1)
        ph = rec["phases"]
        assert ph["data_wait"] == pytest.approx(0.02)
        assert ph["updater"] == pytest.approx(0.01)
        # in-step phases subtract from the compute residual...
        assert ph["compute"] == pytest.approx(0.09)
        # ...out-of-step phases extend the total beyond the step span
        assert rec["step_seconds"] == pytest.approx(0.1)
        assert rec["total_seconds"] == pytest.approx(0.12)
        assert sum(ph.values()) == pytest.approx(rec["total_seconds"])

    def test_checkpoint_stall_routing(self):
        ss = stepstats.collector()
        ss.note_checkpoint_stall(0.05)
        rec = ss.close_step("mln", 0, 0.1)
        assert rec["phases"]["checkpoint_stall"] == pytest.approx(0.05)
        assert rec["total_seconds"] == pytest.approx(0.15)

    def test_update_exchange_counts_only_excess(self):
        # the update_exchange span WRAPS the fused step: a 0.15s span
        # around a 0.1s step is 0.05s of real collective/dispatch time
        ss = stepstats.collector()
        rec = ss.close_step("mln", 0, 0.1)
        ss.note_collective("update_exchange", 0.15)
        last = ss.last()
        assert last is rec
        assert last["phases"]["collective"] == pytest.approx(0.05)
        assert last["total_seconds"] == pytest.approx(0.15)
        assert last["collectives"]["update_exchange"] == \
            pytest.approx(0.15)

    def test_other_collective_kinds_route_to_phases(self):
        ss = stepstats.collector()
        ss.note_collective("global_assembly", 0.02)
        ss.note_collective("state_placement", 0.01)
        rec = ss.close_step("mln", 0, 0.1)
        assert rec["phases"]["host_sync"] == pytest.approx(0.02)
        assert rec["phases"]["updater"] == pytest.approx(0.01)
        assert rec["collectives"] == {"global_assembly": 0.02,
                                      "state_placement": 0.01}

    def test_disabled_collects_nothing(self):
        ss = stepstats.collector()
        ss.set_enabled(False)
        ss.note_data_wait(0.5)
        assert ss.close_step("mln", 0, 0.1) is None
        assert ss.records() == []
        ss.set_enabled(True)
        rec = ss.close_step("mln", 1, 0.1)
        # the disabled-era data_wait did not leak into this step
        assert rec["phases"]["data_wait"] == 0.0

    def test_summary_block_and_metric(self):
        ss = stepstats.collector()
        for i in range(4):
            ss.note_data_wait(0.01)
            ss.close_step("mln", i, 0.1)
        s = ss.summary()
        assert s["steps"] == 4
        assert s["mean_step_seconds"] == pytest.approx(0.1)
        assert s["mean_total_seconds"] == pytest.approx(0.11)
        assert sum(s["phases_mean_seconds"].values()) == \
            pytest.approx(s["mean_total_seconds"])
        assert s["phases_pct"]["compute"] == pytest.approx(90.9, abs=0.1)
        page = MetricsRegistry.get().render_prometheus()
        assert 'dl4j_step_phase_seconds' in page
        assert 'phase="compute"' in page
        assert 'phase="data_wait"' in page

    def test_fit_closes_breakdowns(self):
        """The funnel integration: a real tiny fit() lands breakdown
        records whose phases sum to ~the observed step time."""
        net, ds = _net_and_data()
        for _ in range(3):
            net.fit(ds)
        recs = stepstats.collector().records()
        assert len(recs) >= 3
        for rec in recs:
            assert rec["step_seconds"] > 0
            assert sum(rec["phases"].values()) == \
                pytest.approx(rec["total_seconds"], rel=1e-6)


class TestClockOffset:
    def test_estimate(self):
        # local clock 5s ahead: t0=10.0, leader says 5.1, t1=10.2
        assert estimate_clock_offset(10.0, 5.1, 10.2) == \
            pytest.approx(5.0)
        assert estimate_clock_offset(1.0, 1.1, 1.2) == \
            pytest.approx(0.0)


class TestAggregator:
    def test_clean_run_never_trips(self):
        agg = StepStatsAggregator(expected_workers=3, trip_factor=2.0,
                                  min_step_seconds=1e-3)
        try:
            for step in range(5):
                for w, dt in ((0, 0.100), (1, 0.104), (2, 0.098)):
                    merged = agg.ingest(_breakdown(step, dt, worker=w))
                assert merged is not None and not merged["tripped"]
            assert agg.trips == 0
            rep = agg.report()
            assert rep["steps_merged"] == 5
            assert rep["workers"] == 3
            assert rep["max_skew_seconds"] < 0.01
        finally:
            agg.close()

    def test_straggler_trips_and_names_host_and_phase(self, caplog):
        agg = StepStatsAggregator(expected_workers=3, trip_factor=2.0,
                                  min_step_seconds=1e-3)
        try:
            # one clean step, then worker 2 stalls on input: 0.9s vs a
            # 0.367s mean is >2x — must trip within that one step
            for w in range(3):
                agg.ingest(_breakdown(0, 0.1, worker=w))
            assert agg.trips == 0
            merged = None
            with caplog.at_level("WARNING", "deeplearning4j_tpu"):
                for w, dt, ph in ((0, 0.1, None), (1, 0.1, None),
                                  (2, 0.9, {"data_wait": 0.7})):
                    merged = agg.ingest(
                        _breakdown(1, dt, worker=w, phases=ph))
            assert merged["tripped"]
            assert agg.trips == 1
            assert merged["worst_worker"] == 2
            assert merged["worst_host"] == "host2"
            assert merged["worst_phase"] == "data_wait"
            assert merged["max_skew_seconds"] == pytest.approx(
                0.9 - (0.1 + 0.1 + 0.9) / 3)
            assert any("straggler" in r.getMessage()
                       and "host2" in r.getMessage()
                       for r in caplog.records)
            c = telemetry.counter("dl4j_straggler_trips_total", "t")
            assert c.value(worker="2", phase="data_wait") == 1
            g = telemetry.gauge("dl4j_straggler_skew_seconds", "t")
            assert g.value(worker="2") > 0.5
        finally:
            agg.close()

    def test_concurrent_ingest_counts_every_trip(self):
        """Regression (dl4j-lint lock-discipline finding): ``_merge``
        bumped ``trips`` outside the aggregator lock, so per-connection
        threads merging different steps could lose increments
        (load/add/store interleave). Hammer ingest from several
        threads with every step tripping: the count must be exact."""
        import random

        agg = StepStatsAggregator(expected_workers=2, trip_factor=1.5,
                                  min_step_seconds=1e-3)
        n_steps, n_threads = 400, 8
        recs = [_breakdown(s, 0.1 if w == 0 else 0.9, worker=w)
                for s in range(n_steps) for w in (0, 1)]
        random.Random(0).shuffle(recs)
        shards = [recs[i::n_threads] for i in range(n_threads)]
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)    # force preemption mid-increment
        try:
            threads = [threading.Thread(
                target=lambda rs: [agg.ingest(r) for r in rs],
                args=(shard,)) for shard in shards]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            sys.setswitchinterval(old_interval)
            rep = agg.report()
            agg.close()
        assert agg.trips == n_steps
        assert rep["steps_merged"] == n_steps
        assert rep["trips"] == n_steps

    def test_min_step_guard_blocks_noise_trips(self):
        # microsecond steps with huge RELATIVE skew must not trip:
        # the mean is below min_step_seconds
        agg = StepStatsAggregator(expected_workers=3, trip_factor=2.0,
                                  min_step_seconds=1e-3)
        try:
            for w, dt in ((0, 1e-5), (1, 1e-5), (2, 9e-4)):
                merged = agg.ingest(_breakdown(0, dt, worker=w))
            assert not merged["tripped"]
            assert agg.trips == 0
        finally:
            agg.close()

    def test_socket_roundtrip_with_skewed_clock(self):
        agg = StepStatsAggregator(expected_workers=2, port=0,
                                  trip_factor=10.0,
                                  min_step_seconds=1e-3)
        clients = []
        try:
            c0 = StepStatsClient("127.0.0.1", agg.port, worker=0,
                                 hostname="a")
            c1 = StepStatsClient("127.0.0.1", agg.port, worker=1,
                                 hostname="b",
                                 clock=lambda: time.time() + 5.0)
            clients += [c0, c1]
            # the NTP-lite handshake sees host b's clock 5s ahead
            assert abs(c0.clock_offset_s) < 0.5
            assert c1.clock_offset_s == pytest.approx(5.0, abs=0.5)
            c0.ship(_breakdown(0, 0.10, worker=0))
            c1.ship(_breakdown(0, 0.12, worker=1))
            deadline = time.time() + 5.0
            while time.time() < deadline and not agg.merged:
                time.sleep(0.01)
            assert agg.merged, "step never merged over the socket"
            rep = agg.report()
            assert rep["steps_merged"] == 1
            assert rep["worker_clock_offsets_s"][1] == \
                pytest.approx(5.0, abs=0.5)
            assert agg.worker_hosts[1] == "b"
        finally:
            for c in clients:
                c.close()
            agg.close()

    def test_dead_client_disables_not_raises(self):
        agg = StepStatsAggregator(expected_workers=1, port=0)
        c = StepStatsClient("127.0.0.1", agg.port, worker=0)
        agg.close()
        c._f.close()
        # shipping into a closed socket must not raise — observability
        # never takes training down
        c.ship(_breakdown(0, 0.1))
        c.ship(_breakdown(1, 0.1))
        assert c._dead


_WORKER_SCRIPT = r"""
import json, sys, time
sys.path.insert(0, sys.argv[3])
from deeplearning4j_tpu.common import telemetry
out, offset = sys.argv[1], float(sys.argv[2])
telemetry.MetricsRegistry.get().set_enabled(True)
with telemetry.span("worker_step", rank=sys.argv[2]):
    time.sleep(0.05)
telemetry.instant("worker_mark")
telemetry.export_chrome_trace(
    out, metadata={"host": "host_off%g" % offset,
                   "clock_offset_s": offset})
# simulate the skewed wall clock: shift every recorded timestamp by
# the offset, as if time.time() on this host ran that far ahead
doc = json.load(open(out))
for ev in doc["traceEvents"]:
    if "ts" in ev:
        ev["ts"] = int(ev["ts"] + offset * 1e6)
json.dump(doc, open(out, "w"))
"""


class TestHostTraceMerge:
    def test_two_subprocess_workers_offset_clocks(self, tmp_path):
        """Two real worker processes, one with its clock 5s ahead;
        the merge must pull both onto one monotonic leader timeline."""
        paths = []
        for i, offset in enumerate((0.0, 5.0)):
            p = tmp_path / f"w{i}.trace.json"
            subprocess.run(
                [sys.executable, "-c", _WORKER_SCRIPT, str(p),
                 str(offset), str(_ROOT)],
                check=True, timeout=60)
            paths.append(p)
        merged = tmp_path / "merged.trace.json"
        # worker 0 passed explicitly; worker 1's offset comes from the
        # clock_offset_s its own trace metadata carries
        telemetry.merge_host_traces(
            str(merged),
            {"path": str(paths[0]), "host": "leader",
             "clock_offset_s": 0.0},
            str(paths[1]))
        doc = json.loads(merged.read_text())
        ts = [ev["ts"] for ev in doc["traceEvents"] if "ts" in ev]
        assert ts
        # the 5s artificial skew is gone: both workers ran within the
        # same ~second of wall time, so the corrected union is narrow
        assert (max(ts) - min(ts)) / 1e6 < 4.0
        # pids remapped per source onto separate rows
        pids = {ev["pid"] for ev in doc["traceEvents"]
                if ev.get("ph") != "M"}
        assert any(1000 <= p < 2000 for p in pids)
        assert any(2000 <= p < 3000 for p in pids)
        names = {ev["args"]["name"] for ev in doc["traceEvents"]
                 if ev.get("ph") == "M"
                 and ev.get("name") == "process_name"}
        assert names == {"leader", "host_off5"}
        hosts = doc["metadata"]["hosts"]
        assert [h["clock_offset_s"] for h in hosts] == [0.0, 5.0]

    def test_uncorrected_merge_keeps_the_skew(self, tmp_path):
        # control: forcing offset 0 for the skewed worker leaves the
        # 5s gap in place — proving the correction above did the work
        paths = []
        for i, offset in enumerate((0.0, 5.0)):
            p = tmp_path / f"w{i}.trace.json"
            subprocess.run(
                [sys.executable, "-c", _WORKER_SCRIPT, str(p),
                 str(offset), str(_ROOT)],
                check=True, timeout=60)
            paths.append(p)
        merged = tmp_path / "raw.trace.json"
        telemetry.merge_host_traces(
            str(merged),
            {"path": str(paths[0]), "clock_offset_s": 0.0},
            {"path": str(paths[1]), "clock_offset_s": 0.0})
        doc = json.loads(merged.read_text())
        ts = [ev["ts"] for ev in doc["traceEvents"] if "ts" in ev]
        assert (max(ts) - min(ts)) / 1e6 > 4.0


class TestScalingBlock:
    def test_efficiency_vs_baseline(self):
        block = stepstats.scaling_block(
            {"sizes": [1, 8],
             "throughput": {"1": 100.0, "8": 640.0}})
        assert block["baseline_chips"] == 1
        assert block["throughput_per_chip"] == {"1": 100.0, "8": 80.0}
        assert block["efficiency"]["1"] == pytest.approx(1.0)
        assert block["efficiency"]["8"] == pytest.approx(0.8)
        assert block["max_worker_skew_seconds"] == 0.0

    def test_exchange_report_wire_accounting(self):
        from deeplearning4j_tpu.parallel import zero
        rep = zero.exchange_report(
            {"w": np.zeros((8, 8), dtype=np.float32)}, 4)
        assert rep["param_bytes"] == 256
        # ring all-reduce: 2(n-1)/n of the params cross the wire
        assert rep["wire_bytes_per_replica"] == 384
        assert rep["wire_to_param_ratio"] == pytest.approx(1.5)
        assert rep["mode"] == "dense"

    def test_observatory_report_attaches(self):
        obs = {"steps_merged": 10, "max_skew_seconds": 0.02,
               "trips": 1}
        block = stepstats.scaling_block(
            {"sizes": [1], "throughput": {"1": 10.0}},
            observatory=obs)
        assert block["observatory"] is obs
        assert block["max_worker_skew_seconds"] == pytest.approx(0.02)


class TestRegressionGatePolarity:
    @staticmethod
    def _mod():
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_bench_regression",
            _ROOT / "scripts" / "check_bench_regression.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_scaling_block_polarity(self):
        mod = self._mod()
        base = {"metric": "x", "value": 100.0,
                "scaling": {"efficiency": {"1": 1.0, "8": 0.9},
                            "max_worker_skew_seconds": 0.05},
                "step_breakdown": {
                    "phases_mean_seconds": {"data_wait": 0.02}}}
        fresh = {"metric": "x", "value": 100.0,
                 "scaling": {"efficiency": {"1": 1.0, "8": 0.6},
                             "max_worker_skew_seconds": 0.01},
                 "step_breakdown": {
                     "phases_mean_seconds": {"data_wait": 0.01}}}
        regs, imps, _ = mod.compare(base, fresh, 10.0)
        reg_keys = {k for k, *_ in regs}
        imp_keys = {k for k, *_ in imps}
        # an efficiency collapse at 8 chips is a gated regression...
        assert "scaling.efficiency.8" in reg_keys
        # ...while less skew and less data_wait are improvements
        assert "scaling.max_worker_skew_seconds" in imp_keys
        assert "step_breakdown.phases_mean_seconds.data_wait" in \
            imp_keys

    def test_reverse_direction_flags_skew_growth(self):
        mod = self._mod()
        base = {"metric": "x",
                "scaling": {"max_worker_skew_seconds": 0.01}}
        fresh = {"metric": "x",
                 "scaling": {"max_worker_skew_seconds": 0.05}}
        regs, _, _ = mod.compare(base, fresh, 10.0)
        assert {k for k, *_ in regs} == \
            {"scaling.max_worker_skew_seconds"}


class TestFlightRecorderRetention:
    @pytest.fixture(autouse=True)
    def _fresh_env(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.common import diagnostics
        monkeypatch.setenv("DL4J_TPU_FLIGHT_RECORDER_DIR",
                           str(tmp_path / "fr"))
        monkeypatch.setenv("DL4J_TPU_FLIGHT_RECORDER_KEEP", "3")
        Environment.reset()
        diagnostics.FlightRecorder._reset_for_tests()
        yield
        diagnostics.FlightRecorder._reset_for_tests()
        Environment.reset()

    def test_default_dir_is_flightrec(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_FLIGHT_RECORDER_DIR",
                           raising=False)
        Environment.reset()
        assert Environment.get().flight_recorder_dir == "flightrec"
        assert Environment.get().flight_recorder_keep == 3

    def test_prune_keeps_newest_k(self, tmp_path):
        from deeplearning4j_tpu.common import diagnostics
        rec = diagnostics.FlightRecorder.get()
        rec.enabled = True
        for i in range(5):
            assert rec.dump(f"r{i}") is not None
            # spread mtimes so keep-newest ordering is deterministic
            time.sleep(0.02)
        d = tmp_path / "fr"
        left = sorted(p.name for p in d.glob("flightrec_*.jsonl"))
        assert len(left) == 3
        assert all(any(f"_r{i}." in n for n in left)
                   for i in (2, 3, 4))
        # trace.json partners of pruned dumps went with them
        traces = sorted(p.name for p in d.glob("*.trace.json"))
        assert len(traces) == 3


class TestProfileCapture:
    def test_concurrent_capture_conflicts(self, tmp_path):
        ss = stepstats.collector()
        status = ProfileCapture.start(
            3, out_dir=str(tmp_path / "cap"), use_jax=False,
            expire_seconds=60.0)
        assert status["active"] and status["remaining_steps"] == 3
        with pytest.raises(CaptureActiveError):
            ProfileCapture.start(5, out_dir=str(tmp_path / "cap2"),
                                 use_jax=False)
        # step-bounded: three closed steps finalize it
        for i in range(3):
            ss.close_step("mln", i, 0.01)
        st = ProfileCapture.current_status()
        assert st["active"] is False
        assert st["last"]["reason"] == "complete"
        assert st["last"]["steps_captured"] == 3
        obs = Path(st["last"]["out_dir"]) / "observatory.trace.json"
        assert obs.exists()
        assert json.loads(obs.read_text())["traceEvents"] is not None
        # the slot freed: a new capture can start
        ProfileCapture.start(1, out_dir=str(tmp_path / "cap3"),
                             use_jax=False, expire_seconds=60.0)
        ss.close_step("mln", 9, 0.01)
        assert ProfileCapture.current_status()["active"] is False

    def test_wall_clock_expiry_backstop(self, tmp_path):
        ProfileCapture.start(10_000, out_dir=str(tmp_path / "cap"),
                             use_jax=False, expire_seconds=0.2)
        deadline = time.time() + 5.0
        while time.time() < deadline and \
                ProfileCapture.current_status()["active"]:
            time.sleep(0.05)
        st = ProfileCapture.current_status()
        assert st["active"] is False
        assert st["last"]["reason"] == "expired"
        c = telemetry.counter("dl4j_profile_captures_total", "t")
        assert c.value(reason="expired") == 1

    def test_http_endpoint(self, tmp_path):
        from deeplearning4j_tpu.ui import UIServer
        server = UIServer.get_instance().start(port=0)
        try:
            url = server.url + "/api/profile"
            idle = json.loads(urllib.request.urlopen(url).read())
            assert idle["active"] is False
            post = urllib.request.Request(
                url + "?steps=2&jax=0&expire_seconds=60"
                + f"&out_dir={tmp_path / 'cap'}",
                data=b"", method="POST")
            resp = urllib.request.urlopen(post)
            body = json.loads(resp.read())
            assert resp.status == 200
            assert body["started"] and body["remaining_steps"] == 2
            # a second POST while active is a 409 conflict
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    url + "?steps=2&jax=0", data=b"", method="POST"))
            assert ei.value.code == 409
            ss = stepstats.collector()
            ss.close_step("mln", 0, 0.01)
            ss.close_step("mln", 1, 0.01)
            done = json.loads(urllib.request.urlopen(url).read())
            assert done["active"] is False
            assert done["last"]["reason"] == "complete"
            # bad input is a 400, and non-profile POSTs 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    url + "?steps=nope", data=b"", method="POST"))
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    server.url + "/api/nope", data=b"",
                    method="POST"))
            assert ei.value.code == 404
        finally:
            server.stop()


class TestCheckpointStallMetric:
    def test_save_records_stall(self, tmp_path):
        from deeplearning4j_tpu.utils.checkpoint import \
            CheckpointListener
        net, ds = _net_and_data()
        listener = CheckpointListener(str(tmp_path),
                                      save_every_n_iterations=1,
                                      keep_last=2)
        net.set_listeners(listener)
        net.fit(ds)
        listener.flush()
        page = MetricsRegistry.get().render_prometheus()
        assert "dl4j_checkpoint_stall_seconds" in page
        rec = stepstats.collector().records()[-1]
        assert "checkpoint_stall" in rec["phases"]
