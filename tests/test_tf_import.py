"""TF GraphDef import conformance (SURVEY.md S6/S7, §4.4).

The reference proves import fidelity by executing a corpus of real
exported TF graphs and comparing tensors against TF-produced ground
truth (TFGraphTestAllSameDiff). Same approach here: graphs are built
with the in-image TF 2.21, frozen to GraphDef bytes, imported, and
outputs compared against TF's own execution.
"""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.modelimport.tensorflow import (  # noqa: E402
    TensorflowFrameworkImporter, TFGraphMapper)
from deeplearning4j_tpu.modelimport.tensorflow.protobuf import (  # noqa
    parse_graphdef, parse_tensor)


def freeze(fn, *specs):
    """tf.function → frozen GraphDef bytes + concrete function."""
    from tensorflow.python.framework.convert_to_constants import \
        convert_variables_to_constants_v2
    cf = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def()
    return gd.SerializeToString(), frozen


def _import_and_compare(fn, feeds, atol=1e-4, input_shapes=None):
    specs = [tf.TensorSpec(v.shape, tf.as_dtype(v.dtype))
             for v in feeds.values()]
    gd_bytes, frozen = freeze(fn, *specs)
    expected = frozen(**{k: tf.constant(v) for k, v in feeds.items()})
    if isinstance(expected, (list, tuple)):
        expected = expected[0]
    shapes = input_shapes or {k: v.shape for k, v in feeds.items()}
    imp = TensorflowFrameworkImporter.run_import(gd_bytes, shapes)
    importer_outs = [n for n in imp.vars if n.startswith("Identity")]
    out_name = sorted(importer_outs)[0]
    got = imp.output(feeds, [out_name])[out_name]
    np.testing.assert_allclose(got, np.asarray(expected), atol=atol,
                               rtol=1e-3)
    return imp


class TestProtobufDecoder:
    def test_const_roundtrip_dtypes(self):
        for arr in [np.arange(6, dtype=np.float32).reshape(2, 3),
                    np.arange(6, dtype=np.int64).reshape(3, 2),
                    np.asarray([True, False]),
                    np.asarray(3.5, np.float64)]:
            gd = tf.Graph()
            with gd.as_default():
                tf.constant(arr, name="c")
            raw = gd.as_graph_def().SerializeToString()
            nodes = parse_graphdef(raw)
            const = [n for n in nodes if n.name == "c"][0]
            got = const.attr("value")
            np.testing.assert_array_equal(got, arr)

    def test_splat_fill_tensor(self):
        gd = tf.Graph()
        with gd.as_default():
            tf.constant(np.full((4, 4), 7.0, np.float32), name="c")
        nodes = parse_graphdef(gd.as_graph_def().SerializeToString())
        got = [n for n in nodes if n.name == "c"][0].attr("value")
        np.testing.assert_array_equal(got, np.full((4, 4), 7.0))


class TestOpConformance:
    def test_mlp(self):
        w1 = tf.Variable(np.random.RandomState(0)
                         .randn(8, 16).astype(np.float32))
        b1 = tf.Variable(np.zeros(16, np.float32))
        w2 = tf.Variable(np.random.RandomState(1)
                         .randn(16, 4).astype(np.float32))

        def f(x):
            h = tf.nn.relu(tf.matmul(x, w1) + b1)
            return tf.nn.softmax(tf.matmul(h, w2))

        x = np.random.RandomState(2).randn(3, 8).astype(np.float32)
        _import_and_compare(f, {"x": x})

    def test_shape_arith_reshape_chain(self):
        def f(x):
            s = tf.shape(x)
            b = s[0]
            flat = tf.reshape(x, tf.stack([b, -1]))
            return tf.reduce_mean(flat, axis=1, keepdims=True)

        x = np.random.RandomState(0).randn(2, 3, 4).astype(np.float32)
        _import_and_compare(f, {"x": x})

    def test_strided_slice_masks(self):
        def f(x):
            return x[:, 1:, ::2] + x[:, :-1, 1::2]

        x = np.random.RandomState(0).randn(2, 5, 8).astype(np.float32)
        _import_and_compare(f, {"x": x})

    def test_concat_pad_tile(self):
        def f(x):
            y = tf.concat([x, x * 2.0], axis=-1)
            y = tf.pad(y, [[0, 0], [1, 1]])
            return tf.tile(y, [1, 2])

        x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        _import_and_compare(f, {"x": x})

    def test_conv_bn_pool(self):
        rs = np.random.RandomState(0)
        k = tf.Variable(rs.randn(3, 3, 2, 4).astype(np.float32) * 0.1)
        gamma = tf.Variable(np.ones(4, np.float32))
        beta = tf.Variable(np.zeros(4, np.float32))
        mean = tf.Variable(rs.randn(4).astype(np.float32) * 0.01)
        var = tf.Variable(np.abs(rs.randn(4)).astype(np.float32) + 1.0)

        def f(x):
            y = tf.nn.conv2d(x, k, strides=1, padding="SAME")
            y, _, _ = tf.compat.v1.nn.fused_batch_norm(
                y, gamma, beta, mean, var, is_training=False)
            y = tf.nn.max_pool2d(y, 2, 2, "VALID")
            return tf.nn.relu(y)

        x = rs.randn(2, 8, 8, 2).astype(np.float32)
        _import_and_compare(f, {"x": x})

    def test_gather_one_hot_argmax(self):
        table = tf.Variable(np.random.RandomState(0)
                            .randn(10, 6).astype(np.float32))

        def f(ids):
            emb = tf.gather(table, ids)
            probs = tf.nn.softmax(emb, axis=-1)
            am = tf.argmax(probs, axis=-1)
            return tf.one_hot(am, 6)

        ids = np.asarray([[1, 2], [7, 3]], np.int32)
        _import_and_compare(f, {"ids": ids})

    def test_legacy_mapper_front_door(self):
        def f(x):
            return tf.exp(x) * tf.sigmoid(x)

        x = np.random.RandomState(0).randn(4).astype(np.float32)
        gd_bytes, frozen = freeze(
            f, tf.TensorSpec([4], tf.float32))
        sd = TFGraphMapper.import_graph(gd_bytes, {"x": (4,)})
        out = [n for n in sd.vars if n.startswith("Identity")][0]
        got = sd.output({"x": x}, [out])[out]
        np.testing.assert_allclose(
            got, np.exp(x) / (1 + np.exp(-x)) * (1 + np.exp(-x))
            * (1 / (1 + np.exp(-x))), atol=1e-5)

    def test_unmapped_op_reports_names(self):
        def f(x):
            return tf.raw_ops.Betainc(a=x, b=x, x=x)

        x = np.abs(np.random.RandomState(0).randn(3)
                   .astype(np.float32)) + 0.5
        gd_bytes, _ = freeze(f, tf.TensorSpec([3], tf.float32))
        with pytest.raises(NotImplementedError, match="Betainc"):
            TensorflowFrameworkImporter.run_import(gd_bytes,
                                                   {"x": (3,)})


class TestBertImport:
    """Acceptance config #4 skeleton: BERT-class encoder via TF import
    (BASELINE.md #4). A compact BERT encoder (embeddings + transformer
    blocks with Einsum MHA + LayerNorm + GELU FFN + pooler) is frozen
    from TF and must reproduce TF's outputs through the importer."""

    def _build_bert(self, vocab=50, hidden=16, heads=2, layers=2,
                    seq=12):
        rs = np.random.RandomState(0)
        p = {}
        p["tok"] = tf.Variable(rs.randn(vocab, hidden)
                               .astype(np.float32) * 0.1)
        p["pos"] = tf.Variable(rs.randn(seq, hidden)
                               .astype(np.float32) * 0.1)
        p["seg"] = tf.Variable(rs.randn(2, hidden)
                               .astype(np.float32) * 0.1)
        for i in range(layers):
            for nm in ["q", "k", "v", "o"]:
                p[f"l{i}_{nm}w"] = tf.Variable(
                    rs.randn(hidden, hidden).astype(np.float32) * 0.1)
                p[f"l{i}_{nm}b"] = tf.Variable(
                    np.zeros(hidden, np.float32))
            p[f"l{i}_ffw1"] = tf.Variable(
                rs.randn(hidden, hidden * 4).astype(np.float32) * 0.1)
            p[f"l{i}_ffb1"] = tf.Variable(
                np.zeros(hidden * 4, np.float32))
            p[f"l{i}_ffw2"] = tf.Variable(
                rs.randn(hidden * 4, hidden).astype(np.float32) * 0.1)
            p[f"l{i}_ffb2"] = tf.Variable(np.zeros(hidden, np.float32))
            for ln in ["ln1", "ln2"]:
                p[f"l{i}_{ln}g"] = tf.Variable(np.ones(hidden,
                                                       np.float32))
                p[f"l{i}_{ln}b"] = tf.Variable(np.zeros(hidden,
                                                        np.float32))
        p["poolw"] = tf.Variable(rs.randn(hidden, hidden)
                                 .astype(np.float32) * 0.1)
        p["poolb"] = tf.Variable(np.zeros(hidden, np.float32))
        self.heads = heads
        self.hidden = hidden
        self.layers = layers
        return p

    def _bert_fn(self, p):
        heads, hidden, layers = self.heads, self.hidden, self.layers
        hd = hidden // heads

        def layer_norm(x, g, b):
            mu = tf.reduce_mean(x, axis=-1, keepdims=True)
            var = tf.reduce_mean(tf.math.squared_difference(x, mu),
                                 axis=-1, keepdims=True)
            return (x - mu) * tf.math.rsqrt(var + 1e-12) * g + b

        def f(ids, seg, mask):
            x = (tf.gather(p["tok"], ids) + p["pos"][None]
                 + tf.gather(p["seg"], seg))
            neg = (1.0 - tf.cast(mask, tf.float32)) * -1e9
            neg = neg[:, None, None, :]
            for i in range(layers):
                def proj(nm, t):
                    y = tf.matmul(t, p[f"l{i}_{nm}w"]) + p[f"l{i}_{nm}b"]
                    s = tf.shape(y)
                    y = tf.reshape(y, tf.stack([s[0], s[1], heads, hd]))
                    return tf.transpose(y, [0, 2, 1, 3])

                q, k, v = (proj("q", x), proj("k", x), proj("v", x))
                scores = tf.matmul(q, k, transpose_b=True) \
                    / np.float32(np.sqrt(hd))
                probs = tf.nn.softmax(scores + neg, axis=-1)
                ctxv = tf.transpose(tf.matmul(probs, v), [0, 2, 1, 3])
                s = tf.shape(ctxv)
                ctxv = tf.reshape(ctxv, tf.stack([s[0], s[1], hidden]))
                att = tf.matmul(ctxv, p[f"l{i}_ow"]) + p[f"l{i}_ob"]
                x = layer_norm(x + att, p[f"l{i}_ln1g"],
                               p[f"l{i}_ln1b"])
                h = tf.matmul(x, p[f"l{i}_ffw1"]) + p[f"l{i}_ffb1"]
                h = 0.5 * h * (1.0 + tf.math.erf(
                    h / np.float32(np.sqrt(2.0))))
                h = tf.matmul(h, p[f"l{i}_ffw2"]) + p[f"l{i}_ffb2"]
                x = layer_norm(x + h, p[f"l{i}_ln2g"], p[f"l{i}_ln2b"])
            pooled = tf.tanh(
                tf.matmul(x[:, 0], p["poolw"]) + p["poolb"])
            return pooled

        return f

    def test_bert_encoder_conformance(self):
        p = self._build_bert()
        f = self._bert_fn(p)
        rs = np.random.RandomState(3)
        ids = rs.randint(0, 50, (2, 12)).astype(np.int32)
        seg = np.zeros((2, 12), np.int32)
        seg[:, 6:] = 1
        mask = np.ones((2, 12), np.int32)
        mask[1, 9:] = 0
        _import_and_compare(
            f, {"ids": ids, "seg": seg, "mask": mask}, atol=1e-4)

    def test_bert_graph_reimport_roundtrip(self, tmp_path):
        """Imported graph must survive our native save/load (S5)."""
        p = self._build_bert(layers=1)
        f = self._bert_fn(p)
        ids = np.asarray([[1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12]],
                         np.int32)
        seg = np.zeros((1, 12), np.int32)
        mask = np.ones((1, 12), np.int32)
        specs = [tf.TensorSpec(v.shape, tf.as_dtype(v.dtype))
                 for v in (ids, seg, mask)]
        gd_bytes, _ = freeze(f, *specs)
        sd = TensorflowFrameworkImporter.run_import(
            gd_bytes, {"ids": (1, 12), "seg": (1, 12),
                       "mask": (1, 12)})
        out = [n for n in sd.vars if n.startswith("Identity")][0]
        want = sd.output({"ids": ids, "seg": seg, "mask": mask}, [out])
        path = str(tmp_path / "bert.sdz")
        sd.save(path)
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd2 = SameDiff.load(path)
        got = sd2.output({"ids": ids, "seg": seg, "mask": mask}, [out])
        np.testing.assert_allclose(got[out], want[out], atol=1e-6)


class TestResizeVariants:
    """SURVEY Appendix A image-domain resize tail (r4 verdict Missing
    #4): bicubic + area, TF ground truth."""

    def test_resize_bicubic_matches_tf(self):
        def f(x):
            return tf.image.resize(x, [7, 9], method="bicubic")

        x = np.random.RandomState(0).rand(2, 5, 6, 3).astype(
            np.float32)
        # 1e-3: TF renormalizes edge rows in f32; interior is exact
        _import_and_compare(f, {"x": x}, atol=1e-3)

    def test_resize_bicubic_upscale(self):
        def f(x):
            return tf.image.resize(x, [10, 12], method="bicubic")

        x = np.random.RandomState(1).rand(1, 5, 6, 2).astype(
            np.float32)
        _import_and_compare(f, {"x": x}, atol=1e-4)

    def test_resize_area_matches_tf(self):
        def f(x):
            return tf.image.resize(x, [3, 4], method="area")

        x = np.random.RandomState(2).rand(2, 9, 8, 3).astype(
            np.float32)
        _import_and_compare(f, {"x": x}, atol=1e-4)

    def test_resize_area_fractional(self):
        def f(x):
            return tf.image.resize(x, [4, 5], method="area")

        x = np.random.RandomState(3).rand(1, 7, 9, 2).astype(
            np.float32)
        _import_and_compare(f, {"x": x}, atol=1e-4)
