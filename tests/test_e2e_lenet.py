"""The minimum end-to-end slice (SURVEY.md section 7.5, BASELINE config #1):
LeNet-5 on MNIST — config builder -> compiled step -> MNIST iterator ->
fit() -> Evaluation >= 99% test accuracy -> checkpoint save/restore.

Runs against the deterministic synthetic MNIST surrogate in this
zero-egress container (real IDX/npz data is picked up automatically when
present — see datasets/mnist.py).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets import (AsyncDataSetIterator,
                                         ImagePreProcessingScaler,
                                         MnistDataSetIterator)
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                               DenseLayer, OutputLayer,
                                               PoolingType,
                                               SubsamplingLayer)
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.utils import ModelSerializer


def lenet5_conf(seed=123):
    """LeNet-5 as in the reference's dl4j-examples LeNetMNIST
    (conv5x5x20 -> max2 -> conv5x5x50 -> max2 -> dense500 -> softmax10)."""
    return (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(Adam(1e-3))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(ConvolutionLayer.Builder(5, 5)
                   .n_out(20).stride((1, 1))
                   .activation(Activation.IDENTITY).build())
            .layer(SubsamplingLayer.Builder(PoolingType.MAX)
                   .kernel_size((2, 2)).stride((2, 2)).build())
            .layer(ConvolutionLayer.Builder(5, 5)
                   .n_out(50).stride((1, 1))
                   .activation(Activation.IDENTITY).build())
            .layer(SubsamplingLayer.Builder(PoolingType.MAX)
                   .kernel_size((2, 2)).stride((2, 2)).build())
            .layer(DenseLayer.Builder().n_out(500)
                   .activation(Activation.RELU).build())
            .layer(OutputLayer.Builder(LossFunction.NEGATIVELOGLIKELIHOOD)
                   .n_out(10).activation(Activation.SOFTMAX).build())
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())


@pytest.fixture(scope="module")
def trained_lenet():
    train_iter = MnistDataSetIterator(batch_size=128, train=True,
                                      num_examples=6400)
    net = MultiLayerNetwork(lenet5_conf()).init()
    net.fit(AsyncDataSetIterator(train_iter), n_epochs=3)
    return net


class TestLeNetEndToEnd:
    def test_param_count(self):
        net = MultiLayerNetwork(lenet5_conf()).init()
        # conv1: 5*5*1*20+20, conv2: 5*5*20*50+50, dense: 800*500+500,
        # out: 500*10+10
        expected = (5 * 5 * 1 * 20 + 20) + (5 * 5 * 20 * 50 + 50) + \
            (4 * 4 * 50 * 500 + 500) + (500 * 10 + 10)
        assert net.num_params() == expected

    def test_accuracy_gate(self, trained_lenet):
        """BASELINE.md protocol step 1: >= 99% test accuracy."""
        test_iter = MnistDataSetIterator(batch_size=256, train=False,
                                         num_examples=2560)
        ev = trained_lenet.evaluate(test_iter)
        assert ev.accuracy() >= 0.99, ev.stats()
        assert ev.f1() >= 0.99

    def test_checkpoint_round_trip(self, trained_lenet, tmp_path):
        """BASELINE.md protocol step 1: checkpoint save/restore."""
        path = tmp_path / "lenet.zip"
        ModelSerializer.write_model(trained_lenet, path)
        restored = ModelSerializer.restore_multi_layer_network(path)
        x = MnistDataSetIterator(batch_size=32, train=False,
                                 num_examples=32).next().features
        np.testing.assert_allclose(
            np.asarray(trained_lenet.output(x)),
            np.asarray(restored.output(x)), rtol=1e-5, atol=1e-6)
        assert restored.iteration_count == trained_lenet.iteration_count
        # updater state restored too: one more fit step must not explode
        ds = MnistDataSetIterator(batch_size=32, train=True,
                                  num_examples=32).next()
        restored.fit(ds)
        assert np.isfinite(restored.score())

    def test_training_continues_after_restore(self, trained_lenet,
                                              tmp_path):
        path = tmp_path / "resume.zip"
        ModelSerializer.write_model(trained_lenet, path)
        restored = ModelSerializer.restore_multi_layer_network(path)
        it = MnistDataSetIterator(batch_size=128, train=True,
                                  num_examples=640)
        before = restored.iteration_count
        restored.fit(it, n_epochs=1)
        assert restored.iteration_count == before + 5


class TestDataPipeline:
    def test_mnist_shapes(self):
        it = MnistDataSetIterator(batch_size=64, train=True,
                                  num_examples=256)
        ds = it.next()
        assert ds.features.shape == (64, 784)
        assert ds.labels.shape == (64, 10)
        assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
        # one-hot labels
        np.testing.assert_allclose(ds.labels.sum(-1), np.ones(64))

    def test_iterator_reset_and_count(self):
        it = MnistDataSetIterator(batch_size=100, train=True,
                                  num_examples=250)
        n = sum(ds.num_examples() for ds in it)
        assert n == 250
        n2 = sum(ds.num_examples() for ds in it)  # auto-reset via __iter__
        assert n2 == 250

    def test_async_iterator_equivalence(self):
        base = MnistDataSetIterator(batch_size=64, train=True,
                                    num_examples=256, shuffle=False)
        async_it = AsyncDataSetIterator(
            MnistDataSetIterator(batch_size=64, train=True,
                                 num_examples=256, shuffle=False))
        a = [ds.features for ds in base]
        b = [ds.features for ds in async_it]
        assert len(a) == len(b)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_normalizer_preprocessor_hook(self):
        it = MnistDataSetIterator(batch_size=64, train=True,
                                  num_examples=64)
        scaler = ImagePreProcessingScaler(0.0, 1.0, max_pixel=1.0)
        it.set_pre_processor(scaler)
        ds = it.next()
        assert ds.features.max() <= 1.0


class TestNormalizers:
    def test_standardize_round_trip(self):
        from deeplearning4j_tpu.datasets import (DataSet,
                                                 NormalizerStandardize)
        rng = np.random.RandomState(0)
        x = (rng.randn(100, 5) * 7 + 3).astype(np.float32)
        ds = DataSet(x.copy(), np.zeros((100, 1), np.float32))
        norm = NormalizerStandardize()
        norm.fit(ds)
        norm.transform(ds)
        np.testing.assert_allclose(ds.features.mean(0), 0.0, atol=1e-4)
        np.testing.assert_allclose(ds.features.std(0), 1.0, atol=1e-2)
        norm.revert(ds)
        np.testing.assert_allclose(ds.features, x, rtol=1e-3, atol=1e-3)

    def test_minmax(self):
        from deeplearning4j_tpu.datasets import (DataSet,
                                                 NormalizerMinMaxScaler)
        rng = np.random.RandomState(0)
        x = (rng.rand(50, 3) * 10 - 5).astype(np.float32)
        ds = DataSet(x, np.zeros((50, 1), np.float32))
        norm = NormalizerMinMaxScaler()
        norm.fit(ds)
        norm.transform(ds)
        assert ds.features.min() >= -1e-6
        assert ds.features.max() <= 1.0 + 1e-6

    def test_normalizer_serde(self):
        from deeplearning4j_tpu.datasets import (DataSet,
                                                 NormalizerStandardize)
        from deeplearning4j_tpu.datasets.normalizers import Normalizer
        x = np.random.RandomState(0).randn(20, 4).astype(np.float32)
        norm = NormalizerStandardize()
        norm.fit(DataSet(x, np.zeros((20, 1))))
        back = Normalizer.from_map(norm.to_map())
        np.testing.assert_allclose(back.mean, norm.mean)


class TestEvaluation:
    def test_evaluation_metrics(self):
        from deeplearning4j_tpu.evaluation import Evaluation
        ev = Evaluation()
        labels = np.eye(3)[[0, 0, 1, 1, 2, 2]]
        preds = np.eye(3)[[0, 1, 1, 1, 2, 0]]  # 4/6 correct
        ev.eval(labels, preds)
        assert ev.accuracy() == pytest.approx(4 / 6)
        assert ev.confusion_matrix()[0, 1] == 1
        assert "Accuracy" in ev.stats()

    def test_evaluation_with_mask(self):
        from deeplearning4j_tpu.evaluation import Evaluation
        ev = Evaluation()
        labels = np.eye(2)[[0, 1, 1]]
        preds = np.eye(2)[[0, 0, 0]]
        mask = np.array([1.0, 1.0, 0.0])
        ev.eval(labels, preds, mask=mask)
        assert ev.confusion.sum() == 2
        assert ev.accuracy() == pytest.approx(0.5)

    def test_roc_auc(self):
        from deeplearning4j_tpu.evaluation import ROC
        roc = ROC()
        labels = np.array([0, 0, 1, 1])
        scores = np.array([0.1, 0.4, 0.35, 0.8])
        roc.eval(labels, scores)
        assert roc.calculate_auc() == pytest.approx(0.75)

    def test_regression_eval(self):
        from deeplearning4j_tpu.evaluation import RegressionEvaluation
        ev = RegressionEvaluation()
        y = np.array([[1.0], [2.0], [3.0]])
        p = np.array([[1.1], [1.9], [3.2]])
        ev.eval(y, p)
        assert ev.mean_squared_error(0) == pytest.approx(
            (0.01 + 0.01 + 0.04) / 3)
        assert ev.r_squared(0) > 0.95
