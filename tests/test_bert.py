"""BERT family tests (BASELINE config #4's model class; reference gets
BERT via SameDiff TF import + BertIterator, SURVEY.md S6/D16)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.models.bert import (Bert, BertConfig,
                                            BertForSequenceClassification)


def _mlm_batch(n=16, t=32, vocab=1000, seed=0, mask_id=3):
    """Synthetic copy task: mask 15% of tokens, predict them."""
    rng = np.random.RandomState(seed)
    # learnable structure: token at i+1 == token at i + 1 (mod small set)
    base = rng.randint(10, 30, size=(n, 1))
    ids = (base + np.arange(t)[None, :]) % 20 + 10
    labels = np.full((n, t), -1, np.int64)
    mask_pos = rng.rand(n, t) < 0.15
    labels[mask_pos] = ids[mask_pos]
    inp = ids.copy()
    inp[mask_pos] = mask_id
    return {
        "input_ids": inp.astype(np.int32),
        "token_type_ids": np.zeros((n, t), np.int32),
        "attention_mask": np.ones((n, t), np.float32),
        "mlm_labels": labels,
        "nsp_labels": rng.randint(0, 2, n).astype(np.int32),
    }


class TestBertEncoder:
    def test_output_shapes(self):
        c = BertConfig.tiny()
        bert = Bert(c).init()
        ids = np.zeros((2, 16), np.int32)
        seq, pooled = bert.output(ids)
        assert seq.shape == (2, 16, c.hidden_size)
        assert pooled.shape == (2, c.hidden_size)

    def test_attention_mask_isolates_padding(self):
        bert = Bert(BertConfig.tiny()).init()
        rng = np.random.RandomState(0)
        ids = rng.randint(10, 100, (2, 16)).astype(np.int32)
        am = np.ones((2, 16), np.float32)
        am[:, 12:] = 0.0
        seq1, _ = bert.output(ids, attention_mask=am)
        ids2 = ids.copy()
        ids2[:, 12:] = 999       # change padded tokens
        seq2, _ = bert.output(ids2, attention_mask=am)
        np.testing.assert_allclose(np.asarray(seq1[:, :12]),
                                   np.asarray(seq2[:, :12]), atol=1e-5)

    def test_pretraining_learns(self):
        bert = Bert(BertConfig.tiny(), updater=Adam(1e-3)).init()
        batch = _mlm_batch()
        first = bert.fit_batch(batch)
        for _ in range(60):
            loss = bert.fit_batch(batch)
        assert loss < first * 0.5, f"{first} -> {loss}"

    def test_remat_matches_plain(self):
        ids = np.arange(32, dtype=np.int32).reshape(2, 16) + 10
        b1 = Bert(BertConfig.tiny(remat=False), seed=5).init()
        b2 = Bert(BertConfig.tiny(remat=True), seed=5).init()
        s1, _ = b1.output(ids)
        s2, _ = b2.output(ids)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   atol=1e-5)

    def test_bf16_compute(self):
        c = BertConfig.tiny(compute_dtype="bfloat16")
        bert = Bert(c).init()
        seq, pooled = bert.output(np.zeros((2, 8), np.int32) + 11)
        assert seq.dtype == jnp.float32      # cast back at the top
        assert np.all(np.isfinite(np.asarray(seq)))
        loss = bert.fit_batch(_mlm_batch(n=4, t=8))
        assert np.isfinite(loss)

    def test_flash_attention_path_matches_dense(self):
        ids = (np.arange(256, dtype=np.int32).reshape(2, 128) % 50) + 10
        b1 = Bert(BertConfig.tiny(use_flash_attention=False),
                  seed=3).init()
        b2 = Bert(BertConfig.tiny(use_flash_attention=True),
                  seed=3).init()
        s1, _ = b1.output(ids)       # no mask -> flash kicks in for b2
        s2, _ = b2.output(ids)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   atol=2e-3)


class TestBertFineTune:
    def test_classifier_learns(self):
        bert = Bert(BertConfig.tiny()).init()
        clf = BertForSequenceClassification(bert, num_labels=2,
                                            updater=Adam(1e-3))
        rng = np.random.RandomState(0)
        n, t = 32, 16
        ids = rng.randint(10, 100, (n, t)).astype(np.int32)
        labels = (ids[:, 0] > 50).astype(np.int32)
        batch = {"input_ids": ids,
                 "attention_mask": np.ones((n, t), np.float32),
                 "labels": labels}
        first = clf.fit_batch(batch)
        for _ in range(60):
            loss = clf.fit_batch(batch)
        assert loss < first * 0.3, f"{first} -> {loss}"
        acc = float(np.mean(clf.predict(ids) == labels))
        assert acc > 0.9

    def test_mlm_loss_ignores_unmasked(self):
        bert = Bert(BertConfig.tiny()).init()
        batch = _mlm_batch(n=4, t=8)
        batch["mlm_labels"][:] = -1          # nothing to predict
        batch.pop("nsp_labels")
        loss = bert.pretrain_loss(bert.params,
                                  {k: jnp.asarray(v)
                                   for k, v in batch.items()})
        assert float(loss) == 0.0
