"""BERT family tests (BASELINE config #4's model class; reference gets
BERT via SameDiff TF import + BertIterator, SURVEY.md S6/D16)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.models.bert import (Bert, BertConfig,
                                            BertForSequenceClassification)


def _mlm_batch(n=16, t=32, vocab=1000, seed=0, mask_id=3):
    """Synthetic copy task: mask 15% of tokens, predict them."""
    rng = np.random.RandomState(seed)
    # learnable structure: token at i+1 == token at i + 1 (mod small set)
    base = rng.randint(10, 30, size=(n, 1))
    ids = (base + np.arange(t)[None, :]) % 20 + 10
    labels = np.full((n, t), -1, np.int64)
    mask_pos = rng.rand(n, t) < 0.15
    labels[mask_pos] = ids[mask_pos]
    inp = ids.copy()
    inp[mask_pos] = mask_id
    return {
        "input_ids": inp.astype(np.int32),
        "token_type_ids": np.zeros((n, t), np.int32),
        "attention_mask": np.ones((n, t), np.float32),
        "mlm_labels": labels,
        "nsp_labels": rng.randint(0, 2, n).astype(np.int32),
    }


class TestBertEncoder:
    def test_output_shapes(self):
        c = BertConfig.tiny()
        bert = Bert(c).init()
        ids = np.zeros((2, 16), np.int32)
        seq, pooled = bert.output(ids)
        assert seq.shape == (2, 16, c.hidden_size)
        assert pooled.shape == (2, c.hidden_size)

    def test_attention_mask_isolates_padding(self):
        bert = Bert(BertConfig.tiny()).init()
        rng = np.random.RandomState(0)
        ids = rng.randint(10, 100, (2, 16)).astype(np.int32)
        am = np.ones((2, 16), np.float32)
        am[:, 12:] = 0.0
        seq1, _ = bert.output(ids, attention_mask=am)
        ids2 = ids.copy()
        ids2[:, 12:] = 999       # change padded tokens
        seq2, _ = bert.output(ids2, attention_mask=am)
        np.testing.assert_allclose(np.asarray(seq1[:, :12]),
                                   np.asarray(seq2[:, :12]), atol=1e-5)

    def test_fit_steps_matches_per_step_fit(self):
        """One fori-loop dispatch of n steps == n fit_batch calls
        (dropout off, so the per-step rng is inert and the update
        sequence is deterministic)."""
        conf = BertConfig.tiny(hidden_dropout_prob=0.0,
                               attention_probs_dropout_prob=0.0)
        batch = _mlm_batch()
        a = Bert(conf, updater=Adam(1e-3)).init()
        b = Bert(conf, updater=Adam(1e-3)).init()
        b.params = jax.tree_util.tree_map(jnp.array, a.params)
        losses = [a.fit_batch(batch) for _ in range(5)]
        final = b.fit_steps(batch, 5)
        np.testing.assert_allclose(final, losses[-1],
                                   rtol=1e-5, atol=1e-6)
        # params marched in lockstep too
        for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                          jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_allclose(np.asarray(la),
                                       np.asarray(lb),
                                       rtol=2e-4, atol=2e-5)

    def test_pretraining_learns(self):
        bert = Bert(BertConfig.tiny(), updater=Adam(1e-3)).init()
        batch = _mlm_batch()
        first = bert.fit_batch(batch)
        for _ in range(60):
            loss = bert.fit_batch(batch)
        assert loss < first * 0.5, f"{first} -> {loss}"

    def test_remat_matches_plain(self):
        ids = np.arange(32, dtype=np.int32).reshape(2, 16) + 10
        b1 = Bert(BertConfig.tiny(remat=False), seed=5).init()
        b2 = Bert(BertConfig.tiny(remat=True), seed=5).init()
        s1, _ = b1.output(ids)
        s2, _ = b2.output(ids)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   atol=1e-5)

    def test_bf16_compute(self):
        c = BertConfig.tiny(compute_dtype="bfloat16")
        bert = Bert(c).init()
        seq, pooled = bert.output(np.zeros((2, 8), np.int32) + 11)
        assert seq.dtype == jnp.float32      # cast back at the top
        assert np.all(np.isfinite(np.asarray(seq)))
        loss = bert.fit_batch(_mlm_batch(n=4, t=8))
        assert np.isfinite(loss)

    def test_flash_attention_path_matches_dense(self):
        ids = (np.arange(256, dtype=np.int32).reshape(2, 128) % 50) + 10
        b1 = Bert(BertConfig.tiny(use_flash_attention=False),
                  seed=3).init()
        b2 = Bert(BertConfig.tiny(use_flash_attention=True),
                  seed=3).init()
        s1, _ = b1.output(ids)       # no mask -> flash kicks in for b2
        s2, _ = b2.output(ids)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   atol=2e-3)


class TestBertFineTune:
    def test_classifier_learns(self):
        bert = Bert(BertConfig.tiny()).init()
        clf = BertForSequenceClassification(bert, num_labels=2,
                                            updater=Adam(1e-3))
        rng = np.random.RandomState(0)
        n, t = 32, 16
        ids = rng.randint(10, 100, (n, t)).astype(np.int32)
        labels = (ids[:, 0] > 50).astype(np.int32)
        batch = {"input_ids": ids,
                 "attention_mask": np.ones((n, t), np.float32),
                 "labels": labels}
        first = clf.fit_batch(batch)
        for _ in range(60):
            loss = clf.fit_batch(batch)
        assert loss < first * 0.3, f"{first} -> {loss}"
        acc = float(np.mean(clf.predict(ids) == labels))
        assert acc > 0.9

    def test_mlm_loss_ignores_unmasked(self):
        bert = Bert(BertConfig.tiny()).init()
        batch = _mlm_batch(n=4, t=8)
        batch["mlm_labels"][:] = -1          # nothing to predict
        batch.pop("nsp_labels")
        loss = bert.pretrain_loss(bert.params,
                                  {k: jnp.asarray(v)
                                   for k, v in batch.items()})
        assert float(loss) == 0.0

    def test_gathered_mlm_head_matches_full_decode(self):
        """max_predictions_per_seq >= masked count per row must yield
        the exact full-decode loss and gradients (models/bert.py)."""
        batch = _mlm_batch(n=8, t=32)
        batch.pop("nsp_labels")
        max_masked = int((batch["mlm_labels"] >= 0).sum(1).max())
        full = Bert(BertConfig.tiny(), seed=5).init()
        gath = Bert(BertConfig.tiny(
            max_predictions_per_seq=max_masked + 2), seed=5).init()
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        lf = float(full.pretrain_loss(full.params, jb, training=False))
        lg = float(gath.pretrain_loss(gath.params, jb, training=False))
        assert abs(lf - lg) < 1e-5, (lf, lg)
        gf = jax.grad(lambda p: full.pretrain_loss(
            p, jb, training=False))(full.params)
        gg = jax.grad(lambda p: gath.pretrain_loss(
            p, jb, training=False))(gath.params)
        deltas = jax.tree_util.tree_map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))), gf, gg)
        assert max(jax.tree_util.tree_leaves(deltas)) < 1e-6

    def test_gathered_mlm_head_truncates_overfull_rows(self):
        """Rows with more masked positions than the cap train on the
        first cap positions (reference TF-BERT truncation)."""
        batch = _mlm_batch(n=4, t=16)
        batch.pop("nsp_labels")
        batch["mlm_labels"] = batch["input_ids"].astype(np.int64).copy()
        bert = Bert(BertConfig.tiny(max_predictions_per_seq=4),
                    seed=1).init()
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        loss = float(bert.pretrain_loss(bert.params, jb, training=False))
        # manually decode only the first 4 positions
        ref = Bert(BertConfig.tiny(), seed=1).init()
        jb4 = dict(jb)
        lab = np.full((4, 16), -1, np.int64)
        lab[:, :4] = batch["mlm_labels"][:, :4]
        jb4["mlm_labels"] = jnp.asarray(lab)
        ref_loss = float(ref.pretrain_loss(ref.params, jb4,
                                           training=False))
        assert abs(loss - ref_loss) < 1e-5, (loss, ref_loss)


def test_fused_qkv_matches_unfused():
    """fused_qkv computes identical attention (one [H,3H] GEMM vs
    three [H,H] GEMMs over the same params)."""
    import jax
    base = BertConfig.tiny(hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0)
    import dataclasses
    fused_conf = dataclasses.replace(base, fused_qkv=True)
    a = Bert(base).init()
    b = Bert(fused_conf).init()
    b.params = jax.tree_util.tree_map(jnp.array, a.params)
    ids = np.arange(10, 42, dtype=np.int32)[None].repeat(2, 0)
    sa, pa = a.output(ids)
    sb, pb = b.output(ids)
    np.testing.assert_allclose(np.asarray(sa), np.asarray(sb),
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(pa), np.asarray(pb),
                               atol=2e-5)


def test_updater_reassignment_evicts_compiled_step():
    """Replacing model.updater after the first fit must recompile the
    cached step/fori programs with the NEW update rule and reset the
    opt state (r4 advisor finding: the cache had no invalidation
    key, so a swapped updater was silently ignored)."""
    from deeplearning4j_tpu.learning.updaters import Sgd
    c = BertConfig.tiny(hidden_dropout_prob=0.0,
                        attention_probs_dropout_prob=0.0)
    m = Bert(c, Adam(1e-3)).init()
    batch = _mlm_batch(n=4, t=16, vocab=c.vocab_size)
    m.fit_batch(batch)
    old_step = m._step
    assert m._iteration == 1

    m.updater = Sgd(0.0)            # lr 0: params must stop moving
    before = jax.tree_util.tree_map(np.asarray, m.params)
    m.fit_batch(batch)
    assert m._step is not old_step, "stale compiled step kept old rule"
    assert m._iteration == 1        # opt state (and iteration) reset
    after = jax.tree_util.tree_map(np.asarray, m.params)
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(a, b)
