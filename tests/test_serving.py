"""Model-serving subsystem tests (ISSUE 3): versioned registry with
shape-bucketed warmup, dynamic batcher, HTTP inference server, and
admission control (shed / deadline / drain).

The load tests assert BITWISE equality between served responses and
direct ``model.output`` — on the CPU backend the small test net's
per-row results are identical across batch paddings, so any
divergence means the serving path changed the math."""
import io
import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.telemetry import MetricsRegistry
from deeplearning4j_tpu.serving import (AdmissionController,
                                        DeadlineExceeded,
                                        InferenceServer, ModelRegistry,
                                        ModelStatus, ServingBatcher,
                                        ShedError)


@pytest.fixture(autouse=True)
def _fresh_registry():
    MetricsRegistry._reset_for_tests()
    yield
    MetricsRegistry._reset_for_tests()


def _mlp(seed=42):
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_in=8, n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _post(base, name, payload, headers=None, raw=False):
    """POST a predict request; returns (code, body_bytes, headers)."""
    h = {"Content-Type": ("application/octet-stream" if raw
                          else "application/json")}
    h.update(headers or {})
    data = payload if isinstance(payload, bytes) \
        else json.dumps(payload).encode()
    req = urllib.request.Request(
        f"{base}/v1/models/{name}:predict", data=data, headers=h)
    try:
        r = urllib.request.urlopen(req)
        return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


# ----------------------------------------------------------------------
class TestServingBatcher:
    def test_buckets_round_up_to_shard_multiples(self):
        b = ServingBatcher(_mlp(), buckets=(3, 9))
        w = b.n_workers
        assert all(x % w == 0 for x in b.buckets)
        assert b.batch_limit == b.buckets[-1]
        b.shutdown()

    def test_warmup_compiles_buckets_and_steady_state_never_retraces(
            self):
        net = _mlp()
        b = ServingBatcher(net, buckets=(8, 16), batch_window_ms=5.0)
        b.warmup((8,))
        warm = b.guard.n_signatures
        assert warm == len(b.buckets)
        rng = np.random.RandomState(0)
        # every size from 1 to the largest bucket pads onto a warm
        # signature — zero recompiles in steady state
        for n in (1, 3, 7, 8, 9, 15, 16):
            x = rng.randn(n, 8).astype(np.float32)
            out = b.submit(x).result(timeout=60)
            np.testing.assert_array_equal(out, np.asarray(net.output(x)))
        assert b.guard.n_signatures == warm
        assert telemetry.counter(
            "dl4j_serving_bucket_miss_total").value(model="model") == 0
        b.shutdown()

    def test_oversized_request_chunks_onto_warm_buckets(self):
        """A request larger than the biggest bucket chunks by it —
        no cold compile, every chunk lands warm."""
        net = _mlp()
        b = ServingBatcher(net, buckets=(8,), batch_window_ms=1.0)
        b.warmup((8,))
        x = np.random.RandomState(1).randn(11, 8).astype(np.float32)
        out = b.submit(x).result(timeout=60)
        np.testing.assert_array_equal(out, np.asarray(net.output(x)))
        assert out.shape == (11, 3)
        assert b.guard.n_signatures == 1        # 8-chunk + padded tail
        assert telemetry.counter(
            "dl4j_serving_bucket_miss_total").value(model="model") == 0
        b.shutdown()

    def test_signature_drift_after_warmup_counts_bucket_miss(self):
        """Post-warmup requests whose padded signature the warmup set
        never compiled (dtype drift on a generic model) are served but
        counted as bucket misses — the cold-compile alarm."""
        class _Double:
            def output(self, x):
                return np.asarray(x)[:, :1] * 2

        b = ServingBatcher(_Double(), buckets=(4,), name="drift",
                           batch_window_ms=1.0)
        b.warmup((8,))                          # float32 signature
        miss = telemetry.counter("dl4j_serving_bucket_miss_total")
        assert miss.value(model="drift") == 0
        out = b.submit(np.ones((2, 8), np.float64)).result(timeout=60)
        np.testing.assert_array_equal(out, np.full((2, 1), 2.0))
        assert miss.value(model="drift") == 1
        # same drifted signature again: now known, no second miss
        b.submit(np.ones((2, 8), np.float64)).result(timeout=60)
        assert miss.value(model="drift") == 1
        b.shutdown()

    def test_empty_flush_and_empty_output_batched(self):
        b = ServingBatcher(_mlp(), buckets=(8,))
        assert b.output_batched([]) == []
        b.shutdown()

    def test_deadline_expired_request_cancelled_not_computed(self):
        net = _mlp()
        # window policy: the worker holds the batch 150ms, letting the
        # doomed request's 10ms deadline expire while queued
        b = ServingBatcher(net, buckets=(8,), batch_window_ms=150.0,
                           flush_policy="window")
        b.warmup((8,))
        x = np.zeros((1, 8), np.float32)
        computed = []
        orig = b.output_batched
        b.output_batched = lambda reqs: computed.extend(reqs) or orig(
            reqs)
        doomed = b.submit(x, deadline=time.monotonic() + 0.01)
        live = b.submit(x)               # same batch, no deadline
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=60)
        out = live.result(timeout=60)
        np.testing.assert_array_equal(out, np.asarray(net.output(x)))
        # the expired request never reached the forward: one request
        # computed, not two
        assert len(computed) == 1
        assert telemetry.counter(
            "dl4j_serving_deadline_expired_total").value(
                model="model") == 1
        assert telemetry.counter(
            "dl4j_serving_deadline_shed_total").value(
                model="model", where="queue") == 1
        b.shutdown()

    def test_continuous_batching_aggregates_under_busy_device(self):
        """The continuous worker takes whatever is queued the moment
        the device frees: requests arriving while a flush computes
        coalesce into ONE next flush — no window clock involved."""
        flushes = []
        release = threading.Event()

        class _Slow:
            def output(self, x):
                # first flush blocks until the test has queued more
                if not flushes:
                    release.wait(timeout=30)
                return np.asarray(x)[:, :1] * 2

        b = ServingBatcher(_Slow(), buckets=(8,), name="cont")
        assert b.flush_policy == "continuous"
        b.warmup((4,))
        orig = b.output_batched
        b.output_batched = lambda reqs: flushes.append(len(reqs)) \
            or orig(reqs)
        x = np.ones((1, 4), np.float32)
        first = b.submit(x)              # occupies the worker
        time.sleep(0.05)                 # worker is inside the flush
        rest = [b.submit(x) for _ in range(5)]
        time.sleep(0.05)                 # all five are queued
        release.set()
        for f in [first] + rest:
            np.testing.assert_array_equal(f.result(timeout=30),
                                          [[2.0]])
        # flush 1 took the lone first request; flush 2 took ALL five
        # waiters at once — batch formation from device busyness alone
        assert flushes == [1, 5]
        b.shutdown()

    def test_continuous_lone_request_flushes_immediately(self):
        """An idle continuous batcher adds no window wait: a single
        request's queue latency is far below the old 2ms floor times
        any reasonable load factor (bounded here at 50ms for CI
        noise, but typically sub-ms)."""
        net = _mlp()
        b = ServingBatcher(net, buckets=(8,))
        b.warmup((8,))
        x = np.zeros((1, 8), np.float32)
        t0 = time.perf_counter()
        b.submit(x).result(timeout=30)
        assert time.perf_counter() - t0 < 0.5
        b.shutdown()

    def test_flush_policy_validated(self):
        with pytest.raises(ValueError):
            ServingBatcher(_mlp(), buckets=(8,), flush_policy="nope")
        with pytest.raises(ValueError):
            ServingBatcher(_mlp(), buckets=(8,), mode="bogus")

    def test_serving_batch_occupancy_histogram_observed(self):
        net = _mlp()
        b = ServingBatcher(net, buckets=(8,))
        b.warmup((8,))
        b.submit(np.zeros((2, 8), np.float32)).result(timeout=30)
        h = telemetry.histogram("dl4j_serving_batch_occupancy")
        assert h.count_of(model="model", policy="continuous") >= 1
        # 2 live rows on an 8-bucket = 0.25 occupancy
        assert 0 < h.sum_of(model="model", policy="continuous") <= 1
        b.shutdown()


# ----------------------------------------------------------------------
class TestAdmissionController:
    def test_admit_release_and_shed(self):
        adm = AdmissionController(max_queue=2, retry_after_s=0.5)
        adm.admit("m")
        adm.admit("m")
        with pytest.raises(ShedError) as ei:
            adm.admit("m")
        assert ei.value.reason == "queue_full"
        assert adm.retry_after_header() == "1"
        adm.release("m")
        adm.admit("m")                    # capacity freed
        assert adm.inflight("m") == 2
        assert telemetry.counter("dl4j_serving_shed_total").value(
            model="m", reason="queue_full") == 1

    def test_drain_waits_for_inflight_then_sheds_new(self):
        adm = AdmissionController(max_queue=4)
        adm.admit("m")
        done = []

        def finish():
            time.sleep(0.1)
            adm.release("m")
            done.append(True)

        threading.Thread(target=finish).start()
        assert adm.drain(timeout=10)
        assert done == [True]
        with pytest.raises(ShedError) as ei:
            adm.admit("m")
        assert ei.value.reason == "draining"
        adm.resume()
        adm.admit("m")

    def test_expired_deadline_fast_fails_without_taking_a_slot(self):
        adm = AdmissionController(max_queue=4)
        with pytest.raises(DeadlineExceeded):
            adm.admit("m", deadline=time.monotonic() - 0.001)
        assert adm.inflight("m") == 0
        assert telemetry.counter(
            "dl4j_serving_deadline_shed_total").value(
                model="m", where="admission") == 1
        # a live deadline admits normally
        adm.admit("m", deadline=time.monotonic() + 60)
        assert adm.inflight("m") == 1

    def test_retry_after_cold_start_returns_floor(self):
        """Zero observations: no drain rate exists yet, so the header
        falls back to the configured floor (ceil'd to >= 1s)."""
        adm = AdmissionController(max_queue=2, retry_after_s=0.5)
        assert adm.retry_after_s_for("m") == 0.5
        assert adm.retry_after_header("m") == "1"
        adm2 = AdmissionController(max_queue=2, retry_after_s=3.0)
        assert adm2.retry_after_header("m") == "3"

    def test_retry_after_derived_from_measured_drain_rate(self):
        adm = AdmissionController(max_queue=2, retry_after_s=1.0)
        # 4 completions over 2 simulated seconds -> ~2 rps drain
        t0 = 1000.0
        for i in range(4):
            adm.observe_total("m", 0.05, now=t0 + 0.5 * (i + 1))
        # saturate the budget: excess = 1 slot to drain at ~2rps
        adm.admit("m")
        adm.admit("m")
        ra = adm.retry_after_s_for("m", now=t0 + 2.0)
        assert 1.0 <= ra <= 2.0       # floored at 1s, ~0.5s computed
        assert int(adm.retry_after_header("m")) >= 1
        # the gauge published the measured rate
        assert telemetry.gauge(
            "dl4j_serving_drain_rate_rps").value(model="m") > 0

    def test_slo_budget_shrinks_on_p95_violation_and_regrows(self):
        adm = AdmissionController(max_queue=16)
        adm.set_slo("m", 50.0)                 # 50ms SLO
        assert adm.budget("m") == 16
        # sustained 200ms totals: p95 >> SLO, AIMD shrink kicks in
        for i in range(8):
            adm.observe_total("m", 0.2, now=1000.0 + i)
        assert adm.budget("m") < 16
        shrunk = adm.budget("m")
        assert shrunk >= adm.min_budget
        # sustained 1ms totals: p95 < 80% of SLO, budget regrows +1
        for i in range(64):
            adm.observe_total("m", 0.001, now=2000.0 + i)
        assert adm.budget("m") > shrunk
        assert telemetry.gauge(
            "dl4j_serving_admission_budget").value(model="m") == \
            adm.budget("m")

    def test_adaptive_budget_gates_admission(self):
        adm = AdmissionController(max_queue=16, min_budget=1)
        adm.set_slo("m", 10.0)
        # hammer the controller until the budget collapses to the floor
        for i in range(64):
            adm.observe_total("m", 5.0, now=1000.0 + i)
        assert adm.budget("m") == 1
        adm.admit("m")
        with pytest.raises(ShedError) as ei:
            adm.admit("m")                # static cap is 16, budget is 1
        assert ei.value.reason == "queue_full"

    def test_no_slo_keeps_static_budget(self):
        adm = AdmissionController(max_queue=4)
        for i in range(32):
            adm.observe_total("m", 9.9, now=1000.0 + i)
        assert adm.budget("m") == 4       # no SLO -> no adaptation


# ----------------------------------------------------------------------
class TestModelRegistry:
    def test_register_warm_and_hot_swap(self):
        reg = ModelRegistry(default_buckets=(8,), batch_window_ms=2.0)
        v1 = reg.register("m", _mlp(seed=1), warmup_shape=(8,))
        assert v1.status == ModelStatus.READY
        assert v1.version == 1
        assert v1.warm_signatures == 1
        assert reg.model("m") is v1

        v2 = reg.register("m", _mlp(seed=2), warmup_shape=(8,))
        assert reg.model("m") is v2
        assert v2.version == 2
        assert v1.status == ModelStatus.RETIRED
        assert telemetry.counter(
            "dl4j_serving_hot_swaps_total").value(model="m") == 1
        desc = reg.describe()
        assert desc[0]["live_version"] == 2
        assert [d["version"] for d in desc[0]["versions"]] == [1, 2]
        assert reg.ready()
        reg.shutdown()

    def test_register_from_serializer_zip(self, tmp_path):
        from deeplearning4j_tpu.utils.serializer import ModelSerializer
        net = _mlp(seed=3)
        p = tmp_path / "model.zip"
        ModelSerializer.write_model(net, p)
        assert ModelSerializer.peek_meta(p)["model_class"] == \
            "MultiLayerNetwork"
        reg = ModelRegistry(default_buckets=(8,))
        ver = reg.register("z", str(p), warmup_shape=(8,))
        x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
        out = ver.batcher.submit(x).result(timeout=60)
        np.testing.assert_allclose(out, np.asarray(net.output(x)),
                                   rtol=1e-6, atol=1e-7)
        reg.shutdown()

    def test_register_samediff_zip_and_serve(self, tmp_path):
        from deeplearning4j_tpu.autodiff import SameDiff
        from deeplearning4j_tpu.nn.weights import WeightInit
        from deeplearning4j_tpu.utils.serializer import ModelSerializer
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 4))
        w = sd.var("w", shape=(4, 3), init=WeightInit.XAVIER)
        logits = x @ w
        probs = sd.nn.softmax(logits, name="probs")
        p = tmp_path / "sd.zip"
        sd.save(str(p))
        # restore_model sniffs the SameDiff archive (satellite:
        # serializer dispatch)
        loaded = ModelSerializer.restore_model(p)
        assert isinstance(loaded, SameDiff)
        assert ModelSerializer.peek_meta(p)["model_class"] == "SameDiff"

        reg = ModelRegistry(default_buckets=(8,))
        ver = reg.register("sd", str(p), warmup_shape=(4,))
        xv = np.random.RandomState(1).randn(8, 4).astype(np.float32)
        out = ver.batcher.submit(xv).result(timeout=60)
        ref = sd.output({"x": xv}, [probs.name])[probs.name]
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-7)
        assert ver.retraces_since_warmup() == 0
        reg.shutdown()


# ----------------------------------------------------------------------
def _serve(net=None, buckets=(8, 16), window_ms=5.0, admission=None,
           warm=True, flush_policy="continuous", **register_kw):
    net = net or _mlp()
    reg = ModelRegistry(default_buckets=buckets,
                        batch_window_ms=window_ms,
                        flush_policy=flush_policy)
    reg.register("m", net, warmup_shape=(8,) if warm else None,
                 **register_kw)
    srv = InferenceServer(reg, admission
                          or AdmissionController(max_queue=64))
    srv.start(port=0)
    return net, reg, srv


class TestInferenceServer:
    def test_concurrent_load_bitwise_and_zero_retraces(self):
        """The acceptance loop: N client threads × M requests against
        a live server; every response bitwise-matches model.output and
        the warmed version never recompiles."""
        net, reg, srv = _serve()
        base = srv.url
        rng = np.random.RandomState(0)
        reqs = [rng.randn(1 + i % 5, 8).astype(np.float32)
                for i in range(24)]
        refs = [np.asarray(net.output(x)) for x in reqs]
        errors = []

        def client(idx):
            for j in range(idx, len(reqs), 6):
                code, body, _ = _post(base, "m",
                                      {"inputs": reqs[j].tolist()})
                if code != 200:
                    errors.append((j, code, body))
                    continue
                out = np.asarray(json.loads(body)["outputs"],
                                 np.float32)
                if not np.array_equal(out, refs[j]):
                    errors.append((j, "mismatch",
                                   np.abs(out - refs[j]).max()))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        try:
            assert errors == []
            assert reg.retraces_since_warmup("m") == 0
            # listing + probes + metrics all live
            models = json.loads(urllib.request.urlopen(
                base + "/v1/models").read())["models"]
            assert models[0]["name"] == "m"
            assert models[0]["versions"][0][
                "retraces_since_warmup"] == 0
            assert urllib.request.urlopen(
                base + "/healthz").status == 200
            assert urllib.request.urlopen(
                base + "/readyz").status == 200
            metrics = urllib.request.urlopen(
                base + "/metrics").read().decode()
            assert 'dl4j_serving_requests_total{code="200",model="m"}' \
                in metrics
            assert "dl4j_serving_latency_seconds_bucket" in metrics
        finally:
            srv.stop(drain=True, timeout=10)
            reg.shutdown()

    def test_raw_npy_body_roundtrip(self):
        net, reg, srv = _serve()
        try:
            x = np.random.RandomState(3).randn(4, 8).astype(np.float32)
            buf = io.BytesIO()
            np.save(buf, x)
            code, body, hdrs = _post(srv.url, "m", buf.getvalue(),
                                     raw=True)
            assert code == 200
            assert hdrs["X-Model-Version"] == "1"
            np.testing.assert_array_equal(
                np.load(io.BytesIO(body)), np.asarray(net.output(x)))
        finally:
            srv.stop()
            reg.shutdown()

    def test_unknown_model_404_and_bad_body_400(self):
        _, reg, srv = _serve()
        try:
            assert _post(srv.url, "nope", {"inputs": [[0] * 8]})[0] \
                == 404
            assert _post(srv.url, "m", {"wrong": 1})[0] == 400
            code, body, _ = _post(srv.url, "m", b"not json", raw=False)
            assert code == 400
        finally:
            srv.stop()
            reg.shutdown()

    def test_hot_swap_under_load_drops_nothing(self):
        """Clients hammer the model while a new version registers:
        every response is a 200 matching v1 or v2 exactly, and the
        final state serves v2."""
        net1 = _mlp(seed=1)
        net1, reg, srv = _serve(net=net1)
        base = srv.url
        x = np.random.RandomState(5).randn(2, 8).astype(np.float32)
        net2 = _mlp(seed=99)
        ref1 = np.asarray(net1.output(x))
        ref2 = np.asarray(net2.output(x))
        assert not np.array_equal(ref1, ref2)
        stop, errors, seen = threading.Event(), [], set()

        def client():
            while not stop.is_set():
                code, body, _ = _post(base, "m",
                                      {"inputs": x.tolist()})
                if code != 200:
                    errors.append(code)
                    continue
                out = np.asarray(json.loads(body)["outputs"],
                                 np.float32)
                if np.array_equal(out, ref1):
                    seen.add(1)
                elif np.array_equal(out, ref2):
                    seen.add(2)
                else:
                    errors.append("mismatch")

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.15)
        reg.register("m", net2, warmup_shape=(8,))   # hot swap
        time.sleep(0.25)
        stop.set()
        for t in threads:
            t.join()
        try:
            assert errors == []
            assert seen == {1, 2}
            code, body, _ = _post(base, "m", {"inputs": x.tolist()})
            assert code == 200
            assert json.loads(body)["version"] == 2
        finally:
            srv.stop(drain=True, timeout=10)
            reg.shutdown()

    def test_deadline_expiry_http_504(self):
        # window policy holds the request 100ms so its 1ms deadline
        # reliably expires while queued
        _, reg, srv = _serve(window_ms=100.0, flush_policy="window")
        try:
            code, body, _ = _post(
                srv.url, "m", {"inputs": [[0.0] * 8]},
                headers={"X-Deadline-Ms": "1"})
            assert code == 504
            shed = telemetry.counter(
                "dl4j_serving_deadline_shed_total")
            assert (shed.value(model="m", where="queue")
                    + shed.value(model="m", where="admission")) >= 1
        finally:
            srv.stop(drain=True, timeout=10)
            reg.shutdown()

    def test_already_expired_deadline_fast_fails_before_batcher(self):
        """A request dead on arrival is answered 504 straight from
        admission — it never occupies a slot, never reaches the
        batcher queue, never touches the model."""
        net, reg, srv = _serve()
        ver = reg.model("m")
        submitted = []
        orig = ver.batcher.submit
        ver.batcher.submit = lambda *a, **kw: submitted.append(a) or \
            orig(*a, **kw)
        try:
            code, body, _ = _post(
                srv.url, "m", {"inputs": [[0.0] * 8]},
                headers={"X-Deadline-Ms": "0"})
            assert code == 504
            assert submitted == []
            assert srv.admission.inflight("m") == 0
            assert telemetry.counter(
                "dl4j_serving_deadline_shed_total").value(
                    model="m", where="admission") == 1
        finally:
            srv.stop(drain=True, timeout=10)
            reg.shutdown()

    def test_zero_copy_npy_roundtrip_and_slo_wiring(self):
        """The raw .npy path round-trips through npy_view /
        send_body_parts, and a version's latency_slo_ms arms the
        admission controller on first service."""
        net, reg, srv = _serve(latency_slo_ms=250.0)
        try:
            x = np.random.RandomState(11).randn(3, 8).astype(
                np.float32)
            buf = io.BytesIO()
            np.save(buf, x)
            code, body, hdrs = _post(srv.url, "m", buf.getvalue(),
                                     raw=True)
            assert code == 200
            np.testing.assert_array_equal(
                np.load(io.BytesIO(body)), np.asarray(net.output(x)))
            # the completed request observed into the SLO stream and
            # wired the model's SLO into the controller
            assert srv.admission._slo_ms.get("m") == 250.0
            assert telemetry.histogram(
                "dl4j_serving_total_seconds").count_of(model="m") >= 1
        finally:
            srv.stop(drain=True, timeout=10)
            reg.shutdown()

    def test_shed_then_recover(self):
        """Overload: 8 simultaneous clients against an in-flight
        budget of 2 and a 150ms batch window — admitted requests
        complete in-SLO (200, correct bytes), the rest shed with
        429 + Retry-After, and capacity recovers afterwards."""
        net = _mlp()
        adm = AdmissionController(max_queue=2, retry_after_s=0.5)
        # window policy keeps each admitted request in flight ~150ms,
        # so the barrier-released surplus deterministically sheds
        net, reg, srv = _serve(net=net, window_ms=150.0,
                               admission=adm, flush_policy="window")
        base = srv.url
        x = np.random.RandomState(7).randn(1, 8).astype(np.float32)
        ref = np.asarray(net.output(x))
        barrier = threading.Barrier(8)
        results = []

        def client():
            barrier.wait()
            code, body, hdrs = _post(base, "m",
                                     {"inputs": x.tolist()})
            results.append((code, body, hdrs))

        threads = [threading.Thread(target=client) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        try:
            codes = [c for c, _, _ in results]
            assert set(codes) <= {200, 429}, codes
            assert 429 in codes, codes
            assert 200 in codes, codes
            for code, body, hdrs in results:
                if code == 200:
                    np.testing.assert_array_equal(
                        np.asarray(json.loads(body)["outputs"],
                                   np.float32), ref)
                else:
                    assert int(hdrs["Retry-After"]) >= 1
                    assert json.loads(body)["reason"] == "queue_full"
            assert telemetry.counter(
                "dl4j_serving_shed_total").value(
                    model="m", reason="queue_full") == codes.count(429)
            # recover: load gone, a fresh request is admitted
            code, body, _ = _post(base, "m", {"inputs": x.tolist()})
            assert code == 200
        finally:
            srv.stop(drain=True, timeout=10)
            reg.shutdown()

    def test_drain_rejects_with_503_and_readyz_flips(self):
        _, reg, srv = _serve()
        base = srv.url
        try:
            assert srv.admission.drain(timeout=5)
            code, body, hdrs = _post(base, "m",
                                     {"inputs": [[0.0] * 8]})
            assert code == 503
            assert json.loads(body)["reason"] == "draining"
            assert "Retry-After" in hdrs
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/readyz")
            assert ei.value.code == 503
            srv.admission.resume()
            assert _post(base, "m", {"inputs": [[0.0] * 8]})[0] == 200
        finally:
            srv.stop(drain=False)
            reg.shutdown()


# ----------------------------------------------------------------------
class TestNpyZeroCopy:
    def test_npy_view_aliases_the_buffer(self):
        from deeplearning4j_tpu.common.httputil import npy_view
        x = np.arange(24, dtype=np.float32).reshape(4, 6)
        buf = io.BytesIO()
        np.save(buf, x)
        raw = buf.getvalue()
        v = npy_view(raw)
        np.testing.assert_array_equal(v, x)
        assert v.dtype == x.dtype and v.shape == x.shape
        # a view, not a copy: no ndarray owns this memory and the
        # bytes object's buffer is the backing store (read-only)
        assert not v.flags.owndata
        assert not v.flags.writeable
        assert np.shares_memory(v, np.frombuffer(raw, np.uint8))

    def test_npy_view_fortran_order_and_float64(self):
        from deeplearning4j_tpu.common.httputil import npy_view
        x = np.asfortranarray(
            np.random.RandomState(0).randn(3, 5))
        buf = io.BytesIO()
        np.save(buf, x)
        np.testing.assert_array_equal(npy_view(buf.getvalue()), x)

    def test_npy_view_rejects_junk_and_pickles(self):
        from deeplearning4j_tpu.common.httputil import npy_view
        with pytest.raises(ValueError):
            npy_view(b"not an npy payload at all")
        obj = np.array([{"a": 1}], dtype=object)
        buf = io.BytesIO()
        np.save(buf, obj, allow_pickle=True)
        with pytest.raises(ValueError):
            npy_view(buf.getvalue())

    def test_npy_header_plus_buffer_equals_np_save(self):
        from deeplearning4j_tpu.common.httputil import npy_header
        x = np.random.RandomState(2).randn(7, 3).astype(np.float32)
        buf = io.BytesIO()
        np.save(buf, x)
        streamed = npy_header(x) + memoryview(x).cast("B").tobytes()
        assert streamed == buf.getvalue()


# ----------------------------------------------------------------------
class TestHttpPlumbing:
    def test_bind_host_env_applies_to_both_servers(self, monkeypatch):
        from deeplearning4j_tpu.common.httputil import bind_host
        monkeypatch.setenv("DL4J_TPU_HTTP_HOST", "0.0.0.0")
        assert bind_host() == "0.0.0.0"
        _, reg, srv = _serve()
        try:
            assert srv._httpd.server_address[0] == "0.0.0.0"
            # url maps the wildcard bind back to loopback for clients
            assert srv.url.startswith("http://127.0.0.1:")
            assert _post(srv.url, "m", {"inputs": [[0.0] * 8]})[0] \
                == 200
        finally:
            srv.stop()
            reg.shutdown()
        from deeplearning4j_tpu.ui.server import UIServer
        ui = UIServer()                   # fresh instance, not the
        ui.start(port=0)                  # singleton: tests stay isolated
        try:
            assert ui._httpd.server_address[0] == "0.0.0.0"
            assert urllib.request.urlopen(
                ui.url + "/metrics").status == 200
        finally:
            ui.stop()
