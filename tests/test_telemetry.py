"""Unified telemetry spine tests (ISSUE 2): registry semantics,
thread-safety, Prometheus rendering, the ``/metrics`` endpoint, the
chrome-trace span buffer, and — the part that matters — the hot paths
(prefetcher, compile cache, fit funnels) actually recording during a
tiny ``fit()``."""
import json
import math
import subprocess
import sys
import threading
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.telemetry import (DEFAULT_BUCKETS,
                                                 MetricsRegistry,
                                                 MetricsReporterListener)

_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_registry():
    MetricsRegistry._reset_for_tests()
    yield
    MetricsRegistry._reset_for_tests()


def _net_and_data(n=64):
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    rng = np.random.RandomState(0)
    x = rng.randn(n, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
         .list()
         .layer(DenseLayer(n_out=8, activation=Activation.RELU))
         .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                            loss_function=LossFunction.MCXENT))
         .set_input_type(InputType.feed_forward(4)).build())).init()
    return net, DataSet(x, y)


class TestRegistry:
    def test_counter_gauge_basics(self):
        c = telemetry.counter("dl4j_t_total", "help")
        c.inc()
        c.inc(2, model="a")
        assert c.value() == 1
        assert c.value(model="a") == 2
        g = telemetry.gauge("dl4j_t_gauge", "help")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6

    def test_registration_idempotent_and_kind_checked(self):
        a = telemetry.counter("dl4j_t_same", "x")
        b = telemetry.counter("dl4j_t_same", "other help ignored")
        assert a is b
        with pytest.raises(ValueError, match="already registered"):
            telemetry.gauge("dl4j_t_same", "x")

    def test_histogram_bucketing(self):
        h = telemetry.histogram("dl4j_t_h", "x", buckets=(0.01, 0.1, 1))
        for v in (0.005, 0.01, 0.05, 0.5, 5.0):
            h.observe(v)
        s = h._series[()]
        # le=0.01 gets 0.005 AND the boundary value 0.01 (le is <=)
        assert s.counts == [2, 1, 1, 1]
        assert s.count == 5
        assert abs(s.sum - 5.565) < 1e-9
        assert h.count_of() == 5

    def test_histogram_quantile_estimate(self):
        h = telemetry.histogram("dl4j_t_q", "x",
                                buckets=(0.01, 0.1, 1.0))
        assert math.isnan(h.quantile(0.5))      # no observations yet
        for v in (0.005, 0.02, 0.05, 0.2, 5.0):
            h.observe(v)
        # median target 2.5 lands in the (0.01, 0.1] bucket (2 obs):
        # linear interpolation inside it
        q50 = h.quantile(0.5)
        assert 0.01 < q50 <= 0.1
        # +Inf observations clamp to the top finite edge
        assert h.quantile(0.99) == 1.0
        assert h.quantile(0.2) <= 0.01

    def test_histogram_quantile_empty_is_nan(self):
        """Regression: an empty series must answer NaN, not 0.0 — a
        0.0 p99 on a dashboard reads as 'everything was instant'
        when nothing was observed at all."""
        h = telemetry.histogram("dl4j_t_q_empty", "x",
                                buckets=(0.01, 0.1, 1.0))
        for q in (0.0, 0.5, 0.99):
            assert math.isnan(h.quantile(q))
        # an unseen label set is just as empty as an unseen series
        h.observe(0.05, model="a")
        assert math.isnan(h.quantile(0.5, model="b"))
        assert not math.isnan(h.quantile(0.5, model="a"))

    def test_disabled_records_nothing(self):
        reg = MetricsRegistry.get()
        reg.set_enabled(False)
        c = telemetry.counter("dl4j_t_off", "x")
        c.inc()
        telemetry.histogram("dl4j_t_off_h", "x").observe(1.0)
        with telemetry.span("off_span"):
            pass
        assert c.value() == 0
        assert telemetry.histogram("dl4j_t_off_h", "x").count_of() == 0
        assert not any(e["name"] == "off_span"
                       for e in telemetry.trace_events())

    def test_env_gate(self, monkeypatch):
        from deeplearning4j_tpu.common.environment import Environment
        monkeypatch.setenv("DL4J_TPU_TELEMETRY", "0")
        Environment.reset()
        MetricsRegistry._reset_for_tests()
        try:
            assert not MetricsRegistry.get().enabled
        finally:
            monkeypatch.delenv("DL4J_TPU_TELEMETRY")
            Environment.reset()
            MetricsRegistry._reset_for_tests()

    def test_thread_safety_concurrent_writers(self):
        c = telemetry.counter("dl4j_t_mt_total", "x")
        h = telemetry.histogram("dl4j_t_mt_h", "x")
        n_threads, n_ops = 8, 2000
        start = threading.Barrier(n_threads)

        def work(i):
            start.wait()
            for _ in range(n_ops):
                c.inc(worker=str(i % 2))
                h.observe(0.001)

        ts = [threading.Thread(target=work, args=(i,))
              for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        total = sum(c.value(worker=str(w)) for w in (0, 1))
        assert total == n_threads * n_ops       # no lost increments
        assert h.count_of() == n_threads * n_ops
        assert abs(h.sum_of() - n_threads * n_ops * 0.001) < 1e-6

    def test_prometheus_rendering(self):
        telemetry.counter("dl4j_t_c_total", "a counter").inc(
            3, model="mln")
        telemetry.gauge("dl4j_t_g", "a gauge").set(2.5)
        h = telemetry.histogram("dl4j_t_h_seconds", "a hist",
                                buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        text = MetricsRegistry.get().render_prometheus()
        assert "# TYPE dl4j_t_c_total counter" in text
        assert 'dl4j_t_c_total{model="mln"} 3' in text
        assert "# TYPE dl4j_t_g gauge" in text
        assert "dl4j_t_g 2.5" in text
        assert "# HELP dl4j_t_h_seconds a hist" in text
        # cumulative buckets + +Inf + sum/count
        assert 'dl4j_t_h_seconds_bucket{le="0.1"} 1' in text
        assert 'dl4j_t_h_seconds_bucket{le="1"} 2' in text
        assert 'dl4j_t_h_seconds_bucket{le="+Inf"} 2' in text
        assert "dl4j_t_h_seconds_count 2" in text

    def test_summary_snapshot(self):
        telemetry.counter("dl4j_t_c_total", "x").inc(model="a")
        telemetry.histogram("dl4j_t_h", "x").observe(2.0)
        s = MetricsRegistry.get().summary()
        assert s["dl4j_t_c_total"]["model=a"] == 1
        assert s["dl4j_t_h"][""]["count"] == 1
        assert s["dl4j_t_h"][""]["mean"] == 2.0
        json.dumps(s)                       # JSON-serializable


class TestSpans:
    def test_span_and_instant_events(self):
        with telemetry.span("outer", stage="test"):
            telemetry.instant("marker", k=1)
        events = telemetry.trace_events()
        names = [e["name"] for e in events]
        assert "outer" in names and "marker" in names
        outer = next(e for e in events if e["name"] == "outer")
        assert outer["ph"] == "X" and outer["dur"] >= 0
        assert outer["args"] == {"stage": "test"}

    def test_export_and_merge(self, tmp_path):
        with telemetry.span("a"):
            pass
        p1 = telemetry.export_chrome_trace(str(tmp_path / "t1.json"))
        doc = json.load(open(p1))
        assert any(e["name"] == "a" for e in doc["traceEvents"])
        assert doc["metadata"]["dropped_events"] == 0
        # merge with a jax.profiler-shaped second trace
        p2 = tmp_path / "t2.json"
        p2.write_text(json.dumps(
            {"traceEvents": [{"name": "tpu_op", "ph": "X", "pid": 9,
                              "tid": 1, "ts": 1, "dur": 2}]}))
        merged = telemetry.merge_chrome_traces(
            str(tmp_path / "m.json"), p1, str(p2))
        events = json.load(open(merged))["traceEvents"]
        assert {"a", "tpu_op"} <= {e["name"] for e in events}

    def test_merge_host_traces_keeps_named_scopes(self, tmp_path):
        """The layerprof join depends on three merge invariants: the
        ``dl4j.<scope>`` strings survive verbatim (attribute_trace
        keys on them), the pid remap keeps every event attached to
        its host's process_name row, and the clock shift keeps each
        host's event stream monotonic on the leader timeline."""
        leader = tmp_path / "leader.json"
        worker = tmp_path / "worker.json"
        leader.write_text(json.dumps({"traceEvents": [
            {"name": "dl4j.layer_0", "ph": "X", "pid": 7, "tid": 1,
             "ts": 100, "dur": 10},
            {"name": "jit_step", "ph": "X", "pid": 7, "tid": 1,
             "ts": 120, "dur": 5,
             "args": {"op_name": "dl4j.layer_1/dot"}},
        ]}))
        worker.write_text(json.dumps({"traceEvents": [
            {"name": "transpose(dl4j.layer_0)", "ph": "X", "pid": 7,
             "tid": 1, "ts": 5000, "dur": 8},
            {"name": "dl4j.encoder.ffn", "ph": "X", "pid": 7,
             "tid": 1, "ts": 5100, "dur": 12},
        ]}))
        merged = telemetry.merge_host_traces(
            str(tmp_path / "m.json"),
            {"path": str(leader), "host": "leader",
             "clock_offset_s": 0.0},
            {"path": str(worker), "host": "worker1",
             "clock_offset_s": 0.004})
        doc = json.load(open(merged))
        events = doc["traceEvents"]
        # scope strings survive verbatim, in names and in op_name args
        names = {e["name"] for e in events}
        assert {"dl4j.layer_0", "transpose(dl4j.layer_0)",
                "dl4j.encoder.ffn"} <= names
        jit = next(e for e in events if e["name"] == "jit_step")
        assert jit["args"]["op_name"] == "dl4j.layer_1/dot"
        # pid remap: same source pid 7 lands on distinct rows, each
        # labeled with its host
        proc = {e["pid"]: e["args"]["name"] for e in events
                if e.get("ph") == "M" and e["name"] == "process_name"}
        assert sorted(proc.values()) == ["leader", "worker1"]
        by_host = {proc[e["pid"]] for e in events if e.get("ph") == "X"}
        assert by_host == {"leader", "worker1"}
        # clock shift: worker events moved onto the leader clock
        # (-4000us) and each host's stream stays monotonic
        ffn = next(e for e in events if e["name"] == "dl4j.encoder.ffn")
        assert ffn["ts"] == 5100 - 4000
        for host in ("leader", "worker1"):
            ts = [e["ts"] for e in events
                  if e.get("ph") == "X" and proc[e["pid"]] == host]
            assert ts == sorted(ts)

    def test_buffer_cap_counts_drops(self, tmp_path):
        buf = telemetry._trace_buffer
        old_max = buf.max_events
        buf.max_events = len(buf.events) + 1
        try:
            with telemetry.span("kept"):
                pass
            with telemetry.span("dropped"):
                pass
            assert buf.dropped == 1
            doc = json.load(open(telemetry.export_chrome_trace(
                str(tmp_path / "t.json"))))
            assert doc["metadata"]["dropped_events"] == 1
        finally:
            buf.max_events = old_max


class TestMetricsEndpoint:
    def test_metrics_roundtrip(self):
        from deeplearning4j_tpu.ui import UIServer
        telemetry.counter("dl4j_t_served_total", "x").inc(5)
        server = UIServer.get_instance().start(port=0)
        try:
            resp = urllib.request.urlopen(server.url + "/metrics")
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
            assert "dl4j_t_served_total 5" in text
            assert "# TYPE dl4j_t_served_total counter" in text
        finally:
            server.stop()


class TestInstrumentedFit:
    def test_fit_records_step_prefetch_and_cache_metrics(self):
        """The acceptance-criteria smoke: a tiny fit() over a real
        iterator yields non-zero step-time histogram counts, prefetch
        queue-depth samples + staged batches, and compile-cache
        hit/miss counters — all visible in one Prometheus page."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import \
            ListDataSetIterator
        net, ds = _net_and_data(64)
        batches = [DataSet(ds.features[i:i + 16], ds.labels[i:i + 16])
                   for i in range(0, 64, 16)]
        it = ListDataSetIterator(batches, batch_size=16)
        net.fit(it, n_epochs=2)

        h = telemetry.histogram("dl4j_train_step_seconds", "")
        assert h.count_of(model="MultiLayerNetwork") == 8
        assert h.sum_of(model="MultiLayerNetwork") > 0
        staged = telemetry.counter(
            "dl4j_prefetch_batches_staged_total", "")
        assert staged.value() == 8
        stall = telemetry.histogram("dl4j_feed_stall_seconds", "")
        # one observation per queue pop (incl. the end-of-epoch
        # sentinel pull): at least one per consumed batch
        assert stall.count_of(source="device_prefetch") >= 8
        hits = telemetry.counter("dl4j_compile_cache_hits_total", "")
        misses = telemetry.counter(
            "dl4j_compile_cache_misses_total", "")
        name = "MultiLayerNetwork train step"
        assert misses.value(network=name) == 1      # one signature
        assert hits.value(network=name) == 7        # 7 reuses
        # the whole panel renders
        text = MetricsRegistry.get().render_prometheus()
        assert "dl4j_train_step_seconds_count" in text
        assert "dl4j_prefetch_queue_depth" in text

    def test_retrace_counter_on_shape_churn(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net, ds = _net_and_data(64)
        net.fit(DataSet(ds.features[:32], ds.labels[:32]))
        net.fit(DataSet(ds.features[:48], ds.labels[:48]))
        retr = telemetry.counter("dl4j_retrace_total", "")
        assert retr.value(network="MultiLayerNetwork train step") == 1
        assert any(e["name"] == "retrace"
                   for e in telemetry.trace_events())

    def test_reporter_listener_folds_snapshots(self):
        from deeplearning4j_tpu.ui import InMemoryStatsStorage
        storage = InMemoryStatsStorage()
        net, ds = _net_and_data()
        net.set_listeners(MetricsReporterListener(storage, frequency=2))
        net.fit(ds, n_epochs=5)
        reports = storage.get_reports()
        assert len(reports) == 3                    # iterations 0,2,4
        tel = reports[-1]["telemetry"]
        assert "dl4j_train_step_seconds" in tel
        assert tel["dl4j_train_step_seconds"][
            "model=MultiLayerNetwork"]["count"] >= 4

    def test_checkpoint_metrics(self, tmp_path):
        from deeplearning4j_tpu.utils.checkpoint import \
            CheckpointListener
        net, ds = _net_and_data()
        lis = CheckpointListener(tmp_path, save_every_n_epochs=1,
                                 asynchronous=False)
        net.add_listeners(lis)
        net.fit([ds], n_epochs=2)
        assert telemetry.histogram(
            "dl4j_checkpoint_save_seconds", "").count_of() == 2
        saved_bytes = telemetry.counter(
            "dl4j_checkpoint_bytes_total", "").value(op="save")
        assert saved_bytes > 0
        CheckpointListener.load_checkpoint(tmp_path)
        assert telemetry.histogram(
            "dl4j_checkpoint_load_seconds", "").count_of() == 1
        assert telemetry.counter(
            "dl4j_checkpoint_bytes_total", "").value(op="load") > 0

    def test_inference_queue_metrics(self):
        from deeplearning4j_tpu.parallel.inference import \
            ParallelInference
        net, ds = _net_and_data()
        pi = (ParallelInference.Builder(net).workers(1)
              .batch_limit(8).build())
        try:
            futs = [pi.submit(ds.features[i:i + 2])
                    for i in range(0, 8, 2)]
            for f in futs:
                assert f.result(timeout=30).shape[-1] == 2
        finally:
            pi.shutdown()
        assert telemetry.counter(
            "dl4j_inference_requests_total", "").value(
                mode="BATCHED") == 4
        assert telemetry.histogram(
            "dl4j_inference_queue_seconds", "").count_of() == 4
        occ = telemetry.histogram("dl4j_inference_batch_occupancy", "")
        assert occ.count_of() >= 1


class TestOverhead:
    def test_disabled_overhead_is_trivial(self):
        """With the gate off a record call must cost no more than a
        bare method call — budget is generous (5µs) to stay robust on
        loaded CI, but catches accidental work on the off path."""
        import time
        reg = MetricsRegistry.get()
        c = telemetry.counter("dl4j_t_ovh_total", "x")
        reg.set_enabled(False)
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            c.inc()
        per_op = (time.perf_counter() - t0) / n
        assert per_op < 5e-6

    def test_enabled_step_overhead_under_one_pct(self):
        """ISSUE acceptance: <1% step-time impact with telemetry on.
        Measured deterministically: the FULL per-step record (a
        step_span = one histogram observe + one trace event) is timed
        per-op and compared against a 1ms step — the floor of any
        real accelerator step (CPU-proxy LeNet steps are ~1ms, TPU
        ResNet/BERT steps are tens of ms, so 1% here is the worst
        case). bench_telemetry.py measures the real fit() funnel."""
        import time
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            with telemetry.step_span("ovh"):
                pass
        per_step = (time.perf_counter() - t0) / n
        telemetry._trace_buffer.clear()
        assert per_step < 0.01 * 1e-3       # <1% of a 1ms step


class TestCatalogChecker:
    def test_catalog_in_sync(self):
        """Tier-1 wiring for scripts/check_telemetry_catalog.py: every
        registered metric is documented in README, none are stale."""
        out = subprocess.run(
            [sys.executable,
             str(_ROOT / "scripts" / "check_telemetry_catalog.py")],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr


class TestSatellites:
    def test_score_listener_logs_not_prints(self, capsys, caplog):
        import logging
        from deeplearning4j_tpu.optimize.listeners import \
            ScoreIterationListener
        net, ds = _net_and_data()
        net.set_listeners(ScoreIterationListener(1))
        with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
            net.fit(ds)
        assert "Score at iteration" in caplog.text
        assert "Score at iteration" not in capsys.readouterr().out

    def test_score_listener_stdout_opt_in(self, capsys):
        from deeplearning4j_tpu.optimize.listeners import \
            ScoreIterationListener
        net, ds = _net_and_data()
        net.set_listeners(ScoreIterationListener(1, stdout=True))
        net.fit(ds)
        assert "Score at iteration" in capsys.readouterr().out

    def test_performance_listener_logs_not_prints(self, capsys, caplog):
        import logging
        from deeplearning4j_tpu.optimize.listeners import \
            PerformanceListener
        net, ds = _net_and_data()
        net.set_listeners(PerformanceListener(frequency=1))
        with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
            net.fit(ds, n_epochs=3)
        assert "iters/sec" in caplog.text
        assert "iters/sec" not in capsys.readouterr().out

    def test_profiling_listener_counts_drops(self, tmp_path, caplog):
        import logging
        from deeplearning4j_tpu.ui import ProfilingListener
        p = str(tmp_path / "trace.json")
        prof = ProfilingListener(p, max_events=2)
        net, ds = _net_and_data()
        net.set_listeners(prof)
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu"):
            net.fit([ds, ds, ds, ds, ds, ds], n_epochs=1)
        doc = json.load(open(p))
        assert len(doc["traceEvents"]) == 2
        assert doc["metadata"]["dropped_events"] == prof.dropped > 0
        assert "dropped" in caplog.text

    def test_file_stats_storage_skips_corrupt_tail(self, tmp_path,
                                                   caplog):
        import logging
        from deeplearning4j_tpu.ui import FileStatsStorage
        p = tmp_path / "stats.jsonl"
        s = FileStatsStorage(str(p))
        s.put_report({"iteration": 0, "time": 1.0, "score": 2.0})
        s.put_report({"iteration": 1, "time": 2.0, "score": 1.0})
        # simulate a crash mid-append: truncated trailing line
        with open(p, "a") as f:
            f.write('{"iteration": 2, "time": 3.0, "sco')
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu"):
            again = FileStatsStorage(str(p))
        assert len(again.get_reports()) == 2
        assert again.latest()["iteration"] == 1
        assert "corrupt" in caplog.text
        # storage stays appendable after a dirty resume
        again.put_report({"iteration": 3, "time": 4.0, "score": 0.5})
        assert FileStatsStorage(str(p)).latest()["iteration"] == 3
