"""Bundled pretrained zoo checkpoints (SURVEY.md D15: the reference
ZooModel ships usable weights; here they are trained in-repo by
scripts/train_pretrained.py on the deterministic synthetic surrogates
and committed under models/pretrained/). These tests gate the
COMMITTED artifacts — load offline, hit the recorded accuracy, and
fine-tune via TransferLearning."""
import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import (LeNet, ResNet50, char_rnn,
                                           lenet, pretrained_meta,
                                           resnet_cifar)


class TestBundledCheckpoints:
    def test_lenet_pretrained_accuracy(self):
        from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
        net = lenet(pretrained=True)
        it = MnistDataSetIterator(512, train=False, num_examples=2000)
        acc = float(net.evaluate(it).accuracy())
        assert acc >= 0.99, acc
        assert pretrained_meta()["lenet"]["accuracy"] >= 0.99

    def test_init_pretrained_default_path(self):
        net = LeNet().init_pretrained()
        out = np.asarray(net.output(np.zeros((2, 784), np.float32)))
        assert out.shape == (2, 10)

    def test_resnet_cifar_pretrained_accuracy(self):
        from deeplearning4j_tpu.datasets.vision import \
            Cifar10DataSetIterator
        net = resnet_cifar(pretrained=True)
        it = Cifar10DataSetIterator(512, train=False,
                                    num_examples=1000)
        acc = float(net.evaluate(it).accuracy())
        assert acc >= 0.90, acc

    def test_resnet_cifar_hard_split_gate_not_saturated(self):
        """The quality gate proper (round-2 verdict Weak #4): a
        held-out split hard enough that the gate sits BELOW
        saturation — asserted here on the committed checkpoint, not
        just recorded in meta.json."""
        from deeplearning4j_tpu.models.pretrained_gates import (
            HARD_GATE, eval_resnet_cifar_hard)
        net = resnet_cifar(pretrained=True)
        hard = eval_resnet_cifar_hard(net, n=1000)
        assert HARD_GATE[0] <= hard < HARD_GATE[1], hard
        meta = pretrained_meta()["resnet_cifar"]
        assert HARD_GATE[0] <= meta["hard_split_accuracy"] \
            < HARD_GATE[1]

    def test_resnet50_class_route(self):
        net = ResNet50().init_pretrained()   # CIFAR-scale checkpoint
        x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(
            np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (2, 10)

    def test_charrnn_pretrained_predicts_text(self):
        net, chars = char_rnn(pretrained=True)
        idx = {c: i for i, c in enumerate(chars)}
        text = "the quick brown fox jumps over the lazy dog. "
        n = len(chars)
        eye = np.eye(n, dtype=np.float32)
        ids = np.asarray([idx[c] for c in text], np.int32)
        x = eye[ids[:-1]][None]
        probs = np.asarray(net.output(x))[0]
        acc = float((probs.argmax(-1) == ids[1:]).mean())
        assert acc >= 0.85, acc

    def test_missing_pretrained_raises_helpfully(self):
        from deeplearning4j_tpu.models.zoo import AlexNet
        with pytest.raises(ValueError, match="no bundled pretrained"):
            AlexNet().init_pretrained()


class TestTransferFromPretrained:
    def test_finetune_lenet_to_new_task(self):
        """Reference workflow: load zoo weights, freeze the feature
        extractor, swap the head, fine-tune on a new TASK over the
        same domain (classes relabeled mod 5 — the synthetic
        surrogate's features are template-matched, so a different
        template seed would be a domain shift, not transfer)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.mnist import synthetic_mnist
        from deeplearning4j_tpu.learning import Adam
        from deeplearning4j_tpu.lossfunctions import LossFunction
        from deeplearning4j_tpu.nn.conf.layers import OutputLayer
        from deeplearning4j_tpu.nn.transferlearning import (
            FineTuneConfiguration, TransferLearning)

        base = lenet(pretrained=True)
        ft = (TransferLearning.Builder(base)
              .fine_tune_configuration(
                  FineTuneConfiguration(updater=Adam(1e-3)))
              .set_feature_extractor(3)      # freeze convs + pools
              .remove_output_layer()
              .add_layer(OutputLayer(
                  n_out=5,
                  loss_function=LossFunction.NEGATIVELOGLIKELIHOOD,
                  activation="softmax"))
              .build())

        xtr, ytr = synthetic_mnist(2000, train=True)
        xte, yte = synthetic_mnist(500, train=False)
        ytr, yte = ytr % 5, yte % 5          # 5-class relabel
        eye = np.eye(5, dtype=np.float32)
        ds = DataSet(xtr, eye[ytr])
        for _ in range(40):          # full-batch Adam steps
            ft.fit(ds)
        pred = np.asarray(ft.output(xte)).argmax(-1)
        acc = float((pred == yte).mean())
        assert acc >= 0.90, acc

    def test_customized_architecture_rejected(self):
        """Customized dataclass fields cannot apply to a bundled
        checkpoint (it carries its own config) — loading must raise,
        not silently return a different architecture."""
        with pytest.raises(ValueError, match="customizes"):
            ResNet50(num_classes=5).init_pretrained()
        with pytest.raises(ValueError, match="customizes"):
            LeNet(height=32, width=32).init_pretrained()
