# dl4j-lint: disable-file=all  (fixture snippets below would trip
# every rule by design — this file must never join the repo scan)
"""dl4j-lint: per-rule fixtures through the real lint pipeline.

Each rule gets the four variants the gate must distinguish: a
violating snippet (finding fires), a clean snippet (no finding), a
suppressed snippet (site-level ``# dl4j-lint: disable=<rule>``), and a
baselined run (finding fires but is grandfathered, exit stays 0).
Fixtures are written to ``tmp_path`` trees shaped like the repo so the
per-rule ``wants()`` scoping applies exactly as in CI.
"""
from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_ROOT))

from scripts.dl4j_lint import lint_repo, load_baseline  # noqa: E402
from scripts.dl4j_lint.core import (Baseline, gate,  # noqa: E402
                                    write_baseline)


def _lint(tmp_path: Path, rules, files: dict, readme: str = ""):
    """Write ``{relpath: source}`` fixtures under tmp_path and lint
    them with the selected rules."""
    paths = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        paths.append(p)
    if readme:
        (tmp_path / "README.md").write_text(textwrap.dedent(readme))
    return lint_repo(tmp_path, rule_names=rules, files=paths)


def _keys(findings):
    return {f.key for f in findings}


# ----------------------------------------------------------------------
class TestJitPurity:
    REL = "deeplearning4j_tpu/mod.py"

    def test_decorated_root_impurity_fires(self, tmp_path):
        fs = _lint(tmp_path, ["jit-purity"], {self.REL: """\
            import time
            import jax

            @jax.jit
            def step(x):
                t0 = time.time()
                return x + t0
            """})
        assert any(f.rule == "jit-purity" and "time.time" in f.message
                   for f in fs)

    def test_interprocedural_chain_fires(self, tmp_path):
        """Impurity two calls deep from a jit CALL-SITE root — the
        reachability walk, not just the decorator scan."""
        fs = _lint(tmp_path, ["jit-purity"], {self.REL: """\
            import numpy as np
            import jax

            def helper(x):
                return x * np.random.rand()

            def step(x):
                return helper(x) + 1

            fast_step = jax.jit(step)
            """})
        assert any(f.rule == "jit-purity"
                   and "np.random" in f.message for f in fs)

    def test_pure_fn_is_clean(self, tmp_path):
        fs = _lint(tmp_path, ["jit-purity"], {self.REL: """\
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x):
                return jnp.tanh(x) * 2.0
            """})
        assert fs == []

    def test_suppression_comment_silences_site(self, tmp_path):
        fs = _lint(tmp_path, ["jit-purity"], {self.REL: """\
            import time
            import jax

            @jax.jit
            def step(x):
                # trace-time stamp is deliberate here
                # dl4j-lint: disable=jit-purity
                t0 = time.time()
                return x + t0
            """})
        assert fs == []


# ----------------------------------------------------------------------
class TestLockDiscipline:
    REL = "deeplearning4j_tpu/serving/svc.py"   # in-scope path

    VIOLATING = """\
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def start(self):
                t = threading.Thread(target=self._loop)
                t.start()

            def _loop(self):
                self.count += 1

            def snapshot(self):
                return self.count
        """

    def test_unlocked_shared_mutation_fires(self, tmp_path):
        fs = _lint(tmp_path, ["lock-discipline"],
                   {self.REL: self.VIOLATING})
        assert any(f.rule == "lock-discipline"
                   and f.key.endswith(":count") for f in fs)

    def test_guarded_mutation_is_clean(self, tmp_path):
        fs = _lint(tmp_path, ["lock-discipline"], {self.REL: """\
            import threading

            class Svc:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0

                def start(self):
                    t = threading.Thread(target=self._loop)
                    t.start()

                def _loop(self):
                    with self._lock:
                        self.count += 1

                def snapshot(self):
                    with self._lock:
                        return self.count
            """})
        assert fs == []

    def test_threadsafe_container_is_clean(self, tmp_path):
        """queue.Queue carries its own lock — not a finding."""
        fs = _lint(tmp_path, ["lock-discipline"], {self.REL: """\
            import queue
            import threading

            class Svc:
                def __init__(self):
                    self.q = queue.Queue()

                def start(self):
                    t = threading.Thread(target=self._loop)
                    t.start()

                def _loop(self):
                    self.q.put(1)

                def submit(self, item):
                    self.q.put(item)
            """})
        assert fs == []

    def test_suppression_on_line_above(self, tmp_path):
        src = self.VIOLATING.replace(
            "        self.count += 1",
            "        # benign torn read is fine here\n"
            "        # dl4j-lint: disable=lock-discipline\n"
            "        self.count += 1")
        fs = _lint(tmp_path, ["lock-discipline"], {self.REL: src})
        assert fs == []


# ----------------------------------------------------------------------
class TestEnvRegistry:
    ENV_MODULE = "deeplearning4j_tpu/common/environment.py"

    def test_undocumented_read_fires_both_registries(self, tmp_path):
        fs = _lint(tmp_path, ["env-registry"], {
            "deeplearning4j_tpu/mod.py": """\
                import os
                KNOB = os.environ.get("DL4J_TPU_FIXTURE_KNOB", "0")
                """,
            self.ENV_MODULE: '"""Env vars: (none yet)."""\n',
        }, readme="# fixture\n")
        keys = _keys(fs)
        assert "env-registry:env-doc:DL4J_TPU_FIXTURE_KNOB" in keys
        assert "env-registry:readme:DL4J_TPU_FIXTURE_KNOB" in keys

    def test_documented_read_is_clean(self, tmp_path):
        fs = _lint(tmp_path, ["env-registry"], {
            "deeplearning4j_tpu/mod.py": """\
                import os
                KNOB = os.environ.get("DL4J_TPU_FIXTURE_KNOB", "0")
                """,
            self.ENV_MODULE:
                '"""Env vars: DL4J_TPU_FIXTURE_KNOB."""\n',
        }, readme="""\
            ## Environment variables
            | Variable | Default | Meaning |
            |---|---|---|
            | `DL4J_TPU_FIXTURE_KNOB` | `0` | fixture knob. |
            """)
        assert fs == []

    def test_stale_entries_fire(self, tmp_path):
        """A README row and a docstring entry nothing reads are as
        misleading as missing docs."""
        fs = _lint(tmp_path, ["env-registry"], {
            "deeplearning4j_tpu/mod.py": "X = 1\n",
            self.ENV_MODULE: '"""Env vars: DL4J_TPU_GONE_KNOB."""\n',
        }, readme="""\
            ## Environment variables
            | Variable | Default | Meaning |
            |---|---|---|
            | `DL4J_TPU_GHOST_KNOB` | `0` | nothing reads me. |
            """)
        keys = _keys(fs)
        assert "env-registry:stale-readme:DL4J_TPU_GHOST_KNOB" in keys
        assert "env-registry:stale-env-doc:DL4J_TPU_GONE_KNOB" in keys

    def test_docstring_mention_is_not_a_read(self, tmp_path):
        """The catalog inside environment.py's own docstrings must not
        count as code reads (it would make every entry self-reading)."""
        fs = _lint(tmp_path, ["env-registry"], {
            self.ENV_MODULE:
                '"""Env vars: DL4J_TPU_GONE_KNOB."""\n',
        }, readme="# fixture\n")
        assert _keys(fs) == {
            "env-registry:stale-env-doc:DL4J_TPU_GONE_KNOB"}


# ----------------------------------------------------------------------
class TestMetricRegistry:
    REL = "deeplearning4j_tpu/mod.py"
    REG = """\
        from deeplearning4j_tpu.common import telemetry

        def touch():
            telemetry.counter("dl4j_fixture_total", "d").inc()
        """

    def test_unregistered_metric_fires(self, tmp_path):
        fs = _lint(tmp_path, ["metric-registry"], {self.REL: self.REG},
                   readme="## Observability\nno table here\n")
        assert "metric-registry:missing:dl4j_fixture_total" in _keys(fs)

    def test_documented_metric_is_clean(self, tmp_path):
        fs = _lint(tmp_path, ["metric-registry"], {self.REL: self.REG},
                   readme="""\
                   ## Observability
                   | Metric | Type | Meaning |
                   |---|---|---|
                   | `dl4j_fixture_total` | counter | fixture. |
                   """)
        assert fs == []

    def test_kind_mismatch_and_stale_fire(self, tmp_path):
        fs = _lint(tmp_path, ["metric-registry"], {self.REL: self.REG},
                   readme="""\
                   ## Observability
                   | Metric | Type | Meaning |
                   |---|---|---|
                   | `dl4j_fixture_total` | gauge | wrong kind. |
                   | `dl4j_ghost_total` | counter | stale. |
                   """)
        keys = _keys(fs)
        assert "metric-registry:kind:dl4j_fixture_total" in keys
        assert "metric-registry:stale:dl4j_ghost_total" in keys


# ----------------------------------------------------------------------
class TestSpecInvariants:
    REL = "deeplearning4j_tpu/mod.py"

    def test_pipe_spec_literal_fires(self, tmp_path):
        fs = _lint(tmp_path, ["spec-invariants"], {self.REL: """\
            from jax.sharding import PartitionSpec as P

            SPEC = P("pipe", None)
            """})
        assert any(f.rule == "spec-invariants"
                   and ":pipe-spec:" in f.key for f in fs)

    def test_use_after_donation_fires(self, tmp_path):
        fs = _lint(tmp_path, ["spec-invariants"], {self.REL: """\
            import jax

            def g(p, x):
                return p + x

            def run(p, x):
                f = jax.jit(g, donate_argnums=(0,))
                y = f(p, x)
                return p + y
            """})
        assert any(f.rule == "spec-invariants"
                   and f.key.endswith(":donated:f:p") for f in fs)

    def test_rebind_resurrects_donated_name(self, tmp_path):
        """The idiomatic ``params = step(params, ...)`` donation
        pattern must stay clean."""
        fs = _lint(tmp_path, ["spec-invariants"], {self.REL: """\
            import jax

            def g(p, x):
                return p + x

            def run(p, x):
                f = jax.jit(g, donate_argnums=(0,))
                p = f(p, x)
                return p + 1
            """})
        assert fs == []

    def test_suppression_silences_pipe_spec(self, tmp_path):
        fs = _lint(tmp_path, ["spec-invariants"], {self.REL: """\
            from jax.sharding import PartitionSpec as P

            # stage-partitioned layout owns this literal
            # dl4j-lint: disable=spec-invariants
            SPEC = P("pipe", None)
            """})
        assert fs == []


# ----------------------------------------------------------------------
class TestBaselineGate:
    def _finding(self, tmp_path):
        fs = _lint(tmp_path, ["spec-invariants"],
                   {"deeplearning4j_tpu/mod.py":
                    'SPEC = PartitionSpec("pipe")\n'})
        assert len(fs) == 1
        return fs

    def test_baselined_finding_passes_gate(self, tmp_path):
        fs = self._finding(tmp_path)
        res = gate(fs, Baseline({fs[0].key: "grandfathered"}))
        assert not res.failed
        assert res.new == [] and res.grown == {}

    def test_new_finding_fails_gate(self, tmp_path):
        fs = self._finding(tmp_path)
        res = gate(fs, Baseline({}))
        assert res.failed and res.new == fs

    def test_count_growth_fails_even_with_rotated_keys(self, tmp_path):
        """Two findings of a rule baselined at one entry: even if one
        key matches, the rule's count grew — the debt may not ratchet
        up under churned keys."""
        fs = _lint(tmp_path, ["spec-invariants"],
                   {"deeplearning4j_tpu/mod.py":
                    'A = PartitionSpec("pipe")\n'
                    'B = PartitionSpec("pipe", None)\n'})
        assert len(fs) == 2
        res = gate(fs, Baseline({fs[0].key: "grandfathered"}))
        assert res.failed

    def test_stale_baseline_keys_reported(self, tmp_path):
        res = gate([], Baseline({"spec-invariants:gone:key": "old"}))
        assert not res.failed
        assert res.stale == ["spec-invariants:gone:key"]

    def test_roundtrip_write_then_load(self, tmp_path):
        fs = self._finding(tmp_path)
        p = tmp_path / "baseline.json"
        write_baseline(p, fs, Baseline({fs[0].key: "kept reason"}))
        bl = load_baseline(p)
        assert bl.reasons == {fs[0].key: "kept reason"}

    def test_load_rejects_missing_reason(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps(
            {"findings": [{"key": "jit-purity:x", "reason": ""}]}))
        with pytest.raises(ValueError, match="no reason"):
            load_baseline(p)


# ----------------------------------------------------------------------
class TestCli:
    """The exact invocation ci_check.sh gate 12 runs."""

    _SEEDS = {
        "jit-purity": ("deeplearning4j_tpu/mod.py",
                       "import time, jax\n\n"
                       "@jax.jit\n"
                       "def f(x):\n"
                       "    return x + time.time()\n"),
        "lock-discipline": (
            "deeplearning4j_tpu/serving/svc.py",
            TestLockDiscipline.VIOLATING),
        "env-registry": ("deeplearning4j_tpu/mod.py",
                         "import os\n"
                         "K = os.environ.get('DL4J_TPU_SEEDED', '')\n"),
        "metric-registry": ("deeplearning4j_tpu/mod.py",
                            TestMetricRegistry.REG),
        "spec-invariants": ("deeplearning4j_tpu/mod.py",
                            "SPEC = PartitionSpec('pipe')\n"),
    }

    @pytest.mark.parametrize("rule", sorted(_SEEDS))
    def test_seeded_violation_exits_nonzero(self, tmp_path, rule):
        rel, src = self._SEEDS[rule]
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        r = subprocess.run(
            [sys.executable, "-m", "scripts.dl4j_lint",
             "--root", str(tmp_path), "--rules", rule, str(p)],
            cwd=_ROOT, capture_output=True, text=True, timeout=120)
        assert r.returncode == 1, r.stdout + r.stderr
        assert f"[{rule}]" in r.stdout

    def test_repo_is_clean_under_checked_in_baseline(self):
        r = subprocess.run(
            [sys.executable, "-m", "scripts.dl4j_lint",
             "--baseline", "scripts/dl4j_lint_baseline.json"],
            cwd=_ROOT, capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "OK:" in r.stdout

    def test_baselined_seed_exits_zero(self, tmp_path):
        rel, src = self._SEEDS["spec-invariants"]
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        findings = lint_repo(tmp_path, ["spec-invariants"], [p])
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps({"findings": [
            {"key": f.key, "reason": "seeded fixture"}
            for f in findings]}))
        r = subprocess.run(
            [sys.executable, "-m", "scripts.dl4j_lint",
             "--root", str(tmp_path), "--rules", "spec-invariants",
             "--baseline", str(bl), str(p)],
            cwd=_ROOT, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
