"""ImageLoader decode/resize fidelity (SURVEY.md V3). The r5 ETL
benchmark moved file decodes onto Pillow's C resize (GIL-released,
3.5x faster than the numpy fallback per core); these tests pin the
two paths to each other and the JPEG draft-mode fast path to the
full-decode result."""
import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402

from deeplearning4j_tpu.datavec.image import (  # noqa: E402
    ImageLoader, _resize_bilinear)


def _photo(size=256, seed=0):
    rng = np.random.RandomState(seed)
    y, x = np.mgrid[0:size, 0:size]
    img = np.clip((y * 0.4 + x * 0.3)[:, :, None] % 256 +
                  rng.randint(-30, 30, (size, size, 3)), 0,
                  255).astype(np.uint8)
    return img


def test_file_decode_matches_array_path(tmp_path):
    """PNG (lossless) file through the PIL resize vs the same pixels
    through the numpy-array fallback path: the two bilinear resamplers
    differ only by PIL's antialias taps — close, not identical."""
    img = _photo()
    p = str(tmp_path / "img.png")
    Image.fromarray(img).save(p)
    loader = ImageLoader(224, 224, 3)
    from_file = loader.load(p)
    from_array = loader.load(img)
    assert from_file.shape == from_array.shape == (224, 224, 3)
    assert np.mean(np.abs(from_file - from_array)) < 4.0
    assert np.corrcoef(from_file.ravel(),
                       from_array.ravel())[0, 1] > 0.99


def test_jpeg_draft_downscale_close_to_full_decode(tmp_path):
    """Big downscale (512 -> 64) engages JPEG draft mode (DCT-domain
    scaling); the result must stay close to a full decode + resize."""
    img = _photo(512, seed=1)
    p = str(tmp_path / "img.jpg")
    Image.fromarray(img).save(p, quality=95)
    small = ImageLoader(64, 64, 3).load(p)
    with Image.open(p) as im:        # full decode, then C resize
        full = np.asarray(im.convert("RGB"))
    ref = _resize_bilinear(full, 64, 64)
    assert small.shape == (64, 64, 3)
    assert np.mean(np.abs(small - ref)) < 6.0


def test_grayscale_and_upscale(tmp_path):
    img = _photo(32, seed=2)
    p = str(tmp_path / "img.png")
    Image.fromarray(img).save(p)
    g = ImageLoader(48, 48, 1).load(p)
    assert g.shape == (48, 48, 1)
    assert g.dtype == np.float32


def test_exact_resize_bitwise_matches_array_path(tmp_path):
    """``exact_resize=True`` removes the r5 divergence: a lossless
    file decode routes through the SAME half-pixel numpy resize as an
    ndarray input — bit-identical, from PNG and from JPEG (draft mode
    disabled so the resize sees full-size pixels)."""
    img = _photo()
    loader = ImageLoader(224, 224, 3, exact_resize=True)
    p = str(tmp_path / "img.png")
    Image.fromarray(img).save(p)
    np.testing.assert_array_equal(loader.load(p), loader.load(img))
    # default loader on the same file: PIL's antialiased resize —
    # close, but NOT the array path's pixels (the documented default)
    default = ImageLoader(224, 224, 3).load(p)
    assert np.any(default != loader.load(p))
    # JPEG: lossy decode, but file vs decoded-array must still agree
    # bitwise once both go through the numpy resize
    pj = str(tmp_path / "img.jpg")
    Image.fromarray(img).save(pj, quality=95)
    with Image.open(pj) as im:
        decoded = np.asarray(im.convert("RGB"))
    np.testing.assert_array_equal(loader.load(pj),
                                  loader.load(decoded))
