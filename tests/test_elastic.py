"""Elastic world-size changes (ISSUE 11 tentpole c): resuming or
re-placing onto a mesh with a DIFFERENT device count must continue the
exact dense trajectory — dense/sharded/fsdp layouts round-trip through
the dense layout and re-ravel for the new shard count."""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning.updaters import Adam, is_fsdp
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.parallel import ParallelWrapper, UpdateExchange
from deeplearning4j_tpu.parallel.zero import (DP_SHARDED_KEY,
                                              fsdp_spec_shards,
                                              states_to_dense,
                                              states_to_sharded,
                                              to_sharded_state)


def _mlp(seed=42):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Adam(0.01))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return DataSet(x, y)


def _assert_tree_close(a, b, **kw):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


# -- updater-state re-ravel unit level --------------------------------------
def test_to_sharded_state_re_ravels_for_new_world_size():
    """Flat ZeRO-1 state raveled for 8 shards fed to an n=4 conversion
    must re-pad for 4 (not silently keep the 8-way padding), with the
    dense values preserved exactly."""
    net = _mlp()
    net.fit(_data())                       # materialize updater state
    dense = jax.tree_util.tree_map(np.asarray, net.updater_states)
    s8 = states_to_sharded(net.params, net.updater_states, 8)
    # same shard count: conversion is a no-op (identity)
    for k, sub in s8.items():
        if sub:
            assert to_sharded_state(net.params[k], sub, 8) is sub
    s4 = states_to_sharded(net.params, s8, 4)
    for k, sub in s4.items():
        if not sub:
            continue
        for flats in sub[DP_SHARDED_KEY].values():
            for flat in flats.values():
                assert flat.shape[0] % 4 == 0
    back = states_to_dense(net.params, s4)
    _assert_tree_close(dense, back, rtol=0, atol=0)


def test_fsdp_spec_shards_reads_world_size():
    from deeplearning4j_tpu.parallel.zero import params_to_fsdp
    net = _mlp()
    _, specs = params_to_fsdp(net.params, 8)
    assert fsdp_spec_shards(specs) == 8
    assert fsdp_spec_shards({}) is None
    assert fsdp_spec_shards(None) is None


# -- remesh trajectory equivalence ------------------------------------------
@pytest.mark.parametrize("mode", ["sharded", "fsdp"])
def test_remesh_8_4_8_continues_dense_trajectory(mode):
    """The ISSUE acceptance test: train 2 batches on an 8-way mesh,
    remesh to 4, train 2, remesh back to 8, train 2 — parameters must
    track a fixed dense 8-way run batch for batch (data-parallel SGD
    is world-size invariant for divisible batches)."""
    batches = [_data(64, seed=i) for i in range(6)]
    ref = _mlp(seed=7)
    pw_ref = ParallelWrapper.Builder(ref).workers(8) \
        .update_exchange("dense").build()
    el = _mlp(seed=7)
    pw_el = ParallelWrapper.Builder(el).workers(8) \
        .update_exchange(mode).build()

    def dense(m):
        return m.dense_params() if hasattr(m, "dense_params") \
            else m.params

    for i, ds in enumerate(batches):
        if i == 2:
            pw_el.remesh(workers=4)        # shrink: 8 -> 4
        elif i == 4:
            pw_el.remesh(workers=8)        # grow back: 4 -> 8
        pw_ref.fit_batch(ds)
        pw_el.fit_batch(ds)
        _assert_tree_close(ref.params, dense(el), rtol=2e-5, atol=1e-6)
    if mode == "fsdp":
        # flats really re-raveled to each world size along the way
        assert pw_el.update_exchange is UpdateExchange.FSDP
        assert all(is_fsdp(p) for p in el.params.values())
        for flat in jax.tree_util.tree_leaves(el.params):
            assert len(flat.addressable_shards) == 8
        assert fsdp_spec_shards(el._fsdp_specs) == 8


def test_remesh_fsdp_shrink_re_shards_residency():
    net = _mlp(seed=3)
    pw = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange("fsdp").build()
    pw.fit_batch(_data(64, seed=0))
    for flat in jax.tree_util.tree_leaves(net.params):
        assert len(flat.addressable_shards) == 8
    pw.remesh(workers=4)
    pw.fit_batch(_data(64, seed=1))
    assert pw.n_workers == 4
    for flat in jax.tree_util.tree_leaves(net.params):
        assert len(flat.addressable_shards) == 4
    assert fsdp_spec_shards(net._fsdp_specs) == 4


def test_remesh_mode_change_fsdp_to_dense_densifies():
    """A wrapper re-placing a previously-fsdp-resident model with a
    dense exchange must densify the stale flats first (the layout must
    always match the exchange about to consume it)."""
    net = _mlp(seed=5)
    ParallelWrapper.Builder(net).workers(8).update_exchange("fsdp") \
        .build().fit_batch(_data(64, seed=0))
    assert all(is_fsdp(p) for p in net.params.values())
    pw = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange("dense").build()
    pw.fit_batch(_data(64, seed=1))
    assert pw.update_exchange is UpdateExchange.DENSE
    assert not any(is_fsdp(p) for p in net.params.values())
    assert np.isfinite(float(net.score(_data(32, seed=9))))


# -- checkpoint resume across world sizes -----------------------------------
@pytest.mark.parametrize("mode,shrink", [
    ("sharded", 4), ("fsdp", 4), ("fsdp", 8),
], ids=["sharded-8to4", "fsdp-8to4", "fsdp-8to8"])
def test_checkpoint_resume_on_new_world_size_continues_trajectory(
        tmp_path, mode, shrink):
    """Kill-and-restart flavor of elasticity: a checkpoint written
    under an 8-way run restores and CONTINUES on a different device
    count, matching the uninterrupted dense trajectory."""
    from deeplearning4j_tpu.utils import CheckpointListener
    batches = [_data(64, seed=i) for i in range(4)]
    ref = _mlp(seed=11)
    pw_ref = ParallelWrapper.Builder(ref).workers(8) \
        .update_exchange("dense").build()
    for ds in batches:
        pw_ref.fit_batch(ds)

    net = _mlp(seed=11)
    lis = CheckpointListener(tmp_path, save_every_n_iterations=2)
    net.set_listeners(lis)
    pw = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange(mode).build()
    for ds in batches[:2]:
        pw.fit_batch(ds)
    lis.flush()

    restored = CheckpointListener.load_checkpoint(tmp_path)
    assert restored.iteration_count == 2
    pw2 = ParallelWrapper.Builder(restored).workers(shrink) \
        .update_exchange(mode).build()
    for ds in batches[2:]:
        pw2.fit_batch(ds)
    dense = restored.dense_params() \
        if hasattr(restored, "dense_params") else restored.params
    _assert_tree_close(ref.params, dense, rtol=2e-5, atol=1e-6)
