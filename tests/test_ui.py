"""UI stats + profiling tests (SURVEY.md D17, S8/§5.1)."""
import json

import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.ui import (FileStatsStorage,
                                   InMemoryStatsStorage,
                                   ProfilingListener, StatsListener,
                                   render_html_report)


def _net_and_data(listeners):
    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
         .list()
         .layer(DenseLayer(n_out=8, activation=Activation.RELU))
         .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                            loss_function=LossFunction.MCXENT))
         .set_input_type(InputType.feed_forward(4)).build())).init()
    net.set_listeners(*listeners)
    return net, DataSet(x, y)


class TestStatsListener:
    def test_collects_reports(self):
        storage = InMemoryStatsStorage()
        net, ds = _net_and_data([StatsListener(storage, frequency=1)])
        net.fit(ds, n_epochs=5)
        reports = storage.get_reports()
        assert len(reports) == 5
        r = reports[-1]
        assert np.isfinite(r["score"])
        assert "layer_0.W" in r["layers"] or any(
            "W" in k for k in r["layers"])
        # update stats + ratio present from the 2nd report onward
        wkey = next(k for k in r["layers"] if k.endswith("W"))
        assert "update_param_ratio" in r["layers"][wkey]
        assert r["layers"][wkey]["update_param_ratio"] > 0
        assert len(r["layers"][wkey]["param"]["hist"]) == 20

    def test_file_storage_roundtrip(self, tmp_path):
        p = str(tmp_path / "stats.jsonl")
        storage = FileStatsStorage(p)
        net, ds = _net_and_data([StatsListener(storage)])
        net.fit(ds, n_epochs=3)
        # a new storage instance reloads the same reports
        again = FileStatsStorage(p)
        assert len(again.get_reports()) == 3
        assert again.latest()["iteration"] == \
            storage.latest()["iteration"]

    def test_html_report(self, tmp_path):
        storage = InMemoryStatsStorage()
        net, ds = _net_and_data([StatsListener(storage)])
        net.fit(ds, n_epochs=4)
        out = render_html_report(storage, str(tmp_path / "r.html"))
        html = open(out).read()
        assert "<canvas" in html and "Score vs iteration" in html
        # data payload embedded
        assert '"scores"' in html


class TestProfilingListener:
    def test_live_ui_server(self):
        """UIServer serves the dashboard + JSON API for an attached
        storage (reference: VertxUIServer.attach(statsStorage))."""
        import json as _json
        import urllib.request

        from deeplearning4j_tpu.ui import InMemoryStatsStorage, UIServer
        storage = InMemoryStatsStorage()
        storage.put_report({"iteration": 0, "epoch": 0, "time": 1.0,
                            "score": 2.5, "layers": {}})
        server = UIServer.get_instance().attach(storage)
        server.start(port=0)
        try:
            base = server.url
            html = urllib.request.urlopen(base + "/").read().decode()
            assert "Training dashboard" in html
            reports = _json.loads(urllib.request.urlopen(
                base + "/api/reports").read())
            assert len(reports) == 1 and reports[0]["score"] == 2.5
            storage.put_report({"iteration": 1, "epoch": 0, "time": 2.0,
                                "score": 1.5, "layers": {}})
            latest = _json.loads(urllib.request.urlopen(
                base + "/api/latest").read())
            assert latest["score"] == 1.5    # live: sees new reports
        finally:
            server.stop()
            server.detach(storage)

    def test_chrome_trace(self, tmp_path):
        p = str(tmp_path / "trace.json")
        prof = ProfilingListener(p)
        net, ds = _net_and_data([prof])
        net.fit([ds], n_epochs=3)      # iterator path fires epochs
        trace = json.load(open(p))
        events = trace["traceEvents"]
        assert any(e["name"] == "epoch" for e in events)
        iters = [e for e in events if e["name"].startswith("iteration")]
        assert iters and all(e["ph"] == "X" and e["dur"] >= 0
                             for e in iters)
