"""Distributed-ETL seam (SURVEY.md V2/P4; round-3 verdict ask #7):
ShardedDataSetIterator deterministically partitions a RecordReader/
TransformProcess across the process world and feeds the global-batch
assembly.  Single-process unit tests here; the 2-process integration
lives in test_multiprocess_distributed."""
import numpy as np
import pytest

from deeplearning4j_tpu.datavec.records import (CollectionRecordReader,
                                                CSVRecordReader)
from deeplearning4j_tpu.datavec.sharded import ShardedDataSetIterator
from deeplearning4j_tpu.datavec.split import FileSplit


def _rows(n, cols=4, seed=0):
    rng = np.random.RandomState(seed)
    data = rng.randn(n, cols - 1)
    labels = rng.randint(0, 3, size=(n, 1))
    return np.concatenate([data, labels], axis=1)


def _reader(mat):
    return CollectionRecordReader(
        [[float(v) for v in row] for row in mat]).initialize()


class TestShardingDeterminism:
    def test_shards_are_disjoint_contiguous_and_cover(self):
        mat = _rows(25)
        shards = []
        for pid in range(3):
            it = ShardedDataSetIterator(
                _reader(mat), batch_size=4, label_index=3, n_labels=3,
                process_index=pid, process_count=3)
            feats = np.concatenate([np.asarray(ds.features)
                                    for ds in it], axis=0)
            shards.append(feats)
        # 25 // 3 = 8 per process, batch 4 -> 8 rows each, contiguous
        for pid, got in enumerate(shards):
            want = mat[pid * 8:pid * 8 + 8, :3]
            np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_equal_batch_counts_always(self):
        """The lockstep guarantee: every process yields the SAME
        number of batches even when N is ragged."""
        mat = _rows(29)                     # 29 = 3*9 + 2 ragged
        counts = {len(list(ShardedDataSetIterator(
            _reader(mat), batch_size=2, label_index=3, n_labels=3,
            process_index=pid, process_count=3)))
            for pid in range(3)}
        assert counts == {4}                # 9 // 2 = 4 each

    def test_same_code_single_process(self):
        """Defaults pick up the live world (1 process here)."""
        mat = _rows(12)
        it = ShardedDataSetIterator(_reader(mat), batch_size=4,
                                    label_index=3, n_labels=3)
        dss = list(it)
        assert len(dss) == 3
        assert dss[0].features.shape == (4, 3)
        assert dss[0].labels.shape == (4, 3)   # one-hot
        # labels one-hot encode the label column
        np.testing.assert_array_equal(
            np.argmax(dss[0].labels, axis=1), mat[:4, 3].astype(int))

    def test_regression_labels_and_reset(self):
        mat = _rows(8)
        it = ShardedDataSetIterator(_reader(mat), batch_size=4,
                                    label_index=3)
        a = [np.asarray(ds.labels) for ds in it]
        it.reset()
        b = [np.asarray(ds.labels) for ds in it]
        assert a[0].shape == (4, 1)
        np.testing.assert_array_equal(a[0], b[0])

    def test_csv_reader_with_transform_process(self, tmp_path):
        from deeplearning4j_tpu.datavec.schema import Schema
        from deeplearning4j_tpu.datavec.transform import \
            TransformProcess
        mat = _rows(10)
        f = tmp_path / "data.csv"
        f.write_text("\n".join(",".join(f"{v:.6f}" for v in row)
                               for row in mat) + "\n")
        schema = (Schema.Builder()
                  .add_column_double("a").add_column_double("b")
                  .add_column_double("c").add_column_double("y")
                  .build())
        tp = (TransformProcess.Builder(schema)
              .convert_to_double("a").convert_to_double("b")
              .convert_to_double("c").convert_to_double("y")
              .build())
        rr = CSVRecordReader().initialize(FileSplit(str(f)))
        it = ShardedDataSetIterator(rr, batch_size=5, label_index=3,
                                    n_labels=3, transform_process=tp)
        feats = np.concatenate([np.asarray(ds.features) for ds in it],
                               axis=0)
        np.testing.assert_allclose(feats, mat[:, :3], atol=1e-5)

    def test_too_few_records_raises(self):
        mat = _rows(3)
        with pytest.raises(ValueError, match="shard"):
            ShardedDataSetIterator(_reader(mat), batch_size=2,
                                   label_index=3, n_labels=3,
                                   process_index=0, process_count=4)
