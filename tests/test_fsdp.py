"""Full FSDP / ZeRO-3 (parallel.zero + the nn/autodiff step tails) on
the virtual 8-device CPU mesh (ISSUE 10).

Covers: fsdp==dense end-to-end trajectory parity (Sgd / Nesterovs /
Adam), 1/N parameter residency (the ISSUE acceptance bar: per-chip
param + updater-state bytes <= 1/4 of dense), composition with
gradient accumulation, dense device-count-portable checkpoints
restored onto a different mesh size, the resolver's fallback ladder
and both env kill switches, per-mode exchange accounting, the graph
and SameDiff step tails, and the new telemetry surfaces.
"""
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning.updaters import (Adam, Nesterovs, Sgd,
                                                  FSDP_KEY, is_fsdp)
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.parallel import ParallelWrapper, UpdateExchange
from deeplearning4j_tpu.parallel.mesh import MeshFactory
from deeplearning4j_tpu.parallel.zero import (exchange_report,
                                              fsdp_gather,
                                              params_to_dense,
                                              params_to_fsdp,
                                              resolve_update_exchange)


def _mlp(updater=None, seed=42):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(updater or Sgd(0.1))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _graph(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(0.01)).weight_init(WeightInit.XAVIER)
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(8))
            .add_layer("d1", DenseLayer(n_out=16,
                                        activation=Activation.TANH),
                       "in")
            .add_layer("out", OutputLayer(
                n_out=3, loss_function=LossFunction.MCXENT,
                activation=Activation.SOFTMAX), "d1")
            .set_outputs("out").build())
    return ComputationGraph(conf).init()


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return DataSet(x, y)


def _assert_tree_close(a, b, **kw):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


def _tree_bytes(tree):
    return sum(int(np.prod(a.shape)) * a.dtype.itemsize
               for a in jax.tree_util.tree_leaves(tree)
               if hasattr(a, "shape"))


# -- flat layout round trip ------------------------------------------------
def test_params_to_fsdp_roundtrip():
    net = _mlp()
    dense = jax.tree_util.tree_map(np.asarray, net.params)
    flat, specs = params_to_fsdp(net.params, 8)
    assert all(is_fsdp(v) for v in flat.values())
    back = params_to_dense(flat, specs)
    _assert_tree_close(dense, back, rtol=0, atol=0)


def test_fsdp_gather_grad_is_reduce_scattered():
    """The custom_vjp keeps the gather's cotangent sharded: d/dflat of
    a function of the gathered params lands back on the 1/N layout
    with the right values (sum over the dense leaves here)."""
    mesh = MeshFactory.data_parallel()
    net = _mlp()
    flat, specs = params_to_fsdp(net.params, 8)
    k = "layer_0"

    def f(fl):
        dense = fsdp_gather(fl, specs[k], mesh)
        return sum(jnp.sum(v ** 2) for v in dense.values())

    g = jax.grad(f)(flat[k][FSDP_KEY])
    expect = {kk: 2 * v for kk, v in
              params_to_dense({k: flat[k]}, {k: specs[k]})[k].items()}
    got = params_to_dense({k: {FSDP_KEY: g}}, {k: specs[k]})[k]
    _assert_tree_close(expect, got, rtol=1e-6, atol=1e-7)


# -- end-to-end parity -----------------------------------------------------
@pytest.mark.parametrize("updater,rtol,atol", [
    (Sgd(0.1), 1e-6, 1e-7),
    (Nesterovs(0.1, 0.9), 1e-5, 1e-6),
    (Adam(0.01), 1e-5, 1e-6),
], ids=["sgd", "nesterovs", "adam"])
def test_fsdp_matches_dense_trajectory(updater, rtol, atol):
    """Two identically-seeded nets, same 4 batches: the fsdp exchange
    must track the dense exchange's parameters at EVERY step, not just
    the endpoint (a compensating-error pair would pass an
    endpoint-only check)."""
    batches = [_data(64, seed=i) for i in range(4)]
    dense_net = _mlp(updater, seed=7)
    fsdp_net = _mlp(updater, seed=7)
    pw_d = ParallelWrapper.Builder(dense_net).workers(8) \
        .update_exchange("dense").build()
    pw_f = ParallelWrapper.Builder(fsdp_net).workers(8) \
        .update_exchange("fsdp").build()
    for ds in batches:
        pw_d.fit_batch(ds)
        pw_f.fit_batch(ds)
        _assert_tree_close(dense_net.params, fsdp_net.dense_params(),
                           rtol=rtol, atol=atol)
    assert pw_f.update_exchange is UpdateExchange.FSDP
    # params really stayed in the fsdp layout the whole time
    assert all(is_fsdp(p) for p in fsdp_net.params.values())
    # scores agree too
    np.testing.assert_allclose(
        float(dense_net.score(_data(32, seed=9))),
        float(fsdp_net.score(_data(32, seed=9))), rtol=1e-5)


def test_fsdp_param_residency_quarter_of_dense():
    """ISSUE 10 acceptance: per-chip param + updater-state residency
    under fsdp <= 1/4 of the dense replicated footprint (it is 1/8
    here: every flat lives 1/N per device)."""
    from deeplearning4j_tpu.common import diagnostics
    dense_net = _mlp(Adam(0.01), seed=3)
    fsdp_net = _mlp(Adam(0.01), seed=3)
    ParallelWrapper.Builder(dense_net).workers(8) \
        .update_exchange("dense").build().fit_batch(_data(64))
    ParallelWrapper.Builder(fsdp_net).workers(8) \
        .update_exchange("fsdp").build().fit_batch(_data(64))

    for flat in jax.tree_util.tree_leaves(fsdp_net.params):
        shards = flat.addressable_shards
        assert len(shards) == 8
        assert shards[0].data.shape[0] == flat.shape[0] // 8

    d = diagnostics.memory_report(model=dense_net)["models"]
    f = diagnostics.memory_report(model=fsdp_net)["models"]
    d = d["MultiLayerNetwork"]
    f = f["MultiLayerNetwork"]
    dense_resident = (d["params_resident_bytes"] +
                      d["updater_state_resident_bytes"])
    fsdp_resident = (f["params_resident_bytes"] +
                     f["updater_state_resident_bytes"])
    assert fsdp_resident <= dense_resident / 4
    # dense nets report resident == logical
    assert d["params_resident_bytes"] == d["params_bytes"]


def test_fsdp_composes_with_accumulation():
    """fsdp + accumulation_steps=2 == one dense big-batch step (mean
    gradient, equal micro-batches); params untouched mid-window and
    exactly one applied update."""
    ds = _data(128, seed=3)
    x, y = np.asarray(ds.features), np.asarray(ds.labels)
    big = _mlp(seed=11)
    ParallelWrapper.Builder(big).workers(8).update_exchange("dense") \
        .build().fit_batch(DataSet(x, y))

    accum = _mlp(seed=11)
    pw = ParallelWrapper.Builder(accum).workers(8) \
        .update_exchange("fsdp").accumulation_steps(2).build()
    init = jax.tree_util.tree_map(np.asarray, accum.dense_params())
    pw.fit_batch(DataSet(x[:64], y[:64]))
    _assert_tree_close(accum.dense_params(), init, rtol=0, atol=0)
    pw.fit_batch(DataSet(x[64:], y[64:]))
    assert accum._updates_applied == 1
    _assert_tree_close(big.params, accum.dense_params(),
                       rtol=1e-5, atol=1e-6)


# -- checkpoint portability ------------------------------------------------
def test_fsdp_checkpoint_restores_on_different_device_count(tmp_path):
    """A net training under fsdp on 8 shards checkpoints DENSE and
    restores onto a 4-device mesh (ISSUE 10 acceptance: the archive
    carries no trace of the padded 8-way flats)."""
    from deeplearning4j_tpu.utils import CheckpointListener
    net = _mlp(Adam(0.01), seed=9)
    lis = CheckpointListener(tmp_path, save_every_n_iterations=2)
    net.set_listeners(lis)
    pw = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange("fsdp").build()
    for i in range(2):
        pw.fit_batch(_data(64, seed=i))
    lis.flush()
    assert all(is_fsdp(p) for p in net.params.values())

    restored = CheckpointListener.load_checkpoint(tmp_path)
    assert restored.iteration_count == 2
    assert not any(is_fsdp(p) for p in restored.params.values())
    _assert_tree_close(restored.params, net.dense_params(),
                       rtol=1e-6, atol=1e-7)
    # restored net trains standalone (dense) ...
    restored.fit(_data(64, seed=2))
    # ... and re-enters fsdp on a DIFFERENT device count
    pw4 = ParallelWrapper.Builder(restored).workers(4) \
        .update_exchange("fsdp").build()
    pw4.fit_batch(_data(64, seed=3))
    assert pw4.update_exchange is UpdateExchange.FSDP
    for flat in jax.tree_util.tree_leaves(restored.params):
        assert len(flat.addressable_shards) == 4
    assert np.isfinite(float(restored.score(_data(32))))


# -- resolver + kill switches ----------------------------------------------
def test_resolver_fsdp_is_opt_in_and_falls_back():
    mesh = MeshFactory.data_parallel()
    # auto never silently picks fsdp
    assert resolve_update_exchange(mesh) is UpdateExchange.SHARDED
    assert resolve_update_exchange(mesh, requested="fsdp") \
        is UpdateExchange.FSDP
    assert resolve_update_exchange(None, requested="fsdp") \
        is UpdateExchange.DENSE
    one = MeshFactory.data_parallel(1)
    assert resolve_update_exchange(one, requested="fsdp") \
        is UpdateExchange.DENSE


def test_resolver_fsdp_falls_back_on_constraints_and_gn():
    from deeplearning4j_tpu.nn.conf.builders import \
        GradientNormalization
    from deeplearning4j_tpu.nn.conf.constraints import UnitNormConstraint
    mesh = MeshFactory.data_parallel()
    net = _mlp()
    net.conf.layers[0].constrain_weights = [UnitNormConstraint()]
    assert resolve_update_exchange(mesh, requested="fsdp", model=net) \
        is UpdateExchange.SHARDED
    net2 = _mlp()
    net2.conf.gradient_normalization = \
        GradientNormalization.CLIP_L2_PER_LAYER
    assert resolve_update_exchange(mesh, requested="fsdp", model=net2) \
        is UpdateExchange.DENSE


def test_fsdp_kill_switch_demotes_to_sharded(monkeypatch):
    """DL4J_TPU_FSDP=0 demotes fsdp requests to the ZeRO-1 sharded
    exchange; DL4J_TPU_SHARDED_UPDATE=0 kills both down to dense."""
    from deeplearning4j_tpu.common.environment import Environment
    mesh = MeshFactory.data_parallel()
    monkeypatch.setenv("DL4J_TPU_FSDP", "0")
    Environment.reset()
    try:
        assert resolve_update_exchange(mesh, requested="fsdp") \
            is UpdateExchange.SHARDED
        net = _mlp(Adam(0.01))
        pw = ParallelWrapper.Builder(net).workers(8) \
            .update_exchange("fsdp").build()
        pw.fit_batch(_data(64))
        assert pw.update_exchange is UpdateExchange.SHARDED
        assert not any(is_fsdp(p) for p in net.params.values())
        monkeypatch.setenv("DL4J_TPU_SHARDED_UPDATE", "0")
        Environment.reset()
        assert resolve_update_exchange(mesh, requested="fsdp") \
            is UpdateExchange.DENSE
    finally:
        monkeypatch.delenv("DL4J_TPU_FSDP")
        monkeypatch.delenv("DL4J_TPU_SHARDED_UPDATE", raising=False)
        Environment.reset()


# -- accounting + telemetry satellites -------------------------------------
def test_exchange_report_per_mode_breakdown():
    net = _mlp()
    total = _tree_bytes(net.params)
    half = int(7 * total / 8)
    dense = exchange_report(net.params, 8, "dense")
    assert dense["all_reduce_bytes"] == dense["wire_bytes_per_replica"]
    assert "param_resident_bytes_per_replica" not in dense
    sharded = exchange_report(net.params, 8, UpdateExchange.SHARDED)
    assert sharded["grad_reduce_scatter_bytes"] == half
    assert sharded["param_all_gather_bytes"] == half
    fsdp = exchange_report(net.params, 8, "fsdp")
    assert fsdp["grad_reduce_scatter_bytes"] == half
    assert fsdp["param_all_gather_bytes"] == half
    assert fsdp["param_resident_bytes_per_replica"] == total // 8
    # every mode moves the same per-step wire volume (to int rounding);
    # fsdp pays it in per-layer gathers instead of one fused collective
    assert abs(dense["wire_bytes_per_replica"] -
               fsdp["wire_bytes_per_replica"]) <= 1
    assert abs(fsdp["wire_bytes_per_replica"] - 2 * half) <= 1


def test_fsdp_telemetry_surfaces():
    from deeplearning4j_tpu.common import telemetry
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    telemetry.MetricsRegistry._reset_for_tests()
    net = _mlp(Adam(0.01))
    pw = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange("fsdp").build()
    pw.fit(ListDataSetIterator([_data(64)]), n_epochs=1)
    assert telemetry.counter(
        "dl4j_dp_update_exchange_bytes_total", "").value(
            mode="fsdp") > 0
    assert telemetry.counter(
        "dl4j_fsdp_gather_bytes_total", "").value(workers=8) > 0
    assert telemetry.gauge(
        "dl4j_fsdp_param_shard_bytes", "").value() > 0
    n_before = telemetry.histogram(
        "dl4j_fsdp_gather_seconds", "").count_of()
    net.dense_params()          # host-side regather is timed
    assert telemetry.histogram(
        "dl4j_fsdp_gather_seconds", "").count_of() > n_before


# -- graph + SameDiff tails ------------------------------------------------
def test_graph_fsdp_matches_dense():
    batches = [_data(64, seed=i) for i in range(3)]
    dense_g = _graph(seed=7)
    fsdp_g = _graph(seed=7)
    pw_d = ParallelWrapper.Builder(dense_g).workers(8) \
        .update_exchange("dense").build()
    pw_f = ParallelWrapper.Builder(fsdp_g).workers(8) \
        .update_exchange("fsdp").build()
    for ds in batches:
        pw_d.fit_batch(ds)
        pw_f.fit_batch(ds)
    assert pw_f.update_exchange is UpdateExchange.FSDP
    assert all(is_fsdp(p) for p in fsdp_g.params.values())
    _assert_tree_close(dense_g.params, fsdp_g.dense_params(),
                       rtol=1e-5, atol=1e-6)
    # inference on the live fsdp-resident graph still works
    out = fsdp_g.output(np.zeros((4, 8), np.float32))
    assert np.asarray(out).shape == (4, 3)


def test_samediff_fsdp_matches_dense():
    from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
    from deeplearning4j_tpu.parallel import make_mesh

    def build():
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 2))
        y = sd.placeholder("y", shape=(None, 1))
        sd.var("w", array=np.zeros((2, 1), np.float32))
        sd.var("b", array=np.zeros((1,), np.float32))
        w, b = sd.get_variable("w"), sd.get_variable("b")
        sd.loss.mean_squared_error(y, x @ w + b, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(
            TrainingConfig.Builder().updater(Adam(0.1))
            .data_set_feature_mapping("x")
            .data_set_label_mapping("y").build())
        return sd

    rng = np.random.RandomState(0)
    xv = rng.randn(64, 2).astype(np.float32)
    yv = (xv @ np.array([[2.0], [-3.0]], np.float32)) + 0.5
    batch = {"x": xv, "y": yv}
    mesh = make_mesh({"data": 8}, jax.devices()[:8])

    dense = build()
    l_dense = dense.fit_steps(batch, 6, mesh=mesh,
                              update_exchange="dense")
    fsdp = build()
    l_fsdp = fsdp.fit_steps(batch, 6, mesh=mesh,
                            update_exchange="fsdp")
    np.testing.assert_allclose(l_fsdp, l_dense, rtol=1e-5, atol=1e-7)
    for n in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(fsdp.get_variable(n).get_arr()),
            np.asarray(dense.get_variable(n).get_arr()),
            rtol=1e-5, atol=1e-6)
    # variables densify between windows: a second fsdp window resumes
    l2 = fsdp.fit_steps(batch, 2, mesh=mesh, update_exchange="fsdp")
    assert np.isfinite(float(l2)) and float(l2) < float(l_fsdp)
