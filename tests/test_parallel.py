"""Parallelism tests on the virtual 8-device CPU mesh (SURVEY.md §4.7).

Covers: mesh construction, ParallelWrapper DP training (exactness vs
single-device), ParallelInference batching, SharedTrainingMaster
single-process path, threshold encoding semantics.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.learning.updaters import Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.parallel import (AdaptiveThresholdAlgorithm,
                                         EncodingHandler,
                                         FixedThresholdAlgorithm,
                                         ParallelInference, ParallelWrapper,
                                         SharedTrainingMaster, make_mesh,
                                         encode_threshold)
from deeplearning4j_tpu.parallel.mesh import MeshFactory


def _mlp(seed=42):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(Sgd(0.1))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return DataSet(x, y)


def test_make_mesh_shapes():
    m = make_mesh()
    assert m.shape["data"] == 8
    m2 = make_mesh({"data": -1, "model": 2})
    assert m2.shape["data"] == 4 and m2.shape["model"] == 2
    with pytest.raises(ValueError):
        make_mesh({"data": 3})
    m3 = MeshFactory.full(data=2, model=2, seq=2, stage=1)
    assert m3.shape["seq"] == 2


def test_parallel_wrapper_matches_single_device():
    """8-way DP on the same global batch must equal single-device SGD
    (exact synchronous semantics)."""
    ds = _data(64)
    single = _mlp(seed=7)
    single.fit(ds)

    parallel_net = _mlp(seed=7)
    pw = ParallelWrapper.Builder(parallel_net).workers(8).build()
    assert pw.n_workers == 8
    pw.fit_batch(ds)

    for k in single.params:
        for name in single.params[k]:
            np.testing.assert_allclose(
                np.asarray(single.params[k][name]),
                np.asarray(parallel_net.params[k][name]),
                rtol=2e-4, atol=2e-5)


def test_parallel_wrapper_trains_iterator():
    net = _mlp()
    it = ListDataSetIterator([_data(32, seed=i) for i in range(4)])
    pw = ParallelWrapper.Builder(net).workers(8).averaging_frequency(3) \
        .build()
    before = net.score()
    pw.fit(it, n_epochs=3)
    assert np.isfinite(net.score())
    assert net.iteration_count == 12


def test_parallel_wrapper_trims_odd_batch():
    net = _mlp()
    pw = ParallelWrapper.Builder(net).workers(8).build()
    pw.fit_batch(_data(61))          # trimmed to 56
    assert net.last_batch_size == 56


def test_parallel_inference_pads_and_matches():
    net = _mlp()
    x = np.random.RandomState(1).randn(13, 8).astype(np.float32)
    pi = ParallelInference.Builder(net).batch_limit(8).build()
    out = pi.output(x)
    assert out.shape == (13, 3)
    ref = np.asarray(net.output(x))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    outs = pi.output_batched([x[:3], x[3:10], x[10:]])
    assert [o.shape[0] for o in outs] == [3, 7, 3]
    np.testing.assert_allclose(np.concatenate(outs), ref, rtol=1e-5,
                               atol=1e-6)


def test_shared_training_master_single_process():
    net = _mlp()
    it = ListDataSetIterator([_data(32, seed=i) for i in range(3)])
    master = (SharedTrainingMaster.Builder(batch_size_per_worker=4)
              .threshold_algorithm(AdaptiveThresholdAlgorithm())
              .build())
    master.fit(net, it, n_epochs=2)
    assert net.iteration_count == 6
    assert np.isfinite(net.score())


def test_encode_threshold_roundtrip():
    g = jnp.asarray([0.5, -0.2, 0.001, -0.0005, 2.0])
    q, r = encode_threshold(g, 0.1)
    np.testing.assert_allclose(np.asarray(q), [0.1, -0.1, 0.0, 0.0, 0.1])
    np.testing.assert_allclose(np.asarray(q + r), np.asarray(g), rtol=1e-6)


def test_encoding_handler_residual_carry():
    h = EncodingHandler(FixedThresholdAlgorithm(0.1))
    g = {"W": jnp.full((4,), 0.06)}          # below tau: nothing sent
    q1 = h.encode(g)
    assert float(jnp.sum(jnp.abs(q1["W"]))) == 0.0
    q2 = h.encode(g)                          # residual accumulates: sent
    np.testing.assert_allclose(np.asarray(q2["W"]), np.full((4,), 0.1))


def test_adaptive_threshold_moves_tau():
    a = AdaptiveThresholdAlgorithm(initial_threshold=1e-3,
                                   min_target=1e-4, max_target=1e-2)
    assert a.next_tau(1e-3, 0.5) > 1e-3       # too dense -> raise tau
    assert a.next_tau(1e-3, 1e-6) < 1e-3      # too sparse -> lower tau


def test_parallel_inference_async_submit_batches_and_matches():
    """The async observable path (reference: ParallelInference's
    request queue + worker batching): concurrent submits resolve to
    exactly the per-request results of a direct forward, and the
    worker aggregated them into shared batches."""
    from deeplearning4j_tpu.parallel.inference import InferenceMode
    net = _mlp()
    rng = np.random.RandomState(2)
    reqs = [rng.randn(1, 8).astype(np.float32) for _ in range(24)]
    pi = ParallelInference.Builder(net).batch_limit(8) \
        .batch_window_ms(20.0).build()   # window long enough to fill
    flushes = []
    orig_flush = pi._flush
    pi._flush = lambda batch: (flushes.append(len(batch)),
                               orig_flush(batch))[-1]
    futs = [pi.submit(r) for r in reqs]
    outs = [f.result(timeout=60) for f in futs]
    pi.shutdown()
    for r, o in zip(reqs, outs):
        np.testing.assert_allclose(o, np.asarray(net.output(r)),
                                   rtol=1e-5, atol=1e-6)
    # the worker actually AGGREGATED: far fewer flushes than requests
    assert sum(flushes) == len(reqs)
    assert len(flushes) < len(reqs), flushes

    # INPLACE bypasses the queue: no worker thread is ever created
    pi2 = (ParallelInference.Builder(net)
           .inference_mode(InferenceMode.INPLACE).build())
    out = pi2.submit(reqs[0]).result(timeout=5)
    np.testing.assert_allclose(out, np.asarray(net.output(reqs[0])),
                               rtol=1e-5, atol=1e-6)
    assert getattr(pi2, "_worker", None) is None


def test_parallel_inference_empty_request_list_returns_empty():
    """output_batched([]) used to raise ValueError out of
    np.concatenate; an empty flush must be a no-op (ISSUE 3
    satellite)."""
    net = _mlp()
    pi = ParallelInference.Builder(net).build()
    assert pi.output_batched([]) == []
    # the _flush path guards the same way: an all-cancelled batch
    # reaches the worker as an empty live list and must not raise
    pi._flush([])
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    out = pi.submit(x).result(timeout=60)     # worker still healthy
    assert out.shape == (2, 3)
    pi.shutdown()


def test_parallel_inference_cancelled_future_does_not_kill_worker():
    """A client cancelling its queued request (timeout) must not kill
    the batching worker or starve its batch-mates (code-review
    regression: set_result on a cancelled Future raises)."""
    net = _mlp()
    rng = np.random.RandomState(3)
    pi = ParallelInference.Builder(net).batch_limit(4) \
        .batch_window_ms(50.0).build()
    r = rng.randn(1, 8).astype(np.float32)
    doomed = pi.submit(r)
    assert doomed.cancel()               # still queued: cancellable
    live = [pi.submit(rng.randn(1, 8).astype(np.float32))
            for _ in range(6)]
    outs = [f.result(timeout=60) for f in live]
    assert all(o.shape == (1, 3) for o in outs)
    # and the worker is still alive for later requests
    again = pi.submit(r).result(timeout=60)
    assert again.shape == (1, 3)
    pi.shutdown()
