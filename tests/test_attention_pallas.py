"""Pallas flash-attention backend (ops/attention_pallas.py):
interpret-mode forward/gradient conformance against the dense einsum
reference, the [b, t_k] key-mask reduction, and the backend-selection
heuristic (structural fallbacks, env override, auto thresholds)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.attention import dot_product_attention
from deeplearning4j_tpu.ops.attention_pallas import (
    FLASH_MIN_SEQ, as_key_mask, flash_attention_override, flash_sdpa,
    maybe_flash_sdpa, select_attention_backend)

R = np.random.RandomState(0)


def _qkv(b=2, h=2, t=64, d=8):
    return tuple(jnp.asarray(R.randn(b, h, t, d), jnp.float32)
                 for _ in range(3))


def _dense(q, k, v, scale, key_mask=None):
    mask = (key_mask[:, None, None, :]
            if key_mask is not None else None)
    return dot_product_attention(q, k, v, mask=mask, scale=scale)


class TestFlashConformance:
    """interpret mode runs the SAME kernel code the chip runs."""

    @pytest.mark.parametrize("scale", [None, 0.37])
    def test_forward_matches_dense(self, scale):
        q, k, v = _qkv()
        got = flash_sdpa(q, k, v, scale, block_q=32, block_k=32)
        want = _dense(q, k, v, scale)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_key_mask_matches_dense(self):
        q, k, v = _qkv()
        km = jnp.asarray(
            np.concatenate([np.ones((2, 48)), np.zeros((2, 16))],
                           axis=1), jnp.float32)
        got = flash_sdpa(q, k, v, 0.5, key_mask=km, block_q=32,
                         block_k=32)
        want = _dense(q, k, v, 0.5, key_mask=km)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_rank3_unit_heads(self):
        q, k, v = (x[:, 0] for x in _qkv())
        got = flash_sdpa(q, k, v, block_q=32, block_k=32)
        want = _dense(q[:, None], k[:, None], v[:, None], None)[:, 0]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_match_dense(self):
        q, k, v = _qkv(t=32)
        km = jnp.asarray(
            np.concatenate([np.ones((2, 24)), np.zeros((2, 8))],
                           axis=1), jnp.float32)

        def loss_flash(q, k, v):
            return jnp.sum(flash_sdpa(q, k, v, 0.37, key_mask=km,
                                      block_q=16, block_k=16) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(_dense(q, k, v, 0.37, key_mask=km) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)


class TestKeyMaskReduction:
    def test_broadcast_forms_reduce(self):
        m = jnp.asarray(R.rand(2, 1, 1, 16) > 0.3, jnp.float32)
        km = as_key_mask(m, 2, 16, 4)
        assert km.shape == (2, 16)
        np.testing.assert_array_equal(np.asarray(km),
                                      np.asarray(m[:, 0, 0, :]))
        # shared-across-batch [1, 1, 1, t_k] broadcasts up
        m1 = m[:1]
        km1 = as_key_mask(m1, 2, 16, 4)
        assert km1.shape == (2, 16)
        np.testing.assert_array_equal(np.asarray(km1[0]),
                                      np.asarray(km1[1]))
        # plain [t_k] vector
        assert as_key_mask(jnp.ones((16,)), 2, 16, 4).shape == (2, 16)

    def test_per_query_and_per_head_masks_rejected(self):
        assert as_key_mask(jnp.ones((2, 1, 16, 16)), 2, 16, 4) is None
        assert as_key_mask(jnp.ones((2, 4, 1, 16)), 2, 16, 4) is None
        assert as_key_mask(jnp.ones((2, 1, 1, 8)), 2, 16, 4) is None


class TestBackendSelection:
    Q4 = (2, 4, 512, 64)

    def test_structural_fallbacks_dominate(self):
        b, r = select_attention_backend(self.Q4, self.Q4,
                                        has_bias=True, override=True)
        assert b == "dense" and "bias" in r
        b, r = select_attention_backend((512, 64), (512, 64),
                                        override=True)
        assert b == "dense" and "rank" in r
        b, r = select_attention_backend(self.Q4, (2, 4, 512, 32),
                                        override=True)
        assert b == "dense" and "mismatch" in r
        b, r = select_attention_backend(self.Q4, self.Q4,
                                        mask_ok=False, override=True)
        assert b == "dense" and "mask" in r

    def test_override_beats_auto(self):
        b, _ = select_attention_backend(self.Q4, self.Q4,
                                        override=True, platform="cpu")
        assert b == "flash"
        long = (2, 4, FLASH_MIN_SEQ, 64)
        b, r = select_attention_backend(long, long, override=False,
                                        platform="tpu")
        assert b == "dense" and "kill switch" in r

    def test_auto_heuristic(self):
        b, r = select_attention_backend(self.Q4, self.Q4,
                                        platform="cpu",
                                        use_env_override=False)
        assert b == "dense" and "not tpu" in r
        long = (2, 4, FLASH_MIN_SEQ, 64)
        b, r = select_attention_backend(long, long, platform="tpu",
                                        use_env_override=False)
        assert b == "flash" and str(FLASH_MIN_SEQ) in r
        # short seq, plenty of HBM: dense wins
        b, _ = select_attention_backend(self.Q4, self.Q4,
                                        platform="tpu",
                                        free_hbm=16 << 30,
                                        use_env_override=False)
        assert b == "dense"
        # short seq but the scores tensor would eat the free HBM
        b, r = select_attention_backend(self.Q4, self.Q4,
                                        platform="tpu",
                                        free_hbm=1 << 20,
                                        use_env_override=False)
        assert b == "flash" and "free HBM" in r

    def test_env_var_gates(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FLASH_ATTENTION", "0")
        assert flash_attention_override() is False
        q, k, v = _qkv(t=16)
        assert maybe_flash_sdpa(q, k, v, 0.5) is None
        monkeypatch.setenv("DL4J_TPU_FLASH_ATTENTION", "1")
        assert flash_attention_override() is True
        out = maybe_flash_sdpa(q, k, v, 0.5)      # interpret on CPU
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_dense(q, k, v, 0.5)),
                                   rtol=2e-5, atol=2e-5)
        monkeypatch.delenv("DL4J_TPU_FLASH_ATTENTION")
        assert flash_attention_override() is None
        # auto on CPU: dense path (returns None)
        assert maybe_flash_sdpa(q, k, v, 0.5) is None

    def test_dense_bias_site_falls_back(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FLASH_ATTENTION", "1")
        q, k, v = _qkv(t=16)
        bias = jnp.asarray(R.randn(2, 2, 16, 16), jnp.float32)
        assert maybe_flash_sdpa(q, k, v, 0.5, bias=bias) is None

    def test_per_query_mask_falls_back(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FLASH_ATTENTION", "1")
        q, k, v = _qkv(t=16)
        causal = jnp.tril(jnp.ones((16, 16)))[None, None]
        assert maybe_flash_sdpa(q, k, v, 0.5, mask=causal) is None


class TestFusedBnBwdDefault:
    """DL4J_TPU_FUSED_BN_BWD semantics change: default ON on TPU, off
    elsewhere; =0 stays the kill switch, =1 forces anywhere."""

    def test_default_tracks_platform(self, monkeypatch):
        from deeplearning4j_tpu.ops import bn_pallas
        monkeypatch.delenv("DL4J_TPU_FUSED_BN_BWD", raising=False)
        assert bn_pallas.fused_bn_bwd_enabled() == \
            (jax.devices()[0].platform == "tpu")
        monkeypatch.setenv("DL4J_TPU_FUSED_BN_BWD", "1")
        assert bn_pallas.fused_bn_bwd_enabled() is True
        monkeypatch.setenv("DL4J_TPU_FUSED_BN_BWD", "0")
        assert bn_pallas.fused_bn_bwd_enabled() is False
