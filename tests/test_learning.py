"""Updater / schedule / activation / loss tests.

Modeled on the reference's updater math tests
(org.nd4j.linalg.learning.UpdaterTest style: closed-form single-step
expectations) plus convergence smoke tests.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import (
    Adam, AdaDelta, AdaGrad, AMSGrad, AdaMax, ExponentialSchedule,
    FixedSchedule, ISchedule, IUpdater, LinearSchedule, MapSchedule, Nadam,
    Nesterovs, NoOp, PolySchedule, RmsProp, Sgd, StepSchedule,
    WarmupSchedule)
from deeplearning4j_tpu.lossfunctions import LossFunction

ALL_UPDATERS = [Sgd(0.1), Nesterovs(0.1, 0.9), Adam(1e-2), AdaMax(1e-2),
                Nadam(1e-2), AMSGrad(1e-2), AdaGrad(0.1), AdaDelta(),
                RmsProp(1e-2), NoOp()]


class TestUpdaters:
    def test_sgd_single_step(self):
        up = Sgd(0.5)
        p = {"w": jnp.ones(3)}
        g = {"w": jnp.full(3, 2.0)}
        s = up.init_state(p)
        upd, s = up.apply(g, s, 0)
        np.testing.assert_allclose(upd["w"], 1.0)

    def test_adam_first_step_is_lr_sized(self):
        # after bias correction, |update| == lr for the first step
        up = Adam(1e-2)
        p = {"w": jnp.zeros(4)}
        g = {"w": jnp.full(4, 3.0)}
        upd, _ = up.apply(g, up.init_state(p), 0)
        np.testing.assert_allclose(upd["w"], 1e-2, rtol=1e-4)

    def test_adagrad_accumulates(self):
        up = AdaGrad(1.0, epsilon=0.0)
        p = {"w": jnp.zeros(1)}
        g = {"w": jnp.full(1, 2.0)}
        s = up.init_state(p)
        upd1, s = up.apply(g, s, 0)
        np.testing.assert_allclose(upd1["w"], 1.0)  # 2/sqrt(4)
        upd2, s = up.apply(g, s, 1)
        np.testing.assert_allclose(upd2["w"], 2.0 / np.sqrt(8.0), rtol=1e-6)

    def test_noop_returns_zero(self):
        up = NoOp()
        g = {"w": jnp.ones(3)}
        upd, _ = up.apply(g, up.init_state(g), 0)
        assert float(jnp.sum(jnp.abs(upd["w"]))) == 0.0

    @pytest.mark.parametrize("updater", ALL_UPDATERS,
                             ids=lambda u: type(u).__name__)
    def test_converges_on_quadratic(self, updater):
        """Every updater must reduce f(w)=|w|^2 over 100 jitted steps."""
        if isinstance(updater, NoOp):
            pytest.skip("NoOp never moves")
        p = {"w": jnp.full(5, 3.0)}
        s = updater.init_state(p)

        @jax.jit
        def step(p, s, it):
            g = jax.grad(lambda q: jnp.sum(q["w"] ** 2))(p)
            upd, s = updater.apply(g, s, it)
            return jax.tree_util.tree_map(lambda a, b: a - b, p, upd), s

        f0 = float(jnp.sum(p["w"] ** 2))
        # AdaDelta ramps from ~sqrt(eps)-sized steps, so it needs more of them
        n = 2000 if isinstance(updater, AdaDelta) else 100
        for it in range(n):
            p, s = step(p, s, it)
        assert float(jnp.sum(p["w"] ** 2)) < 0.5 * f0

    @pytest.mark.parametrize("updater", ALL_UPDATERS,
                             ids=lambda u: type(u).__name__)
    def test_json_round_trip(self, updater):
        d = updater.to_map()
        back = IUpdater.from_map(d)
        assert back == updater

    def test_schedule_inside_updater(self):
        up = Sgd(StepSchedule(initial_value=1.0, decay_rate=0.1, step=10))
        g = {"w": jnp.ones(1)}
        upd0, _ = up.apply(g, (), 0)
        upd10, _ = up.apply(g, (), 10)
        np.testing.assert_allclose(upd0["w"], 1.0)
        np.testing.assert_allclose(upd10["w"], 0.1, rtol=1e-6)


class TestSchedules:
    def test_fixed(self):
        assert FixedSchedule(0.5).value_at(100) == 0.5

    def test_step(self):
        s = StepSchedule(1.0, 0.5, 10)
        assert float(s.value_at(0)) == 1.0
        assert float(s.value_at(10)) == 0.5
        assert float(s.value_at(25)) == 0.25

    def test_exponential(self):
        s = ExponentialSchedule(1.0, 0.9)
        np.testing.assert_allclose(float(s.value_at(2)), 0.81, rtol=1e-6)

    def test_poly_hits_zero(self):
        s = PolySchedule(1.0, power=1.0, max_iter=100)
        np.testing.assert_allclose(float(s.value_at(100)), 0.0, atol=1e-7)
        np.testing.assert_allclose(float(s.value_at(50)), 0.5, rtol=1e-6)

    def test_map_schedule(self):
        s = MapSchedule({0: 1.0, 10: 0.1, 20: 0.01})
        assert float(s.value_at(5)) == 1.0
        assert float(s.value_at(10)) == pytest.approx(0.1)
        assert float(s.value_at(99)) == pytest.approx(0.01)

    def test_map_requires_zero(self):
        with pytest.raises(ValueError):
            MapSchedule({5: 1.0})

    def test_linear(self):
        s = LinearSchedule(1.0, 0.0, 10)
        np.testing.assert_allclose(float(s.value_at(5)), 0.5, rtol=1e-6)
        np.testing.assert_allclose(float(s.value_at(100)), 0.0, atol=1e-7)

    def test_warmup(self):
        s = WarmupSchedule(10, FixedSchedule(1.0))
        np.testing.assert_allclose(float(s.value_at(5)), 0.5, rtol=1e-6)
        np.testing.assert_allclose(float(s.value_at(50)), 1.0, rtol=1e-6)

    def test_traced_iteration(self):
        s = StepSchedule(1.0, 0.5, 10)
        out = jax.jit(lambda t: s.value_at(t))(jnp.asarray(10))
        np.testing.assert_allclose(float(out), 0.5)

    def test_json_round_trip(self):
        for s in [FixedSchedule(0.1), StepSchedule(1.0, 0.5, 10),
                  MapSchedule({0: 1.0, 5: 0.5}),
                  WarmupSchedule(10, ExponentialSchedule(1.0, 0.99))]:
            back = ISchedule.from_map(s.to_map())
            np.testing.assert_allclose(float(back.value_at(7)),
                                       float(s.value_at(7)), rtol=1e-6)


class TestActivations:
    @pytest.mark.parametrize("act", list(Activation))
    def test_all_finite(self, act):
        x = jnp.linspace(-3.0, 3.0, 31)
        y = act(x)
        assert y.shape == x.shape
        assert bool(jnp.all(jnp.isfinite(y)))

    def test_values(self):
        x = jnp.asarray([-1.0, 0.0, 2.0])
        np.testing.assert_allclose(Activation.RELU(x), [0, 0, 2])
        np.testing.assert_allclose(Activation.IDENTITY(x), x)
        np.testing.assert_allclose(Activation.CUBE(x), [-1, 0, 8])
        np.testing.assert_allclose(Activation.HARDTANH(x), [-1, 0, 1])
        sm = Activation.SOFTMAX(jnp.zeros((2, 4)))
        np.testing.assert_allclose(jnp.sum(sm, -1), 1.0, rtol=1e-6)

    def test_from_name(self):
        assert Activation.from_name("relu") is Activation.RELU


class TestLosses:
    def test_mse(self):
        y = jnp.asarray([[1.0, 2.0]])
        p = jnp.asarray([[2.0, 4.0]])
        np.testing.assert_allclose(
            float(LossFunction.MSE.score(y, p)), (1 + 4) / 2, rtol=1e-6)

    def test_mcxent_matches_nll(self):
        y = jax.nn.one_hot(jnp.asarray([1, 0]), 3)
        p = jax.nn.softmax(jnp.asarray([[1.0, 2.0, 0.5],
                                        [0.1, 0.2, 0.3]]))
        a = float(LossFunction.MCXENT.score(y, p))
        b = float(LossFunction.NEGATIVELOGLIKELIHOOD.score(y, p))
        np.testing.assert_allclose(a, b)

    def test_logits_path_matches_probability_path(self):
        logits = jnp.asarray([[2.0, -1.0, 0.5], [0.0, 3.0, -2.0]])
        y = jax.nn.one_hot(jnp.asarray([0, 1]), 3)
        a = float(LossFunction.MCXENT.score_from_logits(y, logits))
        b = float(LossFunction.MCXENT.score(y, jax.nn.softmax(logits)))
        # rtol covers TPU f32 transcendental/accumulation differences
        # (measured ~2.6e-4 relative on v5e; exact on CPU)
        np.testing.assert_allclose(a, b, rtol=5e-4)

    def test_xent_binary(self):
        y = jnp.asarray([[1.0], [0.0]])
        p = jnp.asarray([[0.9], [0.1]])
        expected = -np.log(0.9)
        # rtol covers TPU f32 log differences (measured ~8e-5 on v5e)
        np.testing.assert_allclose(float(LossFunction.XENT.score(y, p)),
                                   expected, rtol=2e-4)

    def test_mask_excludes_examples(self):
        y = jnp.asarray([[1.0], [1.0]])
        p = jnp.asarray([[1.0], [0.0]])
        mask = jnp.asarray([1.0, 0.0])
        # only first example counts -> loss 0
        np.testing.assert_allclose(
            float(LossFunction.MSE.score(y, p, mask=mask)), 0.0, atol=1e-7)
        mask2 = jnp.asarray([0.0, 1.0])
        np.testing.assert_allclose(
            float(LossFunction.MSE.score(y, p, mask=mask2)), 1.0, rtol=1e-6)

    def test_timeseries_mask(self):
        # [batch=1, time=3, feat=2]
        y = jnp.ones((1, 3, 2))
        p = jnp.zeros((1, 3, 2))
        mask = jnp.asarray([[1.0, 1.0, 0.0]])
        # MSE per (b,t) = 1.0; two active steps
        np.testing.assert_allclose(
            float(LossFunction.MSE.score(y, p, mask=mask)), 1.0, rtol=1e-6)

    def test_hinge(self):
        y = jnp.asarray([[1.0], [-1.0]])
        p = jnp.asarray([[0.5], [-2.0]])
        np.testing.assert_allclose(float(LossFunction.HINGE.score(y, p)),
                                   0.25, rtol=1e-6)  # (0.5 + 0)/2

    def test_kld_zero_when_equal(self):
        y = jnp.asarray([[0.3, 0.7]])
        np.testing.assert_allclose(
            float(LossFunction.KL_DIVERGENCE.score(y, y)), 0.0, atol=1e-6)

    def test_gradients_flow(self):
        y = jax.nn.one_hot(jnp.asarray([1]), 3)
        logits = jnp.asarray([[0.1, 0.2, 0.3]])
        g = jax.grad(lambda l: LossFunction.MCXENT.score_from_logits(y, l))(
            logits)
        # softmax-xent gradient: p - y
        np.testing.assert_allclose(
            g, jax.nn.softmax(logits) - y, rtol=1e-5)
