"""Expert-parallelism (MoE) tests (SURVEY.md §2.6 P10 — TPU-native
extension). EP-sharded MoE must match the all-experts-local run when
no tokens overflow capacity; gating must respect capacity limits."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel import make_mesh
from deeplearning4j_tpu.parallel.expert import (
    init_moe_params, moe_ffn, topk_gating)
from deeplearning4j_tpu.parallel.mesh import shard_map as _shard_map

B, T, D, FF, E = 8, 4, 16, 32, 4
N = B * T


def _x(seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(B, T, D).astype(np.float32))


class TestGating:
    def test_capacity_respected(self):
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.randn(64, E).astype(np.float32))
        combine, dispatch, aux, c = topk_gating(logits, k=2,
                                                capacity=5)
        assert c == 5
        # no expert slot double-booked, <= c tokens per expert
        per_slot = np.asarray(dispatch.sum(0))        # [E, C]
        assert per_slot.max() <= 1
        assert np.asarray(dispatch.sum((0, 2))).max() <= 5
        assert np.isfinite(float(aux))

    def test_combine_normalized(self):
        rng = np.random.RandomState(2)
        logits = jnp.asarray(rng.randn(32, E).astype(np.float32))
        combine, dispatch, aux, c = topk_gating(logits, k=2,
                                                capacity=32)
        s = np.asarray(combine.sum((1, 2)))
        np.testing.assert_allclose(s, np.ones(32), atol=1e-5)

    def test_top1_switch(self):
        rng = np.random.RandomState(3)
        logits = jnp.asarray(rng.randn(32, E).astype(np.float32))
        combine, dispatch, aux, c = topk_gating(logits, k=1,
                                                capacity=32)
        # each token dispatched to exactly its argmax expert
        np.testing.assert_array_equal(
            np.asarray(dispatch.sum((1, 2))), np.ones(32))
        np.testing.assert_array_equal(
            np.asarray(dispatch.any(2)).argmax(1),
            np.asarray(logits.argmax(1)))


class TestMoeFfn:
    def _local_ref(self, x, capacity):
        params = init_moe_params(jax.random.PRNGKey(11), D, FF, E,
                                 ep=1, ep_rank=0)
        out, aux = moe_ffn(x, params, axis=None, k=2,
                           capacity=capacity)
        return out

    @pytest.mark.parametrize("ep", [2, 4])
    def test_ep_matches_local(self, ep):
        """With capacity == all local tokens nothing drops, so the
        EP-sharded result equals the single-device result."""
        x = _x()
        ref = self._local_ref(x, capacity=N)
        mesh = make_mesh({"expert": ep}, jax.devices()[:ep])

        def run(xs):
            rank = jax.lax.axis_index("expert")
            params = init_moe_params(jax.random.PRNGKey(11), D, FF, E,
                                     ep=ep, ep_rank=rank)
            out, aux = moe_ffn(xs, params, axis="expert", k=2,
                               capacity=N)
            return out

        out = _shard_map(run, mesh, in_specs=(P("expert"),),
                         out_specs=P("expert"))(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_grads_flow(self):
        x = _x(5)
        params = init_moe_params(jax.random.PRNGKey(11), D, FF, E,
                                 ep=1, ep_rank=0)

        def loss(p, xs):
            out, aux = moe_ffn(xs, p, axis=None, k=2, capacity=N)
            return jnp.sum(out ** 2) + 0.01 * aux

        g = jax.grad(loss)(params, x)
        for leaf in jax.tree_util.tree_leaves(g):
            assert np.isfinite(np.asarray(leaf)).all()
        # gate grads nonzero (aux loss + combine weights both feed Wg)
        assert float(jnp.abs(g["Wg"]).sum()) > 0
