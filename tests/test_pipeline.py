"""Pipeline parallelism on the REAL fit path (ISSUE 18), on the
virtual 8-device CPU mesh.

Covers: the 1F1B tick table against a hand-computed 2-stage /
4-microbatch schedule, the strictly-lower-than-GPipe peak activation
residency bound, pp=2 and pp2×dp 4-step trajectory parity with the
dp-only dense baseline (Sgd / Nesterovs / Adam, MLN + graph, both
schedules), full 3D (dp×tp×pp) composition, the non-divisible
microbatch error path, pp checkpoints restored onto a 1D mesh, the
remesh pipe-axis guard, builder device-count validation, the
fsdp→per-stage-ZeRO-1 downgrade, and the per-stage SpecLayout / wire
accounting surfaces.

Trajectory tolerances follow test_2d_parallel.py: XLA reassociates
the microbatch-sum and update-tail reductions differently per layout,
so parity is float32 noise, not bitwise.
"""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning.updaters import Adam, Nesterovs, Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.parallel import (ParallelWrapper,
                                         PipelineTrainer, SpecLayout,
                                         StagePartition,
                                         bubble_fraction,
                                         build_schedule, make_mesh,
                                         peak_residency)
from deeplearning4j_tpu.parallel.pipeline import (schedule_idle_ticks,
                                                  to_microbatches)
from deeplearning4j_tpu.parallel.zero import exchange_report


def _mlp(updater=None, seed=42):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(updater or Sgd(0.1))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _graph(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(0.01)).weight_init(WeightInit.XAVIER)
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(8))
            .add_layer("d1", DenseLayer(n_out=16,
                                        activation=Activation.TANH),
                       "in")
            .add_layer("out", OutputLayer(
                n_out=3, loss_function=LossFunction.MCXENT,
                activation=Activation.SOFTMAX), "d1")
            .set_outputs("out").build())
    return ComputationGraph(conf).init()


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return DataSet(x, y)


def _assert_tree_close(a, b, **kw):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


def _pp_mesh(dp=4, pp=2):
    return make_mesh({"data": dp, "pipe": pp}, jax.devices()[:dp * pp])


# -- the schedule itself ----------------------------------------------------
def test_1f1b_schedule_matches_hand_table():
    """S=2, M=4 against the hand-computed 1F1B table: one warm-up
    forward on stage 0, then strict one-forward-one-backward
    alternation, drain at the end."""
    F, B = "F", "B"
    expected = [
        ((F, 0), None),
        ((F, 1), (F, 0)),
        (None, (B, 0)),
        ((B, 0), (F, 1)),
        ((F, 2), (B, 1)),
        ((B, 1), (F, 2)),
        ((F, 3), (B, 2)),
        ((B, 2), (F, 3)),
        (None, (B, 3)),
        ((B, 3), None),
    ]
    assert build_schedule(2, 4, "1f1b") == expected


def test_gpipe_schedule_all_forward_then_backward():
    """GPipe reference: every stage finishes all M forwards before any
    backward, backwards run in reverse microbatch order (the scan
    engine's VJP order)."""
    sched = build_schedule(2, 4, "gpipe")
    for s in range(2):
        ops = [op for ops in sched if (op := ops[s]) is not None]
        assert [m for k, m in ops if k == "F"] == [0, 1, 2, 3]
        assert [m for k, m in ops if k == "B"] == [3, 2, 1, 0]
        assert [k for k, _ in ops] == ["F"] * 4 + ["B"] * 4


@pytest.mark.parametrize("s_n,m_n", [(2, 4), (2, 8), (4, 8)])
def test_1f1b_residency_strictly_below_gpipe(s_n, m_n):
    """The acceptance bar: at equal n_micro, 1F1B's peak in-flight
    microbatch count is min(M, S-s) per stage — strictly below GPipe's
    M on every stage where M > S-s."""
    p1 = peak_residency(build_schedule(s_n, m_n, "1f1b"), s_n)
    pg = peak_residency(build_schedule(s_n, m_n, "gpipe"), s_n)
    assert p1 == [min(m_n, s_n - s) for s in range(s_n)]
    assert pg == [m_n] * s_n
    assert all(a < b for a, b in zip(p1, pg))


def test_bubble_fraction_and_idle_ticks():
    """Analytic bubble (S-1)/(M+S-1) matches the tick table's actual
    idle count for both schedules — 1F1B trades residency, not
    bubble."""
    assert bubble_fraction(2, 4) == pytest.approx(0.2)
    for kind in ("gpipe", "1f1b"):
        sched = build_schedule(2, 4, kind)
        assert len(sched) == 10          # 2*M + 2*(S-1)
        assert schedule_idle_ticks(sched, 2) == [2, 2]
    assert bubble_fraction(4, 8) == pytest.approx(3 / 11)


def test_schedule_validation():
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        build_schedule(2, 4, "interleaved")
    with pytest.raises(ValueError, match="n_stages"):
        build_schedule(0, 4)


# -- stage partitioning -----------------------------------------------------
def test_stage_partition_contiguous_and_balanced():
    params = {f"layer_{i}": {"W": np.zeros((8, 8), np.float32)}
              for i in range(4)}
    part = StagePartition.build(list(params), params, 2)
    assert part.stage_entries(0) == ["layer_0", "layer_1"]
    assert part.stage_entries(1) == ["layer_2", "layer_3"]
    assert part.stage_of("layer_2") == 1
    with pytest.raises(ValueError, match="cannot split"):
        StagePartition.build(["layer_0"], params, 2)


def test_infer_stages_specs_match_2d_and_never_name_pipe():
    """SpecLayout.infer_stages: per-stage specs equal what the 2D
    layout infers for the same entries, and the pipe axis never
    appears in a PartitionSpec (it partitions whole entries)."""
    mesh = make_mesh({"data": 2, "model": 2, "pipe": 2},
                     jax.devices()[:8])
    params = {f"layer_{i}": {"W": np.zeros((8, 16), np.float32),
                             "b": np.zeros((16,), np.float32)}
              for i in range(4)}
    part = StagePartition.build(list(params), params, 2)
    lay = SpecLayout(mesh)
    assert lay.pp == 2
    staged = lay.infer_stages(params, part)
    assert [sorted(d) for d in staged] == [["layer_0", "layer_1"],
                                           ["layer_2", "layer_3"]]
    flat2d = SpecLayout(make_mesh({"data": 2, "model": 2},
                                  jax.devices()[:4])).infer(params)
    for d in staged:
        for k, specs in d.items():
            assert specs == flat2d[k]
            for leaf in specs.values():
                assert "pipe" not in tuple(leaf.compute)
                assert "pipe" not in tuple(leaf.resident)


# -- trajectory parity: direct trainer --------------------------------------
@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
@pytest.mark.parametrize("updater,rtol,atol", [
    (lambda: Sgd(0.1), 1e-6, 1e-7),
    (lambda: Nesterovs(0.1, 0.9), 1e-5, 1e-6),
    (lambda: Adam(0.01), 1e-5, 1e-6),
], ids=["sgd", "nesterovs", "adam"])
def test_pp2_trajectory_matches_dense(schedule, updater, rtol, atol):
    """The ISSUE acceptance bar (pp flavor of test_2d_parallel's):
    pp=2 through the real microbatched fit path tracks the unsplit
    dense baseline batch for batch — grads sum over microbatches into
    exactly the full-batch gradient."""
    ref = _mlp(updater())
    net = _mlp(updater())
    tr = PipelineTrainer(net, _pp_mesh(), n_micro=4, schedule=schedule)
    for i in range(4):
        ds = _data(16, seed=i)
        ref.fit(ds)
        tr.fit_batch(ds)
    _assert_tree_close(ref.params, net.params, rtol=rtol, atol=atol)
    rep = tr.last_report
    assert rep["schedule"] == schedule
    assert rep["bubble_fraction"] == pytest.approx(0.2)
    assert rep["pipe_wire_bytes"] > 0


def test_pp2_graph_trajectory_matches_dense():
    """ComputationGraph through the topo-sliced stage forward: same
    4-batch parity bar as the MLN path."""
    ref = _graph()
    net = _graph()
    tr = PipelineTrainer(net, _pp_mesh(), n_micro=4)
    for i in range(4):
        ds = _data(16, seed=i)
        ref.fit(ds)
        tr.fit_batch(ds)
    _assert_tree_close(ref.params, net.params, rtol=1e-5, atol=1e-6)


def test_1f1b_measured_residency_below_gpipe():
    """The residency bound holds for MEASURED activation-stash bytes,
    not just schedule counts."""
    reps = {}
    for kind in ("1f1b", "gpipe"):
        net = _mlp()
        tr = PipelineTrainer(net, _pp_mesh(), n_micro=4, schedule=kind)
        tr.fit_batch(_data(16))
        reps[kind] = tr.last_report
    assert reps["1f1b"]["peak_residency_microbatches"] == [2, 1]
    assert reps["gpipe"]["peak_residency_microbatches"] == [4, 4]
    assert sum(reps["1f1b"]["peak_residency_bytes"]) < \
        sum(reps["gpipe"]["peak_residency_bytes"])


# -- trajectory parity: wrapper (3D mesh) -----------------------------------
@pytest.mark.parametrize("mode", ["dense", "sharded"])
def test_pp2_dp_wrapper_trajectory_matches_dp_only_dense(mode):
    """pp2×dp through ParallelWrapper.Builder.pipeline_stages tracks
    the dp-only 8-way dense baseline — the pipe axis is a purely
    physical re-layout of the same math, in both exchange tails."""
    batches = [_data(64, seed=i) for i in range(4)]
    ref = _mlp(Adam(0.01), seed=7)
    pw_ref = ParallelWrapper.Builder(ref).workers(8) \
        .update_exchange("dense").build()
    net = _mlp(Adam(0.01), seed=7)
    pw = (ParallelWrapper.Builder(net).workers(2).pipeline_stages(2)
          .microbatches(4).update_exchange(mode).build())
    assert pw.pipeline_stages == 2 and pw.n_workers == 2
    for ds in batches:
        pw_ref.fit_batch(ds)
        pw.fit_batch(ds)
    _assert_tree_close(ref.params, net.params, rtol=1e-5, atol=1e-6)
    assert pw._exchange_bytes > 0          # dp=2 per stage exchanges
    assert pw._pipeline.last_report["pipe_wire_bytes"] > 0


def test_3d_dp_tp_pp_trajectory_matches_dense():
    """True 3D: (dp=2, tp=2, pp=2) over all 8 virtual devices tracks
    the dp-only dense baseline — stage partition, per-stage tp specs
    and the ZeRO-1 per-stage flats all compose."""
    batches = [_data(64, seed=i) for i in range(4)]
    ref = _mlp(Adam(0.01), seed=9)
    pw_ref = ParallelWrapper.Builder(ref).workers(8) \
        .update_exchange("dense").build()
    net = _mlp(Adam(0.01), seed=9)
    pw = (ParallelWrapper.Builder(net).workers(2).tensor_parallel(2)
          .pipeline_stages(2).microbatches(4)
          .update_exchange("sharded").build())
    assert dict(pw.mesh.shape) == {"data": 2, "model": 2, "pipe": 2}
    for ds in batches:
        pw_ref.fit_batch(ds)
        pw.fit_batch(ds)
    _assert_tree_close(ref.params, net.params, rtol=2e-5, atol=1e-6)
    # per-stage tp specs were inferred (one sharded entry per stage)
    assert all(pw._pipeline._tp_specs)


def test_fsdp_downgrades_to_per_stage_zero1():
    """fsdp×pp downgrades to the per-stage ZeRO-1 sharded tail (flats
    stay local to each pipe group) and still trains."""
    net = _mlp(Adam(0.01), seed=9)
    pw = (ParallelWrapper.Builder(net).workers(4).pipeline_stages(2)
          .update_exchange("fsdp").build())
    pw.fit_batch(_data(64))
    assert np.isfinite(float(net.score()))
    assert pw._pipeline._tail == "sharded"


# -- error paths ------------------------------------------------------------
def test_microbatch_non_divisible_raises():
    with pytest.raises(ValueError, match="not divisible by 4"):
        to_microbatches(np.zeros((62, 8), np.float32), 4)
    net = _mlp()
    tr = PipelineTrainer(net, _pp_mesh(), n_micro=4)
    with pytest.raises(ValueError, match="not divisible"):
        tr.fit_batch(_data(62))


def test_builder_device_count_validation():
    with pytest.raises(ValueError, match="does not divide"):
        ParallelWrapper.Builder(_mlp()).workers(3) \
            .pipeline_stages(3).build()
    from deeplearning4j_tpu.parallel import SharedTrainingMaster
    master = SharedTrainingMaster.Builder(32).workers_per_node(3) \
        .pipeline_stages(3).build()
    with pytest.raises(ValueError, match="does not divide"):
        master._global_mesh()
    with pytest.raises(ValueError, match="pipeline_stages"):
        ParallelWrapper.Builder(_mlp()).pipeline_stages(0)


def test_trainer_needs_two_stages_on_pipe_axis():
    mesh = make_mesh({"data": 8}, jax.devices()[:8])
    with pytest.raises(ValueError, match=">= 2 stages"):
        PipelineTrainer(_mlp(), mesh)


def test_remesh_rejects_pipe_axis_change_while_placed():
    """Regression (ISSUE 18 satellite): remesh() must refuse to change
    the pipe axis under a placed pipeline — stage jits and the
    partition are keyed to it — and direct the caller to shutdown()
    first. After shutdown the same remesh works."""
    net = _mlp(Adam(0.01))
    pw = (ParallelWrapper.Builder(net).workers(4).pipeline_stages(2)
          .update_exchange("dense").build())
    pw.fit_batch(_data(64))
    with pytest.raises(ValueError, match="pipe axis"):
        pw.remesh(make_mesh({"data": 8}, jax.devices()[:8]))
    pw.shutdown()
    pw.remesh(make_mesh({"data": 8}, jax.devices()[:8]))
    assert pw.pipeline_stages == 1
    pw.fit_batch(_data(64, seed=1))
    assert np.isfinite(float(net.score()))


# -- elasticity: pp -> 1D ---------------------------------------------------
def test_pp_checkpoint_restores_onto_1d_mesh(tmp_path):
    """A checkpoint written under pp=2 restores and CONTINUES on a
    plain dp-only 8-way mesh, tracking the uninterrupted dense
    trajectory (checkpoints densify, so they are stage-count
    portable)."""
    from deeplearning4j_tpu.utils import CheckpointListener
    batches = [_data(64, seed=i) for i in range(4)]
    ref = _mlp(seed=11)
    pw_ref = ParallelWrapper.Builder(ref).workers(8) \
        .update_exchange("dense").build()
    for ds in batches:
        pw_ref.fit_batch(ds)

    net = _mlp(seed=11)
    lis = CheckpointListener(tmp_path, save_every_n_iterations=2)
    net.set_listeners(lis)
    pw = (ParallelWrapper.Builder(net).workers(4).pipeline_stages(2)
          .update_exchange("dense").build())
    for ds in batches[:2]:
        pw.fit_batch(ds)
    lis.flush()

    restored = CheckpointListener.load_checkpoint(tmp_path)
    assert restored.iteration_count == 2
    pw2 = ParallelWrapper.Builder(restored).workers(8) \
        .update_exchange("dense").build()
    assert pw2.pipeline_stages == 1
    for ds in batches[2:]:
        pw2.fit_batch(ds)
    _assert_tree_close(ref.params, restored.params,
                       rtol=2e-5, atol=1e-6)


# -- observability ----------------------------------------------------------
def test_pipeline_report_and_accounting_surfaces():
    """last_report carries the observatory fields, the stepstats
    breakdown gains the pipeline phase, and exchange_report joins the
    per-stage accounting under pipe_shards."""
    from deeplearning4j_tpu.common.stepstats import PHASES
    assert "pipeline" in PHASES
    net = _mlp()
    tr = PipelineTrainer(net, _pp_mesh(), n_micro=4)
    tr.fit_batch(_data(16))
    rep = tr.last_report
    for key in ("bubble_fraction", "bubble_seconds",
                "stage_idle_seconds", "stage_busy_seconds",
                "peak_residency_microbatches", "peak_residency_bytes",
                "pipe_wire_fwd_bytes", "pipe_wire_bwd_bytes",
                "pipe_wire_bytes", "stage_param_bytes"):
        assert key in rep, key
    assert len(rep["stage_idle_seconds"]) == 2

    erep = exchange_report(net.params, 4, "dense", pipe_shards=2,
                           stage_param_bytes=rep["stage_param_bytes"])
    assert erep["pipe_shards"] == 2
    assert erep["pipeline"]["cross_pipe_bytes"] == 0
    assert erep["pipeline"]["stage_param_bytes"] == \
        rep["stage_param_bytes"]
