"""DataVec Reducer + Join tests (reference test style: TestReduce /
TestJoin in datavec-api, SURVEY.md V2)."""
import numpy as np
import pytest

from deeplearning4j_tpu.datavec.reduce_join import (Join, JoinType,
                                                    Reducer, ReduceOp)
from deeplearning4j_tpu.datavec.schema import ColumnType, Schema


def _schema():
    return (Schema.Builder()
            .add_column_string("user")
            .add_column_double("amount")
            .add_column_integer("qty")
            .build())


RECORDS = [
    ["alice", 10.0, 1],
    ["bob", 2.0, 5],
    ["alice", 30.0, 3],
    ["bob", 4.0, 1],
    ["alice", 20.0, 2],
]


class TestReducer:
    def test_sum_and_mean(self):
        red = (Reducer.Builder(ReduceOp.SUM)
               .key_columns("user")
               .mean_columns("amount")
               .build())
        out = red.execute(_schema(), RECORDS)
        by_user = {r[0]: r for r in out}
        assert by_user["alice"][1] == pytest.approx(20.0)  # mean amount
        assert by_user["alice"][2] == 6                    # sum qty
        assert by_user["bob"][1] == pytest.approx(3.0)
        assert by_user["bob"][2] == 6

    def test_schema_transform(self):
        red = (Reducer.Builder(ReduceOp.SUM)
               .key_columns("user")
               .mean_columns("amount")
               .count_columns("qty")
               .build())
        out_schema = red.transform_schema(_schema())
        assert out_schema.column_names() == \
            ["user", "mean(amount)", "count(qty)"]
        assert out_schema.type_of("mean(amount)") is ColumnType.DOUBLE
        assert out_schema.type_of("count(qty)") is ColumnType.LONG

    def test_stdev_minmax_range_unique(self):
        red = (Reducer.Builder(ReduceOp.MIN)
               .key_columns("user")
               .stdev_columns("amount")
               .count_unique_columns("qty")
               .build())
        out = red.execute(_schema(), RECORDS)
        by_user = {r[0]: r for r in out}
        assert by_user["alice"][1] == pytest.approx(10.0)  # stdev
        assert by_user["alice"][2] == 3                    # unique qtys
        assert by_user["bob"][2] == 2


    def test_numeric_op_on_string_column_rejected(self):
        red = (Reducer.Builder(ReduceOp.SUM)
               .key_columns("amount")   # leaves 'user' (string) to SUM
               .build())
        with pytest.raises(ValueError, match="user"):
            red.execute(_schema(), RECORDS)

    def test_string_column_with_first_op_ok(self):
        red = (Reducer.Builder(ReduceOp.SUM)
               .key_columns("qty")
               .first_columns("user")
               .build())
        out = red.execute(_schema(), RECORDS)
        # schema order: [user, amount, qty]; key=qty keeps its position
        by_qty = {r[2]: r for r in out}
        assert by_qty[1][0] == "alice"   # first record with qty=1
        assert by_qty[5][0] == "bob"
        assert by_qty[1][1] == pytest.approx(14.0)  # 10.0 + 4.0

    def test_int_sum_stays_int(self):
        red = (Reducer.Builder(ReduceOp.SUM)
               .key_columns("user")
               .first_columns("amount")
               .build())
        out = red.execute(_schema(), RECORDS)
        qty_sum = {r[0]: r[2] for r in out}
        assert qty_sum["alice"] == 6 and isinstance(qty_sum["alice"],
                                                    int)


class TestJoin:
    def _schemas(self):
        left = (Schema.Builder().add_column_string("k")
                .add_column_double("lv").build())
        right = (Schema.Builder().add_column_string("k")
                 .add_column_integer("rv").build())
        return left, right

    def _join(self, jt):
        left, right = self._schemas()
        return (Join.Builder(jt).set_join_columns("k")
                .set_schemas(left, right).build())

    LEFT = [["a", 1.0], ["b", 2.0], ["c", 3.0]]
    RIGHT = [["a", 10], ["a", 11], ["d", 40]]

    def test_inner(self):
        out = self._join(JoinType.INNER).execute(self.LEFT, self.RIGHT)
        assert sorted(out) == [["a", 1.0, 10], ["a", 1.0, 11]]

    def test_left_outer(self):
        out = self._join(JoinType.LEFT_OUTER).execute(self.LEFT,
                                                      self.RIGHT)
        assert ["b", 2.0, None] in out and ["c", 3.0, None] in out
        assert len(out) == 4

    def test_right_outer(self):
        out = self._join(JoinType.RIGHT_OUTER).execute(self.LEFT,
                                                       self.RIGHT)
        assert ["d", None, 40] in out
        assert len(out) == 3

    def test_full_outer(self):
        out = self._join(JoinType.FULL_OUTER).execute(self.LEFT,
                                                      self.RIGHT)
        assert len(out) == 5   # 2 matches + b + c + d

    def test_output_schema(self):
        j = self._join(JoinType.INNER)
        assert j.output_schema().column_names() == ["k", "lv", "rv"]


class TestSequenceOps:
    def test_convert_to_sequence_sorted(self):
        from deeplearning4j_tpu.datavec.transform import \
            convert_to_sequence
        schema = (Schema.Builder().add_column_string("user")
                  .add_column_integer("t")
                  .add_column_double("v").build())
        recs = [["a", 2, 1.0], ["b", 1, 9.0], ["a", 1, 2.0],
                ["a", 3, 3.0], ["b", 2, 8.0]]
        keys, seqs = convert_to_sequence(schema, recs, "user",
                                         sort_column="t")
        assert keys == ["a", "b"]
        assert [r[1] for r in seqs[0]] == [1, 2, 3]
        assert [r[2] for r in seqs[1]] == [9.0, 8.0]

    def test_trim_and_offset(self):
        from deeplearning4j_tpu.datavec.transform import (offset_sequence,
                                                          trim_sequence)
        seqs = [[[i] for i in range(6)]]
        assert trim_sequence(seqs, 3)[0] == [[0], [1], [2]]
        assert trim_sequence(seqs, 2, from_start=False)[0] == [[4], [5]]
        assert offset_sequence(seqs, 2)[0][0] == [2]
        assert offset_sequence(seqs, -2)[0][-1] == [3]

    def test_reduce_sequence_by_window(self):
        from deeplearning4j_tpu.datavec.transform import \
            reduce_sequence_by_window
        schema = (Schema.Builder().add_column_string("user")
                  .add_column_double("v").build())
        seq = [["a", 1.0], ["a", 2.0], ["a", 3.0], ["a", 4.0]]
        red = (Reducer.Builder(ReduceOp.MEAN)
               .key_columns("user").build())
        out = reduce_sequence_by_window(schema, seq, 2, red)
        assert out == [["a", 1.5], ["a", 3.5]]

    def test_window_partial_tail(self):
        from deeplearning4j_tpu.datavec.transform import \
            reduce_sequence_by_window
        schema = (Schema.Builder().add_column_string("user")
                  .add_column_double("v").build())
        seq = [["a", 1.0], ["a", 2.0], ["a", 3.0], ["a", 4.0],
               ["a", 10.0]]
        red = (Reducer.Builder(ReduceOp.MEAN)
               .key_columns("user").build())
        # partial tail kept by default...
        out = reduce_sequence_by_window(schema, seq, 2, red)
        assert out == [["a", 1.5], ["a", 3.5], ["a", 10.0]]
        # ...and droppable on request
        out2 = reduce_sequence_by_window(schema, seq, 2, red,
                                         include_partial=False)
        assert out2 == [["a", 1.5], ["a", 3.5]]

    def test_trim_to_zero(self):
        from deeplearning4j_tpu.datavec.transform import trim_sequence
        seqs = [[[1], [2]]]
        assert trim_sequence(seqs, 0) == [[]]
        assert trim_sequence(seqs, 0, from_start=False) == [[]]
