"""Unified kernel-selection ladder (ops/kernel_select.py): structural
gate -> force/kill env -> measured auto-heuristic, every decision
counted in dl4j_kernel_select_total{kernel,decision}.  The ladder is
regression-proven against the gates it unified: the attention backend
selector and the fused-BN-backward switch must behave exactly as they
did when each carried its own ad-hoc gate."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.common.environment import Environment
from deeplearning4j_tpu.ops import conv_pallas, kernel_select
from deeplearning4j_tpu.ops.attention_pallas import (
    flash_attention_override, select_attention_backend)
from deeplearning4j_tpu.ops.bn_pallas import fused_bn_bwd_enabled


@pytest.fixture(autouse=True)
def _clean_extra():
    env = Environment.get()
    keys = ("fused_conv", "fused_bn_bwd", "flash_attention")
    saved = {k: env.extra.get(k) for k in keys}
    for k in keys:
        env.extra.pop(k, None)
    yield
    for k, v in saved.items():
        if v is None:
            env.extra.pop(k, None)
        else:
            env.extra[k] = v


def _delta(kernel, fn):
    before = kernel_select.decisions(kernel)
    out = fn()
    after = kernel_select.decisions(kernel)
    return out, {d: after[d] - before[d] for d in after
                 if after[d] != before[d]}


class TestLadder:
    def test_structural_gate_dominates_force(self):
        sel, counts = _delta("conv_epilogue", lambda: kernel_select.select(
            "conv_epilogue", structural="dtype int32 is not floating",
            auto=(True, "auto"), override=True,
            use_env_override=False))
        assert not sel.fused
        assert sel.decision == "structural"
        assert "int32" in sel.reason
        assert counts == {"structural": 1}

    def test_force_and_kill_beat_auto(self):
        sel, counts = _delta("conv_epilogue", lambda: kernel_select.select(
            "conv_epilogue", auto=(False, "auto says no"),
            override=True, use_env_override=False))
        assert sel.fused and sel.decision == "forced"
        assert sel.reason == "DL4J_TPU_FUSED_CONV=1 forced"
        assert counts == {"forced": 1}
        sel, counts = _delta("conv_epilogue", lambda: kernel_select.select(
            "conv_epilogue", auto=(True, "auto says yes"),
            override=False, use_env_override=False))
        assert not sel.fused and sel.decision == "killed"
        assert sel.reason == "DL4J_TPU_FUSED_CONV=0 kill switch"
        assert counts == {"killed": 1}

    def test_auto_thunk_decides_when_unset(self):
        sel, counts = _delta("conv_epilogue", lambda: kernel_select.select(
            "conv_epilogue", auto=lambda: (True, "auto: measured"),
            override=None, use_env_override=False))
        assert sel.fused and sel.decision == "auto_fused"
        assert counts == {"auto_fused": 1}

    def test_extra_overrides_env_var(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FUSED_CONV", "0")
        assert kernel_select.gate_override("conv_epilogue") is False
        Environment.get().extra["fused_conv"] = "1"
        assert kernel_select.gate_override("conv_epilogue") is True

    def test_env_var_tristate(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_FUSED_CONV", raising=False)
        assert kernel_select.gate_override("conv_epilogue") is None
        monkeypatch.setenv("DL4J_TPU_FUSED_CONV", "1")
        assert kernel_select.gate_override("conv_epilogue") is True
        monkeypatch.setenv("DL4J_TPU_FUSED_CONV", "0")
        assert kernel_select.gate_override("conv_epilogue") is False


class TestConvFamilyGates:
    def test_structural_demotions_logged_reasons(self):
        cases = [
            # (kwargs, reason substring)
            (dict(out_shape=(2, 8, 8, 16), dtype=jnp.int32,
                  act_name="relu"), "not floating"),
            (dict(out_shape=(2, 8, 8, 5), dtype=jnp.float32,
                  act_name="relu"), "sublane-aligned"),
            (dict(out_shape=(2, 8, 8, 16), dtype=jnp.float32,
                  act_name="tanh"), "not streamable"),
            (dict(out_shape=(2, 8, 8, 16), dtype=jnp.float32,
                  act_name="identity", has_epilogue=False),
             "no epilogue"),
            (dict(out_shape=(16,), dtype=jnp.float32,
                  act_name="relu"), "rank 1"),
        ]
        for kwargs, substr in cases:
            sel = conv_pallas.select_conv_epilogue(
                platform="tpu", override=True, **kwargs)
            assert not sel.fused and sel.decision == "structural"
            assert substr in sel.reason, (kwargs, sel.reason)

    def test_f64_demotes_on_tpu_only(self):
        kw = dict(out_shape=(2, 8, 8, 16), dtype=jnp.float64,
                  act_name="relu", override=True)
        assert not conv_pallas.select_conv_epilogue(
            platform="tpu", **kw).fused
        assert conv_pallas.select_conv_epilogue(
            platform="cpu", **kw).fused

    def test_bn_forward_inference_is_structural(self):
        """The training-vs-inference gate: the batch-stats kernel is
        a training-mode construct; forcing cannot resurrect it in
        inference."""
        sel = conv_pallas.select_bn_forward(
            (2, 8, 8, 16), jnp.float32, training=False,
            platform="tpu", override=True)
        assert not sel.fused and sel.decision == "structural"
        assert "inference" in sel.reason
        assert conv_pallas.select_bn_forward(
            (2, 8, 8, 16), jnp.float32, training=True,
            platform="tpu", override=True).fused

    def test_auto_heuristic_platform_and_floor(self):
        kw = dict(out_shape=(256, 1024), dtype=jnp.float32,
                  act_name="relu")
        sel = conv_pallas.select_conv_epilogue(
            platform="cpu", override=None, use_env_override=False,
            **kw)
        assert not sel.fused and "not tpu" in sel.reason
        sel = conv_pallas.select_conv_epilogue(
            platform="tpu", override=None, use_env_override=False,
            **kw)
        assert sel.fused and sel.decision == "auto_fused"
        small = dict(out_shape=(8, 16), dtype=jnp.float32,
                     act_name="relu")
        sel = conv_pallas.select_conv_epilogue(
            platform="tpu", override=None, use_env_override=False,
            **small)
        assert not sel.fused and "below the fusion floor" in sel.reason

    def test_counter_increments_per_decision(self):
        _, counts = _delta("conv_epilogue", lambda: [
            conv_pallas.select_conv_epilogue(
                (2, 8, 8, 16), jnp.float32, "relu", platform="cpu",
                override=True),
            conv_pallas.select_conv_epilogue(
                (2, 8, 8, 16), jnp.float32, "tanh", platform="cpu",
                override=True),
            conv_pallas.select_conv_epilogue(
                (2, 8, 8, 16), jnp.float32, "relu", platform="cpu",
                override=None, use_env_override=False),
        ])
        assert counts == {"forced": 1, "structural": 1,
                          "auto_dense": 1}


class TestAttentionGateMirrored:
    """The flash gate behaves exactly as before the unification, and
    its decisions now land in the shared counter."""

    Q4 = (2, 4, 512, 64)

    def test_reason_strings_preserved(self):
        assert select_attention_backend(
            self.Q4, self.Q4, has_bias=True) == \
            ("dense", "additive bias is not streamable")
        assert select_attention_backend(
            self.Q4, self.Q4, override=False) == \
            ("dense", "DL4J_TPU_FLASH_ATTENTION=0 kill switch")
        assert select_attention_backend(
            self.Q4, self.Q4, override=True) == \
            ("flash", "DL4J_TPU_FLASH_ATTENTION=1 forced")
        backend, reason = select_attention_backend(
            self.Q4, (2, 4, 8192, 64), platform="tpu", override=None,
            use_env_override=False)
        assert backend == "flash" and "t_k=8192" in reason

    def test_decisions_counted(self):
        _, counts = _delta("attention", lambda: [
            select_attention_backend(self.Q4, self.Q4, has_bias=True),
            select_attention_backend(self.Q4, self.Q4, override=True),
            select_attention_backend(self.Q4, self.Q4,
                                     platform="cpu", override=None,
                                     use_env_override=False),
        ])
        assert counts == {"structural": 1, "forced": 1,
                          "auto_dense": 1}

    def test_override_reads_extra_then_env(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FLASH_ATTENTION", "1")
        assert flash_attention_override() is True
        Environment.get().extra["flash_attention"] = "0"
        assert flash_attention_override() is False


class TestBnBwdGateMirrored:
    def test_env_semantics_preserved(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FUSED_BN_BWD", "1")
        assert fused_bn_bwd_enabled() is True
        monkeypatch.setenv("DL4J_TPU_FUSED_BN_BWD", "0")
        assert fused_bn_bwd_enabled() is False
        monkeypatch.delenv("DL4J_TPU_FUSED_BN_BWD", raising=False)
        # auto rung: ON exactly on tpu
        expected = jax.devices()[0].platform == "tpu"
        assert fused_bn_bwd_enabled() is expected

    def test_decisions_counted(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FUSED_BN_BWD", "1")
        _, counts = _delta("bn_bwd", fused_bn_bwd_enabled)
        assert counts == {"forced": 1}


class TestFusedSitesCounter:
    def test_fused_steps_counter_increments(self):
        env = Environment.get()
        env.extra["fused_conv"] = "1"
        try:
            x = jnp.asarray(np.random.RandomState(0)
                            .randn(2, 4, 4, 16), jnp.float32)
            before = conv_pallas._fused_steps.value(site="bn_infer")
            out = conv_pallas.maybe_bn_inference_epilogue(
                x, jnp.ones(16), jnp.zeros(16), Activation.RELU)
            assert out is not None
            after = conv_pallas._fused_steps.value(site="bn_infer")
            assert after == before + 1
        finally:
            env.extra.pop("fused_conv", None)
