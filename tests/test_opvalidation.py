"""Op validation suite (SURVEY.md §4.3: OpValidation — forward vs
numpy ground truth + analytic-vs-numeric gradients per op, with
coverage accounting)."""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.opvalidation import (TestCase,
                                                      coverage_report,
                                                      validate,
                                                      validated_ops)

R = np.random.RandomState(7)
A = R.randn(3, 4).astype(np.float32)
B = R.randn(3, 4).astype(np.float32)
P = (np.abs(A) + 0.5).astype(np.float32)       # strictly positive
U = (R.rand(3, 4).astype(np.float32) * 1.6 - 0.8)  # in (-0.8, 0.8)
M1 = R.randn(4, 5).astype(np.float32)
IMG = R.randn(2, 8, 8, 3).astype(np.float32)
KER = (R.randn(3, 3, 3, 4) * 0.2).astype(np.float32)


def sp(x):
    return np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0)


CASES = [
    # arithmetic / broadcastable
    TestCase("add", [A, B], expected_fn=np.add),
    TestCase("sub", [A, B], expected_fn=np.subtract),
    TestCase("mul", [A, B], expected_fn=np.multiply),
    TestCase("div", [A, P], expected_fn=np.divide),
    TestCase("pow", [P, np.float32(2.5)],
             expected_fn=lambda a, b: a ** b, grad_inputs=[0]),
    TestCase("maximum", [A, B], expected_fn=np.maximum,
             gradient_check=False),   # kink at ties
    TestCase("minimum", [A, B], expected_fn=np.minimum,
             gradient_check=False),
    TestCase("squared_difference", [A, B],
             expected_fn=lambda a, b: (a - b) ** 2),
    # transforms / unary
    TestCase("neg", [A], expected_fn=np.negative),
    TestCase("abs", [P], expected_fn=np.abs),
    TestCase("exp", [U], expected_fn=np.exp),
    TestCase("log", [P], expected_fn=np.log),
    TestCase("log1p", [P], expected_fn=np.log1p),
    TestCase("sqrt", [P], expected_fn=np.sqrt),
    TestCase("rsqrt", [P], expected_fn=lambda a: 1 / np.sqrt(a)),
    TestCase("square", [A], expected_fn=np.square),
    TestCase("reciprocal", [P], expected_fn=lambda a: 1 / a),
    TestCase("sin", [A], expected_fn=np.sin),
    TestCase("cos", [A], expected_fn=np.cos),
    TestCase("tan", [U], expected_fn=np.tan),
    TestCase("asin", [U], expected_fn=np.arcsin),
    TestCase("acos", [U], expected_fn=np.arccos),
    TestCase("atan", [A], expected_fn=np.arctan),
    TestCase("sinh", [U], expected_fn=np.sinh),
    TestCase("cosh", [U], expected_fn=np.cosh),
    TestCase("tanh", [A], expected_fn=np.tanh),
    TestCase("erf", [U],
             expected_fn=lambda a: np.vectorize(__import__(
                 "math").erf)(a).astype(np.float32)),
    TestCase("sign", [P], expected_fn=np.sign,
             gradient_check=False),
    TestCase("floor", [A], expected_fn=np.floor,
             gradient_check=False),
    TestCase("ceil", [A], expected_fn=np.ceil,
             gradient_check=False),
    TestCase("clip_by_value", [A],
             {"clip_value_min": -0.5, "clip_value_max": 0.5},
             expected_fn=lambda a: np.clip(a, -0.5, 0.5),
             gradient_check=False),
    # activations
    TestCase("relu", [P], expected_fn=lambda a: np.maximum(a, 0)),
    TestCase("sigmoid", [A],
             expected_fn=lambda a: 1 / (1 + np.exp(-a))),
    TestCase("softplus", [A], expected_fn=sp),
    TestCase("elu", [U],
             expected_fn=lambda a: np.where(a > 0, a,
                                            np.expm1(a))),
    TestCase("leaky_relu", [P], {"alpha": 0.1},
             expected_fn=lambda a: np.where(a > 0, a, 0.1 * a)),
    TestCase("softmax", [A], {"axis": -1},
             expected_fn=lambda a: np.exp(a) / np.exp(a).sum(
                 -1, keepdims=True)),
    TestCase("log_softmax", [A], {"axis": -1},
             expected_fn=lambda a: a - a.max(-1, keepdims=True)
             - np.log(np.exp(a - a.max(-1, keepdims=True)).sum(
                 -1, keepdims=True))),
    TestCase("gelu", [A], gradient_check=True),
    # reductions
    TestCase("reduce_sum", [A], {"axis": (1,)},
             expected_fn=lambda a: a.sum(1)),
    TestCase("reduce_mean", [A], {"axis": (0,), "keep_dims": True},
             expected_fn=lambda a: a.mean(0, keepdims=True)),
    TestCase("reduce_max", [A], {"axis": (1,)},
             expected_fn=lambda a: a.max(1), gradient_check=False),
    TestCase("reduce_min", [A], {"axis": None},
             expected_fn=lambda a: a.min(), gradient_check=False),
    TestCase("reduce_prod", [P], {"axis": (1,)},
             expected_fn=lambda a: a.prod(1)),
    TestCase("reduce_std", [A], {"axis": (1,)},
             expected_fn=lambda a: a.std(1)),
    TestCase("reduce_var", [A], {"axis": (1,)},
             expected_fn=lambda a: a.var(1)),
    # shape
    TestCase("reshape", [A], {"shape": [4, 3]},
             expected_fn=lambda a: a.reshape(4, 3)),
    TestCase("permute", [A], {"axes": [1, 0]},
             expected_fn=lambda a: a.T),
    TestCase("expand_dims", [A], {"axis": 1},
             expected_fn=lambda a: a[:, None, :]),
    TestCase("squeeze", [A[:, None, :]], {"axis": (1,)},
             expected_fn=lambda a: a[:, 0, :]),
    TestCase("concat", [A, B], {"axis": 0},
             expected_fn=lambda a, b: np.concatenate([a, b], 0)),
    TestCase("stack", [A, B], {"axis": 0},
             expected_fn=lambda a, b: np.stack([a, b], 0)),
    TestCase("tile", [A], {"reps": (2, 1)},
             expected_fn=lambda a: np.tile(a, (2, 1))),
    TestCase("flip", [A], {"axis": 1},
             expected_fn=lambda a: np.flip(a, 1)),
    TestCase("gather", [A, np.asarray([2, 0], np.int32)], {"axis": 0},
             expected_fn=lambda a, i: a[i], grad_inputs=[0]),
    TestCase("pad", [A], {"paddings": [(1, 0), (0, 2)]},
             expected_fn=lambda a: np.pad(a, [(1, 0), (0, 2)])),
    TestCase("strided_slice", [A],
             {"begin": [0, 1], "end": [3, 4], "strides": [2, 1]},
             expected_fn=lambda a: a[0:3:2, 1:4]),
    TestCase("slice", [A], {"begin": [1, 0], "size": [2, 3]},
             expected_fn=lambda a: a[1:3, 0:3]),
    # blas
    TestCase("matmul", [A, M1], expected_fn=np.matmul),
    TestCase("matmul", [A.T, M1], {"transpose_a": True},
             expected_fn=lambda a, b: a.T @ b),
    # normalization
    TestCase("batch_norm",
             [IMG, np.zeros(3, np.float32),
              np.ones(3, np.float32),
              np.ones(3, np.float32), np.zeros(3, np.float32)],
             {"epsilon": 1e-5},
             expected_fn=lambda x, m, v, g, b:
             (x - m) / np.sqrt(v + 1e-5),
             # loss sums 384 elements in f32: summation noise needs a
             # larger step + tolerance
             grad_inputs=[0, 3, 4], epsilon=3e-2, grad_tol=5e-2),
    TestCase("layer_norm",
             [A, np.ones(4, np.float32), np.zeros(4, np.float32)],
             expected_fn=lambda x, g, b:
             (x - x.mean(-1, keepdims=True))
             / np.sqrt(x.var(-1, keepdims=True) + 1e-5)),
    # convolution family (forward vs lax is definitional; gradient
    # check is the content here)
    TestCase("conv2d", [IMG, KER],
             {"stride": (1, 1), "padding": "SAME"}, max_entries=4),
    TestCase("max_pool2d", [IMG],
             {"kernel": (2, 2), "stride": (2, 2)},
             gradient_check=False),
    TestCase("avg_pool2d", [IMG],
             {"kernel": (2, 2), "stride": (2, 2)}, max_entries=4),
]


@pytest.mark.parametrize(
    "tc", CASES,
    ids=[f"{c.op}_{i}" for i, c in enumerate(CASES)])
def test_op(tc):
    validate(tc)


def test_coverage_accounting():
    """reference behavior: coverage is ACCOUNTED — the suite states
    how much of the registry carries validation cases and enforces a
    floor that only moves up."""
    for tc in CASES:
        validate(tc)
    rep = coverage_report()
    assert rep["covered"] >= 55, rep["covered"]
    # batch-1's own fraction: the denominator is the WHOLE registry,
    # so this floor dips as the registry grows (r5: +resize_bicubic/
    # resize_area -> 238 ops). The ratchet that must only move up is
    # the COMBINED batches-1+2 floor (>=0.95,
    # test_opvalidation_2.test_combined_coverage_floor)
    assert rep["fraction"] >= 0.26, (rep["fraction"],
                                     rep["missing"][:20])
    assert "matmul" in validated_ops()
