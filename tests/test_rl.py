"""RL subsystem tests (SURVEY.md D18: MDP, DQN, A2C, policies)."""
import numpy as np
import pytest

from deeplearning4j_tpu.rl import (A2CConfiguration, A2CDiscreteDense,
                                   CartPole, DQNPolicy, GridWorld,
                                   QLearningConfiguration,
                                   QLearningDiscreteDense)


class TestMdp:
    def test_cartpole_contract(self):
        env = CartPole(seed=0)
        obs = env.reset()
        assert obs.shape == (4,)
        reply = env.step(1)
        assert reply.observation.shape == (4,)
        assert reply.reward == 1.0
        # random policy fails well before max_steps
        steps = 0
        env.reset()
        while not env.is_done() and steps < 600:
            env.step(np.random.randint(2))
            steps += 1
        assert steps < 500

    def test_gridworld_deterministic(self):
        env = GridWorld(4)
        env.reset()
        r = [env.step(1) for _ in range(3)]
        assert r[-1].done and r[-1].reward == 1.0
        assert sum(x.reward for x in r[:-1]) == 0


class TestDqn:
    def test_gridworld_learns_optimal_policy(self):
        env = GridWorld(5)
        conf = QLearningConfiguration(
            seed=3, max_step=4000, max_epoch_step=30,
            exp_replay_size=2000, batch_size=32,
            target_dqn_update_freq=50, update_start=50,
            epsilon_nb_step=1500, learning_rate=5e-3, hidden=(32,))
        dqn = QLearningDiscreteDense(env, conf)
        dqn.train()
        policy = dqn.get_policy()
        assert isinstance(policy, DQNPolicy)
        # optimal: 4 steps right, total reward 1
        total = policy.play(GridWorld(5), max_steps=10)
        assert total == 1.0
        # greedy action from start must be RIGHT
        assert policy.next_action(GridWorld(5).reset()) == 1

    def test_epsilon_anneals(self):
        dqn = QLearningDiscreteDense(GridWorld(4),
                                     QLearningConfiguration(
                                         epsilon_nb_step=100,
                                         min_epsilon=0.1))
        assert dqn.epsilon() == pytest.approx(1.0)
        dqn.step_count = 50
        assert 0.1 < dqn.epsilon() < 1.0
        dqn.step_count = 1000
        assert dqn.epsilon() == pytest.approx(0.1)

    def test_cartpole_improves(self):
        conf = QLearningConfiguration(
            seed=0, max_step=15000, max_epoch_step=500,
            batch_size=64, target_dqn_update_freq=100,
            update_start=500, epsilon_nb_step=5000,
            learning_rate=1e-3, hidden=(64, 64),
            exp_replay_size=20000)
        dqn = QLearningDiscreteDense(CartPole(seed=1), conf)
        rewards = dqn.train()
        early = np.mean(rewards[:5])
        greedy = np.mean([dqn.get_policy().play(CartPole(seed=100 + i),
                                                max_steps=500)
                          for i in range(3)])
        assert greedy > early + 20, (early, greedy)
        assert greedy > 40, greedy


class TestA2C:
    def test_gridworld_learns(self):
        env = GridWorld(5)
        conf = A2CConfiguration(seed=1, max_step=6000, n_step=16,
                                learning_rate=5e-3, hidden=(32,))
        a2c = A2CDiscreteDense(env, conf)
        a2c.train()
        # greedy rollout reaches the goal
        env2 = GridWorld(5)
        obs = env2.reset()
        for _ in range(6):
            reply = env2.step(a2c.choose_action(obs, greedy=True))
            obs = reply.observation
            if reply.done:
                break
        assert reply.done and reply.reward == 1.0
