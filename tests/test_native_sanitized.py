"""Run the ENTIRE native test suite against the ASan+UBSan build
(SURVEY.md §5.2 / N16; round-2 verdict ask #8): the C++ runtime does
pointer arithmetic, arena math, and a pthread ring queue — the
sanitizers must see every code path the normal suite exercises.

Mechanics: ``make sanitize`` produces ``libdl4j_native_san.so``; a
subprocess re-runs tests/test_native.py with libasan LD_PRELOADed and
``DL4J_TPU_NATIVE_LIB`` pointing at the sanitized library
(``-fno-sanitize-recover=all``, halt-on-error, so any finding fails
the run).  Leak detection is off — the host is a full CPython
interpreter."""
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE = os.path.join(_ROOT, "native")
_SAN_LIB = os.path.join(_NATIVE, "build", "libdl4j_native_san.so")


def _libasan_path():
    try:
        out = subprocess.run(["g++", "-print-file-name=libasan.so"],
                             capture_output=True, text=True,
                             timeout=30)
        path = out.stdout.strip()
        return path if path and os.path.exists(path) else None
    except Exception:
        return None


def test_native_suite_under_asan_ubsan():
    libasan = _libasan_path()
    if libasan is None:
        pytest.skip("libasan not available")
    build = subprocess.run(["make", "-C", _NATIVE, "sanitize"],
                           capture_output=True, text=True,
                           timeout=300)
    assert build.returncode == 0, build.stderr[-2000:]
    assert os.path.exists(_SAN_LIB)

    env = {
        **os.environ,
        "PYTHONPATH": _ROOT,          # no axon sitecustomize
        "JAX_PLATFORMS": "cpu",
        "LD_PRELOAD": libasan,
        "DL4J_TPU_NATIVE_LIB": _SAN_LIB,
        # CPython itself is not leak-clean; every real ASan/UBSan
        # finding still aborts via -fno-sanitize-recover=all
        "ASAN_OPTIONS": "detect_leaks=0:abort_on_error=1:"
                        "allocator_may_return_null=1",
        "UBSAN_OPTIONS": "halt_on_error=1:print_stacktrace=1",
    }
    # -k: jaxlib is not ASan-instrumented and crashes under the
    # preload; test_streams_all_batches is the one case that imports
    # jax (via DataSet) — the native ring queue it rides on is fully
    # covered by TestQueue, which runs here
    run = subprocess.run(
        [sys.executable, "-m", "pytest",
         os.path.join(_ROOT, "tests", "test_native.py"), "-q",
         "--no-header", "-p", "no:cacheprovider",
         "-k", "not streams_all_batches"],
        capture_output=True, text=True, timeout=480, env=env,
        cwd=_ROOT)
    tail = (run.stdout + "\n" + run.stderr)[-4000:]
    assert run.returncode == 0, \
        f"native suite under ASan+UBSan failed:\n{tail}"
    assert "passed" in run.stdout, tail
