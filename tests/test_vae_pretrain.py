"""AutoEncoder + VariationalAutoencoder layer tests, incl. layerwise
pretraining through MultiLayerNetwork.pretrain (reference test style:
TestVAE / AutoEncoderTest, SURVEY.md §4.8)."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.layers_vae import (AutoEncoder,
                                                   VariationalAutoencoder)


def _blobs(n=256, d=8, seed=0):
    """Two gaussian blobs in d dims."""
    rng = np.random.RandomState(seed)
    ys = rng.randint(0, 2, n)
    centers = np.zeros((2, d), np.float32)
    centers[0, 0] = 2.0
    centers[1, 0] = -2.0
    xs = centers[ys] + 0.3 * rng.randn(n, d).astype(np.float32)
    return xs, ys


class TestAutoEncoder:
    def test_pretrain_reduces_reconstruction_error(self):
        xs, _ = _blobs()
        layer = AutoEncoder(n_out=4, activation=Activation.SIGMOID,
                            corruption_level=0.2)
        conf = (NeuralNetConfiguration.Builder()
                .seed(3).updater(Adam(1e-2))
                .list()
                .layer(layer)
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        p0 = net.params["layer_0"]
        err0 = float(jnp.mean(jnp.sum(
            (layer.reconstruct(p0, jnp.asarray(xs)) - xs) ** 2, -1)))
        for _ in range(100):
            net.pretrain_layer(0, xs)
        p1 = net.params["layer_0"]
        err1 = float(jnp.mean(jnp.sum(
            (layer.reconstruct(p1, jnp.asarray(xs)) - xs) ** 2, -1)))
        assert err1 < err0 * 0.8

    def test_pretrain_then_finetune(self):
        xs, ys = _blobs()
        labels = np.eye(2, dtype=np.float32)[ys]
        conf = (NeuralNetConfiguration.Builder()
                .seed(3).updater(Adam(1e-2))
                .list()
                .layer(AutoEncoder(n_out=4,
                                   activation=Activation.SIGMOID))
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.pretrain(xs, n_epochs=30)
        for _ in range(40):
            net.fit(xs, labels)
        acc = (np.asarray(net.output(xs)).argmax(-1) == ys).mean()
        assert acc > 0.95


class TestPretrainPreprocessor:
    def test_pretrain_above_conv_stack(self):
        """AutoEncoder above a conv layer: the auto-inserted
        CnnToFeedForward preprocessor must apply during pretraining too
        (regression: stop_at skipped the pretrain layer's preprocessor)."""
        from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                       SubsamplingLayer)
        rng = np.random.RandomState(0)
        xs = rng.rand(16, 8, 8, 1).astype(np.float32)
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(1e-3))
                .list()
                .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=4,
                                        activation=Activation.RELU))
                .layer(SubsamplingLayer(kernel_size=(2, 2),
                                        stride=(2, 2)))
                .layer(AutoEncoder(n_out=8,
                                   activation=Activation.SIGMOID))
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.pretrain_layer(2, xs, n_epochs=3)   # must not shape-error
        assert np.isfinite(float(net._score))

    def test_pretrain_accepts_indarray(self):
        from deeplearning4j_tpu.ndarray import Nd4j
        xs, _ = _blobs(n=32)
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(1e-3))
                .list()
                .layer(AutoEncoder(n_out=4,
                                   activation=Activation.SIGMOID))
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.pretrain_layer(0, Nd4j.create(xs), n_epochs=2)
        assert np.isfinite(float(net._score))


class TestVAE:
    def _vae_layer(self, dist="gaussian"):
        return VariationalAutoencoder(
            n_out=2, encoder_layer_sizes=(16,), decoder_layer_sizes=(16,),
            activation=Activation.TANH,
            reconstruction_distribution=dist)

    def test_elbo_decreases(self):
        xs, _ = _blobs()
        layer = self._vae_layer()
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(1e-2))
                .list()
                .layer(layer)
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        losses = []
        for _ in range(120):
            net.pretrain_layer(0, xs)
            losses.append(float(net._score))
        assert np.mean(losses[-10:]) < np.mean(losses[:10]) - 1.0

    def test_forward_outputs_latent_mean(self):
        layer = self._vae_layer()
        layer.n_in = 8
        params = layer.init_params(jax.random.PRNGKey(0),
                                   InputType.feed_forward(8))
        y, _ = layer.forward(params, jnp.ones((4, 8)), training=False)
        assert y.shape == (4, 2)

    def test_reconstruction_scoring_api(self):
        xs, _ = _blobs(n=32)
        layer = self._vae_layer()
        layer.n_in = 8
        params = layer.init_params(jax.random.PRNGKey(0),
                                   InputType.feed_forward(8))
        lp = layer.reconstruction_log_probability(
            params, jnp.asarray(xs), jax.random.PRNGKey(1), num_samples=4)
        assert lp.shape == (32,)
        assert np.all(np.isfinite(np.asarray(lp)))
        err = layer.reconstruction_error(params, jnp.asarray(xs))
        assert err.shape == (32,)
        z = jnp.zeros((5, 2))
        gen = layer.generate_at_mean_given_z(params, z)
        assert gen.shape == (5, 8)

    def test_bernoulli_distribution(self):
        rng = np.random.RandomState(0)
        xs = (rng.rand(64, 8) > 0.5).astype(np.float32)
        layer = self._vae_layer(dist="bernoulli")
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(1e-2))
                .list()
                .layer(layer)
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(20):
            net.pretrain_layer(0, xs)
        assert np.isfinite(float(net._score))
        gen = layer.generate_at_mean_given_z(net.params["layer_0"],
                                             jnp.zeros((3, 2)))
        assert float(gen.min()) >= 0.0 and float(gen.max()) <= 1.0


class TestGraphPretrain:
    """ComputationGraph.pretrain (reference:
    ComputationGraph.pretrain(iter) — r4 verdict Missing #3: an AE/VAE
    vertex in a DAG must be greedily pretrainable, like MLN's)."""

    def _graph(self, seed=5):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        g = (NeuralNetConfiguration.Builder()
             .seed(seed).updater(Adam(1e-2))
             .graph_builder()
             .add_inputs("in"))
        g.add_layer("d", DenseLayer(n_out=8,
                                    activation=Activation.TANH), "in")
        g.add_layer("ae", AutoEncoder(n_out=4,
                                      activation=Activation.SIGMOID,
                                      corruption_level=0.2), "d")
        g.add_layer("out", OutputLayer(n_out=2,
                                       loss_function=LossFunction.MCXENT,
                                       activation=Activation.SOFTMAX),
                    "ae")
        g.set_outputs("out")
        g.set_input_types(InputType.feed_forward(8))
        return ComputationGraph(g.build()).init()

    def _recon_err(self, net, xs):
        layer = net.conf.vertices["ae"].content
        acts, _ = net._forward(net.params, net.states,
                               [jnp.asarray(xs)], training=False,
                               rng=None, want_logits=False)
        h = acts["d"]
        p = net.params["ae"]
        return float(jnp.mean(jnp.sum(
            (layer.reconstruct(p, h) - h) ** 2, -1)))

    def test_pretrain_vertex_reduces_reconstruction_error(self):
        xs, _ = _blobs()
        net = self._graph()
        before = dict(net.params)
        err0 = self._recon_err(net, xs)
        for _ in range(100):
            net.pretrain_vertex("ae", xs)
        err1 = self._recon_err(net, xs)
        assert err1 < err0 * 0.8
        # only the AE vertex moved; the rest of the graph is frozen
        for k in ("d", "out"):
            for pn in before[k]:
                np.testing.assert_array_equal(
                    np.asarray(before[k][pn]),
                    np.asarray(net.params[k][pn]), err_msg=f"{k}/{pn}")

    def test_pretrain_walks_all_pretrainable_vertices(self):
        xs, ys = _blobs()
        net = self._graph()
        err0 = self._recon_err(net, xs)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        labels = np.eye(2, dtype=np.float32)[ys]
        for _ in range(60):
            net.pretrain(DataSet(xs, labels))
        err1 = self._recon_err(net, xs)
        assert err1 < err0 * 0.85
        # then fine-tunes supervised end-to-end without error
        for _ in range(40):
            net.fit([xs], [labels])
        from deeplearning4j_tpu.evaluation import Evaluation
        out = np.asarray(net.output([xs])[0])
        acc = float(np.mean(out.argmax(-1) == ys))
        assert acc > 0.9

    def test_pretrain_vertex_rejects_non_pretrainable(self):
        import pytest
        net = self._graph()
        with pytest.raises(ValueError):
            net.pretrain_vertex("d", np.zeros((4, 8), np.float32))
