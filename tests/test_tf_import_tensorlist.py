"""TF TensorArray/TensorList import (SURVEY.md S3): the v2 lowering
of ``tf.TensorArray`` — TensorListReserve/SetItem/GetItem/Stack —
maps onto a dense [n, *element_shape] accumulator (SetItem is a
dynamic slice update: differentiable, and the loop-carry layout XLA
wants).  The element shape is recovered from downstream consts, since
TF records -1 on the Reserve itself."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.modelimport.tensorflow import (  # noqa: E402
    TensorflowFrameworkImporter)


def _freeze(fn, *specs):
    cf = tf.function(fn).get_concrete_function(*specs)
    return cf.graph.as_graph_def().SerializeToString(), cf


def _out(imp):
    return sorted(n for n in imp.vars if n.startswith("Identity"))[0]


class TestTensorArrayImport:
    def test_while_accumulator_scalar(self):
        """The canonical pattern: a loop writing one scalar per step,
        stacked after the loop."""
        def f(x):
            ta0 = tf.TensorArray(tf.float32, size=3)

            def body(i, ta):
                return i + 1, ta.write(
                    i, tf.reduce_sum(x) * tf.cast(i, tf.float32))

            _, ta = tf.while_loop(lambda i, ta: i < 3, body,
                                  (tf.constant(0), ta0))
            return ta.stack()

        gd, frozen = _freeze(f, tf.TensorSpec((2,), tf.float32))
        xv = np.float32([1.5, 2.5])
        want = np.asarray(frozen(tf.constant(xv)))
        imp = TensorflowFrameworkImporter.run_import(gd, {"x": (2,)})
        got = imp.output({"x": xv}, [_out(imp)])[_out(imp)]
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_while_accumulator_vector(self):
        def f(x):
            ta0 = tf.TensorArray(tf.float32, size=4)

            def body(i, ta):
                return i + 1, ta.write(i, x * tf.cast(i, tf.float32))

            _, ta = tf.while_loop(lambda i, ta: i < 4, body,
                                  (tf.constant(0), ta0))
            return ta.stack()

        gd, frozen = _freeze(f, tf.TensorSpec((3,), tf.float32))
        xv = np.float32([1.0, -2.0, 0.5])
        want = np.asarray(frozen(tf.constant(xv)))
        imp = TensorflowFrameworkImporter.run_import(gd, {"x": (3,)})
        got = imp.output({"x": xv}, [_out(imp)])[_out(imp)]
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_read_back_inside_loop(self):
        """write + read in the same loop (GetItem through the carried
        handle)."""
        def f(x):
            ta0 = tf.TensorArray(tf.float32, size=4,
                                 clear_after_read=False)
            ta0 = ta0.write(0, tf.reduce_sum(x))

            def body(i, ta):
                prev = ta.read(i - 1)
                return i + 1, ta.write(i, prev * 2.0)

            _, ta = tf.while_loop(lambda i, ta: i < 4, body,
                                  (tf.constant(1), ta0))
            return ta.stack()

        gd, frozen = _freeze(f, tf.TensorSpec((2,), tf.float32))
        xv = np.float32([0.5, 1.0])
        want = np.asarray(frozen(tf.constant(xv)))
        imp = TensorflowFrameworkImporter.run_import(gd, {"x": (2,)})
        got = imp.output({"x": xv}, [_out(imp)])[_out(imp)]
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_gradient_through_accumulator(self):
        """Gradients flow through the dense SetItem accumulator in the
        bounded-while lowering — vs tf.GradientTape ground truth."""
        w0 = np.float32([1.2, 0.8])

        def loop_fn(w):
            ta0 = tf.TensorArray(tf.float32, size=3)

            def body(i, ta):
                return i + 1, ta.write(
                    i, tf.reduce_sum(w) ** tf.cast(i + 1, tf.float32))

            _, ta = tf.while_loop(lambda i, ta: i < 3, body,
                                  (tf.constant(0), ta0))
            return tf.reduce_sum(ta.stack())

        with tf.GradientTape() as tape:
            wt = tf.Variable(w0)
            loss = loop_fn(wt)
        want_grad = np.asarray(tape.gradient(loss, wt))

        gd, frozen = _freeze(loop_fn, tf.TensorSpec((2,), tf.float32))
        imp = TensorflowFrameworkImporter.run_import(
            gd, {"w": (2,)}, while_max_iterations=8)
        out = _out(imp)
        got_loss = float(imp.output({"w": w0}, [out])[out])
        assert got_loss == pytest.approx(float(frozen(
            tf.constant(w0))), rel=1e-5)
        imp.convert_to_variables(["w"], {"w": w0})
        imp.set_loss_variables([out])
        got = imp.calculate_gradients({}, ["w"])["w"]
        np.testing.assert_allclose(got, want_grad, rtol=1e-4)

    def test_dynamic_size_fails_loudly(self):
        """PushBack-style (dynamic size) lists have no static-shape
        lowering and must fail with the TensorList message, not import
        silently wrong."""
        def f(x):
            ta0 = tf.TensorArray(tf.float32, size=0,
                                 dynamic_size=True)

            def body(i, ta):
                return i + 1, ta.write(i, x[0] * tf.cast(
                    i, tf.float32))

            _, ta = tf.while_loop(lambda i, ta: i < 3, body,
                                  (tf.constant(0), ta0))
            return ta.stack()

        gd, _ = _freeze(f, tf.TensorSpec((2,), tf.float32))
        with pytest.raises(NotImplementedError,
                           match="TensorList|no mapping"):
            TensorflowFrameworkImporter.run_import(gd, {"x": (2,)})
