"""Pipeline-parallelism tests (SURVEY.md §2.6 P8 — TPU-native
extension). The pipelined stack must equal running the stages
sequentially on one device, forward and backward."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel import make_mesh
from deeplearning4j_tpu.parallel.pipeline import (
    from_microbatches, pipeline_apply, pipeline_loss, to_microbatches)
from deeplearning4j_tpu.parallel.mesh import shard_map as _shard_map

B, T, D = 8, 4, 16
N_STAGES = 4
N_MICRO = 4


def _stage_weights(stage: int):
    rng = np.random.RandomState(100 + stage)
    return {"w": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.3),
            "b": jnp.asarray(rng.randn(D).astype(np.float32) * 0.1)}


def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def seq_ref(x):
    for s in range(N_STAGES):
        x = stage_fn(_stage_weights(s), x)
    return x


def _stacked_params():
    """[n_stages, ...] stacked stage weights, shard-mapped over pipe."""
    ws = [_stage_weights(s) for s in range(N_STAGES)]
    return jax.tree_util.tree_map(lambda *a: jnp.stack(a), *ws)


def _x(seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(B, T, D).astype(np.float32))


def _pipe(fn):
    mesh = make_mesh({"pipe": N_STAGES}, jax.devices()[:N_STAGES])
    return _shard_map(fn, mesh,
                      in_specs=(P("pipe"), P()),
                      out_specs=P())


class TestPipeline:
    def test_forward_matches_sequential(self):
        x = _x()
        xm = to_microbatches(x, N_MICRO)

        def run(sp, xm):
            # sp arrives as [1, ...] slice of the stacked stage params
            sp = jax.tree_util.tree_map(lambda a: a[0], sp)
            outs = pipeline_apply(stage_fn, sp, xm)
            # outputs valid on last stage only; broadcast via psum
            from deeplearning4j_tpu.parallel.pipeline import \
                last_stage_only
            return last_stage_only(outs, "pipe")

        outs = _pipe(run)(_stacked_params(), xm)
        np.testing.assert_allclose(np.asarray(from_microbatches(outs)),
                                   np.asarray(seq_ref(x)), atol=1e-5)

    @pytest.mark.parametrize("remat", [False, True])
    def test_loss_and_grad_match(self, remat):
        x = _x(1)
        y = _x(2)
        xm, ym = to_microbatches(x, N_MICRO), to_microbatches(y, N_MICRO)
        sp = _stacked_params()

        def loss_pipe(sp, xm, ym):
            def run(sp_slice, xm, ym):
                local = jax.tree_util.tree_map(lambda a: a[0], sp_slice)
                return pipeline_loss(
                    stage_fn, lambda o, t: jnp.mean((o - t) ** 2),
                    local, xm, ym, remat=remat)
            mesh = make_mesh({"pipe": N_STAGES},
                             jax.devices()[:N_STAGES])
            return _shard_map(run, mesh,
                              in_specs=(P("pipe"), P(), P()),
                              out_specs=P())(sp, xm, ym)

        def loss_ref(sp, x, y):
            out = x
            for s in range(N_STAGES):
                local = jax.tree_util.tree_map(lambda a: a[s], sp)
                out = stage_fn(local, out)
            return jnp.mean((out - y) ** 2)

        lp = loss_pipe(sp, xm, ym)
        lr = loss_ref(sp, x, y)
        np.testing.assert_allclose(float(lp), float(lr), atol=1e-6)

        gp = jax.grad(loss_pipe)(sp, xm, ym)
        gr = jax.grad(loss_ref)(sp, x, y)
        for a, b in zip(jax.tree_util.tree_leaves(gp),
                        jax.tree_util.tree_leaves(gr)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-4)
