"""SameDiff control flow (SURVEY.md S3 / Appendix A: while/cond/
switch/merge) lowering to lax.while_loop / lax.cond / lax.scan."""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.samediff import SameDiff


def io_bytes(b):
    import io
    return io.BytesIO(b)


class TestControlFlowSerialization:
    """sd.save/load round-trips graphs containing control-flow ops:
    subgraph closures serialize as graph specs and rebuild on load
    (reference: SameDiff FlatBuffers serialization carries loop/branch
    subgraphs, SURVEY.md S5)."""

    def test_while_loop_roundtrip(self, tmp_path):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(4,))
        out = sd.while_loop(
            [x],
            lambda v: v.sd._op("lt",
                               [v.sd._op("reduce_sum", [v]),
                                v.sd.constant(np.float32(100.0))]),
            lambda v: v.sd._op("mul",
                               [v, v.sd.constant(np.float32(2.0))]))
        out = out.rename("res")
        feed = {"x": np.ones(4, np.float32)}
        want = sd.output(feed, ["res"])["res"]
        p = str(tmp_path / "wl.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        got = sd2.output(feed, ["res"])["res"]
        np.testing.assert_allclose(got, want)

    def test_cond_with_capture_roundtrip(self, tmp_path):
        sd = SameDiff()
        flag = sd.placeholder("flag", shape=())
        x = sd.placeholder("x", shape=(3,))
        w = sd.var("w", array=np.asarray([2., 2., 2.], np.float32))
        out = sd.cond(
            flag,
            lambda v: v.sd._op("mul", [v, w]),     # captures parent var
            lambda v: v.sd._op("add", [v, w]),
            operands=[x]).rename("res")
        feed = {"flag": np.asarray(True),
                "x": np.asarray([1., 2., 3.], np.float32)}
        want = sd.output(feed, ["res"])["res"]
        p = str(tmp_path / "cond.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        got = sd2.output(feed, ["res"])["res"]
        np.testing.assert_allclose(got, want)
        feed["flag"] = np.asarray(False)
        np.testing.assert_allclose(sd2.output(feed, ["res"])["res"],
                                   sd.output(feed, ["res"])["res"])

    def test_large_capture_stays_binary(self, tmp_path):
        """Captured weights serialize into arrays.npz, not graph.json
        (regression: tolist() ballooned the JSON)."""
        import json as _json
        import zipfile
        sd = SameDiff()
        x = sd.placeholder("x", shape=(256,))
        big = np.arange(256 * 256, dtype=np.float32).reshape(256, 256) \
            / (256 * 256)
        out = sd.cond(
            sd.constant(np.asarray(True)),
            # child-local constant: serializes with the subgraph spec
            lambda v: v.sd._op("mmul", [v.sd.constant(big), v]),
            lambda v: v,
            operands=[x]).rename("res")
        p = str(tmp_path / "big.sdz")
        sd.save(p)
        with zipfile.ZipFile(p) as z:
            gj = z.read("graph.json")
            assert len(gj) < 64_000, len(gj)    # 256KB weight NOT inline
            names = np.load(io_bytes(z.read("arrays.npz"))).files
            assert any("/" in n for n in names)  # cf-prefixed entries
        sd2 = SameDiff.load(p)
        feed = {"x": np.ones(256, np.float32)}
        np.testing.assert_allclose(sd2.output(feed, ["res"])["res"],
                                   sd.output(feed, ["res"])["res"])

    def test_scan_roundtrip(self, tmp_path):
        sd = SameDiff()
        xs = sd.placeholder("xs", shape=(5,))
        c0 = sd.constant("c0", np.float32(0.0))
        outs = sd.scan(
            lambda c, x: [c.sd._op("add", [c, x])], [c0], xs=[xs])
        res = (outs[0] if isinstance(outs, (list, tuple)) else
               outs).rename("final")
        feed = {"xs": np.arange(5, dtype=np.float32)}
        want = sd.output(feed, ["final"])["final"]
        p = str(tmp_path / "scan.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        np.testing.assert_allclose(sd2.output(feed, ["final"])["final"],
                                   want)


class TestWhileLoop:
    def test_iterative_doubling(self):
        """double x until its sum exceeds 100 (data-dependent trip
        count — the thing static graphs can't unroll)."""
        sd = SameDiff()
        x = sd.placeholder("x", shape=(4,))

        out = sd.while_loop(
            [x],
            lambda v: v.sd._op("lt",
                               [v.sd._op("reduce_sum", [v]),
                                v.sd.constant(np.float32(100.0))]),
            lambda v: v.sd._op("mul",
                               [v, v.sd.constant(np.float32(2.0))]))
        res = sd.output({"x": np.ones(4, np.float32)}, [out])
        got = res[out.name]
        # 4 -> 8 -> 16 -> 32 -> 64 -> 128 (stops when sum >= 100)
        np.testing.assert_allclose(got, np.full(4, 32.0))

    def test_multi_var(self):
        sd = SameDiff()
        i0 = sd.constant("i0", np.int32(0))
        acc0 = sd.constant("acc0", np.float32(0.0))

        outs = sd.while_loop(
            [i0, acc0],
            lambda i, a: i.sd._op("lt",
                                  [i, i.sd.constant(np.int32(5))]),
            lambda i, a: [
                i.sd._op("add", [i, i.sd.constant(np.int32(1))]),
                a.sd._op("add", [a, a.sd._op(
                    "cast", [i], {"dtype": "float32"})])])
        res = sd.output({}, list(outs))
        assert res[outs[0].name] == 5
        assert res[outs[1].name] == 0 + 1 + 2 + 3 + 4


class TestCond:
    @pytest.mark.parametrize("flag,want", [(1.0, 9.0), (0.0, -3.0)])
    def test_branches(self, flag, want):
        sd = SameDiff()
        p = sd.placeholder("p", shape=())
        x = sd.placeholder("x", shape=())
        out = sd.cond(
            p,
            lambda v: v.sd._op("mul",
                               [v, v.sd.constant(np.float32(3.0))]),
            lambda v: v.sd._op("neg", [v]),
            operands=[x])
        res = sd.output({"p": np.float32(flag),
                         "x": np.float32(3.0)}, [out])
        assert float(res[out.name]) == want


class TestScan:
    def test_cumsum(self):
        sd = SameDiff()
        xs = sd.placeholder("xs", shape=(6,))
        c0 = sd.constant("c0", np.float32(0.0))

        outs = sd.scan(
            lambda c, x: [c.sd._op("add", [c, x]),
                          c.sd._op("add", [c, x])],
            init=[c0], xs=[xs])
        data = np.arange(1, 7, dtype=np.float32)
        res = sd.output({"xs": data}, list(outs))
        assert float(res[outs[0].name]) == data.sum()
        np.testing.assert_allclose(res[outs[1].name],
                                   np.cumsum(data))

    def test_linear_rnn_unroll(self):
        """A tiny recurrent cell as a scan: h' = tanh(h W + x)."""
        rng = np.random.RandomState(0)
        W = rng.randn(3, 3).astype(np.float32) * 0.5
        xs_np = rng.randn(5, 3).astype(np.float32)

        sd = SameDiff()
        xs = sd.placeholder("xs", shape=(5, 3))
        h0 = sd.constant("h0", np.zeros(3, np.float32))
        Wc = sd.constant("W", W)

        def cell(h, x):
            z = h.sd._op("add", [h.sd._op("matmul", [h, Wc]), x])
            hn = h.sd._op("tanh", [z])
            return [hn, hn]

        outs = sd.scan(cell, init=[h0], xs=[xs])
        res = sd.output({"xs": xs_np}, list(outs))

        h = np.zeros(3, np.float32)
        hist = []
        for t in range(5):
            h = np.tanh(h @ W + xs_np[t])
            hist.append(h)
        np.testing.assert_allclose(res[outs[0].name], h, atol=1e-5)
        np.testing.assert_allclose(res[outs[1].name],
                                   np.stack(hist), atol=1e-5)


class TestCaptures:
    def test_parent_capture_no_name_shadowing(self):
        """A body that closes over a parent constant AND makes its own
        same-auto-named constant must keep them distinct (regression:
        child 'const' used to shadow parent 'const')."""
        sd = SameDiff()
        outer = sd.constant(np.float32(2.0))     # auto-named 'const'
        x = sd.placeholder("x", shape=())

        def branch(v):
            inner = v.sd.constant(np.float32(5.0))  # child 'const'
            return v.sd._op("add",
                            [v.sd._op("mul", [v, outer]), inner])

        out = sd.cond(sd.constant(np.float32(1.0)), branch,
                      lambda v: v, operands=[x])
        res = sd.output({"x": np.float32(3.0)}, [out])
        assert float(res[out.name]) == 3.0 * 2.0 + 5.0

    def test_captured_placeholder_is_live(self):
        """Captures thread through op inputs, so a captured parent
        PLACEHOLDER reads the per-call fed value."""
        sd = SameDiff()
        limit = sd.placeholder("limit", shape=())
        c0 = sd.constant(np.float32(0.0))
        out = sd.while_loop(
            [c0],
            lambda v: v.sd._op("lt", [v, limit]),
            lambda v: v.sd._op("add",
                               [v, v.sd.constant(np.float32(1.0))]))
        for lim in (3.0, 7.0):
            r = sd.output({"limit": np.float32(lim)}, [out])
            assert float(r[out.name]) == lim

    def test_captured_variable_trains(self):
        """A trainable VARIABLE captured by a cond body must receive
        gradients (regression: captures used to be frozen at trace
        time, silently zeroing their grads)."""
        from deeplearning4j_tpu.autodiff.training import TrainingConfig
        from deeplearning4j_tpu.learning import Sgd
        sd = SameDiff()
        x = sd.placeholder("x", shape=(8, 1))
        y = sd.placeholder("y", shape=(8, 1))
        w = sd.var("w", array=np.zeros((1, 1), np.float32))
        out = sd.cond(sd.constant(np.float32(1.0)),
                      lambda v: v.sd._op("matmul", [v, w]),
                      lambda v: v, operands=[x])
        sd._op("mean_squared_error", [y, out], name="loss")
        sd.set_loss_variables(["loss"])
        sd.set_training_config(TrainingConfig(
            updater=Sgd(0.2),
            data_set_feature_mapping=["x"],
            data_set_label_mapping=["y"]))
        rng = np.random.RandomState(0)
        X = rng.randn(8, 1).astype(np.float32)
        Y = 3.0 * X

        class It:
            def reset(self):
                pass

            def __iter__(self):
                batch = type("B", (), {"features": [X],
                                       "labels": [Y]})()
                return iter([batch])

        sd.fit(It(), n_epochs=20)
        wv = float(np.asarray(sd._arrays["w"]).squeeze())
        assert abs(wv - 3.0) < 0.2, wv


class TestSwitchMerge:
    def test_tf_style_switch_merge(self):
        """switch -> per-branch ops -> merge(false, true, pred):
        both branches computed, merge selects. Branch ops need NOT be
        zero-preserving (the +10 below would corrupt a sum-merge)."""
        sd = SameDiff()
        x = sd.placeholder("x", shape=(3,))
        p = sd.placeholder("p", shape=())
        f_branch, t_branch = sd._op("switch", [x, p], n_out=2)
        t_out = sd._op("mul", [t_branch,
                               sd.constant(np.float32(2.0))])
        f_out = sd._op("add", [f_branch,
                               sd.constant(np.float32(10.0))])
        merged = sd._op("merge", [f_out, t_out, p])
        v = np.asarray([1.0, 2.0, 3.0], np.float32)
        r1 = sd.output({"x": v, "p": np.float32(1.0)}, [merged])
        np.testing.assert_allclose(r1[merged.name], v * 2)
        r0 = sd.output({"x": v, "p": np.float32(0.0)}, [merged])
        np.testing.assert_allclose(r0[merged.name], v + 10)

    def test_scan_length_only(self):
        """xs-less scan: fixed-trip loop driven by `length`."""
        sd = SameDiff()
        c0 = sd.constant("c0", np.float32(1.0))
        outs = sd.scan(
            lambda c: [c.sd._op("mul",
                                [c, c.sd.constant(np.float32(2.0))]),
                       c],
            init=[c0], xs=(), length=5)
        res = sd.output({}, list(outs))
        assert float(res[outs[0].name]) == 32.0
        np.testing.assert_allclose(res[outs[1].name],
                                   [1, 2, 4, 8, 16])
