"""Checkpoint-restart fault tolerance (SURVEY.md §5.3/§5.4: resumable
jobs are the elasticity guarantee; reference test style:
TestCheckpointListener)."""
import os
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.utils import (CheckpointListener,
                                      FaultTolerantTrainer)


def _factory():
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.RELU))
            .layer(OutputLayer(n_out=2,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    return x, y


class TestCheckpointAccessors:
    def test_available_and_last(self, tmp_path):
        net = _factory()
        x, y = _data()
        lis = CheckpointListener(tmp_path, save_every_n_iterations=2)
        net.set_listeners(lis)
        for _ in range(6):
            net.fit(x, y)
        lis.flush()         # async default: join the background write
        cps = CheckpointListener.available_checkpoints(tmp_path)
        assert len(cps) == 3
        assert CheckpointListener.last_checkpoint_in(tmp_path) == cps[-1]
        restored = CheckpointListener.load_checkpoint(tmp_path)
        assert restored.iteration_count == 6

    def test_corrupt_newest_falls_back(self, tmp_path):
        net = _factory()
        x, y = _data()
        lis = CheckpointListener(tmp_path, save_every_n_iterations=2)
        net.set_listeners(lis)
        for _ in range(4):
            net.fit(x, y)
        lis.flush()
        cps = CheckpointListener.available_checkpoints(tmp_path)
        assert len(cps) == 2
        # simulate crash-truncated newest checkpoint
        with open(cps[-1], "r+b") as f:
            f.truncate(100)
        restored = CheckpointListener.load_checkpoint(tmp_path)
        assert restored.iteration_count == 2   # fell back to older
        with pytest.raises(Exception):
            CheckpointListener.load_checkpoint(cps[-1],
                                               skip_corrupt=False)


class TestFaultTolerantTrainer:
    def test_resume_continues_counters_and_params(self, tmp_path):
        x, y = _data(64)

        class OneEpoch:
            """8-batch iterator."""
            def __init__(self):
                self._i = 0
            def reset(self):
                self._i = 0
            def __iter__(self):
                from deeplearning4j_tpu.datasets.dataset import DataSet
                for i in range(8):
                    yield DataSet(x[i * 8:(i + 1) * 8],
                                  y[i * 8:(i + 1) * 8])

        t1 = FaultTolerantTrainer(_factory, tmp_path,
                                  save_every_n_epochs=1)
        assert not t1.resumed
        t1.fit(OneEpoch(), n_epochs=2)
        it1 = t1.model.iteration_count
        assert it1 == 16

        # "restart the job": new trainer on the same dir resumes
        t2 = FaultTolerantTrainer(_factory, tmp_path,
                                  save_every_n_epochs=1)
        assert t2.resumed
        assert t2.model.iteration_count == it1
        w1 = np.asarray(t1.model.params["layer_0"]["W"])
        w2 = np.asarray(t2.model.params["layer_0"]["W"])
        np.testing.assert_array_equal(w1, w2)
        # n_epochs is the TOTAL target: re-running the crashed job's
        # fit(n_epochs=2) does nothing; asking for 3 runs ONE more
        t2.fit(OneEpoch(), n_epochs=2)
        assert t2.model.iteration_count == it1       # already done
        t2.fit(OneEpoch(), n_epochs=3)
        assert t2.model.iteration_count == 24

    def test_crash_before_final_save_does_not_retrain(self, tmp_path):
        """Epoch-end checkpoints carry the TRUE epochs-completed count:
        even without fit()'s final save, resume must not rerun a
        finished epoch (regression: listener fired before epoch_count
        incremented, persisting a stale count)."""
        x, y = _data()
        t1 = FaultTolerantTrainer(_factory, tmp_path,
                                  save_every_n_epochs=1)
        t1.fit([_ds(x, y)], n_epochs=2)
        it_done = t1.model.iteration_count
        # simulate a crash right after the last epoch-end save (fit's
        # final save was deduplicated against it, so the newest file IS
        # the epoch-end save): keep only it, then "re-run the job"
        cps = CheckpointListener.available_checkpoints(tmp_path)
        epoch_end_cp = cps[-1]
        for p in cps[:-1]:
            p.unlink()
        restored = CheckpointListener.load_checkpoint(epoch_end_cp)
        assert restored.epoch_count == 2       # true epochs completed
        t2 = FaultTolerantTrainer(_factory, tmp_path,
                                  save_every_n_epochs=1)
        t2.fit([_ds(x, y)], n_epochs=2)        # identical re-run
        assert t2.model.iteration_count == it_done   # nothing retrained

    def test_checkpoint_numbering_continues(self, tmp_path):
        x, y = _data()
        t1 = FaultTolerantTrainer(_factory, tmp_path,
                                  save_every_n_epochs=1)
        t1.fit([_ds(x, y)], n_epochs=1)
        names1 = {p.name for p in
                  CheckpointListener.available_checkpoints(tmp_path)}
        t2 = FaultTolerantTrainer(_factory, tmp_path,
                                  save_every_n_epochs=1)
        t2.fit([_ds(x, y)], n_epochs=2)
        names2 = {p.name for p in
                  CheckpointListener.available_checkpoints(tmp_path)}
        # numbering continues upward (no clobbering); rotation may trim
        # the oldest files
        def top(names):
            return max(int(n.split("_")[1].split(".")[0])
                       for n in names)
        assert top(names2) > top(names1)
        assert names2 - names1      # genuinely new checkpoints exist


def _ds(x, y):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    return DataSet(x, y)


class TestAsyncCheckpointing:
    """Round-3 verdict ask #5: the step loop must not block on
    serialize+write — _save snapshots device->host and a background
    thread does the IO; flush() joins it."""

    def test_async_snapshot_is_consistent_under_further_training(
            self, tmp_path):
        """The checkpoint must hold the state AT SAVE TIME even though
        training keeps mutating the live model while the background
        thread serializes."""
        net = _factory()
        x, y = _data()
        for _ in range(3):
            net.fit(x, y)
        lis = CheckpointListener(tmp_path, asynchronous=True)
        import jax as _jax
        at_save = [np.asarray(v) for v in
                   _jax.tree_util.tree_leaves(_jax.device_get(
                       net.params))]
        it_at_save = net.iteration_count
        lis._save(net)
        for _ in range(5):          # keep training during the write
            net.fit(x, y)
        lis.flush()
        restored = CheckpointListener.load_checkpoint(tmp_path)
        assert restored.iteration_count == it_at_save
        got = [np.asarray(v) for v in
               _jax.tree_util.tree_leaves(restored.params)]
        for a, b in zip(got, at_save):
            np.testing.assert_array_equal(a, b)

    def test_async_equals_sync_bytes_semantics(self, tmp_path):
        net = _factory()
        x, y = _data()
        net.fit(x, y)
        sync_dir, async_dir = tmp_path / "s", tmp_path / "a"
        ls = CheckpointListener(sync_dir, asynchronous=False)
        la = CheckpointListener(async_dir, asynchronous=True)
        ls._save(net)
        la._save(net)
        la.flush()
        rs = CheckpointListener.load_checkpoint(sync_dir)
        ra = CheckpointListener.load_checkpoint(async_dir)
        import jax as _jax
        for a, b in zip(_jax.tree_util.tree_leaves(rs.params),
                        _jax.tree_util.tree_leaves(ra.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert rs.iteration_count == ra.iteration_count

    def test_rotation_works_async(self, tmp_path):
        net = _factory()
        x, y = _data()
        lis = CheckpointListener(tmp_path, save_every_n_iterations=1,
                                 keep_last=2, asynchronous=True)
        net.set_listeners(lis)
        for _ in range(5):
            net.fit(x, y)
        lis.flush()
        assert len(CheckpointListener.available_checkpoints(
            tmp_path)) == 2

    def test_flush_propagates_write_errors(self, tmp_path):
        net = _factory()
        x, y = _data()
        net.fit(x, y)
        lis = CheckpointListener(tmp_path / "d", asynchronous=True)
        import shutil
        lis._save(net)
        lis.flush()
        # break the target dir, then save again: the error must not
        # vanish into the background thread
        shutil.rmtree(tmp_path / "d")
        (tmp_path / "d").write_text("not a dir")
        lis._save(net)
        with pytest.raises(Exception):
            lis.flush()


class TestSameDiffCheckpointRestore:
    """load_checkpoint must dispatch on the zip format: SameDiff
    checkpoints (graph.json entry, the r5 CheckpointListener write
    path) load via SameDiff.load, not ModelSerializer (ADVICE.md)."""

    def _toy_sd(self):
        from deeplearning4j_tpu.autodiff import SameDiff
        from deeplearning4j_tpu.autodiff.training import TrainingConfig
        from deeplearning4j_tpu.learning.updaters import Sgd as SdSgd
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 2))
        y = sd.placeholder("y", shape=(None, 1))
        w = sd.var("w", array=np.zeros((2, 1), np.float32))
        sd.loss.mean_squared_error(y, x @ w, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(
            TrainingConfig.Builder().updater(SdSgd(0.1))
            .data_set_feature_mapping("x")
            .data_set_label_mapping("y").build())
        return sd

    def test_load_checkpoint_dispatches_samediff_zip(self, tmp_path):
        from deeplearning4j_tpu.autodiff import SameDiff
        from deeplearning4j_tpu.datasets.iterators import \
            ListDataSetIterator
        sd = self._toy_sd()
        rng = np.random.RandomState(0)
        x = rng.randn(8, 2).astype(np.float32)
        ds = _ds(x, (x @ np.array([[1.], [2.]],
                                  np.float32)).astype(np.float32))
        sd.fit(ListDataSetIterator([ds]), n_epochs=2)
        lis = CheckpointListener(tmp_path, asynchronous=False)
        lis._save(sd)
        cp = lis.last_checkpoint()
        restored = CheckpointListener.load_checkpoint(cp)
        assert isinstance(restored, SameDiff)
        assert restored.epoch_count == sd.epoch_count
        assert restored.iteration_count == sd.iteration_count
        np.testing.assert_array_equal(
            np.asarray(restored._arrays["w"]),
            np.asarray(sd._arrays["w"]))


class TestTornNewestFallback:
    def test_trainer_falls_back_past_torn_newest(self, tmp_path):
        """ISSUE 11 satellite: a truncated newest checkpoint must be
        skipped with a warning and resume continue from the older one
        (epoch-granular: the torn file's sidecar no longer matches)."""
        x, y = _data()
        t1 = FaultTolerantTrainer(_factory, tmp_path,
                                  save_every_n_epochs=1)
        t1.fit([_ds(x, y)], n_epochs=3)
        cps = CheckpointListener.available_checkpoints(tmp_path)
        assert len(cps) >= 2
        with open(cps[-1], "r+b") as f:      # tear the newest
            f.truncate(64)
        t2 = FaultTolerantTrainer(_factory, tmp_path,
                                  save_every_n_epochs=1)
        assert t2.resumed
        assert t2.model.epoch_count < 3      # fell back to an older one
        t2.fit([_ds(x, y)], n_epochs=3)      # and still reaches target
        assert t2.model.epoch_count == 3
        assert t2.model.iteration_count == 3

    def test_all_checkpoints_torn_starts_fresh(self, tmp_path):
        x, y = _data()
        t1 = FaultTolerantTrainer(_factory, tmp_path,
                                  save_every_n_epochs=1)
        t1.fit([_ds(x, y)], n_epochs=1)
        for cp in CheckpointListener.available_checkpoints(tmp_path):
            with open(cp, "r+b") as f:
                f.truncate(16)
        t2 = FaultTolerantTrainer(_factory, tmp_path)
        assert not t2.resumed                # nothing loadable
        t2.fit([_ds(x, y)], n_epochs=1)
        assert t2.model.epoch_count == 1


class TestSameDiffFaultTolerance:
    """ISSUE 11 satellite: FaultTolerantTrainer must resume SameDiff
    models from their zip format (graph.json carries iteration/epoch
    counts and the training config — whole-epoch granularity)."""

    def _sd_factory(self):
        from deeplearning4j_tpu.autodiff import SameDiff
        from deeplearning4j_tpu.autodiff.training import TrainingConfig
        from deeplearning4j_tpu.learning.updaters import Adam as SdAdam
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 2))
        y = sd.placeholder("y", shape=(None, 1))
        w = sd.var("w", array=np.zeros((2, 1), np.float32))
        sd.loss.mean_squared_error(y, x @ w, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(
            TrainingConfig.Builder().updater(SdAdam(0.1))
            .data_set_feature_mapping("x")
            .data_set_label_mapping("y").build())
        return sd

    def _sd_iter(self):
        from deeplearning4j_tpu.datasets.iterators import \
            ListDataSetIterator
        rng = np.random.RandomState(0)
        x = rng.randn(16, 2).astype(np.float32)
        t = (x @ np.array([[1.], [2.]], np.float32)).astype(np.float32)
        return ListDataSetIterator([_ds(x[:8], t[:8]),
                                    _ds(x[8:], t[8:])])

    def test_samediff_resume_continues(self, tmp_path):
        t1 = FaultTolerantTrainer(self._sd_factory, tmp_path,
                                  save_every_n_epochs=1)
        t1.fit(self._sd_iter(), n_epochs=2)
        it1 = t1.model.iteration_count
        assert t1.model.epoch_count == 2
        assert it1 == 4
        w1 = np.asarray(t1.model._arrays["w"])

        t2 = FaultTolerantTrainer(self._sd_factory, tmp_path,
                                  save_every_n_epochs=1)
        assert t2.resumed
        assert t2.model.epoch_count == 2
        assert t2.model.iteration_count == it1
        np.testing.assert_array_equal(
            np.asarray(t2.model._arrays["w"]), w1)
        # TOTAL-epoch semantics hold for the SameDiff path too
        t2.fit(self._sd_iter(), n_epochs=2)      # already done: no-op
        assert t2.model.iteration_count == it1
        t2.fit(self._sd_iter(), n_epochs=3)      # one more epoch
        assert t2.model.epoch_count == 3
        assert t2.model.iteration_count == 6
