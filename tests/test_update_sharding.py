"""ZeRO-1 cross-replica sharded weight update (parallel.zero) on the
virtual 8-device CPU mesh (ISSUE 5).

Covers: flat ravel/unravel padding round-trip, update-tail bitwise
equivalence (Sgd) / float tolerance (Adam family), end-to-end sharded
vs dense trainer parity, gradient accumulation = one big-batch step,
checkpoint round-trip of sharded updater state, the env kill switch,
training_mode validation, and the new telemetry surfaces.
"""
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning.updaters import (Adam, Nesterovs, Sgd,
                                                  dp_ravel, dp_unravel,
                                                  is_dp_sharded)
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.parallel import ParallelWrapper, UpdateExchange
from deeplearning4j_tpu.parallel.mesh import MeshFactory
from deeplearning4j_tpu.parallel.zero import (apply_update_sharded,
                                              resolve_update_exchange,
                                              states_to_dense,
                                              to_sharded_state,
                                              update_exchange_bytes)


def _mlp(updater=None, seed=42):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(updater or Sgd(0.1))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return DataSet(x, y)


def _assert_tree_close(a, b, **kw):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


# -- flat layout -----------------------------------------------------------
def test_dp_ravel_unravel_odd_sizes_roundtrip():
    """Leaves whose total count is NOT a multiple of the shard count
    pad with zeros and unravel back bitwise (the output layer here has
    51 params -> padded to 56 for 8 shards)."""
    rng = np.random.default_rng(0)
    tree = {"W": jnp.asarray(rng.normal(size=(16, 3)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(3,)), jnp.float32)}
    flats, spec = dp_ravel(tree, 8)
    (orig, padded), = spec.sizes.values()
    assert orig == 51 and padded == 56 and padded % 8 == 0
    flat = next(iter(flats.values()))
    assert flat.shape == (56,)
    np.testing.assert_array_equal(np.asarray(flat[51:]), np.zeros(5))
    back = dp_unravel(flats, spec)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(tree[k]),
                                      np.asarray(back[k]))


def test_update_exchange_bytes_ring_formula():
    params = {"W": jnp.zeros((10, 10), jnp.float32)}   # 400 bytes
    assert update_exchange_bytes(params, 1) == 0
    assert update_exchange_bytes(params, 8) == int(2 * 7 * 400 / 8)


# -- the update tail, isolated ---------------------------------------------
def test_update_tail_sgd_bitwise_adam_tolerance():
    """Same summed gradient in -> the sharded tail's per-element math
    is the dense updater's: bitwise for Sgd (ISSUE 5 acceptance),
    float tolerance for Adam (f32 fusion ordering)."""
    mesh = MeshFactory.data_parallel()
    rng = np.random.default_rng(0)
    params = {"W": jnp.asarray(rng.normal(size=(8, 16)), jnp.float32),
              "b": jnp.asarray(rng.normal(size=(17,)), jnp.float32)}
    grads = {"W": jnp.asarray(rng.normal(size=(8, 16)), jnp.float32),
             "b": jnp.asarray(rng.normal(size=(17,)), jnp.float32)}
    for upd, exact in ((Sgd(0.1), True), (Adam(0.01), False)):
        state = upd.init_state(params)
        u, _ = upd.apply(grads, state, jnp.asarray(0))
        dense_new = {k: params[k] - u[k] for k in params}
        sh_state = to_sharded_state(params, state, mesh.shape["data"])
        f = jax.jit(lambda p, g, s: apply_update_sharded(
            upd, g, p, s, jnp.asarray(0), mesh))
        new_p, new_s = f(params, grads, sh_state)
        for k in params:
            a, b = np.asarray(dense_new[k]), np.asarray(new_p[k])
            if exact:
                np.testing.assert_array_equal(a, b)
            else:
                np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
        if state:
            assert is_dp_sharded(new_s)
            # state leaves actually live 1/N per device
            for leaf in jax.tree_util.tree_leaves(new_s):
                shards = leaf.addressable_shards
                assert len(shards) == 8
                assert shards[0].data.shape[0] == leaf.shape[0] // 8
        else:
            assert new_s == ()


# -- resolver --------------------------------------------------------------
def test_resolve_update_exchange():
    mesh = MeshFactory.data_parallel()
    assert resolve_update_exchange(mesh) is UpdateExchange.SHARDED
    assert resolve_update_exchange(mesh, requested="dense") \
        is UpdateExchange.DENSE
    assert resolve_update_exchange(None) is UpdateExchange.DENSE
    one = MeshFactory.data_parallel(1)
    assert resolve_update_exchange(one) is UpdateExchange.DENSE
    with pytest.raises(ValueError, match="update_exchange"):
        resolve_update_exchange(mesh, requested="zerO-3")


def test_resolver_falls_back_on_gradient_normalization():
    from deeplearning4j_tpu.nn.conf.builders import GradientNormalization
    mesh = MeshFactory.data_parallel()
    net = _mlp()
    net.conf.gradient_normalization = \
        GradientNormalization.CLIP_L2_PER_LAYER
    assert resolve_update_exchange(mesh, model=net) \
        is UpdateExchange.DENSE


def test_env_kill_switch_restores_dense(monkeypatch):
    """DL4J_TPU_SHARDED_UPDATE=0 forces the dense tail everywhere,
    even when sharded was requested (ISSUE 5 acceptance)."""
    from deeplearning4j_tpu.common.environment import Environment
    mesh = MeshFactory.data_parallel()
    monkeypatch.setenv("DL4J_TPU_SHARDED_UPDATE", "0")
    Environment.reset()
    try:
        assert resolve_update_exchange(mesh) is UpdateExchange.DENSE
        assert resolve_update_exchange(mesh, requested="sharded") \
            is UpdateExchange.DENSE
        net = _mlp(Adam(0.01))
        pw = ParallelWrapper.Builder(net).workers(8) \
            .update_exchange("sharded").build()
        pw.fit_batch(_data(64))
        assert pw.update_exchange is UpdateExchange.DENSE
        assert not any(is_dp_sharded(s)
                       for s in net.updater_states.values())
    finally:
        monkeypatch.delenv("DL4J_TPU_SHARDED_UPDATE")
        Environment.reset()


# -- end-to-end parity -----------------------------------------------------
@pytest.mark.parametrize("updater,rtol,atol", [
    (Sgd(0.1), 1e-6, 1e-7),
    (Nesterovs(0.1, 0.9), 1e-5, 1e-6),
    (Adam(0.01), 1e-5, 1e-6),
], ids=["sgd", "nesterovs", "adam"])
def test_sharded_matches_dense_end_to_end(updater, rtol, atol):
    """Two identically-seeded nets, same batches: the ZeRO-1 exchange
    must land on the dense exchange's parameters."""
    batches = [_data(64, seed=i) for i in range(3)]
    nets, wrappers = {}, {}
    for mode in ("dense", "sharded"):
        net = _mlp(updater, seed=7)
        pw = ParallelWrapper.Builder(net).workers(8) \
            .update_exchange(mode).build()
        for ds in batches:
            pw.fit_batch(ds)
        nets[mode], wrappers[mode] = net, pw
    assert wrappers["dense"].update_exchange is UpdateExchange.DENSE
    assert wrappers["sharded"].update_exchange is UpdateExchange.SHARDED
    _assert_tree_close(nets["dense"].params, nets["sharded"].params,
                       rtol=rtol, atol=atol)
    # the sharded run's state really is in the flat sharded layout
    sharded_states = nets["sharded"].updater_states
    if jax.tree_util.tree_leaves(nets["dense"].updater_states):
        assert any(is_dp_sharded(s) for s in sharded_states.values())
        _assert_tree_close(
            states_to_dense(nets["sharded"].params, sharded_states),
            nets["dense"].updater_states, rtol=rtol, atol=atol)


def test_accumulation_equals_big_batch_sgd():
    """accumulation_steps=2 over two half-batches == one full-batch
    step for SGD (mean gradient; equal micro-batch sizes)."""
    ds = _data(128, seed=3)
    x, y = np.asarray(ds.features), np.asarray(ds.labels)

    big = _mlp(seed=11)
    pw_big = ParallelWrapper.Builder(big).workers(8).build()
    pw_big.fit_batch(DataSet(x, y))

    accum = _mlp(seed=11)
    init = jax.tree_util.tree_map(np.asarray, accum.params)
    pw_acc = ParallelWrapper.Builder(accum).workers(8) \
        .accumulation_steps(2).build()
    pw_acc.fit_batch(DataSet(x[:64], y[:64]))
    # window not full yet: params unchanged
    _assert_tree_close(accum.params, init, rtol=0, atol=0)
    pw_acc.fit_batch(DataSet(x[64:], y[64:]))

    _assert_tree_close(big.params, accum.params, rtol=1e-5, atol=1e-6)
    # the updater saw ONE update, the listener loop saw two micro-steps
    assert accum.iteration_count == 2
    assert accum._updates_applied == 1


def test_accumulation_flushes_partial_window_at_epoch_end():
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    net = _mlp(seed=5)
    pw = ParallelWrapper.Builder(net).workers(8) \
        .accumulation_steps(4).build()
    it = ListDataSetIterator([_data(32, seed=i) for i in range(3)])
    before = jax.tree_util.tree_map(np.asarray, net.params)
    pw.fit(it, n_epochs=1)      # 3 micro-batches < window of 4
    # the partial window was applied at epoch end, params moved
    moved = any(not np.array_equal(a, np.asarray(b))
                for a, b in zip(jax.tree_util.tree_leaves(before),
                                jax.tree_util.tree_leaves(net.params)))
    assert moved
    assert net._accum_count == 0


# -- checkpoint round-trip -------------------------------------------------
def test_checkpoint_roundtrips_sharded_updater_state(tmp_path):
    """A net training with sharded Adam state checkpoints in the DENSE
    layout and resumes anywhere: restored state matches the live
    sharded state converted down, and training continues."""
    from deeplearning4j_tpu.utils import CheckpointListener
    net = _mlp(Adam(0.01), seed=9)
    lis = CheckpointListener(tmp_path, save_every_n_iterations=2)
    net.set_listeners(lis)
    pw = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange("sharded").build()
    for i in range(2):
        pw.fit_batch(_data(64, seed=i))
    lis.flush()
    assert any(is_dp_sharded(s) for s in net.updater_states.values())

    restored = CheckpointListener.load_checkpoint(tmp_path)
    assert restored.iteration_count == 2
    assert not any(is_dp_sharded(s)
                   for s in restored.updater_states.values())
    _assert_tree_close(
        restored.updater_states,
        states_to_dense(net.params, net.updater_states),
        rtol=1e-6, atol=1e-7)
    _assert_tree_close(restored.params, net.params, rtol=1e-6, atol=1e-7)
    # the restored net trains standalone (dense) ...
    restored.fit(_data(64, seed=2))
    # ... and re-enters the sharded exchange cleanly
    pw2 = ParallelWrapper.Builder(restored).workers(8) \
        .update_exchange("sharded").build()
    pw2.fit_batch(_data(64, seed=3))
    assert np.isfinite(restored.score())


# -- builder / telemetry satellites ---------------------------------------
def test_training_mode_accepts_known_warns_unknown(caplog):
    net = _mlp()
    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        b = ParallelWrapper.Builder(net).workers(8) \
            .training_mode("AVERAGING").training_mode("shared_gradients")
        assert not caplog.records
        b.training_mode("GOSSIP_GRADIENTS")
    assert any("GOSSIP_GRADIENTS" in r.getMessage()
               for r in caplog.records)
    with pytest.raises(ValueError):
        ParallelWrapper.Builder(net).update_exchange("bogus")


def test_workers_gauge_and_exchange_counter_and_sparsity_gauge():
    from deeplearning4j_tpu.common import telemetry
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.parallel import (EncodingHandler,
                                             FixedThresholdAlgorithm,
                                             SharedTrainingMaster)
    telemetry.MetricsRegistry._reset_for_tests()
    net = _mlp()
    master = SharedTrainingMaster.Builder().update_exchange("auto").build()
    master.fit(net, ListDataSetIterator([_data(32)]), n_epochs=1)
    # the workers gauge now says WHICH exchange ran
    assert telemetry.gauge("dl4j_dp_workers", "").value(
        master="SharedTrainingMaster", update_exchange="sharded") == 8
    assert telemetry.counter(
        "dl4j_dp_update_exchange_bytes_total", "").value(
            mode="sharded") > 0
    # the once-dead encoding sparsity() helper now feeds a gauge
    h = EncodingHandler(FixedThresholdAlgorithm(0.1))
    h.encode({"W": jnp.asarray([1.0, 0.0, 0.0, 0.0])})
    assert telemetry.gauge("dl4j_dp_encoding_sparsity", "").value() \
        == pytest.approx(0.25)
