"""REAL multi-process distributed training (SURVEY.md §4.7(a): the
reference tests Spark cluster semantics in one JVM via local[N]; the
TPU translation is multiple OS processes forming a jax.distributed
world on one host — gRPC coordinator, gloo CPU collectives, global
mesh). Validates the SharedTrainingMaster cluster path end-to-end:
every process converges to IDENTICAL params, equal to a single-process
run over the concatenated data (exact synchronous DP)."""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_WORKER = textwrap.dedent('''
import sys
import jax
pid, n_proc, port, outdir = (int(sys.argv[1]), int(sys.argv[2]),
                             sys.argv[3], sys.argv[4])
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
# the world must exist before ANY jax computation (model init included)
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n_proc,
                           process_id=pid)

import numpy as np
from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel.sharedtraining import \\
    SharedTrainingMaster

conf = (NeuralNetConfiguration.Builder()
        .seed(7).updater(Sgd(1e-1))
        .list()
        .layer(DenseLayer(n_out=8, activation=Activation.TANH))
        .layer(OutputLayer(n_out=2, loss_function=LossFunction.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(4)).build())
net = MultiLayerNetwork(conf).init()

# process-LOCAL data partition (deterministic per process id)
rng = np.random.RandomState(100 + pid)
batches = [DataSet(rng.randn(8, 4).astype(np.float32),
                   np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)])
           for _ in range(3)]

master = (SharedTrainingMaster.Builder(batch_size_per_worker=4)
          .coordinator(f"127.0.0.1:{port}", n_proc, pid)
          .build())
master.fit(net, batches, n_epochs=2)

leaves = jax.tree_util.tree_leaves(net.params)
np.savez(f"{outdir}/params_{pid}.npz",
         **{f"l{i}": np.asarray(v) for i, v in enumerate(leaves)})
print("WORKER_DONE", pid, flush=True)
import time; time.sleep(2)   # keep coordinator alive for peers
''')


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.parametrize("n_proc", [2, 4])
def test_shared_training_world(tmp_path, n_proc):
    """np=2 AND np=4 (r4 verdict Weak #5: rank arithmetic and barrier
    discipline had only ever run at exactly 2 processes — the
    reference proves the same shape with Spark local[N], N>2)."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(i), str(n_proc), str(port),
         str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(n_proc)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:           # a hung peer must not outlive the test
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert f"WORKER_DONE {i}" in out, \
            f"worker {i} failed:\n{out[-2000:]}"

    # every process holds identical (replicated) params
    a = np.load(tmp_path / "params_0.npz")
    for i in range(1, n_proc):
        b = np.load(tmp_path / f"params_{i}.npz")
        for k in a.files:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-6,
                                       atol=1e-7)

    # and they equal a single-process run over the concatenated data
    # (exact equality needs the reference on the same f32 CPU math the
    # workers used; in real-TPU test mode only replication is checked)
    import jax as _jax
    if _jax.default_backend() != "cpu":
        return
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.learning import Sgd
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    conf = (NeuralNetConfiguration.Builder()
            .seed(7).updater(Sgd(1e-1))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4)).build())
    ref = MultiLayerNetwork(conf).init()
    rngs = [np.random.RandomState(100 + i) for i in range(n_proc)]
    parts = [[DataSet(r.randn(8, 4).astype(np.float32),
                      np.eye(2, dtype=np.float32)[r.randint(0, 2, 8)])
              for _ in range(3)] for r in rngs]
    merged = [DataSet(
        np.concatenate([parts[i][j].features for i in range(n_proc)]),
        np.concatenate([parts[i][j].labels for i in range(n_proc)]))
        for j in range(3)]
    ref.fit(merged, n_epochs=2)
    ref_leaves = [np.asarray(v) for v in
                  _jax.tree_util.tree_leaves(ref.params)]
    for k, want in zip(a.files, ref_leaves):
        np.testing.assert_allclose(a[k], want, rtol=1e-4, atol=1e-5)


_CKPT_WORKER = textwrap.dedent('''
import sys
import jax
pid, n_proc, port, outdir, total_epochs = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    int(sys.argv[5]))
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n_proc,
                           process_id=pid)

import numpy as np
from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel.sharedtraining import \\
    SharedTrainingMaster

conf = (NeuralNetConfiguration.Builder()
        .seed(7).updater(Sgd(1e-1))
        .list()
        .layer(DenseLayer(n_out=8, activation=Activation.TANH))
        .layer(OutputLayer(n_out=2, loss_function=LossFunction.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(4)).build())
net = MultiLayerNetwork(conf).init()

rng = np.random.RandomState(100 + pid)
batches = [DataSet(rng.randn(8, 4).astype(np.float32),
                   np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)])
           for _ in range(3)]

master = (SharedTrainingMaster.Builder(batch_size_per_worker=4)
          .coordinator(f"127.0.0.1:{port}", n_proc, pid)
          .build())
master.fit(net, batches, n_epochs=total_epochs,
           checkpoint_dir=f"{outdir}/ckpts", save_every_n_epochs=1)
print("RESUMED_AT", pid, net.epoch_count, flush=True)

leaves = jax.tree_util.tree_leaves(net.params)
np.savez(f"{outdir}/params_{pid}.npz",
         **{f"l{i}": np.asarray(v) for i, v in enumerate(leaves)})
print("WORKER_DONE", pid, flush=True)
import time; time.sleep(2)
''')


def _run_world(tmp_path, total_epochs, n_proc=2):
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CKPT_WORKER, str(i), str(n_proc),
         str(port), str(tmp_path), str(total_epochs)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(n_proc)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, out in enumerate(outs):
        assert f"WORKER_DONE {i}" in out, \
            f"worker {i} failed:\n{out[-2000:]}"
    return outs


@pytest.mark.parametrize("n_proc", [2, 4])
def test_multihost_checkpoint_save_kill_resume(tmp_path, n_proc):
    """SURVEY.md §5.4 multi-host discipline (round-3 verdict ask #5,
    widened to np=4 per the r4 verdict): run 1 trains 1 of 2 epochs
    with checkpointing and exits (the "kill"); run 2 — fresh
    processes, same world — RESUMES from the process-0-written
    checkpoint on ALL processes and trains only the remaining epoch.
    Final params must equal the uncrashed single-process run over the
    concatenated data, exactly."""
    _run_world(tmp_path, total_epochs=1, n_proc=n_proc)  # then "crash"
    from deeplearning4j_tpu.utils import CheckpointListener
    cps = CheckpointListener.available_checkpoints(
        tmp_path / "ckpts")
    assert cps, "process 0 must have written an epoch-1 checkpoint"
    outs = _run_world(tmp_path, total_epochs=2, n_proc=n_proc)
    for i, out in enumerate(outs):
        assert f"RESUMED_AT {i} 2" in out       # 2 epochs total done

    a = np.load(tmp_path / "params_0.npz")
    for i in range(1, n_proc):
        b = np.load(tmp_path / f"params_{i}.npz")
        for k in a.files:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-6,
                                       atol=1e-7)

    import jax as _jax
    if _jax.default_backend() != "cpu":
        return
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.learning import Sgd
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    conf = (NeuralNetConfiguration.Builder()
            .seed(7).updater(Sgd(1e-1))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4)).build())
    ref = MultiLayerNetwork(conf).init()
    rngs = [np.random.RandomState(100 + i) for i in range(n_proc)]
    parts = [[DataSet(r.randn(8, 4).astype(np.float32),
                      np.eye(2, dtype=np.float32)[r.randint(0, 2, 8)])
              for _ in range(3)] for r in rngs]
    merged = [DataSet(
        np.concatenate([parts[i][j].features for i in range(n_proc)]),
        np.concatenate([parts[i][j].labels for i in range(n_proc)]))
        for j in range(3)]
    ref.fit(merged, n_epochs=2)                  # uncrashed run
    ref_leaves = [np.asarray(v) for v in
                  _jax.tree_util.tree_leaves(ref.params)]
    for k, want in zip(a.files, ref_leaves):
        np.testing.assert_allclose(a[k], want, rtol=1e-4, atol=1e-5)


_ETL_WORKER = textwrap.dedent('''
import sys
import jax
pid, n_proc, port, outdir, csv_path = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5])
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=n_proc,
                           process_id=pid)

import numpy as np
from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datavec.records import CSVRecordReader
from deeplearning4j_tpu.datavec.sharded import ShardedDataSetIterator
from deeplearning4j_tpu.datavec.split import FileSplit
from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel.sharedtraining import \\
    SharedTrainingMaster

conf = (NeuralNetConfiguration.Builder()
        .seed(7).updater(Sgd(1e-1))
        .list()
        .layer(DenseLayer(n_out=8, activation=Activation.TANH))
        .layer(OutputLayer(n_out=3, loss_function=LossFunction.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(4)).build())
net = MultiLayerNetwork(conf).init()

# EVERY process reads the SAME csv; the iterator takes its own shard
rr = CSVRecordReader().initialize(FileSplit(csv_path))
it = ShardedDataSetIterator(rr, batch_size=8, label_index=4,
                            n_labels=3)
print("SHARD", pid, it.total_examples(), flush=True)

master = (SharedTrainingMaster.Builder(batch_size_per_worker=8)
          .coordinator(f"127.0.0.1:{port}", n_proc, pid)
          .build())
master.fit(net, it, n_epochs=2)

leaves = jax.tree_util.tree_leaves(net.params)
np.savez(f"{outdir}/etl_params_{pid}.npz",
         **{f"l{i}": np.asarray(v) for i, v in enumerate(leaves)})
print("WORKER_DONE", pid, flush=True)
import time; time.sleep(2)
''')


@pytest.mark.parametrize("n_proc", [2, 4])
def test_sharded_etl_world_equals_single(tmp_path, n_proc):
    """SURVEY.md V2/P4 (round-3 verdict ask #7; np=4 per the r4
    verdict): every process reads the SAME CSV through
    ShardedDataSetIterator; the per-process shards assemble into
    global batches whose training trajectory equals a single-process
    run over the equivalently-ordered data. The 50-record count is
    NON-divisible both globally (50 % 4 = 2 dropped rows at np=4) and
    per-shard (12 % 8) — the partial-tail arithmetic the r4 verdict
    called out as never exercised."""
    rng = np.random.RandomState(3)
    n = 50          # np=2: 25/shard, 24 used; np=4: 12/shard, 8 used
    feats = rng.randn(n, 4).astype(np.float32)
    labels = rng.randint(0, 3, size=(n, 1))
    csv = tmp_path / "data.csv"
    csv.write_text("\n".join(
        ",".join(f"{v:.7f}" for v in feats[i])
        + f",{int(labels[i, 0])}"
        for i in range(n)) + "\n")
    per = n // n_proc
    used = (per // 8) * 8

    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, "-c", _ETL_WORKER, str(i), str(n_proc),
         str(port), str(tmp_path), str(csv)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(n_proc)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, out in enumerate(outs):
        assert f"WORKER_DONE {i}" in out, \
            f"worker {i} failed:\n{out[-2000:]}"
        assert f"SHARD {i} {used}" in out

    a = np.load(tmp_path / "etl_params_0.npz")
    for i in range(1, n_proc):
        b = np.load(tmp_path / f"etl_params_{i}.npz")
        for k in a.files:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-6,
                                       atol=1e-7)

    import jax as _jax
    if _jax.default_backend() != "cpu":
        return
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.learning import Sgd
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    conf = (NeuralNetConfiguration.Builder()
            .seed(7).updater(Sgd(1e-1))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4)).build())
    ref = MultiLayerNetwork(conf).init()
    onehot = np.eye(3, dtype=np.float32)[labels[:, 0]]
    # global batch j = concat over shards of each shard's batch j
    merged = [DataSet(
        np.concatenate([feats[i * per + j * 8:i * per + (j + 1) * 8]
                        for i in range(n_proc)]),
        np.concatenate([onehot[i * per + j * 8:i * per + (j + 1) * 8]
                        for i in range(n_proc)]))
        for j in range(per // 8)]
    ref.fit(merged, n_epochs=2)
    ref_leaves = [np.asarray(v) for v in
                  _jax.tree_util.tree_leaves(ref.params)]
    for k, want in zip(a.files, ref_leaves):
        np.testing.assert_allclose(a[k], want, rtol=1e-4, atol=1e-5)
