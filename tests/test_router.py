"""Multi-replica serving router tests (ISSUE 15): least-loaded
dispatch, fleet-wide warm-then-drain rollouts, replica-failure
rerouting, and the router's observability surface.

Replicas are in-process (each its own registry/admission/server on a
free port) — mesh-free, so this module runs on any device count."""
import io
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.telemetry import MetricsRegistry
from deeplearning4j_tpu.serving import ServingRouter


@pytest.fixture(autouse=True)
def _fresh_registry():
    MetricsRegistry._reset_for_tests()
    yield
    MetricsRegistry._reset_for_tests()


def _mlp(seed=42):
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _get(base, path):
    r = urllib.request.urlopen(f"{base}{path}")
    return r.status, r.read()


def _post(base, name, payload, headers=None, raw=False):
    h = {"Content-Type": ("application/octet-stream" if raw
                          else "application/json")}
    h.update(headers or {})
    data = payload if isinstance(payload, bytes) \
        else json.dumps(payload).encode()
    req = urllib.request.Request(
        f"{base}/v1/models/{name}:predict", data=data, headers=h)
    try:
        r = urllib.request.urlopen(req)
        return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


@pytest.fixture
def router():
    rt = ServingRouter(n_replicas=2, default_buckets=(8,),
                       health_interval_s=0.2)
    rt.start(0)
    yield rt
    rt.stop(drain=False, timeout=5)


# ----------------------------------------------------------------------
class TestRouterDispatch:
    def test_rollout_then_predict_across_replicas(self, router):
        net = _mlp()
        ref_x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
        ref = np.asarray(net.output(ref_x))
        versions = router.rollout("m", lambda: _mlp(),
                                  warmup_shape=(8,))
        assert len(versions) == 2          # one per replica
        assert all(v.version == 1 for v in versions)

        code, body = _get(router.url, "/readyz")
        assert code == 200
        # JSON path round-trips through the proxy, bitwise to dense
        code, body, _ = _post(router.url, "m",
                              {"inputs": ref_x.tolist()})
        assert code == 200
        doc = json.loads(body)
        np.testing.assert_array_equal(
            np.asarray(doc["outputs"], dtype=np.float32), ref)
        # raw .npy path relays bytes + X-Model-Version untouched
        buf = io.BytesIO()
        np.save(buf, ref_x)
        code, body, hdrs = _post(router.url, "m", buf.getvalue(),
                                 raw=True)
        assert code == 200
        assert hdrs.get("X-Model-Version") == "1"
        np.testing.assert_array_equal(np.load(io.BytesIO(body)), ref)
        # dispatch was counted per replica
        c = telemetry.counter("dl4j_serving_router_requests_total")
        served = sum(c.value(replica=f"replica-{i}", code="200")
                     for i in range(2))
        assert served == 2

    def test_least_loaded_picks_idle_replica(self, router):
        r0, r1 = router.replicas
        r0.begin(); r0.begin()
        r1.begin()
        assert router._pick() is r1
        r1.begin(); r1.begin()
        assert router._pick() is r0
        r0.end(); r0.end(); r1.end(); r1.end(); r1.end()

    def test_replicas_endpoint_and_catalog(self, router):
        router.rollout("m", lambda: _mlp(), warmup_shape=(8,))
        code, body = _get(router.url, "/v1/replicas")
        assert code == 200
        reps = json.loads(body)["replicas"]
        assert [r["name"] for r in reps] == ["replica-0", "replica-1"]
        assert all(r["healthy"] and r["ready"] for r in reps)
        code, body = _get(router.url, "/v1/models")
        assert code == 200
        models = json.loads(body)["models"]
        assert models[0]["name"] == "m"

    def test_unknown_model_relays_replica_404(self, router):
        router.rollout("m", lambda: _mlp(), warmup_shape=(8,))
        code, body, _ = _post(router.url, "nope",
                              {"inputs": [[0.0] * 8]})
        assert code == 404

    def test_metrics_endpoint(self, router):
        router.rollout("m", lambda: _mlp(), warmup_shape=(8,))
        _post(router.url, "m", {"inputs": [[0.0] * 8]})
        code, body = _get(router.url, "/metrics")
        assert code == 200
        text = body.decode()
        assert "dl4j_serving_router_requests_total" in text
        assert "dl4j_serving_router_healthy" in text
        assert "dl4j_serving_rollouts_total" in text


# ----------------------------------------------------------------------
class TestRouterResilience:
    def test_rollout_under_load_drops_nothing(self, router):
        """The fleet-wide warm-then-drain acceptance: a hot-swap
        rollout under concurrent client load yields only 200s, every
        response matching v1's or v2's math."""
        net1, net2 = _mlp(seed=42), _mlp(seed=99)
        x = np.random.RandomState(1).randn(2, 8).astype(np.float32)
        ref1 = np.asarray(net1.output(x))
        ref2 = np.asarray(net2.output(x))
        router.rollout("m", lambda: _mlp(seed=42), warmup_shape=(8,))

        outs, errors = [], []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    code, body, _ = _post(router.url, "m",
                                          {"inputs": x.tolist()})
                    outs.append((code, body))
                except Exception as e:      # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            router.rollout("m", lambda: _mlp(seed=99),
                           warmup_shape=(8,))
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        assert not errors, errors[:3]
        assert outs
        assert all(code == 200 for code, _ in outs), \
            sorted({code for code, _ in outs})
        for _, body in outs:
            got = np.asarray(json.loads(body)["outputs"],
                             dtype=np.float32)
            assert (np.array_equal(got, ref1)
                    or np.array_equal(got, ref2))
        assert telemetry.counter(
            "dl4j_serving_rollouts_total").value(model="m") == 2

    def test_dead_replica_reroutes_and_leaves_rotation(self, router):
        """A connection-level failure retries on the next replica and
        takes the dead one out of rotation — the client sees 200."""
        x = np.random.RandomState(2).randn(1, 8).astype(np.float32)
        router.rollout("m", lambda: _mlp(), warmup_shape=(8,))
        victim = router.replicas[0]
        victim.server.stop(drain=False)

        code, body, _ = _post(router.url, "m",
                              {"inputs": x.tolist()})
        assert code == 200
        assert victim.healthy is False
        g = telemetry.gauge("dl4j_serving_router_healthy")
        assert g.value(replica="replica-0") == 0
        # the survivor keeps serving
        code, _, _ = _post(router.url, "m", {"inputs": x.tolist()})
        assert code == 200

    def test_no_healthy_replica_is_502(self, router):
        router.rollout("m", lambda: _mlp(), warmup_shape=(8,))
        for r in router.replicas:
            r.set_healthy(False)
            r.server.stop(drain=False)
        router._stopping = True    # freeze the health poller's verdict
        code, body, _ = _post(router.url, "m",
                              {"inputs": [[0.0] * 8]})
        assert code == 502
        assert "no healthy replica" in json.loads(body)["error"]
        assert telemetry.counter(
            "dl4j_serving_router_requests_total").value(
                replica="none", code="502") == 1


# ----------------------------------------------------------------------
class TestRouterObservatory:
    """ISSUE-17 satellites: the router relays the trace id both ways,
    stamps which replica served, and keeps the id across a
    connection-failure retry."""

    def test_trace_header_relays_both_ways_on_predict(self, router):
        from deeplearning4j_tpu.common import tracectx
        router.rollout("m", lambda: _mlp(), warmup_shape=(8,))
        x = np.random.RandomState(0).randn(1, 8).astype(np.float32)
        tid = "router-relay-tid-1"
        code, _, headers = _post(
            router.url, "m", {"inputs": x.tolist()},
            headers={tracectx.TRACE_HEADER: tid})
        assert code == 200
        assert headers.get(tracectx.TRACE_HEADER) == tid
        assert headers.get(tracectx.REPLICA_HEADER, "").startswith(
            "replica-")
        # without a client id the router mints one at ingress
        code, _, headers = _post(router.url, "m",
                                 {"inputs": x.tolist()})
        assert code == 200
        minted = headers.get(tracectx.TRACE_HEADER)
        assert minted and len(minted) == 16

    def test_trace_and_replica_headers_on_generate_stream(
            self, router):
        import http.client

        from deeplearning4j_tpu.common import tracectx
        from deeplearning4j_tpu.models.decoder import (DecoderConfig,
                                                       DecoderLM)
        conf = DecoderConfig.tiny()
        router.rollout("lm", lambda: DecoderLM(conf), generate={
            "kv_blocks": 32, "kv_block_size": 8,
            "prompt_buckets": (16,), "decode_buckets": (4,),
            "max_seq_len": 64})
        tid = "router-relay-gen-1"
        conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                          timeout=60)
        conn.request("POST", "/v1/models/lm:generate",
                     body=json.dumps({"prompt": [5, 9, 2, 7],
                                      "max_tokens": 4}).encode(),
                     headers={"Content-Type": "application/json",
                              tracectx.TRACE_HEADER: tid})
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Transfer-Encoding") == "chunked"
        # the chunked relay carries the observatory headers up front
        assert resp.getheader(tracectx.TRACE_HEADER) == tid
        assert resp.getheader(tracectx.REPLICA_HEADER, "").startswith(
            "replica-")
        lines = [json.loads(ln) for ln in
                 resp.read().decode().strip().splitlines()]
        assert lines[-1]["done"] and lines[-1]["tokens"] == 4
        conn.close()

    def test_retry_after_connect_failure_keeps_trace(self, router):
        """A connection-level replica failure retries on the
        survivor — and the response still carries the ORIGINAL trace
        id plus the replica that actually served."""
        from deeplearning4j_tpu.common import tracectx
        router.rollout("m", lambda: _mlp(), warmup_shape=(8,))
        victim = router.replicas[0]
        victim.server.stop(drain=False)
        x = np.random.RandomState(2).randn(1, 8).astype(np.float32)
        tid = "router-retry-tid-1"
        code, _, headers = _post(
            router.url, "m", {"inputs": x.tolist()},
            headers={tracectx.TRACE_HEADER: tid})
        assert code == 200
        assert headers.get(tracectx.TRACE_HEADER) == tid
        assert headers.get(tracectx.REPLICA_HEADER) == \
            router.replicas[1].name
        assert victim.healthy is False
