"""StableHLO emission + portable program export (SURVEY.md §2.7 item
1: the reference's native graph runtime compiles/serializes graphs;
here the built SameDiff subgraph lowers to ONE StableHLO program,
inspectable as text and serializable via jax.export for AOT
hand-off)."""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.samediff import SameDiff


def _toy():
    sd = SameDiff()
    x = sd.placeholder("x", (4, 3))
    w = sd.var("w", array=np.float32(np.ones((3, 2))))
    y = sd.math.matmul(x, w)
    out = sd.math.tanh(y, name="out")
    return sd


class TestStableHlo:
    def test_text_contains_program(self):
        sd = _toy()
        txt = sd.to_stablehlo({"x": np.zeros((4, 3), np.float32)},
                              ["out"])
        assert "stablehlo" in txt or "mhlo" in txt or "func.func" in txt
        assert "dot_general" in txt or "dot" in txt
        assert "tanh" in txt

    def test_shape_dtype_struct_inputs(self):
        import jax
        sd = _toy()
        txt = sd.to_stablehlo(
            {"x": jax.ShapeDtypeStruct((8, 3), np.float32)}, ["out"])
        assert "8x3" in txt            # traced at the requested shape

    def test_serialized_roundtrip_matches_output(self):
        sd = _toy()
        xv = np.random.RandomState(0).randn(4, 3).astype(np.float32)
        want = sd.output({"x": xv}, ["out"])["out"]
        blob = sd.export_serialized({"x": xv}, ["out"])
        assert isinstance(blob, (bytes, bytearray)) and len(blob) > 100
        got = SameDiff.deserialize_and_call(blob, {"x": xv})
        np.testing.assert_allclose(np.asarray(got[0]), want,
                                   rtol=1e-6)

    def test_control_flow_exports(self):
        """A bounded while-loop subgraph lowers into the same single
        exported program."""
        sd = SameDiff()
        x = sd.placeholder("x", (3,))

        def cond(i, acc):
            return i.sd.math.lt(i, i.sd._as_var(np.int32(4)))

        def body(i, acc):
            return (i.sd.math.add(i, i.sd._as_var(np.int32(1))),
                    acc * 1.5)

        outs = sd.while_loop([sd._as_var(np.int32(0)), x], cond, body,
                             max_iterations=8)
        sd.math.reduce_sum(outs[1], name="out")
        xv = np.float32([1.0, 2.0, 3.0])
        want = sd.output({"x": xv}, ["out"])["out"]
        blob = sd.export_serialized({"x": xv}, ["out"])
        got = SameDiff.deserialize_and_call(blob, {"x": xv})
        np.testing.assert_allclose(np.asarray(got[0]), want,
                                   rtol=1e-5)
