"""Diagnostics layer tests (ISSUE 7): HBM accounting, collective
spans, the numerics watchdog (clean runs never trip; an injected NaN
trips within one step, with first-bad-leaf attribution), flight
recorder ring semantics, crash/SIGTERM dump artifacts, bench
provenance, and the bench-regression gate's self-test on the
checked-in BENCH_r04/r05 rounds."""
import json
import os
import signal
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.common import diagnostics, telemetry
from deeplearning4j_tpu.common.diagnostics import (FlightRecorder,
                                                   NumericsEvent)
from deeplearning4j_tpu.common.environment import Environment
from deeplearning4j_tpu.common.telemetry import MetricsRegistry

_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_state():
    MetricsRegistry._reset_for_tests()
    Environment.reset()
    FlightRecorder._reset_for_tests()
    yield
    MetricsRegistry._reset_for_tests()
    Environment.reset()
    FlightRecorder._reset_for_tests()


def _net_and_data(n=32):
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    rng = np.random.RandomState(0)
    x = rng.randn(n, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
         .list()
         .layer(DenseLayer(n_out=8, activation=Activation.RELU))
         .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                            loss_function=LossFunction.MCXENT))
         .set_input_type(InputType.feed_forward(4)).build())).init()
    return net, DataSet(x, y)


# ----------------------------------------------------------------------
# HBM accounting
class TestHbmAccounting:
    STATS = [{"id": 0, "kind": "fake-tpu", "bytes_in_use": 1000,
              "peak_bytes_in_use": 1500, "bytes_limit": 4000},
             {"id": 1, "kind": "fake-tpu", "bytes_in_use": 900,
              "peak_bytes_in_use": 1600, "bytes_limit": 4000}]

    def test_gauges_from_injected_stats(self):
        diagnostics.update_hbm_gauges(self.STATS)
        live = telemetry.gauge("dl4j_hbm_live_bytes")
        peak = telemetry.gauge("dl4j_hbm_peak_bytes")
        assert live.value(device="0") == 1000
        assert live.value(device="1") == 900
        assert peak.value(device="1") == 1600
        text = MetricsRegistry.get().render_prometheus()
        assert 'dl4j_hbm_live_bytes{device="0"} 1000' in text

    def test_memory_report_attribution(self):
        net, ds = _net_and_data()
        net.fit(ds)                 # records a step -> tracks the model
        rep = diagnostics.memory_report()
        assert rep["schema_version"] == diagnostics.SCHEMA_VERSION
        models = [v for k, v in rep["models"].items()
                  if k.startswith("MultiLayerNetwork")]
        assert models and models[0]["params_bytes"] > 0
        assert models[0]["updater_state_bytes"] > 0     # Adam m+v
        assert rep["accounted_bytes"] >= models[0]["params_bytes"]
        # narrowing to one model keys by bare class name
        one = diagnostics.memory_report(model=net)
        assert one["models"]["MultiLayerNetwork"]["params_bytes"] == \
            models[0]["params_bytes"]

    def test_report_shape_on_cpu(self):
        # CPU backend exposes no allocator stats: devices empty, no
        # residual estimate (it would be meaningless), totals zero
        rep = diagnostics.memory_report()
        if not rep["devices"]:
            assert rep["live_bytes_total"] == 0
            assert "activations_and_workspace_bytes_est" not in rep

    def test_roofline_classification(self):
        # 10 TF/s achieved against a 100 TF/s / 100 GB/s machine:
        # AI = 1e13/1e12 = 10 flops/B, ridge = 1000 -> HBM bound
        r = diagnostics.roofline(1e13, 1e12, 1.0, peak_tflops=100,
                                 peak_hbm_gbps=100)
        assert r["bound"] == "hbm"
        assert r["pct_of_roof"] == r["pct_hbm_peak"] == 1000.0
        # flip the intensity: compute bound
        r = diagnostics.roofline(1e14, 1e9, 1.0, peak_tflops=100,
                                 peak_hbm_gbps=100)
        assert r["bound"] == "compute"
        # no peaks known (non-TPU): classification keys absent
        r = diagnostics.roofline(1e12, 1e9, 1.0)
        assert "bound" not in r and r["tflops"] == 1.0


# ----------------------------------------------------------------------
# collective spans
class TestCollectiveSpan:
    def test_emits_span_histogram_and_bytes(self):
        with diagnostics.collective_span("update_exchange", "data",
                                         4096, mode="all_reduce"):
            pass
        h = telemetry.histogram("dl4j_collective_seconds")
        assert h.count_of(kind="update_exchange", axis="data") == 1
        c = telemetry.counter("dl4j_collective_bytes_total")
        assert c.value(kind="update_exchange", axis="data") == 4096
        names = [e["name"] for e in telemetry.trace_events()]
        assert "collective.update_exchange" in names

    def test_zero_bytes_skips_counter(self):
        with diagnostics.collective_span("global_assembly", "data"):
            pass
        assert telemetry.histogram("dl4j_collective_seconds").count_of(
            kind="global_assembly", axis="data") == 1
        assert "dl4j_collective_bytes_total" not in \
            MetricsRegistry.get()._metrics

    def test_disabled_is_bare(self):
        MetricsRegistry.get().set_enabled(False)
        with diagnostics.collective_span("update_exchange", "data",
                                         4096):
            pass
        assert "dl4j_collective_seconds" not in \
            MetricsRegistry.get()._metrics


# ----------------------------------------------------------------------
# numerics watchdog
@pytest.fixture()
def _watchdog(monkeypatch, tmp_path):
    monkeypatch.setenv("DL4J_TPU_NUMERICS_WATCHDOG", "1")
    monkeypatch.setenv("DL4J_TPU_FLIGHT_RECORDER_DIR", str(tmp_path))
    Environment.reset()
    FlightRecorder._reset_for_tests()
    yield tmp_path


class TestNumericsWatchdog:
    def test_first_nonfinite_attribution(self):
        import jax.numpy as jnp
        tree = {"a": jnp.ones((3,), jnp.float32),
                "b": jnp.asarray([0.0, 1.0, np.nan, 2.0], jnp.float32)}
        bad = diagnostics.first_nonfinite(tree)
        assert bad is not None
        assert "b" in bad["leaf"]
        assert bad["flat_index"] == 2
        assert diagnostics.first_nonfinite(
            {"a": jnp.ones((3,), jnp.float32)}) is None

    def test_clean_run_never_trips(self, _watchdog):
        net, ds = _net_and_data()
        for _ in range(5):
            net.fit(ds)
        assert net.iteration_count == 5
        c = telemetry.counter("dl4j_numerics_trips_total")
        assert c.value(model="MultiLayerNetwork", group="loss") == 0
        assert not list(_watchdog.glob("flightrec_*"))

    def test_nan_input_trips_within_one_step(self, _watchdog):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net, ds = _net_and_data()
        net.fit(ds)                             # step 0: clean
        bad_x = np.array(ds.features)
        bad_x[0, 0] = np.nan
        with pytest.raises(NumericsEvent) as ei:
            net.fit(DataSet(bad_x, np.array(ds.labels)))
        ev = ei.value
        assert ev.step == 1                     # caught on ITS step
        assert ev.tensor_group == "loss"
        assert not np.isfinite(ev.value)
        # attribution scanned the poisoned post-update params
        assert ev.first_bad is not None
        assert ev.first_bad["leaf"]
        c = telemetry.counter("dl4j_numerics_trips_total")
        assert c.value(model="MultiLayerNetwork", group="loss") == 1
        # the recorder dumped, and the poisoned step is in the ring
        # exactly once (no double record from after_step + the trip)
        dumps = list(_watchdog.glob("flightrec_*_numerics.jsonl"))
        assert len(dumps) == 1
        lines = [json.loads(s) for s in
                 dumps[0].read_text().splitlines()]
        meta, recs = lines[0], lines[1:]
        assert meta["reason"] == "numerics"
        assert meta["event"]["step"] == 1
        assert [r["step"] for r in recs] == [0, 1]
        assert not np.isfinite(recs[1]["loss"])
        # the in-jit global grad norm was wired in (watchdog was armed
        # when the step traced) and materialized at dump time
        assert recs[0]["grad_norm"] is not None
        assert np.isfinite(recs[0]["grad_norm"])

    def test_sampling_skips_intermediate_steps(self, _watchdog,
                                               monkeypatch):
        monkeypatch.setenv("DL4J_TPU_NUMERICS_SAMPLE", "1000")
        Environment.reset()
        FlightRecorder._reset_for_tests()
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net, ds = _net_and_data()
        net.fit(ds)                             # step 0: 0 % 1000 == 0
        bad_x = np.array(ds.features)
        bad_x[:] = np.nan
        # steps 1..3 are off-sample: the poison flows through unchecked
        for _ in range(3):
            net.fit(DataSet(bad_x, np.array(ds.labels)))
        assert net.iteration_count == 4

    def test_off_by_default(self, tmp_path):
        assert not diagnostics.watchdog_enabled()
        # check_numerics is a no-op even on a NaN loss
        diagnostics.check_numerics(None, "m", 0, float("nan"))


# ----------------------------------------------------------------------
# flight recorder
class TestFlightRecorder:
    def test_ring_truncates_to_capacity(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FLIGHT_RECORDER_STEPS", "8")
        Environment.reset()
        FlightRecorder._reset_for_tests()
        rec = FlightRecorder.get()
        assert rec.max_steps == 8
        for i in range(20):
            rec.record(self, "t", i, 0.5)
        steps = [r["step"] for r in rec.records()]
        assert steps == list(range(12, 20))

    def test_record_fields_and_lazy_loss(self, tmp_path):
        import jax.numpy as jnp
        rec = FlightRecorder.get()
        rec.dir = str(tmp_path)
        dev_loss = jnp.float32(0.25)        # device scalar stays lazy
        rec.record(self, "t", 0, dev_loss, None, grad_norm=None)
        r = rec.records()[0]
        for key in ("step", "t", "model", "step_seconds", "loss",
                    "grad_norm", "retraces", "collective_bytes",
                    "hbm_live_bytes", "hbm_peak_bytes"):
            assert key in r
        assert r["loss"] is dev_loss        # not float()ed on record
        path = rec.dump("manual")
        recs = [json.loads(s) for s in
                Path(path).read_text().splitlines()][1:]
        assert recs[0]["loss"] == 0.25      # materialized at dump

    def test_dump_writes_trace_and_dedups(self, tmp_path):
        rec = FlightRecorder.get()
        rec.dir = str(tmp_path)
        rec.record(self, "t", 0, 0.5)
        path = rec.dump("manual", event={"why": "test"})
        assert path and os.path.exists(path)
        assert os.path.exists(path.replace(".jsonl", ".trace.json"))
        meta = json.loads(Path(path).read_text().splitlines()[0])
        assert meta["event"] == {"why": "test"}
        assert meta["ring_capacity"] == rec.max_steps
        # second dump for the same reason: suppressed
        assert rec.dump("manual") is None
        c = telemetry.counter("dl4j_flightrec_dumps_total")
        assert c.value(reason="manual") == 1

    def test_disabled_records_nothing(self, monkeypatch, tmp_path):
        monkeypatch.setenv("DL4J_TPU_FLIGHT_RECORDER", "0")
        Environment.reset()
        FlightRecorder._reset_for_tests()
        rec = FlightRecorder.get()
        rec.record(self, "t", 0, 0.5)
        assert rec.records() == []
        assert rec.dump("manual") is None

    def test_fit_populates_ring(self):
        net, ds = _net_and_data()
        for _ in range(3):
            net.fit(ds)
        rec = FlightRecorder.get()
        recs = [r for r in rec.records()
                if r["model"] == "MultiLayerNetwork"]
        assert [r["step"] for r in recs] == [0, 1, 2]
        assert recs[0]["step_seconds"] is not None
        assert recs[0]["step_seconds"] > 0


_SUBPROC_PRELUDE = textwrap.dedent("""\
    import os, sys
    sys.path.insert(0, {root!r})
    import numpy as np
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    rng = np.random.RandomState(0)
    x = rng.randn(16, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
         .list()
         .layer(DenseLayer(n_out=8, activation=Activation.RELU))
         .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                            loss_function=LossFunction.MCXENT))
         .set_input_type(InputType.feed_forward(4)).build())).init()
    ds = DataSet(x, y)
""").format(root=str(_ROOT))


def _run_subproc(body: str, tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DL4J_TPU_FLIGHT_RECORDER_DIR=str(tmp_path))
    return subprocess.run(
        [sys.executable, "-c", _SUBPROC_PRELUDE + body],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(_ROOT))


class TestCrashArtifacts:
    def test_crash_dump_has_final_window(self, tmp_path):
        # acceptance bar: after a crash mid-training the dump holds the
        # final >=32 steps with time/loss/grad-norm/collective/HBM
        # fields
        p = _run_subproc(textwrap.dedent("""\
            for _ in range(40):
                net.fit(ds)
            raise RuntimeError("boom")
        """), tmp_path)
        assert p.returncode != 0
        assert "boom" in p.stderr           # original traceback kept
        dumps = list(tmp_path.glob("flightrec_*_crash.jsonl"))
        assert len(dumps) == 1, p.stderr
        lines = [json.loads(s) for s in
                 dumps[0].read_text().splitlines()]
        meta, recs = lines[0], lines[1:]
        assert meta["reason"] == "crash"
        assert "boom" in meta["event"]["error"]
        assert len(recs) >= 32
        assert [r["step"] for r in recs] == list(range(40))
        for r in recs:
            assert r["step_seconds"] > 0
            assert np.isfinite(r["loss"])
            assert r["collective_bytes"] >= 0
            assert "hbm_live_bytes" in r
        assert dumps[0].with_name(
            dumps[0].name.replace(".jsonl", ".trace.json")).exists()

    def test_sigterm_dump_and_redelivery(self, tmp_path):
        # preemption path: dump, then die OF SIGTERM (exit status must
        # still tell the scheduler the truth)
        p = _run_subproc(textwrap.dedent("""\
            import signal
            for _ in range(3):
                net.fit(ds)
            os.kill(os.getpid(), signal.SIGTERM)
        """), tmp_path)
        assert p.returncode == -signal.SIGTERM, p.stderr
        dumps = list(tmp_path.glob("flightrec_*_sigterm.jsonl"))
        assert len(dumps) == 1, p.stderr
        lines = [json.loads(s) for s in
                 dumps[0].read_text().splitlines()]
        assert lines[0]["reason"] == "sigterm"
        assert [r["step"] for r in lines[1:]] == [0, 1, 2]


# ----------------------------------------------------------------------
# bench provenance + regression gate
class TestBenchMeta:
    def test_fields(self):
        meta = diagnostics.bench_meta()
        assert meta["schema_version"] == diagnostics.SCHEMA_VERSION
        import jax
        assert meta["jax_version"] == jax.__version__
        assert meta["platform"] in ("cpu", "tpu", "gpu")
        assert meta["device_count"] >= 1
        assert isinstance(meta["env"], dict)


class TestRegressionGate:
    R04 = str(_ROOT / "BENCH_r04.json")
    R05 = str(_ROOT / "BENCH_r05.json")

    def _main(self, argv):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_bench_regression",
            _ROOT / "scripts" / "check_bench_regression.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_r04_to_r05_passes_default_threshold(self, capsys):
        mod = self._main(None)
        assert mod.main([self.R04, self.R05, "-q"]) == 0

    def test_tight_threshold_flags_throughput_drop(self, capsys):
        # r04 -> r05 moved the headline images/s by ~-0.5%: invisible
        # at the default 10%, a regression at 0.2%
        mod = self._main(None)
        assert mod.main([self.R04, self.R05, "--threshold", "0.2",
                         "-q"]) == 1
        out = capsys.readouterr().out
        assert "REGRESSION" in out and "value" in out

    def test_unusable_input_is_rc2(self, tmp_path):
        mod = self._main(None)
        bad = tmp_path / "bad.json"
        bad.write_text("[]")
        assert mod.main([str(bad), self.R05]) == 2

    def test_pct_metrics_compare_in_points(self):
        mod = self._main(None)
        base = {"metric": "m", "value": 100.0, "overhead_pct": -0.9}
        fresh = {"metric": "m", "value": 100.0, "overhead_pct": 1.4}
        regs, _, _ = mod.compare(base, fresh, 10.0)
        # 2.3 points of overhead growth is under a 10-point threshold;
        # the old relative math would have read it as -256%
        assert regs == []
        regs, _, _ = mod.compare(base, fresh, 1.0)
        assert [r[0] for r in regs] == ["overhead_pct"]

    def test_canary_keys_skipped(self):
        mod = self._main(None)
        base = {"metric": "m", "scaling_canary_ips": 100.0}
        fresh = {"metric": "m", "scaling_canary_ips": 1.0}
        regs, _, _ = mod.compare(base, fresh, 10.0)
        assert regs == []
