"""Op validation, batch 2 — ratchets §4.3 coverage across the
remaining domains (boolean, bitwise, losses, index/segment ops,
shape constructors, conv variants, linalg, recurrent cells, image,
compression, aliases)."""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.opvalidation import (TestCase,
                                                      coverage_report,
                                                      validate)

R = np.random.RandomState(11)
A = R.randn(3, 4).astype(np.float32)
B = R.randn(3, 4).astype(np.float32)
P = (np.abs(A) + 0.5).astype(np.float32)
I1 = R.randint(0, 8, (3, 4)).astype(np.int32)
I2 = R.randint(0, 8, (3, 4)).astype(np.int32)
IMG = R.randn(2, 6, 6, 3).astype(np.float32)
SPD = (lambda m: (m @ m.T + 4 * np.eye(4)).astype(np.float32))(
    R.randn(4, 4))
SQ = R.randn(4, 4).astype(np.float32) + 4 * np.eye(4,
                                                   dtype=np.float32)
LOGITS = R.randn(5, 6).astype(np.float32)
ONEHOT = np.eye(6, dtype=np.float32)[R.randint(0, 6, 5)]
PROBS = np.clip(R.rand(5, 6).astype(np.float32), 0.05, 0.95)
BIN = (R.rand(5, 6) > 0.5).astype(np.float32)


def _softmax(z):
    e = np.exp(z - z.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


CASES = [
    # arithmetic variants
    TestCase("floordiv", [A, P], expected_fn=np.floor_divide,
             gradient_check=False),
    TestCase("mod", [P, np.float32(0.7)], expected_fn=np.mod,
             gradient_check=False),
    TestCase("rdiv", [P, B], expected_fn=lambda a, b: b / a),
    TestCase("rsub", [A, B], expected_fn=lambda a, b: b - a),
    TestCase("atan2", [A, P], expected_fn=np.arctan2),
    TestCase("cube", [A], expected_fn=lambda a: a ** 3),
    TestCase("expm1", [A], expected_fn=np.expm1),
    TestCase("erfc", [A], gradient_check=True),
    TestCase("identity", [A], expected_fn=lambda a: a),
    TestCase("cast", [A], {"dtype": "int32"},
             expected_fn=lambda a: a.astype(np.int32),
             gradient_check=False),
    TestCase("clip_by_norm", [A], {"clip_norm": 1.0},
             expected_fn=lambda a: a / np.linalg.norm(a)
             if np.linalg.norm(a) > 1 else a,
             gradient_check=False),
    # activations (remaining)
    TestCase("relu6", [A * 4], gradient_check=False),
    TestCase("hard_sigmoid", [A], gradient_check=False),
    TestCase("hard_tanh", [A], gradient_check=False),
    TestCase("swish", [A]),
    TestCase("mish", [A]),
    TestCase("gelu_tanh", [A]),
    TestCase("softsign", [A],
             expected_fn=lambda a: a / (1 + np.abs(a))),
    TestCase("selu", [A], gradient_check=True),
    TestCase("prelu", [A, np.full((4,), 0.2, np.float32)]),
    # boolean / comparison
    TestCase("eq", [I1, I2], expected_fn=np.equal,
             gradient_check=False),
    TestCase("neq", [I1, I2], expected_fn=np.not_equal,
             gradient_check=False),
    TestCase("gt", [A, B], expected_fn=np.greater,
             gradient_check=False),
    TestCase("gte", [A, B], expected_fn=np.greater_equal,
             gradient_check=False),
    TestCase("lt", [A, B], expected_fn=np.less,
             gradient_check=False),
    TestCase("lte", [A, B], expected_fn=np.less_equal,
             gradient_check=False),
    TestCase("logical_and", [I1 > 3, I2 > 3],
             expected_fn=np.logical_and, gradient_check=False),
    TestCase("logical_or", [I1 > 3, I2 > 3],
             expected_fn=np.logical_or, gradient_check=False),
    TestCase("logical_xor", [I1 > 3, I2 > 3],
             expected_fn=np.logical_xor, gradient_check=False),
    TestCase("logical_not", [I1 > 3], expected_fn=np.logical_not,
             gradient_check=False),
    TestCase("is_nan", [np.asarray([1.0, np.nan], np.float32)],
             expected_fn=np.isnan, gradient_check=False),
    TestCase("is_inf", [np.asarray([1.0, np.inf], np.float32)],
             expected_fn=np.isinf, gradient_check=False),
    TestCase("is_finite", [np.asarray([1.0, np.inf], np.float32)],
             expected_fn=np.isfinite, gradient_check=False),
    TestCase("where", [A > 0, A, B],
             expected_fn=lambda c, a, b: np.where(c, a, b),
             grad_inputs=[1, 2]),
    TestCase("select", [A > 0, A, B],
             expected_fn=lambda c, a, b: np.where(c, a, b),
             gradient_check=False),
    # bitwise
    TestCase("bitwise_and", [I1, I2], expected_fn=np.bitwise_and,
             gradient_check=False),
    TestCase("bitwise_or", [I1, I2], expected_fn=np.bitwise_or,
             gradient_check=False),
    TestCase("bitwise_xor", [I1, I2], expected_fn=np.bitwise_xor,
             gradient_check=False),
    TestCase("bitwise_not", [I1], expected_fn=np.invert,
             gradient_check=False),
    TestCase("left_shift", [I1, np.int32(2)],
             expected_fn=np.left_shift, gradient_check=False),
    TestCase("right_shift", [I1, np.int32(1)],
             expected_fn=np.right_shift, gradient_check=False),
    # blas aliases / extras
    TestCase("mmul", [A, R.randn(4, 5).astype(np.float32)],
             expected_fn=np.matmul),
    TestCase("batch_matmul",
             [R.randn(2, 3, 4).astype(np.float32),
              R.randn(2, 4, 5).astype(np.float32)],
             expected_fn=np.matmul),
    TestCase("dot", [R.randn(4).astype(np.float32),
                     R.randn(4).astype(np.float32)],
             expected_fn=np.dot),
    TestCase("outer", [R.randn(3).astype(np.float32),
                       R.randn(4).astype(np.float32)],
             expected_fn=np.outer),
    TestCase("tensordot_last", [A, R.randn(4, 5).astype(np.float32)],
             expected_fn=lambda a, b: np.tensordot(a, b, 1)),
    TestCase("einsum", [A, R.randn(4, 5).astype(np.float32)],
             {"equation": "ij,jk->ik"}, expected_fn=np.matmul),
    # reductions (remaining + aliases)
    TestCase("sum", [A], {"axis": (1,)},
             expected_fn=lambda a: a.sum(1)),
    TestCase("mean", [A], {"axis": (0,)},
             expected_fn=lambda a: a.mean(0)),
    TestCase("amax", [A], {"axis": (1,)},
             expected_fn=lambda a: a.max(1), gradient_check=False),
    TestCase("amin", [A], {"axis": (1,)},
             expected_fn=lambda a: a.min(1), gradient_check=False),
    TestCase("cumsum", [A], {"axis": 1},
             expected_fn=lambda a: np.cumsum(a, 1)),
    TestCase("cumprod", [P], {"axis": 1},
             expected_fn=lambda a: np.cumprod(a, 1)),
    TestCase("reduce_logsumexp", [A], {"axis": (1,)},
             expected_fn=lambda a: np.log(np.exp(a).sum(1))),
    TestCase("reduce_norm1", [A], {"axis": (1,)},
             expected_fn=lambda a: np.abs(a).sum(1),
             gradient_check=False),   # |x| kink vs finite eps
    TestCase("reduce_norm2", [A], {"axis": (1,)},
             expected_fn=lambda a: np.sqrt((a * a).sum(1))),
    TestCase("reduce_all", [I1 > 0], {"axis": (1,)},
             expected_fn=lambda a: a.all(1), gradient_check=False),
    TestCase("reduce_any", [I1 > 6], {"axis": (1,)},
             expected_fn=lambda a: a.any(1), gradient_check=False),
    # index reductions
    TestCase("argmax", [A], {"axis": 1},
             expected_fn=lambda a: a.argmax(1),
             gradient_check=False),
    TestCase("argmin", [A], {"axis": 1},
             expected_fn=lambda a: a.argmin(1),
             gradient_check=False),
    TestCase("top_k", [A], {"k": 2},
             expected_fn=lambda a: (np.sort(a, 1)[:, ::-1][:, :2],
                                    np.argsort(-a, 1)[:, :2]),
             gradient_check=False),
    TestCase("in_top_k", [LOGITS,
                          np.asarray([0, 1, 2, 3, 4], np.int32)],
             {"k": 3}, gradient_check=False),
    # segment ops
    TestCase("segment_sum",
             [R.randn(6, 3).astype(np.float32),
              np.asarray([0, 0, 1, 1, 2, 2], np.int32)],
             {"num_segments": 3},
             expected_fn=lambda x, s: np.stack(
                 [x[s == i].sum(0) for i in range(3)]),
             grad_inputs=[0]),
    TestCase("segment_mean",
             [R.randn(6, 3).astype(np.float32),
              np.asarray([0, 0, 1, 1, 2, 2], np.int32)],
             {"num_segments": 3},
             expected_fn=lambda x, s: np.stack(
                 [x[s == i].mean(0) for i in range(3)]),
             grad_inputs=[0]),
    TestCase("segment_max",
             [R.randn(6, 3).astype(np.float32),
              np.asarray([0, 0, 1, 1, 2, 2], np.int32)],
             {"num_segments": 3},
             expected_fn=lambda x, s: np.stack(
                 [x[s == i].max(0) for i in range(3)]),
             gradient_check=False),
    TestCase("segment_min",
             [R.randn(6, 3).astype(np.float32),
              np.asarray([0, 0, 1, 1, 2, 2], np.int32)],
             {"num_segments": 3},
             expected_fn=lambda x, s: np.stack(
                 [x[s == i].min(0) for i in range(3)]),
             gradient_check=False),
    # shape constructors / manipulators
    TestCase("one_hot", [np.asarray([0, 2, 1], np.int32)],
             {"depth": 4},
             expected_fn=lambda i: np.eye(4, dtype=np.float32)[i],
             gradient_check=False),
    TestCase("broadcast_to", [R.randn(1, 4).astype(np.float32)],
             {"shape": (3, 4)},
             expected_fn=lambda a: np.broadcast_to(a, (3, 4))),
    TestCase("zeros_like", [A], expected_fn=np.zeros_like,
             gradient_check=False),
    TestCase("ones_like", [A], expected_fn=np.ones_like,
             gradient_check=False),
    TestCase("fill", [], {"shape": (2, 3), "value": 1.5},
             expected_fn=lambda: np.full((2, 3), 1.5, np.float32),
             gradient_check=False),
    TestCase("range", [], {"start": 1, "limit": 7, "delta": 2},
             expected_fn=lambda: np.arange(1, 7, 2),
             gradient_check=False),
    TestCase("linspace", [], {"start": 0.0, "stop": 1.0, "num": 5},
             expected_fn=lambda: np.linspace(0, 1, 5),
             gradient_check=False),
    TestCase("eye", [], {"rows": 3, "cols": 4},
             expected_fn=lambda: np.eye(3, 4, dtype=np.float32),
             gradient_check=False),
    TestCase("shape_of", [A], expected_fn=lambda a: np.asarray(
        a.shape, np.int32), gradient_check=False),
    TestCase("size", [A],
             expected_fn=lambda a: np.int32(a.size),
             gradient_check=False),
    TestCase("rank", [A], expected_fn=lambda a: np.int32(a.ndim),
             gradient_check=False),
    TestCase("transpose", [A], {"axes": [1, 0]},
             expected_fn=lambda a: a.T),
    TestCase("repeat", [A], {"repeats": 2, "axis": 1},
             expected_fn=lambda a: np.repeat(a, 2, 1)),
    TestCase("split", [A], {"num_splits": 2, "axis": 1},
             expected_fn=lambda a: tuple(np.split(a, 2, 1))),
    TestCase("split_v", [A], {"size_splits": [1, 3], "axis": 1},
             expected_fn=lambda a: (a[:, :1], a[:, 1:])),
    TestCase("unstack", [A], {"axis": 0},
             expected_fn=lambda a: tuple(a[i] for i in range(3))),
    TestCase("gather_nd",
             [A, np.asarray([[0, 1], [2, 3]], np.int32)],
             expected_fn=lambda a, i: a[tuple(i.T)],
             grad_inputs=[0]),
    TestCase("scatter_update",
             [A, np.asarray([0, 2], np.int32),
              R.randn(2, 4).astype(np.float32)],
             expected_fn=lambda a, i, u: (
                 lambda c: (c.__setitem__(i, u), c)[1])(a.copy()),
             gradient_check=False),
    TestCase("scatter_add",
             [A, np.asarray([0, 0], np.int32),
              np.ones((2, 4), np.float32)],
             expected_fn=lambda a, i, u: (
                 lambda c: (np.add.at(c, i, u), c)[1])(a.copy()),
             grad_inputs=[0]),
    TestCase("reverse_sequence",
             [R.randn(2, 4, 3).astype(np.float32),
              np.asarray([2, 4], np.int32)],
             {"seq_axis": 1, "batch_axis": 0},
             expected_fn=lambda x, l: np.stack(
                 [np.concatenate([x[b, :l[b]][::-1], x[b, l[b]:]])
                  for b in range(2)]),
             grad_inputs=[0]),
    # losses
    TestCase("softmax_cross_entropy", [ONEHOT, LOGITS],
             expected_fn=lambda y, z:
             (-(y * np.log(_softmax(z))).sum(-1)).mean()),
    TestCase("sparse_softmax_cross_entropy",
             [np.asarray([1, 0, 3, 2, 5], np.int32), LOGITS],
             expected_fn=lambda y, z: np.mean(
                 [-np.log(_softmax(z))[i, y[i]] for i in range(5)]),
             grad_inputs=[1]),
    TestCase("sigmoid_cross_entropy", [BIN, LOGITS],
             expected_fn=lambda y, z: np.mean(
                 np.maximum(z, 0) - z * y
                 + np.log1p(np.exp(-np.abs(z))))),
    TestCase("mean_squared_error", [ONEHOT, PROBS],
             expected_fn=lambda a, b: ((a - b) ** 2).mean()),
    TestCase("absolute_difference", [ONEHOT, PROBS],
             gradient_check=False,
             expected_fn=lambda a, b: np.abs(a - b).mean()),
    TestCase("huber_loss", [ONEHOT, PROBS], {"delta": 0.3},
             expected_fn=lambda a, b: np.where(
                 np.abs(a - b) <= 0.3, 0.5 * (a - b) ** 2,
                 0.3 * (np.abs(a - b) - 0.15)).mean()),
    TestCase("log_loss", [BIN, PROBS],
             expected_fn=lambda y, p: -np.mean(
                 y * np.log(p + 1e-7)
                 + (1 - y) * np.log(1 - p + 1e-7))),
    TestCase("hinge_loss", [BIN, LOGITS], gradient_check=False),
    TestCase("cosine_distance", [ONEHOT + 0.1, PROBS],
             gradient_check=True),
    # normalization extras
    TestCase("standardize", [A], {"axis": -1},
             expected_fn=lambda a:
             (a - a.mean(-1, keepdims=True))
             / np.maximum(a.std(-1, keepdims=True), 1e-12)),
    TestCase("moments", [A], {"axis": (0,)},
             expected_fn=lambda a: (a.mean(0), a.var(0)),
             gradient_check=False),
    TestCase("lrn", [IMG], max_entries=4),
    # convolution variants (gradient check is the content)
    TestCase("conv1d", [R.randn(2, 8, 3).astype(np.float32),
                        (R.randn(3, 3, 4) * 0.3).astype(np.float32)],
             {"stride": 1, "padding": "SAME"}, max_entries=4),
    TestCase("conv3d",
             [R.randn(1, 4, 4, 4, 2).astype(np.float32),
              (R.randn(2, 2, 2, 2, 3) * 0.3).astype(np.float32)],
             {"stride": (1, 1, 1), "padding": "VALID"},
             max_entries=2),
    TestCase("depthwise_conv2d",
             [IMG, (R.randn(3, 3, 3, 2) * 0.3).astype(np.float32)],
             max_entries=4),
    TestCase("separable_conv2d",
             [IMG, (R.randn(3, 3, 3, 1) * 0.3).astype(np.float32),
              (R.randn(1, 1, 3, 4) * 0.3).astype(np.float32)],
             max_entries=4),
    TestCase("deconv2d",
             [R.randn(1, 4, 4, 2).astype(np.float32),
              (R.randn(2, 2, 2, 3) * 0.3).astype(np.float32)],
             {"stride": (2, 2)}, max_entries=4),
    TestCase("upsampling2d", [IMG], {"scale": 2},
             expected_fn=lambda x: np.repeat(
                 np.repeat(x, 2, 1), 2, 2)),
    TestCase("im2col", [IMG], {"kernel": (2, 2)},
             gradient_check=False),
    TestCase("max_pool1d", [R.randn(2, 8, 3).astype(np.float32)],
             {"kernel": 2, "stride": 2},
             gradient_check=False),
    TestCase("avg_pool1d", [R.randn(2, 8, 3).astype(np.float32)],
             {"kernel": 2, "stride": 2}, max_entries=3),
    TestCase("max_pool3d",
             [R.randn(1, 4, 4, 4, 2).astype(np.float32)],
             {"kernel": (2, 2, 2), "stride": (2, 2, 2)},
             gradient_check=False),
    TestCase("avg_pool3d",
             [R.randn(1, 4, 4, 4, 2).astype(np.float32)],
             {"kernel": (2, 2, 2), "stride": (2, 2, 2)},
             max_entries=2),
    # image
    TestCase("resize_bilinear", [IMG], {"size": (12, 12)},
             gradient_check=False),
    TestCase("resize_nearest", [IMG], {"size": (12, 12)},
             gradient_check=False),
    TestCase("extract_image_patches", [IMG],
             {"kernel": (2, 2), "stride": (2, 2)},
             gradient_check=False),
    # linalg
    TestCase("cholesky", [SPD],
             expected_fn=np.linalg.cholesky, fwd_tol=1e-4,
             gradient_check=False),
    TestCase("matrix_inverse", [SQ],
             expected_fn=np.linalg.inv, fwd_tol=1e-3,
             gradient_check=False),
    TestCase("matrix_determinant", [SQ],
             expected_fn=np.linalg.det, fwd_tol=1e-2,
             gradient_check=False),
    TestCase("trace", [SQ], expected_fn=np.trace),
    TestCase("diag", [R.randn(4).astype(np.float32)],
             expected_fn=np.diag),
    TestCase("diag_part", [SQ], expected_fn=np.diag),
    TestCase("solve", [SPD, R.randn(4, 2).astype(np.float32)],
             expected_fn=np.linalg.solve, fwd_tol=1e-3,
             gradient_check=False),
    # recurrent cells (gradient check is the content)
    TestCase("lstm_cell",
             [R.randn(2, 3).astype(np.float32),
              R.randn(2, 4).astype(np.float32),
              R.randn(2, 4).astype(np.float32),
              (R.randn(3, 16) * 0.3).astype(np.float32),
              (R.randn(4, 16) * 0.3).astype(np.float32),
              np.zeros(16, np.float32)], max_entries=4),
    TestCase("gru_cell",
             [R.randn(2, 3).astype(np.float32),
              R.randn(2, 4).astype(np.float32),
              (R.randn(3, 12) * 0.3).astype(np.float32),
              (R.randn(4, 12) * 0.3).astype(np.float32),
              np.zeros(12, np.float32)], max_entries=4),
    # remaining transcendentals
    TestCase("asinh", [A], expected_fn=np.arcsinh),
    TestCase("acosh", [P + 1.0], expected_fn=np.arccosh),
    TestCase("atanh", [np.clip(A * 0.3, -0.7, 0.7)],
             expected_fn=np.arctanh),
    TestCase("round", [A * 3], expected_fn=np.round,
             gradient_check=False),
    # linalg decompositions (forward reconstruction checks)
    TestCase("lu", [SQ], gradient_check=False),
    TestCase("qr", [SQ], gradient_check=False),
    TestCase("svd", [SQ], gradient_check=False),
    TestCase("triangular_solve",
             [np.tril(SPD).astype(np.float32),
              R.randn(4, 2).astype(np.float32)],
             {"lower": True}, gradient_check=False, fwd_tol=1e-3),
    # compression codec round-trip semantics
    TestCase("encode_threshold",
             [np.asarray([0.5, -0.01, 0.02, -0.6], np.float32)],
             {"threshold": 0.1}, gradient_check=False),
    # unsorted segment family (ids deliberately unsorted)
    TestCase("unsorted_segment_sum",
             [np.asarray([1., 2., 3., 4.], np.float32),
              np.asarray([1, 0, 1, 0], np.int32)],
             {"num_segments": 2},
             expected=[np.asarray([6., 4.], np.float32)],
             gradient_check=False),
    TestCase("unsorted_segment_max",
             [np.asarray([1., 5., 3., 4.], np.float32),
              np.asarray([1, 0, 1, 0], np.int32)],
             {"num_segments": 2},
             expected=[np.asarray([5., 3.], np.float32)],
             gradient_check=False),
    TestCase("unsorted_segment_mean",
             [np.asarray([1., 2., 3., 4.], np.float32),
              np.asarray([1, 0, 1, 0], np.int32)],
             {"num_segments": 2},
             expected=[np.asarray([3., 2.], np.float32)],
             gradient_check=False),
    TestCase("unsorted_segment_prod",
             [np.asarray([1., 2., 3., 4.], np.float32),
              np.asarray([1, 0, 1, 0], np.int32)],
             {"num_segments": 2},
             expected=[np.asarray([8., 3.], np.float32)],
             gradient_check=False),
    TestCase("unsorted_segment_sqrt_n",
             [np.asarray([1., 2., 3., 4.], np.float32),
              np.asarray([1, 0, 1, 0], np.int32)],
             {"num_segments": 2},
             expected=[np.asarray([6. / np.sqrt(2.), 4. / np.sqrt(2.)],
                                  np.float32)],
             gradient_check=False),
    TestCase("segment_prod",
             [np.asarray([2., 3., 4.], np.float32),
              np.asarray([0, 0, 1], np.int32)],
             {"num_segments": 2},
             expected=[np.asarray([6., 4.], np.float32)],
             gradient_check=False),
    # bit rotations (uint32 semantics)
    TestCase("cyclic_shift_left",
             [np.asarray([1, 2 ** 31], np.uint32),
              np.asarray([1, 1], np.uint32)],
             expected=[np.asarray([2, 1], np.uint32)],
             gradient_check=False),
    TestCase("cyclic_shift_right",
             [np.asarray([1, 4], np.uint32),
              np.asarray([1, 1], np.uint32)],
             expected=[np.asarray([2 ** 31, 2], np.uint32)],
             gradient_check=False),
    # signed int8: arithmetic shift would sign-fill; rotation must wrap
    TestCase("cyclic_shift_right",
             [np.asarray([-128, 2], np.int8),
              np.asarray([1, 1], np.int8)],
             expected=[np.asarray([64, 1], np.int8)],
             gradient_check=False),
    TestCase("cyclic_shift_left",
             [np.asarray([-128, 1], np.int8),
              np.asarray([1, 7], np.int8)],
             expected=[np.asarray([1, -128], np.int8)],
             gradient_check=False),
    TestCase("fmod",
             [np.asarray([5.5, -5.5], np.float32),
              np.asarray([3.0, 3.0], np.float32)],
             expected=[np.asarray([2.5, -2.5], np.float32)],
             gradient_check=False),
    TestCase("scatter_nd_update",
             [np.asarray([1., 2., 3., 4.], np.float32),
              np.asarray([[0], [2]], np.int32),
              np.asarray([9., 8.], np.float32)],
             expected=[np.asarray([9., 2., 8., 4.], np.float32)],
             gradient_check=False),
    TestCase("unsorted_segment_min",
             [np.asarray([5., 2., 3., 4.], np.float32),
              np.asarray([1, 0, 1, 0], np.int32)],
             {"num_segments": 2},
             expected=[np.asarray([2., 3.], np.float32)],
             gradient_check=False),
    TestCase("sru_cell",
             [(R.randn(2, 4) * 0.3).astype(np.float32),
              np.zeros((2, 4), np.float32),
              (R.randn(4, 12) * 0.3).astype(np.float32),
              np.zeros(12, np.float32)], max_entries=4),
    # block rearrangement + shape ops
    TestCase("space_to_depth",
             [np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)],
             {"block_size": 2}, gradient_check=False),
    TestCase("depth_to_space",
             [np.arange(16, dtype=np.float32).reshape(1, 2, 2, 4)],
             {"block_size": 2}, gradient_check=False),
    TestCase("reverse", [np.asarray([[1., 2., 3.], [4., 5., 6.]],
                                    np.float32)],
             {"axes": [1]},
             expected=[np.asarray([[3., 2., 1.], [6., 5., 4.]],
                                  np.float32)]),
    TestCase("roll", [np.asarray([1., 2., 3., 4.], np.float32)],
             {"shift": [1], "axes": [0]},
             expected=[np.asarray([4., 1., 2., 3.], np.float32)]),
    TestCase("scatter_nd",
             [np.asarray([[1], [3]], np.int32),
              np.asarray([9., 7.], np.float32)],
             {"shape": [5]},
             expected=[np.asarray([0., 9., 0., 7., 0.], np.float32)],
             gradient_check=False),
    TestCase("invert_permutation",
             [np.asarray([2, 0, 1], np.int32)],
             expected=[np.asarray([1, 2, 0], np.int32)],
             gradient_check=False),
    TestCase("matrix_diag", [np.asarray([1., 2., 3.], np.float32)],
             expected=[np.diag([1., 2., 3.]).astype(np.float32)]),
    TestCase("matrix_diag_part",
             [np.asarray([[1., 9.], [8., 2.]], np.float32)],
             expected=[np.asarray([1., 2.], np.float32)]),
    # full-sequence recurrent ops (scan-based)
    TestCase("lstm_layer",
             [(R.randn(2, 5, 3) * 0.3).astype(np.float32),
              np.zeros((2, 4), np.float32),
              np.zeros((2, 4), np.float32),
              (R.randn(3, 16) * 0.3).astype(np.float32),
              (R.randn(4, 16) * 0.3).astype(np.float32),
              np.zeros(16, np.float32)], max_entries=4),
    TestCase("sru",
             [(R.randn(2, 5, 4) * 0.3).astype(np.float32),
              np.zeros((2, 4), np.float32),
              (R.randn(4, 12) * 0.3).astype(np.float32),
              np.zeros(12, np.float32)], max_entries=4),
]


def _dpa_expected(q, k, v):
    s = np.einsum("btd,bsd->bts", q, k) / np.sqrt(q.shape[-1])
    return np.einsum("bts,bsd->btd", _softmax(s), v)


def _mha_expected(x, wq, wk, wv, wo, h):
    b, t, _ = x.shape

    def split(a):
        return a.reshape(b, t, h, -1).transpose(0, 2, 1, 3)

    q, k, v = split(x @ wq), split(x @ wk), split(x @ wv)
    s = np.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(q.shape[-1])
    o = np.einsum("bhts,bhsd->bhtd", _softmax(s), v)
    return o.transpose(0, 2, 1, 3).reshape(b, t, -1) @ wo


_QKV = [(R.randn(2, 3, 4) * 0.5).astype(np.float32) for _ in range(3)]
_MHX = (R.randn(2, 3, 8) * 0.5).astype(np.float32)
_MHW = [(R.randn(8, 8) * 0.3).astype(np.float32) for _ in range(4)]

# batch 3 (round-2 verdict: ratchet the floor) — attention, image,
# indexing, compression ops previously covered only by their dedicated
# suites now also carry opvalidation ground truth
CASES += [
    TestCase("dot_product_attention", _QKV,
             expected=[_dpa_expected(*_QKV)]),
    TestCase("multi_head_dot_product_attention",
             [_MHX] + _MHW, {"num_heads": 2},
             expected=[_mha_expected(_MHX, *_MHW, h=2)]),
    TestCase("index", [A],
             {"spec": [{"kind": "int", "i": 1},
                       {"kind": "slice", "begin": 0, "end": 4,
                        "stride": 2}]},
             expected=[A[1, 0:4:2]]),
    TestCase("decode_threshold", [A], expected=[A],
             gradient_check=False),
    # exact-grid crop: box [0,0,1,1] at the full crop size samples
    # integer coordinates, so bilinear == identity
    TestCase("crop_and_resize",
             [IMG, np.asarray([[0., 0., 1., 1.]], np.float32),
              np.asarray([0], np.int32)], {"crop_size": (6, 6)},
             expected=[IMG[0:1]], gradient_check=False),
    TestCase("non_max_suppression",
             [np.asarray([[0, 0, 1, 1], [0, 0, 1, 1],
                          [2, 2, 3, 3], [0, 0, .9, .9]],
                         np.float32),
              np.asarray([.9, .8, .7, .6], np.float32)],
             {"max_output_size": 3, "iou_threshold": 0.5},
             expected=[np.asarray([0, 2, -1], np.int32)],
             gradient_check=False),
]


@pytest.mark.parametrize(
    "tc", CASES, ids=[f"{c.op}_{i}" for i, c in enumerate(CASES)])
def test_op(tc):
    validate(tc)


def test_combined_coverage_floor():
    """Batches 1+2 together must keep the registry coverage ratchet."""
    from test_opvalidation import CASES as CASES1
    for tc in CASES1 + CASES:
        validate(tc)
    rep = coverage_report()
    assert rep["covered"] >= 220, (rep["covered"],
                                   rep["missing"][:30])
    assert rep["fraction"] >= 0.95, rep["fraction"]
