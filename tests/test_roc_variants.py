"""ROCBinary / ROCMultiClass / AUPRC tests (reference test style:
ROCBinaryTest / ROCTest in org.nd4j.evaluation, SURVEY.md J10)."""
import numpy as np

from deeplearning4j_tpu.evaluation import ROC, ROCBinary, ROCMultiClass


class TestROCAuprc:
    def test_perfect_ranking(self):
        roc = ROC()
        roc.eval(np.array([0, 0, 1, 1]), np.array([0.1, 0.2, 0.8, 0.9]))
        assert roc.calculate_auc() == 1.0
        assert roc.calculate_auprc() == 1.0

    def test_random_ranking_auprc_near_base_rate(self):
        rng = np.random.RandomState(0)
        y = (rng.rand(4000) < 0.3).astype(float)
        s = rng.rand(4000)
        roc = ROC()
        roc.eval(y, s)
        assert abs(roc.calculate_auprc() - 0.3) < 0.05
        assert abs(roc.calculate_auc() - 0.5) < 0.05


class TestROCBinary:
    def test_per_output_auc(self):
        labels = np.array([[1, 0], [1, 0], [0, 1], [0, 1]], float)
        # output 0 ranks perfectly; output 1 ranks inversely
        preds = np.array([[0.9, 0.9], [0.8, 0.8], [0.1, 0.1],
                          [0.2, 0.2]], float)
        rb = ROCBinary()
        rb.eval(labels, preds)
        assert rb.num_labels() == 2
        assert rb.calculate_auc(0) == 1.0
        assert rb.calculate_auc(1) == 0.0
        assert rb.calculate_average_auc() == 0.5

    def test_incremental_accumulation(self):
        rng = np.random.RandomState(1)
        rb = ROCBinary()
        all_y, all_s = [], []
        for _ in range(5):
            y = (rng.rand(50, 3) < 0.5).astype(float)
            s = np.clip(y * 0.7 + 0.3 * rng.rand(50, 3), 0, 1)
            rb.eval(y, s)
            all_y.append(y)
            all_s.append(s)
        ref = ROCBinary()
        ref.eval(np.concatenate(all_y), np.concatenate(all_s))
        for i in range(3):
            assert abs(rb.calculate_auc(i) - ref.calculate_auc(i)) < 1e-12


    def test_time_series_with_timestep_mask(self):
        """[b, t, c] multi-label series with a [b, t] mask flattens
        through the mask (regression: mask was misindexed per column)."""
        labels = np.zeros((2, 3, 2))
        preds = np.zeros((2, 3, 2))
        labels[0, :2] = [[1, 0], [0, 1]]
        preds[0, :2] = [[0.9, 0.2], [0.1, 0.8]]
        labels[1, 0] = [1, 1]
        preds[1, 0] = [0.8, 0.9]
        labels[0, 2] = [0, 1]          # masked garbage, inverted
        preds[0, 2] = [0.99, 0.01]
        mask = np.array([[1, 1, 0], [1, 0, 0]], float)
        rb = ROCBinary()
        rb.eval(labels, preds, mask=mask)
        assert rb.calculate_auc(0) == 1.0
        assert rb.calculate_auc(1) == 1.0


class TestROCMultiClass:
    def test_one_vs_all(self):
        labels = np.eye(3)[[0, 1, 2, 0, 1, 2]].astype(float)
        preds = labels * 0.8 + 0.1  # perfectly informative
        rmc = ROCMultiClass()
        rmc.eval(labels, preds)
        assert rmc.num_classes() == 3
        for c in range(3):
            assert rmc.calculate_auc(c) == 1.0
        assert rmc.calculate_average_auc() == 1.0

    def test_time_series_with_mask(self):
        # [b, t, c]: masked timesteps carry garbage that would break AUC
        labels = np.zeros((2, 3, 2))
        preds = np.zeros((2, 3, 2))
        labels[0, :2] = [[1, 0], [0, 1]]
        preds[0, :2] = [[0.9, 0.1], [0.2, 0.8]]
        labels[1, :1] = [[1, 0]]
        preds[1, :1] = [[0.7, 0.3]]
        # garbage in masked region: inverted scores
        labels[0, 2] = [1, 0]
        preds[0, 2] = [0.0, 1.0]
        mask = np.array([[1, 1, 0], [1, 0, 0]], float)
        rmc = ROCMultiClass()
        rmc.eval(labels, preds, mask=mask)
        assert rmc.calculate_auc(0) == 1.0
        assert rmc.calculate_auc(1) == 1.0
