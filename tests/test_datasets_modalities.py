"""Dataset + DataVec modality breadth tests (SURVEY.md D13, V4)."""
import wave

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.vision import (
    Cifar10DataSetIterator, EmnistDataSetIterator,
    TinyImageNetDataSetIterator)
from deeplearning4j_tpu.datavec.audio import (WavFileRecordReader,
                                              log_mel, read_wav,
                                              stft_power)
from deeplearning4j_tpu.datavec.codec import CodecRecordReader
from deeplearning4j_tpu.datavec.nlpvec import (BagOfWordsVectorizer,
                                               TfidfVectorizer)
from deeplearning4j_tpu.datavec.split import FileSplit


class TestVisionIterators:
    def test_cifar10(self):
        it = Cifar10DataSetIterator(8, train=True, num_examples=32)
        ds = it.next()
        assert ds.features.shape == (8, 32, 32, 3)
        assert ds.labels.shape == (8, 10)
        n = ds.num_examples()
        total = n
        while it.has_next():
            total += it.next().num_examples()
        assert total == 32
        it.reset()
        assert it.has_next()

    def test_emnist_sets(self):
        it = EmnistDataSetIterator("LETTERS", 4, num_examples=8)
        ds = it.next()
        assert ds.features.shape == (4, 28 * 28)
        assert ds.labels.shape == (4, 26)
        with pytest.raises(ValueError, match="unknown EMNIST"):
            EmnistDataSetIterator("NOPE", 4)

    def test_tiny_imagenet(self):
        it = TinyImageNetDataSetIterator(4, num_examples=8)
        ds = it.next()
        assert ds.features.shape == (4, 64, 64, 3)
        assert ds.labels.shape == (4, 200)

    def test_deterministic_synthetic(self):
        a = Cifar10DataSetIterator(4, num_examples=8, seed=5).next()
        b = Cifar10DataSetIterator(4, num_examples=8, seed=5).next()
        np.testing.assert_array_equal(np.asarray(a.features),
                                      np.asarray(b.features))


class TestAudio:
    def _write_wav(self, path, sr=8000, seconds=0.5, freq=440.0):
        t = np.arange(int(sr * seconds)) / sr
        x = (np.sin(2 * np.pi * freq * t) * 0.5 * 32767) \
            .astype(np.int16)
        with wave.open(str(path), "wb") as w:
            w.setnchannels(1)
            w.setsampwidth(2)
            w.setframerate(sr)
            w.writeframes(x.tobytes())
        return x

    def test_wav_roundtrip(self, tmp_path):
        p = tmp_path / "tone.wav"
        raw = self._write_wav(p)
        x, sr = read_wav(p)
        assert sr == 8000
        np.testing.assert_allclose(x, raw / 32768.0, atol=1e-4)

    def test_spectrogram_peak_at_tone(self, tmp_path):
        p = tmp_path / "tone.wav"
        self._write_wav(p, sr=8000, freq=1000.0)
        x, sr = read_wav(p)
        pw = stft_power(x, 512, 256)
        peak_bin = np.asarray(pw.mean(0)).argmax()
        peak_hz = peak_bin * sr / 512
        assert abs(peak_hz - 1000.0) < 40
        lm = log_mel(pw, sr, n_mels=20)
        assert lm.shape == (pw.shape[0], 20)
        assert np.isfinite(lm).all()

    def test_record_reader(self, tmp_path):
        for i in range(2):
            self._write_wav(tmp_path / f"a{i}.wav",
                            freq=440.0 * (i + 1))
        rr = WavFileRecordReader(features="logmel")
        rr.initialize(FileSplit(str(tmp_path), ["wav"]))
        recs = list(rr)
        assert len(recs) == 2
        assert recs[0][0].value.ndim == 2


class TestCodec:
    def test_npy_frames(self, tmp_path):
        frames = np.random.RandomState(0).rand(10, 8, 8, 3) \
            .astype(np.float32)
        np.save(tmp_path / "clip.npy", frames)
        rr = CodecRecordReader(start_frame=2, num_frames=3, rate=2)
        rr.initialize(FileSplit(str(tmp_path), ["npy"]))
        seq = rr.next_sequence()
        assert len(seq) == 3
        np.testing.assert_array_equal(seq[0][0].value, frames[2])
        np.testing.assert_array_equal(seq[1][0].value, frames[4])

    def test_unsupported_container_errors(self, tmp_path):
        (tmp_path / "v.mp4").write_bytes(b"x")
        rr = CodecRecordReader()
        rr.initialize(FileSplit(str(tmp_path), ["mp4"]))
        with pytest.raises(NotImplementedError, match="ffmpeg"):
            rr.next_sequence()

    @staticmethod
    def _write_raw_avi(path, frames_rgb):
        """Minimal RIFF/AVI with uncompressed bottom-up BGR frames
        (includes a strh 'vids' header like real muxers)."""
        import struct
        t, h, w, _ = frames_rgb.shape
        row = (w * 3 + 3) & ~3
        strh = b"vids" + b"DIB " + b"\0" * 48
        strf = struct.pack("<IiiHHI", 40, w, h, 1, 24, 0) + b"\0" * 20

        def chunk(fourcc, body):
            pad = b"\0" if len(body) % 2 else b""
            return fourcc + struct.pack("<I", len(body)) + body + pad

        movi_frames = b""
        for f in frames_rgb:
            bgr = f[..., ::-1]
            rows = b"".join(
                bgr[y].tobytes() + b"\0" * (row - w * 3)
                for y in range(h - 1, -1, -1))   # bottom-up
            movi_frames += chunk(b"00db", rows)
        strl = b"strl" + chunk(b"strh", strh) + chunk(b"strf", strf)
        hdrl = b"hdrl" + chunk(b"LIST", strl)
        movi = b"movi" + movi_frames
        body = b"AVI " + chunk(b"LIST", hdrl) + chunk(b"LIST", movi)
        with open(path, "wb") as fp:
            fp.write(b"RIFF" + struct.pack("<I", len(body)) + body)

    def test_raw_avi_frames(self, tmp_path):
        frames = np.random.RandomState(1).randint(
            0, 255, (4, 6, 5, 3), dtype=np.uint8)
        self._write_raw_avi(tmp_path / "clip.avi", frames)
        rr = CodecRecordReader()
        rr.initialize(FileSplit(str(tmp_path), ["avi"]))
        seq = rr.next_sequence()
        assert len(seq) == 4
        np.testing.assert_array_equal(seq[0][0].value, frames[0])
        np.testing.assert_array_equal(seq[3][0].value, frames[3])

    def test_gif_frames(self, tmp_path):
        pil = pytest.importorskip("PIL.Image")
        rng = np.random.RandomState(2)
        frames = rng.randint(0, 255, (3, 8, 8, 3), dtype=np.uint8)
        imgs = [pil.fromarray(f) for f in frames]
        imgs[0].save(tmp_path / "anim.gif", save_all=True,
                     append_images=imgs[1:], duration=100, loop=0)
        rr = CodecRecordReader()
        rr.initialize(FileSplit(str(tmp_path), ["gif"]))
        seq = rr.next_sequence()
        assert len(seq) == 3
        # GIF palettizes to 256 colors; frames survive approximately
        got = np.stack([s[0].value for s in seq]).astype(np.int32)
        assert np.abs(got - frames.astype(np.int32)).mean() < 16


class TestTextVectorizers:
    CORPUS = ["the cat sat on the mat",
              "the dog sat on the log",
              "cats and dogs"]

    def test_bag_of_words(self):
        v = BagOfWordsVectorizer()
        m = v.fit_transform(self.CORPUS)
        assert m.shape == (3, len(v.vocab))
        i_the = v.vocab["the"]
        assert m[0, i_the] == 2.0
        assert m[2, i_the] == 0.0

    def test_tfidf_downweights_common(self):
        v = TfidfVectorizer()
        m = v.fit_transform(self.CORPUS)
        # 'the' (2 docs) carries lower idf than 'cat' (1 doc)
        assert v.idf[v.vocab["the"]] < v.idf[v.vocab["cat"]]
        assert np.isfinite(m).all()
        # transform of unseen doc uses fitted vocab only
        u = v.transform("the purple cat")
        assert u.shape == (len(v.vocab),)
        assert u[v.vocab["cat"]] > 0
