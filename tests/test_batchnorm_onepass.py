"""One-pass BN statistics gating (nn/conf/layers.py BatchNormalization).

bf16/f16 activations take the fused single-read E[x]/E[x^2] path with
f32 accumulation; f32+ activations keep the accurate two-pass form —
the E[x^2]-E[x]^2 cancellation has no headroom at equal precision
(review finding: un-normalized inputs with |mean| >> std would see
catastrophic cancellation, possibly var clamped to 0).
"""
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.layers import BatchNormalization


def _forward(x, dtype):
    bn = BatchNormalization(n_in=x.shape[-1], n_out=x.shape[-1])
    nf = x.shape[-1]
    params = {"gamma": jnp.ones(nf, dtype), "beta": jnp.zeros(nf, dtype)}
    state = {"mean": jnp.zeros(nf, jnp.float32),
             "var": jnp.ones(nf, jnp.float32)}
    return bn.forward(params, jnp.asarray(x, dtype), training=True,
                      state=state)


class TestOnePassBN:
    def test_bf16_stats_match_reference(self):
        rng = np.random.RandomState(0)
        x = (rng.randn(64, 16) * 2 + 5).astype(np.float64)
        _, ns = _forward(x, jnp.bfloat16)
        # decay 0.9: new_mean = 0.1 * batch_mean
        got_mean = np.asarray(ns["mean"]) / 0.1
        got_var = (np.asarray(ns["var"]) - 0.9) / 0.1
        assert np.allclose(got_mean, x.mean(0), rtol=2e-2, atol=1e-2)
        assert np.allclose(got_var, x.var(0), rtol=5e-2, atol=1e-2)

    def test_f32_high_dynamic_range_stays_accurate(self):
        # mean ~1e4, std ~1: one-pass in f32 would lose the variance
        # entirely (cancellation); the two-pass branch must hold
        rng = np.random.RandomState(1)
        x = (rng.randn(256, 8) + 1e4).astype(np.float32)
        out, ns = _forward(x, jnp.float32)
        got_var = (np.asarray(ns["var"]) - 0.9) / 0.1
        ref_var = x.astype(np.float64).var(0)
        assert np.allclose(got_var, ref_var, rtol=1e-2), (got_var,
                                                          ref_var)
        # normalized output must have ~unit variance, not explode
        ov = np.asarray(out, np.float64).var(0)
        assert np.all(ov > 0.5) and np.all(ov < 2.0), ov

    def test_bf16_output_normalized(self):
        rng = np.random.RandomState(2)
        x = (rng.randn(128, 4) * 3 - 7).astype(np.float32)
        out, _ = _forward(x, jnp.bfloat16)
        o = np.asarray(out, np.float64)
        assert np.allclose(o.mean(0), 0, atol=5e-2)
        assert np.allclose(o.var(0), 1, atol=1e-1)
