"""Statistical validation of the RNG op domain (the one §4.3 domain
exact-value ground truth can't cover): distribution moments, range,
determinism-under-seed, dropout semantics."""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.registry import get_op

N = 200_000


def _run(op, attrs, seed=0, ins=()):
    attrs = dict(attrs)
    attrs["rng"] = jax.random.PRNGKey(seed)
    return np.asarray(get_op(op)(list(ins), attrs))


class TestRandomOps:
    def test_random_normal_moments(self):
        x = _run("random_normal", {"shape": (N,)})
        assert abs(x.mean()) < 0.02
        assert abs(x.std() - 1.0) < 0.02

    def test_random_uniform_range_and_mean(self):
        x = _run("random_uniform", {"shape": (N,), "min": 2.0,
                                    "max": 5.0})
        assert x.min() >= 2.0 and x.max() < 5.0
        assert abs(x.mean() - 3.5) < 0.02

    def test_random_bernoulli_rate(self):
        x = _run("random_bernoulli", {"shape": (N,), "prob": 0.3})
        assert set(np.unique(x)) <= {0.0, 1.0}
        assert abs(x.mean() - 0.3) < 0.01

    def test_seed_determinism(self):
        a = _run("random_normal", {"shape": (64,)}, seed=7)
        b = _run("random_normal", {"shape": (64,)}, seed=7)
        c = _run("random_normal", {"shape": (64,)}, seed=8)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_dropout_semantics(self):
        x = np.ones((N,), np.float32)
        y = _run("dropout", {"rate": 0.25, "training": True},
                 ins=(x,))
        kept = y != 0
        # inverted dropout: survivors scaled by 1/(1-rate)
        np.testing.assert_allclose(np.unique(y[kept]), [1 / 0.75],
                                   atol=1e-6)
        assert abs(kept.mean() - 0.75) < 0.01
        assert abs(y.mean() - 1.0) < 0.02        # expectation kept
        # inference mode: identity
        y_eval = _run("dropout", {"rate": 0.25, "training": False},
                      ins=(x,))
        np.testing.assert_array_equal(y_eval, x)
