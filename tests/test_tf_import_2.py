"""TF GraphDef import conformance, batch 2 (SURVEY.md S6/§4.4):
3D conv/pool, block rearrangement, segment/scatter, linalg, LRN,
cross-entropy ops. Same protocol as test_tf_import: freeze a
tf.function with the in-image TF, import, compare outputs."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from test_tf_import import _import_and_compare  # noqa: E402

R = np.random.RandomState(0)


class TestFullModelConformance:
    def test_keras_resnet50_graphdef(self):
        """Import a full Keras ResNet50 frozen GraphDef (~1800 nodes:
        Conv/BiasAdd/folded-BN/Pad/MaxPool/Mean/residual-Add/Softmax)
        and match TF's outputs — the §4.4 conformance protocol on the
        BASELINE config #2 architecture."""
        from tensorflow.python.framework.convert_to_constants import \
            convert_variables_to_constants_v2
        from deeplearning4j_tpu.modelimport.tensorflow import \
            TensorflowFrameworkImporter
        keras = tf.keras
        keras.utils.set_random_seed(0)
        m = keras.applications.ResNet50(weights=None,
                                        input_shape=(64, 64, 3),
                                        classes=10)
        cf = tf.function(
            lambda x: m(x, training=False)).get_concrete_function(
            tf.TensorSpec((2, 64, 64, 3), tf.float32))
        frozen = convert_variables_to_constants_v2(cf)
        gd = frozen.graph.as_graph_def().SerializeToString()
        x = R.randn(2, 64, 64, 3).astype(np.float32)
        res = frozen(tf.constant(x))
        want = np.asarray(res[0] if isinstance(res, (list, tuple))
                          else res)
        imp = TensorflowFrameworkImporter.run_import(
            gd, {"x": x.shape})
        out = sorted(n for n in imp.vars
                     if n.startswith("Identity"))[0]
        got = imp.output({"x": x}, [out])[out]
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)


class TestBreadthBatch2:
    def test_space_depth_roundtrip(self):
        x = R.randn(2, 4, 4, 3).astype(np.float32)

        def fn(x):
            return tf.nn.depth_to_space(tf.nn.space_to_depth(x, 2), 2)

        _import_and_compare(fn, {"x": x})

    def test_space_depth_nchw(self):
        """NCHW data_format (round-2 verdict gap: NHWC-only).  The
        in-image TF has no CPU kernel for NCHW block rearrangement, so
        ground truth is the layout identity: NCHW s2d∘d2s == id (and a
        numpy check that d2s actually moved data)."""
        from deeplearning4j_tpu.modelimport.tensorflow import \
            TensorflowFrameworkImporter
        x = R.randn(2, 12, 4, 4).astype(np.float32)

        def fn(x):
            y = tf.nn.depth_to_space(x, 2, data_format="NCHW")
            z = tf.nn.space_to_depth(y, 2, data_format="NCHW")
            return y, z

        cf = tf.function(fn).get_concrete_function(
            tf.TensorSpec((2, 12, 4, 4), tf.float32))
        gd = cf.graph.as_graph_def().SerializeToString()
        imp = TensorflowFrameworkImporter.run_import(
            gd, {"x": (2, 12, 4, 4)})
        outs = sorted(n for n in imp.vars if n.startswith("Identity"))
        res = imp.output({"x": x}, outs[:2])
        got_y, got_z = res[outs[0]], res[outs[1]]
        if got_y.shape != (2, 3, 8, 8):
            got_y, got_z = got_z, got_y
        # NCHW DepthToSpace (DCR): C splits as [b, b, C/(b*b)]
        want_y = (x.reshape(2, 2, 2, 3, 4, 4)
                  .transpose(0, 3, 4, 1, 5, 2).reshape(2, 3, 8, 8))
        np.testing.assert_allclose(got_y, want_y, atol=1e-6)
        np.testing.assert_allclose(got_z, x, atol=1e-6)

    def test_gather_batch_dims(self):
        """GatherV2 batch_dims != 0 (round-2 verdict gap)."""
        params = R.randn(3, 5, 4).astype(np.float32)
        idx = R.randint(0, 5, (3, 2)).astype(np.int32)

        def fn(p, i):
            return tf.gather(p, i, axis=1, batch_dims=1)

        _import_and_compare(fn, {"p": params, "i": idx})

    def test_gather_batch_dims_negative_axis(self):
        """axis=-1 with batch_dims (regression: the batch offset was
        applied to the raw negative axis, gathering the wrong dim)."""
        params = R.randn(3, 5, 4).astype(np.float32)
        idx = R.randint(0, 4, (3, 2)).astype(np.int32)

        def fn(p, i):
            return tf.gather(p, i, axis=-1, batch_dims=1)

        _import_and_compare(fn, {"p": params, "i": idx})

    def test_cumsum_exclusive_reverse(self):
        x = R.randn(3, 6).astype(np.float32)

        def fn(x):
            a = tf.cumsum(x, axis=1, exclusive=True)
            b = tf.cumsum(x, axis=1, reverse=True)
            return a + tf.cumsum(b, axis=0, exclusive=True,
                                 reverse=True)

        _import_and_compare(fn, {"x": x})

    def test_conv3d_pool3d(self):
        x = R.randn(1, 6, 6, 6, 2).astype(np.float32)
        w = (R.randn(3, 3, 3, 2, 4) * 0.3).astype(np.float32)

        def fn(x):
            y = tf.nn.conv3d(x, w, [1, 1, 1, 1, 1], "SAME")
            return tf.nn.max_pool3d(y, 2, 2, "VALID")

        _import_and_compare(fn, {"x": x})

    def test_conv3d_dilated(self):
        """Dilated Conv3D (regression: dilation was silently dropped)."""
        x = R.randn(1, 8, 8, 8, 1).astype(np.float32)
        w = (R.randn(2, 2, 2, 1, 2) * 0.3).astype(np.float32)

        def fn(x):
            return tf.nn.conv3d(x, w, [1, 1, 1, 1, 1], "VALID",
                                dilations=[1, 2, 2, 2, 1])

        _import_and_compare(fn, {"x": x})

    def test_matrix_diag_nonzero_k_rejected(self):
        x = R.randn(3, 4, 4).astype(np.float32)

        def fn(x):
            return tf.linalg.diag_part(x, k=1)

        with pytest.raises(NotImplementedError, match="k=0"):
            _import_and_compare(fn, {"x": x})

    def test_reverse_roll(self):
        x = R.randn(3, 5).astype(np.float32)

        def fn(x):
            return tf.roll(tf.reverse(x, axis=[1]), shift=[2], axis=[0])

        _import_and_compare(fn, {"x": x})

    def test_cumprod_matrixdiag(self):
        x = (R.rand(3, 4).astype(np.float32) + 0.5)

        def fn(x):
            return tf.linalg.diag(tf.math.cumprod(x, axis=1))

        _import_and_compare(fn, {"x": x})

    def test_scatter_nd_invert_permutation(self):
        idx = np.asarray([[1], [3]], np.int32)
        upd = np.asarray([9.0, 7.0], np.float32)

        def fn(u):
            s = tf.scatter_nd(idx, u, [5])
            p = tf.constant([2, 0, 1, 4, 3], tf.int32)
            return tf.gather(s, tf.math.invert_permutation(p))

        _import_and_compare(fn, {"u": upd})

    def test_segment_ops(self):
        x = R.randn(6, 3).astype(np.float32)
        seg = np.asarray([0, 0, 1, 1, 1, 2], np.int32)

        def fn(x):
            return tf.math.segment_sum(x, seg)

        _import_and_compare(fn, {"x": x})

    def test_unsorted_segment(self):
        x = R.randn(6, 3).astype(np.float32)
        seg = np.asarray([2, 0, 1, 0, 1, 2], np.int32)

        def fn(x):
            return tf.math.unsorted_segment_sum(x, seg, 3)

        _import_and_compare(fn, {"x": x})

    def test_lrn(self):
        x = R.randn(2, 4, 4, 8).astype(np.float32)

        def fn(x):
            return tf.nn.local_response_normalization(
                x, depth_radius=2, bias=1.0, alpha=1e-3, beta=0.75)

        _import_and_compare(fn, {"x": x})

    def test_cholesky_inverse(self):
        a = R.randn(4, 4).astype(np.float32)
        spd = (a @ a.T + 4 * np.eye(4)).astype(np.float32)

        def fn(m):
            return tf.linalg.cholesky(m) + tf.linalg.inv(m)

        _import_and_compare(fn, {"m": spd}, atol=1e-3)

    def test_sparse_softmax_xent(self):
        logits = R.randn(5, 7).astype(np.float32)
        labels = np.asarray([0, 3, 6, 2, 1], np.int64)

        def fn(lg):
            return tf.nn.sparse_softmax_cross_entropy_with_logits(
                labels=labels, logits=lg)

        _import_and_compare(fn, {"lg": logits})

    def test_softmax_xent(self):
        logits = R.randn(5, 7).astype(np.float32)
        labels = np.eye(7, dtype=np.float32)[[0, 3, 6, 2, 1]]

        def fn(lg):
            return tf.nn.softmax_cross_entropy_with_logits(
                labels=labels, logits=lg)

        _import_and_compare(fn, {"lg": logits})


class TestRound4ImporterGaps:
    """Round-3 verdict ask #6: Cumprod exclusive/reverse and
    NCDHW-layout Conv3D/Pool3D (the transpose-wrap treatment the 2D
    ops and SpaceToDepth already had)."""

    @pytest.mark.parametrize("exclusive,reverse", [
        (False, False), (True, False), (False, True), (True, True)])
    def test_cumprod_modes(self, exclusive, reverse):
        x = (R.rand(3, 5).astype(np.float32) + 0.5)

        def fn(x):
            return tf.math.cumprod(x, axis=1, exclusive=exclusive,
                                   reverse=reverse)

        _import_and_compare(fn, {"x": x})

    def test_cumsum_modes_still_green(self):
        x = R.randn(2, 6).astype(np.float32)

        def fn(x):
            return tf.math.cumsum(x, axis=1, exclusive=True,
                                  reverse=True)

        _import_and_compare(fn, {"x": x})

    def _import_ncdhw(self, fn, x, want):
        from test_tf_import import freeze
        from deeplearning4j_tpu.modelimport.tensorflow import \
            TensorflowFrameworkImporter
        gd_bytes, _ = freeze(
            fn, tf.TensorSpec(x.shape, tf.float32))
        imp = TensorflowFrameworkImporter.run_import(
            gd_bytes, {"x": x.shape})
        out = sorted(n for n in imp.vars
                     if n.startswith("Identity"))[0]
        got = imp.output({"x": x}, [out])[out]
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)

    def test_conv3d_ncdhw(self):
        """Graph built NCDHW; ground truth computed via the NDHWC
        twin + transposes (TF's CPU kernels are NDHWC-only, so the
        frozen NCDHW graph can't run on the host — exactly the
        situation an importer meets with GPU-exported graphs)."""
        x = R.randn(2, 3, 6, 6, 6).astype(np.float32)    # N C D H W
        w = (R.randn(3, 3, 3, 3, 4) * 0.3).astype(np.float32)

        def fn(x):
            return tf.nn.conv3d(x, w, strides=[1, 1, 1, 1, 1],
                                padding="SAME", data_format="NCDHW")

        want = tf.nn.conv3d(
            tf.transpose(tf.constant(x), [0, 2, 3, 4, 1]),
            w, [1, 1, 1, 1, 1], "SAME")
        want = np.transpose(np.asarray(want), [0, 4, 1, 2, 3])
        self._import_ncdhw(fn, x, want)

    def test_conv3d_ncdhw_strided(self):
        x = R.randn(1, 2, 8, 8, 8).astype(np.float32)
        w = (R.randn(2, 2, 2, 2, 3) * 0.3).astype(np.float32)

        def fn(x):
            return tf.nn.conv3d(x, w, strides=[1, 1, 2, 2, 2],
                                padding="VALID", data_format="NCDHW")

        want = tf.nn.conv3d(
            tf.transpose(tf.constant(x), [0, 2, 3, 4, 1]),
            w, [1, 2, 2, 2, 1], "VALID")
        want = np.transpose(np.asarray(want), [0, 4, 1, 2, 3])
        self._import_ncdhw(fn, x, want)

    @pytest.mark.parametrize("pool", ["max", "avg"])
    def test_pool3d_ncdhw(self, pool):
        x = R.randn(2, 3, 8, 8, 8).astype(np.float32)
        tf_pool = (tf.nn.max_pool3d if pool == "max"
                   else tf.nn.avg_pool3d)

        def fn(x):
            return tf_pool(x, ksize=2, strides=2, padding="VALID",
                           data_format="NCDHW")

        want = tf_pool(
            tf.transpose(tf.constant(x), [0, 2, 3, 4, 1]),
            ksize=2, strides=2, padding="VALID")
        want = np.transpose(np.asarray(want), [0, 4, 1, 2, 3])
        self._import_ncdhw(fn, x, want)
