"""Threshold-encoded update exchange (parallel.zero ENCODED — ISSUE
20) on the virtual 8-device CPU mesh, plus the low-precision serving
residency it shares a PR with.

Covers: encoded-vs-dense 20-step convergence under error feedback,
the bitwise dense-layout checkpoint round-trip restored onto a
DIFFERENT device count, the `DL4J_TPU_ENCODED_UPDATE` kill switch and
resolver fallbacks, and `param_dtype="bf16"|"int8"` serving residency
(resident bytes shrink; f32 stays bitwise, low-precision stays within
tolerance).
"""
import numpy as np
import pytest

import jax

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.common.telemetry import MetricsRegistry
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning.updaters import (ENCODED_KEY, Adam,
                                                  Sgd, is_encoded)
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.parallel import ParallelWrapper, UpdateExchange
from deeplearning4j_tpu.parallel.mesh import MeshFactory
from deeplearning4j_tpu.parallel.zero import (ensure_encoded_states,
                                              resolve_update_exchange,
                                              states_to_dense)


@pytest.fixture(autouse=True)
def _fresh_registry():
    MetricsRegistry._reset_for_tests()
    yield
    MetricsRegistry._reset_for_tests()


def _mlp(updater=None, seed=42):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(updater or Sgd(0.1))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return DataSet(x, y)


def _assert_tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- convergence under error feedback --------------------------------------
def test_encoded_tracks_dense_convergence_20_steps():
    """The satellite's stated tolerance: over 20 steps on identical
    batches, error-feedback residuals must keep the encoded loss
    trajectory within 0.05 absolute of the uncompressed dense run's,
    and the encoded loss must actually descend."""
    batches = [_data(64, seed=i % 4) for i in range(20)]
    # score on a training batch: a disjoint random-label probe set can
    # legitimately rise while the fit loss falls
    probe = _data(64, seed=0)
    finals = {}
    for mode in ("dense", "encoded"):
        net = _mlp(Adam(0.01), seed=7)
        pw = ParallelWrapper.Builder(net).workers(8) \
            .update_exchange(mode).build()
        first = None
        for ds in batches:
            pw.fit_batch(ds)
            if first is None:
                first = float(net.score(probe))
        finals[mode] = float(net.score(probe))
        if mode == "encoded":
            assert pw.update_exchange is UpdateExchange.ENCODED
            assert any(is_encoded(s)
                       for s in net.updater_states.values())
            assert finals[mode] < first, "encoded loss did not descend"
    assert abs(finals["encoded"] - finals["dense"]) < 0.05, finals


# -- checkpoint round-trip onto a different device count -------------------
def test_encoded_checkpoint_roundtrips_onto_different_device_count(
        tmp_path):
    """Checkpoints from an encoded run store the exact dense layout
    (params AND the error-feedback residual), restore bitwise, and the
    residual re-ravels losslessly for a different shard count."""
    from deeplearning4j_tpu.utils.serializer import ModelSerializer
    net = _mlp(Adam(0.01), seed=9)
    pw = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange("encoded").build()
    for i in range(3):
        pw.fit_batch(_data(64, seed=i))
    assert any(is_encoded(s) for s in net.updater_states.values())

    path = tmp_path / "enc.zip"
    ModelSerializer.write_model(net, path)
    restored = ModelSerializer.restore_multi_layer_network(path)

    # bitwise round-trip of params and the dense-layout updater state
    _assert_tree_equal(restored.params, net.params)
    live_dense = states_to_dense(net.params, net.updater_states)
    _assert_tree_equal(restored.updater_states, live_dense)

    # the dense residual re-ravels for a DIFFERENT device count and
    # converts back to the identical dense layout (pad zeros only)
    pw4 = ParallelWrapper.Builder(restored).workers(4) \
        .update_exchange("encoded").build()
    pw4.fit_batch(_data(64, seed=3))
    assert pw4.update_exchange is UpdateExchange.ENCODED
    assert pw4.n_workers == 4
    assert any(is_encoded(s)
               for s in restored.updater_states.values())
    assert np.isfinite(restored.score(_data(64, seed=3)))


def test_encoded_reravel_is_lossless_across_shard_counts():
    """ensure -> dense -> ensure(other count) -> dense is bitwise: the
    device-count portability claim, isolated from training noise."""
    from deeplearning4j_tpu.parallel.encoding import resolve_encoding
    net = _mlp(Adam(0.01), seed=3)
    pw = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange("encoded").build()
    pw.fit_batch(_data(64, seed=0))
    enc = resolve_encoding(None)
    dense8 = states_to_dense(net.params, net.updater_states)
    re4 = ensure_encoded_states(net.params, dense8, 4, enc)
    dense4 = states_to_dense(net.params, re4)
    _assert_tree_equal(dense4, dense8)


# -- kill switch and resolver fallbacks ------------------------------------
def test_encoded_kill_switch_demotes_to_sharded(monkeypatch):
    """DL4J_TPU_ENCODED_UPDATE=0 keeps the uncompressed sharded rung
    even when encoded was requested — the exchange still shards, it
    just stops compressing."""
    from deeplearning4j_tpu.common.environment import Environment
    mesh = MeshFactory.data_parallel()
    monkeypatch.setenv("DL4J_TPU_ENCODED_UPDATE", "0")
    Environment.reset()
    try:
        assert resolve_update_exchange(mesh, requested="encoded") \
            is UpdateExchange.SHARDED
        net = _mlp(Adam(0.01))
        pw = ParallelWrapper.Builder(net).workers(8) \
            .update_exchange("encoded").build()
        pw.fit_batch(_data(64))
        assert pw.update_exchange is UpdateExchange.SHARDED
        assert not any(is_encoded(s)
                       for s in net.updater_states.values())
    finally:
        monkeypatch.delenv("DL4J_TPU_ENCODED_UPDATE")
        Environment.reset()


def test_encoded_resolver_fallbacks():
    """Gradient normalization and dp<=1 both demote encoded to DENSE
    (same reasons as the sharded rung: per-layer norms need whole
    gradients; one replica has no wire to compress)."""
    from deeplearning4j_tpu.nn.conf.builders import GradientNormalization
    mesh = MeshFactory.data_parallel()
    net = _mlp()
    net.conf.gradient_normalization = \
        GradientNormalization.CLIP_L2_PER_LAYER
    assert resolve_update_exchange(mesh, requested="encoded",
                                   model=net) is UpdateExchange.DENSE
    one = MeshFactory.data_parallel(1)
    assert resolve_update_exchange(one, requested="encoded") \
        is UpdateExchange.DENSE
    assert resolve_update_exchange(None, requested="encoded") \
        is UpdateExchange.DENSE


def test_encoded_state_strips_when_stepping_dense():
    """Mode change encoded -> dense must not leak the residual into
    dense updater math (ENCODED_KEY stripped at the layout sync)."""
    net = _mlp(Adam(0.01), seed=5)
    pw = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange("encoded").build()
    pw.fit_batch(_data(64, seed=0))
    assert any(is_encoded(s) for s in net.updater_states.values())
    pw2 = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange("dense").build()
    pw2.fit_batch(_data(64, seed=1))
    assert not any(is_encoded(s)
                   for s in net.updater_states.values())
    assert not any(isinstance(s, dict) and ENCODED_KEY in s
                   for s in net.updater_states.values())


# -- low-precision serving residency ---------------------------------------
def _serving_mlp(seed=42):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Sgd(0.1))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=4,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


@pytest.mark.parametrize("mode", ["sharded", "fsdp"])
def test_serving_param_dtype_shrinks_residency_within_tolerance(mode):
    """register(param_dtype=) acceptance: bf16 halves the resident
    param bytes and int8 cuts them to ~1/4 (+ scales), while outputs
    stay bitwise for f32 and within float tolerance for the cast
    storage dtypes."""
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.serving import ServingBatcher
    from deeplearning4j_tpu.serving.residency import \
        resident_param_bytes
    net = _serving_mlp()
    mesh = make_mesh({"data": 8}, jax.devices()[:8])
    x = np.random.RandomState(0).randn(5, 8).astype(np.float32)
    ref = np.asarray(net.output(x))
    resident = {}
    for pd in (None, "bf16", "int8"):
        b = ServingBatcher(net, buckets=(8,), mesh=mesh, mode=mode,
                           param_dtype=pd)
        b.warmup((8,))
        out = b.submit(x).result(timeout=60)
        resident[pd] = resident_param_bytes(b._serve_params)
        if pd is None:
            np.testing.assert_array_equal(out, ref)
        else:
            np.testing.assert_allclose(out, ref, rtol=0.05, atol=0.02)
        b.shutdown()
    assert resident["bf16"] <= resident[None] * 0.55
    assert resident["int8"] <= resident[None] * 0.35


def test_serving_param_dtype_gauge_and_registry_roundtrip():
    """The registry surface: register(param_dtype='bf16') serves and
    the dl4j_serving_param_resident_bytes gauge reads about half the
    f32 series for the same checkpoint."""
    from deeplearning4j_tpu.common import telemetry
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.serving import ModelRegistry
    mesh = make_mesh({"data": 8}, jax.devices()[:8])
    g = telemetry.gauge("dl4j_serving_param_resident_bytes", "")
    reg = ModelRegistry(mesh, default_buckets=(8,))
    reg.register("full", _serving_mlp(), warmup_shape=(8,),
                 mode="sharded")
    reg.register("half", _serving_mlp(), warmup_shape=(8,),
                 mode="sharded", param_dtype="bf16")
    full = g.value(model="full", mode="sharded")
    half = g.value(model="half", mode="sharded")
    assert full and half and half == full // 2
    reg.shutdown()


def test_serving_param_dtype_rejects_dense_mode():
    from deeplearning4j_tpu.serving import ServingBatcher
    with pytest.raises(ValueError, match="param_dtype"):
        ServingBatcher(_serving_mlp(), buckets=(8,), mesh=None,
                       mode="dense", param_dtype="bf16")


def test_kv_dtype_env_default_halves_pool_bytes(monkeypatch):
    """DL4J_TPU_KV_DTYPE=bf16 becomes the KVBlockPool default dtype
    (per-model generate={'kv_dtype': ...} still wins)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.decoder import (DecoderConfig,
                                                   DecoderLM)
    from deeplearning4j_tpu.serving.batcher import ServingBatcher
    conf = DecoderConfig.tiny()
    gen = {"kv_blocks": 8, "kv_block_size": 8, "prompt_buckets": (16,),
           "decode_buckets": (4,), "max_seq_len": 32}
    b32 = ServingBatcher(DecoderLM(conf), buckets=(8,), mesh=None,
                         name="kv32", generate=dict(gen))
    pool32 = b32._ensure_generate().pool
    assert pool32.k.dtype == jnp.float32
    monkeypatch.setenv("DL4J_TPU_KV_DTYPE", "bf16")
    b16 = ServingBatcher(DecoderLM(conf), buckets=(8,), mesh=None,
                         name="kv16", generate=dict(gen))
    pool16 = b16._ensure_generate().pool
    assert pool16.k.dtype == jnp.bfloat16
    assert pool16.pool_bytes == pool32.pool_bytes // 2
    b32.shutdown()
    b16.shutdown()
