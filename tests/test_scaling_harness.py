"""Scaling-efficiency measurement harness (BASELINE.md step 3
machinery, validated on the virtual 8-device CPU mesh — real numbers
come from running the same function on an ICI pod)."""
import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel.scaling import (measure_dp_scaling,
                                                 scaling_report)


def _factory():
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Sgd(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _make_batch(global_batch):
    rng = np.random.RandomState(0)
    x = rng.randn(global_batch, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, global_batch)]
    return DataSet(x, y)


def test_measures_all_sizes_and_reports():
    res = measure_dp_scaling(_factory, _make_batch, (1, 2, 4, 8),
                             per_chip_batch=4, steps=3, warmup=1)
    assert res["sizes"] == [1, 2, 4, 8]
    assert res["base"] == 1
    for n in res["sizes"]:
        assert res["throughput"][n] > 0
    assert res["efficiency"][1] == 1.0
    report = scaling_report(res)
    assert "chips" in report and "8" in report


def test_oversized_counts_skipped():
    res = measure_dp_scaling(_factory, _make_batch, (2, 4, 1024),
                             per_chip_batch=4, steps=2, warmup=1)
    assert res["sizes"] == [2, 4]      # 1024 > virtual mesh size
