"""Examples stay importable and the fast ones run (reference:
dl4j-examples parity; heavy examples are exercised by their own
subsystem suites)."""
import importlib.util
import pathlib
import sys

import pytest

EX = pathlib.Path(__file__).parent.parent / "examples"


def _load(name):
    spec = importlib.util.spec_from_file_location(name, EX / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("name", ["lenet_mnist", "char_rnn",
                                  "transfer_learning", "data_parallel",
                                  "custom_layer_samediff",
                                  "tf_frozen_import", "a3c_cartpole",
                                  "serving_inference", "serve_mnist"])
def test_importable(name):
    assert _load(name).main is not None


def test_tf_frozen_import_example_runs():
    pytest.importorskip("tensorflow")
    _load("tf_frozen_import").main()   # asserts parity internally


def test_custom_layer_example_runs():
    assert _load("custom_layer_samediff").main() > 0.9


def test_data_parallel_example_runs():
    import numpy as np
    assert np.isfinite(_load("data_parallel").main())


def test_serving_inference_example_runs():
    _load("serving_inference").main()   # asserts parity internally


def test_serve_mnist_example_runs():
    # returns retraces_since_warmup — the zero-recompile guarantee
    assert _load("serve_mnist").main() == 0
