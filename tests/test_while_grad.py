"""Gradients through dynamic while-loops (SURVEY.md S2/S3: the
reference SameDiff backprops through TF Enter/Exit/NextIteration loop
frames; here while_loop(max_iterations=N) lowers to a bounded masked
lax.scan with a transpose rule — autodiff/registry.py).

Also pins the loud-failure contract: an UNBOUNDED while_loop has no
reverse rule, and a gradient request through a captured value must
raise (round-1 behavior silently stopped the gradient — a correctness
cliff for imported graphs with trainable dynamic loops)."""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.samediff import SameDiff


def _doubling_loop(sd, x, max_iterations=None):
    """double v until sum(v) >= 100 (data-dependent trip count)."""
    return sd.while_loop(
        [x],
        lambda v: v.sd._op("lt",
                           [v.sd._op("reduce_sum", [v]),
                            v.sd.constant(np.float32(100.0))]),
        lambda v: v.sd._op("mul",
                           [v, v.sd.constant(np.float32(2.0))]),
        max_iterations=max_iterations)


class TestBoundedWhileGrad:
    def test_forward_matches_unbounded(self):
        for seed in range(3):
            rng = np.random.RandomState(seed)
            xv = rng.rand(4).astype(np.float32) + 0.5
            outs = {}
            for mi in (None, 16):
                sd = SameDiff()
                x = sd.placeholder("x", shape=(4,))
                out = _doubling_loop(sd, x, mi).rename("res")
                outs[mi] = sd.output({"x": xv}, ["res"])["res"]
            np.testing.assert_allclose(outs[None], outs[16])

    def test_analytic_vs_numeric_gradient(self):
        """d(loss)/dw through a data-dependent trip count: w scales
        the start vector; away from trip-count boundaries the loop is
        locally k doublings, so the gradient is smooth and the
        numeric check is valid."""
        sd = SameDiff()
        w = sd.var("w", array=np.float32([1.1, 0.9, 1.3, 0.7]))
        x = sd.placeholder("x", shape=(4,))
        scaled = sd._op("mul", [w, x])
        out = _doubling_loop(sd, scaled, max_iterations=16)
        loss = sd._op("reduce_sum", [out]).rename("loss")
        sd.set_loss_variables(["loss"])
        xv = np.float32([1.0, 2.0, 0.5, 1.5])
        g = sd.calculate_gradients({"x": xv}, ["w"])["w"]

        def f(wv):
            sd2 = SameDiff()
            w2 = sd2.var("w", array=wv.astype(np.float32))
            x2 = sd2.placeholder("x", shape=(4,))
            s2 = sd2._op("mul", [w2, x2])
            o2 = _doubling_loop(sd2, s2, max_iterations=16)
            l2 = sd2._op("reduce_sum", [o2]).rename("l2")
            return float(sd2.output({"x": xv}, ["l2"])["l2"])

        w0 = np.float64([1.1, 0.9, 1.3, 0.7])
        eps = 1e-3
        num = np.zeros(4)
        for i in range(4):
            wp, wm = w0.copy(), w0.copy()
            wp[i] += eps
            wm[i] -= eps
            num[i] = (f(wp) - f(wm)) / (2 * eps)
        np.testing.assert_allclose(g, num, rtol=1e-2, atol=1e-3)

    def test_capture_receives_gradient(self):
        """A trainable captured by the bounded loop BODY (not threaded
        through the carry) gets real gradients: loss = sum(x + w
        added k times) -> dloss/dw = k * size."""
        sd = SameDiff()
        w = sd.var("w", array=np.float32(0.5))
        x = sd.placeholder("x", shape=(3,))
        out = sd.while_loop(
            [x],
            lambda v: v.sd._op("lt",
                               [v.sd._op("reduce_sum", [v]),
                                v.sd.constant(np.float32(30.0))]),
            lambda v: v.sd._op("add", [v, w]),
            max_iterations=64)
        loss = sd._op("reduce_sum", [out]).rename("loss")
        sd.set_loss_variables(["loss"])
        xv = np.float32([1.0, 1.0, 1.0])
        # trips: sum goes 3 -> +1.5/trip; stops when >= 30: 18 trips
        g = sd.calculate_gradients({"x": xv}, ["w"])["w"]
        assert float(g) == pytest.approx(18 * 3, rel=1e-5)

    def test_truncation_at_max_iterations(self):
        """Fewer allowed trips than the condition wants: TF
        maximum_iterations semantics — stop after N."""
        sd = SameDiff()
        x = sd.placeholder("x", shape=(4,))
        out = _doubling_loop(sd, x, max_iterations=2).rename("res")
        got = sd.output({"x": np.ones(4, np.float32)}, ["res"])["res"]
        np.testing.assert_allclose(got, np.full(4, 4.0))  # 2 doublings

    def test_bounded_roundtrip_serialization(self, tmp_path):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(4,))
        out = _doubling_loop(sd, x, max_iterations=16).rename("res")
        feed = {"x": np.ones(4, np.float32)}
        want = sd.output(feed, ["res"])["res"]
        p = str(tmp_path / "bounded.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        got = sd2.output(feed, ["res"])["res"]
        np.testing.assert_allclose(got, want)


class TestUnboundedWhileGradRaises:
    def test_capture_gradient_raises_loudly(self):
        sd = SameDiff()
        w = sd.var("w", array=np.float32(0.5))
        x = sd.placeholder("x", shape=(3,))
        out = sd.while_loop(
            [x],
            lambda v: v.sd._op("lt",
                               [v.sd._op("reduce_sum", [v]),
                                v.sd.constant(np.float32(30.0))]),
            lambda v: v.sd._op("add", [v, w]))
        sd._op("reduce_sum", [out]).rename("loss")
        sd.set_loss_variables(["loss"])
        with pytest.raises(Exception, match="max_iterations"):
            sd.calculate_gradients({"x": np.ones(3, np.float32)},
                                   ["w"])

    def test_forward_still_works_unbounded(self):
        sd = SameDiff()
        x = sd.placeholder("x", shape=(4,))
        out = _doubling_loop(sd, x).rename("res")
        got = sd.output({"x": np.ones(4, np.float32)}, ["res"])["res"]
        np.testing.assert_allclose(got, np.full(4, 32.0))
