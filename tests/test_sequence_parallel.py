"""Sequence/context parallelism tests (SURVEY.md P9/§5.7 extension).

Every sharded/blocked attention form must equal dense softmax
attention (ops.attention.dot_product_attention) on gathered data.
Runs on the virtual 8-device CPU mesh (conftest)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.attention import dot_product_attention
from deeplearning4j_tpu.parallel import make_mesh
from deeplearning4j_tpu.parallel.sequence import (
    blockwise_attention, flash_attention, ring_attention,
    ring_self_attention, ulysses_self_attention)

from conftest import require_devices


@pytest.fixture(autouse=True)
def _f32_matmuls():
    """These are ALGORITHM-equivalence tests (blocked/sharded vs
    dense); run matmuls at f32 precision so TPU's default-bf16
    multiplies (~2e-3 abs at these scales) don't drown the
    comparison. Production precision is a benchmark concern, not a
    correctness one."""
    with jax.default_matmul_precision("highest"):
        yield


def _qkv(b=2, h=4, t=64, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, t, d).astype(np.float32))
    return mk(), mk(), mk()


def _dense(q, k, v, causal=False):
    mask = None
    if causal:
        t = q.shape[-2]
        mask = jnp.tril(jnp.ones((t, t), bool))
    return dot_product_attention(q, k, v, mask)


class TestBlockwise:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("block_k", [16, 24, 64])
    def test_matches_dense(self, causal, block_k):
        q, k, v = _qkv()
        out = blockwise_attention(q, k, v, causal=causal,
                                  block_k=block_k)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_dense(q, k, v, causal)),
                                   atol=2e-5)

    def test_key_mask(self):
        q, k, v = _qkv(t=32)
        km = jnp.asarray((np.arange(32) < 20)[None, None, :]
                         * np.ones((2, 4, 1)), jnp.float32)
        out = blockwise_attention(q, k, v, key_mask=km, block_k=16)
        ref = dot_product_attention(q, k, v, km[..., None, :])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_grad_matches_dense(self):
        q, k, v = _qkv(b=1, h=2, t=32, d=8)

        def loss_block(q, k, v):
            return jnp.sum(blockwise_attention(q, k, v, causal=True,
                                               block_k=16) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(_dense(q, k, v, True) ** 2)

        g1 = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = _qkv(t=256, d=32)
        out = flash_attention(q, k, v, causal, 128, 128)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_dense(q, k, v, causal)),
                                   atol=2e-5)

    def test_grad_flows(self):
        q, k, v = _qkv(b=1, h=1, t=128, d=16)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(_dense(q, k, v, True) ** 2)

        g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            # 2e-4 abs: sum-of-squares loss over t=128 amplifies the
            # f32 rounding on real TPU to ~5e-5 (relative ~6e-5);
            # CPU sits well under the old 5e-5
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("masked", [False, True])
    def test_kernel_backward_gradcheck(self, causal, masked):
        """r4: the backward is a pair of Pallas dq / dk+dv kernels
        (probabilities recomputed from the saved log-sum-exp), run
        here through interpret mode — the SAME kernel code path as
        TPU — against blockwise autodiff, multi-block grid, all
        causal x mask combinations."""
        from deeplearning4j_tpu.parallel.sequence import \
            blockwise_attention
        rng = np.random.RandomState(0)
        b, h, t, d = 2, 3, 256, 64
        q, k, v = (jnp.asarray(rng.randn(b, h, t, d)
                               .astype(np.float32) * 0.3)
                   for _ in range(3))
        km = None
        if masked:
            kma = np.ones((b, t), np.float32)
            kma[:, t // 2:] = 0.0
            km = jnp.asarray(kma)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal, 128, 128, True,
                                key_mask=km)
            return jnp.sum(jnp.sin(o))

        def loss_ref(q, k, v):
            kmb = None if km is None else km[:, None, :]
            o = blockwise_attention(q, k, v, causal=causal,
                                    block_k=128, key_mask=kmb)
            return jnp.sum(jnp.sin(o))

        gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
        for a, want in zip(gf, gr):
            # 5e-5: the masked case on real TPU sits at ~2.4e-5 even
            # at f32 matmul precision (fully-masked blocks round the
            # lse differently); CPU interpret mode is < 7e-6
            np.testing.assert_allclose(np.asarray(a),
                                       np.asarray(want), atol=5e-5)

    def test_indivisible_lengths_autofit_blocks(self):
        """Blocks that don't divide the sequence shrink to a divisor
        instead of erroring (t=48 with 32-blocks runs at 16)."""
        q, k, v = _qkv(t=48)
        out = flash_attention(q, k, v, False, 32, 32, True)
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
        e = np.exp(s - s.max(-1, keepdims=True))
        want = np.einsum("bhqk,bhkd->bhqd",
                         e / e.sum(-1, keepdims=True), v)
        np.testing.assert_allclose(np.asarray(out), want, atol=2e-5)


class TestRingAttention:

    @pytest.fixture(autouse=True)
    def _needs_mesh(self):
        require_devices(8)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_over_mesh(self, causal):
        mesh = make_mesh({"seq": 8})
        q, k, v = _qkv(t=64)
        out = ring_self_attention(mesh, q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_dense(q, k, v, causal)),
                                   atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_ring_matches_dense(self, causal):
        """r4 use_flash ring: per-shard attention-with-lse merged
        EXACTLY via log-sum-exps; causal decomposes into fully-
        visible / locally-causal / skipped shards.  (On the CPU mesh
        the per-shard call is the exact dense-with-lse reference —
        the MERGE algebra, which is what ring adds, is fully
        exercised; the Pallas kernels themselves are interpret-tested
        in TestFlashAttention.)"""
        mesh = make_mesh({"seq": 8})
        q, k, v = _qkv(t=128, d=32)
        out = ring_self_attention(mesh, q, k, v, causal=causal,
                                  use_flash=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_dense(q, k, v, causal)),
                                   atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_ring_grads_match_dense(self, causal):
        mesh = make_mesh({"seq": 8})
        q, k, v = _qkv(t=128, d=32)

        def loss_r(q, k, v):
            return jnp.sum(jnp.sin(ring_self_attention(
                mesh, q, k, v, causal=causal, use_flash=True)))

        def loss_d(q, k, v):
            return jnp.sum(jnp.sin(_dense(q, k, v, causal)))

        gr = jax.grad(loss_r, (0, 1, 2))(q, k, v)
        gd = jax.grad(loss_d, (0, 1, 2))(q, k, v)
        for a, want in zip(gr, gd):
            np.testing.assert_allclose(np.asarray(a),
                                       np.asarray(want), atol=5e-5)

    def test_flash_with_lse_matches_dense_lse(self):
        """flash_attention_with_lse: both outputs conform, and the
        lse COTANGENT flows (a loss using lse directly)."""
        from deeplearning4j_tpu.parallel.sequence import (
            NEG_INF, flash_attention_with_lse)
        q, k, v = _qkv(t=256, d=32)

        def dense_lse(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) \
                / np.sqrt(q.shape[-1])
            return jax.scipy.special.logsumexp(s, axis=-1)

        o, lse = flash_attention_with_lse(q, k, v, False, 128, 128,
                                          True)
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(_dense(q, k, v)),
                                   atol=2e-5)
        np.testing.assert_allclose(np.asarray(lse),
                                   np.asarray(dense_lse(q, k, v)),
                                   atol=2e-5)

        def loss_f(q, k, v):
            _, l = flash_attention_with_lse(q, k, v, False, 128, 128,
                                            True)
            return jnp.sum(jnp.cos(l))

        def loss_d(q, k, v):
            return jnp.sum(jnp.cos(dense_lse(q, k, v)))

        gf = jax.grad(loss_f, (0, 1, 2))(q, k, v)
        gd = jax.grad(loss_d, (0, 1, 2))(q, k, v)
        for a, want in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a),
                                       np.asarray(want), atol=5e-5)

    def test_with_data_axis(self):
        mesh = make_mesh({"data": 2, "seq": 4})
        q, k, v = _qkv(b=4, t=32)
        out = ring_self_attention(mesh, q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_dense(q, k, v, True)),
                                   atol=2e-5)

    def test_grad_through_ring(self):
        mesh = make_mesh({"seq": 4}, jax.devices()[:4])
        q, k, v = _qkv(b=1, h=2, t=32, d=8)

        def loss(q, k, v):
            return jnp.sum(ring_self_attention(mesh, q, k, v,
                                               causal=True) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(_dense(q, k, v, True) ** 2)

        g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5)


class TestUlysses:

    @pytest.fixture(autouse=True)
    def _needs_mesh(self):
        require_devices(4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_over_mesh(self, causal):
        mesh = make_mesh({"seq": 4}, jax.devices()[:4])  # h=4 % 4 == 0
        q, k, v = _qkv(t=64)
        out = ulysses_self_attention(mesh, q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_dense(q, k, v, causal)),
                                   atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_use_flash_flag_plumbs(self, causal):
        """use_flash on the CPU mesh keeps the blockwise form (the
        kernel engages on TPU only — validated on-chip: 1349.7 ->
        15.1 ms/step at causal seq 8192, BENCH_notes_r04.md); the
        flag must plumb through and stay exact either way."""
        mesh = make_mesh({"seq": 4}, jax.devices()[:4])
        q, k, v = _qkv(t=64)
        out = ulysses_self_attention(mesh, q, k, v, causal=causal,
                                     use_flash=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_dense(q, k, v, causal)),
                                   atol=2e-5)

    def test_fully_masked_rows_are_zero(self):
        """Fully-masked rows must be 0 like the dense reference, not
        mean(V) (code-review regression)."""
        q, k, v = _qkv(b=1, h=1, t=16, d=8)
        km = jnp.zeros((1, 1, 16))         # everything masked
        out = blockwise_attention(q, k, v, key_mask=km, block_k=8)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.zeros_like(out))

    def test_flash_key_mask_matches_dense(self):
        """In-kernel key masking equals dense masked attention."""
        q, k, v = _qkv(b=2, h=4, t=128, d=16)
        km_np = np.ones((2, 128), np.float32)
        km_np[0, 100:] = 0.0
        km_np[1, 64:] = 0.0
        km = jnp.asarray(km_np)
        out = flash_attention(q, k, v, False, 64, 64, None, km)
        ref = dot_product_attention(q, k, v, km[:, None, None, :])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_flash_key_mask_grad(self):
        q, k, v = _qkv(b=1, h=2, t=64, d=8)
        km = jnp.asarray(np.concatenate(
            [np.ones((1, 48)), np.zeros((1, 16))], 1), jnp.float32)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, False, 64, 64,
                                           None, km) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(dot_product_attention(
                q, k, v, km[:, None, None, :]) ** 2)

        g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5)
