"""Keras import conformance (SURVEY.md D14, §4.6).

The reference validates Keras import against stored .h5 fixtures whose
activations were produced by Keras itself. Same protocol: models are
built+saved with the in-image Keras, imported, and predictions compared
against Keras outputs.
"""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = tf.keras

from deeplearning4j_tpu.modelimport.keras import (  # noqa: E402
    InvalidKerasConfigurationException, KerasModelImport)


def _save(model, tmp_path, fmt):
    path = str(tmp_path / f"model.{fmt}")
    model.save(path)
    return path


def _compare_sequential(model, x, tmp_path, fmt="keras", atol=1e-4):
    path = _save(model, tmp_path, fmt)
    net = KerasModelImport.import_keras_sequential_model_and_weights(
        path)
    want = np.asarray(model(x, training=False))
    got = net.output(x)
    np.testing.assert_allclose(got, want, atol=atol, rtol=1e-3)
    return net


class TestSequentialImport:
    def test_mlp_both_formats(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((12,)),
            keras.layers.Dense(16, activation="relu"),
            keras.layers.Dense(5, activation="softmax"),
        ])
        x = np.random.RandomState(0).randn(4, 12).astype(np.float32)
        _compare_sequential(model, x, tmp_path, "keras")
        _compare_sequential(model, x, tmp_path, "h5")

    def test_cnn_bn_pool_flatten(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((12, 12, 3)),
            keras.layers.Conv2D(8, 3, padding="same",
                                activation="relu"),
            keras.layers.BatchNormalization(),
            keras.layers.MaxPooling2D(2),
            keras.layers.Conv2D(4, 3, padding="valid"),
            keras.layers.Activation("tanh"),
            keras.layers.Flatten(),
            keras.layers.Dense(7, activation="softmax"),
        ])
        # give BN non-trivial moving stats
        model.layers[1].set_weights([
            np.random.RandomState(1).rand(8).astype(np.float32) + 0.5,
            np.random.RandomState(2).randn(8).astype(np.float32) * 0.1,
            np.random.RandomState(3).randn(8).astype(np.float32) * 0.1,
            np.random.RandomState(4).rand(8).astype(np.float32) + 0.5,
        ])
        x = np.random.RandomState(5).randn(2, 12, 12, 3) \
            .astype(np.float32)
        _compare_sequential(model, x, tmp_path)

    def test_lstm_return_sequences_false(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((7, 5)),
            keras.layers.LSTM(6),
            keras.layers.Dense(3, activation="softmax"),
        ])
        x = np.random.RandomState(0).randn(2, 7, 5).astype(np.float32)
        _compare_sequential(model, x, tmp_path)

    def test_gru_reset_after_bias(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((5, 4)),
            keras.layers.GRU(6, return_sequences=True),
        ])
        # nonzero recurrent candidate bias exercises the rb param
        w = model.layers[0].get_weights()
        w[2] = np.random.RandomState(0).randn(*w[2].shape) \
            .astype(np.float32) * 0.3
        model.layers[0].set_weights(w)
        x = np.random.RandomState(1).randn(3, 5, 4).astype(np.float32)
        _compare_sequential(model, x, tmp_path)

    def test_simple_rnn_and_embedding(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((6,)),
            keras.layers.Embedding(20, 8),
            keras.layers.SimpleRNN(5, activation="tanh"),
            keras.layers.Dense(2, activation="softmax"),
        ])
        x = np.random.RandomState(0).randint(0, 20, (3, 6)) \
            .astype(np.int32)
        path = _save(model, tmp_path, "keras")
        net = KerasModelImport \
            .import_keras_sequential_model_and_weights(path)
        want = np.asarray(model(x, training=False))
        got = net.output(x.astype(np.float32))
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)

    def test_compiled_model_gets_output_layer(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((4,)),
            keras.layers.Dense(3, activation="softmax"),
        ])
        model.compile(loss="categorical_crossentropy", optimizer="sgd")
        path = _save(model, tmp_path, "keras")
        net = KerasModelImport \
            .import_keras_sequential_model_and_weights(path)
        from deeplearning4j_tpu.lossfunctions import LossFunction
        from deeplearning4j_tpu.nn.conf.layers import OutputLayer
        assert isinstance(net.conf.layers[-1], OutputLayer)
        assert net.conf.layers[-1].loss_function is LossFunction.MCXENT
        # and it can fit
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[
            np.random.RandomState(1).randint(0, 3, 8)]
        net.fit(x, y)

    def test_unsupported_layer_reports_type(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((8, 1)),
            keras.layers.Dense(4),
            # still-unmapped layer type: the error must NAME it
            keras.layers.CategoryEncoding(num_tokens=4),
        ])
        path = _save(model, tmp_path, "keras")
        with pytest.raises(InvalidKerasConfigurationException,
                           match="CategoryEncoding"):
            KerasModelImport \
                .import_keras_sequential_model_and_weights(path)


class TestFunctionalImport:
    def test_two_branch_residual(self, tmp_path):
        inp = keras.Input((10,), name="feat")
        a = keras.layers.Dense(8, activation="relu")(inp)
        b = keras.layers.Dense(8, activation="tanh")(inp)
        s = keras.layers.Add()([a, b])
        out = keras.layers.Dense(4, activation="softmax")(s)
        model = keras.Model(inp, out)
        path = _save(model, tmp_path, "keras")
        net = KerasModelImport.import_keras_model_and_weights(path)
        x = np.random.RandomState(0).randn(3, 10).astype(np.float32)
        want = np.asarray(model(x, training=False))
        got = net.outputs(x)[0]
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)

    def test_concat_branches(self, tmp_path):
        inp = keras.Input((6,))
        a = keras.layers.Dense(4, activation="relu")(inp)
        b = keras.layers.Dense(3, activation="sigmoid")(inp)
        c = keras.layers.Concatenate()([a, b])
        out = keras.layers.Dense(2)(c)
        model = keras.Model(inp, out)
        path = _save(model, tmp_path, "keras")
        net = KerasModelImport.import_keras_model_and_weights(path)
        x = np.random.RandomState(0).randn(5, 6).astype(np.float32)
        want = np.asarray(model(x, training=False))
        got = net.outputs(x)[0]
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)
