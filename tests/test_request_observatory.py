"""Serving request observatory tests (ISSUE 17): trace propagation,
span-tree connectivity, SLO error-budget accounting, and the request
flight recorder.

The structural contract under test: ONE request = ONE trace id = ONE
connected timeline. The id round-trips on the ``X-Dl4j-Trace-Id``
header, every ``req.<phase>`` span nests inside the request's root
span, the latency histogram's exemplar points at a concrete trace,
the sampled access log carries the same id, and concurrent requests
across models never contaminate each other's ids — the leakage
hazard of reused keep-alive handler threads.

Timing caveat the tests must respect: the replica emits the
``request`` root span AFTER the response bytes are on the wire
(finish_json sends, then closes the context), so a client that just
read the body can race the span — every trace assertion polls.
"""
from __future__ import annotations

import http.client
import json
import socket
import struct
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.common import telemetry, tracectx
from deeplearning4j_tpu.common.telemetry import MetricsRegistry
from deeplearning4j_tpu.serving import (AdmissionController,
                                        InferenceServer, ModelRegistry,
                                        RequestRecorder, SLOTracker)


@pytest.fixture(autouse=True)
def _fresh_registry():
    MetricsRegistry._reset_for_tests()
    yield
    MetricsRegistry._reset_for_tests()


def _mlp(seed=42):
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _post(base, name, payload, headers=None):
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    req = urllib.request.Request(
        f"{base}/v1/models/{name}:predict",
        data=json.dumps(payload).encode(), headers=h)
    try:
        r = urllib.request.urlopen(req, timeout=60)
        return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _serve(name="m", **register_kw):
    reg = ModelRegistry(default_buckets=(8,))
    reg.register(name, _mlp(), warmup_shape=(8,), **register_kw)
    srv = InferenceServer(reg).start(0)
    return reg, srv


def _trace_spans(trace_id, want=("request",), timeout=5.0):
    """Spans in the ring carrying ``trace_id``, polled until every
    name in ``want`` has landed (the root span is emitted after the
    response bytes — see the module docstring)."""
    deadline = time.monotonic() + timeout
    while True:
        events = [e for e in telemetry.trace_events()
                  if e.get("args", {}).get("trace") == trace_id]
        names = {e["name"] for e in events}
        if all(w in names for w in want) \
                or time.monotonic() >= deadline:
            return events
        time.sleep(0.02)


def _x(n=2, seed=0):
    return np.random.RandomState(seed).randn(n, 8).astype(np.float32)


# ----------------------------------------------------------------------
class TestPredictSpanTree:
    def test_adopted_id_echoes_and_tree_is_connected(self):
        reg, srv = _serve()
        tid = "obs-test-predict-01"
        try:
            code, body, headers = _post(
                srv.url, "m", {"inputs": _x().tolist()},
                headers={tracectx.TRACE_HEADER: tid})
            assert code == 200
            assert headers.get(tracectx.TRACE_HEADER) == tid
        finally:
            srv.stop(drain=False)
            reg.shutdown()
        events = _trace_spans(tid)
        roots = [e for e in events if e["name"] == "request"]
        assert len(roots) == 1
        root = roots[0]
        assert root["args"]["kind"] == "predict"
        assert root["args"]["verdict"] == "200"
        r0, r1 = root["ts"], root["ts"] + root["dur"]
        phases = {e["name"]: e for e in events
                  if e["name"].startswith("req.")
                  and e.get("ph") == "X"}
        for want in ("req.admit", "req.queue", "req.device",
                     "req.serialize"):
            assert want in phases, f"missing {want}"
        slack = 1000    # chrome-trace integer-µs rounding
        for e in phases.values():
            assert e["ts"] >= r0 - slack
            assert e["ts"] + e["dur"] <= r1 + slack

    def test_exemplar_carries_trace_id(self):
        reg, srv = _serve()
        tid = "obs-test-exemplar-01"
        try:
            code, _, _ = _post(srv.url, "m",
                               {"inputs": _x().tolist()},
                               headers={tracectx.TRACE_HEADER: tid})
            assert code == 200
        finally:
            srv.stop(drain=False)
            reg.shutdown()
        ex = telemetry.histogram(
            "dl4j_serving_total_seconds").exemplar_of(model="m")
        assert ex is not None
        assert ex["labels"]["trace_id"] == tid

    def test_minted_id_when_header_absent_or_hostile(self):
        reg, srv = _serve()
        try:
            _, _, h1 = _post(srv.url, "m", {"inputs": _x().tolist()})
            minted = h1.get(tracectx.TRACE_HEADER)
            assert minted and len(minted) == 16
            # a hostile header (spaces, over-long) is never adopted
            _, _, h2 = _post(
                srv.url, "m", {"inputs": _x().tolist()},
                headers={tracectx.TRACE_HEADER: "a bad id!"})
            assert h2.get(tracectx.TRACE_HEADER) != "a bad id!"
            _, _, h3 = _post(
                srv.url, "m", {"inputs": _x().tolist()},
                headers={tracectx.TRACE_HEADER: "x" * 65})
            assert h3.get(tracectx.TRACE_HEADER) != "x" * 65
        finally:
            srv.stop(drain=False)
            reg.shutdown()

    def test_gate_off_serves_without_spans_or_header(self):
        reg, srv = _serve()
        try:
            tracectx.set_enabled(False)
            code, _, headers = _post(
                srv.url, "m", {"inputs": _x().tolist()},
                headers={tracectx.TRACE_HEADER: "gated-off-01"})
            assert code == 200
            assert tracectx.TRACE_HEADER not in headers
        finally:
            tracectx.set_enabled(None)
            srv.stop(drain=False)
            reg.shutdown()
        assert not [e for e in telemetry.trace_events()
                    if e.get("args", {}).get("trace") == "gated-off-01"]


# ----------------------------------------------------------------------
class TestAccessLog:
    def test_log_line_carries_trace_id(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.common import httputil
        log = tmp_path / "access.jsonl"
        monkeypatch.setenv("DL4J_TPU_ACCESS_LOG", str(log))
        httputil._reset_access_conf()
        reg, srv = _serve()
        tid = "obs-test-accesslog-1"
        try:
            code, _, _ = _post(srv.url, "m",
                               {"inputs": _x().tolist()},
                               headers={tracectx.TRACE_HEADER: tid})
            assert code == 200
        finally:
            srv.stop(drain=False)
            reg.shutdown()
            httputil._reset_access_conf()
        lines = [json.loads(ln) for ln in
                 log.read_text().strip().splitlines()]
        mine = [ln for ln in lines if ln["trace_id"] == tid]
        assert len(mine) == 1
        assert mine[0]["method"] == "POST"
        assert mine[0]["path"].endswith("m:predict")
        assert mine[0]["status"] == 200
        assert mine[0]["bytes"] > 0
        assert mine[0]["duration_ms"] > 0

    def test_sampling_keeps_one_in_n(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.common import httputil
        log = tmp_path / "sampled.jsonl"
        monkeypatch.setenv("DL4J_TPU_ACCESS_LOG", str(log))
        monkeypatch.setenv("DL4J_TPU_ACCESS_LOG_SAMPLE", "0.5")
        httputil._reset_access_conf()
        reg, srv = _serve()
        try:
            for i in range(8):
                code, _, _ = _post(srv.url, "m",
                                   {"inputs": _x(seed=i).tolist()})
                assert code == 200
        finally:
            srv.stop(drain=False)
            reg.shutdown()
            httputil._reset_access_conf()
        # deterministic 1-in-2: 8 consecutive sequence numbers hold
        # exactly 4 multiples of 2, wherever the shared counter sat
        lines = log.read_text().strip().splitlines()
        assert len(lines) == 4


# ----------------------------------------------------------------------
def _serve_generative(**overrides):
    from deeplearning4j_tpu.models.decoder import (DecoderConfig,
                                                   DecoderLM)
    conf = DecoderConfig.tiny()
    gen = {"kv_blocks": 32, "kv_block_size": 8,
           "prompt_buckets": (16,), "decode_buckets": (4,),
           "max_seq_len": 64}
    gen.update(overrides)
    reg = ModelRegistry()
    reg.register("lm", DecoderLM(conf), generate=gen)
    srv = InferenceServer(reg).start(0)
    return reg, srv


def _gen_request(port, body, headers=None, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    conn.request("POST", "/v1/models/lm:generate",
                 body=json.dumps(body).encode(),
                 headers={"Content-Type": "application/json",
                          **(headers or {})})
    return conn, conn.getresponse()


class TestGenerateSpanTree:
    def test_stream_trace_with_ttft_and_stream_phase(self):
        reg, srv = _serve_generative()
        tid = "obs-test-generate-1"
        try:
            conn, resp = _gen_request(
                srv.port, {"prompt": [5, 9, 2, 7], "max_tokens": 4},
                headers={tracectx.TRACE_HEADER: tid})
            assert resp.status == 200
            assert resp.getheader(tracectx.TRACE_HEADER) == tid
            lines = [json.loads(ln) for ln in
                     resp.read().decode().strip().splitlines()]
            assert lines[-1]["done"]
            conn.close()
        finally:
            srv.stop(drain=False)
            reg.shutdown()
        events = _trace_spans(tid)
        roots = [e for e in events if e["name"] == "request"]
        assert len(roots) == 1
        assert roots[0]["args"]["kind"] == "generate"
        assert roots[0]["args"]["verdict"] == "200"
        assert roots[0]["args"]["tokens"] == 4
        names = {e["name"] for e in events}
        assert "req.stream" in names
        assert "req.ttft" in names          # first-token instant
        assert "req.inter_token" in names   # per-token cadence
        # the streamed phases nest inside the root like predict's do
        r0 = roots[0]["ts"]
        r1 = r0 + roots[0]["dur"]
        for e in events:
            if e["name"].startswith("req.") and e.get("ph") == "X":
                assert e["ts"] >= r0 - 1000
                assert e["ts"] + e["dur"] <= r1 + 1000

    def test_client_disconnect_closes_span_as_499(self):
        # enough decode iterations that the stream is still live well
        # after the client's close — a 60-token stream can finish
        # into the socket buffers before the disconnect is noticed
        reg, srv = _serve_generative(kv_blocks=80, max_seq_len=512)
        tid = "obs-test-cancel-01"
        try:
            conn, resp = _gen_request(
                srv.port, {"prompt": [5, 9, 2, 7],
                           "max_tokens": 450},
                headers={tracectx.TRACE_HEADER: tid})
            resp.fp.readline()      # one token, then slam the socket
            # a plain close() would linger: resp.fp still references
            # the fd, and a graceful FIN lets the server stream into
            # the receive buffer to completion — RST-on-close is the
            # real "client went away mid-stream"
            conn.sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0))
            resp.close()
            conn.close()
            events = _trace_spans(tid, timeout=15.0)
            roots = [e for e in events if e["name"] == "request"]
            assert len(roots) == 1
            assert roots[0]["args"]["verdict"] == "499"
            recs = [r for r in RequestRecorder.get().records()
                    if r["trace_id"] == tid]
            assert len(recs) == 1
            assert recs[0]["verdict"] == "499"
        finally:
            srv.stop(drain=False)
            reg.shutdown()


# ----------------------------------------------------------------------
class TestTraceLeakage:
    def test_concurrent_predict_and_generate_no_crosstalk(self):
        """Concurrent requests across two models on reused keep-alive
        handler threads: every response must echo ITS OWN id, and
        every id must own exactly one root span on the right model —
        the cross-request contamination the ambient binding could
        cause if it ever leaked."""
        from deeplearning4j_tpu.models.decoder import (DecoderConfig,
                                                       DecoderLM)
        reg = ModelRegistry(default_buckets=(8,))
        reg.register("m", _mlp(), warmup_shape=(8,))
        reg.register("lm", DecoderLM(DecoderConfig.tiny()), generate={
            "kv_blocks": 32, "kv_block_size": 8,
            "prompt_buckets": (16,), "decode_buckets": (4,),
            "max_seq_len": 64})
        srv = InferenceServer(reg).start(0)
        errors = []
        try:
            def predict_client(k):
                for i in range(3):
                    tid = f"leak-p{k}-{i}"
                    code, _, h = _post(
                        srv.url, "m", {"inputs": _x(seed=i).tolist()},
                        headers={tracectx.TRACE_HEADER: tid})
                    if code != 200:
                        errors.append(f"predict {tid}: {code}")
                    elif h.get(tracectx.TRACE_HEADER) != tid:
                        errors.append(
                            f"predict {tid} echoed "
                            f"{h.get(tracectx.TRACE_HEADER)!r}")

            def generate_client(k):
                for i in range(2):
                    tid = f"leak-g{k}-{i}"
                    conn, resp = _gen_request(
                        srv.port,
                        {"prompt": [5, 9, 2, 7], "max_tokens": 3},
                        headers={tracectx.TRACE_HEADER: tid})
                    got = resp.getheader(tracectx.TRACE_HEADER)
                    resp.read()
                    conn.close()
                    if resp.status != 200:
                        errors.append(f"generate {tid}: "
                                      f"{resp.status}")
                    elif got != tid:
                        errors.append(f"generate {tid} echoed "
                                      f"{got!r}")

            threads = [threading.Thread(target=predict_client,
                                        args=(k,)) for k in range(3)]
            threads += [threading.Thread(target=generate_client,
                                         args=(k,)) for k in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
        finally:
            srv.stop(drain=False)
            reg.shutdown()
        # every id owns exactly one root span, on the right model
        for tid, model, kind in \
                [(f"leak-p{k}-{i}", "m", "predict")
                 for k in range(3) for i in range(3)] + \
                [(f"leak-g{k}-{i}", "lm", "generate")
                 for k in range(2) for i in range(2)]:
            roots = [e for e in _trace_spans(tid)
                     if e["name"] == "request"]
            assert len(roots) == 1, f"{tid}: {len(roots)} roots"
            assert roots[0]["args"]["model"] == model
            assert roots[0]["args"]["kind"] == kind


# ----------------------------------------------------------------------
class TestSLOAccounting:
    def test_multi_window_burn_rate_math(self):
        t = SLOTracker(target=0.99, fast_window_s=300.0,
                       slow_window_s=3600.0)
        t0 = 10_000.0
        for i in range(90):
            t.observe("m", 0.010, slo_ms=50.0, now=t0 + i * 0.1)
        for i in range(10):
            t.observe("m", 0.500, slo_ms=50.0, now=t0 + 9 + i * 0.1)
        now = t0 + 10
        # 10/100 violations against a 1% budget → burn rate 10 on
        # both windows while everything is recent
        assert t.burn_rate("m", "fast", now=now) == pytest.approx(10.0)
        assert t.burn_rate("m", "slow", now=now) == pytest.approx(10.0)
        rep = t.report(now=now)["models"]["m"]
        assert rep["windows"]["fast"]["in_slo_fraction"] == \
            pytest.approx(0.90)
        assert rep["budget_remaining"] == pytest.approx(-9.0)
        # the fast window forgets the burst, the slow window doesn't:
        # the multi-window signal that separates a blip from a trend
        later = t0 + 10 + 400
        assert t.burn_rate("m", "fast", now=later) == 0.0
        assert t.burn_rate("m", "slow",
                           now=later) == pytest.approx(10.0)

    def test_gauges_published_per_window(self):
        t = SLOTracker(target=0.99)
        t.observe("m", 0.500, slo_ms=50.0, now=1000.0)
        g = telemetry.gauge("dl4j_slo_in_fraction")
        assert g.value(model="m", window="fast") == 0.0
        assert g.value(model="m", window="slow") == 0.0
        assert telemetry.gauge("dl4j_slo_burn_rate").value(
            model="m", window="fast") == pytest.approx(100.0)
        assert telemetry.gauge(
            "dl4j_slo_budget_remaining").value(
                model="m") == pytest.approx(-99.0)

    def test_api_slo_reports_forced_violation(self):
        """A model whose SLO every request violates must show up on
        GET /api/slo with burn rate > 1 and budget draining."""
        reg, srv = _serve(latency_slo_ms=0.000001)
        try:
            code, _, _ = _post(srv.url, "m",
                               {"inputs": _x().tolist()})
            assert code == 200
            with urllib.request.urlopen(f"{srv.url}/api/slo",
                                        timeout=10) as r:
                doc = json.load(r)
        finally:
            srv.stop(drain=False)
            reg.shutdown()
        assert doc["target"] == pytest.approx(0.99)
        m = doc["models"]["m"]
        assert m["slo_ms"] == pytest.approx(0.000001)
        assert m["windows"]["fast"]["n"] >= 1
        assert m["windows"]["fast"]["in_slo_fraction"] == 0.0
        assert m["windows"]["fast"]["burn_rate"] > 1.0
        assert m["budget_remaining"] < 1.0


# ----------------------------------------------------------------------
class TestFlightRecorder:
    def test_ring_records_and_api_endpoints(self, tmp_path,
                                            monkeypatch):
        monkeypatch.setenv("DL4J_TPU_REQREC_DIR", str(tmp_path))
        RequestRecorder._reset_for_tests()
        reg, srv = _serve()
        tid = "obs-test-reqrec-01"
        try:
            code, _, _ = _post(srv.url, "m",
                               {"inputs": _x().tolist()},
                               headers={tracectx.TRACE_HEADER: tid})
            assert code == 200
            with urllib.request.urlopen(
                    f"{srv.url}/api/reqrec?n=5", timeout=10) as r:
                live = json.load(r)["requests"]
            req = urllib.request.Request(
                f"{srv.url}/api/reqrec/dump", data=b"")
            with urllib.request.urlopen(req, timeout=10) as r:
                dump = json.load(r)
        finally:
            srv.stop(drain=False)
            reg.shutdown()
        mine = [r for r in live if r["trace_id"] == tid]
        assert len(mine) == 1
        assert mine[0]["model"] == "m"
        assert mine[0]["verdict"] == "200"
        assert mine[0]["phase_ms"].get("device", 0) >= 0
        assert "queue_depth" in mine[0]
        path = dump["path"]
        assert path and path.startswith(str(tmp_path))
        lines = [json.loads(ln) for ln in
                 open(path).read().strip().splitlines()]
        assert lines[0]["record"] == "meta"
        assert lines[0]["reason"] == "api"
        assert any(r.get("trace_id") == tid for r in lines[1:])
        assert telemetry.counter(
            "dl4j_reqrec_dumps_total").value(reason="api") == 1

    def test_shed_storm_threshold_and_cooldown(self, tmp_path,
                                               monkeypatch):
        monkeypatch.setenv("DL4J_TPU_REQREC_DIR", str(tmp_path))
        monkeypatch.setenv("DL4J_TPU_REQREC_SHED_THRESHOLD", "3")
        monkeypatch.setenv("DL4J_TPU_REQREC_SHED_WINDOW_S", "30")
        monkeypatch.setenv("DL4J_TPU_REQREC_STORM_COOLDOWN_S", "60")
        RequestRecorder._reset_for_tests()
        rec = RequestRecorder.get()
        try:
            assert rec.note_shed("m", "queue_full") is None
            assert rec.note_shed("m", "queue_full") is None
            path = rec.note_shed("m", "queue_full")
            assert path is not None     # third shed crosses threshold
            meta = json.loads(open(path).readline())
            assert meta["reason"] == "shed_storm"
            assert meta["event"]["sheds_in_window"] == 3
            # cooldown: the storm keeps raging but dumps once
            assert rec.note_shed("m", "queue_full") is None
        finally:
            RequestRecorder._reset_for_tests()


# ----------------------------------------------------------------------
class TestDrainRateColdWindow:
    def test_single_completion_reports_floor_not_spike(self):
        """Regression: one completion observed 'just now' used to
        divide by the 1e-3 span floor and report ~1000 rps, which
        collapsed the measured Retry-After to its floor right after
        startup. With < 2 samples the rate must be the conservative
        floor (completions over the FULL window)."""
        c = AdmissionController(max_queue=4, rate_window_s=30.0)
        t0 = 100.0
        c.observe_total("m", 0.01, now=t0)
        with c._lock:
            rate = c._drain_rate_locked("m", t0 + 0.0005)
        assert rate == pytest.approx(1 / 30.0)

    def test_two_spanning_samples_measure_real_rate(self):
        c = AdmissionController(max_queue=4, rate_window_s=30.0)
        t0 = 100.0
        c.observe_total("m", 0.01, now=t0)
        c.observe_total("m", 0.01, now=t0 + 1.0)
        with c._lock:
            rate = c._drain_rate_locked("m", t0 + 1.0)
        assert rate == pytest.approx(2.0)
