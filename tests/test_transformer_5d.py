"""Flagship distributed TransformerLM: dp+pp+tp+sp+ep in one step.

The sharded train step's loss must equal a plain single-device
reference computed from the SAME global parameters, in both layouts:
- megatron-SP: mesh (data, pipe, model), time sharded over `model`;
- ring-CP:     mesh (data, pipe, seq, model), ring attention.
MoE equality holds when capacity is large enough that nothing drops.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.learning.updaters import Sgd
from deeplearning4j_tpu.models.transformer import (
    DistributedTransformerLM, TransformerLMConfig)
from deeplearning4j_tpu.ops.attention import dot_product_attention
from deeplearning4j_tpu.parallel import make_mesh
from deeplearning4j_tpu.parallel.expert import moe_ffn
from deeplearning4j_tpu.parallel.tensor import layer_norm

V, T, D, H, FF, B = 64, 16, 32, 4, 64, 8


def _conf(n_experts=0):
    return TransformerLMConfig(
        vocab_size=V, max_len=T, d_model=D, n_heads=H, d_ff=FF,
        layers_per_stage=2, n_experts=n_experts,
        moe_capacity=B * T, aux_coef=0.0)


def _data(seed=0):
    rng = np.random.RandomState(seed)
    ids = jnp.asarray(rng.randint(0, V, (B, T)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, V, (B, T)), jnp.int32)
    return ids, labels


def ref_loss(g, conf, pp, ids, labels, moe_layers):
    """Plain single-device forward from global params."""
    x = g["embed"][ids] + g["pos"][:T]
    mask = jnp.tril(jnp.ones((T, T), bool))[None, None]
    for s in range(pp):
        for l in range(conf.layers_per_stage):
            p = jax.tree_util.tree_map(lambda a: a[s], g["stages"][l])
            h = layer_norm(x, p["ln1_g"], p["ln1_b"])
            a = p["attn"]
            dh = D // H
            hd = lambda z: z.reshape(B, T, H, dh).transpose(0, 2, 1, 3)
            o = dot_product_attention(hd(h @ a["Wq"]), hd(h @ a["Wk"]),
                                      hd(h @ a["Wv"]), mask)
            o = o.transpose(0, 2, 1, 3).reshape(B, T, D)
            x = x + o @ a["Wo"] + a["bo"]
            h = layer_norm(x, p["ln2_g"], p["ln2_b"])
            if l in moe_layers:
                y, _ = moe_ffn(h, p["moe"], axis=None,
                               k=conf.moe_top_k,
                               capacity=conf.moe_capacity)
                x = x + y
            else:
                m = p["mlp"]
                x = x + jax.nn.gelu(h @ m["Wi"] + m["bi"]) \
                    @ m["Wo"] + m["bo"]
    h = layer_norm(x, g["ln_f_g"], g["ln_f_b"])
    logits = h @ g["head"]
    lse = jax.scipy.special.logsumexp(logits, -1)
    tgt = jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
    return jnp.mean(lse - tgt)


def _loss_of_first_step(model, params, opt, ids, labels):
    _, _, loss = model.train_step(params, opt, ids, labels, 0)
    return float(loss)


class TestMegatronMode:
    @pytest.mark.parametrize("n_experts", [0, 4])
    def test_loss_matches_reference(self, n_experts):
        conf = _conf(n_experts)
        mesh = make_mesh({"data": 2, "pipe": 2, "model": 2})
        model = DistributedTransformerLM(conf, mesh, Sgd(0.0),
                                         n_micro=2)
        params, opt = model.init(seed=3)
        g = model.init_global_params(seed=3)
        ids, labels = _data()
        moe_layers = {conf.layers_per_stage - 1} if n_experts else set()
        want = float(ref_loss(g, conf, 2, ids, labels, moe_layers))
        got = _loss_of_first_step(model, params, opt, ids, labels)
        np.testing.assert_allclose(got, want, rtol=2e-4)

    def test_gradients_match_reference(self):
        """One SGD step on the sharded model == global params minus
        lr * grad of the single-device reference loss, leaf for leaf
        (validates the whole reduction rule: psum placement over
        data/pipe/model for every sharding kind)."""
        lr = 0.1
        conf = _conf(0)
        mesh = make_mesh({"data": 2, "pipe": 2, "model": 2})
        model = DistributedTransformerLM(conf, mesh, Sgd(lr),
                                         n_micro=2)
        params, opt = model.init(seed=3)
        g = model.init_global_params(seed=3)
        ids, labels = _data()
        new_params, _, _ = model.train_step(params, opt, ids, labels, 0)
        ref_grads = jax.grad(
            lambda gp: ref_loss(gp, conf, 2, ids, labels, set()))(g)
        want = jax.tree_util.tree_map(lambda p, dg: p - lr * dg,
                                      g, ref_grads)
        flat_got = jax.tree_util.tree_leaves(new_params)
        flat_want = jax.tree_util.tree_leaves(want)
        for a, b in zip(flat_got, flat_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=1e-4)

    def test_loss_decreases(self):
        conf = _conf(4)
        conf.aux_coef = 0.01
        mesh = make_mesh({"data": 2, "pipe": 2, "model": 2})
        model = DistributedTransformerLM(conf, mesh, Sgd(0.05),
                                         n_micro=2)
        params, opt = model.init(seed=0)
        ids, labels = _data(1)
        losses = []
        for i in range(8):
            params, opt, loss = model.train_step(params, opt, ids,
                                                 labels, i)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0] - 0.1, losses


class TestRingMode:
    def test_loss_matches_reference(self):
        conf = _conf(0)
        mesh = make_mesh({"data": 1, "pipe": 2, "seq": 2, "model": 2})
        model = DistributedTransformerLM(conf, mesh, Sgd(0.0),
                                         n_micro=2)
        params, opt = model.init(seed=5)
        g = model.init_global_params(seed=5)
        ids, labels = _data(2)
        want = float(ref_loss(g, conf, 2, ids, labels, set()))
        got = _loss_of_first_step(model, params, opt, ids, labels)
        np.testing.assert_allclose(got, want, rtol=2e-4)

    def test_loss_decreases_with_moe(self):
        conf = _conf(2)
        mesh = make_mesh({"data": 2, "pipe": 2, "seq": 2, "model": 1})
        model = DistributedTransformerLM(conf, mesh, Sgd(0.5),
                                         n_micro=2)
        params, opt = model.init(seed=0)
        ids, labels = _data(4)
        losses = []
        for i in range(5):
            params, opt, loss = model.train_step(params, opt, ids,
                                                 labels, i)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses
