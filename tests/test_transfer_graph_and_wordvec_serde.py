"""ComputationGraph transfer learning + word-vector serialization."""
import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.learning.updaters import NoOp
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration, TransferLearning)
from deeplearning4j_tpu.nlp import Word2Vec
from deeplearning4j_tpu.nlp.serializer import (read_word2vec_model,
                                               read_word_vectors,
                                               write_word2vec_model,
                                               write_word_vectors)


def _graph(n_out=3):
    g = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(3e-2))
         .graph_builder().add_inputs("in")
         .set_input_types(InputType.feed_forward(4)))
    g.add_layer("f1", DenseLayer(n_out=12,
                                 activation=Activation.RELU), "in")
    g.add_layer("f2", DenseLayer(n_out=8,
                                 activation=Activation.RELU), "f1")
    g.add_layer("out", OutputLayer(
        n_out=n_out, activation=Activation.SOFTMAX,
        loss_function=LossFunction.MCXENT), "f2")
    return ComputationGraph(g.set_outputs("out").build()).init()


def _blob_ds(n=120, k=3, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, k, n)
    x = (np.eye(k, 4, dtype=np.float32)[y] * 2.5
         + rng.randn(n, 4).astype(np.float32) * 0.4)
    return DataSet(x, np.eye(k, dtype=np.float32)[y])


class TestGraphTransferLearning:
    def test_freeze_replace_head(self):
        src = _graph()
        src.fit(_blob_ds(), n_epochs=25)

        new = (TransferLearning.GraphBuilder(src)
               .fine_tune_configuration(
                   FineTuneConfiguration(updater=Adam(5e-2)))
               .set_feature_extractor("f2")
               .remove_vertex_and_connections("out")
               .add_layer("newout", OutputLayer(
                   n_in=8, n_out=2, activation=Activation.SOFTMAX,
                   loss_function=LossFunction.MCXENT), "f2")
               .set_outputs("newout")
               .build())
        # retained weights copied; extractor frozen
        np.testing.assert_array_equal(
            np.asarray(src.params["f1"]["W"]),
            np.asarray(new.params["f1"]["W"]))
        assert isinstance(new.conf.vertices["f1"].content.updater,
                          NoOp)
        assert isinstance(new.conf.vertices["f2"].content.updater,
                          NoOp)
        assert "out" not in new.conf.vertices

        w1 = np.asarray(new.params["f1"]["W"]).copy()
        ds3 = _blob_ds(seed=2)
        y2 = np.eye(2, dtype=np.float32)[
            (np.asarray(ds3.labels).argmax(1) > 0).astype(int)]
        ds2 = DataSet(ds3.features, y2)
        new.fit(ds2, n_epochs=30)
        np.testing.assert_array_equal(
            w1, np.asarray(new.params["f1"]["W"]))
        pred = np.asarray(new.output(ds2.features)).argmax(1)
        acc = (pred == y2.argmax(1)).mean()
        assert acc > 0.85, acc


class TestWordVectorSerde:
    def _model(self):
        rng = np.random.RandomState(0)
        corpus = [" ".join(rng.choice(["red", "green", "blue",
                                       "cat", "dog"], 5))
                  for _ in range(40)]
        w2v = Word2Vec(layer_size=8, epochs=2, seed=1,
                       learning_rate=0.003)
        w2v.fit(corpus)
        return w2v

    def test_text_roundtrip(self, tmp_path):
        w2v = self._model()
        p = str(tmp_path / "vecs.txt")
        write_word_vectors(w2v, p)
        back = read_word_vectors(p)
        for w in w2v.vocab.words:
            assert back.has_word(w)
            np.testing.assert_allclose(back.get_word_vector(w),
                                       w2v.get_word_vector(w),
                                       rtol=1e-4, atol=1e-5)
        assert abs(back.similarity("cat", "dog")
                   - w2v.similarity("cat", "dog")) < 1e-3

    def test_binary_roundtrip_resumable(self, tmp_path):
        w2v = self._model()
        p = str(tmp_path / "model.npz")
        write_word2vec_model(w2v, p)
        back = read_word2vec_model(p)
        np.testing.assert_array_equal(back.syn0, w2v.syn0)
        np.testing.assert_array_equal(back.syn1, w2v.syn1)
        assert back.vocab.words == w2v.vocab.words
        assert back.vocab.counts == w2v.vocab.counts
        # resumable: continue training without error
        back.epochs = 1
        back._train_pairs(
            np.asarray([[0, 1], [1, 2]], np.int32),
            len(back.vocab))
        assert np.isfinite(back.syn0).all()
