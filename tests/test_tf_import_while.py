"""TF2 functional control-flow import: While/StatelessWhile/If nodes
whose cond/body live in the GraphDef function library (SURVEY.md S3 —
the reference maps legacy Enter/Exit/NextIteration frames; TF2 exports
the same loops as library functions), including GRADIENTS through an
imported trainable dynamic loop via while_max_iterations
(tests generate ground truth with the in-image TF at test time)."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.modelimport.tensorflow import (  # noqa: E402
    TensorflowFrameworkImporter)


def _freeze(fn, *specs):
    """Concrete-function GraphDef — the TF2 functional export form.
    The legacy v1 Enter/Exit frame form that
    convert_variables_to_constants produces is covered by
    test_tf_import_v1_control_flow.py (frame reconstruction)."""
    cf = tf.function(fn).get_concrete_function(*specs)
    return cf.graph.as_graph_def().SerializeToString(), cf


def _output_name(imp):
    outs = [n for n in imp.vars if n.startswith("Identity")]
    return sorted(outs)[0]


class TestWhileImport:
    def test_stateless_while_forward(self):
        """double x until its sum exceeds a bound (data-dependent
        trip count) — forward conformance vs TF."""
        def f(x):
            def cond(v):
                return tf.reduce_sum(v) < 100.0

            def body(v):
                return (v * 2.0,)

            return tf.while_loop(cond, body, (x,))[0]

        spec = tf.TensorSpec((4,), tf.float32)
        gd, frozen = _freeze(f, spec)
        xv = np.ones(4, np.float32)
        want = np.asarray(frozen(tf.constant(xv)))
        imp = TensorflowFrameworkImporter.run_import(gd, {"x": (4,)})
        out = _output_name(imp)
        got = imp.output({"x": xv}, [out])[out]
        np.testing.assert_allclose(got, want)

    def test_while_multi_var(self):
        """(i, acc) loop: counter + accumulator carried together."""
        def f(x):
            def cond(i, acc):
                return i < 5

            def body(i, acc):
                return i + 1, acc + tf.cast(i, tf.float32) * x

            return tf.while_loop(cond, body,
                                 (tf.constant(0), x * 0.0))[1]

        spec = tf.TensorSpec((3,), tf.float32)
        gd, frozen = _freeze(f, spec)
        xv = np.float32([1.0, 2.0, 3.0])
        want = np.asarray(frozen(tf.constant(xv)))
        imp = TensorflowFrameworkImporter.run_import(gd, {"x": (3,)})
        out = _output_name(imp)
        got = imp.output({"x": xv}, [out])[out]
        np.testing.assert_allclose(got, want)

    def test_trainable_loop_gradient_matches_tf(self):
        """The verdict's acceptance case: import a graph whose loss
        depends on a dynamic loop over a trained tensor; gradients
        through the imported loop (while_max_iterations lowering)
        must match tf.GradientTape on the original graph."""
        w0 = np.float32([1.1, 0.9, 1.3, 0.7])

        def loop_fn(w, x):
            v = w * x

            def cond(v):
                return tf.reduce_sum(v) < 100.0

            def body(v):
                return (v * 2.0,)

            return tf.reduce_sum(tf.while_loop(cond, body, (v,))[0])

        xv = np.float32([1.0, 2.0, 0.5, 1.5])
        with tf.GradientTape() as tape:
            wt = tf.Variable(w0)
            loss = loop_fn(wt, tf.constant(xv))
        want_grad = np.asarray(tape.gradient(loss, wt))

        # freeze with w as a second INPUT so the imported graph keeps
        # it as a differentiable placeholder-turned-variable
        def f(w, x):
            return loop_fn(w, x)

        gd, frozen = _freeze(f, tf.TensorSpec((4,), tf.float32),
                             tf.TensorSpec((4,), tf.float32))
        want_loss = float(frozen(tf.constant(w0), tf.constant(xv)))

        imp = TensorflowFrameworkImporter.run_import(
            gd, {"w": (4,), "x": (4,)}, while_max_iterations=16)
        out = _output_name(imp)
        got_loss = float(imp.output({"w": w0, "x": xv}, [out])[out])
        assert got_loss == pytest.approx(want_loss, rel=1e-5)

        # promote the imported w placeholder to a VARIABLE and
        # differentiate the imported graph
        imp.convert_to_variables(["w"], {"w": w0})
        imp.set_loss_variables([out])
        got_grad = imp.calculate_gradients({"x": xv}, ["w"])["w"]
        np.testing.assert_allclose(got_grad, want_grad, rtol=1e-5)

    def test_unbounded_import_gradient_raises(self):
        """Without while_max_iterations the import stays unbounded and
        a gradient request must raise loudly, never silently zero."""
        def f(w, x):
            def cond(v):
                return tf.reduce_sum(v) < 100.0

            def body(v):
                return (v * 2.0,)

            return tf.reduce_sum(
                tf.while_loop(cond, body, (w * x,))[0])

        gd, _ = _freeze(f, tf.TensorSpec((4,), tf.float32),
                        tf.TensorSpec((4,), tf.float32))
        imp = TensorflowFrameworkImporter.run_import(
            gd, {"w": (4,), "x": (4,)})
        out = _output_name(imp)
        w0 = np.float32([1.1, 0.9, 1.3, 0.7])
        imp.convert_to_variables(["w"], {"w": w0})
        imp.set_loss_variables([out])
        with pytest.raises(Exception,
                           match="max_iterations|while_loop"):
            imp.calculate_gradients(
                {"x": np.float32([1, 2, 0.5, 1.5])}, ["w"])


class TestIfImport:
    def test_stateless_if_both_branches(self):
        def f(x):
            return tf.cond(tf.reduce_sum(x) > 0.0,
                           lambda: x * 2.0, lambda: x - 1.0)

        spec = tf.TensorSpec((3,), tf.float32)
        gd, frozen = _freeze(f, spec)
        imp = TensorflowFrameworkImporter.run_import(gd, {"x": (3,)})
        out = _output_name(imp)
        for xv in (np.float32([1, 2, 3]), np.float32([-1, -2, -3])):
            want = np.asarray(frozen(tf.constant(xv)))
            got = imp.output({"x": xv}, [out])[out]
            np.testing.assert_allclose(got, want)

class TestFunctionBodyPorts:
    def test_multi_output_port_in_branch(self):
        """Named ports of multi-output ops inside function bodies must
        bind by flat offset: 'topk:indices:0' is flat port 1, not 0
        (regression: it used to bind the VALUES)."""
        def f(x):
            return tf.cond(
                tf.reduce_sum(x) > 0.0,
                lambda: tf.cast(tf.math.top_k(x, k=2).indices,
                                tf.float32),
                lambda: tf.zeros((2,)))

        spec = tf.TensorSpec((4,), tf.float32)
        gd, frozen = _freeze(f, spec)
        xv = np.float32([0.5, 5.0, 1.0, 3.0])
        want = np.asarray(frozen(tf.constant(xv)))  # indices [1, 3]
        imp = TensorflowFrameworkImporter.run_import(gd, {"x": (4,)})
        out = _output_name(imp)
        got = imp.output({"x": xv}, [out])[out]
        np.testing.assert_allclose(got, want)

    def test_unmapped_op_in_body_fails_precheck(self):
        """An unmapped op inside a While body must fail the import
        precheck with the 'no mapping' parity message, not a bare
        KeyError mid-trace.  (TensorArray, the original example here,
        imports now — test_tf_import_tensorlist.py.)"""
        def f(x):
            def cond(i, acc):
                return i < 3

            def body(i, acc):
                s = tf.linalg.svd(tf.reshape(acc, (2, 2)),
                                  compute_uv=False)
                return i + 1, acc + tf.reduce_sum(s)

            _, acc = tf.while_loop(cond, body,
                                   (tf.constant(0), x))
            return acc

        gd, _ = _freeze(f, tf.TensorSpec((4,), tf.float32))
        with pytest.raises(NotImplementedError, match="no mapping"):
            TensorflowFrameworkImporter.run_import(gd, {"x": (4,)})

    def test_zero_operand_branches(self):
        """Branches that capture nothing (constant-only lambdas)
        produce zero-arg FunctionDefs; each must still trace into its
        OWN child graph (regression: both imported into the parent,
        colliding on same-named nodes)."""
        def f(x):
            return tf.cond(tf.reduce_sum(x) > 0.0,
                           lambda: tf.constant([1.0, 2.0]),
                           lambda: tf.constant([3.0, 4.0]))

        spec = tf.TensorSpec((3,), tf.float32)
        gd, frozen = _freeze(f, spec)
        imp = TensorflowFrameworkImporter.run_import(gd, {"x": (3,)})
        out = _output_name(imp)
        for xv in (np.float32([1, 1, 1]), np.float32([-1, -1, -1])):
            want = np.asarray(frozen(tf.constant(xv)))
            got = imp.output({"x": xv}, [out])[out]
            np.testing.assert_allclose(got, want)
