"""Layer-breadth tests: shape/pad/crop family, 1D/3D conv family, misc
parameterised layers (reference test style: ConvolutionLayerTest /
Convolution3DTest / LocallyConnectedLayerTest equivalents, SURVEY.md §4.8).

Each layer is checked for (a) shape-inference vs actual forward shape
agreement, (b) value semantics on small hand-checkable inputs, and
(c) end-to-end training inside a MultiLayerNetwork where meaningful.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DenseLayer, Layer, OutputLayer, PoolingType,
    RnnOutputLayer)
from deeplearning4j_tpu.nn.conf.layers_conv_1d3d import (
    Cnn3DLossLayer, Convolution1DLayer, Convolution3D, Deconvolution3D,
    Subsampling1DLayer, Subsampling3DLayer)
from deeplearning4j_tpu.nn.conf.layers_misc import (
    ElementWiseMultiplicationLayer, LocalResponseNormalization,
    LocallyConnected1D, LocallyConnected2D, PReLULayer, RnnLossLayer)
from deeplearning4j_tpu.nn.conf.layers_recurrent import LSTM
from deeplearning4j_tpu.nn.conf.layers_shape import (
    Cropping1D, Cropping2D, Cropping3D, DepthToSpaceLayer, FrozenLayer,
    MaskLayer, MaskZeroLayer, RepeatVector, SpaceToDepthLayer,
    TimeDistributed, Upsampling1D, Upsampling3D, ZeroPadding1DLayer,
    ZeroPadding3DLayer, ZeroPaddingLayer)


def _shape_of(layer, in_type, rng_seed=0, batch=2):
    """Run forward on zeros and also return the inferred output type."""
    layer.set_n_in(in_type, override=True)
    key = jax.random.PRNGKey(rng_seed)
    params = (layer.init_params(key, in_type) if layer.has_params()
              else {})
    x = jnp.ones(in_type.shape(batch))
    y, _ = layer.forward(params, x, training=False)
    out_t = layer.get_output_type(in_type)
    return y.shape, out_t.shape(batch)


class TestShapeFamily:
    def test_cropping_1d_2d_3d(self):
        got, want = _shape_of(Cropping1D(cropping=(1, 2)),
                              InputType.recurrent(5, 10))
        assert got == want == (2, 7, 5)
        got, want = _shape_of(
            Cropping2D(crop_top_bottom=(1, 1), crop_left_right=(2, 0)),
            InputType.convolutional(8, 8, 3))
        assert got == want == (2, 6, 6, 3)
        got, want = _shape_of(
            Cropping3D(crop_depth=(1, 1), crop_height=(1, 0),
                       crop_width=(0, 2)),
            InputType.convolutional_3d(6, 6, 6, 2))
        assert got == want == (2, 4, 5, 4, 2)

    def test_zero_padding_1d_2d_3d(self):
        got, want = _shape_of(ZeroPadding1DLayer(padding=(2, 1)),
                              InputType.recurrent(4, 5))
        assert got == want == (2, 8, 4)
        got, want = _shape_of(
            ZeroPaddingLayer(pad_top_bottom=(1, 1), pad_left_right=(2, 2)),
            InputType.convolutional(4, 4, 3))
        assert got == want == (2, 6, 8, 3)
        got, want = _shape_of(
            ZeroPadding3DLayer(pad_depth=(1, 0), pad_height=(0, 1),
                               pad_width=(1, 1)),
            InputType.convolutional_3d(3, 3, 3, 2))
        assert got == want == (2, 4, 4, 5, 2)

    def test_pad_values(self):
        layer = ZeroPaddingLayer(pad_top_bottom=(1, 1),
                                 pad_left_right=(1, 1))
        x = jnp.ones((1, 2, 2, 1))
        y, _ = layer.forward({}, x, training=False)
        assert float(y.sum()) == 4.0          # only interior is ones
        assert float(y[0, 0, 0, 0]) == 0.0    # border zero

    def test_space_to_depth_roundtrip(self):
        s2d, d2s = SpaceToDepthLayer(block_size=2), \
            DepthToSpaceLayer(block_size=2)
        x = jnp.arange(2 * 4 * 4 * 3, dtype=jnp.float32).reshape(2, 4, 4, 3)
        z, _ = s2d.forward({}, x, training=False)
        assert z.shape == (2, 2, 2, 12)
        back, _ = d2s.forward({}, z, training=False)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))
        got, want = _shape_of(SpaceToDepthLayer(block_size=2),
                              InputType.convolutional(4, 4, 3))
        assert got == want == (2, 2, 2, 12)

    def test_upsampling_1d_3d(self):
        got, want = _shape_of(Upsampling1D(size=3),
                              InputType.recurrent(4, 5))
        assert got == want == (2, 15, 4)
        got, want = _shape_of(Upsampling3D(size=2),
                              InputType.convolutional_3d(2, 3, 4, 2))
        assert got == want == (2, 4, 6, 8, 2)

    def test_repeat_vector(self):
        got, want = _shape_of(RepeatVector(repetition_factor=4),
                              InputType.feed_forward(7))
        assert got == want == (2, 4, 7)
        layer = RepeatVector(repetition_factor=3)
        x = jnp.array([[1.0, 2.0]])
        y, _ = layer.forward({}, x, training=False)
        np.testing.assert_array_equal(np.asarray(y),
                                      [[[1, 2], [1, 2], [1, 2]]])


class TestMaskAndWrappers:
    def test_mask_layer(self):
        layer = MaskLayer()
        x = jnp.ones((2, 3, 4))
        mask = jnp.array([[1, 1, 0], [1, 0, 0]], dtype=jnp.float32)
        y, _ = layer.forward({}, x, training=False, mask=mask)
        assert float(y[0, 2].sum()) == 0.0
        assert float(y[0, 1].sum()) == 4.0
        assert float(y[1, 1].sum()) == 0.0

    def test_mask_zero_layer_wraps_lstm(self):
        inner = LSTM(n_out=6, activation=Activation.TANH)
        layer = MaskZeroLayer(underlying=inner, mask_value=0.0)
        in_t = InputType.recurrent(3, 5)
        layer.set_n_in(in_t, override=True)
        params = layer.init_params(jax.random.PRNGKey(0), in_t)
        x = jnp.ones((2, 5, 3))
        x = x.at[:, 3:, :].set(0.0)  # last two steps are padding
        y, _ = layer.forward(params, x, training=False)
        assert y.shape == (2, 5, 6)
        np.testing.assert_allclose(np.asarray(y[:, 3:, :]), 0.0)
        assert float(jnp.abs(y[:, :3, :]).sum()) > 0.0

    def test_frozen_layer_blocks_grads(self):
        inner = DenseLayer(n_in=4, n_out=4, activation=Activation.TANH)
        frozen = FrozenLayer(underlying=inner)
        params = frozen.init_params(jax.random.PRNGKey(0),
                                    InputType.feed_forward(4))

        def loss(p, x):
            y, _ = frozen.forward(p, x, training=True)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(params, jnp.ones((2, 4)))
        assert float(jnp.abs(g["W"]).sum()) == 0.0
        assert float(jnp.abs(g["b"]).sum()) == 0.0

    def test_time_distributed_dense(self):
        inner = DenseLayer(n_out=5, activation=Activation.RELU)
        layer = TimeDistributed(underlying=inner)
        in_t = InputType.recurrent(3, 7)
        layer.set_n_in(in_t, override=True)
        params = layer.init_params(jax.random.PRNGKey(0), in_t)
        x = jnp.ones((2, 7, 3))
        y, _ = layer.forward(params, x, training=False)
        assert y.shape == (2, 7, 5)
        assert layer.get_output_type(in_t).shape(2) == (2, 7, 5)
        # per-timestep independence: all timesteps identical for identical
        # inputs
        np.testing.assert_allclose(np.asarray(y[:, 0]), np.asarray(y[:, 6]))

    def test_frozen_layer_immune_to_l2(self):
        """l1/l2 regularization must not update frozen weights
        (regression: the reg term bypassed forward's stop_gradient)."""
        from deeplearning4j_tpu.learning import Sgd
        from deeplearning4j_tpu.lossfunctions import LossFunction
        from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers import OutputLayer
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Sgd(0.5)).l2(0.1)
                .list()
                .layer(FrozenLayer(underlying=DenseLayer(
                    n_out=4, activation=Activation.TANH)))
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        w0 = np.asarray(net.params["layer_0"]["W"]).copy()
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[np.zeros(8, int)]
        net.fit(x, y)
        np.testing.assert_array_equal(
            np.asarray(net.params["layer_0"]["W"]), w0)

    def test_time_distributed_stateful_underlying(self):
        """TimeDistributed over a stateful layer (BatchNormalization)
        allocates/threads the state (regression: state delegation)."""
        from deeplearning4j_tpu.nn.conf.layers import BatchNormalization
        inner = BatchNormalization(n_in=3, n_out=3)
        layer = TimeDistributed(underlying=inner)
        in_t = InputType.recurrent(3, 4)
        assert layer.has_state()
        params = layer.init_params(jax.random.PRNGKey(0), in_t)
        state = layer.init_state(in_t)
        x = jnp.ones((2, 4, 3))
        y, ns = layer.forward(params, x, training=True, state=state)
        assert y.shape == (2, 4, 3)
        assert ns is not None and len(ns) > 0

    def test_wrapper_serde_roundtrip(self):
        layer = FrozenLayer(underlying=DenseLayer(n_in=4, n_out=3))
        d = layer.to_map()
        back = Layer.from_map(d)
        assert isinstance(back, FrozenLayer)
        assert isinstance(back.underlying, DenseLayer)
        assert back.underlying.n_out == 3


class TestConv1D3D:
    def test_conv1d_shapes(self):
        from deeplearning4j_tpu.nn.conf.layers import ConvolutionMode
        got, want = _shape_of(
            Convolution1DLayer(kernel_size=3, stride=1, n_out=8,
                               convolution_mode=ConvolutionMode.SAME),
            InputType.recurrent(4, 10))
        assert got == want == (2, 10, 8)

    def test_conv1d_truncate(self):
        from deeplearning4j_tpu.nn.conf.layers import ConvolutionMode
        got, want = _shape_of(
            Convolution1DLayer(kernel_size=3, stride=2, n_out=6,
                               convolution_mode=ConvolutionMode.TRUNCATE),
            InputType.recurrent(4, 11))
        assert got == want == (2, 5, 6)

    def test_subsampling1d(self):
        layer = Subsampling1DLayer(kernel_size=2, stride=2,
                                   pooling_type=PoolingType.MAX)
        x = jnp.array([[[1.], [4.], [2.], [3.]]])
        y, _ = layer.forward({}, x, training=False)
        np.testing.assert_array_equal(np.asarray(y), [[[4.], [3.]]])
        got, want = _shape_of(layer, InputType.recurrent(4, 10))
        assert got == want == (2, 5, 4)

    def test_conv3d_shapes(self):
        got, want = _shape_of(Convolution3D(kernel_size=(3, 3, 3),
                                            n_out=4),
                              InputType.convolutional_3d(6, 6, 6, 2))
        assert got == want == (2, 4, 4, 4, 4)

    def test_subsampling3d(self):
        got, want = _shape_of(
            Subsampling3DLayer(kernel_size=(2, 2, 2), stride=(2, 2, 2)),
            InputType.convolutional_3d(4, 4, 4, 3))
        assert got == want == (2, 2, 2, 2, 3)

    def test_deconv3d_shapes(self):
        got, want = _shape_of(
            Deconvolution3D(kernel_size=(2, 2, 2), stride=(2, 2, 2),
                            n_out=3),
            InputType.convolutional_3d(2, 2, 2, 4))
        assert got == want == (2, 4, 4, 4, 3)

    def test_deconv2d_truncate_shapes(self):
        from deeplearning4j_tpu.nn.conf.layers_conv_extra import \
            Deconvolution2D
        got, want = _shape_of(
            Deconvolution2D(kernel_size=(2, 2), stride=(2, 2), n_out=3),
            InputType.convolutional(5, 5, 4))
        assert got == want == (2, 10, 10, 3)

    def test_conv3d_gradient_flows(self):
        layer = Convolution3D(kernel_size=(2, 2, 2), n_in=1, n_out=2)
        in_t = InputType.convolutional_3d(3, 3, 3, 1)
        params = layer.init_params(jax.random.PRNGKey(0), in_t)

        def loss(p):
            y, _ = layer.forward(p, jnp.ones((1, 3, 3, 3, 1)),
                                 training=True)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(params)
        assert float(jnp.abs(g["W"]).sum()) > 0.0


class TestMiscLayers:
    def test_prelu(self):
        layer = PReLULayer(alpha_init=0.25)
        in_t = InputType.feed_forward(3)
        layer.set_n_in(in_t, override=True)
        params = layer.init_params(jax.random.PRNGKey(0), in_t)
        x = jnp.array([[-4.0, 0.0, 2.0]])
        y, _ = layer.forward(params, x, training=False)
        np.testing.assert_allclose(np.asarray(y), [[-1.0, 0.0, 2.0]])

    def test_prelu_shared_axes(self):
        layer = PReLULayer(alpha_init=0.1, shared_axes=(1, 2))
        in_t = InputType.convolutional(4, 4, 3)
        params = layer.init_params(jax.random.PRNGKey(0), in_t)
        assert params["alpha"].shape == (1, 1, 3)

    def test_elementwise_mult(self):
        layer = ElementWiseMultiplicationLayer(n_in=3, n_out=3)
        params = layer.init_params(jax.random.PRNGKey(0),
                                   InputType.feed_forward(3))
        params = {"W": jnp.array([1.0, 2.0, 3.0]),
                  "b": jnp.zeros(3)}
        y, _ = layer.forward(params, jnp.array([[1.0, 1.0, 1.0]]),
                             training=False)
        np.testing.assert_allclose(np.asarray(y), [[1.0, 2.0, 3.0]])

    def test_lrn_identity_at_small_alpha(self):
        layer = LocalResponseNormalization(alpha=0.0, beta=0.75, k=1.0)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8))
        y, _ = layer.forward({}, x, training=False)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-6)

    def test_lrn_normalizes(self):
        layer = LocalResponseNormalization(alpha=1.0, beta=1.0, k=0.0, n=1)
        # with n=1, window is just the channel itself: y = x / x^2 = 1/x
        x = jnp.full((1, 1, 1, 4), 2.0)
        y, _ = layer.forward({}, x, training=False)
        np.testing.assert_allclose(np.asarray(y), 0.5)

    def test_locally_connected_2d(self):
        layer = LocallyConnected2D(kernel_size=(2, 2), stride=(1, 1),
                                   n_out=4)
        in_t = InputType.convolutional(4, 4, 2)
        got, want = _shape_of(layer, in_t)
        assert got == want == (2, 3, 3, 4)

    def test_locally_connected_2d_is_unshared(self):
        """Distinct kernels per position: constant input but per-position
        weights give different outputs across positions."""
        layer = LocallyConnected2D(kernel_size=(2, 2), n_in=1, n_out=1,
                                   has_bias=False)
        in_t = InputType.convolutional(3, 3, 1)
        layer.set_n_in(in_t, override=False)
        params = layer.init_params(jax.random.PRNGKey(3), in_t)
        x = jnp.ones((1, 3, 3, 1))
        y, _ = layer.forward(params, x, training=False)
        flat = np.asarray(y).ravel()
        assert np.ptp(flat) > 1e-4  # positions differ

    def test_locally_connected_1d(self):
        layer = LocallyConnected1D(kernel_size=3, n_out=5)
        got, want = _shape_of(layer, InputType.recurrent(4, 9))
        assert got == want == (2, 7, 5)

    def test_conv1d_trains_in_network(self):
        """Temporal conv + pooling head classifies a trivial sequence
        pattern (rising vs falling)."""
        rng = np.random.RandomState(0)
        n, t = 128, 8
        xs = np.zeros((n, t, 1), np.float32)
        ys = rng.randint(0, 2, n)
        ramp = np.linspace(-1, 1, t, dtype=np.float32)[:, None]
        xs[ys == 0] = ramp
        xs[ys == 1] = -ramp
        xs += 0.05 * rng.randn(n, t, 1).astype(np.float32)
        labels = np.eye(2, dtype=np.float32)[ys]

        from deeplearning4j_tpu.nn.conf.layers import GlobalPoolingLayer
        conf = (NeuralNetConfiguration.Builder()
                .seed(7).updater(Adam(1e-2))
                .list()
                .layer(Convolution1DLayer(kernel_size=3, n_out=8,
                                          activation=Activation.RELU))
                .layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.recurrent(1, t))
                .build())
        net = MultiLayerNetwork(conf)
        net.init()
        for _ in range(60):
            net.fit(xs, labels)
        preds = np.asarray(net.output(xs)).argmax(-1)
        assert (preds == ys).mean() > 0.95

    def test_rnn_loss_layer_in_network(self):
        """RnnLossLayer as per-timestep head after an LSTM."""
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(1e-2))
                .list()
                .layer(LSTM(n_out=4))
                .layer(RnnLossLayer(
                    loss_function=LossFunction.MSE,
                    activation=Activation.IDENTITY))
                .set_input_type(InputType.recurrent(4, 6))
                .build())
        net = MultiLayerNetwork(conf)
        net.init()
        x = np.random.RandomState(0).randn(3, 6, 4).astype(np.float32)
        y = net.output(x)
        assert np.asarray(y).shape == (3, 6, 4)
