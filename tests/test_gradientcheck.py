"""Network-level gradient checks (SURVEY.md §4.5:
GradientCheckUtil + GradientCheckTests / CNNGradientCheckTest /
LSTMGradientCheckTests)."""
import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.graph_vertices import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                               ConvolutionLayer,
                                               DenseLayer, OutputLayer,
                                               RnnOutputLayer,
                                               SubsamplingLayer)
from deeplearning4j_tpu.nn.conf.layers_recurrent import LSTM
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.utils.gradientcheck import GradientCheckUtil


def _base():
    return (NeuralNetConfiguration.Builder().seed(3)
            .updater(Sgd(1e-2)))


class TestGradientChecks:
    def test_mlp(self):
        conf = (_base().l2(1e-4).list()
                .layer(DenseLayer(n_out=10,
                                  activation=Activation.TANH))
                .layer(DenseLayer(n_out=8,
                                  activation=Activation.SIGMOID))
                .layer(OutputLayer(n_out=3,
                                   activation=Activation.SOFTMAX,
                                   loss_function=LossFunction.MCXENT))
                .set_input_type(InputType.feed_forward(5)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(0)
        ds = DataSet(rng.randn(6, 5).astype(np.float32),
                     np.eye(3, dtype=np.float32)[rng.randint(0, 3, 6)])
        assert GradientCheckUtil.check_gradients(net, ds)

    def test_cnn_with_bn(self):
        conf = (_base().list()
                .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=4,
                                        activation=Activation.IDENTITY))
                .layer(BatchNormalization(
                    activation=Activation.TANH))
                .layer(SubsamplingLayer(kernel_size=(2, 2),
                                        stride=(2, 2)))
                .layer(OutputLayer(n_out=2,
                                   activation=Activation.SOFTMAX,
                                   loss_function=LossFunction.MCXENT))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(1)
        ds = DataSet(rng.randn(4, 8, 8, 1).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.randint(0, 2, 4)])
        assert GradientCheckUtil.check_gradients(net, ds)

    def test_lstm(self):
        conf = (_base().list()
                .layer(LSTM(n_out=6, activation=Activation.TANH))
                .layer(RnnOutputLayer(
                    n_out=2, activation=Activation.SOFTMAX,
                    loss_function=LossFunction.MCXENT))
                .set_input_type(InputType.recurrent(3, 7)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(2)
        ds = DataSet(
            rng.randn(3, 7, 3).astype(np.float32),
            np.eye(2, dtype=np.float32)[
                rng.randint(0, 2, (3, 7))].astype(np.float32))
        assert GradientCheckUtil.check_gradients(net, ds)

    def test_graph_residual(self):
        g = (_base().graph_builder().add_inputs("in")
             .set_input_types(InputType.feed_forward(6)))
        g.add_layer("d1", DenseLayer(n_out=6,
                                     activation=Activation.TANH), "in")
        g.add_layer("d2", DenseLayer(n_out=6,
                                     activation=Activation.TANH), "d1")
        g.add_vertex("add", ElementWiseVertex(ElementWiseVertex.Op.Add),
                     "d1", "d2")
        g.add_layer("out", OutputLayer(
            n_out=2, activation=Activation.SOFTMAX,
            loss_function=LossFunction.MCXENT), "add")
        net = ComputationGraph(g.set_outputs("out").build()).init()
        rng = np.random.RandomState(4)
        ds = DataSet(rng.randn(5, 6).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.randint(0, 2, 5)])
        assert GradientCheckUtil.check_gradients(net, ds)

    def test_capsule_net(self):
        """Dynamic-routing capsules pass the f64 numeric gradient check
        (§4.5 style for the new layer families)."""
        from deeplearning4j_tpu.nn.conf.layers_capsule import (
            CapsuleLayer, CapsuleStrengthLayer, PrimaryCapsules)
        conf = (_base().list()
                .layer(PrimaryCapsules(capsule_dimensions=4, channels=2,
                                       kernel_size=(3, 3),
                                       stride=(2, 2)))
                .layer(CapsuleLayer(capsules=3, capsule_dimensions=4,
                                    routings=2))
                .layer(CapsuleStrengthLayer())
                .layer(OutputLayer(n_out=3,
                                   activation=Activation.SOFTMAX,
                                   loss_function=LossFunction.MCXENT))
                .set_input_type(InputType.convolutional(7, 7, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(5)
        ds = DataSet(rng.randn(3, 7, 7, 1).astype(np.float32),
                     np.eye(3, dtype=np.float32)[rng.randint(0, 3, 3)])
        assert GradientCheckUtil.check_gradients(net, ds)

    def test_locally_connected_and_conv1d(self):
        from deeplearning4j_tpu.nn.conf.layers import GlobalPoolingLayer
        from deeplearning4j_tpu.nn.conf.layers_conv_1d3d import \
            Convolution1DLayer
        from deeplearning4j_tpu.nn.conf.layers_misc import \
            LocallyConnected1D
        conf = (_base().list()
                .layer(Convolution1DLayer(kernel_size=3, n_out=4,
                                          causal=True,
                                          activation=Activation.TANH))
                .layer(LocallyConnected1D(kernel_size=3, n_out=3,
                                          activation=Activation.TANH))
                .layer(GlobalPoolingLayer())
                .layer(OutputLayer(n_out=2,
                                   activation=Activation.SOFTMAX,
                                   loss_function=LossFunction.MCXENT))
                .set_input_type(InputType.recurrent(3, 8)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(6)
        ds = DataSet(rng.randn(3, 8, 3).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.randint(0, 2, 3)])
        assert GradientCheckUtil.check_gradients(net, ds)

    def test_center_loss_head(self):
        from deeplearning4j_tpu.nn.conf.layers_output_extra import \
            CenterLossOutputLayer
        conf = (_base().list()
                .layer(DenseLayer(n_out=6,
                                  activation=Activation.TANH))
                .layer(CenterLossOutputLayer(
                    n_out=3, lambda_=0.3,
                    activation=Activation.SOFTMAX,
                    loss_function=LossFunction.MCXENT))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(7)
        # non-zero centers so the center term has a gradient everywhere
        net.params["layer_1"]["centers"] = \
            jnp.asarray(rng.randn(3, 6).astype(np.float32) * 0.1)
        ds = DataSet(rng.randn(5, 4).astype(np.float32),
                     np.eye(3, dtype=np.float32)[rng.randint(0, 3, 5)])
        assert GradientCheckUtil.check_gradients(net, ds)

    def test_mixed_precision_net_checked_in_f64(self):
        """compute_dtype must be suspended during the check — else
        both sides reduce to bf16 rounding noise."""
        conf = (_base().compute_data_type("bfloat16").list()
                .layer(DenseLayer(n_out=6,
                                  activation=Activation.TANH))
                .layer(OutputLayer(n_out=2,
                                   activation=Activation.SOFTMAX,
                                   loss_function=LossFunction.MCXENT))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(6)
        ds = DataSet(rng.randn(5, 4).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.randint(0, 2, 5)])
        assert GradientCheckUtil.check_gradients(net, ds)
        assert net.conf.compute_dtype == "bfloat16"   # restored

    def test_detects_broken_gradient(self):
        """Sanity: a wrong analytic gradient MUST fail the check."""
        conf = (_base().list()
                .layer(DenseLayer(n_out=4,
                                  activation=Activation.TANH))
                .layer(OutputLayer(n_out=2,
                                   activation=Activation.SOFTMAX,
                                   loss_function=LossFunction.MCXENT))
                .set_input_type(InputType.feed_forward(3)).build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(5)
        ds = DataSet(rng.randn(4, 3).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.randint(0, 2, 4)])

        assert GradientCheckUtil.check_gradients(net, ds)

        # corrupt the ANALYTIC side only (scale grads by 1.5): the
        # checker must notice the disagreement with the numeric side
        import jax
        import deeplearning4j_tpu.utils.gradientcheck as gc
        loss_fn = gc._net_loss_fn(net, ds)
        real_grad = jax.grad(loss_fn)
        with _patched(gc.jax, "grad", lambda f: (
                lambda p: jax.tree_util.tree_map(
                    lambda a: a * 1.5, real_grad(p)))):
            assert not GradientCheckUtil.check_gradients(net, ds)


class _patched:
    def __init__(self, obj, name, value):
        self.obj, self.name, self.value = obj, name, value

    def __enter__(self):
        self._old = getattr(self.obj, self.name)
        setattr(self.obj, self.name, self.value)

    def __exit__(self, *a):
        setattr(self.obj, self.name, self._old)
        return False
